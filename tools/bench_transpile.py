"""Optimizing-transpiler bench: op-count, trace+cold-compile time, and
feed-churn recompile reduction, with in-run parity checks.

Three measurements, one JSON line per config (schema
``bench_transpile/1``, pinned by tests/test_bench_transpile_smoke.py):

1. **Structure**: global-block op count before/after
   ``optimize_program`` at ``--opt-level`` on the bundled example
   graphs (the same builders tools/program_lint.py ships), plus
   per-pass applied counts and pass wall time.

2. **Trace + cold XLA compile** (interleaved A/B, order-alternated
   across replicates like bench_resume): the explicit
   ``jit → lower → compile`` split on the raw vs the optimized
   program — ``trace_*`` is `.lower()` (the per-op Python tracing the
   transpiler shrinks), ``xla_*`` is `.compile()`.
   ``trace_speedup`` = raw_trace_median / opt_trace_median;
   ``cold_total_speedup`` the same over trace+compile (what a cold
   start pays).

3. **Feed churn** (``transpile_churn`` line): the same inference graph
   fed a cycle of ragged batch sizes, raw vs opt-level-2 (bucketize
   stamp). ``compiles_*`` counts executor compile-cache entries;
   ``cache_misses_*`` counter-verifies against the
   paddle_tpu_compile_cache_misses_total{kind=run,tier=memory} series.
   The bucketized arm's compile count must hit the pow2 bucket bound.

Every ``transpile`` line carries ``parity_ok``: raw and optimized
outputs compared EXACTLY (np.array_equal) on the measured feeds — a
bench run that breaks parity reports it instead of banking a bogus
win. The churn line compares the PADDED path at ulp tolerance
(``parity_close``) and reports the observed ``parity_max_abs_diff``:
XLA's GEMM may reduce in a different order at a different batch dim
(see transpiler/passes/bucketize.py), so padded rows are exact math,
same-ulp-class floats.

Usage:
    JAX_PLATFORMS=cpu python tools/bench_transpile.py \
        [--configs mlp,deepfm,lstm] [--opt-level 2] [--replicates 5] \
        [--churn-sizes 3,5,6,7,9,11,13,3,5,6] [--churn-config mlp]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import jax

jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import numpy as np

SCHEMA = "bench_transpile/1"


def _build(config):
    """(program, feed, fetch_names, scope) — bundled example graphs
    (program_lint builders), params initialized, INFERENCE form (the
    deployment artifact the optimizing transpiler targets)."""
    import paddle_tpu as fluid
    from paddle_tpu import layers

    rs = np.random.RandomState(0)
    scope = fluid.Scope()
    main, startup = fluid.Program(), fluid.Program()
    with fluid.scope_guard(scope), fluid.program_guard(main, startup):
        with fluid.unique_name.guard():
            if config in ("mlp", "mlp-tiny"):
                dim = 784 if config == "mlp" else 16
                x = layers.data(name="pixel", shape=[dim])
                if config == "mlp":
                    from paddle_tpu.models.mnist import mlp_model

                    predict = mlp_model(x)
                else:
                    predict = layers.fc(layers.fc(x, 8, act="relu"), 2,
                                        act="softmax")
                feed = {"pixel": rs.rand(8, dim).astype(np.float32)}
                fetches = [predict.name]
            elif config == "deepfm":
                from paddle_tpu.models.deepfm import deepfm_net

                feat_ids = layers.data(name="feat_ids", shape=[10],
                                       dtype="int64")
                dense = layers.data(name="dense", shape=[13])
                label = layers.data(name="label", shape=[1],
                                    dtype="int64")
                avg_cost, prob = deepfm_net(feat_ids, dense, label,
                                            num_features=1000,
                                            num_fields=10)
                feed = {
                    "feat_ids": rs.randint(0, 1000, (8, 10))
                    .astype(np.int64),
                    "dense": rs.rand(8, 13).astype(np.float32),
                    "label": rs.randint(0, 2, (8, 1)).astype(np.int64),
                }
                fetches = [prob.name]
            elif config == "lstm":
                from paddle_tpu.models.stacked_lstm import stacked_lstm_net

                words = layers.data(name="words", shape=[80],
                                    dtype="int64")
                lengths = layers.data(name="lengths", shape=[],
                                      dtype="int32")
                predict = stacked_lstm_net(words, lengths, dict_dim=3000,
                                           emb_dim=64, hid_dim=64,
                                           stacked_num=2)
                feed = {"words": rs.randint(0, 3000, (4, 80))
                        .astype(np.int64),
                        "lengths": rs.randint(8, 80, (4,))
                        .astype(np.int32)}
                fetches = [predict.name]
            else:
                raise SystemExit("unknown config %r" % config)
        exe = fluid.Executor()
        with fluid.scope_guard(scope):
            exe.run(startup)
    infer = main.clone(for_test=True)
    return infer, feed, fetches, scope


def _parity_outputs(program, feed, fetches, scope):
    import paddle_tpu as fluid

    exe = fluid.Executor(opt_level=0)
    exe._disk.enabled = False
    with fluid.scope_guard(scope):
        return exe.run(program, feed=feed, fetch_list=fetches)


def _trace_xla_s(program, feed, fetches, scope):
    """(trace_s, xla_s): explicit ``jit → lower → compile`` split, the
    same path Executor._aot_compile takes. Separating the split beats
    timing a cold run(): dispatch noise on a contended 2-core box
    swamps the per-arm difference, while lower() isolates exactly the
    per-op Python tracing the transpiler shrinks."""
    import jax

    from paddle_tpu.executor import Executor, analyze_state, build_step_fn

    feed_sig = tuple((n, np.asarray(v).shape, str(np.asarray(v).dtype))
                     for n, v in sorted(feed.items()))
    state_in, state_out = analyze_state(program, set(feed))
    stepfn = build_step_fn(program, list(fetches), state_in, state_out)
    fn = jax.jit(stepfn, donate_argnums=(1,))
    args = Executor._avals_for(feed_sig, state_in, scope, loop=False)
    t0 = time.perf_counter()
    lowered = fn.lower(*args)
    t1 = time.perf_counter()
    lowered.compile()
    t2 = time.perf_counter()
    return t1 - t0, t2 - t1


def _run_mem_misses():
    from paddle_tpu.observability import export

    doc = json.loads(export.dumps_json())
    m = doc["metrics"].get("paddle_tpu_compile_cache_misses_total", {})
    return sum(s["value"] for s in m.get("series", ())
               if s["labels"].get("kind") == "run"
               and s["labels"].get("tier") == "memory")


def bench_config(config, opt_level, replicates):
    from paddle_tpu.transpiler.passes import optimize_program

    program, feed, fetches, scope = _build(config)
    t0 = time.perf_counter()
    opt, ctx = optimize_program(program, scope=scope, level=opt_level,
                                feed_names=list(feed),
                                fetch_names=fetches)
    passes_ms = (time.perf_counter() - t0) * 1e3
    ops_before = len(program.global_block().ops)
    ops_after = len(opt.global_block().ops)

    # parity gate: the measured programs must agree EXACTLY on the
    # bench feed (unpadded: both arms run at the feed's own batch)
    raw_out = _parity_outputs(program, feed, fetches, scope)
    opt_out = _parity_outputs(opt, feed, fetches, scope)
    parity_ok = all(np.array_equal(a, b)
                    for a, b in zip(raw_out, opt_out))

    raw_tr, raw_xla, opt_tr, opt_xla = [], [], [], []
    for rep in range(replicates):
        arms = [("raw", program), ("opt", opt)]
        if rep % 2:  # alternate order: CPU-governor fairness
            arms.reverse()
        for name, prog in arms:
            tr, xla = _trace_xla_s(prog, feed, fetches, scope)
            if name == "raw":
                raw_tr.append(tr)
                raw_xla.append(xla)
            else:
                opt_tr.append(tr)
                opt_xla.append(xla)
    raw_trm, opt_trm = float(np.median(raw_tr)), float(np.median(opt_tr))
    raw_xm, opt_xm = float(np.median(raw_xla)), float(np.median(opt_xla))
    return {
        "bench": "transpile", "schema": SCHEMA, "config": config,
        "opt_level": opt_level, "replicates": replicates,
        "ops_before": ops_before, "ops_after": ops_after,
        "op_reduction_frac": round(1.0 - ops_after / ops_before, 4),
        "passes_ms": round(passes_ms, 3),
        "pass_applied": {k: v.get("applied", 0)
                         for k, v in ctx.stats.items()
                         if v.get("applied")},
        "trace_s_raw": [round(s, 4) for s in raw_tr],
        "trace_s_opt": [round(s, 4) for s in opt_tr],
        "trace_median_raw_s": round(raw_trm, 4),
        "trace_median_opt_s": round(opt_trm, 4),
        "trace_speedup": round(raw_trm / opt_trm, 4) if opt_trm else None,
        "xla_median_raw_s": round(raw_xm, 4),
        "xla_median_opt_s": round(opt_xm, 4),
        "cold_total_median_raw_s": round(raw_trm + raw_xm, 4),
        "cold_total_median_opt_s": round(opt_trm + opt_xm, 4),
        "cold_total_speedup": round(
            (raw_trm + raw_xm) / (opt_trm + opt_xm), 4)
        if (opt_trm + opt_xm) else None,
        "bucketized": bool(getattr(opt, "_bucketize", None)),
        "parity_ok": bool(parity_ok),
    }


def bench_churn(config, sizes):
    """Ragged batch sizes through raw vs bucketized (opt level 2)."""
    import paddle_tpu as fluid
    from paddle_tpu.transpiler.passes import next_pow2

    program, feed, fetches, scope = _build(config)
    rs = np.random.RandomState(1)

    def churn_feed(n):
        out = {}
        for name, arr in feed.items():
            if arr.dtype.kind == "i":
                hi = max(int(arr.max()), 1)
                out[name] = rs.randint(0, hi + 1, (n,) + arr.shape[1:]) \
                    .astype(arr.dtype)
            else:
                out[name] = rs.rand(n, *arr.shape[1:]).astype(arr.dtype)
        return out

    feeds = [churn_feed(n) for n in sizes]
    results = {}
    misses = {}
    for level in (0, 2):
        exe = fluid.Executor(opt_level=level)
        exe._disk.enabled = False
        before = _run_mem_misses()
        outs = []
        with fluid.scope_guard(scope):
            for f in feeds:
                outs.append(exe.run(program, feed=f, fetch_list=fetches))
        results[level] = (len(exe._cache), outs)
        misses[level] = _run_mem_misses() - before
    # padded-path parity: mathematically the real rows are unchanged
    # (row-wise is proved by the pass), but XLA's GEMM may reduce in a
    # different order at a different batch dim — compare at ulp
    # tolerance and REPORT the observed bound (see bucketize.py)
    max_diff = 0.0
    parity_close = True
    for o0, o2 in zip(results[0][1], results[2][1]):
        for a, b in zip(o0, o2):
            a64 = np.asarray(a, np.float64)
            b64 = np.asarray(b, np.float64)
            if a64.shape != b64.shape:
                parity_close = False
                continue
            d = float(np.max(np.abs(a64 - b64))) if a64.size else 0.0
            max_diff = max(max_diff, d)
            parity_close = parity_close and bool(
                np.allclose(a64, b64, rtol=2e-5, atol=1e-6))
    bound = len({next_pow2(n) for n in sizes})
    return {
        "bench": "transpile_churn", "schema": SCHEMA,
        "config": config + "-churn", "batch_sizes": list(sizes),
        "distinct_sizes": len(set(sizes)),
        "compiles_raw": results[0][0], "compiles_opt": results[2][0],
        "cache_misses_raw": int(misses[0]),
        "cache_misses_opt": int(misses[2]),
        "bucket_bound": bound,
        "bucket_bound_hit": results[2][0] <= bound,
        "parity_close": bool(parity_close),
        "parity_max_abs_diff": max_diff,
    }


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--configs", default="mlp,deepfm,lstm")
    ap.add_argument("--opt-level", type=int, default=2)
    ap.add_argument("--replicates", type=int, default=5)
    ap.add_argument("--churn-config", default="mlp")
    ap.add_argument("--churn-sizes",
                    default="3,5,6,7,9,11,13,3,5,6,7,9,24,3,5")
    args = ap.parse_args(argv)

    lines = []
    for config in [c for c in args.configs.split(",") if c]:
        line = bench_config(config, args.opt_level, args.replicates)
        lines.append(line)
        print(json.dumps(line), flush=True)
    sizes = [int(s) for s in args.churn_sizes.split(",") if s]
    churn = bench_churn(args.churn_config, sizes)
    print(json.dumps(churn), flush=True)

    summary = {
        "bench": "transpile_summary", "schema": SCHEMA,
        "configs": [ln["config"] for ln in lines],
        "min_op_reduction_frac": min(ln["op_reduction_frac"]
                                     for ln in lines),
        "max_op_reduction_frac": max(ln["op_reduction_frac"]
                                     for ln in lines),
        "min_trace_speedup": min(ln["trace_speedup"] for ln in lines),
        "min_cold_total_speedup": min(ln["cold_total_speedup"]
                                      for ln in lines),
        "churn_compile_ratio": (churn["compiles_raw"]
                                / max(churn["compiles_opt"], 1)),
        "churn_bucket_bound_hit": churn["bucket_bound_hit"],
        "churn_parity_max_abs_diff": churn["parity_max_abs_diff"],
        "all_parity_ok": all(ln["parity_ok"] for ln in lines)
        and churn["parity_close"],
    }
    print(json.dumps(summary), flush=True)
    return 0 if summary["all_parity_ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
