"""Chaos harness: SIGKILL training mid-epoch (and mid-checkpoint-write)
and prove the resume contract.

Two subcommand-ish modes:

``--role run`` — one training process: a small deterministic MLP
regression driven by a ResumableLoop + inline DataLoader, emitting one
JSON line per trained step to ``--ledger`` (appended, flushed):

    {"event": "step", "epoch": E, "offset": K, "global": G,
     "loss": <repr float>, "loss_hex": <bit-exact>, "ids": [...]}

plus a ``start`` line carrying what (if anything) it resumed from and a
``done`` line on clean completion. ``--die-after-step N`` SIGKILLs the
process itself right after global step N (a preemption mid-epoch, no
cleanup, async checkpoint writer included); arming
``PADDLE_TPU_FAULT_KILL=ckpt.before_rename`` (etc., checkpoint/faults)
kills it INSIDE the checkpoint writer instead — mid-write.

default (orchestrator) — runs the full chaos experiment and prints a
verdict JSON line per scenario (schema ``chaos_train/1``):

1. control: uninterrupted run, ledger C.
2. victim: same config, killed (mid-epoch SIGKILL, and/or mid-
   checkpoint-write via --kill-point), ledger V1.
3. resume: fresh process, same checkpoint dir; restores the newest
   COMPLETE checkpoint, ledger V2.
4. checks: (a) the resume actually loaded a checkpoint and partials
   were invisible; (b) the effective trajectory — V1 truncated to the
   restored global step, then V2 — matches C BIT-exactly (loss_hex);
   (c) the effective sample-id ledger equals C's: no sample duplicated
   or dropped across the restart.

Usage:
    python tools/chaos_train.py [--scenario sigkill|midwrite|both]
        [--epochs 2] [--batches 8] [--batch 4] [--step-interval 2]
        [--die-after-step 11] [--dim 8] [--workers 0]

tests/test_chaos_train.py runs the small config in tier-1 (fast
variant) and a larger randomized one under ``-m slow``.
"""
from __future__ import annotations

import argparse
import json
import os
import shutil
import signal
import subprocess
import sys
import tempfile
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

SCHEMA = "chaos_train/1"


# ---------------------------------------------------------------------------
# the training process (--role run)
# ---------------------------------------------------------------------------


def _emit(ledger, obj):
    ledger.write(json.dumps(obj) + "\n")
    ledger.flush()
    os.fsync(ledger.fileno())


class _Source:
    """Deterministic sample source: sample i is a fixed function of i,
    so every process (control, victim, resume) sees byte-identical
    batches, and the sample id rides along as its own column for the
    ledger. Module-level class: picklable for DataLoader worker
    processes (--workers > 0)."""

    def __init__(self, n_samples, dim):
        self.n_samples, self.dim = n_samples, dim

    def __call__(self):
        import numpy as np

        for i in range(self.n_samples):
            rs = np.random.RandomState(1000 + i)
            x = rs.randn(self.dim).astype(np.float32)
            y = np.array([x.sum() * 0.5 + 0.1], np.float32)
            yield (np.array([i], np.int64), x, y)


def _run(args):
    import numpy as np

    import paddle_tpu as fluid
    from paddle_tpu import layers
    from paddle_tpu.checkpoint import ResumableLoop
    from paddle_tpu.io.dataloader import DataLoader
    from paddle_tpu.io.reader import EOFException

    dim, batch, batches = args.dim, args.batch, args.batches
    source = _Source(batches * batch, dim)

    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.scope_guard(scope), fluid.program_guard(main, startup):
        with fluid.unique_name.guard():
            x = layers.data(name="x", shape=[dim])
            y = layers.data(name="y", shape=[1])
            h = layers.fc(x, 16, act="relu")
            pred = layers.fc(h, 1)
            loss = layers.mean(layers.square_error_cost(input=pred,
                                                        label=y))
            fluid.optimizer.Adam(learning_rate=0.01).minimize(loss)

    loader = DataLoader(["sid", "x", "y"],
                        shapes=[[1], [dim], [1]],
                        dtypes=["int64", "float32", "float32"],
                        num_workers=args.workers)
    loader.decorate_sample_reader(source, batch_size=batch,
                                  drop_last=True)

    ledger = open(args.ledger, "a")
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        loop = ResumableLoop(exe, main, args.ckpt_dir, scope=scope,
                             loader=loader,
                             step_interval=args.step_interval,
                             max_pending=2)
        _emit(ledger, {
            "event": "start", "pid": os.getpid(),
            "resumed": ({"serial": loop.resumed_meta.get("_serial"),
                         "epoch": loop.epoch, "offset": loop.offset,
                         "global": loop.global_step}
                        if loop.resumed_meta else None)})
        try:
            for _epoch in loop.epochs(args.epochs):
                loader.start()
                while True:
                    try:
                        feed = loader.next()
                    except EOFException:
                        break
                    ids = [int(v) for v in
                           np.asarray(feed.pop("sid")).ravel()]
                    (lv,) = exe.run(main, feed=feed, fetch_list=[loss])
                    lv = float(np.asarray(lv).ravel()[0])
                    loop.step_done()
                    _emit(ledger, {
                        "event": "step", "epoch": loop.epoch,
                        "offset": loop.offset,
                        "global": loop.global_step, "loss": lv,
                        "loss_hex": float(lv).hex(), "ids": ids})
                    if args.die_after_step == loop.global_step:
                        os.kill(os.getpid(), signal.SIGKILL)
                loop.end_epoch()
            loop.close()
            _emit(ledger, {"event": "done", "global": loop.global_step})
        finally:
            loader.close()
    ledger.close()


# ---------------------------------------------------------------------------
# the orchestrator
# ---------------------------------------------------------------------------


def _spawn(args, ckpt_dir, ledger, *, die_after=0, kill_point=None,
           timeout=600):
    env = dict(os.environ, JAX_PLATFORMS=os.environ.get(
        "JAX_PLATFORMS", "cpu"))
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.pop("PADDLE_TPU_FAULT_KILL", None)
    if kill_point:
        env["PADDLE_TPU_FAULT_KILL"] = kill_point
    cmd = [sys.executable, os.path.abspath(__file__), "--role", "run",
           "--ckpt-dir", ckpt_dir, "--ledger", ledger,
           "--epochs", str(args.epochs), "--batches", str(args.batches),
           "--batch", str(args.batch), "--dim", str(args.dim),
           "--step-interval", str(args.step_interval),
           "--workers", str(args.workers),
           "--die-after-step", str(die_after)]
    t0 = time.perf_counter()
    proc = subprocess.run(cmd, capture_output=True, text=True,
                          timeout=timeout, env=env, cwd=_REPO)
    return proc, time.perf_counter() - t0


def _read_ledger(path):
    events = []
    if os.path.exists(path):
        with open(path) as f:
            for line in f:
                line = line.strip()
                if line:
                    events.append(json.loads(line))
    return events


def _steps(events):
    return [e for e in events if e.get("event") == "step"]


def _effective(v1_steps, v2_start, v2_steps):
    """The training history that counts after a restart: everything the
    killed run trained UP TO the restored checkpoint, then everything
    the resumed run trained."""
    resumed = (v2_start or {}).get("resumed") or {}
    cut = int(resumed.get("global", 0))
    return [s for s in v1_steps if s["global"] <= cut] + list(v2_steps)


def _scenario(args, name, *, die_after=0, kill_point=None, control=None):
    work = tempfile.mkdtemp(prefix="ptpu-chaos-%s-" % name)
    ck = os.path.join(work, "ck")
    out = {"bench": "chaos", "schema": SCHEMA, "scenario": name,
           "epochs": args.epochs, "batches": args.batches,
           "batch": args.batch, "step_interval": args.step_interval,
           "die_after_step": die_after, "kill_point": kill_point}
    try:
        led_v1 = os.path.join(work, "v1.jsonl")
        led_v2 = os.path.join(work, "v2.jsonl")
        victim, _ = _spawn(args, ck, led_v1, die_after=die_after,
                           kill_point=kill_point)
        out["victim_rc"] = victim.returncode
        if victim.returncode == 0:
            out["verdict"] = "fail"
            out["why"] = "victim survived its own kill"
            return out
        # the kill must look like a kill, not a crash with a traceback
        out["victim_sigkill"] = victim.returncode == -signal.SIGKILL
        resume, wall = _spawn(args, ck, led_v2)
        out["resume_rc"] = resume.returncode
        out["resume_wall_s"] = round(wall, 3)
        if resume.returncode != 0:
            out["verdict"] = "fail"
            out["why"] = "resume failed: " + resume.stderr[-2000:]
            return out

        v1 = _read_ledger(led_v1)
        v2 = _read_ledger(led_v2)
        v2_start = next((e for e in v2 if e["event"] == "start"), None)
        out["resumed"] = (v2_start or {}).get("resumed")
        if not out["resumed"]:
            out["verdict"] = "fail"
            out["why"] = "resume found no complete checkpoint"
            return out

        eff = _effective(_steps(v1), v2_start, _steps(v2))
        ctl = _steps(control)
        checks = {}
        # (2) bit-exact loss-trajectory continuation
        ctl_by_g = {s["global"]: s["loss_hex"] for s in ctl}
        eff_by_g = {s["global"]: s["loss_hex"] for s in eff}
        checks["trajectory_bit_exact"] = eff_by_g == ctl_by_g
        # (3) zero duplicated / dropped samples: the effective ledger
        # equals the control's, and within every epoch no id repeats
        ctl_ids = [i for s in ctl for i in s["ids"]]
        eff_ids = [i for s in eff for i in s["ids"]]
        checks["samples_exact"] = eff_ids == ctl_ids
        by_epoch = {}
        for s in eff:
            by_epoch.setdefault(s["epoch"], []).append(s["ids"])
        checks["no_duplicates"] = all(
            len([i for ids in chunks for i in ids])
            == len({i for ids in chunks for i in ids})
            for chunks in by_epoch.values())
        checks["completed"] = any(e["event"] == "done" for e in v2)
        out["checks"] = checks
        out["steps_control"] = len(ctl)
        out["steps_effective"] = len(eff)
        out["verdict"] = "pass" if all(checks.values()) else "fail"
        if out["verdict"] == "fail":
            bad_g = sorted(g for g in set(ctl_by_g) | set(eff_by_g)
                           if ctl_by_g.get(g) != eff_by_g.get(g))[:5]
            out["why"] = "first differing global steps: %s" % bad_g
        return out
    finally:
        shutil.rmtree(work, ignore_errors=True)


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--role", default="chaos", choices=["chaos", "run"],
                    help=argparse.SUPPRESS)
    ap.add_argument("--scenario", default="both",
                    choices=["sigkill", "midwrite", "both"])
    ap.add_argument("--epochs", type=int, default=2)
    ap.add_argument("--batches", type=int, default=8,
                    help="batches per epoch")
    ap.add_argument("--batch", type=int, default=4)
    ap.add_argument("--dim", type=int, default=8)
    ap.add_argument("--step-interval", type=int, default=2)
    ap.add_argument("--workers", type=int, default=0,
                    help="DataLoader worker processes (0 = inline)")
    ap.add_argument("--die-after-step", type=int, default=0,
                    help="run role: SIGKILL self after this global step")
    ap.add_argument("--kill-point", default="ckpt.before_rename",
                    help="midwrite scenario: checkpoint/faults barrier "
                         "for PADDLE_TPU_FAULT_KILL")
    ap.add_argument("--ckpt-dir", default=None, help=argparse.SUPPRESS)
    ap.add_argument("--ledger", default=None, help=argparse.SUPPRESS)
    args = ap.parse_args()

    if args.role == "run":
        _run(args)
        return

    total = args.epochs * args.batches
    die_at = args.die_after_step or (total // 2 + 1)

    # control run (shared by every scenario)
    work = tempfile.mkdtemp(prefix="ptpu-chaos-control-")
    try:
        led_c = os.path.join(work, "control.jsonl")
        ctl_proc, _ = _spawn(args, os.path.join(work, "ck"), led_c)
        if ctl_proc.returncode != 0:
            raise SystemExit("control run failed:\n"
                             + ctl_proc.stderr[-4000:])
        control = _read_ledger(led_c)
    finally:
        pass  # control ledger needed below; removed at exit

    verdicts = []
    try:
        if args.scenario in ("sigkill", "both"):
            # mid-epoch preemption: SIGKILL between steps
            verdicts.append(_scenario(args, "sigkill",
                                      die_after=die_at, control=control))
            print(json.dumps(verdicts[-1]), flush=True)
        if args.scenario in ("midwrite", "both"):
            # die INSIDE the checkpoint writer at the named barrier (the
            # 2nd save, so a complete older checkpoint exists)
            verdicts.append(_scenario(
                args, "midwrite", kill_point="%s:2" % args.kill_point,
                control=control))
            print(json.dumps(verdicts[-1]), flush=True)
    finally:
        shutil.rmtree(work, ignore_errors=True)

    ok = all(v["verdict"] == "pass" for v in verdicts)
    print(json.dumps({"bench": "chaos_summary", "schema": SCHEMA,
                      "scenarios": [v["scenario"] for v in verdicts],
                      "verdict": "pass" if ok else "fail"}), flush=True)
    if not ok:
        raise SystemExit(1)


if __name__ == "__main__":
    main()
