from xprof.convert import raw_to_tool_data as rtd
import glob
fs = glob.glob("/tmp/jaxprof/**/*.xplane.pb", recursive=True)
data, _ = rtd.xspace_to_tool_data(fs, "op_profile", {})
import json
d = json.loads(data)
def walk(node, depth=0, path=""):
    m = node.get("metrics", {})
    name = node.get("name","")
    out = []
    t = m.get("rawTime", 0)
    out.append((t, depth, name))
    for c in node.get("children", []):
        out.extend(walk(c, depth+1, path+"/"+name))
    return out
root = d.get("byProgram") or d.get("byCategory")
rows = walk(root)
rows.sort(reverse=True)
for t, depth, name in rows[:45]:
    print(f"{t/1e9:10.3f}ms  d{depth}  {name[:110]}")
