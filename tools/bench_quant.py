"""Int8 quantization bench: serving rows/s A/B, parity, slab capacity.

Three measurements, one JSON line per config (schema ``bench_quant/1``,
pinned by tests/test_bench_quant_smoke.py):

1. **Serving rows/s** (``quant`` lines): the float serving path
   (optimize-level-2 export) vs the int8 export
   (``save_inference_model(quantize=calib_table)``) of the SAME
   trained-init model, both through ``Predictor.run`` — interleaved
   rounds with arm order alternated per round (the bench_transpile /
   bench_decode discipline), medians reported, ``rows_per_s_speedup``
   = quant / float.

2. **Parity** (embedded in every ``quant`` line): the
   ``quant.parity_report`` fields (max/mean abs logits diff, top-1
   agreement) on held-out batches — a run that breaks parity reports
   ``parity_ok: false`` instead of banking a bogus speedup.

3. **Slab capacity** (``quant_slab`` line): ``kv_slab_slots`` at a
   serving-realistic decode config and byte budget — how many
   continuous-batching sequences one KV slab budget holds at
   float32 / bfloat16 / int8, with ``capacity_ratio_vs_bf16`` the
   2x-sequences claim. Pure arithmetic plus (with ``--decode-roundtrip``)
   an actual int8-slab DecodeServer round trip at the computed slot
   count.

CPU honesty (the PR-8/PR-9 lesson): this box's XLA CPU GEMM has no
int8 fast path — the device-window claim (>=1.5x rows/s on MLP/DeepFM
at matched accuracy, int8 on the MXU) is banked as residue in
PERF_NOTES with this exact command; the numbers here measure the
mechanism and the parity, not the silicon win.

Usage:
    JAX_PLATFORMS=cpu python tools/bench_quant.py \
        [--configs mlp,deepfm] [--rounds 3] [--batches 16] \
        [--batch-rows 256] [--decode-roundtrip]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

import jax

jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import numpy as np

SCHEMA = "bench_quant/1"


def _build(config, batch_rows, rs):
    """(inference program, scope, feed_names, fetch_names, make_feed):
    initialized inference graphs for the serving benches."""
    import paddle_tpu as fluid
    from paddle_tpu import layers

    scope = fluid.Scope()
    main, startup = fluid.Program(), fluid.Program()
    with fluid.scope_guard(scope), fluid.program_guard(main, startup):
        with fluid.unique_name.guard():
            if config in ("mlp", "mlp-tiny"):
                dim = 784 if config == "mlp" else 16
                x = layers.data(name="pixel", shape=[dim])
                if config == "mlp":
                    from paddle_tpu.models.mnist import mlp_model

                    predict = mlp_model(x)
                else:
                    predict = layers.fc(layers.fc(x, 8, act="relu"), 4,
                                        act="softmax")
                feed_names = ["pixel"]
                fetches = [predict.name]

                def make_feed():
                    return {"pixel": rs.rand(batch_rows, dim)
                            .astype(np.float32)}
            elif config == "deepfm":
                from paddle_tpu.models.deepfm import deepfm_net

                feat_ids = layers.data(name="feat_ids", shape=[10],
                                       dtype="int64")
                dense = layers.data(name="dense", shape=[13])
                label = layers.data(name="label", shape=[1],
                                    dtype="int64")
                _cost, prob = deepfm_net(feat_ids, dense, label,
                                         num_features=1000,
                                         num_fields=10)
                feed_names = ["feat_ids", "dense", "label"]
                fetches = [prob.name]

                def make_feed():
                    return {
                        "feat_ids": rs.randint(0, 1000, (batch_rows, 10))
                        .astype(np.int64),
                        "dense": rs.rand(batch_rows, 13)
                        .astype(np.float32),
                        "label": rs.randint(0, 2, (batch_rows, 1))
                        .astype(np.int64),
                    }
            else:
                raise SystemExit("unknown config %r" % config)
        exe = fluid.Executor()
        with fluid.scope_guard(scope):
            exe.run(startup)
    infer = main.clone(for_test=True)
    return infer, scope, feed_names, fetches, make_feed


def _rows_per_s(predictor, feeds):
    t0 = time.perf_counter()
    for f in feeds:
        predictor.run(f)
    dt = time.perf_counter() - t0
    rows = sum(next(iter(f.values())).shape[0] for f in feeds)
    return rows / dt


def bench_config(config, rounds, batches, batch_rows, calib_batches):
    import tempfile

    import paddle_tpu as fluid
    from paddle_tpu.inference import Predictor
    from paddle_tpu.quant import calibrate, parity_report

    rs = np.random.RandomState(0)
    infer, scope, feed_names, fetches, make_feed = _build(
        config, batch_rows, rs)
    calib_feeds = [make_feed() for _ in range(calib_batches)]
    table = calibrate(infer, scope, feed_names, calib_feeds,
                      max_batches=calib_batches)

    td = tempfile.mkdtemp(prefix="bench_quant_")
    float_dir = os.path.join(td, "float")
    quant_dir = os.path.join(td, "int8")
    exe = fluid.Executor()
    with fluid.scope_guard(scope):
        fluid.io.save_inference_model(
            float_dir, feed_names, fetches, exe, main_program=infer,
            scope=scope, optimize=2)
        fluid.io.save_inference_model(
            quant_dir, feed_names, fetches, exe, main_program=infer,
            scope=scope, quantize=table)

    p_float = Predictor(float_dir, aot_cache=False)
    p_quant = Predictor(quant_dir, aot_cache=False)
    bench_feeds = [make_feed() for _ in range(batches)]
    # warm both arms (compile outside the measured window)
    _rows_per_s(p_float, bench_feeds[:1])
    _rows_per_s(p_quant, bench_feeds[:1])

    f_rates, q_rates = [], []
    for rep in range(rounds):
        arms = [("float", p_float, f_rates), ("int8", p_quant, q_rates)]
        if rep % 2:
            arms.reverse()
        for _name, pred, acc in arms:
            acc.append(_rows_per_s(pred, bench_feeds))
    f_med = float(np.median(f_rates))
    q_med = float(np.median(q_rates))

    held_out = [make_feed() for _ in range(4)]
    par = parity_report(p_float, p_quant, held_out,
                        logits_tol=0.05, metric_tol=0.02)
    return {
        "bench": "quant", "schema": SCHEMA, "config": config,
        "rounds": rounds, "batches": batches, "batch_rows": batch_rows,
        "calib_batches": table.batches,
        "quantized_ops": int(
            (json.load(open(os.path.join(quant_dir, "__model__")))
             ["program"].get("quantized") or {}).get("ops", 0)),
        "rows_per_s_float": [round(r, 2) for r in f_rates],
        "rows_per_s_int8": [round(r, 2) for r in q_rates],
        "rows_per_s_float_median": round(f_med, 2),
        "rows_per_s_int8_median": round(q_med, 2),
        "rows_per_s_speedup": round(q_med / f_med, 4) if f_med else None,
        "parity_max_abs_diff": par["max_abs_diff"],
        "parity_mean_abs_diff": par["mean_abs_diff"],
        "parity_metric_agreement": par["metric_agreement"],
        "parity_ok": par["ok"],
    }


def bench_slab(decode_roundtrip: bool):
    """KV-slab capacity at a serving-realistic decode config: slots per
    byte budget by slab dtype (+ an int8 DecodeServer round trip at the
    computed slot count when requested)."""
    from paddle_tpu.serving.decode import DecodeConfig, kv_slab_slots

    cfg = DecodeConfig(vocab_size=32768, n_layer=12, n_head=8,
                       d_model=1024, d_inner=4096, max_len=2048)
    seq = 1024
    budget = 256 << 20  # 256 MiB of slab per replica
    slots = {dt: kv_slab_slots(budget, cfg, seq, dt)
             for dt in ("float32", "bfloat16", "int8")}
    line = {
        "bench": "quant_slab", "schema": SCHEMA,
        "config": "lm-%dx%d" % (cfg.n_layer, cfg.d_model),
        "seq": seq, "budget_bytes": budget,
        "slots_float32": slots["float32"],
        "slots_bfloat16": slots["bfloat16"],
        "slots_int8": slots["int8"],
        "capacity_ratio_vs_bf16": round(
            slots["int8"] / max(slots["bfloat16"], 1), 4),
        "decode_roundtrip": None,
    }
    if decode_roundtrip:
        line["decode_roundtrip"] = _decode_roundtrip()
    return line


def _decode_roundtrip():
    """Tiny-LM int8-slab DecodeServer round trip: at one slab byte
    budget the int8 server admits 2x the bf16 slot count and completes
    every sequence."""
    import tempfile

    import paddle_tpu as fluid
    from paddle_tpu import layers
    from paddle_tpu.models import transformer as _T
    from paddle_tpu.serving.decode import (
        DecodeConfig, DecodePredictor, DecodeServer, kv_slab_slots,
        save_decode_model)

    cfg = DecodeConfig(vocab_size=64, n_layer=2, n_head=2, d_model=32,
                       d_inner=64, max_len=64)
    seq = 32
    scope = fluid.Scope()
    mdir = os.path.join(tempfile.mkdtemp(prefix="bench_quant_kv_"), "m")
    with fluid.scope_guard(scope):
        exe = fluid.Executor()
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            with fluid.unique_name.guard():
                tokens = layers.data(name="tokens", shape=[2, 16],
                                     dtype="int64",
                                     append_batch_size=False)
                lengths = layers.data(name="lengths", shape=[2],
                                      dtype="int32",
                                      append_batch_size=False)
                _T.transformer_lm_prefill(
                    tokens, lengths, cfg.vocab_size, n_layer=cfg.n_layer,
                    n_head=cfg.n_head, d_model=cfg.d_model,
                    d_inner=cfg.d_inner, max_len=cfg.max_len)
        exe.run(startup)
        save_decode_model(mdir, cfg, exe, scope=scope)
    # a budget sized to 4 int8 slots -> 2 bf16 slots
    budget = 4 * 2 * cfg.n_layer * seq * (cfg.n_head * cfg.d_head + 4)
    slots_i8 = kv_slab_slots(budget, cfg, seq, "int8")
    slots_bf = kv_slab_slots(budget, cfg, seq, "bfloat16")
    pred = DecodePredictor(mdir, aot_cache=False)
    srv = DecodeServer(pred, slots=slots_i8, max_seq=seq,
                       max_new_tokens=4, strategy="greedy",
                       prewarm=False, kv_dtype="int8")
    srv.start()
    prompts = [np.arange(1, 4 + i) % 60 + 1 for i in range(slots_i8)]
    futs = [srv.submit((p,)) for p in prompts]
    outs = [f.result(timeout=240)[0] for f in futs]
    srv.stop()
    return {
        "slots_int8": slots_i8, "slots_bf16": slots_bf,
        "sequences_served": len(outs),
        "all_completed": all(len(o) == 4 for o in outs),
    }


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--configs", default="mlp,deepfm")
    ap.add_argument("--rounds", type=int, default=3)
    ap.add_argument("--batches", type=int, default=16)
    ap.add_argument("--batch-rows", type=int, default=256)
    ap.add_argument("--calib-batches", type=int, default=4)
    ap.add_argument("--decode-roundtrip", action="store_true",
                    help="run the int8-slab DecodeServer round trip "
                         "inside the quant_slab line")
    args = ap.parse_args(argv)

    lines = []
    for config in [c for c in args.configs.split(",") if c]:
        line = bench_config(config, args.rounds, args.batches,
                            args.batch_rows, args.calib_batches)
        lines.append(line)
        print(json.dumps(line), flush=True)
    slab = bench_slab(args.decode_roundtrip)
    print(json.dumps(slab), flush=True)

    summary = {
        "bench": "quant_summary", "schema": SCHEMA,
        "configs": [ln["config"] for ln in lines],
        "min_speedup": min(ln["rows_per_s_speedup"] for ln in lines),
        "max_speedup": max(ln["rows_per_s_speedup"] for ln in lines),
        "max_parity_abs_diff": max(ln["parity_max_abs_diff"]
                                   for ln in lines),
        "all_parity_ok": all(ln["parity_ok"] for ln in lines),
        "capacity_ratio_vs_bf16": slab["capacity_ratio_vs_bf16"],
    }
    print(json.dumps(summary), flush=True)
    return 0 if summary["all_parity_ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
