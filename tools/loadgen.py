"""Traffic generator + SLO verdict for the serving fleet (loadgen/2).

The fleet's latency contract is only as real as the traffic it was
proven under. This tool generates that traffic against a live Router —
open-loop (Poisson arrivals at a target rate: the millions-of-users
shape, where clients do NOT slow down because the fleet did) and
closed-loop (N clients back to back: the benchmark shape) — through
diurnal ramps, bursts, and heavy-tail per-arrival fan-out, with every
request submitted under an SLO class (priority + deadline). It records
per-class latency percentiles, every structured shed reject, and the
fleet counters, and emits ONE JSON verdict line per run (schema
``loadgen/2``; ``--curve`` sweeps offered load and emits one line per
level — the latency-vs-offered-load curve for PERF_NOTES).

loadgen/2 adds ``trace_phases``: per-phase p50/p99 latency attribution
pulled from the distributed-tracing flight recorder
(``router.fleet_trace()``), keyed by span name (router.queue,
server.device, worker.reply, ...). It is ``{}`` unless sampling is
armed (``--trace-sample`` / ``PADDLE_TPU_TRACE_SAMPLE``) — the verdict
costs nothing when tracing is off. All loadgen/1 fields are unchanged.

Traffic is scripted: ``--shape steady|burst|diurnal`` builds a trace,
``--trace FILE`` loads one:

    {"name": "evening-burst",
     "classes": {"interactive": {"priority": 0, "deadline_ms": 500,
                                 "weight": 0.8},
                 "batch": {"priority": 2, "weight": 0.2}},
     "phases": [
       {"duration_s": 2.0, "rps": 50, "mode": "open"},
       {"duration_s": 1.0, "rps": 250, "mode": "open",
        "fanout": {"dist": "pareto", "alpha": 1.4, "max": 16}},
       {"duration_s": 2.0, "rps": 50, "mode": "open"}]}

Chaos riders: ``--chaos-kill T`` SIGKILLs a random ready replica T
seconds into the trace (the PR-8 crash-requeue path must absorb it);
``--autoscale MIN:MAX`` runs the Autoscaler so the trace drives real
scale-up/drain-shrink. The verdict is strict: ``ok`` requires zero
dropped futures (every request answered — result OR explicit reject),
zero non-reject errors, and zero misversioned responses.

Usage:
    JAX_PLATFORMS=cpu python tools/loadgen.py --model-dir DIR \
        --shape burst --rps 100 --duration 6 --replicas 2 \
        --autoscale 1:3 --chaos-kill 3 --json
"""
from __future__ import annotations

import argparse
import json
import math
import os
import random
import sys
import threading
import time
from typing import Callable, Dict, List, Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

SCHEMA = "loadgen/2"

DEFAULT_CLASSES = {
    "interactive": {"priority": 0, "deadline_ms": None, "weight": 0.7},
    "batch": {"priority": 2, "deadline_ms": None, "weight": 0.3},
}


# -- traces ----------------------------------------------------------------

def build_shape(shape: str, rps: float, duration_s: float,
                burst_x: float = 4.0, clients: int = 4,
                mode: str = "open", diurnal_slices: int = 8) -> Dict:
    """A scripted trace from a named shape. ``steady`` = one flat phase;
    ``burst`` = baseline, a ``burst_x`` Poisson burst in the middle
    fifth, baseline again; ``diurnal`` = a sinusoidal ramp approximated
    by ``diurnal_slices`` flat slices (peak = ``rps``, trough =
    rps/4)."""
    phases: List[Dict]
    if shape == "steady":
        phases = [{"duration_s": duration_s, "rps": rps, "mode": mode,
                   "clients": clients}]
    elif shape == "burst":
        edge = duration_s * 0.4
        phases = [
            {"duration_s": edge, "rps": rps, "mode": mode,
             "clients": clients},
            {"duration_s": duration_s - 2 * edge, "rps": rps * burst_x,
             "mode": mode, "clients": clients,
             "fanout": {"dist": "pareto", "alpha": 1.4, "max": 16}},
            {"duration_s": edge, "rps": rps, "mode": mode,
             "clients": clients},
        ]
    elif shape == "diurnal":
        phases = []
        for i in range(diurnal_slices):
            # peak at mid-trace; trough = peak/4
            frac = 0.5 - 0.5 * math.cos(2 * math.pi * (i + 0.5)
                                        / diurnal_slices)
            phases.append({"duration_s": duration_s / diurnal_slices,
                           "rps": rps * (0.25 + 0.75 * frac),
                           "mode": mode, "clients": clients})
    else:
        raise ValueError("unknown shape %r (steady|burst|diurnal)" % shape)
    return {"name": shape, "classes": dict(DEFAULT_CLASSES),
            "phases": phases}


def load_trace(path: str) -> Dict:
    with open(path) as f:
        trace = json.load(f)
    if not isinstance(trace.get("phases"), list) or not trace["phases"]:
        raise ValueError("trace %s: 'phases' must be a non-empty list"
                         % path)
    for i, ph in enumerate(trace["phases"]):
        if "duration_s" not in ph:
            raise ValueError("trace %s: phase %d has no duration_s"
                             % (path, i))
    trace.setdefault("name", os.path.basename(path))
    trace.setdefault("classes", dict(DEFAULT_CLASSES))
    return trace


def slo_classes_of(trace: Dict):
    """Router slo_classes built from the trace's class table."""
    from paddle_tpu.serving import SLOClass

    out = {}
    for name, cfg in trace["classes"].items():
        out[name] = SLOClass(name, int(cfg.get("priority", 1)),
                             cfg.get("deadline_ms"))
    out.setdefault("standard", SLOClass("standard", 1))
    return out


# -- recording -------------------------------------------------------------

class _Recorder:
    """Thread-safe per-class outcome ledger fed by done callbacks."""

    def __init__(self, classes):
        self._lock = threading.Lock()
        self._done_ev = threading.Event()
        self.offered = 0
        self.completed = 0
        self.lat: Dict[str, List[float]] = {k: [] for k in classes}
        self.rejected: Dict[str, int] = {k: 0 for k in classes}
        self.errors: Dict[str, int] = {k: 0 for k in classes}

    def submitted(self, klass: str):
        with self._lock:
            self.offered += 1
            self.lat.setdefault(klass, [])
            self.rejected.setdefault(klass, 0)
            self.errors.setdefault(klass, 0)

    def done(self, klass: str, t0: float, fut):
        from paddle_tpu.serving import RejectedError

        try:
            fut.result(timeout=0)
            status = "ok"
        except RejectedError:
            status = "rejected"
        except Exception:
            status = "error"
        with self._lock:
            self.completed += 1
            if status == "ok":
                self.lat[klass].append((time.perf_counter() - t0) * 1e3)
            elif status == "rejected":
                self.rejected[klass] += 1
            else:
                self.errors[klass] += 1
            if self.completed >= self.offered:
                self._done_ev.set()

    def wait_all(self, timeout: float) -> int:
        """Block until every offered request completed (result OR
        reject); returns the number still unanswered — MUST be 0, a
        nonzero value is the hang the shedding contract forbids."""
        deadline = time.monotonic() + timeout
        while True:
            with self._lock:
                remaining = self.offered - self.completed
                if remaining == 0:
                    return 0
                self._done_ev.clear()
            left = deadline - time.monotonic()
            if left <= 0:
                return remaining
            self._done_ev.wait(min(left, 0.5))


def _pctl(xs: List[float], q: float) -> Optional[float]:
    if not xs:
        return None
    xs = sorted(xs)
    i = min(len(xs) - 1, int(math.ceil(q / 100.0 * len(xs))) - 1)
    return xs[max(0, i)]


# -- request sources -------------------------------------------------------

def dense_sampler(model_dir: str, seed: int = 0, pool: int = 64):
    """(prime the AOT cache, return a sample factory) for a dense model:
    random rows matching the exported feed signature. The direct
    Predictor run here is what makes every fleet worker warm-start."""
    import numpy as np

    from paddle_tpu.inference import Predictor

    p = Predictor(model_dir)
    rs = np.random.RandomState(seed)
    block = p._program.global_block()
    rows = []
    for _ in range(pool):
        sample = []
        for name in p.feed_names:
            var = block.var(name)
            shape = tuple(int(d) for d in var.shape[1:])
            dt = np.dtype(var.dtype)
            if dt.kind in "iu":
                sample.append(rs.randint(0, 8, size=shape).astype(dt))
            else:
                sample.append(rs.uniform(-1, 1, size=shape).astype(dt))
        rows.append(tuple(sample))
    p.run({n: np.stack([r[i] for r in rows[:4]])
           for i, n in enumerate(p.feed_names)})
    idx = [0]

    def next_sample():
        idx[0] = (idx[0] + 1) % pool
        return rows[idx[0]]

    return next_sample


def decode_sampler(vocab: int = 100, max_prompt: int = 24, seed: int = 0,
                   alpha: float = 1.3):
    """Heavy-tail prompt lengths (bounded Pareto) for decode traffic —
    the request-SIZE tail that makes continuous batching earn its
    keep."""
    import numpy as np

    rs = np.random.RandomState(seed)

    def next_sample():
        n = min(max_prompt, max(1, int(rs.pareto(alpha) + 1)))
        return (rs.randint(1, vocab, size=(n,)).astype(np.int32),)

    return next_sample


# -- the trace runner ------------------------------------------------------

def run_trace(router, trace: Dict, next_sample: Callable, seed: int = 0,
              result_timeout: float = 120.0,
              samplers: Optional[Dict[str, Callable]] = None) -> Dict:
    """Drive `trace` through `router.submit` and return the loadgen/1
    report. ``samplers`` optionally maps a class name to its own sample
    factory (e.g. decode-class prompts vs dense rows); everything else
    uses ``next_sample``."""
    from paddle_tpu import observability as obs

    classes = trace["classes"]
    names = sorted(classes)
    weights = [float(classes[k].get("weight", 1.0)) for k in names]
    rec = _Recorder(names)
    rng = random.Random(seed)
    samplers = samplers or {}

    def submit_one(klass: str):
        cfg = classes[klass]
        sample = samplers.get(klass, next_sample)()
        rec.submitted(klass)
        t0 = time.perf_counter()
        try:
            fut = router.submit(
                sample, slo=klass,
                deadline_ms=cfg.get("deadline_ms"),
                priority=cfg.get("priority"))
        except Exception:
            with rec._lock:
                rec.errors[klass] += 1
                rec.completed += 1
            return
        fut.add_done_callback(
            lambda f, k=klass, t=t0: rec.done(k, t, f))

    def draw_class() -> str:
        return rng.choices(names, weights=weights)[0]

    def draw_fanout(ph: Dict) -> int:
        fo = ph.get("fanout")
        if not fo or fo.get("dist", "fixed") == "fixed":
            return int((fo or {}).get("n", 1))
        k = int(rng.paretovariate(float(fo.get("alpha", 1.4))))
        return max(1, min(int(fo.get("max", 16)), k))

    mis0 = obs.FLEET_MISVERSIONED.total()
    shed0 = obs.FLEET_SHED.total()
    req0 = obs.FLEET_REQUEUED.total()
    replicas0 = router.stats()["ready"]
    phase_stats = []
    t_start = time.perf_counter()
    for ph in trace["phases"]:
        ph_offered0 = rec.offered
        dur = float(ph["duration_s"])
        mode = ph.get("mode", "open")
        end = time.perf_counter() + dur
        if mode == "closed":
            stop_ev = threading.Event()

            def client():
                while not stop_ev.is_set():
                    cfg_k = draw_class()
                    sample = samplers.get(cfg_k, next_sample)()
                    rec.submitted(cfg_k)
                    t0 = time.perf_counter()
                    try:
                        fut = router.submit(
                            sample, slo=cfg_k,
                            deadline_ms=classes[cfg_k].get("deadline_ms"),
                            priority=classes[cfg_k].get("priority"))
                        rec.done(cfg_k, t0, _waited(fut, result_timeout))
                    except Exception:
                        with rec._lock:
                            rec.errors[cfg_k] += 1
                            rec.completed += 1

            threads = [threading.Thread(target=client, daemon=True)
                       for _ in range(int(ph.get("clients", 4)))]
            for t in threads:
                t.start()
            time.sleep(dur)
            stop_ev.set()
            for t in threads:
                t.join(timeout=result_timeout)
        else:  # open loop: Poisson arrivals at ph["rps"]
            rps = float(ph.get("rps", 10.0))
            next_t = time.perf_counter()
            while True:
                now = time.perf_counter()
                if now >= end:
                    break
                next_t += rng.expovariate(rps) if rps > 0 else dur
                delay = next_t - time.perf_counter()
                if delay > 0:
                    time.sleep(min(delay, end - now))
                    if time.perf_counter() >= end:
                        break
                for _ in range(draw_fanout(ph)):
                    submit_one(draw_class())
        phase_stats.append({"mode": mode, "rps": ph.get("rps"),
                            "duration_s": dur,
                            "offered": rec.offered - ph_offered0})
    dropped = rec.wait_all(result_timeout)
    wall_s = time.perf_counter() - t_start

    per_class = {}
    for k in sorted(rec.lat):
        lats = rec.lat[k]
        dl = classes.get(k, {}).get("deadline_ms")
        met = (None if dl is None or not lats
               else sum(1 for x in lats if x <= dl) / len(lats))
        per_class[k] = {
            "count": len(lats) + rec.rejected[k] + rec.errors[k],
            "ok": len(lats),
            "rejected": rec.rejected[k],
            "errors": rec.errors[k],
            "p50_ms": _pctl(lats, 50), "p90_ms": _pctl(lats, 90),
            "p99_ms": _pctl(lats, 99),
            "mean_ms": (sum(lats) / len(lats)) if lats else None,
            "deadline_ms": dl, "deadline_met_frac": met,
        }
    st = router.stats()
    report = {
        "schema": SCHEMA,
        "trace": trace.get("name", "trace"),
        "duration_s": round(wall_s, 3),
        "offered": rec.offered,
        "completed": rec.completed,
        "rejected": sum(rec.rejected.values()),
        "errors": sum(rec.errors.values()),
        "dropped": dropped,
        "achieved_rps": round(rec.offered / wall_s, 2) if wall_s else 0.0,
        "per_class": per_class,
        "phases": phase_stats,
        "fleet": {
            "replicas_start": replicas0,
            "replicas_end": st["ready"],
            "shed_total": obs.FLEET_SHED.total() - shed0,
            "requeued": obs.FLEET_REQUEUED.total() - req0,
            "misversioned": obs.FLEET_MISVERSIONED.total() - mis0,
        },
        "ok": (dropped == 0 and sum(rec.errors.values()) == 0
               and obs.FLEET_MISVERSIONED.total() - mis0 == 0),
    }
    # a shed that was never surfaced as a reject would be a silent drop:
    # the shed counter and the rejects the clients saw must agree
    report["sheds_all_rejected"] = (
        report["fleet"]["shed_total"] == report["rejected"])
    # loadgen/2: per-phase latency attribution from the fleet's trace
    # recorders — WHERE the p99 went (queue vs device vs stacking), not
    # just how big it was. Empty unless sampling is armed.
    phase_ms: Dict[str, List[float]] = {}
    fleet_trace = getattr(router, "fleet_trace", None)
    if fleet_trace is not None:
        try:
            for s in fleet_trace(timeout=10.0).get("spans", ()):
                phase_ms.setdefault(s["name"], []).append(
                    float(s.get("dur_ms", 0.0)))
        except Exception:
            pass
    report["trace_phases"] = {
        name: {"count": len(xs),
               "p50_ms": _pctl(xs, 50), "p99_ms": _pctl(xs, 99)}
        for name, xs in sorted(phase_ms.items())}
    return report


def _waited(fut, timeout):
    """Closed-loop helper: wait the future out, hand it back completed
    (Recorder.done re-reads the result with timeout=0)."""
    try:
        fut.result(timeout=timeout)
    except Exception:
        pass
    return fut


def chaos_kill_after(router, delay_s: float) -> threading.Timer:
    """Arm a SIGKILL of a random ready replica `delay_s` seconds from
    now (the mid-burst preemption the crash-requeue path must absorb)."""
    def kill():
        with router._cond:
            ready = [w for w in router._workers if w.state == "ready"]
        if ready:
            victim = random.choice(ready)
            victim.proc.kill()
            sys.stderr.write("[loadgen] chaos: SIGKILLed %s\n"
                             % victim.name)
    t = threading.Timer(delay_s, kill)
    t.daemon = True
    t.start()
    return t


# -- CLI -------------------------------------------------------------------

def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--model-dir", required=True)
    ap.add_argument("--trace", help="scripted trace JSON file")
    ap.add_argument("--shape", default="steady",
                    choices=("steady", "burst", "diurnal"))
    ap.add_argument("--rps", type=float, default=50.0)
    ap.add_argument("--duration", type=float, default=5.0)
    ap.add_argument("--burst-x", type=float, default=4.0)
    ap.add_argument("--mode", default="open", choices=("open", "closed"))
    ap.add_argument("--clients", type=int, default=4,
                    help="closed-loop clients per phase")
    ap.add_argument("--deadline-ms", type=float, default=None,
                    help="arm this deadline on the interactive class")
    ap.add_argument("--replicas", type=int, default=1)
    ap.add_argument("--max-batch", type=int, default=8)
    ap.add_argument("--max-outstanding", type=int, default=None)
    ap.add_argument("--decode", action="store_true",
                    help="decode fleet: heavy-tail prompts through "
                         "Router(decode=True)")
    ap.add_argument("--max-new-tokens", type=int, default=16)
    ap.add_argument("--autoscale", metavar="MIN:MAX",
                    help="run the Autoscaler across the trace")
    ap.add_argument("--chaos-kill", type=float, default=None,
                    metavar="T", help="SIGKILL a random replica T "
                    "seconds into the trace")
    ap.add_argument("--curve", metavar="RPS,RPS,...",
                    help="sweep offered load, one loadgen/1 line per "
                         "level (the latency-vs-offered-load curve)")
    ap.add_argument("--trace-sample", type=float, default=None,
                    metavar="RATE", help="arm distributed tracing at "
                    "this sample rate (0..1); fills the verdict's "
                    "trace_phases attribution")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--result-timeout", type=float, default=120.0)
    ap.add_argument("--start-timeout", type=float, default=300.0)
    ap.add_argument("--json", action="store_true",
                    help="emit ONLY the JSON verdict line(s)")
    args = ap.parse_args()

    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    if args.trace:
        trace = load_trace(args.trace)
    else:
        trace = build_shape(args.shape, args.rps, args.duration,
                            burst_x=args.burst_x, clients=args.clients,
                            mode=args.mode)
    if args.deadline_ms is not None:
        trace["classes"].setdefault("interactive", {"priority": 0,
                                                    "weight": 0.7})
        trace["classes"]["interactive"]["deadline_ms"] = args.deadline_ms

    from paddle_tpu.serving import Autoscaler, Router

    if args.trace_sample is not None:
        # the ONE sampling decision lives at the client edge (here);
        # workers record on header arrival and need no configuration
        from paddle_tpu.observability import tracing
        tracing.set_sample_rate(args.trace_sample)

    levels = ([float(x) for x in args.curve.split(",")] if args.curve
              else [None])
    for level in levels:
        t = json.loads(json.dumps(trace))  # deep copy per level
        if level is not None:
            for ph in t["phases"]:
                if "rps" in ph and ph["rps"]:
                    ph["rps"] = level
            t["name"] = "%s@%g" % (t["name"], level)
        router = Router(
            args.model_dir, replicas=args.replicas,
            max_batch=args.max_batch,
            max_outstanding=args.max_outstanding,
            jax_platform=os.environ.get("JAX_PLATFORMS") or None,
            start_timeout=args.start_timeout,
            decode=args.decode,
            max_new_tokens=args.max_new_tokens,
            slo_classes=slo_classes_of(t))
        if args.decode:
            next_sample = decode_sampler(seed=args.seed)
        else:
            next_sample = dense_sampler(args.model_dir, seed=args.seed)
        router.start()
        scaler = None
        timer = None
        try:
            if args.autoscale:
                lo, hi = (int(x) for x in args.autoscale.split(":"))
                scaler = Autoscaler(router, min_replicas=lo,
                                    max_replicas=hi, interval_s=0.5,
                                    cooldown_s=2.0, down_ticks=4,
                                    spawn_timeout=args.start_timeout)
                scaler.start()
            if args.chaos_kill is not None:
                timer = chaos_kill_after(router, args.chaos_kill)
            report = run_trace(router, t, next_sample, seed=args.seed,
                               result_timeout=args.result_timeout)
            if level is not None:
                report["offered_rps_target"] = level
            if scaler is not None:
                ups = sum(1 for _t, d in scaler.actions if d == "up")
                downs = sum(1 for _t, d in scaler.actions if d == "down")
                heals = sum(1 for _t, d in scaler.actions if d == "heal")
                report["fleet"]["autoscale"] = {
                    "up": ups, "down": downs, "heal": heals}
            print(json.dumps(report, sort_keys=True))
        finally:
            if timer is not None:
                timer.cancel()
            if scaler is not None:
                scaler.stop()
            router.stop()
        if not args.json and not report.get("ok"):
            sys.stderr.write("[loadgen] verdict NOT ok: dropped=%s "
                             "errors=%s misversioned=%s\n"
                             % (report["dropped"], report["errors"],
                                report["fleet"]["misversioned"]))
            sys.exit(1)


if __name__ == "__main__":
    main()
