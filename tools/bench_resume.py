"""Async-checkpoint overhead + preemption warm-restart time-to-first-step.

Two measurements per config, one JSON line per config (schema
``bench_resume/1``, pinned by tests/test_bench_resume_smoke.py):

1. **Overhead** (in-process, interleaved A/B): steps/s of a training
   loop with NO checkpointing vs the same loop with a ResumableLoop
   async-checkpointing every ``--step-interval`` batches
   (CheckpointManager background writer, max_pending=2). The timed
   window includes any save() blocking — a writer that can't keep up
   shows up as lost throughput, not as a hidden drain afterwards.
   ``overhead_frac`` = 1 - ckpt/plain (acceptance: < 0.05 at
   step_interval=10).

2. **Warm restart** (fresh subprocesses, the bench_coldstart
   methodology): a prime child trains + checkpoints (filling the AOT
   executable cache and the checkpoint dir), then interleaved restart
   children restore the newest checkpoint and run the first
   post-resume step — cold (EMPTY AOT cache: pays trace + XLA compile)
   vs warm (primed cache: deserializes). ``warm_restart_speedup`` =
   cold_median / warm_median (acceptance: >= 3x) — what a preempted
   job actually pays before its first post-resume step.

Usage:
    JAX_PLATFORMS=cpu python tools/bench_resume.py \
        [--configs mlp,deepfm] [--steps 60] [--step-interval 10] \
        [--replicates 3] [--restart-replicates 3]
"""
from __future__ import annotations

import argparse
import json
import os
import shutil
import subprocess
import sys
import tempfile
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

SCHEMA = "bench_resume/1"

# config name -> builder parameters (see _build). Batches are sized like
# the bench.py training configs (production CTR/MLP batches), NOT toy
# sizes: the overhead measurement divides per-save cost by interval x
# step time, so an unrealistically light step overstates the overhead.
CONFIGS = {
    "mlp": {"kind": "mlp", "in_dim": 64, "widths": (512, 512, 512),
            "batch": 1024},
    "mlp-wide": {"kind": "mlp", "in_dim": 256,
                 "widths": (1024, 1024, 1024, 1024), "batch": 256},
    "deepfm": {"kind": "deepfm", "num_features": 10000, "num_fields": 10,
               "dense_dim": 13, "batch": 1024},
    "mlp-tiny": {"kind": "mlp", "in_dim": 8, "widths": (16,), "batch": 4},
}


def _build(config: str):
    """(main, startup, scope, feed, loss_name) for one config."""
    import numpy as np

    import paddle_tpu as fluid
    from paddle_tpu import layers

    cfg = CONFIGS[config]
    rs = np.random.RandomState(0)
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.scope_guard(scope), fluid.program_guard(main, startup):
        with fluid.unique_name.guard():
            if cfg["kind"] == "mlp":
                x = layers.data(name="x", shape=[cfg["in_dim"]])
                y = layers.data(name="y", shape=[1])
                h = x
                for w in cfg["widths"]:
                    h = layers.fc(h, w, act="relu")
                loss = layers.mean(layers.square(layers.fc(h, 1) - y))
                feed = {"x": rs.rand(cfg["batch"], cfg["in_dim"])
                        .astype(np.float32),
                        "y": rs.rand(cfg["batch"], 1).astype(np.float32)}
            else:  # deepfm
                from paddle_tpu.models.deepfm import get_model

                loss, _prob, _feeds = get_model(
                    num_features=cfg["num_features"],
                    num_fields=cfg["num_fields"],
                    dense_dim=cfg["dense_dim"])
                feed = {
                    "feat_ids": rs.randint(
                        0, cfg["num_features"],
                        (cfg["batch"], cfg["num_fields"])).astype(np.int64),
                    "dense": rs.rand(cfg["batch"], cfg["dense_dim"])
                    .astype(np.float32),
                    "label": rs.randint(0, 2, (cfg["batch"], 1))
                    .astype(np.int64),
                }
            fluid.optimizer.Adam(learning_rate=1e-3).minimize(loss)
    return main, startup, scope, feed, loss.name


def _overhead(config: str, steps: int, step_interval: int,
              replicates: int):
    """Interleaved plain-vs-checkpointed steps/s, one pair per
    replicate. The two arms ALTERNATE order across replicates (CPU
    governors ramp frequency through a run, so a fixed order
    systematically flatters whichever arm goes second), and the async
    writer is drained UNTIMED between arms so a checkpoint tail never
    bleeds into the plain arm's window. Saves queued during the timed
    ckpt window still compete with the steps — that contention IS the
    overhead being measured."""
    import paddle_tpu as fluid
    from paddle_tpu.checkpoint import ResumableLoop

    main, startup, scope, feed, loss_name = _build(config)
    plain, ckpt, saves = [], [], 0
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        for _ in range(5):  # compile + settle
            exe.run(main, feed=feed, fetch_list=[loss_name])

        def run_plain():
            t0 = time.perf_counter()
            for _ in range(steps):
                exe.run(main, feed=feed, fetch_list=[loss_name])
            plain.append(steps / (time.perf_counter() - t0))

        def run_ckpt():
            nonlocal saves
            ckdir = tempfile.mkdtemp(prefix="ptpu-bench-resume-ov-")
            try:
                loop = ResumableLoop(exe, main, ckdir, scope=scope,
                                     step_interval=step_interval,
                                     max_pending=2)
                t0 = time.perf_counter()
                for _ in range(steps):
                    exe.run(main, feed=feed, fetch_list=[loss_name])
                    loop.step_done()
                ckpt.append(steps / (time.perf_counter() - t0))
                loop.close()  # drain OUTSIDE the timed window
                saves = max(saves, loop.manager.latest() + 1)
            finally:
                shutil.rmtree(ckdir, ignore_errors=True)

        for rep in range(replicates):
            for arm in ((run_plain, run_ckpt) if rep % 2 == 0
                        else (run_ckpt, run_plain)):
                arm()
    return plain, ckpt, saves


# ---------------------------------------------------------------------------
# restart children
# ---------------------------------------------------------------------------


def _child(config: str, role: str, ckpt_dir: str, prime_steps: int,
           step_interval: int):
    """One fresh-process sample, one JSON line on stdout."""
    t_proc = time.perf_counter()
    import jax  # noqa: F401

    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    import numpy as np

    import paddle_tpu as fluid
    from paddle_tpu import observability as obs
    from paddle_tpu.checkpoint import ResumableLoop

    t_import = time.perf_counter()
    main, startup, scope, feed, loss_name = _build(config)
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.CPUPlace())
        if role == "prime":
            exe.run(startup)
            loop = ResumableLoop(exe, main, ckpt_dir, scope=scope,
                                 step_interval=step_interval)
            for _ in range(prime_steps):
                exe.run(main, feed=feed, fetch_list=[loss_name])
                loop.step_done()
            loop.save_now(block=True)
            loop.close()
            out = {"role": role, "saved_serial": loop.manager.latest()}
        else:  # restart: restore newest checkpoint, run first step
            t0 = time.perf_counter()
            loop = ResumableLoop(exe, main, ckpt_dir, scope=scope,
                                 step_interval=step_interval)
            assert loop.resumed_meta is not None, "nothing to resume"
            t_restore = time.perf_counter()
            first = exe.run(main, feed=feed, fetch_list=[loss_name])[0]
            t_first = time.perf_counter()
            loop.close()
            warm = sum(obs.AOT_COMPILE_MS.stats(path="warm", kind=k)["count"]
                       for k in ("run", "loop"))
            cold = sum(obs.AOT_COMPILE_MS.stats(path="cold", kind=k)["count"]
                       for k in ("run", "loop"))
            out = {
                "role": role,
                "import_s": t_import - t_proc,
                "restore_s": t_restore - t0,
                "first_step_s": t_first - t_restore,
                "ttfs_s": t_first - t0,
                "first_loss": float(np.asarray(first).ravel()[0]),
                "resumed_global": loop.global_step,
                "warm_loads": warm,
                "cold_compiles": cold,
            }
    json.dump(out, sys.stdout)
    sys.stdout.write("\n")


def _run_child(config, role, ckpt_dir, cache_dir, prime_steps,
               step_interval):
    env = dict(os.environ,
               JAX_PLATFORMS=os.environ.get("JAX_PLATFORMS", "cpu"),
               PADDLE_TPU_AOT_CACHE_DIR=cache_dir,
               PADDLE_TPU_AOT_CACHE="1")
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.pop("PADDLE_TPU_JAX_CACHE_DIR", None)
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--child",
         "--config", config, "--role", role, "--ckpt-dir", ckpt_dir,
         "--prime-steps", str(prime_steps),
         "--step-interval", str(step_interval)],
        capture_output=True, text=True, timeout=1200, env=env, cwd=_REPO)
    if proc.returncode != 0:
        raise RuntimeError("bench_resume child failed:\n"
                           + proc.stderr[-4000:])
    return json.loads(proc.stdout.strip().splitlines()[-1])


def _median(xs):
    xs = sorted(xs)
    n = len(xs)
    return xs[n // 2] if n % 2 else 0.5 * (xs[n // 2 - 1] + xs[n // 2])


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--child", action="store_true", help=argparse.SUPPRESS)
    ap.add_argument("--role", default="restart", help=argparse.SUPPRESS)
    ap.add_argument("--config", default="mlp", help=argparse.SUPPRESS)
    ap.add_argument("--ckpt-dir", default=None, help=argparse.SUPPRESS)
    ap.add_argument("--configs", default="mlp,deepfm",
                    help="comma-separated config names (%s)"
                         % ",".join(sorted(CONFIGS)))
    ap.add_argument("--steps", type=int, default=60,
                    help="steps per overhead-measurement arm")
    ap.add_argument("--step-interval", type=int, default=10,
                    help="checkpoint cadence (batches)")
    ap.add_argument("--replicates", type=int, default=3,
                    help="interleaved plain/ckpt pairs (overhead)")
    ap.add_argument("--restart-replicates", type=int, default=3,
                    help="interleaved cold/warm restart pairs")
    ap.add_argument("--prime-steps", type=int, default=12,
                    help="training steps in the prime child")
    args = ap.parse_args()

    if args.child:
        _child(args.config, args.role, args.ckpt_dir, args.prime_steps,
               args.step_interval)
        return

    results = []
    for config in [c for c in args.configs.split(",") if c]:
        if config not in CONFIGS:
            raise SystemExit("unknown config %r (have: %s)"
                             % (config, ", ".join(sorted(CONFIGS))))
        plain, ckpt, saves = _overhead(config, args.steps,
                                       args.step_interval,
                                       args.replicates)
        plain_med, ckpt_med = _median(plain), _median(ckpt)
        # PAIRED per-replicate ratios: each (plain, ckpt) pair ran
        # back-to-back, so CPU frequency / load drift across the sweep
        # cancels inside the pair instead of polluting the medians
        paired_overhead = _median(
            [1.0 - c / p for p, c in zip(plain, ckpt)])

        work = tempfile.mkdtemp(prefix="ptpu-bench-resume-")
        ckpt_dir = os.path.join(work, "ck")
        warm_cache = os.path.join(work, "aot-warm")
        try:
            _run_child(config, "prime", ckpt_dir, warm_cache,
                       args.prime_steps, args.step_interval)
            cold, warm = [], []
            for i in range(args.restart_replicates):
                cold_cache = os.path.join(work, "aot-cold-%d" % i)
                cold.append(_run_child(config, "restart", ckpt_dir,
                                       cold_cache, args.prime_steps,
                                       args.step_interval))
                warm.append(_run_child(config, "restart", ckpt_dir,
                                       warm_cache, args.prime_steps,
                                       args.step_interval))
            cold_med = _median([c["ttfs_s"] for c in cold])
            warm_med = _median([w["ttfs_s"] for w in warm])
            line = {
                "bench": "resume",
                "schema": SCHEMA,
                "config": config,
                "steps": args.steps,
                "step_interval": args.step_interval,
                "replicates": args.replicates,
                "plain_steps_per_s": [round(v, 2) for v in plain],
                "ckpt_steps_per_s": [round(v, 2) for v in ckpt],
                "plain_median": round(plain_med, 2),
                "ckpt_median": round(ckpt_med, 2),
                "overhead_frac": round(paired_overhead, 4),
                "saves_per_arm": saves,
                "cold_ttfs_s": [round(c["ttfs_s"], 4) for c in cold],
                "warm_ttfs_s": [round(w["ttfs_s"], 4) for w in warm],
                "cold_median_s": round(cold_med, 4),
                "warm_median_s": round(warm_med, 4),
                "warm_restart_speedup": round(cold_med / warm_med, 3)
                if warm_med else None,
                "restore_median_s": round(_median(
                    [w["restore_s"] for w in warm]), 4),
                "warm_used_cache": all(w["warm_loads"] > 0 for w in warm),
                "resume_loaded_ckpt": all(
                    r["resumed_global"] > 0 for r in cold + warm),
            }
            results.append(line)
            print(json.dumps(line), flush=True)
        finally:
            shutil.rmtree(work, ignore_errors=True)

    if results:
        print(json.dumps({
            "bench": "resume_summary",
            "schema": SCHEMA,
            "configs": [r["config"] for r in results],
            "max_overhead_frac": max(r["overhead_frac"] for r in results),
            "min_warm_restart_speedup": min(
                r["warm_restart_speedup"] for r in results),
        }), flush=True)


if __name__ == "__main__":
    main()
