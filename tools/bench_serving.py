"""Serving-path measurements (VERDICT r3 weak #4): the numbers behind
"usable in production", measured instead of asserted.

Reference counterpart: paddle/fluid/inference/api/api_impl.cc — the
NativePredictor whose cold-start/per-call costs this tool records for
our AOT predictor, PredictorServer, and (via runtime/capi_test.c's
bench mode) the pure-C ABI.

Prints one JSON line per phase:
  {"phase": "predictor_cold_start", ...}
  {"phase": "predictor_latency", ...}
  {"phase": "server_throughput", ...}

Usage:
  python tools/bench_serving.py            # CPU (forced)
  BENCH_SERVING_PLATFORM=device python tools/bench_serving.py  # real chip

The model is the MLP the C ABI test embeds (16->128->10 softmax) at
SERVING_BATCH (default 8); adjust with SERVING_DIM / SERVING_HIDDEN.
"""
from __future__ import annotations

import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

if os.environ.get("BENCH_SERVING_PLATFORM", "cpu") == "cpu":
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)

import numpy as np  # noqa: E402

import jax  # noqa: E402

if os.environ.get("BENCH_SERVING_PLATFORM", "cpu") == "cpu":
    jax.config.update("jax_platforms", "cpu")

import paddle_tpu as fluid  # noqa: E402


DIM = int(os.environ.get("SERVING_DIM", 16))
HIDDEN = int(os.environ.get("SERVING_HIDDEN", 128))
BATCH = int(os.environ.get("SERVING_BATCH", 8))


def _save_model(model_dir):
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 17
    with fluid.unique_name.guard(), fluid.program_guard(main, startup):
        img = fluid.layers.data(name="img", shape=[DIM], dtype="float32")
        h = fluid.layers.fc(img, HIDDEN, act="relu")
        prob = fluid.layers.softmax(fluid.layers.fc(h, 10))
    scope = fluid.core.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(startup)
        fluid.io.save_inference_model(model_dir, ["img"], [prob], exe,
                                      main_program=main)


def _emit(obj):
    print(json.dumps(obj), flush=True)


def main():
    from paddle_tpu.inference import Predictor, PredictorServer

    tmp = tempfile.mkdtemp(prefix="ptpu_serving_")
    model_dir = os.path.join(tmp, "model")
    _save_model(model_dir)
    batch = np.random.RandomState(3).randn(BATCH, DIM).astype(np.float32)

    # -- cold start: construction + first predict, cache-cold vs warm ----
    t0 = time.perf_counter()
    p = Predictor(model_dir)
    t1 = time.perf_counter()
    p.run({"img": batch})
    t2 = time.perf_counter()
    cold_construct_ms = (t1 - t0) * 1e3
    cold_first_run_ms = (t2 - t1) * 1e3  # includes the XLA compile

    # second process-equivalent: fresh Predictor over the now-warm AOT
    # cache, preload on (default) vs off
    t0 = time.perf_counter()
    p2 = Predictor(model_dir)
    t1 = time.perf_counter()
    p2.run({"img": batch})
    t2 = time.perf_counter()
    warm_preload_construct_ms = (t1 - t0) * 1e3
    warm_preload_first_run_ms = (t2 - t1) * 1e3

    t0 = time.perf_counter()
    p3 = Predictor(model_dir, preload=False)
    t1 = time.perf_counter()
    p3.run({"img": batch})
    t2 = time.perf_counter()
    warm_lazy_construct_ms = (t1 - t0) * 1e3
    warm_lazy_first_run_ms = (t2 - t1) * 1e3

    _emit({"phase": "predictor_cold_start",
           "cold_construct_ms": round(cold_construct_ms, 1),
           "cold_first_run_ms": round(cold_first_run_ms, 1),
           "warm_preload_construct_ms": round(warm_preload_construct_ms, 1),
           "warm_preload_first_run_ms": round(warm_preload_first_run_ms, 3),
           "warm_lazy_construct_ms": round(warm_lazy_construct_ms, 1),
           "warm_lazy_first_run_ms": round(warm_lazy_first_run_ms, 1),
           "device": jax.devices()[0].device_kind})

    # -- steady-state latency -------------------------------------------
    iters = int(os.environ.get("SERVING_ITERS", 200))
    for _ in range(10):
        p2.run({"img": batch})
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        out, = p2.run({"img": batch})  # return_numpy fences device->host
        times.append((time.perf_counter() - t0) * 1e3)
    times.sort()
    import math

    p99_idx = max(0, math.ceil(0.99 * len(times)) - 1)
    _emit({"phase": "predictor_latency", "batch": BATCH,
           "run_ms_min": round(times[0], 3),
           "run_ms_p50": round(times[len(times) // 2], 3),
           "run_ms_p99": round(times[p99_idx], 3),
           "iters": iters})

    # -- PredictorServer dynamic-batching throughput ---------------------
    import threading

    for max_batch in (8, 32):
        server = PredictorServer(p2, max_batch=max_batch)
        server.start()
        n_req = int(os.environ.get("SERVING_REQUESTS", 2000))
        rows = [np.random.RandomState(i % 7).randn(DIM).astype(np.float32)
                for i in range(8)]
        # warm the padded-batch signature (one XLA compile) off the clock
        for f in [server.submit((rows[0],)) for _ in range(max_batch)]:
            f.result()
        futs = []
        t0 = time.perf_counter()

        def feed_requests(k0, k1):
            local = []
            for i in range(k0, k1):
                local.append(server.submit((rows[i % 8],)))
            futs.extend(local)

        threads = [threading.Thread(target=feed_requests,
                                    args=(k * n_req // 4,
                                          (k + 1) * n_req // 4))
                   for k in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        for f in futs:
            f.result()
        dt = time.perf_counter() - t0
        server.stop()
        _emit({"phase": "server_throughput", "max_batch": max_batch,
               "requests": n_req, "concurrency": 4,
               "rows_per_sec": round(n_req / dt, 1),
               "wall_s": round(dt, 3)})


if __name__ == "__main__":
    sys.exit(main())
