"""Serving-path measurements (VERDICT r3 weak #4): the numbers behind
"usable in production", measured instead of asserted.

Reference counterpart: paddle/fluid/inference/api/api_impl.cc — the
NativePredictor whose cold-start/per-call costs this tool records for
our AOT predictor, PredictorServer, and (via runtime/capi_test.c's
bench mode) the pure-C ABI.

Prints one JSON line per phase / sweep config:
  {"phase": "predictor_cold_start", ...}
  {"phase": "predictor_latency", ...}
  {"phase": "server_sweep", "mode": "padmax"|"bucket", ...}   one per config
  {"phase": "server_speedup", ...}   best bucket config vs padmax baseline

The server sweep crosses PredictorServer's batching knobs — padding
policy (legacy pad-to-max vs power-of-two buckets), `max_wait_ms`
batching deadline, and in-flight pipeline depth — at a fixed submitter
count, reporting rows/s plus the pad-waste ratio (padded rows / device
rows) straight from the serving metrics.

With ``--fleet`` the tool instead measures the HORIZONTAL layer
(serving.Router): a single in-process PredictorServer (the PR-2
baseline) against an N-replica worker fleet behind the router, crossed
over replicas x submitters x batching deadline. Baseline and fleet
rounds are INTERLEAVED (base, fleet, base, fleet, ...) per config so
host noise hits both arms equally — the PR-2/3/5 A/B discipline — and
every config line carries its own ``fleet_speedup`` (median fleet
rows/s over median baseline rows/s):
  {"phase": "fleet_sweep", "replicas": N, ..., "fleet_speedup": ...}
  {"phase": "fleet_best", ...}   best config overall

Usage:
  python tools/bench_serving.py            # CPU (forced)
  python tools/bench_serving.py --fleet    # replica-scaling sweep
  BENCH_SERVING_PLATFORM=device python tools/bench_serving.py  # real chip

The model is the MLP the C ABI test embeds (16->128->10 softmax) at
SERVING_BATCH (default 8); adjust with SERVING_DIM / SERVING_HIDDEN.
Sweep grid: SERVING_SWEEP_BATCHES / SERVING_SWEEP_WAITS_MS /
SERVING_SWEEP_INFLIGHT (comma lists), SERVING_SUBMITTERS,
SERVING_REQUESTS. Fleet grid: FLEET_REPLICAS / FLEET_SUBMITTERS /
FLEET_WAITS_MS (comma lists), FLEET_ROUNDS, FLEET_MAX_BATCH,
FLEET_INFLIGHT, FLEET_REQUESTS.
"""
from __future__ import annotations

import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

if os.environ.get("BENCH_SERVING_PLATFORM", "cpu") == "cpu":
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)

import numpy as np  # noqa: E402

import jax  # noqa: E402

if os.environ.get("BENCH_SERVING_PLATFORM", "cpu") == "cpu":
    jax.config.update("jax_platforms", "cpu")

import paddle_tpu as fluid  # noqa: E402


DIM = int(os.environ.get("SERVING_DIM", 16))
HIDDEN = int(os.environ.get("SERVING_HIDDEN", 128))
BATCH = int(os.environ.get("SERVING_BATCH", 8))


def _save_model(model_dir):
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 17
    with fluid.unique_name.guard(), fluid.program_guard(main, startup):
        img = fluid.layers.data(name="img", shape=[DIM], dtype="float32")
        h = fluid.layers.fc(img, HIDDEN, act="relu")
        prob = fluid.layers.softmax(fluid.layers.fc(h, 10))
    scope = fluid.core.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(startup)
        fluid.io.save_inference_model(model_dir, ["img"], [prob], exe,
                                      main_program=main)


def _emit(obj):
    print(json.dumps(obj), flush=True)


def main():
    from paddle_tpu.inference import Predictor, PredictorServer

    tmp = tempfile.mkdtemp(prefix="ptpu_serving_")
    model_dir = os.path.join(tmp, "model")
    _save_model(model_dir)
    batch = np.random.RandomState(3).randn(BATCH, DIM).astype(np.float32)

    # -- cold start: construction + first predict, cache-cold vs warm ----
    t0 = time.perf_counter()
    p = Predictor(model_dir)
    t1 = time.perf_counter()
    p.run({"img": batch})
    t2 = time.perf_counter()
    cold_construct_ms = (t1 - t0) * 1e3
    cold_first_run_ms = (t2 - t1) * 1e3  # includes the XLA compile

    # second process-equivalent: fresh Predictor over the now-warm AOT
    # cache, preload on (default) vs off
    t0 = time.perf_counter()
    p2 = Predictor(model_dir)
    t1 = time.perf_counter()
    p2.run({"img": batch})
    t2 = time.perf_counter()
    warm_preload_construct_ms = (t1 - t0) * 1e3
    warm_preload_first_run_ms = (t2 - t1) * 1e3

    t0 = time.perf_counter()
    p3 = Predictor(model_dir, preload=False)
    t1 = time.perf_counter()
    p3.run({"img": batch})
    t2 = time.perf_counter()
    warm_lazy_construct_ms = (t1 - t0) * 1e3
    warm_lazy_first_run_ms = (t2 - t1) * 1e3

    _emit({"phase": "predictor_cold_start",
           "cold_construct_ms": round(cold_construct_ms, 1),
           "cold_first_run_ms": round(cold_first_run_ms, 1),
           "warm_preload_construct_ms": round(warm_preload_construct_ms, 1),
           "warm_preload_first_run_ms": round(warm_preload_first_run_ms, 3),
           "warm_lazy_construct_ms": round(warm_lazy_construct_ms, 1),
           "warm_lazy_first_run_ms": round(warm_lazy_first_run_ms, 1),
           "device": jax.devices()[0].device_kind})

    # -- steady-state latency -------------------------------------------
    iters = int(os.environ.get("SERVING_ITERS", 200))
    for _ in range(10):
        p2.run({"img": batch})
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        out, = p2.run({"img": batch})  # return_numpy fences device->host
        times.append((time.perf_counter() - t0) * 1e3)
    times.sort()
    import math

    p99_idx = max(0, math.ceil(0.99 * len(times)) - 1)
    _emit({"phase": "predictor_latency", "batch": BATCH,
           "run_ms_min": round(times[0], 3),
           "run_ms_p50": round(times[len(times) // 2], 3),
           "run_ms_p99": round(times[p99_idx], 3),
           "iters": iters})

    # -- PredictorServer batching sweep: policy x deadline x in-flight ---
    from paddle_tpu import observability as obs

    n_req = int(os.environ.get("SERVING_REQUESTS", 2000))
    submitters = int(os.environ.get("SERVING_SUBMITTERS", 4))
    batches = _int_list("SERVING_SWEEP_BATCHES", "8,32")
    waits = _float_list("SERVING_SWEEP_WAITS_MS", "0,2")
    depths = _int_list("SERVING_SWEEP_INFLIGHT", "1,4")
    rows = [np.random.RandomState(i % 7).randn(DIM).astype(np.float32)
            for i in range(8)]

    # closed loop = each submitter waits for its row before the next one
    # (arrival-limited PARTIAL fill, where padding policy dominates);
    # open loop = submitters flood as fast as they can (full batches,
    # where the pipeline + zero-copy path dominates)
    loops = [v for v in os.environ.get("SERVING_LOOP_MODES",
                                       "closed,open").split(",") if v]
    baseline = {}
    best = {}
    for loop in loops:
        for max_batch in batches:
            configs = [("padmax", 0.0, 1)]  # pre-pipeline pad-to-max policy
            configs += [("bucket", w, d) for w in waits for d in depths]
            for mode, wait_ms, in_flight in configs:
                rec = _run_server_config(
                    PredictorServer, p2, obs, mode=mode, loop=loop,
                    max_batch=max_batch, wait_ms=wait_ms,
                    in_flight=in_flight, n_req=n_req,
                    submitters=submitters, rows=rows)
                _emit(rec)
                if mode == "padmax":
                    baseline[(loop, max_batch)] = rec
                if mode == "bucket" and (loop not in best
                                         or rec["rows_per_sec"]
                                         > best[loop]["rows_per_sec"]):
                    best[loop] = rec

    for loop in loops:
        top = best.get(loop)
        # compare against the padmax baseline at the SAME max_batch, so
        # the reported speedup isolates the padding policy instead of
        # conflating it with the batch-size choice
        base = baseline.get((loop, top["max_batch"])) if top else None
        if not (base and top):
            continue
        _emit({"phase": "server_speedup", "loop": loop,
               "baseline_rows_per_sec": base["rows_per_sec"],
               "best_rows_per_sec": top["rows_per_sec"],
               "speedup": round(top["rows_per_sec"]
                                / max(base["rows_per_sec"], 1e-9), 3),
               "baseline_pad_waste": base["pad_waste"],
               "best_pad_waste": top["pad_waste"],
               "best_config": {k: top[k] for k in
                               ("mode", "max_batch", "max_wait_ms",
                                "in_flight")}})


def _fleet_rows_per_sec(submit, n_req, submitters, rows, loop="closed",
                        timeout=600.0):
    """Serve n_req single-row requests from `submitters` threads through
    `submit`; returns rows/s. loop="closed": each thread waits for its
    row before the next (latency-bound — what an RPC frontend sees);
    loop="open": threads flood and futures are awaited at the end
    (aggregate CAPACITY — the front channel's backpressure bounds
    memory). The shared measurement body for the baseline-server and
    fleet-router arms."""
    import threading

    errs = []

    def feed_requests(k):
        try:
            futs = []
            for i in range(k * n_req // submitters,
                           (k + 1) * n_req // submitters):
                fut = submit((rows[i % len(rows)],))
                if loop == "closed":
                    fut.result(timeout=timeout)
                else:
                    futs.append(fut)
            for fut in futs:
                fut.result(timeout=timeout)
        except Exception as e:  # pragma: no cover - failure reporting
            errs.append(repr(e))

    threads = [threading.Thread(target=feed_requests, args=(k,))
               for k in range(submitters)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    dt = time.perf_counter() - t0
    if errs:
        raise RuntimeError("bench clients failed: %s" % errs[:3])
    return n_req / dt


def fleet_main():
    """--fleet: replicas x submitters x deadline sweep, interleaved A/B
    against a single PR-2 PredictorServer baseline."""
    from paddle_tpu.inference import Predictor, PredictorServer
    from paddle_tpu.serving import Router

    platform = os.environ.get("BENCH_SERVING_PLATFORM", "cpu")
    tmp = tempfile.mkdtemp(prefix="ptpu_fleet_")
    model_dir = os.path.join(tmp, "model")
    _save_model(model_dir)

    n_req = int(os.environ.get("FLEET_REQUESTS",
                               os.environ.get("SERVING_REQUESTS", 2000)))
    rounds = int(os.environ.get("FLEET_ROUNDS", 3))
    max_batch = int(os.environ.get("FLEET_MAX_BATCH", 32))
    in_flight = int(os.environ.get("FLEET_INFLIGHT", 4))
    replicas_grid = _int_list("FLEET_REPLICAS", "1,2,4")
    submitters_grid = _int_list("FLEET_SUBMITTERS", "8")
    waits_grid = _float_list("FLEET_WAITS_MS", "0")
    loops = [v for v in os.environ.get("FLEET_LOOP_MODES",
                                       "closed,open").split(",") if v]
    rows = [np.random.RandomState(i % 7).randn(DIM).astype(np.float32)
            for i in range(8)]

    # the baseline arm: one in-process pipelined server, PR-2 bucket
    # config — constructed once, reused in every interleaved round
    pred = Predictor(model_dir)
    base_server = PredictorServer(pred, max_batch=max_batch,
                                  in_flight=in_flight)
    base_server.start()
    # prime both arms' compiled buckets off the clock
    for f in [base_server.submit((rows[0],)) for _ in range(max_batch)]:
        f.result(timeout=600)

    best = None
    for replicas in replicas_grid:
        for wait_ms in waits_grid:
            router = Router(
                model_dir, replicas=replicas, max_batch=max_batch,
                max_wait_ms=wait_ms, in_flight=in_flight,
                jax_platform=("cpu" if platform == "cpu" else None))
            t_up = time.perf_counter()
            router.start()
            fleet_up_s = time.perf_counter() - t_up
            for submitters in submitters_grid:
                # warm the routed path off the clock
                for f in [router.submit((rows[0],))
                          for _ in range(max_batch)]:
                    f.result(timeout=600)
                for loop in loops:
                    base_rs, fleet_rs = [], []
                    t0 = time.perf_counter()
                    for _ in range(rounds):  # interleaved A/B per round
                        base_rs.append(_fleet_rows_per_sec(
                            base_server.submit, n_req, submitters, rows,
                            loop=loop))
                        fleet_rs.append(_fleet_rows_per_sec(
                            router.submit, n_req, submitters, rows,
                            loop=loop))
                    wall = time.perf_counter() - t0
                    base_med = sorted(base_rs)[len(base_rs) // 2]
                    fleet_med = sorted(fleet_rs)[len(fleet_rs) // 2]
                    rec = {
                        "phase": "fleet_sweep", "replicas": replicas,
                        "submitters": submitters, "loop": loop,
                        "max_wait_ms": wait_ms,
                        "shard": 1, "max_batch": max_batch,
                        "in_flight": in_flight, "requests": n_req,
                        "rounds": rounds,
                        "rows_per_sec": round(fleet_med, 1),
                        "baseline_rows_per_sec": round(base_med, 1),
                        "fleet_speedup": round(
                            fleet_med / max(base_med, 1e-9), 3),
                        "rows_per_sec_rounds": [round(v, 1)
                                                for v in fleet_rs],
                        "baseline_rounds": [round(v, 1) for v in base_rs],
                        "fleet_up_s": round(fleet_up_s, 2),
                        "wall_s": round(wall, 3),
                    }
                    _emit(rec)
                    if (best is None
                            or rec["fleet_speedup"] > best["fleet_speedup"]):
                        best = rec
            router.stop()
    base_server.stop()
    if best is not None:
        _emit({"phase": "fleet_best",
               "fleet_speedup": best["fleet_speedup"],
               "rows_per_sec": best["rows_per_sec"],
               "baseline_rows_per_sec": best["baseline_rows_per_sec"],
               "best_config": {k: best[k] for k in
                               ("replicas", "submitters", "loop",
                                "max_wait_ms", "max_batch", "in_flight")}})


def _int_list(env, default):
    return [int(v) for v in os.environ.get(env, default).split(",") if v]


def _float_list(env, default):
    return [float(v) for v in os.environ.get(env, default).split(",") if v]


def _run_server_config(server_cls, pred, obs, *, mode, loop, max_batch,
                       wait_ms, in_flight, n_req, submitters, rows):
    """One sweep point: serve n_req single-row requests from `submitters`
    concurrent threads and read the pad accounting back out of the
    serving metrics (registry delta over the timed window)."""
    import threading

    kwargs = dict(max_batch=max_batch, max_wait_ms=wait_ms,
                  in_flight=in_flight)
    if mode == "padmax":
        kwargs["buckets"] = [max_batch]  # every batch pads to max_batch
    server = server_cls(pred, **kwargs)
    server.start()
    # off the clock: fill the pipeline once (bucket signatures are
    # already pre-warmed by start(), this warms the thread handoff)
    for f in [server.submit((rows[0],)) for _ in range(max_batch)]:
        f.result(timeout=300)
    real0 = obs.SERVER_ROWS.value(kind="real")
    pad0 = obs.SERVER_ROWS.value(kind="pad")
    server.batch_size_counts.clear()
    futs = [[] for _ in range(submitters)]
    t0 = time.perf_counter()

    def feed_requests(k):
        local = futs[k]
        for i in range(k * n_req // submitters,
                       (k + 1) * n_req // submitters):
            fut = server.submit((rows[i % len(rows)],))
            local.append(fut)
            if loop == "closed":
                fut.result(timeout=300)

    threads = [threading.Thread(target=feed_requests, args=(k,))
               for k in range(submitters)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    for chunk in futs:
        for f in chunk:
            f.result(timeout=300)
    dt = time.perf_counter() - t0
    real = obs.SERVER_ROWS.value(kind="real") - real0
    pad = obs.SERVER_ROWS.value(kind="pad") - pad0
    counts = dict(server.batch_size_counts)
    server.stop()
    n_batches = sum(counts.values())
    return {"phase": "server_sweep", "mode": mode, "loop": loop,
            "max_batch": max_batch,
            "max_wait_ms": wait_ms, "in_flight": in_flight,
            "submitters": submitters, "requests": n_req,
            "rows_per_sec": round(n_req / dt, 1), "wall_s": round(dt, 3),
            "real_rows": int(real), "pad_rows": int(pad),
            "pad_waste": round(pad / max(real + pad, 1), 4),
            "batches": n_batches,
            "mean_fill": round(sum(k * v for k, v in counts.items())
                               / n_batches, 2) if n_batches else 0.0}


if __name__ == "__main__":
    sys.exit(fleet_main() if "--fleet" in sys.argv[1:] else main())
