"""Decode-serving measurements: the O(T^2)-vs-O(T) story, measured.

Two interleaved A/B experiments over the same exported causal LM
(random-init weights — throughput does not care what the logits say):

1. **KV-cache incremental decode vs full-forward recompute**
   (``decode_ab``): generate DECODE_STEPS tokens per row at
   DECODE_BATCH. The kv arm is ``DecodePredictor.generate`` (one
   prefill + one single-query decode step per token); the full arm
   replays the serving status quo ante — re-running the SAME compiled
   prefill executable over the whole growing prefix for every token.
   Rounds interleave (kv, full, kv, full, ...) so host noise hits both
   arms equally — the PR-2/3/5/8 discipline.

2. **Continuous vs static batching at mixed request lengths**
   (``batch_mode``): CONT_REQUESTS generations with alternating short/
   long ``max_new`` budgets through the same DecodeServer, once with
   continuous admission (new requests enter free cache slots
   mid-flight, finished rows retire eagerly) and once gang-scheduled
   (``continuous=False``: a batch must fully drain before the next is
   admitted). ``mean_active`` is the measured per-step slot occupancy —
   the mechanism behind the speedup, not just the outcome.

Prints one JSON line per config / phase:
  {"phase": "decode_ab", "mode": "kv_cache"|"full_forward", ...}
  {"phase": "decode_speedup", "speedup": ...}
  {"phase": "batch_mode", "mode": "continuous"|"static", ...}
  {"phase": "batching_speedup", "speedup": ...}

Usage:
  python tools/bench_decode.py                       # CPU (forced)
  BENCH_DECODE_PLATFORM=device python tools/bench_decode.py  # real chip

Model: DECODE_LAYERS x DECODE_HEADS heads x DECODE_DMODEL (ffn
DECODE_DINNER) over DECODE_VOCAB tokens; prompts DECODE_PROMPT long.
Grid: DECODE_BATCH, DECODE_STEPS, DECODE_ROUNDS; continuous phase:
CONT_REQUESTS, CONT_SLOTS, CONT_MAXNEW_MIX (comma list cycled across
requests), CONT_ROUNDS.
"""
from __future__ import annotations

import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

if os.environ.get("BENCH_DECODE_PLATFORM", "cpu") == "cpu":
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)

import numpy as np  # noqa: E402

import jax  # noqa: E402

if os.environ.get("BENCH_DECODE_PLATFORM", "cpu") == "cpu":
    jax.config.update("jax_platforms", "cpu")

import paddle_tpu as fluid  # noqa: E402

LAYERS = int(os.environ.get("DECODE_LAYERS", 2))
HEADS = int(os.environ.get("DECODE_HEADS", 4))
DMODEL = int(os.environ.get("DECODE_DMODEL", 128))
DINNER = int(os.environ.get("DECODE_DINNER", 256))
VOCAB = int(os.environ.get("DECODE_VOCAB", 512))
PROMPT = int(os.environ.get("DECODE_PROMPT", 16))
BATCH = int(os.environ.get("DECODE_BATCH", 4))
STEPS = int(os.environ.get("DECODE_STEPS", 128))
ROUNDS = int(os.environ.get("DECODE_ROUNDS", 3))
CONT_REQUESTS = int(os.environ.get("CONT_REQUESTS", 24))
CONT_SLOTS = int(os.environ.get("CONT_SLOTS", 4))
CONT_MAXNEW_MIX = os.environ.get("CONT_MAXNEW_MIX", "")
CONT_ROUNDS = int(os.environ.get("CONT_ROUNDS", 5))


def emit(rec):
    print(json.dumps(rec), flush=True)


def _export_model(model_dir):
    from paddle_tpu import layers, optimizer  # noqa: F401
    from paddle_tpu.models import transformer as T
    from paddle_tpu.serving.decode import DecodeConfig, save_decode_model

    from paddle_tpu.serving.decode import _pow2_bucket

    max_len = _pow2_bucket(PROMPT + STEPS + 1, floor=16)
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 17
    with fluid.unique_name.guard(), fluid.program_guard(main, startup):
        ids = layers.data(name="ids", shape=[2, 16], dtype="int64",
                          append_batch_size=False)
        lbl = layers.data(name="lbl", shape=[2, 16], dtype="int64",
                          append_batch_size=False)
        T.transformer_lm(ids, lbl, VOCAB, n_layer=LAYERS, n_head=HEADS,
                         d_model=DMODEL, d_inner=DINNER, dropout_rate=0.0,
                         max_len=max_len, fused_head=False)
    exe = fluid.Executor(fluid.CPUPlace() if os.environ.get(
        "JAX_PLATFORMS") == "cpu" else None)
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        save_decode_model(model_dir, DecodeConfig(
            vocab_size=VOCAB, n_layer=LAYERS, n_head=HEADS, d_model=DMODEL,
            d_inner=DINNER, max_len=max_len), exe, scope=scope)
    return max_len


def _prompts(n, rng):
    return [rng.randint(1, VOCAB, PROMPT).astype(np.int64)
            for _ in range(n)]


def _full_forward_rollout(pred, prompts, steps):
    """The no-cache baseline: one FULL prefill forward per generated
    token over the growing prefix (greedy), using the same compiled
    prefill executable family — and the same bucket policy
    (serving.decode._pow2_bucket) — the kv arm warms."""
    from paddle_tpu.serving.decode import _pow2_bucket

    b = len(prompts)
    bb = _pow2_bucket(b)
    s = _pow2_bucket(PROMPT + steps, floor=16)
    tokens = np.zeros((bb, s), np.int64)
    lens = np.ones((bb,), np.int32)
    for i, p in enumerate(prompts):
        tokens[i, :len(p)] = p
        lens[i] = len(p)
    rows = np.arange(bb)
    for _ in range(steps):
        # honest baseline: the full forward runs at the pow2 bucket of
        # the CURRENT prefix, not the final one (what a bucketed
        # full-forward server would actually pay per token)
        sc = min(_pow2_bucket(int(lens.max()), floor=16), s)
        pexe, _ = pred.acquire("prefill", bb, sc)
        outs = pexe({"tokens": tokens[:, :sc], "lengths": lens},
                    pred._state)
        nxt = np.asarray(outs[0]).argmax(axis=1)
        tokens[rows, np.minimum(lens, s - 1)] = nxt
        lens = np.minimum(lens + 1, s - 1)
    return tokens


def bench_decode_ab(pred):
    rng = np.random.RandomState(0)
    prompts = _prompts(BATCH, rng)
    # one full untimed round per arm: EVERY signature either arm will
    # touch (all the growing full-forward buckets, the kv prefill + the
    # (B, S) decode step) compiles/loads outside the measured region
    pred.generate(prompts, max_new_tokens=STEPS)
    _full_forward_rollout(pred, prompts, STEPS)

    kv_rates, full_rates = [], []
    kv_wall = full_wall = 0.0
    for _ in range(ROUNDS):
        t0 = time.perf_counter()
        outs = pred.generate(prompts, max_new_tokens=STEPS)
        dt = time.perf_counter() - t0
        kv_wall += dt
        kv_rates.append(sum(len(o) for o in outs) / dt)

        t0 = time.perf_counter()
        _full_forward_rollout(pred, prompts, STEPS)
        dt = time.perf_counter() - t0
        full_wall += dt
        full_rates.append(BATCH * STEPS / dt)

    from paddle_tpu.serving.decode import _pow2_bucket

    s = _pow2_bucket(PROMPT + STEPS, floor=16)
    for mode, rates, wall in (("kv_cache", kv_rates, kv_wall),
                              ("full_forward", full_rates, full_wall)):
        emit({"phase": "decode_ab", "mode": mode, "batch": BATCH,
              "decode_steps": STEPS, "prompt_len": PROMPT,
              "seq_bucket": s, "rounds": ROUNDS,
              "tokens": BATCH * STEPS * ROUNDS,
              "tokens_per_sec": float(np.median(rates)),
              "tokens_per_sec_rounds": [float(r) for r in rates],
              "wall_s": float(wall)})
    kv, full = float(np.median(kv_rates)), float(np.median(full_rates))
    emit({"phase": "decode_speedup", "batch": BATCH,
          "decode_steps": STEPS, "kv_tokens_per_sec": kv,
          "full_tokens_per_sec": full, "speedup": kv / full})
    return kv / full


def bench_batch_modes(model_dir):
    from paddle_tpu.serving.decode import DecodePredictor, DecodeServer

    rng = np.random.RandomState(1)
    prompts = _prompts(CONT_REQUESTS, rng)
    if CONT_MAXNEW_MIX:
        mix = [int(x) for x in CONT_MAXNEW_MIX.split(",")]
    else:
        mix = [max(4, STEPS // 16), STEPS // 2]
    budgets = [mix[i % len(mix)] for i in range(CONT_REQUESTS)]
    max_new = max(budgets)

    # ONE predictor (and its executable cache) behind both schedules:
    # the A/B measures the SCHEDULING policy, not who compiled first
    pred = DecodePredictor(model_dir)
    servers = {}
    for mode in ("continuous", "static"):
        srv = DecodeServer(pred, slots=CONT_SLOTS,
                           max_seq=PROMPT + max_new,
                           max_new_tokens=max_new,
                           continuous=(mode == "continuous"))
        srv.start()
        servers[mode] = srv

    def run_round(mode):
        srv = servers[mode]
        t0 = time.perf_counter()
        futs = [srv.submit((p, np.array([mn], np.int64)))
                for p, mn in zip(prompts, budgets)]
        outs = [f.result(timeout=600)[0] for f in futs]
        return [np.asarray(o) for o in outs], time.perf_counter() - t0

    results = {}
    rates = {"continuous": [], "static": []}
    walls = {"continuous": 0.0, "static": 0.0}
    active = {"continuous": [], "static": []}
    iters = {}
    for mode in ("continuous", "static"):  # untimed warm round per arm
        results[mode], _ = run_round(mode)
        servers[mode].step_active_counts.clear()
    for rnd in range(CONT_ROUNDS):
        # alternate which arm goes first so slow drifts (thermal, other
        # tenants of this box) hit both equally
        order = (("continuous", "static") if rnd % 2 == 0
                 else ("static", "continuous"))
        for mode in order:
            outs, dt = run_round(mode)
            toks = sum(len(o) for o in outs)
            rates[mode].append(toks / dt)
            walls[mode] += dt
    for mode in ("continuous", "static"):
        srv = servers[mode]
        if srv.step_active_counts:
            active[mode].append(float(np.mean(srv.step_active_counts)))
        # structural, noise-free half of the claim: decode iterations
        # per round — continuous needs fewer sweeps of the same (slots,
        # S) executable to emit the same tokens
        iters[mode] = len(srv.step_active_counts) / float(CONT_ROUNDS)
        srv.stop()
    # both schedules must produce identical tokens (greedy, same model)
    assert all(np.array_equal(a, b) for a, b in
               zip(results["continuous"], results["static"])), \
        "continuous and static batching diverged"
    for mode in ("continuous", "static"):
        emit({"phase": "batch_mode", "mode": mode, "slots": CONT_SLOTS,
              "requests": CONT_REQUESTS,
              "max_new_mix": ",".join(str(m) for m in mix),
              "rounds": CONT_ROUNDS,
              "tokens": sum(budgets),
              "tokens_per_sec": float(np.median(rates[mode])),
              "tokens_per_sec_rounds": [float(r) for r in rates[mode]],
              "mean_active": (float(np.mean(active[mode]))
                              if active[mode] else 0.0),
              "decode_iters_per_round": float(iters[mode]),
              "wall_s": float(walls[mode])})
    cont = float(np.median(rates["continuous"]))
    stat = float(np.median(rates["static"]))
    emit({"phase": "batching_speedup", "slots": CONT_SLOTS,
          "requests": CONT_REQUESTS,
          "continuous_tokens_per_sec": cont,
          "static_tokens_per_sec": stat, "speedup": cont / stat,
          "iters_ratio": float(iters["static"])
          / max(float(iters["continuous"]), 1.0)})
    return cont / stat


def main():
    from paddle_tpu.serving.decode import DecodePredictor

    with tempfile.TemporaryDirectory() as model_dir:
        _export_model(model_dir)
        pred = DecodePredictor(model_dir)
        bench_decode_ab(pred)
        del pred
        bench_batch_modes(model_dir)


if __name__ == "__main__":
    main()
