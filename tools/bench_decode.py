"""Decode-serving measurements: the O(T^2)-vs-O(T) story, measured —
plus the PR-14 levers (shared-prefix KV, speculative decoding).

Interleaved A/B experiments over the same exported causal LM
(random-init weights — throughput does not care what the logits say):

1. **KV-cache incremental decode vs full-forward recompute**
   (``decode_ab``): generate DECODE_STEPS tokens per row at
   DECODE_BATCH. The kv arm is ``DecodePredictor.generate`` (one
   prefill + one single-query decode step per token); the full arm
   replays the serving status quo ante — re-running the SAME compiled
   prefill executable over the whole growing prefix for every token.
   Rounds interleave (kv, full, kv, full, ...) so host noise hits both
   arms equally — the PR-2/3/5/8 discipline. DECODE_STEPS accepts a
   comma ladder ("64,256,1024" — ROADMAP item 1b): one A/B pair + one
   speedup line per rung, showing the O(T^2)/O(T) divergence grow.

2. **Continuous vs static batching at mixed request lengths**
   (``batch_mode``): CONT_REQUESTS generations with alternating short/
   long ``max_new`` budgets through the same DecodeServer, once with
   continuous admission and once gang-scheduled. ``mean_active`` is the
   measured per-step slot occupancy.

3. **Speculative vs plain greedy decode** (``spec_ab``, opt-in via
   ``--speculative``): DECODE_DRAFT_LAYERS-deep self-drafting proposes
   SPEC_K tokens per round, ONE verify window call checks them.
   SPEC_FAVORABLE=1 (default when the arm runs) zeroes the out/fc2
   projections of layers >= DECODE_DRAFT_LAYERS at export, making the
   tail layers exact identities — the draft then agrees with the target
   everywhere (acceptance ~= 1), which measures the MECHANICS CEILING
   of the lever on this box the way a well-trained draft would behave;
   SPEC_FAVORABLE=0 keeps the random model (acceptance is luck) for the
   honest-floor number. ``acceptance_rate`` is emitted either way.

4. **Shared-prefix admission vs private prefills** (``prefix_ab``,
   opt-in via ``--prefix-share``): CONT_REQUESTS requests over
   PREFIX_GROUPS distinct prompts through two DecodeServers — prefix
   store on vs off. ``prefill_executions`` per arm shows the mechanism
   (PREFIX_GROUPS prefills vs one per request); tokens/s shows the
   admission wall-time win.

Prints one JSON line per config / phase; schema pinned by
tests/test_bench_decode_smoke.py.

Usage:
  python tools/bench_decode.py [--speculative] [--prefix-share]
  BENCH_DECODE_PLATFORM=device python tools/bench_decode.py  # real chip

Model: DECODE_LAYERS x DECODE_HEADS heads x DECODE_DMODEL (ffn
DECODE_DINNER) over DECODE_VOCAB tokens; prompts DECODE_PROMPT long.
Grid: DECODE_BATCH, DECODE_STEPS (comma ladder ok), DECODE_ROUNDS;
continuous phase: CONT_REQUESTS, CONT_SLOTS, CONT_MAXNEW_MIX,
CONT_ROUNDS; spec arm: DECODE_DRAFT_LAYERS, SPEC_K, SPEC_FAVORABLE;
prefix arm: PREFIX_GROUPS.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

if os.environ.get("BENCH_DECODE_PLATFORM", "cpu") == "cpu":
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)

import numpy as np  # noqa: E402

import jax  # noqa: E402

if os.environ.get("BENCH_DECODE_PLATFORM", "cpu") == "cpu":
    jax.config.update("jax_platforms", "cpu")

import paddle_tpu as fluid  # noqa: E402

LAYERS = int(os.environ.get("DECODE_LAYERS", 2))
HEADS = int(os.environ.get("DECODE_HEADS", 4))
DMODEL = int(os.environ.get("DECODE_DMODEL", 128))
DINNER = int(os.environ.get("DECODE_DINNER", 256))
VOCAB = int(os.environ.get("DECODE_VOCAB", 512))
PROMPT = int(os.environ.get("DECODE_PROMPT", 16))
BATCH = int(os.environ.get("DECODE_BATCH", 4))
STEPS_LIST = [int(x) for x in
              str(os.environ.get("DECODE_STEPS", "128")).split(",")]
ROUNDS = int(os.environ.get("DECODE_ROUNDS", 3))
CONT_REQUESTS = int(os.environ.get("CONT_REQUESTS", 24))
CONT_SLOTS = int(os.environ.get("CONT_SLOTS", 4))
CONT_MAXNEW_MIX = os.environ.get("CONT_MAXNEW_MIX", "")
CONT_ROUNDS = int(os.environ.get("CONT_ROUNDS", 5))
DRAFT_LAYERS = int(os.environ.get("DECODE_DRAFT_LAYERS", 1))
SPEC_K = int(os.environ.get("SPEC_K", 4))
SPEC_FAVORABLE = os.environ.get("SPEC_FAVORABLE", "1") == "1"
PREFIX_GROUPS = int(os.environ.get("PREFIX_GROUPS", 2))


def emit(rec):
    print(json.dumps(rec), flush=True)


def _export_model(model_dir, spec_favorable=False):
    from paddle_tpu import layers, optimizer  # noqa: F401
    from paddle_tpu.models import transformer as T
    from paddle_tpu.serving.decode import DecodeConfig, save_decode_model

    from paddle_tpu.serving.decode import _pow2_bucket

    max_len = _pow2_bucket(PROMPT + max(STEPS_LIST) + SPEC_K + 2,
                           floor=16)
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 17
    with fluid.unique_name.guard(), fluid.program_guard(main, startup):
        ids = layers.data(name="ids", shape=[2, 16], dtype="int64",
                          append_batch_size=False)
        lbl = layers.data(name="lbl", shape=[2, 16], dtype="int64",
                          append_batch_size=False)
        T.transformer_lm(ids, lbl, VOCAB, n_layer=LAYERS, n_head=HEADS,
                         d_model=DMODEL, d_inner=DINNER, dropout_rate=0.0,
                         max_len=max_len, fused_head=False)
    exe = fluid.Executor(fluid.CPUPlace() if os.environ.get(
        "JAX_PLATFORMS") == "cpu" else None)
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        if spec_favorable:
            # acceptance-favorable: zero the residual-writing
            # projections of every post-draft layer, making them exact
            # identities — the DRAFT_LAYERS-deep draft then argmax-
            # agrees with the target everywhere (what a well-trained
            # draft approximates). Throughput is unaffected (the zeroed
            # matmuls still execute); only the logits change.
            for i in range(DRAFT_LAYERS, LAYERS):
                for name in ("lm.l%d.self.out" % i, "lm.l%d.ffn.fc2" % i):
                    for suffix in (".w", ".b"):
                        old = scope.find_var(name + suffix)
                        if old is not None:
                            scope.set_var(name + suffix,
                                          np.zeros_like(np.asarray(old)))
        save_decode_model(model_dir, DecodeConfig(
            vocab_size=VOCAB, n_layer=LAYERS, n_head=HEADS, d_model=DMODEL,
            d_inner=DINNER, max_len=max_len), exe, scope=scope)
    return max_len


def _prompts(n, rng, length=None):
    return [rng.randint(1, VOCAB, length or PROMPT).astype(np.int64)
            for _ in range(n)]


def _full_forward_rollout(pred, prompts, steps):
    """The no-cache baseline: one FULL prefill forward per generated
    token over the growing prefix (greedy), using the same compiled
    prefill executable family — and the same bucket policy
    (serving.decode._pow2_bucket) — the kv arm warms."""
    from paddle_tpu.serving.decode import _pow2_bucket

    b = len(prompts)
    bb = _pow2_bucket(b)
    s = _pow2_bucket(PROMPT + steps, floor=16)
    tokens = np.zeros((bb, s), np.int64)
    lens = np.ones((bb,), np.int32)
    for i, p in enumerate(prompts):
        tokens[i, :len(p)] = p
        lens[i] = len(p)
    rows = np.arange(bb)
    for _ in range(steps):
        # honest baseline: the full forward runs at the pow2 bucket of
        # the CURRENT prefix, not the final one (what a bucketed
        # full-forward server would actually pay per token)
        sc = min(_pow2_bucket(int(lens.max()), floor=16), s)
        pexe, _ = pred.acquire("prefill", bb, sc)
        outs = pexe({"tokens": tokens[:, :sc], "lengths": lens},
                    pred._state)
        nxt = np.asarray(outs[0]).argmax(axis=1)
        tokens[rows, np.minimum(lens, s - 1)] = nxt
        lens = np.minimum(lens + 1, s - 1)
    return tokens


def bench_decode_ab(pred, steps):
    rng = np.random.RandomState(0)
    prompts = _prompts(BATCH, rng)
    # one full untimed round per arm: EVERY signature either arm will
    # touch (all the growing full-forward buckets, the kv prefill + the
    # (B, S) decode step) compiles/loads outside the measured region
    pred.generate(prompts, max_new_tokens=steps)
    _full_forward_rollout(pred, prompts, steps)

    kv_rates, full_rates = [], []
    kv_wall = full_wall = 0.0
    for _ in range(ROUNDS):
        t0 = time.perf_counter()
        outs = pred.generate(prompts, max_new_tokens=steps)
        dt = time.perf_counter() - t0
        kv_wall += dt
        kv_rates.append(sum(len(o) for o in outs) / dt)

        t0 = time.perf_counter()
        _full_forward_rollout(pred, prompts, steps)
        dt = time.perf_counter() - t0
        full_wall += dt
        full_rates.append(BATCH * steps / dt)

    from paddle_tpu.serving.decode import _pow2_bucket

    s = _pow2_bucket(PROMPT + steps, floor=16)
    for mode, rates, wall in (("kv_cache", kv_rates, kv_wall),
                              ("full_forward", full_rates, full_wall)):
        emit({"phase": "decode_ab", "mode": mode, "batch": BATCH,
              "decode_steps": steps, "prompt_len": PROMPT,
              "seq_bucket": s, "rounds": ROUNDS,
              "tokens": BATCH * steps * ROUNDS,
              "tokens_per_sec": float(np.median(rates)),
              "tokens_per_sec_rounds": [float(r) for r in rates],
              "wall_s": float(wall)})
    kv, full = float(np.median(kv_rates)), float(np.median(full_rates))
    emit({"phase": "decode_speedup", "batch": BATCH,
          "decode_steps": steps, "kv_tokens_per_sec": kv,
          "full_tokens_per_sec": full, "speedup": kv / full})
    return kv / full


def bench_spec_ab(pred, steps):
    """Interleaved speculative-vs-plain greedy A/B on the same
    predictor; acceptance rate measured from the observability
    counters."""
    from paddle_tpu import observability as obs

    rng = np.random.RandomState(3)
    prompts = _prompts(BATCH, rng)
    # untimed warm round per arm (draft + verify signatures compile
    # here, outside the measured region) + the lossless check
    plain = pred.generate(prompts, max_new_tokens=steps)
    spec = pred.generate(prompts, max_new_tokens=steps, speculative=True,
                         spec_k=SPEC_K)
    assert all(np.array_equal(a, b) for a, b in zip(plain, spec)), \
        "speculative greedy diverged from plain greedy (lossless broken)"

    rates = {"speculative": [], "plain": []}
    walls = {"speculative": 0.0, "plain": 0.0}
    p0 = obs.DECODE_SPEC_PROPOSED.value()
    a0 = obs.DECODE_SPEC_ACCEPTED.value()
    for rnd in range(ROUNDS):
        order = (("speculative", "plain") if rnd % 2 == 0
                 else ("plain", "speculative"))
        for mode in order:
            t0 = time.perf_counter()
            outs = pred.generate(prompts, max_new_tokens=steps,
                                 speculative=(mode == "speculative"),
                                 spec_k=SPEC_K)
            dt = time.perf_counter() - t0
            walls[mode] += dt
            rates[mode].append(sum(len(o) for o in outs) / dt)
    proposed = obs.DECODE_SPEC_PROPOSED.value() - p0
    accepted = obs.DECODE_SPEC_ACCEPTED.value() - a0
    acceptance = float(accepted) / max(float(proposed), 1.0)
    for mode in ("speculative", "plain"):
        emit({"phase": "spec_ab", "mode": mode, "batch": BATCH,
              "decode_steps": steps, "spec_k": SPEC_K,
              "draft_layers": DRAFT_LAYERS, "rounds": ROUNDS,
              "favorable": bool(SPEC_FAVORABLE),
              "tokens_per_sec": float(np.median(rates[mode])),
              "tokens_per_sec_rounds": [float(r) for r in rates[mode]],
              "wall_s": float(walls[mode])})
    sp = float(np.median(rates["speculative"]))
    pl = float(np.median(rates["plain"]))
    emit({"phase": "spec_speedup", "batch": BATCH, "decode_steps": steps,
          "spec_k": SPEC_K, "draft_layers": DRAFT_LAYERS,
          "favorable": bool(SPEC_FAVORABLE),
          "acceptance_rate": acceptance,
          "spec_tokens_per_sec": sp, "plain_tokens_per_sec": pl,
          "speedup": sp / pl})
    return sp / pl


def bench_prefix_ab(model_dir):
    """Shared-prefix admission vs private prefills: CONT_REQUESTS
    requests over PREFIX_GROUPS distinct prompts through a prefix-
    cached and an uncached DecodeServer."""
    from paddle_tpu.serving.decode import DecodePredictor, DecodeServer

    rng = np.random.RandomState(4)
    steps = min(STEPS_LIST)
    groups = _prompts(PREFIX_GROUPS, rng, length=PROMPT)
    prompts = [groups[i % PREFIX_GROUPS] for i in range(CONT_REQUESTS)]
    max_new = max(4, steps // 4)

    pred = DecodePredictor(model_dir)
    servers = {}
    for mode in ("shared", "private"):
        srv = DecodeServer(pred, slots=CONT_SLOTS,
                           max_seq=PROMPT + max_new + SPEC_K + 1,
                           max_new_tokens=max_new,
                           prefix_cache=(mode == "shared"))
        srv.start()
        servers[mode] = srv

    def run_round(mode):
        srv = servers[mode]
        t0 = time.perf_counter()
        futs = [srv.submit((p,)) for p in prompts]
        outs = [f.result(timeout=600)[0] for f in futs]
        return outs, time.perf_counter() - t0

    results = {}
    for mode in ("shared", "private"):  # untimed warm round per arm
        results[mode], _ = run_round(mode)
    assert all(np.array_equal(a, b) for a, b in
               zip(results["shared"], results["private"])), \
        "prefix-shared admission diverged from private prefills"
    rates = {"shared": [], "private": []}
    walls = {"shared": 0.0, "private": 0.0}
    prefills = {}
    base = {m: servers[m].prefill_executions for m in servers}
    for rnd in range(CONT_ROUNDS):
        order = (("shared", "private") if rnd % 2 == 0
                 else ("private", "shared"))
        for mode in order:
            outs, dt = run_round(mode)
            rates[mode].append(sum(len(o) for o in outs) / dt)
            walls[mode] += dt
    for mode in ("shared", "private"):
        prefills[mode] = servers[mode].prefill_executions - base[mode]
        servers[mode].stop()
        emit({"phase": "prefix_ab", "mode": mode, "slots": CONT_SLOTS,
              "requests": CONT_REQUESTS, "groups": PREFIX_GROUPS,
              "max_new": max_new, "rounds": CONT_ROUNDS,
              "prefill_executions": int(prefills[mode]),
              "tokens_per_sec": float(np.median(rates[mode])),
              "tokens_per_sec_rounds": [float(r) for r in rates[mode]],
              "wall_s": float(walls[mode])})
    sh = float(np.median(rates["shared"]))
    pr = float(np.median(rates["private"]))
    emit({"phase": "prefix_speedup", "slots": CONT_SLOTS,
          "requests": CONT_REQUESTS, "groups": PREFIX_GROUPS,
          "shared_tokens_per_sec": sh, "private_tokens_per_sec": pr,
          "shared_prefills": int(prefills["shared"]),
          "private_prefills": int(prefills["private"]),
          "speedup": sh / pr})
    return sh / pr


def bench_batch_modes(model_dir):
    from paddle_tpu.serving.decode import DecodePredictor, DecodeServer

    steps = max(STEPS_LIST)
    rng = np.random.RandomState(1)
    prompts = _prompts(CONT_REQUESTS, rng)
    if CONT_MAXNEW_MIX:
        mix = [int(x) for x in CONT_MAXNEW_MIX.split(",")]
    else:
        mix = [max(4, steps // 16), steps // 2]
    budgets = [mix[i % len(mix)] for i in range(CONT_REQUESTS)]
    max_new = max(budgets)

    # ONE predictor (and its executable cache) behind both schedules:
    # the A/B measures the SCHEDULING policy, not who compiled first
    pred = DecodePredictor(model_dir)
    servers = {}
    for mode in ("continuous", "static"):
        srv = DecodeServer(pred, slots=CONT_SLOTS,
                           max_seq=PROMPT + max_new,
                           max_new_tokens=max_new,
                           continuous=(mode == "continuous"))
        srv.start()
        servers[mode] = srv

    def run_round(mode):
        srv = servers[mode]
        t0 = time.perf_counter()
        futs = [srv.submit((p, np.array([mn], np.int64)))
                for p, mn in zip(prompts, budgets)]
        outs = [f.result(timeout=600)[0] for f in futs]
        return [np.asarray(o) for o in outs], time.perf_counter() - t0

    results = {}
    rates = {"continuous": [], "static": []}
    walls = {"continuous": 0.0, "static": 0.0}
    active = {"continuous": [], "static": []}
    iters = {}
    for mode in ("continuous", "static"):  # untimed warm round per arm
        results[mode], _ = run_round(mode)
        servers[mode].step_active_counts.clear()
    for rnd in range(CONT_ROUNDS):
        # alternate which arm goes first so slow drifts (thermal, other
        # tenants of this box) hit both equally
        order = (("continuous", "static") if rnd % 2 == 0
                 else ("static", "continuous"))
        for mode in order:
            outs, dt = run_round(mode)
            toks = sum(len(o) for o in outs)
            rates[mode].append(toks / dt)
            walls[mode] += dt
    for mode in ("continuous", "static"):
        srv = servers[mode]
        if srv.step_active_counts:
            active[mode].append(float(np.mean(srv.step_active_counts)))
        # structural, noise-free half of the claim: decode iterations
        # per round — continuous needs fewer sweeps of the same (slots,
        # S) executable to emit the same tokens
        iters[mode] = len(srv.step_active_counts) / float(CONT_ROUNDS)
        srv.stop()
    # both schedules must produce identical tokens (greedy, same model)
    assert all(np.array_equal(a, b) for a, b in
               zip(results["continuous"], results["static"])), \
        "continuous and static batching diverged"
    for mode in ("continuous", "static"):
        emit({"phase": "batch_mode", "mode": mode, "slots": CONT_SLOTS,
              "requests": CONT_REQUESTS,
              "max_new_mix": ",".join(str(m) for m in mix),
              "rounds": CONT_ROUNDS,
              "tokens": sum(budgets),
              "tokens_per_sec": float(np.median(rates[mode])),
              "tokens_per_sec_rounds": [float(r) for r in rates[mode]],
              "mean_active": (float(np.mean(active[mode]))
                              if active[mode] else 0.0),
              "decode_iters_per_round": float(iters[mode]),
              "wall_s": float(walls[mode])})
    cont = float(np.median(rates["continuous"]))
    stat = float(np.median(rates["static"]))
    emit({"phase": "batching_speedup", "slots": CONT_SLOTS,
          "requests": CONT_REQUESTS,
          "continuous_tokens_per_sec": cont,
          "static_tokens_per_sec": stat, "speedup": cont / stat,
          "iters_ratio": float(iters["static"])
          / max(float(iters["continuous"]), 1.0)})
    return cont / stat


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--speculative", action="store_true",
                    help="add the speculative-vs-plain interleaved A/B")
    ap.add_argument("--prefix-share", action="store_true",
                    help="add the shared-prefix admission A/B")
    args = ap.parse_args(argv)

    from paddle_tpu.serving.decode import DecodePredictor

    with tempfile.TemporaryDirectory() as model_dir:
        _export_model(model_dir,
                      spec_favorable=args.speculative and SPEC_FAVORABLE)
        pred = DecodePredictor(model_dir, draft_n_layer=DRAFT_LAYERS)
        for steps in STEPS_LIST:
            bench_decode_ab(pred, steps)
        if args.speculative:
            bench_spec_ab(pred, max(STEPS_LIST))
        del pred
        bench_batch_modes(model_dir)
        if args.prefix_share:
            bench_prefix_ab(model_dir)


if __name__ == "__main__":
    main()
