"""List / inspect / GC a training checkpoint directory.

Enumerates `checkpoint/layout.py` entries: serial, completeness (the
``_COMPLETE`` sentinel), size, age, and meta (step/epoch/global_step/
fingerprint), plus in-flight or crashed ``tmp-`` partials. `--keep N`
applies the same retention GC the CheckpointManager runs after every
save; `--sweep-stale` removes partials whose writer pid is dead.
tests/test_ckpt_ls_smoke.py pins the `--json` schema in tier-1
(the aot_cache_ls pattern), so a field rename fails CI before it
breaks a cleanup cron.

Usage:
    python tools/ckpt_ls.py DIR [--json]
    python tools/ckpt_ls.py DIR --keep 3
    python tools/ckpt_ls.py DIR --sweep-stale
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

SCHEMA = "ckpt_ls/1"

_META_FIELDS = ("step", "epoch", "offset", "global_step", "trainer_id",
                "fingerprint")


def snapshot(checkpoint_dir: str, now=None) -> dict:
    """The --json payload (also what the smoke test pins)."""
    from paddle_tpu.checkpoint import layout

    now = time.time() if now is None else now
    entries = []
    for path, serial, complete in layout.list_entries(checkpoint_dir):
        try:
            mtime = os.path.getmtime(path)
        except OSError:
            mtime = now
        entry = {
            "name": os.path.basename(path),
            "serial": serial,  # None = tmp- partial
            "complete": complete,
            "bytes": layout.dir_nbytes(path),
            "age_s": max(0.0, now - mtime),
        }
        meta = None
        if complete:
            try:
                meta = layout.read_meta(path)
            except Exception:
                meta = None
        entry["meta"] = ({k: meta.get(k) for k in _META_FIELDS}
                         if isinstance(meta, dict) else None)
        entries.append(entry)
    return {
        "schema": SCHEMA,
        "dir": os.path.abspath(checkpoint_dir),
        "latest": layout.latest_serial(checkpoint_dir),
        "complete": len([e for e in entries if e["complete"]]),
        "incomplete": len([e for e in entries if not e["complete"]]),
        "total_bytes": sum(e["bytes"] for e in entries),
        "entries": entries,
    }


def _fmt_age(s):
    for unit, div in (("d", 86400.0), ("h", 3600.0), ("m", 60.0)):
        if s >= div:
            return "%.1f%s" % (s / div, unit)
    return "%.0fs" % s


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("dir", help="checkpoint directory")
    ap.add_argument("--json", action="store_true",
                    help="print the pinned-schema JSON snapshot")
    ap.add_argument("--keep", type=int, default=None, metavar="N",
                    help="retention GC: keep only the newest N complete "
                         "checkpoints (what CheckpointManager does)")
    ap.add_argument("--sweep-stale", action="store_true",
                    help="remove tmp- partials whose writer pid is dead")
    args = ap.parse_args()

    from paddle_tpu.checkpoint import layout

    out = snapshot(args.dir)
    if args.sweep_stale:
        out["swept"] = [os.path.basename(p)
                        for p in layout.sweep_stale_partials(args.dir)]
        out["entries"] = [e for e in out["entries"]
                          if e["name"] not in out["swept"]]
    if args.keep is not None:
        out["gc_removed"] = layout.retention_gc(args.dir, args.keep)
        removed = {"%s%d" % (layout.CKPT_PREFIX, s)
                   for s in out["gc_removed"]}
        out["entries"] = [e for e in out["entries"]
                          if e["name"] not in removed
                          and os.path.exists(
                              os.path.join(args.dir, e["name"]))]
        out["latest"] = layout.latest_serial(args.dir)

    if args.json:
        json.dump(out, sys.stdout, indent=2)
        sys.stdout.write("\n")
        return

    print("checkpoint dir: %s  (latest complete serial: %s)"
          % (out["dir"], out["latest"]))
    fmt = "%-28s %-9s %10s %8s %6s %6s %-9s"
    print(fmt % ("NAME", "STATE", "BYTES", "AGE", "EPOCH", "STEP",
                 "PROGRAM"))
    for e in out["entries"]:
        meta = e["meta"] or {}
        fp = meta.get("fingerprint") or "?"
        print(fmt % (e["name"],
                     "complete" if e["complete"] else "PARTIAL",
                     e["bytes"], _fmt_age(e["age_s"]),
                     meta.get("epoch", "?"),
                     meta.get("global_step", meta.get("step", "?")),
                     fp[:8] if isinstance(fp, str) else fp))
    print("%d complete, %d incomplete, %d bytes total"
          % (out["complete"], out["incomplete"], out["total_bytes"]))
    if args.sweep_stale:
        print("swept stale partials: %s" % (out["swept"] or "nothing"))
    if args.keep is not None:
        print("gc removed serials: %s" % (out["gc_removed"] or "nothing"))


if __name__ == "__main__":
    main()
