"""Cold-start vs warm-start: fresh-subprocess time-to-first-step.

Measures what the persistent AOT executable cache (runtime/aot_cache.py)
buys a FRESH process: each sample is a real subprocess that builds a
training program, runs the startup program, and executes the first
training step — cold (empty cache directory) or warm (directory primed
by a previous process). Cold and warm replicates are INTERLEAVED
(PERF_NOTES methodology: alternating A/B absorbs drift from CPU
frequency/load), and one JSON line is emitted per config:

    {"bench": "coldstart", "config": "mlp", "cold_ttfs_s": [...],
     "warm_ttfs_s": [...], "cold_median_s": ..., "warm_median_s": ...,
     "warmstart_speedup": ..., ...}

``ttfs_s`` (time-to-first-step) = program build + startup run + first
training step, measured INSIDE the child after imports: interpreter +
jax import time is reported separately (``import_s``) because no
executable cache can help it and it would otherwise dilute the number
being measured. The fused-loop window compile (`run_loop`) is timed as
``loop_s`` on top.

Usage:
    JAX_PLATFORMS=cpu python tools/bench_coldstart.py \
        [--replicates 3] [--configs mlp,mlp-wide] [--loop-steps 4]

tests/test_bench_coldstart_smoke.py pins the line schema in tier-1.
"""
from __future__ import annotations

import argparse
import json
import os
import shutil
import subprocess
import sys
import tempfile
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

SCHEMA = "bench_coldstart/1"

# config name -> (input dim, hidden widths, batch rows). Wider nets give
# XLA more to chew on, so the cold/warm gap grows with size.
CONFIGS = {
    "mlp": (64, (256, 256, 256), 32),
    "mlp-wide": (256, (1024, 1024, 1024, 1024), 64),
    "mlp-tiny": (8, (16,), 4),  # smoke-test sized
}


def _child(config: str, loop_steps: int):
    """One timed sample, printed as a single JSON line. Runs in a FRESH
    interpreter so every cost a restart pays (trace, XLA compile or
    deserialize, weight init) is inside the measurement."""
    t_proc = time.perf_counter()
    import jax  # noqa: F401 — the import being timed

    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    import numpy as np

    import paddle_tpu as fluid
    from paddle_tpu import layers, optimizer, observability as obs

    t_import = time.perf_counter()
    in_dim, widths, batch = CONFIGS[config]

    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.scope_guard(scope), fluid.program_guard(main, startup):
        with fluid.unique_name.guard():
            x = layers.data(name="x", shape=[in_dim])
            y = layers.data(name="y", shape=[1])
            h = x
            for w in widths:
                h = layers.fc(h, w, act="relu")
            loss = layers.mean(layers.square(layers.fc(h, 1) - y))
            optimizer.SGD(learning_rate=0.01).minimize(loss)
    t_build = time.perf_counter()

    rs = np.random.RandomState(0)
    feed = {"x": rs.rand(batch, in_dim).astype(np.float32),
            "y": rs.rand(batch, 1).astype(np.float32)}
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        t_startup = time.perf_counter()
        first = exe.run(main, feed=feed, fetch_list=[loss])[0]
        t_first = time.perf_counter()
        exe.run_loop(main, feed=feed, fetch_list=[loss], steps=loop_steps)
        t_loop = time.perf_counter()

    hits = sum(obs.CACHE_HITS.value(kind=k, tier="disk",
                                    program=obs.program_fp(p))
               for k in ("run", "loop") for p in (main, startup))
    misses = sum(obs.CACHE_MISSES.value(kind=k, tier="disk",
                                        program=obs.program_fp(p))
                 for k in ("run", "loop") for p in (main, startup))
    cold = sum(obs.AOT_COMPILE_MS.stats(path="cold", kind=k)["count"]
               for k in ("run", "loop"))
    warm = sum(obs.AOT_COMPILE_MS.stats(path="warm", kind=k)["count"]
               for k in ("run", "loop"))
    json.dump({
        "config": config,
        "import_s": t_import - t_proc,
        "build_s": t_build - t_import,
        "startup_s": t_startup - t_build,
        "first_step_s": t_first - t_startup,
        "loop_s": t_loop - t_first,
        "ttfs_s": t_first - t_import,
        "total_s": t_loop - t_proc,
        "first_loss": float(np.asarray(first).ravel()[0]),
        "disk_hits": hits,
        "disk_misses": misses,
        "cold_compiles": cold,
        "warm_loads": warm,
    }, sys.stdout)
    sys.stdout.write("\n")


def _run_child(config: str, cache_dir: str, loop_steps: int) -> dict:
    env = dict(os.environ,
               JAX_PLATFORMS=os.environ.get("JAX_PLATFORMS", "cpu"),
               PADDLE_TPU_AOT_CACHE_DIR=cache_dir,
               PADDLE_TPU_AOT_CACHE="1")
    # keep the axon sitecustomize plugin from force-selecting a TPU
    # tunnel in the child (the bench measures host-side compile caching)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    # and keep jax's OWN persistent cache (the optional second tier) out
    # of both arms: an inherited PADDLE_TPU_JAX_CACHE_DIR would warm the
    # "cold" children at the HLO level and understate the speedup
    env.pop("PADDLE_TPU_JAX_CACHE_DIR", None)
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--child",
         "--config", config, "--loop-steps", str(loop_steps)],
        capture_output=True, text=True, timeout=1200, env=env, cwd=_REPO)
    if proc.returncode != 0:
        raise RuntimeError("coldstart child failed:\n" + proc.stderr[-4000:])
    return json.loads(proc.stdout.strip().splitlines()[-1])


def _median(xs):
    xs = sorted(xs)
    n = len(xs)
    return xs[n // 2] if n % 2 else 0.5 * (xs[n // 2 - 1] + xs[n // 2])


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--child", action="store_true", help=argparse.SUPPRESS)
    ap.add_argument("--config", default="mlp", help=argparse.SUPPRESS)
    ap.add_argument("--configs", default="mlp,mlp-wide",
                    help="comma-separated config names (%s)"
                         % ",".join(sorted(CONFIGS)))
    ap.add_argument("--replicates", type=int, default=3,
                    help="interleaved cold/warm pairs per config")
    ap.add_argument("--loop-steps", type=int, default=4,
                    help="run_loop window length timed after the first step")
    args = ap.parse_args()

    if args.child:
        _child(args.config, args.loop_steps)
        return

    results = []
    for config in [c for c in args.configs.split(",") if c]:
        if config not in CONFIGS:
            raise SystemExit("unknown config %r (have: %s)"
                             % (config, ", ".join(sorted(CONFIGS))))
        warm_dir = tempfile.mkdtemp(prefix="ptpu-coldstart-warm-")
        cold_dirs = []
        try:
            # prime the warm directory once (this sample is discarded:
            # it pays the compile that later warm runs reuse)
            prime = _run_child(config, warm_dir, args.loop_steps)
            cold, warm = [], []
            for _ in range(args.replicates):
                d = tempfile.mkdtemp(prefix="ptpu-coldstart-cold-")
                cold_dirs.append(d)
                cold.append(_run_child(config, d, args.loop_steps))
                warm.append(_run_child(config, warm_dir, args.loop_steps))
            bad_warm = [w for w in warm if w["warm_loads"] == 0]
            cold_med = _median([c["ttfs_s"] for c in cold])
            warm_med = _median([w["ttfs_s"] for w in warm])
            line = {
                "bench": "coldstart",
                "schema": SCHEMA,
                "config": config,
                "replicates": args.replicates,
                "loop_steps": args.loop_steps,
                "cold_ttfs_s": [round(c["ttfs_s"], 4) for c in cold],
                "warm_ttfs_s": [round(w["ttfs_s"], 4) for w in warm],
                "cold_median_s": round(cold_med, 4),
                "warm_median_s": round(warm_med, 4),
                "warmstart_speedup": round(cold_med / warm_med, 3)
                if warm_med else None,
                "cold_loop_median_s": round(
                    _median([c["loop_s"] for c in cold]), 4),
                "warm_loop_median_s": round(
                    _median([w["loop_s"] for w in warm]), 4),
                "import_median_s": round(_median(
                    [r["import_s"] for r in cold + warm]), 4),
                "prime_ttfs_s": round(prime["ttfs_s"], 4),
                "warm_used_cache": not bad_warm,
            }
            results.append(line)
            print(json.dumps(line), flush=True)
        finally:
            shutil.rmtree(warm_dir, ignore_errors=True)
            for d in cold_dirs:
                shutil.rmtree(d, ignore_errors=True)
    if results:
        speedups = [r["warmstart_speedup"] for r in results
                    if r["warmstart_speedup"]]
        print(json.dumps({
            "bench": "coldstart_summary",
            "schema": SCHEMA,
            "configs": [r["config"] for r in results],
            "min_speedup": min(speedups) if speedups else None,
            "max_speedup": max(speedups) if speedups else None,
        }), flush=True)


if __name__ == "__main__":
    main()
