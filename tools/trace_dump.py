"""Render a fleet trace snapshot: text waterfall / JSON / Chrome trace.

Input is the merged span list ``Router.fleet_trace()`` returns — the
same document ``GET /trace.json`` serves and the loadgen verdict's
``trace_phases`` is derived from. A single-process recorder snapshot
(``observability.tracing.snapshot()``) is accepted too and merged on
the fly. Three output modes:

* default — a per-request text waterfall: one block per trace_id, one
  line per span with its offset from trace start, duration, origin
  replica, and a proportional bar. The fastest way to answer "where
  did this request's 40 ms go?" at a terminal.
* ``--json`` — a structured ``trace_dump/1`` document (schema-pinned by
  tests/test_trace_dump_smoke.py): spans grouped per trace with start
  time and total extent, plus the fleet ring accounting.
* ``--chrome`` — Chrome trace-event JSON (the ``traceEvents`` array
  format): load it in Perfetto / chrome://tracing and every replica is
  a process row, every trace a thread row, every span a slice.

Stays OFF the jax import path entirely (the metrics_dump --merge
trick): rendering is pure dict arithmetic and the observability
subtree is jax-free, so a trace sidecar pays ~ms, not a framework
import. ``--demo`` synthesizes a two-process request trace through the
real ``merge_snapshots`` path — a fixture for the smoke test and a
format preview that needs no fleet.

Usage:
    curl -s localhost:8000/trace.json | python tools/trace_dump.py
    python tools/trace_dump.py --input fleet_trace.json --chrome > t.json
    python tools/trace_dump.py --demo --json
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import types
from typing import Dict, List

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

SCHEMA = "trace_dump/1"

# span keys that are structure, not user attrs (everything else prints
# in the waterfall's attr column)
_CORE_KEYS = frozenset(("trace_id", "name", "ts", "dur_ms", "seq",
                        "replica"))


def _import_tracing():
    """paddle_tpu.observability.tracing without the parent package's
    jax-importing __init__ (bare namespace stub with the right
    __path__ — the metrics_dump --merge idiom)."""
    if "paddle_tpu" not in sys.modules:
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        stub = types.ModuleType("paddle_tpu")
        stub.__path__ = [os.path.join(root, "paddle_tpu")]
        sys.modules["paddle_tpu"] = stub
    from paddle_tpu.observability import tracing
    return tracing


def demo_snapshot() -> Dict:
    """A deterministic two-process fleet trace (router + one worker,
    one served request + one shed request) through the REAL
    merge_snapshots path — the smoke-test fixture."""
    tracing = _import_tracing()
    base = 1700000000.0
    tid = "deadbeef4ee75ace"
    shed = "deadbeef00005hed"
    router = {
        "capacity": 4096, "recorded": 6, "dropped": 0, "replica": "",
        "spans": [
            {"trace_id": tid, "name": "client.submit", "ts": base,
             "dur_ms": 0.0, "seq": 0, "rid": 1, "klass": "interactive"},
            {"trace_id": tid, "name": "router.queue", "ts": base,
             "dur_ms": 1.8, "seq": 1, "rid": 1, "klass": "interactive"},
            {"trace_id": tid, "name": "router.dispatch",
             "ts": base + 0.0018, "dur_ms": 0.0, "seq": 2, "rid": 1,
             "replica": "w0"},
            {"trace_id": tid, "name": "router.reply",
             "ts": base + 0.0018, "dur_ms": 6.4, "seq": 3, "rid": 1,
             "error": False},
            {"trace_id": shed, "name": "client.submit",
             "ts": base + 0.001, "dur_ms": 0.0, "seq": 4, "rid": 2,
             "klass": "batch"},
            {"trace_id": shed, "name": "router.shed",
             "ts": base + 0.001, "dur_ms": 3.1, "seq": 5, "rid": 2,
             "reason": "expired", "dominant_phase": "queue"},
        ]}
    worker = {
        "capacity": 4096, "recorded": 4, "dropped": 0, "replica": "w0",
        "spans": [
            {"trace_id": tid, "name": "worker.recv", "ts": base + 0.0021,
             "dur_ms": 0.0, "seq": 0, "rid": 7},
            {"trace_id": tid, "name": "server.stack", "ts": base + 0.0034,
             "dur_ms": 0.9, "seq": 1, "rid": 7, "rows": 4, "bucket": 4},
            {"trace_id": tid, "name": "server.device", "ts": base + 0.0043,
             "dur_ms": 3.2, "seq": 2, "rid": 7},
            {"trace_id": tid, "name": "worker.reply", "ts": base + 0.0021,
             "dur_ms": 5.9, "seq": 3, "rid": 7},
        ]}
    return tracing.merge_snapshots([router, worker])


def load_snapshot(path: str) -> Dict:
    """Load a fleet_trace() document — or a single recorder snapshot,
    normalized through merge_snapshots so both shapes render."""
    if path == "-":
        snap = json.load(sys.stdin)
    else:
        with open(path) as f:
            snap = json.load(f)
    if "spans" not in snap:
        raise SystemExit("trace_dump: %s carries no 'spans' list "
                         "(expected a /trace.json or tracing.snapshot() "
                         "document)" % path)
    if "replicas" not in snap:  # single-process recorder snapshot
        snap = _import_tracing().merge_snapshots([snap])
    return snap


def group_traces(merged: Dict) -> List[Dict]:
    """Per-trace_id groups, each ts-sorted with start/extent computed —
    the unit both the waterfall and the JSON doc render."""
    by_tid: Dict[str, List[Dict]] = {}
    for s in merged.get("spans", ()):
        by_tid.setdefault(s["trace_id"], []).append(s)
    traces = []
    for tid, spans in by_tid.items():
        spans = sorted(spans, key=lambda s: (s["ts"], s.get("seq", 0)))
        t0 = min(s["ts"] for s in spans)
        t1 = max(s["ts"] + float(s.get("dur_ms", 0.0)) / 1e3
                 for s in spans)
        traces.append({"trace_id": tid, "start_ts": t0,
                       "total_ms": round((t1 - t0) * 1e3, 4),
                       "spans": spans})
    traces.sort(key=lambda t: t["start_ts"])
    return traces


def _attr_str(span: Dict) -> str:
    attrs = {k: v for k, v in span.items() if k not in _CORE_KEYS}
    if not attrs:
        return ""
    return " ".join("%s=%s" % (k, attrs[k]) for k in sorted(attrs))


def render_text(merged: Dict, width: int = 32) -> str:
    traces = group_traces(merged)
    lines = ["fleet trace: %d span(s), %d trace(s), replicas=%s, "
             "recorded=%d dropped=%d"
             % (len(merged.get("spans", ())), len(traces),
                ",".join(r or "router"
                         for r in merged.get("replicas", [])) or "-",
                merged.get("recorded", 0), merged.get("dropped", 0))]
    for tr in traces:
        extent = max(tr["total_ms"], 1e-9)
        lines.append("")
        lines.append("trace %s  (%d spans, %.3f ms)"
                     % (tr["trace_id"], len(tr["spans"]),
                        tr["total_ms"]))
        for s in tr["spans"]:
            off_ms = (s["ts"] - tr["start_ts"]) * 1e3
            dur = float(s.get("dur_ms", 0.0))
            lo = int(round(off_ms / extent * width))
            lo = min(lo, width - 1)
            if dur > 0:
                n = max(1, int(round(dur / extent * width)))
                bar = " " * lo + "#" * min(n, width - lo)
            else:
                bar = " " * lo + "|"
            lines.append(
                "  +%9.3fms %-16s %-8s %9.3fms  [%-*s] %s"
                % (off_ms, s["name"], s.get("replica", "") or "router",
                   dur, width, bar, _attr_str(s)))
    return "\n".join(lines)


def to_doc(merged: Dict) -> Dict:
    """The trace_dump/1 JSON document (schema pinned in CI)."""
    traces = group_traces(merged)
    return {"schema": SCHEMA,
            "replicas": merged.get("replicas", []),
            "recorded": merged.get("recorded", 0),
            "dropped": merged.get("dropped", 0),
            "span_count": len(merged.get("spans", ())),
            "trace_count": len(traces),
            "traces": traces}


def to_chrome(merged: Dict) -> Dict:
    """Chrome trace-event JSON: replica -> process row, trace_id ->
    thread row, span -> "X" slice (instants become zero-width slices —
    Perfetto renders them as ticks). ts/dur are microseconds."""
    events = []
    pids: Dict[str, int] = {}
    tids: Dict[str, int] = {}
    for s in merged.get("spans", ()):
        replica = s.get("replica", "") or "router"
        if replica not in pids:
            pids[replica] = len(pids) + 1
            events.append({"ph": "M", "name": "process_name",
                           "pid": pids[replica], "tid": 0,
                           "args": {"name": replica}})
        tkey = s["trace_id"]
        if tkey not in tids:
            tids[tkey] = len(tids) + 1
        events.append({
            "ph": "X", "name": s["name"], "cat": "paddle_tpu",
            "pid": pids[replica], "tid": tids[tkey],
            "ts": round(s["ts"] * 1e6, 1),
            "dur": round(float(s.get("dur_ms", 0.0)) * 1e3, 1),
            "args": {k: v for k, v in s.items() if k not in _CORE_KEYS}})
    for tkey, tnum in tids.items():
        for pid in set(e["pid"] for e in events if e["ph"] == "X"
                       and e["tid"] == tnum):
            events.append({"ph": "M", "name": "thread_name", "pid": pid,
                           "tid": tnum,
                           "args": {"name": "trace %s" % tkey}})
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--input", default="-", metavar="FILE",
                    help="fleet /trace.json (or a single recorder "
                    "snapshot); '-' = stdin (default)")
    ap.add_argument("--demo", action="store_true",
                    help="render a synthesized two-process demo trace "
                    "instead of reading input")
    ap.add_argument("--json", action="store_true",
                    help="emit the structured trace_dump/1 document")
    ap.add_argument("--chrome", action="store_true",
                    help="emit Chrome trace-event JSON (Perfetto / "
                    "chrome://tracing)")
    args = ap.parse_args()

    merged = demo_snapshot() if args.demo else load_snapshot(args.input)
    if args.chrome:
        print(json.dumps(to_chrome(merged), sort_keys=True))
    elif args.json:
        print(json.dumps(to_doc(merged), indent=2, sort_keys=True))
    else:
        print(render_text(merged))


if __name__ == "__main__":
    main()
