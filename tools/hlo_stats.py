from xprof.convert import raw_to_tool_data as rtd
import glob, json
fs = glob.glob("/tmp/jaxprof/**/*.xplane.pb", recursive=True)
data, _ = rtd.xspace_to_tool_data(fs, "hlo_stats", {})
d = json.loads(data)
cols = [c["id"] if isinstance(c, dict) else c for c in d["cols"]]
print(cols)
rows = []
for r in d["rows"]:
    vals = [c.get("v") if isinstance(c, dict) else c for c in (r["c"] if isinstance(r, dict) else r)]
    rows.append(dict(zip(cols, vals)))
# sort by total time
key_time = [c for c in cols if "total" in c.lower() or "time" in c.lower()]
print(key_time[:6])
import sys
tt = "total_time" if "total_time" in cols else key_time[0]
rows.sort(key=lambda x: -(x.get(tt) or 0))
for r in rows[:25]:
    print(json.dumps(r)[:400])
