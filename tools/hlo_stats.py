"""Dump the top HLO ops by self time from the newest /tmp/jaxprof capture,
plus a per-category rollup. Companion to tools/profile_bench.py."""
import glob
import json
import sys
from collections import defaultdict

from xprof.convert import raw_to_tool_data as rtd

fs = sorted(glob.glob("/tmp/jaxprof/**/*.xplane.pb", recursive=True))
if not fs:
    sys.exit("no /tmp/jaxprof/**/*.xplane.pb captures found")
data, _ = rtd.xspace_to_tool_data(fs[-1:], "hlo_stats", {})
d = json.loads(data)
cols = [c["id"] if isinstance(c, dict) else c for c in d["cols"]]
rows = []
for r in d["rows"]:
    vals = [c.get("v") if isinstance(c, dict) else c
            for c in (r["c"] if isinstance(r, dict) else r)]
    rows.append(dict(zip(cols, vals)))

tt = "total_self_time" if "total_self_time" in cols else "total_time"
if tt not in cols:
    sys.exit("no time column in hlo_stats table; columns were: %s" % cols)

cat = defaultdict(float)
total = 0.0
for r in rows:
    t = r.get(tt) or 0
    cat[r.get("category", "?")] += t
    total += t
if not rows or total == 0.0:
    sys.exit("capture %s has an empty hlo_stats table (CPU-only traces "
             "carry no HLO device stats — capture on the TPU backend)"
             % fs[-1])
for k, v in sorted(cat.items(), key=lambda kv: -kv[1]):
    print("%6.1f%%  %s" % (100 * v / total, k))
print()
rows.sort(key=lambda x: -(x.get(tt) or 0))
for r in rows[:25]:
    expr = (r.get("hlo_op_expression") or "")[:140]
    print("%5.2f%%  %-22s bound=%-7s %s"
          % (100 * (r.get(tt) or 0) / total, r.get("category", "?"),
             r.get("bound_by"), expr))
