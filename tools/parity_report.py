"""API-parity audit: compare paddle_tpu's public surface against the
reference tree, module by module, and print a coverage table.

Usage:
    JAX_PLATFORMS=cpu python tools/parity_report.py [--ref /root/reference]

For every reference module with an __all__ (fluid layers/*, fluid
top-level modules, paddle.reader, fluid.contrib), reports which symbols
exist here and lists any missing ones — including symbols added through
``__all__ += ...`` and list-variable concatenations like
``+ __activations__``. Also diffs the reference's operator registrations
(paddle/fluid/operators/**/*_op.cc, subdirectories included) against the
kernel registry, bucketing misses by why they are intentionally absent
(LoD/selected-rows/RPC machinery replaced by the dense GSPMD design).

``main()`` returns (symbol_rows, unexplained_ops) so tests/test_parity.py
can assert exact emptiness rather than parsing the printout.
"""
from __future__ import annotations

import argparse
import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# op families the dense/XLA design replaces wholesale rather than ports
INTENTIONAL = {
    "lod/tensor-array machinery (dense + lengths design)": {
        "array_to_lod_tensor", "lod_tensor_to_array", "lod_rank_table",
        "max_sequence_len", "merge_lod_tensor", "split_lod_tensor",
        "shrink_rnn_memory", "rnn_memory_helper", "tensor_array_read_write",
        "reorder_lod_tensor_by_rank",
    },
    "selected-rows machinery (dense scatter-add gradients)": {
        "extract_rows", "lookup_sparse_table", "merge_ids", "split_ids",
        "split_selected_rows", "split_byref",
    },
    "pserver/RPC stack (GSPMD sharding replaces it)": {
        "listen_and_serv", "send", "recv", "send_barrier", "fetch_barrier",
        "prefetch", "checkpoint_notify", "gen_nccl_id", "send_recv_util",
    },
    "executor-level plumbing (executor/scope handle these)": {
        "feed", "fetch", "save", "save_combine", "load", "load_combine",
        "delete_var",
    },
    "host-side CSP (fluid.concurrency)": {
        "channel_create", "channel_send", "channel_recv", "channel_close",
        "go", "select",
    },
    "reader-op pipeline (executor pulls from io/reader.py holders)": {
        # reference operators/reader/*: each C++ reader decorator maps to
        # a host-side pipeline stage behind the `read` op
        "create_py_reader", "create_double_buffer_reader",
        "create_batch_reader", "create_shuffle_reader",
        "create_multi_pass_reader", "create_threaded_reader",
        "create_random_data_generator", "create_recordio_file_reader",
        "create_custom_reader",  # layers.Preprocessor / PreprocessReader
        "open_files", "read",
        # REGISTER_FILE_READER(recordio, ...) is a file-FORMAT tag, not an
        # op; the C++ recordio reader in runtime/ serves the same role
        "recordio",
    },
    "NCCL collectives (XLA psum/all_gather/ppermute over ICI replace them)": {
        "ncclInit", "ncclAllReduce", "ncclReduce", "ncclBcast",
    },
    "host-side multi-device ops (Mesh/pjit + ParallelExecutor replace them)": {
        "parallel_do", "get_places",
    },
    "per-op RNN machinery (lax.scan StaticRNN/DynamicRNN replace it)": {
        "recurrent",
    },
    "layer-decomposed ops (the tracer emits mul/elementwise ops XLA re-fuses)": {
        "fc",
    },
}


_REG_CALL = re.compile(r"REGISTER_\w+\(\s*(\w+)")
_REG_DEFINE_PARAM = re.compile(r"#define\s+REGISTER_\w+\(\s*(\w+)")
_MACRO_LIST = re.compile(r"__macro\(\s*(\w+)\s*,\s*(\w+)")
_OP_NAME = re.compile(r"[a-z][a-zA-Z0-9_]*\Z")


def expand_op_cc(path, base):
    """Return the set of op names a reference *_op.cc actually registers
    (VERDICT r4 weak #2: umbrella files like pool_with_index_op.cc
    register several ops; trusting the basename laundered real gaps into
    'none'). Handles the three registration idioms of the tree:
    - direct REGISTER_OPERATOR/REGISTER_OP*(name, ...) calls;
    - per-file helper macros (REGISTER_COMPARE_OP(less_than, ...)) —
      macro *parameters* are auto-excluded by harvesting every
      `#define REGISTER_*(param` name in the same file;
    - X-macro lists (activation_op: FOR_EACH_OP_FUNCTOR's
      `__macro(CamelName, snake_name)` rows), used only when the direct
      scan finds nothing so generic `__macro` args elsewhere can't leak.
    Grad registrations are dropped: autodiff is jax.vjp, not per-op grad
    kernels. Falls back to the file basename when nothing matches."""
    try:
        src = open(path, encoding="utf-8", errors="replace").read()
    except IOError:
        return {base}
    params = set(_REG_DEFINE_PARAM.findall(src))
    names = {n for n in _REG_CALL.findall(src)
             if n not in params and _OP_NAME.match(n)
             and not n.endswith("_grad")}
    if not names:
        names = {n for pair in _MACRO_LIST.findall(src) for n in pair
                 if _OP_NAME.match(n) and not n.endswith("_grad")}
    return names or {base}


def module_all(path):
    """All public symbols of a module: union of every list literal that
    feeds __all__ (direct assignment, +=, and `+ <listvar>` concatenation
    like layers/ops.py's __activations__)."""
    try:
        src = open(path, encoding="utf-8", errors="replace").read()
    except IOError:
        return None
    # list-literal assignments anywhere in the file: name -> symbols
    lists = {}
    for m in re.finditer(r"^(\w+)\s*\+?=\s*\[(.*?)\]", src, re.S | re.M):
        name, body = m.group(1), m.group(2)
        lists.setdefault(name, set()).update(
            re.findall(r"['\"](\w+)['\"]", body))
    if "__all__" not in lists:
        return None
    symbols = set(lists["__all__"])
    # pull in list variables referenced on any __all__ line (\Z: the
    # statement may be the last thing in the file)
    for m in re.finditer(r"^__all__\s*\+?=\s*(.+?)(?=^\S|\Z)", src,
                         re.S | re.M):
        for ref in re.findall(r"\b(__\w+__|\w+)\b", m.group(1)):
            if ref != "__all__" and ref in lists:
                symbols |= lists[ref]
    return sorted(symbols)


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--ref", default="/root/reference")
    args = ap.parse_args(argv)

    import paddle_tpu as fluid
    from paddle_tpu import layers

    rows = []
    total_have = total_want = 0

    fluid_dir = os.path.join(args.ref, "python", "paddle", "fluid")
    checks = [
        ("fluid.layers.nn", os.path.join(fluid_dir, "layers", "nn.py"), layers),
        ("fluid.layers.ops", os.path.join(fluid_dir, "layers", "ops.py"), layers),
        ("fluid.layers.tensor", os.path.join(fluid_dir, "layers", "tensor.py"), layers),
        ("fluid.layers.control_flow", os.path.join(fluid_dir, "layers", "control_flow.py"), layers),
        ("fluid.layers.io", os.path.join(fluid_dir, "layers", "io.py"), layers),
        ("fluid.layers.detection", os.path.join(fluid_dir, "layers", "detection.py"), layers),
        ("fluid.layers.metric_op", os.path.join(fluid_dir, "layers", "metric_op.py"), layers),
        ("fluid.layers.lr_scheduler", os.path.join(fluid_dir, "layers", "learning_rate_scheduler.py"), layers),
        ("fluid.layers.device", os.path.join(fluid_dir, "layers", "device.py"), layers),
        ("fluid.nets", os.path.join(fluid_dir, "nets.py"), fluid.nets),
        ("fluid.optimizer", os.path.join(fluid_dir, "optimizer.py"), fluid.optimizer),
        ("fluid.initializer", os.path.join(fluid_dir, "initializer.py"), fluid.initializer),
        ("fluid.regularizer", os.path.join(fluid_dir, "regularizer.py"), fluid.regularizer),
        ("fluid.clip", os.path.join(fluid_dir, "clip.py"), fluid.clip),
        ("fluid.metrics", os.path.join(fluid_dir, "metrics.py"), fluid.metrics),
        ("fluid.io", os.path.join(fluid_dir, "io.py"), fluid.io),
        ("fluid.average", os.path.join(fluid_dir, "average.py"), fluid.average),
        ("fluid.concurrency", os.path.join(fluid_dir, "concurrency.py"), fluid),
        ("fluid.recordio_writer", os.path.join(fluid_dir, "recordio_writer.py"), fluid.recordio_writer),
        ("paddle.reader", os.path.join(args.ref, "python", "paddle", "reader", "decorator.py"), fluid.reader),
        ("fluid.contrib.decoder", os.path.join(fluid_dir, "contrib", "decoder", "beam_search_decoder.py"), fluid.contrib),
    ]
    for label, path, target in checks:
        names = module_all(path)
        if names is None:
            continue
        missing = [n for n in names
                   if not hasattr(target, n) and not hasattr(fluid, n)]
        total_have += len(names) - len(missing)
        total_want += len(names)
        rows.append((label, len(names) - len(missing), len(names), missing))

    print("%-32s %9s  %s" % ("module", "coverage", "missing"))
    print("-" * 72)
    for label, have, want, missing in rows:
        print("%-32s %4d/%-4d  %s" % (label, have, want,
                                      ", ".join(missing) or "-"))
    print("-" * 72)
    if not total_want:
        raise SystemExit(
            "no reference modules with __all__ found under %r — wrong "
            "--ref path?" % args.ref)
    print("%-32s %4d/%-4d  (%.1f%%)" % ("TOTAL API symbols", total_have,
                                        total_want,
                                        100.0 * total_have / total_want))

    # operator diff: every *_op.cc anywhere under operators/ (the reader,
    # detection, nccl, ... subdirectories included)
    from paddle_tpu.ops.registry import registered_ops

    ours = set(registered_ops())
    op_dir = os.path.join(args.ref, "paddle", "fluid", "operators")
    ref_ops = set()
    n_files = 0
    for root, _dirs, files in os.walk(op_dir):
        for f in files:
            if f.endswith("_op.cc"):
                base = f[: -len("_op.cc")]
                if base.endswith("_mkldnn") or base == "tensorrt_engine":
                    continue
                n_files += 1
                ref_ops |= expand_op_cc(os.path.join(root, f), base)
    missing_ops = {o for o in ref_ops if o not in ours}
    explained = set()
    print("\nreference operators: %d files registering %d ops; "
          "registered kernels here: %d" % (n_files, len(ref_ops), len(ours)))
    for why, names in INTENTIONAL.items():
        hit = sorted(missing_ops & names)
        explained |= set(hit)
        if hit:
            print("  [by design] %s:\n      %s" % (why, ", ".join(hit)))
    rest = sorted(missing_ops - explained)
    print("  [unexplained gaps] %s" % (", ".join(rest) or "none"))
    return rows, rest


if __name__ == "__main__":
    main()
