#!/bin/bash
# Round-5 window play: run AFTER the watcher banked its plain bench +
# bthd repro (/tmp/autobench_done exists). Strict priority order; every
# row appends guarded JSON to /tmp/sweep_r5.jsonl; safe to re-run (the
# XLA compile cache makes repeat rows fast). ONE TPU process at a time.
#
# Per-row `timeout 2700`: SIGTERMing a claim-holder wedges a HEALTHY
# tunnel (round-3 lesson), so the bound sits far above any sane
# compile+run (~45 min) — a row that still exceeds it means the compile
# service is already wedged, and losing that claim costs nothing while
# freeing every remaining row.
set -u
cd "$(dirname "$0")/.."
OUT=/tmp/sweep_r5.jsonl

row() {
  # defaults first, "$@" last: a row's own BENCH_* assignments win.
  # O1 + no-fused pins the lever-isolation baseline (bench.py bakes the
  # O2+fused winner as its own defaults — without the pin every row
  # here would measure the identical config).
  local tag="$1"; shift
  echo "=== $tag ($(date -u +%H:%M:%S)) ===" | tee -a /tmp/window_play.log
  local line
  line=$(env BENCH_RESNET=0 BENCH_LSTM=0 BENCH_DEEPFM=0 \
         BENCH_AMP_LEVEL=O1 PADDLE_TPU_FLASH_FUSED_BWD=0 "$@" timeout 2700 \
         python bench.py 2>>/tmp/window_play.log | tail -1)
  echo "$line" | tee -a /tmp/window_play.log
  python - "$tag" "$line" <<'EOF' >> "$OUT"
import json, sys
try: r = json.loads(sys.argv[2])
except Exception: r = None
print(json.dumps({"tag": sys.argv[1], "result": r}))
EOF
}

touch /tmp/tpu_busy
trap 'rm -f /tmp/tpu_busy' EXIT

# 0. the baked bench.py defaults (what the driver's plain run measures)
row "baked-defaults"         BENCH_BATCH=16 BENCH_HEADS=8 BENCH_AMP_LEVEL=O2 PADDLE_TPU_FLASH_FUSED_BWD=1
# 1. headline candidates, most-likely-winner first (BTHD engages via the
#    fixed kernels; smoke re-runs automatically on the new kernel hash)
row "heads8-bthd"            BENCH_BATCH=16 BENCH_HEADS=8
row "heads8-bthd-fusedbwd"   BENCH_BATCH=16 BENCH_HEADS=8 PADDLE_TPU_FLASH_FUSED_BWD=1
row "heads8-bthd-O2"         BENCH_BATCH=16 BENCH_HEADS=8 BENCH_AMP_LEVEL=O2
row "heads8-all-levers"      BENCH_BATCH=16 BENCH_HEADS=8 BENCH_AMP_LEVEL=O2 PADDLE_TPU_FLASH_FUSED_BWD=1
row "b24-remat-all"          BENCH_BATCH=24 BENCH_HEADS=8 BENCH_REMAT=1 BENCH_AMP_LEVEL=O2 PADDLE_TPU_FLASH_FUSED_BWD=1
# 1b. tied embed/head table (r5: halves Adam f32 moment traffic + grad
#     convert chains on the two (32768,1024) params — profiled ~1.5-3%
#     lever; cross-lowered clean offline). A/B against baked-defaults.
row "tie-emb-all-levers"     BENCH_BATCH=16 BENCH_HEADS=8 BENCH_AMP_LEVEL=O2 PADDLE_TPU_FLASH_FUSED_BWD=1 BENCH_TIE=1
# 1c. transposed-form dW backward for fc matmuls (r5: targets the 4.65%
#     FFN-hidden relayout copies — moves any layout copy to the 4x
#     smaller gradient; pure schedule change, parity-tested)
row "mul-dwt-all-levers"     BENCH_BATCH=16 BENCH_HEADS=8 BENCH_AMP_LEVEL=O2 PADDLE_TPU_FLASH_FUSED_BWD=1 PADDLE_TPU_MUL_DWT=1
# 2. flash block shapes on the winner's base
row "heads8-bq1024"          BENCH_BATCH=16 BENCH_HEADS=8 PADDLE_TPU_FLASH_BQ=1024 PADDLE_TPU_FLASH_BK=1024
row "heads8-bq256bk512"      BENCH_BATCH=16 BENCH_HEADS=8 PADDLE_TPU_FLASH_BQ=256 PADDLE_TPU_FLASH_BK=512
# 2b. long-context ladder (r5: 0.6698 / 0.7307 / 0.7447 MFU measured)
row "seq2048-b8"             BENCH_BATCH=8 BENCH_SEQ=2048 BENCH_HEADS=8 BENCH_AMP_LEVEL=O2 PADDLE_TPU_FLASH_FUSED_BWD=1
row "seq4096-b4"             BENCH_BATCH=4 BENCH_SEQ=4096 BENCH_HEADS=8 BENCH_AMP_LEVEL=O2 PADDLE_TPU_FLASH_FUSED_BWD=1
row "seq8192-b2"             BENCH_BATCH=2 BENCH_SEQ=8192 BENCH_HEADS=8 BENCH_AMP_LEVEL=O2 PADDLE_TPU_FLASH_FUSED_BWD=1
# 3. resnet ladder + reader-pipeline proof (row() defaults first, the
#    row's own BENCH_RESNET=1 re-enables the phase)
row "resnet-b128"            BENCH_LM=0 BENCH_RESNET=1 BENCH_RN_BATCH=128
row "resnet-b256"            BENCH_LM=0 BENCH_RESNET=1 BENCH_RN_BATCH=256
row "resnet-nhwc"             BENCH_LM=0 BENCH_RESNET=1 BENCH_RN_LAYOUT=NHWC
row "resnet-reader"          BENCH_LM=0 BENCH_RESNET=1 BENCH_RESNET_INPUT=reader
# 4. resnet profile trace for hlo_stats (untimed; writes /tmp/jaxprof)
PROFILE_MODEL=resnet timeout 2700 python tools/profile_bench.py >>/tmp/window_play.log 2>&1 || true
python tools/hlo_stats.py > /tmp/resnet_hlo_stats.txt 2>&1 || true
# 5. serving bench on device
BENCH_SERVING_PLATFORM=device timeout 2700 python tools/bench_serving.py > /tmp/serving_r5.log 2>&1 || true
# 6. deepfm capture (if the watcher bench didn't already get it)
row "deepfm"                 BENCH_LM=0 BENCH_DEEPFM=1
# 7. LAST and riskiest: the stacked-LSTM compile that killed the relay.
#    Only with WINDOW_LSTM=1 (manual opt-in after everything is banked).
if [ "${WINDOW_LSTM:-0}" = "1" ]; then
  row "stacked-lstm"         BENCH_LM=0 BENCH_LSTM=1
fi
echo "WINDOW PLAY DONE $(date -u)" | tee -a /tmp/window_play.log
