#!/bin/bash
# Round-5 window play: run AFTER the watcher banked its plain bench +
# bthd repro (/tmp/autobench_done exists). Strict priority order; every
# row appends to /tmp/sweep_r5.jsonl; safe to re-run (idempotent rows
# skip via the XLA compile cache). ONE TPU process at a time.
set -u
cd /root/repo
OUT=/tmp/sweep_r5.jsonl

row() {
  local tag="$1"; shift
  echo "=== $tag ($(date -u +%H:%M:%S)) ===" | tee -a /tmp/window_play.log
  local line
  line=$(env BENCH_RESNET=0 BENCH_LSTM=0 BENCH_DEEPFM=0 "$@" \
         python bench.py 2>>/tmp/window_play.log | tail -1)
  echo "$line" | tee -a /tmp/window_play.log
  python - "$tag" "$line" <<'EOF' >> "$OUT"
import json, sys
try: r = json.loads(sys.argv[2])
except Exception: r = None
print(json.dumps({"tag": sys.argv[1], "result": r}))
EOF
}

touch /tmp/tpu_busy
trap 'rm -f /tmp/tpu_busy' EXIT

# 1. headline candidates, most-likely-winner first (BTHD engages via the
#    fixed kernels; smoke re-runs automatically on the new kernel hash)
row "heads8-bthd"            BENCH_BATCH=16 BENCH_HEADS=8
row "heads8-bthd-fusedbwd"   BENCH_BATCH=16 BENCH_HEADS=8 PADDLE_TPU_FLASH_FUSED_BWD=1
row "heads8-bthd-O2"         BENCH_BATCH=16 BENCH_HEADS=8 BENCH_AMP_LEVEL=O2
row "heads8-all-levers"      BENCH_BATCH=16 BENCH_HEADS=8 BENCH_AMP_LEVEL=O2 PADDLE_TPU_FLASH_FUSED_BWD=1
row "b24-remat-all"          BENCH_BATCH=24 BENCH_HEADS=8 BENCH_REMAT=1 BENCH_AMP_LEVEL=O2 PADDLE_TPU_FLASH_FUSED_BWD=1
# 2. flash block shapes on the winner's base
row "heads8-bq1024"          BENCH_BATCH=16 BENCH_HEADS=8 PADDLE_TPU_FLASH_BQ=1024 PADDLE_TPU_FLASH_BK=1024
row "heads8-bq256bk512"      BENCH_BATCH=16 BENCH_HEADS=8 PADDLE_TPU_FLASH_BQ=256 PADDLE_TPU_FLASH_BK=512
# 3. resnet ladder + reader-pipeline proof + profile capture
echo "=== resnet rows ===" | tee -a /tmp/window_play.log
for rb in 128 256; do
  line=$(env BENCH_LM=0 BENCH_LSTM=0 BENCH_DEEPFM=0 BENCH_RN_BATCH=$rb \
         python bench.py 2>>/tmp/window_play.log | tail -1)
  echo "{\"tag\": \"resnet-b$rb\", \"result\": $line}" >> "$OUT" || true
  echo "$line" | tee -a /tmp/window_play.log
done
line=$(env BENCH_LM=0 BENCH_LSTM=0 BENCH_DEEPFM=0 BENCH_RESNET_INPUT=reader \
       python bench.py 2>>/tmp/window_play.log | tail -1)
echo "{\"tag\": \"resnet-reader\", \"result\": $line}" >> "$OUT" || true
echo "$line" | tee -a /tmp/window_play.log
# 4. resnet profile trace for hlo_stats (untimed; writes /tmp/jaxprof)
PROFILE_MODEL=resnet python tools/profile_bench.py >>/tmp/window_play.log 2>&1 || true
python tools/hlo_stats.py > /tmp/resnet_hlo_stats.txt 2>&1 || true
# 5. serving bench on device
BENCH_SERVING_PLATFORM=device python tools/bench_serving.py > /tmp/serving_r5.log 2>&1 || true
# 6. deepfm capture (if the watcher bench didn't already get it)
line=$(env BENCH_LM=0 BENCH_RESNET=0 BENCH_LSTM=0 python bench.py 2>>/tmp/window_play.log | tail -1)
echo "{\"tag\": \"deepfm\", \"result\": $line}" >> "$OUT" || true
# 7. LAST and riskiest: the stacked-LSTM compile that killed the relay.
#    Only run if WINDOW_LSTM=1 (manual opt-in after everything is banked).
if [ "${WINDOW_LSTM:-0}" = "1" ]; then
  line=$(env BENCH_LM=0 BENCH_RESNET=0 BENCH_DEEPFM=0 python bench.py 2>>/tmp/window_play.log | tail -1)
  echo "{\"tag\": \"stacked-lstm\", \"result\": $line}" >> "$OUT" || true
fi
echo "WINDOW PLAY DONE $(date -u)" | tee -a /tmp/window_play.log
