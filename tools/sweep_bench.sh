#!/bin/bash
# On-TPU perf sweep, PRIORITY ORDER: the most informative configs run
# first so a short tunnel window still yields the key numbers. Each row
# prints the bench JSON line and appends it to $OUT (default
# /tmp/sweep_results.txt) tagged with its config.
#
#   bash tools/sweep_bench.sh            # LM sweep
#   RN=1 bash tools/sweep_bench.sh      # append ResNet batch sweep
#
# The persistent XLA compile cache (bench.py, .xla_cache/) makes repeat
# configs fast: only genuinely new HLO recompiles through the tunnel.
set -u
cd "$(dirname "$0")/.."
OUT="${OUT:-/tmp/sweep_results.txt}"

run() {
  echo "=== $* ==="
  # defaults first, "$@" last: a row's own BENCH_* assignments win.
  # BENCH_AMP_LEVEL=O1 + FUSED_BWD=0 pin the historical lever-isolation
  # baseline: bench.py now BAKES the sweep winner (O2 + fused) as its
  # process defaults, which would otherwise silently turn every row
  # below into the same config and zero all the deltas.
  line=$(env BENCH_RESNET=0 BENCH_LSTM=0 BENCH_DEEPFM=0 \
         BENCH_AMP_LEVEL=O1 PADDLE_TPU_FLASH_FUSED_BWD=0 \
         BENCH_PROBE_TIMEOUT=150 "$@" timeout 2400 \
         python bench.py 2>/dev/null | tail -1)
  echo "$line"
  echo "{\"cfg\": \"$*\", \"result\": $(json_or_null "$line")}" >> "$OUT"
}

# keep $OUT valid JSON-lines even when a row dies mid-print
json_or_null() {
  python -c 'import json,sys
try: print(json.dumps(json.loads(sys.argv[1])))
except Exception: print("null")' "${1:-null}"
}

# 0. the baked bench.py defaults (r5 winner: O2 + fused backward)
run BENCH_BATCH=16 BENCH_AMP_LEVEL=O2 PADDLE_TPU_FLASH_FUSED_BWD=1
# 1. confirm the O1 lever-isolation baseline + prime the compile cache
run BENCH_BATCH=16
# 2. same config with a profiler trace (cached compile; /tmp/jaxprof)
run BENCH_BATCH=16 BENCH_PROFILE=1
# 3. the r2 reference point
run BENCH_BATCH=8
# 4. fused QKV projection (one (D,3D) matmul instead of three)
run BENCH_BATCH=16 PADDLE_TPU_FUSED_QKV=1
# 5. flash-attention block shapes
run BENCH_BATCH=16 PADDLE_TPU_FLASH_BQ=1024 PADDLE_TPU_FLASH_BK=1024
run BENCH_BATCH=16 PADDLE_TPU_FLASH_BQ=256 PADDLE_TPU_FLASH_BK=512
run BENCH_BATCH=16 PADDLE_TPU_FLASH_BQ=512 PADDLE_TPU_FLASH_BK=256
# 6. fused LM-head vocab chunk
run BENCH_BATCH=16 PADDLE_TPU_LMHEAD_BLOCK=4096
run BENCH_BATCH=16 PADDLE_TPU_LMHEAD_BLOCK=8192
# 6b. unrolled LM-head chunk loop / wider heads (d_head 128 on the MXU)
run BENCH_BATCH=16 PADDLE_TPU_LMHEAD_UNROLL=16
run BENCH_BATCH=16 BENCH_HEADS=8
# 6c. d_head 128 activates the transpose-free BTHD pallas layout by
# default; the =0 row isolates the layout's own contribution
run BENCH_BATCH=16 BENCH_HEADS=8 PADDLE_TPU_ATTN_BTHD=0
run BENCH_BATCH=16 BENCH_HEADS=8 PADDLE_TPU_FLASH_BQ=1024 PADDLE_TPU_FLASH_BK=1024
run BENCH_BATCH=24 BENCH_HEADS=8 BENCH_REMAT=1
# 6c2. tied embed/head table: one less (V,D) param — halves Adam f32
# moment traffic + grad convert chains on the two largest tensors
run BENCH_BATCH=16 BENCH_HEADS=8 BENCH_AMP_LEVEL=O2 PADDLE_TPU_FLASH_FUSED_BWD=1 BENCH_TIE=1
# 6c3. transposed-form dW backward (targets the FFN-hidden relayout copies)
run BENCH_BATCH=16 BENCH_HEADS=8 BENCH_AMP_LEVEL=O2 PADDLE_TPU_FLASH_FUSED_BWD=1 PADDLE_TPU_MUL_DWT=1
# 6d. AMP O2: bf16 residual stream (elementwise path joins the bf16 set)
run BENCH_BATCH=16 BENCH_AMP_LEVEL=O2
run BENCH_BATCH=16 BENCH_HEADS=8 BENCH_AMP_LEVEL=O2
# 6e. single-pass fused flash backward (5 matmuls/tile instead of 7,
# one input read instead of two)
run BENCH_BATCH=16 PADDLE_TPU_FLASH_FUSED_BWD=1
run BENCH_BATCH=16 BENCH_HEADS=8 PADDLE_TPU_FLASH_FUSED_BWD=1
# 6f. the plausible global optimum: all levers at once
run BENCH_BATCH=24 BENCH_HEADS=8 BENCH_AMP_LEVEL=O2 BENCH_REMAT=1 PADDLE_TPU_FLASH_FUSED_BWD=1
# 7. bigger per-chip batches (straight, then rematerialized backward)
run BENCH_BATCH=24
run BENCH_BATCH=24 BENCH_REMAT=1
run BENCH_BATCH=32 BENCH_REMAT=1

# secondary-workload rows (VERDICT r4 item 3): the scan-heavy RNN and the
# embedding-bound CTR paths, each measured without the LM compile
if [ "${AUX:-1}" = "1" ]; then
  # each row measures ONE secondary phase (run()'s defaults turn the
  # others off; BENCH_LM=0 skips the LM compile)
  run BENCH_LM=0 BENCH_LSTM=1
  run BENCH_LM=0 BENCH_LSTM=1 BENCH_LSTM_BATCH=64
  run BENCH_LM=0 BENCH_LSTM=1 BENCH_AMP=0
  run BENCH_LM=0 BENCH_DEEPFM=1
  run BENCH_LM=0 BENCH_DEEPFM=1 BENCH_DFM_BATCH=4096
  run BENCH_LM=0 BENCH_DEEPFM=1 BENCH_AMP=0
fi

if [ "${RN:-0}" = "1" ]; then
  rn_row() {  # resnet-focused row: tiny LM, secondary phases off
    local tag="$1"; shift
    echo "=== $tag ==="
    line=$(env BENCH_LSTM=0 BENCH_DEEPFM=0 BENCH_PROBE_TIMEOUT=150 \
        BENCH_STEPS=3 BENCH_WARMUP=1 BENCH_LAYERS=1 "$@" timeout 2400 \
        python bench.py 2>/dev/null | tail -1)
    echo "$line"
    echo "{\"cfg\": \"$tag\", \"result\": $(json_or_null "$line")}" >> "$OUT"
  }
  for rb in 128 256 64; do
    rn_row "resnet rb=$rb" BENCH_RN_BATCH=$rb
  done
  # input-pipeline proof (VERDICT r3 item 8): the same step fed through
  # recordio -> C++ reader -> reader ops -> run_loop windows; the row's
  # resnet50.reader object records step_ms vs synthetic + overhead pct
  rn_row "resnet reader" BENCH_RESNET_INPUT=reader
fi
