#!/bin/bash
# On-TPU perf sweep: run after the device is reachable. Each line prints
# the bench JSON for one configuration; compare mfu/step_ms across rows.
#
#   bash tools/sweep_bench.sh            # LM sweep (batch x flash blocks)
#   RN=1 bash tools/sweep_bench.sh      # include ResNet batch sweep
set -u
cd "$(dirname "$0")/.."

run() {
  echo "=== $* ==="
  env "$@" BENCH_RESNET=0 BENCH_PROBE_TIMEOUT=120 timeout 900 python bench.py 2>/dev/null | tail -1
}

# batch sweep at default blocks
run BENCH_BATCH=8
run BENCH_BATCH=16
run BENCH_BATCH=24

# flash-attention block sweep at the best-looking batch (edit as needed)
for bq in 256 512 1024; do
  for bk in 256 512 1024; do
    run BENCH_BATCH=16 PADDLE_TPU_FLASH_BQ=$bq PADDLE_TPU_FLASH_BK=$bk
  done
done

# fused LM-head vocab chunk sweep
for bv in 2048 4096 8192; do
  run BENCH_BATCH=16 PADDLE_TPU_LMHEAD_BLOCK=$bv
done

# fused QKV projection (one (D,3D) matmul instead of three)
run BENCH_BATCH=8 PADDLE_TPU_FUSED_QKV=1
run BENCH_BATCH=16 PADDLE_TPU_FUSED_QKV=1

# bigger per-chip batches with rematerialized backward (activation HBM
# freed; MXU utilization usually rises until HBM bandwidth saturates)
run BENCH_BATCH=24 BENCH_REMAT=1
run BENCH_BATCH=32 BENCH_REMAT=1

if [ "${RN:-0}" = "1" ]; then
  for rb in 64 128 256; do
    echo "=== resnet batch $rb ==="
    env BENCH_RN_BATCH=$rb BENCH_PROBE_TIMEOUT=120 BENCH_STEPS=3 \
        BENCH_WARMUP=1 BENCH_LAYERS=1 timeout 900 python bench.py \
        2>/dev/null | tail -1
  done
fi
