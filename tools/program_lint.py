"""Program linter CLI: run the static analyzer over Program IR.

Targets (mix freely):

- a serialized program: a ``__model__`` JSON written by
  ``save_inference_model`` (feed/fetch metadata is used), a raw
  ``Program.to_dict()`` JSON, or a model DIRECTORY containing
  ``__model__``;
- an example SCRIPT (``--script build.py``): executed with fresh default
  programs, then the resulting default main program is linted (set
  ``LINT_FEEDS``/``LINT_FETCHES`` globals in the script to pass feed and
  fetch names);
- the bundled example models (``--example mlp|deepfm|lstm|all``) — the
  same graphs the benchmarks run, kept lint-clean by CI's
  ``lint-programs`` step.

Output: human-readable diagnostics (default) or ``--json`` (one document
covering all targets, including per-program infer coverage). Exit code 1
when any error-severity finding exists (``--strict``: warnings fail too).

Usage:
    JAX_PLATFORMS=cpu python tools/program_lint.py --example all
    python tools/program_lint.py path/to/__model__ --json
    python tools/program_lint.py --script examples/build_graph.py
"""
from __future__ import annotations

import argparse
import json
import os
import sys

# CPU by default: linting is host-side graph analysis, it must run in CI
# and on laptops with no accelerator attached
os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

# a sitecustomize-installed PJRT plugin can override JAX_PLATFORMS at
# import time (see tests/conftest.py) — pin the platform after import too
jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])


# -- bundled example programs ---------------------------------------------

def _build_mlp():
    import paddle_tpu as fluid
    from paddle_tpu import layers, optimizer

    img = layers.data(name="pixel", shape=[784], dtype="float32")
    label = layers.data(name="label", shape=[1], dtype="int64")
    from paddle_tpu.models.mnist import mlp_model

    predict = mlp_model(img)
    cost = layers.cross_entropy(input=predict, label=label)
    avg_cost = layers.mean(cost)
    acc = layers.accuracy(input=predict, label=label)
    optimizer.SGD(learning_rate=0.01).minimize(avg_cost)
    return ["pixel", "label"], [avg_cost.name, acc.name]


def _build_deepfm():
    from paddle_tpu import layers, optimizer
    from paddle_tpu.models.deepfm import deepfm_net

    feat_ids = layers.data(name="feat_ids", shape=[10], dtype="int64")
    dense = layers.data(name="dense", shape=[13], dtype="float32")
    label = layers.data(name="label", shape=[1], dtype="int64")
    avg_cost, prob = deepfm_net(feat_ids, dense, label,
                                num_features=1000, num_fields=10)
    optimizer.Adam(learning_rate=1e-3).minimize(avg_cost)
    return ["feat_ids", "dense", "label"], [avg_cost.name, prob.name]


def _build_lstm():
    from paddle_tpu import layers, optimizer
    from paddle_tpu.models.stacked_lstm import stacked_lstm_net

    words = layers.data(name="words", shape=[80], dtype="int64")
    lengths = layers.data(name="lengths", shape=[], dtype="int32")
    label = layers.data(name="label", shape=[1], dtype="int64")
    predict = stacked_lstm_net(words, lengths, dict_dim=3000,
                               emb_dim=64, hid_dim=64, stacked_num=2)
    cost = layers.cross_entropy(input=predict, label=label)
    avg_cost = layers.mean(cost)
    optimizer.Adam(learning_rate=1e-3).minimize(avg_cost)
    return [words.name, lengths.name, label.name], [avg_cost.name]


def _build_decode():
    """The per-token KV-cache decode step (serving/decode.py): the graph
    the DecodeServer compiles once per (slots, slab) signature —
    decode_attention / cache_append / sampling ops stay lint-clean and
    infer-covered."""
    from paddle_tpu import layers
    from paddle_tpu.models.transformer import transformer_lm_decode

    B, S, V, L, NH, D, DI, ML = 4, 64, 256, 2, 4, 64, 128, 128
    tokens = layers.data(name="tokens", shape=[B, 1], dtype="int64",
                         append_batch_size=False)
    positions = layers.data(name="positions", shape=[B, 1], dtype="int64",
                            append_batch_size=False)
    lengths = layers.data(name="lengths", shape=[B], dtype="int32",
                          append_batch_size=False)
    seed = layers.data(name="seed", shape=[1], dtype="int64",
                       append_batch_size=False)
    kc, vc = [], []
    for i in range(L):
        kc.append(layers.data(name="kcache_%d" % i,
                              shape=[B, S, NH, D // NH], dtype="float32",
                              append_batch_size=False))
        vc.append(layers.data(name="vcache_%d" % i,
                              shape=[B, S, NH, D // NH], dtype="float32",
                              append_batch_size=False))
    next_ids, logits, ncaches = transformer_lm_decode(
        tokens, positions, lengths, kc, vc, V, n_layer=L, n_head=NH,
        d_model=D, d_inner=DI, max_len=ML, strategy="topk", seed=seed)
    feeds = (["tokens", "positions", "lengths", "seed"]
             + [v.name for v in kc] + [v.name for v in vc])
    fetches = ([next_ids.name, logits.name]
               + [c.name for pair in ncaches for c in pair])
    return feeds, fetches


def _build_speculative():
    """The speculative VERIFY window (serving/decode.py kind="verify"):
    the graph that checks spec_k draft proposals in one call —
    cache_append_window / decode_attention_window / spec_accept stay
    lint-clean and infer-covered in CI (PR 14)."""
    from paddle_tpu import layers
    from paddle_tpu.models.transformer import transformer_lm_verify

    B, T, S, V, L, NH, D, DI, ML = 4, 3, 64, 256, 2, 4, 64, 128, 128
    tokens = layers.data(name="tokens", shape=[B, T], dtype="int64",
                         append_batch_size=False)
    positions = layers.data(name="positions", shape=[B, T], dtype="int64",
                            append_batch_size=False)
    lengths = layers.data(name="lengths", shape=[B], dtype="int32",
                          append_batch_size=False)
    last_idx = layers.data(name="last_idx", shape=[B], dtype="int32",
                           append_batch_size=False)
    kc, vc = [], []
    for i in range(L):
        kc.append(layers.data(name="kcache_%d" % i,
                              shape=[B, S, NH, D // NH], dtype="float32",
                              append_batch_size=False))
        vc.append(layers.data(name="vcache_%d" % i,
                              shape=[B, S, NH, D // NH], dtype="float32",
                              append_batch_size=False))
    next_ids, accept, last_logits, ncaches = transformer_lm_verify(
        tokens, positions, lengths, last_idx, kc, vc, V, n_layer=L,
        n_head=NH, d_model=D, d_inner=DI, max_len=ML)
    feeds = (["tokens", "positions", "lengths", "last_idx"]
             + [v.name for v in kc] + [v.name for v in vc])
    fetches = ([next_ids.name, accept.name, last_logits.name]
               + [c.name for pair in ncaches for c in pair])
    return feeds, fetches


def _build_quant():
    """The int8 post-training-quantized serving graph (paddle_tpu/quant/
    + transpiler/passes/quantize.py): an fc stack initialized, run
    through the level-3 quantize pass with a synthetic calibration
    table (unit amax per activation — linting needs ranges to exist,
    not to be accurate), returned as the QUANTIZED program — so
    quantized_matmul stays lint-clean and infer-covered in CI.

    Unlike the other builders this returns the (program, feeds,
    fetches) triple directly: the quantized program is a transformed
    clone, not what program_guard accumulated."""
    import paddle_tpu as fluid
    from paddle_tpu import layers
    from paddle_tpu.quant import CalibrationTable, activation_targets
    from paddle_tpu.transpiler.passes import optimize_program

    scope = fluid.Scope()
    main, startup = fluid.Program(), fluid.Program()
    with fluid.scope_guard(scope), fluid.program_guard(main, startup):
        with fluid.unique_name.guard():
            img = layers.data(name="pixel", shape=[784], dtype="float32")
            from paddle_tpu.models.mnist import mlp_model

            predict = mlp_model(img)
        exe = fluid.Executor()
        exe.run(startup)
    infer = main.clone(for_test=True)
    calib = CalibrationTable(
        activations={n: 1.0 for n in activation_targets(infer)},
        batches=1)
    quantized, _ctx = optimize_program(
        infer, scope=scope, level=3, feed_names=["pixel"],
        fetch_names=[predict.name], calib=calib)
    assert getattr(quantized, "_quantized", None), \
        "quant example failed to quantize any op"
    return quantized, ["pixel"], [predict.name]


EXAMPLES = {"mlp": _build_mlp, "deepfm": _build_deepfm, "lstm": _build_lstm,
            "decode": _build_decode, "speculative": _build_speculative}
# builders that return the (program, feeds, fetches) triple themselves
# (transformed clones rather than ambient default-program graphs)
PROGRAM_EXAMPLES = {"quant": _build_quant}
ALL_EXAMPLES = sorted(set(EXAMPLES) | set(PROGRAM_EXAMPLES))


def build_example(name: str):
    """Build one bundled example graph in fresh default programs; returns
    (program, feed_names, fetch_names)."""
    import paddle_tpu as fluid

    if name in PROGRAM_EXAMPLES:
        return PROGRAM_EXAMPLES[name]()
    prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(prog, startup):
        feeds, fetches = EXAMPLES[name]()
    return prog, feeds, fetches


# -- serialized / script targets ------------------------------------------

def load_target(path: str):
    """(program, feed_names, fetch_names, label) from a path."""
    from paddle_tpu.framework.core import Program

    label = path
    if os.path.isdir(path):
        path = os.path.join(path, "__model__")
    with open(path) as f:
        doc = json.load(f)
    if "program" in doc:  # save_inference_model layout
        return (Program.from_dict(doc["program"]),
                list(doc.get("feed_names", [])),
                list(doc.get("fetch_names", [])), label)
    return Program.from_dict(doc), [], [], label


def run_script(path: str):
    """Execute a graph-building script under fresh default programs and
    lint what it built. The script may set LINT_FEEDS / LINT_FETCHES
    (lists of names); otherwise data vars count as feeds and no fetch
    roots are assumed (persistable writes keep training ops live)."""
    import paddle_tpu as fluid

    prog, startup = fluid.Program(), fluid.Program()
    glb = {"__name__": "__lint__", "__file__": path}
    with fluid.program_guard(prog, startup):
        with open(path) as f:
            code = compile(f.read(), path, "exec")
        exec(code, glb)  # noqa: S102 — explicit, user-invoked
    feeds = list(glb.get("LINT_FEEDS")
                 or [n for b in prog.blocks for n, v in b.vars.items()
                     if v.is_data])
    fetches = list(glb.get("LINT_FETCHES") or [])
    return prog, feeds, fetches, path


# -- driver ---------------------------------------------------------------

def lint_one(program, feeds, fetches, label, min_severity, as_json):
    from paddle_tpu.analysis import analyze_program

    analysis = analyze_program(program, feed_names=feeds,
                               fetch_names=fetches)
    rep = analysis.report
    if as_json:
        doc = rep.to_dict()
        doc["name"] = label
        return doc, rep
    print("== %s: %d ops, infer coverage %d/%d (%.0f%%)"
          % (label, rep.total_ops, rep.covered_ops, rep.total_ops,
             100.0 * rep.coverage))
    print(rep.render(min_severity))
    return None, rep


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Static lint for paddle_tpu Programs "
                    "(shape/dtype inference + TPU lints)")
    ap.add_argument("paths", nargs="*",
                    help="serialized program JSON / model dir")
    ap.add_argument("--example", action="append", default=[],
                    choices=ALL_EXAMPLES + ["all"],
                    help="lint a bundled example program (repeatable)")
    ap.add_argument("--script", action="append", default=[],
                    help="a graph-building python script to execute+lint")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable output")
    ap.add_argument("--optimize", type=int, default=0, metavar="LEVEL",
                    help="additionally lint each target AFTER the "
                         "optimizing transpiler at LEVEL (1|2) — the "
                         "pass manager must keep programs lint-clean "
                         "and fully infer-covered")
    ap.add_argument("--strict", action="store_true",
                    help="exit nonzero on warnings too")
    ap.add_argument("--min-severity", default="info",
                    choices=["note", "info", "warning", "error"],
                    help="floor for the human-readable listing")
    args = ap.parse_args(argv)

    targets = []
    examples = ALL_EXAMPLES if "all" in args.example else args.example
    for name in examples:
        targets.append(("example:" + name,
                        lambda n=name: build_example(n) + ("example:" + n,)))
    for path in args.paths:
        targets.append((path, lambda p=path: load_target(p)))
    for path in args.script:
        targets.append((path, lambda p=path: run_script(p)))
    if not targets:
        ap.error("nothing to lint: give paths, --example or --script")

    json_docs = []
    failed = False
    for label, thunk in targets:
        try:
            program, feeds, fetches, label = thunk()
        except Exception as e:
            failed = True
            if args.as_json:
                json_docs.append({"name": label, "load_error": str(e)})
            else:
                print("== %s: FAILED to load/build: %s" % (label, e))
            continue
        variants = [(label, program)]
        if args.optimize:
            from paddle_tpu.framework.scope import Scope
            from paddle_tpu.transpiler.passes import optimize_program

            try:
                opt, _ctx = optimize_program(
                    program, scope=Scope(), level=args.optimize,
                    feed_names=feeds, fetch_names=fetches)
                variants.append(
                    ("%s+O%d" % (label, args.optimize), opt))
            except Exception as e:
                failed = True
                if args.as_json:
                    json_docs.append({"name": label + "+opt",
                                      "load_error": str(e)})
                else:
                    print("== %s: FAILED to optimize: %s" % (label, e))
        for vlabel, vprogram in variants:
            doc, rep = lint_one(vprogram, feeds, fetches, vlabel,
                                args.min_severity, args.as_json)
            if doc is not None:
                json_docs.append(doc)
            if rep.errors or (args.strict and rep.warnings):
                failed = True
    if args.as_json:
        print(json.dumps({"programs": json_docs}, indent=2))
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
