"""Unified model-zoo benchmark CLI (reference:
benchmark/fluid/fluid_benchmark.py — one harness running any model with
--model/--batch_size/--iterations/--device).

Usage:
    python tools/benchmark.py --model resnet50 --batch-size 64 --iters 10
    JAX_PLATFORMS=cpu python tools/benchmark.py --model mnist --cpu

Prints one JSON line per run: {model, batch, examples_per_sec, step_ms,
loss}. For the headline LM/ResNet numbers with MFU accounting use
bench.py; this harness is for breadth across the zoo.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def _imagenet_feed(r, b, size=224, classes=1000, img="data"):
    return {img: r.randn(b, 3, size, size).astype(np.float32),
            "label": r.randint(0, classes, (b, 1)).astype(np.int64)}


# model -> (build(batch) -> (avg_cost, feeds), make_feed(rng, batch))
def _registry():
    from paddle_tpu import models

    return {
        "mnist": (
            lambda b: models.mnist.get_model()[0],
            lambda r, b: {"pixel": r.randn(b, 1, 28, 28).astype(np.float32),
                          "label": r.randint(0, 10, (b, 1)).astype(np.int64)}),
        "resnet50": (
            lambda b: models.resnet.get_model(dataset="imagenet",
                                              depth=50)[0],
            _imagenet_feed),
        "vgg16": (
            lambda b: models.vgg.get_model()[0],
            _imagenet_feed),
        "mobilenet": (
            lambda b: models.mobilenet.get_model()[0],
            lambda r, b: _imagenet_feed(r, b, img="image")),
        "se_resnext": (
            lambda b: models.se_resnext.get_model(batch_size=b)[0],
            _imagenet_feed),
        "stacked_lstm": (
            lambda b: models.stacked_lstm.get_model(dict_dim=10000,
                                                    seq_len=80)[0],
            lambda r, b: {
                "words": r.randint(0, 10000, (b, 80)).astype(np.int64),
                "lengths": r.randint(8, 81, b).astype(np.int32),
                "label": r.randint(0, 2, (b, 1)).astype(np.int64)}),
        "transformer_lm": (
            lambda b: _lm(b),
            lambda r, b: {
                "ids": r.randint(0, 8192, (b, 256)).astype(np.int64),
                "labels": r.randint(0, 8192, (b, 256)).astype(np.int64)}),
        "seq2seq": (
            lambda b: models.seq2seq.get_model(dict_size=8000)[0],
            lambda r, b: {
                "src_word_id": r.randint(2, 8000, (b, 16)).astype(np.int64),
                "src_len": np.full(b, 16, np.int32),
                "target_language_word": r.randint(2, 8000, (b, 16)).astype(np.int64),
                "trg_len": np.full(b, 16, np.int32),
                "target_language_next_word": r.randint(2, 8000, (b, 16)).astype(np.int64)}),
        "deepfm": (
            lambda b: models.deepfm.get_model()[0],
            lambda r, b: {
                "feat_ids": r.randint(0, 1000, (b, 10)).astype(np.int64),
                "dense": r.randn(b, 13).astype(np.float32),
                "label": r.randint(0, 2, (b, 1)).astype(np.int64)}),
    }


def _lm(b):
    from paddle_tpu import layers, models

    ids = layers.data(name="ids", shape=[b, 256], dtype="int64",
                      append_batch_size=False)
    lbl = layers.data(name="labels", shape=[b, 256], dtype="int64",
                      append_batch_size=False)
    loss, _ = models.transformer.transformer_lm(
        ids, lbl, vocab_size=8192, n_layer=4, n_head=8, d_model=256,
        d_inner=1024, max_len=256)
    return loss


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", required=True)
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--iters", type=int, default=10)
    ap.add_argument("--warmup", type=int, default=2)
    ap.add_argument("--cpu", action="store_true")
    ap.add_argument("--amp", action="store_true")
    ap.add_argument("--loop", action="store_true",
                    help="time a device-side run_loop window (one "
                         "dispatch/fetch total) instead of per-step runs")
    args = ap.parse_args()

    if args.cpu:
        # a sitecustomize PJRT plugin (axon tunnel) may override
        # JAX_PLATFORMS at import time; the config update after import is
        # the reliable way to force the cpu backend (see tests/conftest.py)
        import jax

        jax.config.update("jax_platforms", "cpu")

    import paddle_tpu as fluid
    from paddle_tpu import optimizer

    registry = _registry()
    if args.model not in registry:
        raise SystemExit("unknown model %r; choose from %s"
                         % (args.model, ", ".join(sorted(registry))))
    build, make_feed = registry[args.model]

    prog, startup = fluid.Program(), fluid.Program()
    prog.random_seed = startup.random_seed = 1
    with fluid.program_guard(prog, startup):
        with fluid.unique_name.guard():
            avg_cost = build(args.batch_size)
            optimizer.Adam(learning_rate=1e-4).minimize(avg_cost)
        if args.amp:
            prog.enable_mixed_precision()

    exe = fluid.Executor(fluid.CPUPlace() if args.cpu else fluid.TPUPlace())
    scope = fluid.Scope()
    r = np.random.RandomState(0)
    feed = make_feed(r, args.batch_size)
    with fluid.scope_guard(scope):
        exe.run(startup)
        if args.loop:
            # device-side window: one dispatch + one fetch per call (the
            # numpy return is the sync), robust to host/tunnel latency
            exe.run_loop(prog, feed=feed, fetch_list=[avg_cost],
                         steps=max(1, args.warmup))
            t0 = time.perf_counter()
            out = exe.run_loop(prog, feed=feed, fetch_list=[avg_cost],
                               steps=args.iters)
            dt = (time.perf_counter() - t0) / args.iters
        else:
            exe.run(prog, feed=feed, fetch_list=[])
            # always warm the [avg_cost] fetch variant too (it is its own
            # compile-cache entry) so --warmup 0 cannot push a compile
            # into the timed window
            for _ in range(max(1, args.warmup)):
                exe.run(prog, feed=feed, fetch_list=[avg_cost])
            t0 = time.perf_counter()
            for _ in range(args.iters - 1):
                exe.run(prog, feed=feed, fetch_list=[])
            out = exe.run(prog, feed=feed, fetch_list=[avg_cost])
            dt = (time.perf_counter() - t0) / args.iters

    print(json.dumps({
        "model": args.model,
        "batch": args.batch_size,
        "examples_per_sec": round(args.batch_size / dt, 2),
        "step_ms": round(dt * 1e3, 2),
        "loss": float(np.asarray(out[0]).reshape(-1)[0]),
    }))


if __name__ == "__main__":
    main()
