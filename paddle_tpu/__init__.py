"""paddle_tpu — a TPU-native deep-learning framework with the capabilities
of PaddlePaddle Fluid (reference: codeWorm2015/Paddle @ /root/reference).

Declarative Program/Block/Op IR + layers API like `paddle.fluid`, but the
execution engine lowers whole programs to single jitted XLA computations
(MXU-shaped kernels, lax control flow, pjit/shard_map distribution) instead
of per-op CUDA kernel dispatch.

Typical use — identical in shape to fluid:

    import paddle_tpu as fluid
    x = fluid.layers.data(name="x", shape=[784])
    y = fluid.layers.data(name="y", shape=[1], dtype="int64")
    h = fluid.layers.fc(x, 128, act="relu")
    logits = fluid.layers.fc(h, 10)
    loss = fluid.layers.mean(
        fluid.layers.softmax_with_cross_entropy(logits, y))
    fluid.optimizer.Adam(1e-3).minimize(loss)
    exe = fluid.Executor(fluid.TPUPlace(0))
    exe.run(fluid.default_startup_program())
    exe.run(feed={"x": xs, "y": ys}, fetch_list=[loss])
"""

# kernels register themselves on import
from .ops import math as _k_math  # noqa: F401
from .ops import nn as _k_nn  # noqa: F401
from .ops import rnn as _k_rnn  # noqa: F401
from .ops import optim as _k_optim  # noqa: F401
from .ops import sequence as _k_sequence  # noqa: F401
from .ops import metric as _k_metric  # noqa: F401
from .ops import control_flow as _k_control_flow  # noqa: F401
from .ops import decode as _k_decode  # noqa: F401
from .ops import attention as _k_attention  # noqa: F401
from .ops import fused_loss as _k_fused_loss  # noqa: F401
from .ops import kv_cache as _k_kv_cache  # noqa: F401
from .ops import sampling as _k_sampling  # noqa: F401
from .ops import speculative as _k_speculative  # noqa: F401
from .ops import quant as _k_quant  # noqa: F401
from .ops import detection as _k_detection  # noqa: F401

from .framework import (  # noqa: F401
    Block,
    CPUPlace,
    CUDAPlace,
    Operator,
    Parameter,
    Program,
    Scope,
    TPUPlace,
    Variable,
    default_main_program,
    default_startup_program,
    global_scope,
    grad_var_name,
    name_scope,
    program_guard,
    scope_guard,
    switch_main_program,
    switch_startup_program,
    unique_name,
)
from .executor import Executor  # noqa: F401
from .io.reader import EOFException  # noqa: F401  (reference: core.EOFException)
from .io.dataloader import DataLoader  # noqa: F401  (multiprocess input fast path)
from .backward import append_backward  # noqa: F401
from . import layers  # noqa: F401
from . import nets  # noqa: F401
from . import initializer  # noqa: F401
from . import optimizer  # noqa: F401
from . import regularizer  # noqa: F401
from . import clip  # noqa: F401
from .param_attr import ParamAttr, WeightNormParamAttr  # noqa: F401
from .layer_helper import LayerHelper  # noqa: F401
from . import io  # noqa: F401
from . import reader  # noqa: F401
from .reader import batch  # noqa: F401  (reference: paddle.batch)
from .data_feeder import DataFeeder  # noqa: F401
from . import dataset  # noqa: F401
from . import parallel  # noqa: F401
from .parallel import ParallelExecutor, ExecutionStrategy, BuildStrategy  # noqa: F401
from . import transpiler  # noqa: F401
from .transpiler import (  # noqa: F401
    DistributeTranspiler,
    DistributeTranspilerConfig,
    InferenceTranspiler,
    memory_optimize,
    optimize_program,
    release_memory,
)

from . import metrics  # noqa: F401
from . import evaluator  # noqa: F401
from . import profiler  # noqa: F401
from . import debugger  # noqa: F401
from .framework.verifier import verify_program, ProgramVerifyError  # noqa: F401
from . import analysis  # noqa: F401
from .analysis import analyze_program, AnalysisError  # noqa: F401
from .ops.registry import op_support_tpu, registered_ops, OpProtoHolder  # noqa: F401
from .trainer import (  # noqa: F401
    BeginEpochEvent,
    BeginStepEvent,
    CheckpointConfig,
    EndEpochEvent,
    EndStepEvent,
    Inferencer,
    Trainer,
)
from . import checkpoint  # noqa: F401  (elastic training subsystem)
from .checkpoint import (  # noqa: F401
    CheckpointManager,
    ResumableLoop,
)
from . import quant  # noqa: F401  (int8 post-training quantization tier)

from . import inference  # noqa: F401
from . import lod_tensor  # noqa: F401
from .lod_tensor import create_lod_tensor, create_random_int_lodtensor  # noqa: F401

from . import annotations  # noqa: F401
from . import average  # noqa: F401
from . import core  # noqa: F401  (fluid.core compat shim)
from . import inferencer  # noqa: F401
from . import parallel_executor  # noqa: F401
from .framework.scope import CUDAPinnedPlace  # noqa: F401  (pinned host mem -> plain host mem on TPU)
from .lod_tensor import SequenceTensor as LoDTensor  # noqa: F401  (dense+lengths stand-in)
from .layers import learning_rate_scheduler as learning_rate_decay  # noqa: F401
from . import concurrency  # noqa: F401
from .concurrency import (  # noqa: F401
    Go,
    Select,
    channel_close,
    channel_recv,
    channel_send,
    make_channel,
)
from . import contrib  # noqa: F401
from . import default_scope_funcs  # noqa: F401
from . import graphviz  # noqa: F401
from . import net_drawer  # noqa: F401
from . import op  # noqa: F401
from . import recordio_writer  # noqa: F401
from .runtime.recordio import recordio_convert, recordio_sample_reader  # noqa: F401

# operator sugar on Variable (x + y, x * 0.5, ...) — reference
# layers/math_op_patch.py applies this at fluid import time too
from .framework.math_op_patch import monkey_patch_variable as _mpv

_mpv()

__version__ = "0.1.0"
