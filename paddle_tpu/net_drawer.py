"""Draw a Program as a Graphviz network (reference:
python/paddle/fluid/net_drawer.py).

Walks our Program IR directly (the reference round-trips through the
ProgramDesc protobuf); ops become styled nodes and each def->use of a
variable becomes an edge labeled ``slot(var_name)``. Only block 0 is
plotted, like the reference. See also ``debugger.draw_block_graphviz`` for
the var-and-op bipartite rendering.
"""
from __future__ import annotations

import itertools

from .graphviz import Digraph

__all__ = ["draw_graph"]

OP_STYLE = {
    "shape": "oval",
    "color": "#0F9D58",
    "style": "filled",
    "fontcolor": "#FFFFFF",
}

VAR_STYLE = {}

GRAPH_STYLE = {"rankdir": "TB"}

_graph_ids = itertools.count()


def _parse_graph(program, graph, var_dict, counter):
    """Add block-0 ops of `program` to `graph`; `var_dict` maps a variable
    name to the node name of the op that (last) wrote it."""
    block = program.global_block()
    for name in block.vars:
        var_dict.setdefault(name, "Feed")
    for op in block.ops:
        node_name = "%s_%d" % (op.type, next(counter))
        graph.node(name=node_name, label=op.type)
        for slot, args in op.inputs.items():
            for arg in args:
                name = arg if isinstance(arg, str) else arg.name
                if name in var_dict:
                    graph.edge(var_dict[name], node_name,
                               label="%s(%s)" % (slot, name))
        for slot, args in op.outputs.items():
            for arg in args:
                var_dict[arg if isinstance(arg, str) else arg.name] = node_name


def draw_graph(startup_program, main_program, **kwargs):
    """Render startup+main programs into one digraph; writes `filename`
    (default `<id>.gv`) and returns the Digraph."""
    graph_style = dict(GRAPH_STYLE, **kwargs.pop("graph_attr", {}))
    op_style = dict(OP_STYLE, **kwargs.pop("node_attr", {}))
    var_style = dict(VAR_STYLE, **kwargs.pop("edge_attr", {}))

    graph_id = next(_graph_ids)
    filename = kwargs.pop("filename", None) or str(graph_id) + ".gv"
    g = Digraph(name=str(graph_id), filename=filename,
                graph_attr=graph_style, node_attr=op_style,
                edge_attr=var_style)

    var_dict = {}
    counter = itertools.count()
    _parse_graph(startup_program, g, var_dict, counter)
    _parse_graph(main_program, g, var_dict, counter)
    g.save()
    return g
