"""Engine: the compile/execute core shared by Executor and Predictor.

Before this module, the training ``Executor`` and the serving
``inference.Predictor`` each carried a private copy of the same three
things: a per-program-version feed-conversion plan (declared-variable
lookup + dtype coercion), the AOT disk-cache KEY derivation (what makes
a cached executable reachable), and the load-or-compile acquisition
path (disk hit -> deserialize, miss -> lower + XLA compile + store,
with the hit/miss/latency accounting). Divergence between the copies is
exactly how stale-cache bugs are born — a key field added on one side
but not the other silently serves the wrong executable or recompiles
forever.

``Engine`` owns those three things for ONE program:

- identity: the program, its content fingerprint (cached per version),
  and the environment fingerprint that completes every cache key;
- the AOT-cache handle (``runtime.aot_cache.AotDiskCache``);
- the feed plan: ``feed_var(name)`` (memoized per program version) and
  ``feed_plan(names)`` — the ``(name, declared var, numpy dtype)``
  triples the serving hot path converts feeds with;
- ``key(kind, feed_sig, fetch_names, *extra)`` — the ONE key-derivation
  function (field order is shared by training and serving, so the
  on-disk key space is identical to what PR 5 wrote);
- ``acquire(kind, key, lower, meta=...)`` — the ONE
  disk-load-or-compile path with the cold/warm metrics contract.

``Executor`` holds one Engine per program (weak-keyed);
``inference.Predictor`` and ``serving.sharded.ShardedPredictor`` hold
one for their loaded model — and a fleet replica is just an Engine (via
its Predictor) plus a channel loop (``serving.worker``).
"""
from __future__ import annotations

import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .. import observability as obs
from ..runtime import aot_cache as _aot

__all__ = ["Engine"]


class Engine:
    """Compile/execute core for one Program. Cheap to construct: no I/O
    and no trace until used; the feed plan materializes lazily per
    program version."""

    def __init__(self, program, disk: Optional[_aot.AotDiskCache] = None,
                 feed_names: Optional[Sequence[str]] = None,
                 fetch_names: Optional[Sequence[str]] = None):
        self.program = program
        self.disk = disk if disk is not None else _aot.AotDiskCache()
        self.feed_names = list(feed_names) if feed_names is not None else None
        self.fetch_names = (list(fetch_names) if fetch_names is not None
                            else None)
        # per-version memo: (version, {name: Variable}) — negative
        # lookups are NOT cached (create_var alone does not bump
        # program._version, same contract as the old
        # Executor._feed_var_for)
        self._feed_vars: Tuple = (None, {})
        # optimized-twin memo (the PADDLE_TPU_OPT step): (version, level,
        # feeds, fetches) -> optimized Program. Holding the clone here
        # keeps it alive exactly as long as its source program's Engine,
        # so the Executor's weak-keyed compile caches on the clone can't
        # see id reuse
        self._optimized: Dict = {}

    # -- identity ---------------------------------------------------------
    @property
    def version(self):
        """The program's process-local mutation counter."""
        return getattr(self.program, "_version", None)

    def fingerprint(self) -> str:
        """Short (8-hex) program fingerprint, cached per version."""
        return obs.program_fp(self.program)

    # -- optimizing transpiler --------------------------------------------
    _OPT_MEMO_MAX = 8

    def optimized(self, scope=None, feed_names: Sequence[str] = (),
                  fetch_names: Sequence[str] = (), level: int = 1):
        """The opt-in optimize step (PADDLE_TPU_OPT / explicit API): an
        optimized CLONE of this engine's program from the transpiler
        pass manager, memoized per (program version, level, feed set,
        fetch order). The clone fingerprints differently from the
        original, so its executables land under their own AOT-cache
        keys — optimized and original coexist on disk and in memory."""
        if level <= 0:
            return self.program
        import weakref

        key = (self.version, int(level), tuple(sorted(feed_names)),
               tuple(fetch_names))
        hit = self._optimized.get(key)
        if hit is not None:
            # the twin is only valid with the Scope its passes
            # materialized folded params into — a different scope must
            # re-optimize, not inherit state it doesn't hold
            ref, prog = hit
            same_scope = (scope is None and ref is None) or (
                ref is not None and ref() is scope)
            if same_scope:
                return prog
        from ..transpiler.passes import optimize_program

        prog, _ctx = optimize_program(
            self.program, scope=scope, level=level,
            feed_names=feed_names, fetch_names=fetch_names)
        if len(self._optimized) >= self._OPT_MEMO_MAX:
            self._optimized.pop(next(iter(self._optimized)))
        self._optimized[key] = (
            weakref.ref(scope) if scope is not None else None, prog)
        return prog

    # -- feed plan --------------------------------------------------------
    def feed_var(self, name: str):
        """Declared Variable behind a feed name, memoized per program
        version (the recursive block walk runs once per version, not
        once per call — the serving/training hot-path lookup)."""
        ver, cache = self._feed_vars
        if ver != self.version:
            cache = {}
            self._feed_vars = (self.version, cache)
        var = cache.get(name)
        if var is None:
            var = self.program.global_block()._find_var_recursive(name)
            if var is not None:
                cache[name] = var
        return var

    def feed_plan(self, feed_names: Optional[Sequence[str]] = None
                  ) -> List[Tuple[str, object, Optional[np.dtype]]]:
        """``[(name, declared var, numpy dtype or None)]`` for a frozen
        feed set — the conversion plan the Predictor walks per request
        instead of re-resolving declarations per call."""
        from ..framework.dtypes import as_numpy_dtype

        names = self.feed_names if feed_names is None else feed_names
        plan = []
        for name in names or ():
            var = self.feed_var(name)
            want = (np.dtype(as_numpy_dtype(var.dtype))
                    if var is not None else None)
            plan.append((name, var, want))
        return plan

    def convert_feeds(self, feed: Dict, plan=None) -> Dict[str, np.ndarray]:
        """Feed dict -> contiguous, declared-dtype arrays (the serving
        request path; KeyError names the missing feed)."""
        if plan is None:
            plan = self.feed_plan()
        out = {}
        for name, _var, want in plan:
            if name not in feed:
                raise KeyError("missing feed %r (model expects %s)"
                               % (name, [n for n, _, _ in plan]))
            arr = feed[name]
            if type(arr) is not np.ndarray:
                arr = np.asarray(arr)
            if want is not None and arr.dtype != want:
                arr = arr.astype(want)
            out[name] = arr
        return out

    # -- cache keys -------------------------------------------------------
    def key_fields(self, kind: str, feed_sig, fetch_names, *extra) -> Tuple:
        """The shared key-field layout: (kind, program content
        fingerprint, feed signature, fetch ORDER, <caller extras>,
        environment fingerprint). Training appends its state signature /
        per-step set as extras; serving appends nothing — both end with
        the env fingerprint so a toolchain change is a miss, never a
        stale load. program._version is deliberately absent: the content
        fingerprint already covers it, and a content-identical program
        rebuilt another way must still warm-start."""
        return ((kind, self.program.fingerprint(), feed_sig,
                 tuple(fetch_names)) + tuple(extra)
                + (_aot.env_fingerprint(),))

    def key(self, kind: str, feed_sig, fetch_names, *extra) -> str:
        return self.disk.key(self.key_fields(kind, feed_sig, fetch_names,
                                             *extra))

    def tier(self) -> str:
        """Transpile/quantization tier of this engine's program, from
        its stamps: "int8" (quantize stamp — serialized, so exported
        int8 models keep it), "O<level>" (the in-process marker
        optimize_program leaves on its clones), "O2" (a deserialized
        bucketize-stamped export), else "raw". Best-effort: an O1
        export carries no stamp and reloads as "raw"."""
        p = self.program
        if getattr(p, "_quantized", None):
            return "int8"
        lvl = getattr(p, "_opt_level", None)
        if lvl:
            return "O%d" % int(lvl)
        if getattr(p, "_bucketize", None):
            return "O2"
        return "raw"

    def meta(self, kind: str, feed_sig, fetch_names) -> Dict:
        """Sidecar metadata for preload scans and aot_cache_ls: the
        ``tier`` field is what distinguishes coexisting raw, optimized,
        and quantized executables of one model in the cache listing."""
        return {"kind": kind, "program": self.fingerprint(),
                "tier": self.tier(),
                "feed_sig": feed_sig, "fetch_names": tuple(fetch_names),
                "env": _aot.env_fingerprint(), "created": time.time()}

    # -- acquisition ------------------------------------------------------
    def acquire(self, kind: str, key: str, lower, meta: Optional[Dict] = None):
        """THE load-or-compile path: disk hit deserializes (path=warm),
        miss runs ``lower()`` -> ``.compile()`` and stores the result
        (path=cold). Returns ``(compiled, path, timings)`` where path is
        ``"warm" | "cold"`` and timings is ``{"trace_ms", "xla_ms"}`` on
        the cold path (None on warm — a deserialize has no split).

        ``lower`` may raise (program errors propagate exactly as the
        lazy-jit first call would); disk I/O failures are absorbed by
        AotDiskCache per its never-a-crash contract."""
        fp = self.fingerprint()
        use_disk = self.disk.enabled
        t0 = time.perf_counter()
        loaded = self.disk.load(key) if use_disk else None
        if loaded is not None:
            obs.CACHE_HITS.inc(kind=kind, tier="disk", program=fp)
            obs.AOT_COMPILE_MS.observe((time.perf_counter() - t0) * 1e3,
                                       path="warm", kind=kind)
            obs.TIMELINE.record_compile(kind, fp, cache="aot-load")
            return loaded, "warm", None
        if use_disk:  # a disabled tier compiles without tier accounting
            obs.CACHE_MISSES.inc(kind=kind, tier="disk", program=fp)
        t0 = time.perf_counter()
        lowered = lower()
        t1 = time.perf_counter()
        compiled = lowered.compile()
        t2 = time.perf_counter()
        obs.AOT_COMPILE_MS.observe((t2 - t0) * 1e3, path="cold", kind=kind)
        self.disk.store(key, compiled, meta=meta)
        return compiled, "cold", {"trace_ms": (t1 - t0) * 1e3,
                                  "xla_ms": (t2 - t1) * 1e3}
