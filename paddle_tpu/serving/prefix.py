"""Refcounted shared-prefix KV store for decode serving.

Serving traffic at scale is dominated by SHARED prompt prefixes — one
system prompt / few-shot header fanned out to thousands of concurrent
users. Without sharing, every admission pays a full private prefill
over tokens the fleet has already prefilled thousands of times. This
module is the admission-side cache that removes that cost:

- Entries hold the prefilled per-layer K/V rows of one prompt (host
  arrays in the (P, H, Dh) slab row layout — exactly what
  ``DecodeServer._admit`` scatters into cache slots) plus the
  last-position logits (so a full-prompt hit can sample its first token
  without touching the model at all).

- The index is BLOCK-ALIGNED, vLLM-style: inserting a prompt of length
  P indexes the hash of every ``block``-aligned prefix AND the full
  length, all pointing into the same entry — causal attention means the
  K/V rows of a prefix are literally the first L rows of the longer
  prefill, so one entry serves every prompt that shares any aligned
  header with it. ``lookup`` returns the LONGEST indexed prefix; a
  partial hit (L < P) seeds the slot with the cached rows and the
  server extends the remaining suffix through the verify-window
  executable (multi-token cached prefill) instead of a full private
  prefill.

- Entries are REFCOUNTED: each live sequence admitted from an entry
  holds a reference until it retires (or fails), and eviction — LRU,
  bounded by ``PADDLE_TPU_PREFIX_CACHE_MAX_BYTES`` (the PR-5 AOT-cache
  byte-bound discipline) — skips entries with live references, so a hot
  system prompt cannot be evicted out from under the sequences decoding
  from it.

Correctness note: the store is an ADMISSION cache, not a source of
truth — rows are COPIED into cache slots at admission, so eviction
never invalidates a running sequence; the refcount only protects
residency (a hit tomorrow) for entries in live use.
"""
from __future__ import annotations

import hashlib
import os
import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

from .. import observability as obs

__all__ = ["PrefixStore", "prefix_hash"]

_DEFAULT_MAX_BYTES = 256 * 1024 * 1024


def prefix_hash(tokens: np.ndarray) -> str:
    """Stable content hash of a token sequence (int64 canonical form)."""
    arr = np.ascontiguousarray(np.asarray(tokens, np.int64).reshape(-1))
    return hashlib.blake2b(arr.tobytes(), digest_size=16).hexdigest()


def aligned_prefix_hashes(tokens: np.ndarray, lengths) -> List[str]:
    """``prefix_hash(tokens[:L])`` for each L in ASCENDING ``lengths``,
    in ONE streaming pass: blake2b ingests each inter-boundary span
    once and a digest snapshot (`copy()`) marks every boundary —
    O(p) bytes hashed total, vs O(p^2/block) for per-prefix rehashing
    (at the 8k-32k shared prompts the long-context path targets, the
    quadratic form hashes hundreds of MB per admission, inside the
    store lock)."""
    arr = np.ascontiguousarray(np.asarray(tokens, np.int64).reshape(-1))
    h = hashlib.blake2b(digest_size=16)
    out, prev = [], 0
    for L in lengths:
        h.update(arr[prev:L].tobytes())
        prev = L
        out.append(h.copy().hexdigest())
    return out


class _Entry:
    __slots__ = ("rows", "length", "logits", "nbytes", "refs", "tick",
                 "keys")

    def __init__(self, rows, length, logits, nbytes, tick, keys):
        self.rows = rows          # [2 * n_layer] arrays, (P, H, Dh)
        self.length = length
        self.logits = logits      # (V,) last-position logits
        self.nbytes = nbytes
        self.refs = 0
        self.tick = tick
        self.keys = keys          # the aligned index keys this entry owns


class PrefixStore:
    """Byte-bounded, refcounted, block-aligned prefix cache."""

    def __init__(self, max_bytes: Optional[int] = None, block: int = 16):
        if max_bytes is None:
            env = os.environ.get("PADDLE_TPU_PREFIX_CACHE_MAX_BYTES")
            max_bytes = int(env) if env else _DEFAULT_MAX_BYTES
        self.max_bytes = int(max_bytes)
        self.block = max(int(block), 1)
        self._entries: Dict[int, _Entry] = {}
        # hash -> (L, {entry ids whose rows serve this prefix}): the
        # MULTI-owner set keeps a shared header reachable after any one
        # owner's eviction — the surviving entries' rows still serve it
        self._index: Dict[str, Tuple[int, set]] = {}
        self._next_id = 0
        self._tick = 0
        self._bytes = 0
        self._lock = threading.Lock()

    # -- introspection -----------------------------------------------------
    @property
    def bytes(self) -> int:
        return self._bytes

    def __len__(self) -> int:
        return len(self._entries)

    def refs(self, entry_id: int) -> int:
        with self._lock:
            e = self._entries.get(entry_id)
            return e.refs if e is not None else 0

    # -- lookup / insert ---------------------------------------------------
    def _aligned_lengths(self, p: int) -> List[int]:
        lens = list(range(self.block, p + 1, self.block))
        if not lens or lens[-1] != p:
            lens.append(p)
        return lens

    def lookup(self, prompt: np.ndarray):
        """Longest indexed prefix of ``prompt``. Returns (entry_id, L,
        rows, logits) — ``logits`` only on a FULL hit (the entry IS
        this exact prompt, so its stored last-position logits sample
        the first token store-side); partial hits return the first L
        rows and None logits (the caller extends the suffix). Misses
        return (None, 0, None, None). Every call counts one query; hits
        count by kind=full|partial.

        A prompt that equals a block-aligned PREFIX of a longer entry
        is NOT a full hit: the entry's logits belong to the longer
        prompt's last position, not this one's — the hit demotes to a
        partial at the previous aligned boundary (suffix >= 1 token),
        so the first token comes from a genuine forward over this
        prompt's own final position."""
        obs.DECODE_PREFIX_QUERIES.inc()
        prompt = np.asarray(prompt, np.int64).reshape(-1)
        p = len(prompt)
        lengths = self._aligned_lengths(p)
        keys = aligned_prefix_hashes(prompt, lengths)
        with self._lock:
            for L, key in zip(reversed(lengths), reversed(keys)):
                hit = self._index.get(key)
                if hit is None:
                    continue
                stored_l, eids = hit
                if stored_l != L:
                    continue
                owners = [(i, self._entries[i]) for i in eids
                          if i in self._entries]
                if not owners:
                    continue
                # prefer the exact-length owner: only ITS logits are
                # this prompt's last-position logits
                exact = [(i, o) for i, o in owners if o.length == p]
                if exact:
                    eid, e = exact[0]
                else:
                    eid, e = owners[0]
                    if L == p:
                        # exact length match against LONGER entries
                        # only: their logits are not ours — demote to
                        # the previous aligned boundary (none -> keep
                        # searching / miss)
                        L -= self.block if L % self.block == 0 \
                            else L % self.block
                        if L <= 0:
                            continue
                self._tick += 1
                e.tick = self._tick
                if L == p and e.length == p:
                    obs.DECODE_PREFIX_HITS.inc(kind="full")
                    return eid, L, [r[:L] for r in e.rows], e.logits
                obs.DECODE_PREFIX_HITS.inc(kind="partial")
                return eid, L, [r[:L] for r in e.rows], None
        return None, 0, None, None

    def insert(self, prompt: np.ndarray, rows, logits) -> Optional[int]:
        """Insert one prefilled prompt: ``rows`` are the per-layer K/V
        row arrays (P, H, Dh) in the flat [k0, v0, k1, v1, ...] order,
        ``logits`` the last-position logits row. Indexes every aligned
        prefix; returns the entry id (None when the entry alone exceeds
        the byte bound). Every aligned key records this entry as an
        ADDITIONAL owner (rows are identical across owners by the
        causal-prefix property) — a shared header stays serveable
        after any one owner's eviction."""
        prompt = np.asarray(prompt, np.int64).reshape(-1)
        p = len(prompt)
        # COPY the rows (np.array, not ascontiguousarray — the latter
        # returns a contiguous VIEW uncopied): callers pass views
        # sliced out of the batched prefill outputs, and storing the
        # view would pin the whole (bb, sp, H, Dh) parent array while
        # nbytes accounts only the P sliced rows, silently blowing the
        # byte bound
        rows = [np.array(r) for r in rows]
        logits = np.array(logits)
        nbytes = sum(r.nbytes for r in rows) + logits.nbytes
        if nbytes > self.max_bytes:
            return None
        lengths = self._aligned_lengths(p)
        keys = aligned_prefix_hashes(prompt, lengths)
        with self._lock:
            hit = self._index.get(keys[-1])
            if hit is not None and hit[0] == p:
                for i in hit[1]:
                    e = self._entries.get(i)
                    if e is not None and e.length == p:
                        return i  # this EXACT prompt already resident
                        # (a longer entry sharing the aligned key must
                        # not block its own logits-bearing entry)
            self._tick += 1
            eid = self._next_id
            self._next_id += 1
            self._entries[eid] = _Entry(rows, p, logits, nbytes,
                                        self._tick, list(keys))
            self._bytes += nbytes
            for L, key in zip(lengths, keys):
                ent = self._index.get(key)
                if ent is None:
                    self._index[key] = (L, {eid})
                else:
                    ent[1].add(eid)
            self._evict_locked()
            obs.DECODE_PREFIX_BYTES.set(self._bytes)
        return eid

    # -- refcounting -------------------------------------------------------
    def acquire(self, entry_id: Optional[int]):
        if entry_id is None:
            return
        with self._lock:
            e = self._entries.get(entry_id)
            if e is not None:
                e.refs += 1

    def release(self, entry_id: Optional[int]):
        if entry_id is None:
            return
        with self._lock:
            e = self._entries.get(entry_id)
            if e is not None and e.refs > 0:
                e.refs -= 1
            self._evict_locked()
            obs.DECODE_PREFIX_BYTES.set(self._bytes)

    # -- eviction ----------------------------------------------------------
    def _evict_locked(self):
        if self._bytes <= self.max_bytes:
            return
        victims = sorted(
            (e.tick, eid) for eid, e in self._entries.items()
            if e.refs == 0)
        for _tick, eid in victims:
            if self._bytes <= self.max_bytes:
                break
            e = self._entries.pop(eid)
            self._bytes -= e.nbytes
            # surgical index update: drop THIS entry from each of its
            # keys; a key another entry also owns stays serveable (a
            # shared header must survive one owner's eviction)
            for key in e.keys:
                ent = self._index.get(key)
                if ent is None:
                    continue
                ent[1].discard(eid)
                if not ent[1]:
                    del self._index[key]
