"""paddle_tpu.serving — the horizontal serving layer.

One front door over N model replicas (ROADMAP item 1): the pieces the
single-process ``inference.PredictorServer`` cannot provide by itself.

- ``engine.Engine`` — the compile/execute core factored OUT of
  ``Executor`` and ``inference.Predictor``: program identity/version,
  the persistent AOT-cache handle, the precomputed feed-conversion
  plan, and the one load-or-compile acquisition path. Both executors
  construct their core through it, and a serving replica is exactly
  "engine + channel loop".
- ``sharded.ShardedPredictor`` — one model LARGER than a single device
  served under ``pjit`` over a tensor-parallel mesh, reusing the
  training-side megatron plan rules at inference
  (``parallel.sharding.infer_tp_plan``). Same ``run``/``warm`` surface
  as ``Predictor``, so it drops into ``PredictorServer`` and the fleet
  unchanged.
- ``router.Router`` — the front door: requests enter the same C++
  bounded channel as zero-copy binary frames, a dispatch loop
  load-balances them across worker PROCESSES (least outstanding work,
  sticky per-program-version routing, backpressure when every worker's
  in-flight window is full), per-worker reader threads fan responses
  back out, and the fleet exposes per-replica health plus aggregated
  metrics. Graceful ``drain_restart`` of one worker loses zero
  requests; a crashed worker's in-flight frames are re-dispatched.

- ``slo.SLOClass`` / ``slo.RejectedError`` — per-request latency
  contracts: priority dispatch classes, deadlines, and the structured
  reject bounded-latency load shedding answers with (never a timeout).
- ``autoscale.Autoscaler`` — the control loop over the Router's
  elastic-fleet knobs (``add_replica``/``remove_replica``/``reap_dead``):
  utilization+shed-driven scale-up, hysteretic drain-shrink, cooldown,
  and crash healing.
- ``swap.SwapController`` — zero-downtime hot model swap: surge
  new-version replicas behind the sticky active version (warm AOT
  spawn + bucket prewarm), optionally canary live requests through
  both versions, flip atomically with ``set_version``, retire the old
  replicas with zero drops; any pre-flip failure rolls back with the
  old version never having stopped serving. ``tools/swap_ctl.py``
  watches a streaming trainer's export root and drives it.

Import policy: ``Engine`` is imported eagerly (executor.py depends on
it); ``Router``/``ShardedPredictor`` resolve lazily so importing the
engine from the executor does not drag the inference stack (and its
import cycle) along.
"""
from __future__ import annotations

from .engine import Engine  # noqa: F401

__all__ = ["Engine", "Router", "ShardedPredictor", "worker_main",
           "DecodeConfig", "DecodePredictor", "DecodeServer",
           "save_decode_model", "PrefixStore", "Autoscaler", "SLOClass",
           "RejectedError", "default_slo_classes", "SwapController",
           "SwapError"]

_LAZY = {
    "Router": ("router", "Router"),
    "SwapController": ("swap", "SwapController"),
    "SwapError": ("swap", "SwapError"),
    "ShardedPredictor": ("sharded", "ShardedPredictor"),
    "worker_main": ("worker", "worker_main"),
    "DecodeConfig": ("decode", "DecodeConfig"),
    "DecodePredictor": ("decode", "DecodePredictor"),
    "DecodeServer": ("decode", "DecodeServer"),
    "save_decode_model": ("decode", "save_decode_model"),
    "PrefixStore": ("prefix", "PrefixStore"),
    "Autoscaler": ("autoscale", "Autoscaler"),
    "SLOClass": ("slo", "SLOClass"),
    "RejectedError": ("slo", "RejectedError"),
    "default_slo_classes": ("slo", "default_classes"),
}


def __getattr__(name):  # PEP 562: lazy, cycle-free heavy exports
    entry = _LAZY.get(name)
    if entry is None:
        raise AttributeError("module %r has no attribute %r"
                             % (__name__, name))
    import importlib

    module = importlib.import_module("." + entry[0], __name__)
    return getattr(module, entry[1])
