"""ShardedPredictor: one model larger than a single device, served
under ``pjit`` over a tensor-parallel mesh.

The single-device ``inference.Predictor`` pins params to one device; a
model that does not fit stops there. This predictor reuses the
TRAINING-side machinery at inference (ROADMAP item 1): a
``parallel.mesh`` Mesh over the tp devices, a ``ShardingPlan`` from
``parallel.sharding.infer_tp_plan`` (megatron column/row rules when the
naming matches, the same alternation derived structurally otherwise),
and one ``jax.jit`` with in/out shardings — GSPMD inserts the
all-reduce after each row-parallel matmul exactly as it does for the
training ``ParallelExecutor``.

Surface contract: ``run`` / ``warm`` / ``feed_names`` / ``fetch_names``
match ``Predictor``, so ``PredictorServer`` (and therefore a fleet
worker — ``examples/serve.py --shard K``) hosts either interchangeably.
Sharded executables stay MEMORY-only: ``serialize_executable``
round-trips single-device executables, and a mesh executable would need
per-topology keys (the ParallelExecutor carries the same note), so the
disk tier is disabled on this predictor's Engine.
"""
from __future__ import annotations

import time
from typing import Dict, List, Optional

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .. import observability as obs
from ..framework.scope import Scope
from ..framework.trace import RngStream, trace_block
from ..framework import trace as trace_mod
from ..runtime import aot_cache as _aot
from .engine import Engine

__all__ = ["ShardedPredictor"]


class ShardedPredictor:
    """``Predictor`` over a tensor-parallel device mesh.

    predictor = ShardedPredictor(model_dir, shard=2)
    outs = predictor.run({"img": batch})   # same contract as Predictor
    """

    def __init__(self, model_dir: str, shard: Optional[int] = None,
                 mesh: Optional[Mesh] = None,
                 plan=None, mp_axis: str = "mp", place=None):
        from .. import io as fluid_io
        from ..executor import Executor
        from ..parallel.mesh import make_mesh
        from ..parallel.sharding import infer_tp_plan

        self.model_dir = model_dir
        self._scope = Scope()
        exe = Executor(place)
        # the loader executor's own compiles must not touch the
        # training-side default disk cache (same rule as
        # Predictor(aot_cache=False))
        exe._disk.enabled = False
        self._program, self._feed_names, self._fetch_targets = (
            fluid_io.load_inference_model(model_dir, exe, scope=self._scope))
        self._fetch_names = [t.name for t in self._fetch_targets]
        if mesh is None:
            n = int(shard) if shard else jax.device_count()
            if n > jax.device_count():
                raise ValueError(
                    "shard=%d needs %d devices, only %d available"
                    % (n, n, jax.device_count()))
            mesh = make_mesh((n,), axis_names=(mp_axis,),
                             devices=jax.devices()[:n])
        self.mesh = mesh
        self.mp_axis = mp_axis
        self._plan = (plan if plan is not None
                      else infer_tp_plan(mesh, self._program,
                                         mp_axis=mp_axis))
        # shared core: feed plan + identity (the disk tier stays off —
        # sharded executables are memory-only, see module docstring)
        self._engine = Engine(
            self._program,
            disk=_aot.AotDiskCache(enabled=False),
            feed_names=self._feed_names, fetch_names=self._fetch_names)
        self._feed_plan = self._engine.feed_plan()
        self._compiled: Dict = {}
        self.traces = 0
        self._state_names, self._state = self._load_state()

    # -- state -------------------------------------------------------------
    def _load_state(self):
        from ..executor import analyze_state

        state_in, _ = analyze_state(self._program, set(self._feed_names))
        state = {}
        for n in state_in:
            val = self._scope.find_var(n)
            if val is None:
                raise RuntimeError(
                    "inference model is missing persistable %r" % n)
            arr = np.asarray(val)
            sharding = self._plan.sharding(n, shape=tuple(arr.shape))
            # params are resident SHARDED device state from load time:
            # each device holds only its plan slice of every weight —
            # this is what lets the model exceed one device's memory
            state[n] = jax.device_put(arr, sharding)
        return state_in, state

    # -- compilation -------------------------------------------------------
    def _step_fn(self):
        program = self._program
        fetch_names = self._fetch_names

        def fn(feeds, state):
            self.traces += 1
            env = dict(state)
            env.update(feeds)
            rng = RngStream(jax.random.PRNGKey(0))
            trace_block(program.global_block(), env, rng)
            return tuple(env[n] for n in fetch_names)

        return fn

    def _get_executable(self, feed_arrays):
        feed_sig = tuple((n, tuple(a.shape), str(a.dtype))
                         for n, a in sorted(feed_arrays.items()))
        fp = self._engine.fingerprint()
        if feed_sig in self._compiled:
            obs.CACHE_HITS.inc(kind="predict_sharded", tier="memory",
                               program=fp)
            return self._compiled[feed_sig]
        obs.CACHE_MISSES.inc(kind="predict_sharded", tier="memory",
                             program=fp)
        from ..executor import Executor

        Executor._check_feed_shapes(self._program, feed_sig)
        rep = NamedSharding(self.mesh, P())
        # serving feeds are replicated (batches are small and dynamic);
        # only the params shard — GSPMD propagates the tp pattern from
        # the state shardings through the whole computation
        in_shardings = (
            {n: rep for n, _s, _d in feed_sig},
            {n: self._state[n].sharding for n in self._state_names},
        )
        out_shardings = tuple(rep for _ in self._fetch_names)
        fn = jax.jit(self._step_fn(), in_shardings=in_shardings,
                     out_shardings=out_shardings)
        t0 = time.perf_counter()
        with trace_mod.mesh_context(self.mesh):
            lowered = fn.lower(
                {n: jax.ShapeDtypeStruct(s, np.dtype(d))
                 for n, s, d in feed_sig},
                {n: jax.ShapeDtypeStruct(a.shape, a.dtype)
                 for n, a in self._state.items()})
            compiled = lowered.compile()
        wall_ms = (time.perf_counter() - t0) * 1e3
        obs.COMPILE_TOTAL.inc(kind="predict_sharded")
        obs.COMPILE_LATENCY_MS.observe(wall_ms, kind="predict_sharded")
        obs.TIMELINE.record_compile("predict_sharded", fp, wall_ms=wall_ms)
        self._compiled[feed_sig] = compiled
        return compiled

    # -- pre-warm ----------------------------------------------------------
    def warm(self, batch_rows: int) -> bool:
        """Same contract as ``Predictor.warm``: compile the executable
        for a ``batch_rows``-row batch of the declared feed shapes (the
        PredictorServer bucket pre-warm); False when a declared shape
        makes the bucket signature unknowable."""
        feed_arrays = {}
        for name, var, want in self._feed_plan:
            shape = tuple(getattr(var, "shape", None) or ())
            if (not shape or shape[0] not in (-1, None)
                    or any(d is None or d < 0 for d in shape[1:])):
                return False
            feed_arrays[name] = np.zeros(
                (batch_rows,) + shape[1:], want or np.float32)
        self._get_executable(feed_arrays)
        return True

    # -- prediction --------------------------------------------------------
    def run(self, feed, return_numpy: bool = True,
            _obs_path: str = "direct") -> List[np.ndarray]:
        t0 = time.perf_counter()
        if isinstance(feed, (list, tuple)):
            feed = dict(zip(self._feed_names, feed))
        feed_arrays = self._engine.convert_feeds(feed, self._feed_plan)
        exe = self._get_executable(feed_arrays)
        outs = exe(feed_arrays, self._state)
        outs = ([np.asarray(o) for o in outs] if return_numpy
                else list(outs))
        first = next(iter(feed_arrays.values())) if feed_arrays else None
        rows = (first.shape[0] if first is not None and first.ndim else 1)
        obs.PREDICT_LATENCY_MS.observe((time.perf_counter() - t0) * 1e3,
                                       path=_obs_path)
        obs.PREDICT_REQUESTS.inc(path=_obs_path)
        obs.PREDICT_BATCH_ROWS.observe(rows, path=_obs_path)
        return outs

    predict = run  # api parity sugar

    @property
    def feed_names(self) -> List[str]:
        return list(self._feed_names)

    @property
    def fetch_names(self) -> List[str]:
        return list(self._fetch_names)
