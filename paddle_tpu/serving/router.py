"""Router: one front door over N PredictorServer replicas.

Requests enter exactly as they do for the single-process
``PredictorServer`` — zero-copy binary frames on the C++ bounded
channel (``ptrt_chan_recv_batch`` behind ``Channel.recv_batch``) — and
a dispatch loop forwards each frame VERBATIM to a worker process over
its pipe. Policy:

- **least outstanding work**: each frame goes to the routable replica
  with the fewest unanswered requests (outstanding map, not a counter —
  the map also holds the frame bytes so a dead worker's in-flight
  requests can be re-dispatched).
- **sticky per-program-version routing**: a replica is routable only
  while its reported program version matches the fleet's ACTIVE
  version. During a model load/hot swap a restarted worker that comes
  up on a different version receives no traffic until
  ``set_version()`` flips the fleet — so a client can never get version
  N and N+1 rows interleaved from one logical model
  (``paddle_tpu_fleet_misversioned_total`` counts violations; it must
  stay 0).
- **backpressure**: when every routable replica is at
  ``max_outstanding`` the dispatch loop parks (counted in
  ``paddle_tpu_fleet_backpressure_ms_total``); the front channel then
  fills and ``submit()`` blocks — bounded memory end to end, no
  unbounded queue anywhere.

Lifecycle: ``drain_restart(i)`` marks one replica unroutable, waits for
its outstanding responses, stops it gracefully (the worker's
``server.stop()`` flushes its stacking queue — zero drops), respawns,
and waits ready. A worker that DIES instead of draining has its
in-flight frames re-dispatched to the survivors (predict is stateless,
replay is safe; ``paddle_tpu_fleet_requeued_total``).

Observability: the router process records request latency under
``path="router"`` plus the fleet gauges/counters; ``health()`` is the
per-replica view, ``fleet_metrics()`` pulls every worker's registry
snapshot over the control pipe and merges them
(``observability.export.merge_json_snapshots``); ``start_http()``
serves ``/metrics`` (router process), ``/fleet.json`` (health +
aggregated fleet registry) and ``/health.json``.
"""
from __future__ import annotations

import pickle
import queue
import struct
import threading
import time
from typing import Dict, List, Optional

from .. import observability as obs
from ..inference import _Future, _encode_sample
from ..runtime import recordio as _rio

__all__ = ["Router"]


class _Worker:
    """Router-side handle for one replica process."""

    __slots__ = (
        "idx", "name", "proc", "conn", "state", "version", "pid",
        "metrics_port", "outstanding", "dispatched", "reader",
        "ready_ev", "stopped_ev", "status_q", "send_lock", "error",
    )

    def __init__(self, idx: int, name: str):
        self.idx = idx
        self.name = name
        self.proc = None
        self.conn = None
        self.state = "starting"
        self.version = None
        self.pid = None
        self.metrics_port = 0
        # rid -> (frame bytes, version dispatched under): the frame is
        # kept so a dead worker's in-flight work is re-dispatchable
        self.outstanding: Dict[int, tuple] = {}
        self.dispatched = 0
        self.reader = None
        self.ready_ev = threading.Event()
        self.stopped_ev = threading.Event()
        self.status_q: "queue.Queue" = queue.Queue()
        self.send_lock = threading.Lock()
        self.error = None


class Router:
    """
    router = Router(model_dir, replicas=4, max_batch=32)
    router.start()
    fut = router.submit((row,))      # same surface as PredictorServer
    outs = fut.result()
    router.drain_restart(0)          # zero dropped requests
    router.stop()
    """

    def __init__(self, model_dir: str, replicas: int = 2,
                 max_batch: int = 8, max_wait_ms: float = 0.0,
                 in_flight: int = 2, shard: int = 1,
                 capacity: int = 1024,
                 max_outstanding: Optional[int] = None,
                 start_method: Optional[str] = None,
                 jax_platform: Optional[str] = None,
                 worker_env: Optional[Dict[str, str]] = None,
                 worker_http: bool = False,
                 start_timeout: float = 300.0,
                 dispatch_batch: int = 64,
                 decode: bool = False,
                 decode_slots: int = 4,
                 decode_max_seq: Optional[int] = None,
                 max_new_tokens: int = 32,
                 strategy: Optional[str] = None):
        from ..runtime.recordio import Channel

        if replicas < 1:
            raise ValueError("replicas must be >= 1, got %d" % replicas)
        if decode and shard > 1:
            # fail HERE, not silently in the worker: the decode branch
            # builds a single-device DecodePredictor and would quietly
            # serve a tp-exported model unsharded
            raise ValueError(
                "decode mode does not support shard > 1 yet (the "
                "DecodeServer replica hosts a single-device "
                "DecodePredictor)")
        self.model_dir = model_dir
        self.replicas = int(replicas)
        self.shard = int(shard)
        self.start_timeout = float(start_timeout)
        self.dispatch_batch = int(dispatch_batch)
        # per-replica in-flight window: enough to keep the worker's
        # stacking + device stages full (one bucket building while
        # in_flight batches queue) without hoarding requests a draining
        # neighbour could have served
        self.max_outstanding = (int(max_outstanding) if max_outstanding
                                else max(2 * max_batch * in_flight, 8))
        self._opts = {
            "model_dir": model_dir, "max_batch": int(max_batch),
            "max_wait_ms": float(max_wait_ms), "in_flight": int(in_flight),
            "shard": int(shard), "http": bool(worker_http),
            "jax_platform": jax_platform, "env": dict(worker_env or {}),
            # one capacity knob bounds BOTH the router's front channel
            # and each worker server's channel
            "capacity": int(capacity),
            # decode mode: replicas run the continuous-batching
            # DecodeServer (serving/decode.py) instead of the
            # PredictorServer; requests are (prompt_ids[, opts]) frames
            # and responses one generated-ids row — the router forwards
            # both verbatim, and in-flight decode SEQUENCES inherit the
            # zero-drop drain/restart + crash-requeue contracts
            # (generation is stateless from the router's view: the kept
            # frame re-prefills on a survivor)
            "decode": bool(decode),
            "decode_slots": int(decode_slots),
            "decode_max_seq": decode_max_seq,
            "max_new_tokens": int(max_new_tokens),
            "strategy": strategy,
        }
        import multiprocessing as mp

        if start_method is None:
            # fork from a jax-threaded parent deadlocks children (PR-3
            # DataLoader lesson); forkserver keeps respawn cheap
            start_method = ("forkserver"
                            if "forkserver" in mp.get_all_start_methods()
                            else "spawn")
        self._ctx = mp.get_context(start_method)
        self._chan = Channel(capacity)
        self._workers: List[_Worker] = []
        self._futures: Dict[int, _Future] = {}
        self._next_id = 0
        self._lock = threading.Lock()          # futures + rid allocation
        self._cond = threading.Condition()     # worker states/capacity
        self.active_version: Optional[str] = None
        self._dispatch_thread: Optional[threading.Thread] = None
        self._stopping = False
        self._http = None
        self._http_thread = None

    # -- lifecycle ---------------------------------------------------------
    def start(self):
        if self._dispatch_thread is not None:
            return
        for i in range(self.replicas):
            self._workers.append(self._spawn(i))
        self._wait_ready(self._workers)
        with self._cond:
            if self.active_version is None:
                self.active_version = self._workers[0].version
        self._refresh_worker_gauge()
        self._dispatch_thread = threading.Thread(
            target=self._dispatch_loop, daemon=True,
            name="ptpu-router-dispatch")
        self._dispatch_thread.start()

    def _spawn(self, idx: int, name: Optional[str] = None) -> _Worker:
        from .worker import worker_main

        w = _Worker(idx, name or "replica%d" % idx)
        parent, child = self._ctx.Pipe(duplex=True)
        opts = dict(self._opts, name=w.name)
        w.proc = self._ctx.Process(
            target=worker_main, args=(child, opts), daemon=True,
            name="ptpu-" + w.name)
        w.proc.start()
        child.close()
        w.conn = parent
        w.reader = threading.Thread(
            target=self._reader_loop, args=(w,), daemon=True,
            name="ptpu-router-read-" + w.name)
        w.reader.start()
        return w

    def _wait_ready(self, workers, timeout: Optional[float] = None,
                    abort_scope=None):
        """Wait for every worker in `workers` to report ready. On
        failure, terminate ONLY the workers in `abort_scope` (default:
        the ones being waited on) — a failed drain_restart respawn must
        never take down the healthy replicas still serving traffic."""
        scope = workers if abort_scope is None else abort_scope
        deadline = time.monotonic() + (timeout or self.start_timeout)
        for w in workers:
            # poll so a worker that DIES during bootstrap (bad model
            # dir, spawn outside a __main__ guard, import crash) fails
            # the start immediately instead of eating the full timeout
            while not w.ready_ev.wait(0.25):
                if time.monotonic() >= deadline:
                    self._abort_workers(scope)
                    raise RuntimeError(
                        "fleet worker %s did not become ready within %.0fs"
                        % (w.name, self.start_timeout))
                if w.proc is not None and not w.proc.is_alive():
                    self._abort_workers(scope)
                    raise RuntimeError(
                        "fleet worker %s died during startup (exitcode "
                        "%s)%s" % (w.name, w.proc.exitcode,
                                   ": " + w.error if w.error else ""))
            if w.error is not None:
                err = w.error
                self._abort_workers(scope)
                raise RuntimeError(
                    "fleet worker %s failed to start: %s" % (w.name, err))

    def _abort_workers(self, workers):
        for w in workers:
            try:
                if w.proc is not None and w.proc.is_alive():
                    w.proc.terminate()
                    w.proc.join(timeout=5)
            except Exception:
                pass
        self._refresh_worker_gauge()

    # -- submission --------------------------------------------------------
    def submit(self, sample) -> _Future:
        """sample: one array per feed slot (a single row, no batch dim)
        — identical contract to ``PredictorServer.submit``, same wire
        frame (``inference._encode_sample``)."""
        fut = _Future()
        fut._t0 = time.perf_counter()
        with self._lock:
            rid = self._next_id
            self._next_id += 1
            self._futures[rid] = fut
        fut._bind(self, rid)
        try:
            sent = self._chan.send(_encode_sample(rid, sample))
        except BaseException:
            with self._lock:
                self._futures.pop(rid, None)
            raise
        if not sent:
            with self._lock:
                self._futures.pop(rid, None)
            raise RuntimeError("serving fleet is stopped")
        return fut

    def _pop(self, rid):  # _Future.cancel protocol (same as the server)
        with self._lock:
            return self._futures.pop(rid, None)

    # -- dispatch ----------------------------------------------------------
    def _dispatch_loop(self):
        from . import wire

        while True:
            batch = self._chan.recv_batch(self.dispatch_batch, None)
            if batch is None:
                return  # closed and drained
            # assign every drained frame, then ship each worker ITS
            # frames as ONE coalesced pipe message — at load the pipe
            # hop is per-burst, not per-request. Assignment greedily
            # avoids blocking: when capacity runs out mid-burst, what is
            # already grouped is flushed first (no head-of-line wait),
            # then the rest dispatches one by one through the blocking
            # path.
            groups: Dict[int, list] = {}
            rest = None
            for i, msg in enumerate(batch):
                w = self._assign(msg, block=False)
                if w is False:
                    continue  # failed (fleet dead/stopping), future set
                if w is None:
                    rest = batch[i:]
                    break
                groups.setdefault(w.idx, (w, []))[1].append(msg)
            self._flush_groups(wire, groups)
            for msg in rest or ():
                w = self._assign(msg, block=True)
                if w in (None, False):
                    continue
                self._send_to(w, msg)

    def _flush_groups(self, wire, groups):
        for w, msgs in groups.values():
            self._send_to(w, wire.pack(msgs))

    def _send_to(self, w: _Worker, payload):
        try:
            with w.send_lock:
                w.conn.send_bytes(payload)
        except (OSError, ValueError):
            # worker died between assignment and send: the reader thread
            # notices the dead pipe and requeues its outstanding frames
            pass

    def _eligible(self):
        """Routable replicas: ready, on the active version, with
        in-flight headroom."""
        return [w for w in self._workers
                if w.state == "ready" and w.version == self.active_version
                and len(w.outstanding) < self.max_outstanding]

    def _alive(self):
        return [w for w in self._workers
                if w.state in ("starting", "ready", "draining")]

    def _assign(self, msg, block: bool):
        """Record `msg` against the least-outstanding routable replica.
        Returns the worker, None when nothing is routable and
        ``block=False`` (caller flushes and retries blocking), or False
        when the request had to be FAILED (fleet stopping / all dead)."""
        rid = _rio.frame_tag(msg)
        t0 = time.perf_counter()
        waited = False
        with self._cond:
            while True:
                elig = self._eligible()
                if elig:
                    break
                # park while saturated or mid-restart; give up only when
                # the fleet is stopping or EVERY replica crashed (a
                # gracefully "stopped" replica means a restart is in
                # flight — hold the request, don't fail it)
                if self._stopping or (
                        not self._alive()
                        and all(w.state == "dead" for w in self._workers)):
                    fut = self._pop(rid)
                    if fut is not None:
                        fut.set_exception(RuntimeError(
                            "no serving replica available for request %d"
                            % rid))
                        obs.PREDICT_FAILURES.inc(path="router")
                    return False
                if not block:
                    return None
                waited = True
                self._cond.wait(0.5)
            # least outstanding work
            w = min(elig, key=lambda w: len(w.outstanding))
            w.outstanding[rid] = (msg, self.active_version)
            w.dispatched += 1
            obs.FLEET_OUTSTANDING.set(len(w.outstanding), replica=w.name)
        if waited:
            obs.FLEET_BACKPRESSURE_MS.inc(
                (time.perf_counter() - t0) * 1e3)
        obs.FLEET_DISPATCHES.inc(replica=w.name)
        return w

    # -- responses ---------------------------------------------------------
    def _reader_loop(self, w: _Worker):
        from . import wire

        while True:
            try:
                payload = w.conn.recv_bytes()
            except (EOFError, OSError):
                break
            for msg in wire.iter_messages(payload):
                try:
                    kind = bytes(msg[:1])
                    if kind == b"S":
                        self._on_status(w, pickle.loads(msg[1:]))
                    elif kind == b"R":
                        vlen = struct.unpack_from("<B", msg, 1)[0]
                        version = bytes(msg[2:2 + vlen]).decode("ascii")
                        frame = msg[2 + vlen:]
                        self._complete(w, _rio.frame_tag(frame),
                                       frame=frame, version=version)
                    elif kind == b"E":
                        rid, exc = pickle.loads(msg[1:])
                        self._complete(w, rid, exc=exc)
                except Exception:
                    # one undecodable message (e.g. an exception class
                    # that fails to reconstruct on unpickle) must not
                    # kill the reader thread — that would strand every
                    # other outstanding response AND skip the
                    # _on_worker_exit requeue below. Count it and keep
                    # reading; the affected rid's future is eventually
                    # abandoned by its caller's timeout.
                    obs.PREDICT_FAILURES.inc(path="router_decode")
        self._on_worker_exit(w)

    def _on_status(self, w: _Worker, st: Dict):
        if st.get("ready"):
            with self._cond:
                w.version = st.get("version")
                w.pid = st.get("pid")
                w.metrics_port = st.get("metrics_port", 0)
                w.state = "ready"
                self._cond.notify_all()
            self._refresh_worker_gauge()
            w.ready_ev.set()
        elif "error" in st and not w.ready_ev.is_set():
            w.error = st.get("error")
            if st.get("traceback"):
                w.error += "\n" + st["traceback"]
            with self._cond:
                w.state = "dead"
                self._cond.notify_all()
            w.ready_ev.set()
        elif st.get("stopped"):
            w.stopped_ev.set()
        else:  # pong / metrics replies
            w.status_q.put(st)

    def _complete(self, w: _Worker, rid, frame=None, version=None,
                  exc=None):
        with self._cond:
            entry = w.outstanding.pop(rid, None)
            obs.FLEET_OUTSTANDING.set(len(w.outstanding), replica=w.name)
            self._cond.notify_all()  # capacity freed / drain progressed
        fut = self._pop(rid)
        if fut is None:
            return  # abandoned via cancel/timeout
        if exc is not None:
            obs.PREDICT_FAILURES.inc(path="router")
            fut.set_exception(exc)
            obs.PREDICT_LATENCY_MS.observe(
                (time.perf_counter() - fut._t0) * 1e3, path="router")
            return
        if (entry is not None and version is not None
                and entry[1] is not None and version != entry[1]):
            # a replica answered with a different program version than
            # the one this request was routed under — sticky routing
            # makes this structurally impossible; count loudly if a bug
            # ever breaks that
            obs.FLEET_MISVERSIONED.inc()
        _tag, rows = _rio.decode_frame(frame)
        fut.set_result(rows)
        obs.PREDICT_LATENCY_MS.observe(
            (time.perf_counter() - fut._t0) * 1e3, path="router")
        obs.PREDICT_REQUESTS.inc(path="router")

    def _on_worker_exit(self, w: _Worker):
        """Reader saw EOF: graceful stop keeps state, a crash requeues
        the worker's in-flight frames onto the survivors."""
        with self._cond:
            crashed = not w.stopped_ev.is_set() and w.state != "stopped"
            entries = list(w.outstanding.items())
            w.outstanding.clear()
            obs.FLEET_OUTSTANDING.set(0, replica=w.name)
            w.state = "dead" if crashed else "stopped"
            self._cond.notify_all()
        self._refresh_worker_gauge()
        if not entries:
            return
        for rid, (msg, _ver) in entries:
            obs.FLEET_REQUEUED.inc()
            # back through the front channel: the dispatch loop re-routes
            # to a live replica (predict is stateless — replay is safe)
            if not self._chan.send(msg):
                fut = self._pop(rid)
                if fut is not None:
                    fut.set_exception(RuntimeError(
                        "replica %s died and the fleet is stopping"
                        % w.name))
                    obs.PREDICT_FAILURES.inc(path="router")

    # -- fleet operations --------------------------------------------------
    def set_version(self, version: str):
        """Flip the fleet's active program version (hot-swap cutover):
        replicas reporting `version` become routable, everyone else
        drains naturally as their outstanding work completes."""
        with self._cond:
            self.active_version = version
            self._cond.notify_all()

    def drain_restart(self, idx: int, timeout: float = 300.0):
        """Gracefully recycle one replica with ZERO dropped requests:
        unroute it, wait out its in-flight responses, stop it (the
        worker flushes its own stacking queue before exiting), respawn,
        wait ready. The rest of the fleet keeps serving throughout."""
        w = self._workers[idx]
        deadline = time.monotonic() + timeout
        with self._cond:
            if w.state == "ready":
                w.state = "draining"
            self._cond.notify_all()
        self._refresh_worker_gauge()
        with self._cond:
            while w.outstanding and time.monotonic() < deadline:
                self._cond.wait(0.5)
            pending = len(w.outstanding)
        if pending:
            raise RuntimeError(
                "replica %s still has %d outstanding requests after %.0fs"
                % (w.name, pending, timeout))
        self._stop_worker(w, deadline)
        nw = self._spawn(idx, name=w.name)
        self._workers[idx] = nw
        self._wait_ready([nw], timeout=max(1.0, deadline - time.monotonic()))
        self._refresh_worker_gauge()
        with self._cond:
            self._cond.notify_all()

    def _stop_worker(self, w: _Worker, deadline=None):
        try:
            with w.send_lock:
                w.conn.send_bytes(b"C" + pickle.dumps({"cmd": "stop"},
                                                      protocol=4))
        except (OSError, ValueError):
            pass
        remaining = (max(1.0, deadline - time.monotonic())
                     if deadline else 30.0)
        w.stopped_ev.wait(remaining)
        if w.proc is not None:
            w.proc.join(timeout=remaining)
            if w.proc.is_alive():
                w.proc.terminate()
                w.proc.join(timeout=5)
        with self._cond:
            if w.state != "dead":
                w.state = "stopped"
            self._cond.notify_all()
        if w.reader is not None:
            w.reader.join(timeout=5)

    def stop(self):
        """Drain the front channel through the fleet, then stop every
        replica gracefully (flushing their queues) and reap processes."""
        self.stop_http()
        with self._cond:
            already = self._stopping and self._dispatch_thread is None
        if already:
            return
        self._chan.close()
        if self._dispatch_thread is not None:
            # the dispatch loop finishes routing everything already
            # accepted, then sees the closed+drained channel and exits
            self._dispatch_thread.join(timeout=60)
            self._dispatch_thread = None
        # wait for in-flight responses BEFORE stopping workers: nothing
        # accepted is dropped
        with self._cond:
            deadline = time.monotonic() + 60
            while (any(w.outstanding for w in self._workers)
                   and time.monotonic() < deadline):
                self._cond.wait(0.5)
            self._stopping = True
            self._cond.notify_all()
        for w in self._workers:
            if w.state in ("ready", "draining", "starting"):
                self._stop_worker(w)
        self._refresh_worker_gauge()

    # -- introspection -----------------------------------------------------
    def _refresh_worker_gauge(self):
        counts: Dict[str, int] = {}
        for w in self._workers:
            counts[w.state] = counts.get(w.state, 0) + 1
        for state in ("starting", "ready", "draining", "stopped", "dead"):
            obs.FLEET_WORKERS.set(counts.get(state, 0), state=state)

    def health(self) -> List[Dict]:
        """Per-replica view: state, version, pid, outstanding depth,
        dispatch count, metrics port."""
        with self._cond:
            return [{"replica": w.name, "state": w.state,
                     "version": w.version, "pid": w.pid,
                     "outstanding": len(w.outstanding),
                     "dispatched": w.dispatched,
                     "metrics_port": w.metrics_port,
                     "shard": self.shard}
                    for w in self._workers]

    def _worker_call(self, w: _Worker, cmd: str, timeout: float = 30.0):
        try:
            with w.send_lock:
                w.conn.send_bytes(b"C" + pickle.dumps({"cmd": cmd},
                                                      protocol=4))
            return w.status_q.get(timeout=timeout)
        except (OSError, ValueError, queue.Empty):
            return None

    def fleet_metrics(self, timeout: float = 30.0) -> Dict:
        """Aggregated registry across the fleet: every live worker's
        JSON snapshot (pulled over the control pipe, each labeled by its
        ``replica``) merged with the router's own via
        ``export.merge_json_snapshots``."""
        from ..observability import export

        snaps = [export.to_json(include_timeline=False)]
        with self._cond:
            live = [w for w in self._workers if w.state == "ready"]
        for w in live:
            st = self._worker_call(w, "metrics", timeout=timeout)
            if st and "metrics" in st:
                snaps.append(st["metrics"])
        return export.merge_json_snapshots(snaps)

    # -- HTTP --------------------------------------------------------------
    def start_http(self, port: int = 0, host: str = "127.0.0.1") -> int:
        """Fleet observability endpoint: ``GET /metrics`` (router
        process, Prometheus text), ``GET /health.json`` (per-replica
        states), ``GET /fleet.json`` (health + merged fleet registry).
        port=0 picks a free port; returns the bound port."""
        if self._http is not None:
            return self._http.server_address[1]
        import json as _json
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        from ..observability import export

        router = self

        class _Handler(BaseHTTPRequestHandler):
            def do_GET(h):  # noqa: N805 — BaseHTTPRequestHandler idiom
                path = h.path.split("?", 1)[0]
                if path == "/metrics":
                    body = export.to_prometheus().encode("utf-8")
                    ctype = "text/plain; version=0.0.4; charset=utf-8"
                elif path == "/health.json":
                    body = _json.dumps(router.health(),
                                       indent=2).encode("utf-8")
                    ctype = "application/json"
                elif path == "/fleet.json":
                    body = _json.dumps(
                        {"health": router.health(),
                         "metrics": router.fleet_metrics()},
                        indent=2, sort_keys=True).encode("utf-8")
                    ctype = "application/json"
                else:
                    h.send_response(404)
                    h.end_headers()
                    return
                h.send_response(200)
                h.send_header("Content-Type", ctype)
                h.send_header("Content-Length", str(len(body)))
                h.end_headers()
                h.wfile.write(body)

            def log_message(self, *args):  # scrape spam stays off stderr
                pass

        self._http = ThreadingHTTPServer((host, port), _Handler)
        self._http_thread = threading.Thread(
            target=self._http.serve_forever, daemon=True,
            name="ptpu-router-http")
        self._http_thread.start()
        return self._http.server_address[1]

    def stop_http(self):
        if self._http is None:
            return
        self._http.shutdown()
        self._http.server_close()
        if self._http_thread is not None:
            self._http_thread.join(timeout=5)
            self._http_thread = None
        self._http = None
