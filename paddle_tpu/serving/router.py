"""Router: one front door over N PredictorServer replicas.

Requests enter exactly as they do for the single-process
``PredictorServer`` — zero-copy binary frames on the C++ bounded
channel (``ptrt_chan_recv_batch`` behind ``Channel.recv_batch``) — and
a dispatch loop forwards each frame VERBATIM to a worker process over
its pipe. Policy:

- **SLO classes + priority dispatch**: a request may carry an SLO class
  (priority + optional deadline, ``serving/slo.py``) in its wire frame
  (``wire.pack_slo``); the dispatch loop holds drained frames in a
  strict-priority queue (lower priority number first, FIFO within a
  class), so interactive traffic overtakes batch traffic at the moment
  of dispatch, not merely on average.
- **bounded-latency load shedding**: a queued request that can no
  longer meet its deadline — it expired while waiting, or its remaining
  budget is below the observed (EWMA) dispatch-to-response time — is
  REJECTED immediately with a structured ``RejectedError`` carrying
  queue-depth context, never left to time out
  (``paddle_tpu_fleet_shed_total{class=...}``). Requests without a
  deadline are never shed.
- **least outstanding work**: each frame goes to the routable replica
  with the fewest unanswered requests (outstanding map, not a counter —
  the map also holds the frame bytes so a dead worker's in-flight
  requests can be re-dispatched).
- **sticky per-program-version routing**: a replica is routable only
  while its reported program version matches the fleet's ACTIVE
  version. During a model load/hot swap a restarted worker that comes
  up on a different version receives no traffic until
  ``set_version()`` flips the fleet — so a client can never get version
  N and N+1 rows interleaved from one logical model
  (``paddle_tpu_fleet_misversioned_total`` counts violations; it must
  stay 0).
- **backpressure**: when every routable replica is at
  ``max_outstanding`` the dispatch loop parks (counted in
  ``paddle_tpu_fleet_backpressure_ms_total``); the front channel then
  fills and ``submit()`` blocks — bounded memory end to end, no
  unbounded queue anywhere.

Lifecycle: ``drain_restart(i)`` marks one replica unroutable, waits for
its outstanding responses, stops it gracefully (the worker's
``server.stop()`` flushes its stacking queue — zero drops), respawns
(retrying a replacement that dies during boot, ``spawn_retries``), and
waits ready. A worker that DIES instead of draining has its in-flight
frames re-dispatched to the survivors (predict is stateless, replay is
safe; ``paddle_tpu_fleet_requeued_total``). The fleet also resizes
live: ``add_replica()`` grows it (warm AOT cache makes the spawn
cheap), ``remove_replica()`` drain-shrinks with the same zero-drop
contract, ``reap_dead()`` clears crashed replicas — the knobs
``serving/autoscale.py`` turns.

Observability: the router process records request latency under
``path="router"`` plus the fleet gauges/counters; ``health()`` is the
per-replica view, ``fleet_metrics()`` pulls every worker's registry
snapshot over the control pipe and merges them
(``observability.export.merge_json_snapshots``); ``start_http()``
serves ``/metrics`` (router process), ``/fleet.json`` (health +
aggregated fleet registry) and ``/health.json``.
"""
from __future__ import annotations

import heapq
import os
import pickle
import queue
import struct
import threading
import time
from typing import Dict, List, Optional

from .. import observability as obs
from ..inference import _Future, _encode_sample
from ..observability import tracing as _tracing
from ..runtime import recordio as _rio
from . import slo as _slo

__all__ = ["Router"]


class _Req:
    """One drained request in the dispatch loop: the raw (possibly
    SLO-prefixed) bytes for crash-requeue, the inner frame the worker
    receives (still trace-prefixed for a sampled request — the id must
    cross the process boundary), and the resolved SLO/trace fields.
    ``t0`` is the wall-clock parse time the queue span measures from."""

    __slots__ = ("rid", "raw", "inner", "klass", "priority", "deadline",
                 "trace_id", "t0")

    def __init__(self, rid, raw, inner, klass, priority, deadline,
                 trace_id=None, t0=0.0):
        self.rid = rid
        self.raw = raw
        self.inner = inner
        self.klass = klass
        self.priority = priority
        self.deadline = deadline
        self.trace_id = trace_id
        self.t0 = t0


class _Worker:
    """Router-side handle for one replica process."""

    __slots__ = (
        "idx", "name", "proc", "conn", "state", "version", "pid",
        "metrics_port", "outstanding", "dispatched", "reader",
        "ready_ev", "stopped_ev", "status_q", "send_lock", "error",
        "last_hb", "hb_served", "last_progress", "ctrl_lock",
    )

    def __init__(self, idx: int, name: str):
        self.idx = idx
        self.name = name
        self.proc = None
        self.conn = None
        self.state = "starting"
        self.version = None
        self.pid = None
        self.metrics_port = 0
        # rid -> (frame bytes, version dispatched under): the frame is
        # kept so a dead worker's in-flight work is re-dispatchable
        self.outstanding: Dict[int, tuple] = {}
        self.dispatched = 0
        self.reader = None
        self.ready_ev = threading.Event()
        self.stopped_ev = threading.Event()
        self.status_q: "queue.Queue" = queue.Queue()
        self.send_lock = threading.Lock()
        self.error = None
        # liveness signals for the wedged-worker watchdog: last
        # heartbeat arrival + its served count (pipe/process liveness),
        # and the last COMPLETION (serving progress — the signal that
        # actually clears a wedge suspicion)
        self.last_hb = None
        self.hb_served = 0
        self.last_progress = time.monotonic()
        # serializes whole control ROUND TRIPS (send + reply) — the
        # status queue is uncorrelated, so two concurrent callers
        # (a /fleet.json scrape and a canary probe) would cross-read
        # each other's replies without it
        self.ctrl_lock = threading.Lock()


class Router:
    """
    router = Router(model_dir, replicas=4, max_batch=32)
    router.start()
    fut = router.submit((row,))      # same surface as PredictorServer
    outs = fut.result()
    router.drain_restart(0)          # zero dropped requests
    router.stop()
    """

    def __init__(self, model_dir: str, replicas: int = 2,
                 max_batch: int = 8, max_wait_ms: float = 0.0,
                 in_flight: int = 2, shard: int = 1,
                 capacity: int = 1024,
                 max_outstanding: Optional[int] = None,
                 start_method: Optional[str] = None,
                 jax_platform: Optional[str] = None,
                 worker_env: Optional[Dict[str, str]] = None,
                 worker_http: bool = False,
                 start_timeout: float = 300.0,
                 dispatch_batch: int = 64,
                 decode: bool = False,
                 decode_slots: int = 4,
                 decode_max_seq: Optional[int] = None,
                 decode_speculative: bool = False,
                 decode_spec_k: int = 4,
                 decode_draft_layers: Optional[int] = None,
                 decode_prefix_cache: bool = False,
                 max_new_tokens: int = 32,
                 strategy: Optional[str] = None,
                 slo_classes: Optional[Dict[str, "_slo.SLOClass"]] = None,
                 default_slo: str = _slo.DEFAULT_CLASS,
                 max_pending: Optional[int] = None,
                 shed_interval_ms: float = 50.0,
                 spawn_retries: int = 1,
                 version: Optional[str] = None,
                 wedge_timeout_s: Optional[float] = None,
                 heartbeat_s: float = 1.0,
                 tap_frames: int = 0):
        from ..runtime.recordio import Channel

        if replicas < 1:
            raise ValueError("replicas must be >= 1, got %d" % replicas)
        if decode and shard > 1:
            # fail HERE, not silently in the worker: the decode branch
            # builds a single-device DecodePredictor and would quietly
            # serve a tp-exported model unsharded
            raise ValueError(
                "decode mode does not support shard > 1 yet (the "
                "DecodeServer replica hosts a single-device "
                "DecodePredictor)")
        self.model_dir = model_dir
        self.replicas = int(replicas)
        self.shard = int(shard)
        self.start_timeout = float(start_timeout)
        self.dispatch_batch = int(dispatch_batch)
        self.spawn_retries = max(0, int(spawn_retries))
        # SLO surface: classes, the default for bare submits, and the
        # dispatch-queue bound. pending + channel capacity together
        # bound router-side memory: once both fill, submit() blocks
        # (the same backpressure contract as before, one queue deeper)
        self.slo_classes = dict(slo_classes if slo_classes is not None
                                else _slo.default_classes())
        if default_slo not in self.slo_classes:
            self.slo_classes[default_slo] = _slo.SLOClass(default_slo, 1)
        self.default_slo = default_slo
        self.max_pending = (int(max_pending) if max_pending
                            else int(capacity))
        self._shed_interval_s = max(0.001, float(shed_interval_ms) / 1e3)
        # EWMA of dispatch->response wall time (ms): the service-time
        # estimate behind "cannot meet its deadline" shedding. None
        # until the first response lands — until then only requests
        # whose deadline has ALREADY expired are shed.
        self._svc_ewma_ms: Optional[float] = None
        self._pending_depth = 0
        # THIS router's shed count (the Autoscaler's overload signal —
        # the obs.FLEET_SHED series is process-global, and another
        # fleet's sheds must not scale this one)
        self._shed_count = 0
        self._gauged_classes: set = set()
        # False (default): a fleet whose EVERY replica crashed fails
        # held requests fast (nothing will ever serve them). True (the
        # Autoscaler arms this when healing is on): hold them — a
        # replacement is coming, and deadline-carrying requests are
        # still bounded by the shed sweep
        self.hold_when_dead = False
        # per-replica in-flight window: enough to keep the worker's
        # stacking + device stages full (one bucket building while
        # in_flight batches queue) without hoarding requests a draining
        # neighbour could have served
        self.max_outstanding = (int(max_outstanding) if max_outstanding
                                else max(2 * max_batch * in_flight, 8))
        # wedged-worker watchdog: a replica with in-flight work and NO
        # completion for this long is presumed hung (not merely slow),
        # SIGKILLed, and its frames requeue through the crash path.
        # None (default) = off; set it ABOVE the worst-case single-batch
        # latency (a cold-bucket compile mid-traffic would otherwise be
        # reaped as a wedge)
        self.wedge_timeout_s = (float(wedge_timeout_s)
                                if wedge_timeout_s else None)
        self._watch_stop = threading.Event()
        self._watch_thread: Optional[threading.Thread] = None
        # canary tap: the last few request frames (inner form, copied)
        # so a hot swap can probe LIVE traffic through both versions
        # before flipping (serving/swap.py). Default OFF — the tap is a
        # per-request frame copy on the hot dispatch path, so only
        # fleets that will swap pay it (SwapController.enable_tap /
        # Router(tap_frames=N) arm it)
        self._tap = None
        if tap_frames:
            self.enable_tap(tap_frames)
        self._opts = {
            "model_dir": model_dir, "max_batch": int(max_batch),
            # the fleet's MODEL version label: sticky routing + the
            # misversioned check key on it. None = the program content
            # fingerprint (fine until hot swaps: two exports of one
            # architecture share a fingerprint, so swap controllers pass
            # an explicit per-export label via set_model_dir)
            "version": version,
            "heartbeat_s": float(heartbeat_s),
            # swap.worker_boot barrier gate: armed by SwapController so
            # chaos specs can target ONLY incoming-swap spawns
            "swap_boot": False,
            "max_wait_ms": float(max_wait_ms), "in_flight": int(in_flight),
            "shard": int(shard), "http": bool(worker_http),
            "jax_platform": jax_platform, "env": dict(worker_env or {}),
            # one capacity knob bounds BOTH the router's front channel
            # and each worker server's channel
            "capacity": int(capacity),
            # decode mode: replicas run the continuous-batching
            # DecodeServer (serving/decode.py) instead of the
            # PredictorServer; requests are (prompt_ids[, opts]) frames
            # and responses one generated-ids row — the router forwards
            # both verbatim, and in-flight decode SEQUENCES inherit the
            # zero-drop drain/restart + crash-requeue contracts
            # (generation is stateless from the router's view: the kept
            # frame re-prefills on a survivor)
            "decode": bool(decode),
            "decode_slots": int(decode_slots),
            "decode_max_seq": decode_max_seq,
            # PR-14 decode levers, per replica: draft-verify
            # speculative rounds and the refcounted shared-prefix KV
            # store (each worker process holds its own store; the
            # router's sticky dispatch keeps repeat prompts warm)
            "decode_speculative": bool(decode_speculative),
            "decode_spec_k": int(decode_spec_k),
            "decode_draft_layers": decode_draft_layers,
            "decode_prefix_cache": bool(decode_prefix_cache),
            "max_new_tokens": int(max_new_tokens),
            "strategy": strategy,
        }
        import multiprocessing as mp

        if start_method is None:
            # fork from a jax-threaded parent deadlocks children (PR-3
            # DataLoader lesson); forkserver keeps respawn cheap
            start_method = ("forkserver"
                            if "forkserver" in mp.get_all_start_methods()
                            else "spawn")
        self._ctx = mp.get_context(start_method)
        self._chan = Channel(capacity)
        self._workers: List[_Worker] = []
        # monotone name source: replica names stay unique through
        # add/remove cycles (drain_restart reuses its replica's name)
        self._name_seq = self.replicas - 1
        self._futures: Dict[int, _Future] = {}
        self._next_id = 0
        self._lock = threading.Lock()          # futures + rid allocation
        self._cond = threading.Condition()     # worker states/capacity
        self.active_version: Optional[str] = None
        self._dispatch_thread: Optional[threading.Thread] = None
        self._stopping = False
        self._http = None
        self._http_thread = None

    # -- lifecycle ---------------------------------------------------------
    def start(self):
        if self._dispatch_thread is not None:
            return
        for i in range(self.replicas):
            self._workers.append(self._spawn(i))
        self._wait_ready(self._workers)
        with self._cond:
            if self.active_version is None:
                self.active_version = self._workers[0].version
        self._refresh_worker_gauge()
        self._dispatch_thread = threading.Thread(
            target=self._dispatch_loop, daemon=True,
            name="ptpu-router-dispatch")
        self._dispatch_thread.start()
        if self.wedge_timeout_s and self._watch_thread is None:
            self._watch_stop.clear()
            self._watch_thread = threading.Thread(
                target=self._watchdog_loop, daemon=True,
                name="ptpu-router-watchdog")
            self._watch_thread.start()

    def _spawn(self, idx: int, name: Optional[str] = None) -> _Worker:
        from .worker import worker_main

        w = _Worker(idx, name or "replica%d" % idx)
        parent, child = self._ctx.Pipe(duplex=True)
        opts = dict(self._opts, name=w.name)
        w.proc = self._ctx.Process(
            target=worker_main, args=(child, opts), daemon=True,
            name="ptpu-" + w.name)
        w.proc.start()
        child.close()
        w.conn = parent
        w.reader = threading.Thread(
            target=self._reader_loop, args=(w,), daemon=True,
            name="ptpu-router-read-" + w.name)
        w.reader.start()
        return w

    def _wait_ready(self, workers, timeout: Optional[float] = None,
                    abort_scope=None):
        """Wait for every worker in `workers` to report ready. On
        failure, terminate ONLY the workers in `abort_scope` (default:
        the ones being waited on) — a failed drain_restart respawn must
        never take down the healthy replicas still serving traffic."""
        scope = workers if abort_scope is None else abort_scope
        # the message must name the budget actually enforced: a per-call
        # timeout (e.g. drain_restart's remaining deadline) can be much
        # shorter than start_timeout, and naming the wrong one sends the
        # operator tuning the wrong knob
        effective = timeout if timeout is not None else self.start_timeout
        deadline = time.monotonic() + effective
        for w in workers:
            # poll so a worker that DIES during bootstrap (bad model
            # dir, spawn outside a __main__ guard, import crash) fails
            # the start immediately instead of eating the full timeout
            while not w.ready_ev.wait(0.25):
                if time.monotonic() >= deadline:
                    self._abort_workers(scope)
                    raise RuntimeError(
                        "fleet worker %s did not become ready within "
                        "%.1fs%s" % (w.name, effective,
                                     "" if effective == self.start_timeout
                                     else " (per-call deadline; "
                                     "start_timeout is %.0fs)"
                                     % self.start_timeout))
                if w.proc is not None and not w.proc.is_alive():
                    self._abort_workers(scope)
                    raise RuntimeError(
                        "fleet worker %s died during startup (exitcode "
                        "%s)%s" % (w.name, w.proc.exitcode,
                                   ": " + w.error if w.error else ""))
            if w.error is not None:
                err = w.error
                self._abort_workers(scope)
                raise RuntimeError(
                    "fleet worker %s failed to start: %s" % (w.name, err))

    def _abort_workers(self, workers):
        for w in workers:
            try:
                if w.proc is not None and w.proc.is_alive():
                    w.proc.terminate()
                    w.proc.join(timeout=5)
            except Exception:
                pass
        self._refresh_worker_gauge()

    # -- submission --------------------------------------------------------
    def submit(self, sample, slo: Optional[str] = None,
               deadline_ms: Optional[float] = None,
               priority: Optional[int] = None) -> _Future:
        """sample: one array per feed slot (a single row, no batch dim)
        — identical contract to ``PredictorServer.submit``, same wire
        frame (``inference._encode_sample``).

        ``slo`` names a class from ``slo_classes`` (priority + default
        deadline); ``deadline_ms``/``priority`` override per call. A
        request with a deadline may be SHED: its future then raises
        ``serving.RejectedError`` (an explicit structured answer, never
        a timeout). Bare submits resolve to the default class with no
        deadline — wire-compatible with the pre-SLO form and never
        shed."""
        annotated = (slo is not None or deadline_ms is not None
                     or priority is not None)
        klass = self.slo_classes.get(slo if slo is not None
                                     else self.default_slo)
        if klass is None:
            raise ValueError(
                "unknown SLO class %r (configured: %s)"
                % (slo, ", ".join(sorted(self.slo_classes))))
        prio = klass.priority if priority is None else int(priority)
        if deadline_ms is None:
            deadline_ms = klass.deadline_ms
            annotated = annotated or deadline_ms is not None
        fut = _Future()
        fut._t0 = time.perf_counter()
        with self._lock:
            rid = self._next_id
            self._next_id += 1
            self._futures[rid] = fut
        fut._bind(self, rid)
        if deadline_ms is not None and deadline_ms <= 0:
            # already unmeetable at admission: the explicit reject, now
            self._pop(rid)
            with self._lock:
                self._shed_count += 1
            obs.FLEET_SHED.inc(**{"class": klass.name})
            fut.set_exception(_slo.rejected(
                klass.name, prio, "expired", float(deadline_ms),
                self._pending_depth + self._chan.qsize(),
                sum(len(w.outstanding) for w in self._workers)))
            return fut
        try:
            frame = _encode_sample(rid, sample)
            tid = _tracing.maybe_start()
            if tid is not None or annotated:
                from . import wire
            if tid is not None:
                # the ONE sampling decision: from here the id rides the
                # wire (and any crash-requeue) with the request
                frame = wire.pack_trace(frame, tid)
                _tracing.record_span(tid, "client.submit", rid=rid,
                                     klass=klass.name)
            if annotated:
                deadline = (None if deadline_ms is None
                            else time.monotonic() + deadline_ms / 1e3)
                frame = wire.pack_slo(frame, prio, deadline, klass.name)
            sent = self._chan.send(frame)
        except BaseException:
            with self._lock:
                self._futures.pop(rid, None)
            raise
        if not sent:
            with self._lock:
                self._futures.pop(rid, None)
            raise RuntimeError("serving fleet is stopped")
        return fut

    def _pop(self, rid):  # _Future.cancel protocol (same as the server)
        with self._lock:
            return self._futures.pop(rid, None)

    # -- dispatch ----------------------------------------------------------
    def _parse_request(self, msg) -> _Req:
        from . import wire

        prio, deadline, klass, inner = wire.read_slo(msg)
        if prio is None:  # bare pre-SLO frame: default class, no deadline
            klass = self.default_slo
            prio = self.slo_classes[klass].priority
        # the trace header (if any) STAYS on `inner` — the worker needs
        # the id; `bare` is only for the rid peek and the canary tap
        tid, bare = wire.read_trace(inner)
        req = _Req(_rio.frame_tag(bare), msg, inner, klass, prio,
                   deadline, trace_id=tid, t0=time.time())
        # tap AFTER the frame validated (frame_tag raised otherwise): a
        # malformed frame must never poison the canary probe set
        if self._tap is not None:
            self._tap.append(bytes(bare))
        return req

    def _reject_malformed(self, msg, exc):
        """A frame the dispatch loop cannot parse (fuzzed bytes on the
        channel) must not kill the loop: count it and drop it. There is
        no future to reject — ``submit()`` always encodes valid frames,
        and a _parse_request failure means the tag itself was
        unrecoverable (a torn SLO header hides the inner frame; a bare
        frame's failed tag peek fails identically on retry), so a torn
        channel frame is injected/corrupt bytes, not client work."""
        obs.PREDICT_FAILURES.inc(path="router_decode")

    def _dispatch_loop(self):
        """Drain the front channel into a strict-priority pending queue
        (lower priority number first, FIFO within a class via ``seq``),
        shed queued requests whose deadline can no longer be met, and
        assign the rest least-outstanding. Each worker's burst ships as
        ONE coalesced pipe message — at load the pipe hop is per-burst,
        not per-request. Bounded memory end to end: pending is capped at
        ``max_pending``, behind it the channel (``capacity``) fills, and
        behind THAT ``submit()`` blocks."""
        from . import wire

        pending: list = []  # heap of (priority, seq, _Req)
        seq = 0
        closed = False
        while True:
            if not closed and len(pending) < self.max_pending:
                # block for the first frame only while nothing is
                # queued: with deadlines pending the loop must keep
                # sweeping, so the drain is the non-blocking form
                batch = self._chan.recv_batch(
                    self.dispatch_batch, 0 if pending else None)
                if batch is None:
                    closed = True
                else:
                    for msg in batch:
                        try:
                            req = self._parse_request(msg)
                        except Exception as e:
                            self._reject_malformed(msg, e)
                            continue
                        heapq.heappush(pending, (req.priority, seq, req))
                        seq += 1
            if pending:
                pending = self._shed_sweep(pending)
            self._update_pending_gauges(pending)
            progressed = False
            groups: Dict[str, tuple] = {}
            while pending:
                req = pending[0][2]
                w = self._assign(req, block=False)
                if w is None:
                    break  # nothing routable: park below, keep sweeping
                heapq.heappop(pending)
                progressed = True
                if w is False:
                    continue  # failed (fleet dead/stopping), future set
                groups.setdefault(w.name, (w, []))[1].append(req.inner)
            if progressed:
                # re-read AFTER assignment too: an idle fleet must gauge
                # pending 0, not the depth of the batch it just drained
                self._update_pending_gauges(pending)
            if groups:
                self._flush_groups(wire, groups)
            if closed and not pending:
                return
            if pending and not progressed and not closed:
                # saturated (or mid-restart): park briefly — capacity
                # frees notify _cond, and the bounded wait keeps the
                # deadline sweep live so queued requests are shed the
                # moment they become hopeless, never left to time out
                t0 = time.perf_counter()
                with self._cond:
                    if not self._eligible():
                        self._cond.wait(self._shed_interval_s)
                obs.FLEET_BACKPRESSURE_MS.inc(
                    (time.perf_counter() - t0) * 1e3)
            elif closed and pending:
                # stop(): everything already accepted still goes out —
                # blocking assigns, with the shed check before each so
                # a deadline that lapsed during the drain still gets
                # its explicit reject
                while pending:
                    _p, _s, req = heapq.heappop(pending)
                    self._update_pending_gauges(pending)
                    if (req.deadline is not None
                            and time.monotonic() >= req.deadline):
                        self._shed(req, "expired")
                        continue
                    w = self._assign(req, block=True)
                    if w in (None, False):
                        continue
                    self._send_to(w, req.inner)
                return

    def _flush_groups(self, wire, groups):
        for w, msgs in groups.values():
            self._send_to(w, wire.pack(msgs))

    # -- shedding ----------------------------------------------------------
    def _shed_sweep(self, pending: list) -> list:
        """Reject every queued request that can no longer meet its
        deadline: expired outright, or remaining budget below the
        observed dispatch-to-response time (shedding NOW is strictly
        better than a guaranteed timeout later). Returns the surviving
        heap; untouched when nothing sheds (the common case)."""
        now = time.monotonic()
        est = self._svc_ewma_ms
        # the estimate only updates on COMPLETIONS: with nothing in
        # flight it cannot self-correct, so an idle fleet never sheds
        # on it — the request dispatches immediately and its completion
        # re-seeds the estimate. (Otherwise one pathological cold-start
        # latency could freeze the oracle above every deadline and the
        # fleet would reject 100% of traffic forever.)
        if est is not None and not any(w.outstanding
                                       for w in self._workers):
            est = None
        shed = None
        for item in pending:
            req = item[2]
            if req.deadline is None:
                continue
            remaining_ms = (req.deadline - now) * 1e3
            if remaining_ms <= 0:
                shed = shed or []
                shed.append((item, "expired"))
            elif est is not None and remaining_ms < est:
                shed = shed or []
                shed.append((item, "hopeless"))
        if not shed:
            return pending
        doomed = {id(item) for item, _r in shed}
        kept = [item for item in pending if id(item) not in doomed]
        heapq.heapify(kept)
        for item, reason in shed:
            self._shed(item[2], reason)
        return kept

    def _shed(self, req: _Req, reason: str):
        with self._lock:
            self._shed_count += 1
        obs.FLEET_SHED.inc(**{"class": req.klass})
        if req.trace_id is not None:
            # a shed request never dispatched: its whole life was the
            # queue phase. The dominant phase of the DECISION differs —
            # "hopeless" sheds fire because the service estimate eats
            # the remaining budget, not because queueing already did.
            queued_ms = max(0.0, (time.time() - req.t0) * 1e3)
            est = self._svc_ewma_ms
            dominant = ("service" if reason == "hopeless"
                        and est is not None and est > queued_ms
                        else "queue")
            _tracing.record_span(req.trace_id, "router.shed", ts=req.t0,
                                 dur_ms=queued_ms, rid=req.rid,
                                 reason=reason, dominant_phase=dominant)
            obs.REQUEST_PHASE_MS.observe(queued_ms, phase="queue")
        fut = self._pop(req.rid)
        if fut is None:
            return  # abandoned via cancel/timeout
        remaining = (None if req.deadline is None
                     else (req.deadline - time.monotonic()) * 1e3)
        with self._cond:
            outstanding = sum(len(w.outstanding) for w in self._workers)
        fut.set_exception(_slo.rejected(
            req.klass, req.priority, reason, remaining,
            self._pending_depth, outstanding))

    def _update_pending_gauges(self, pending: list):
        self._pending_depth = len(pending)
        counts: Dict[str, int] = {}
        for _p, _s, req in pending:
            counts[req.klass] = counts.get(req.klass, 0) + 1
        for k in self._gauged_classes | set(counts):
            obs.FLEET_PENDING.set(counts.get(k, 0), **{"class": k})
        self._gauged_classes |= set(counts)

    def _send_to(self, w: _Worker, payload):
        try:
            with w.send_lock:
                w.conn.send_bytes(payload)
        except (OSError, ValueError):
            # worker died between assignment and send: the reader thread
            # notices the dead pipe and requeues its outstanding frames
            pass

    def _eligible(self):
        """Routable replicas: ready, on the active version, with
        in-flight headroom."""
        return [w for w in self._workers
                if w.state == "ready" and w.version == self.active_version
                and len(w.outstanding) < self.max_outstanding]

    def _alive(self):
        return [w for w in self._workers
                if w.state in ("starting", "ready", "draining")]

    def _assign(self, req: _Req, block: bool):
        """Record `req` against the least-outstanding routable replica.
        Returns the worker, None when nothing is routable and
        ``block=False`` (caller parks and retries), or False when the
        request had to be FAILED (fleet stopping / all dead)."""
        t0 = time.perf_counter()
        waited = False
        with self._cond:
            while True:
                elig = self._eligible()
                if elig:
                    break
                # park while saturated or mid-restart; give up only when
                # the fleet is stopping or EVERY replica crashed (a
                # gracefully "stopped" replica means a restart is in
                # flight, an EMPTY list means the autoscaler is mid-heal,
                # and hold_when_dead means a healer is attached — hold
                # the request, don't fail it)
                if self._stopping or (
                        not self._alive() and self._workers
                        and not self.hold_when_dead
                        and all(w.state == "dead" for w in self._workers)):
                    fut = self._pop(req.rid)
                    if fut is not None:
                        fut.set_exception(RuntimeError(
                            "no serving replica available for request %d"
                            % req.rid))
                        obs.PREDICT_FAILURES.inc(path="router")
                    return False
                if not block:
                    return None
                waited = True
                self._cond.wait(0.5)
            # least outstanding work
            w = min(elig, key=lambda w: len(w.outstanding))
            w.outstanding[req.rid] = (req, self.active_version,
                                      time.perf_counter())
            w.dispatched += 1
            obs.FLEET_OUTSTANDING.set(len(w.outstanding), replica=w.name)
        if waited:
            obs.FLEET_BACKPRESSURE_MS.inc(
                (time.perf_counter() - t0) * 1e3)
        obs.FLEET_DISPATCHES.inc(replica=w.name)
        if req.trace_id is not None:
            now = time.time()
            queued_ms = max(0.0, (now - req.t0) * 1e3)
            _tracing.record_span(req.trace_id, "router.queue",
                                 ts=req.t0, dur_ms=queued_ms,
                                 rid=req.rid, klass=req.klass)
            _tracing.record_span(req.trace_id, "router.dispatch", ts=now,
                                 rid=req.rid, replica=w.name)
            obs.REQUEST_PHASE_MS.observe(queued_ms, phase="queue")
        return w

    # -- responses ---------------------------------------------------------
    def _reader_loop(self, w: _Worker):
        from . import wire

        while True:
            try:
                payload = w.conn.recv_bytes()
            except (EOFError, OSError):
                break
            try:
                msgs = list(wire.iter_messages(payload))
            except wire.WireError:
                # a torn multi-message must not kill the reader thread
                # (that would strand every outstanding response AND skip
                # the requeue below); count and wait for the next payload
                obs.PREDICT_FAILURES.inc(path="router_decode")
                continue
            for msg in msgs:
                try:
                    kind = bytes(msg[:1])
                    if kind == b"S":
                        self._on_status(w, pickle.loads(msg[1:]))
                    elif kind == b"R":
                        vlen = struct.unpack_from("<B", msg, 1)[0]
                        version = bytes(msg[2:2 + vlen]).decode("ascii")
                        frame = msg[2 + vlen:]
                        self._complete(w, _rio.frame_tag(frame),
                                       frame=frame, version=version)
                    elif kind == b"E":
                        rid, exc = pickle.loads(msg[1:])
                        self._complete(w, rid, exc=exc)
                except Exception:
                    # one undecodable message (e.g. an exception class
                    # that fails to reconstruct on unpickle) must not
                    # kill the reader thread — that would strand every
                    # other outstanding response AND skip the
                    # _on_worker_exit requeue below. Count it and keep
                    # reading; the affected rid's future is eventually
                    # abandoned by its caller's timeout.
                    obs.PREDICT_FAILURES.inc(path="router_decode")
        self._on_worker_exit(w)

    def _on_status(self, w: _Worker, st: Dict):
        if st.get("ready"):
            with self._cond:
                w.version = st.get("version")
                w.pid = st.get("pid")
                w.metrics_port = st.get("metrics_port", 0)
                w.state = "ready"
                w.last_progress = time.monotonic()
                self._cond.notify_all()
            self._refresh_worker_gauge()
            w.ready_ev.set()
        elif st.get("hb"):
            # worker-initiated heartbeat (pipe + process liveness; the
            # watchdog's wedge verdict keys on COMPLETIONS, but the
            # served count distinguishes hung from merely slow in
            # health())
            w.last_hb = time.monotonic()
            w.hb_served = int(st.get("served", w.hb_served))
        elif "error" in st and not w.ready_ev.is_set():
            w.error = st.get("error")
            if st.get("traceback"):
                w.error += "\n" + st["traceback"]
            with self._cond:
                w.state = "dead"
                self._cond.notify_all()
            w.ready_ev.set()
        elif st.get("stopped"):
            w.stopped_ev.set()
        else:  # pong / metrics replies
            w.status_q.put(st)

    def _complete(self, w: _Worker, rid, frame=None, version=None,
                  exc=None):
        with self._cond:
            entry = w.outstanding.pop(rid, None)
            obs.FLEET_OUTSTANDING.set(len(w.outstanding), replica=w.name)
            w.last_progress = time.monotonic()  # watchdog: not wedged
            self._cond.notify_all()  # capacity freed / drain progressed
        if entry is not None and exc is None:
            # dispatch->response wall time feeds the shedding oracle:
            # deliberately includes the worker-side queue (that IS the
            # latency a newly dispatched request would see right now)
            svc_ms = (time.perf_counter() - entry[2]) * 1e3
            prev = self._svc_ewma_ms
            self._svc_ewma_ms = (svc_ms if prev is None
                                 else 0.8 * prev + 0.2 * svc_ms)
        if entry is not None and entry[0].trace_id is not None:
            svc_ms_t = (time.perf_counter() - entry[2]) * 1e3
            _tracing.record_span(entry[0].trace_id, "router.reply",
                                 dur_ms=svc_ms_t, rid=rid,
                                 replica=w.name, error=exc is not None)
            obs.REQUEST_PHASE_MS.observe(svc_ms_t, phase="service")
        fut = self._pop(rid)
        if fut is None:
            return  # abandoned via cancel/timeout
        if exc is not None:
            obs.PREDICT_FAILURES.inc(path="router")
            fut.set_exception(exc)
            obs.PREDICT_LATENCY_MS.observe(
                (time.perf_counter() - fut._t0) * 1e3, path="router")
            return
        if (entry is not None and version is not None
                and entry[1] is not None and version != entry[1]):
            # a replica answered with a different program version than
            # the one this request was routed under — sticky routing
            # makes this structurally impossible; count loudly if a bug
            # ever breaks that
            obs.FLEET_MISVERSIONED.inc()
        _tag, rows = _rio.decode_frame(frame)
        # version attribution: the hot-swap acceptance contract verifies
        # every served row against the direct predictor of the version
        # that served it — the response already carries it, expose it
        fut._version = version
        fut.set_result(rows)
        obs.PREDICT_LATENCY_MS.observe(
            (time.perf_counter() - fut._t0) * 1e3, path="router")
        obs.PREDICT_REQUESTS.inc(path="router")
        if entry is not None and entry[0].trace_id is not None:
            obs.REQUEST_PHASE_MS.observe(
                (time.perf_counter() - fut._t0) * 1e3, phase="total")

    def _on_worker_exit(self, w: _Worker):
        """Reader saw EOF: graceful stop keeps state, a crash requeues
        the worker's in-flight frames onto the survivors."""
        with self._cond:
            crashed = not w.stopped_ev.is_set() and w.state != "stopped"
            entries = list(w.outstanding.items())
            w.outstanding.clear()
            obs.FLEET_OUTSTANDING.set(0, replica=w.name)
            w.state = "dead" if crashed else "stopped"
            self._cond.notify_all()
        self._refresh_worker_gauge()
        self._requeue_entries(w, entries)

    def _requeue_entries(self, w: _Worker, entries):
        for rid, (req, _ver, _t) in entries:
            obs.FLEET_REQUEUED.inc()
            if req.trace_id is not None:
                # req.raw still carries the trace header: the re-parsed
                # request stays traced and the merged waterfall shows
                # the crash as requeue -> second queue/dispatch pair
                _tracing.record_span(req.trace_id, "router.requeue",
                                     rid=rid, replica=w.name)
            # back through the front channel, SLO header and all: the
            # dispatch loop re-routes to a live replica (predict is
            # stateless — replay is safe) and a deadline that lapsed
            # during the crash still gets its explicit reject
            if not self._chan.send(req.raw):
                fut = self._pop(rid)
                if fut is not None:
                    fut.set_exception(RuntimeError(
                        "replica %s died and the fleet is stopping"
                        % w.name))
                    obs.PREDICT_FAILURES.inc(path="router")

    # -- wedged-worker watchdog --------------------------------------------
    def _watchdog_loop(self):
        period = min(0.25, self.wedge_timeout_s / 4)
        while not self._watch_stop.wait(period):
            self._wedge_sweep()

    def _wedge_sweep(self) -> List[str]:
        """Reap live-but-HUNG replicas: in-flight work whose oldest
        dispatch AND the replica's last completion are both older than
        ``wedge_timeout_s``. ``reap_dead`` only catches dead PIDs — a
        worker stuck in a device dispatch (or a fault-DELAY barrier)
        keeps its PID and its pipe while serving nothing, starving every
        frame routed to it. The reap is a SIGKILL: the reader thread
        then sees EOF and requeues the in-flight frames exactly like a
        crash (``paddle_tpu_fleet_requeued_total``), and
        ``reap_dead()``/the autoscaler heal the fleet. Returns the
        replica names wedged by THIS sweep."""
        if not self.wedge_timeout_s:
            return []
        timeout = self.wedge_timeout_s
        now_p = time.perf_counter()
        now_m = time.monotonic()
        wedged = []
        with self._cond:
            for w in self._workers:
                if w.state not in ("ready", "draining") or not w.outstanding:
                    continue
                oldest = min(t for _req, _v, t in w.outstanding.values())
                if (now_p - oldest) <= timeout:
                    continue
                if (now_m - w.last_progress) <= timeout:
                    continue
                # mark INSIDE the verdict lock: the kill below is
                # asynchronous (the reader's EOF handling finishes the
                # reap), and until it does the next sweep must not
                # re-judge — and re-count — the same wedge
                w.state = "wedged"
                wedged.append(w)
        names = []
        for w in wedged:
            obs.FLEET_WEDGED.inc()
            names.append(w.name)
            if w.proc is not None and w.proc.is_alive():
                # SIGKILL -> reader EOF -> crash path marks it dead and
                # requeues (one code path for crashed AND wedged)
                w.proc.kill()
            else:
                # no process behind the handle (already-dead pid raced
                # the sweep, or a fabricated handle in the metrics
                # smoke): run the crash path directly
                with self._cond:
                    entries = list(w.outstanding.items())
                    w.outstanding.clear()
                    obs.FLEET_OUTSTANDING.set(0, replica=w.name)
                    w.state = "dead"
                    self._cond.notify_all()
                self._refresh_worker_gauge()
                self._requeue_entries(w, entries)
        return names

    # -- fleet operations --------------------------------------------------
    def enable_tap(self, frames: int = 32):
        """Start keeping the last ``frames`` request frames for canary
        probes (a per-request frame copy on the dispatch path — armed
        by SwapController, or up front via Router(tap_frames=N))."""
        import collections

        if self._tap is None or self._tap.maxlen != int(frames):
            self._tap = collections.deque(self._tap or (),
                                          maxlen=int(frames))

    def set_model_dir(self, model_dir: str, version: Optional[str] = None):
        """Point FUTURE spawns (add_replica / drain_restart respawns) at
        a different exported model, labeled ``version`` (default: the
        dir's basename — distinct exports of one architecture share a
        program fingerprint, so routing identity needs an explicit
        label). Running replicas are untouched: this is the hot-swap
        controller's first move — new-version replicas come up UNROUTABLE
        behind the sticky active version until ``set_version`` flips."""
        if version is None:
            version = os.path.basename(os.path.normpath(model_dir))
        with self._cond:
            self.model_dir = model_dir
            self._opts["model_dir"] = model_dir
            self._opts["version"] = version
        return version

    def retire_worker(self, w: _Worker, timeout: float = 300.0) -> str:
        """Drain one replica BY HANDLE and drop it from the fleet — the
        hot-swap retire path: after a version flip the old-version
        replicas are unroutable (sticky routing) but may still hold
        in-flight work, and ``remove_replica``'s index/least-loaded
        selection cannot name them. Zero-drop: outstanding responses are
        waited out, then the worker stops gracefully (flushing its
        queue)."""
        deadline = time.monotonic() + timeout
        pending = self._drain_out(w, deadline)
        if pending:
            raise RuntimeError(
                "replica %s still has %d outstanding requests after "
                "%.0fs" % (w.name, pending, timeout))
        self._stop_worker(w, deadline)
        with self._cond:
            if w in self._workers:
                self._workers.remove(w)
            self._cond.notify_all()
        self._refresh_worker_gauge()
        return w.name

    def set_version(self, version: str):
        """Flip the fleet's active program version (hot-swap cutover):
        replicas reporting `version` become routable, everyone else
        drains naturally as their outstanding work completes."""
        with self._cond:
            self.active_version = version
            self._cond.notify_all()

    def _drain_out(self, w: _Worker, deadline: float) -> int:
        """Unroute `w` and wait out its in-flight responses. Returns
        the count still outstanding at the deadline (0 = drained)."""
        with self._cond:
            if w.state == "ready":
                w.state = "draining"
            self._cond.notify_all()
        self._refresh_worker_gauge()
        with self._cond:
            while w.outstanding and time.monotonic() < deadline:
                self._cond.wait(0.5)
            return len(w.outstanding)

    def _replace_worker(self, old: _Worker, new: _Worker):
        """Swap `old`'s fleet slot for `new` by IDENTITY: a concurrent
        remove_replica/reap_dead (the autoscaler's knobs) shifts list
        positions, so a positional write could evict a healthy
        neighbour's handle mid-restart."""
        with self._cond:
            try:
                self._workers[self._workers.index(old)] = new
            except ValueError:  # old was reaped meanwhile: still grow
                self._workers.append(new)
            self._cond.notify_all()

    def drain_restart(self, idx: int, timeout: float = 300.0):
        """Gracefully recycle one replica with ZERO dropped requests:
        unroute it, wait out its in-flight responses, stop it (the
        worker flushes its own stacking queue before exiting), respawn,
        wait ready. The rest of the fleet keeps serving throughout."""
        w = self._workers[idx]
        deadline = time.monotonic() + timeout
        pending = self._drain_out(w, deadline)
        if pending:
            raise RuntimeError(
                "replica %s still has %d outstanding requests after %.0fs"
                % (w.name, pending, timeout))
        self._stop_worker(w, deadline)
        # a replacement that dies during boot (transient: OOM, a cache
        # race, a preempted host) is retried before giving up — and a
        # failed restart NEVER takes down the survivors, which keep
        # serving throughout; on exhaustion the dead replacement stays
        # visible in health() for reap_dead()/the autoscaler to heal
        attempts = 1 + self.spawn_retries
        last_err = None
        cur = w
        for attempt in range(attempts):
            nw = self._spawn(idx, name=w.name)
            self._replace_worker(cur, nw)
            cur = nw
            try:
                self._wait_ready(
                    [nw], timeout=max(1.0, deadline - time.monotonic()))
                last_err = None
                break
            except RuntimeError as e:
                last_err = e
        if last_err is not None:
            self._refresh_worker_gauge()
            raise RuntimeError(
                "replica %s could not be respawned (%d attempt%s; the "
                "rest of the fleet keeps serving — reap_dead()/the "
                "autoscaler can replace it): %s"
                % (w.name, attempts, "s" if attempts != 1 else "",
                   last_err)) from last_err
        self._refresh_worker_gauge()
        with self._cond:
            self._cond.notify_all()

    # -- elastic fleet (the autoscaler's knobs) ----------------------------
    def add_replica(self, timeout: Optional[float] = None) -> str:
        """Grow the fleet by one replica and wait until it is ready and
        routable (the warm AOT cache makes the spawn nearly
        compile-free). Returns the new replica's name."""
        with self._cond:
            if self._stopping:
                raise RuntimeError("serving fleet is stopping")
            self._name_seq += 1
            name = "replica%d" % self._name_seq
        w = self._spawn(len(self._workers), name=name)
        # readiness is proven BEFORE the fleet list grows: a spawn that
        # dies never pollutes health()/dispatch
        self._wait_ready([w], timeout=timeout)
        with self._cond:
            # re-check: stop() may have swept the fleet while the spawn
            # booted — appending now would leak a live worker process
            # no stop will ever visit
            stopping = self._stopping
            if not stopping:
                self._workers.append(w)
                self._cond.notify_all()
        if stopping:
            self._abort_workers([w])
            raise RuntimeError("serving fleet is stopping")
        self._refresh_worker_gauge()
        return name

    def remove_replica(self, idx: Optional[int] = None,
                       timeout: float = 300.0) -> str:
        """Drain-shrink: unroute one replica (default: the least-loaded
        ready one), wait out its in-flight responses, stop it gracefully
        (the worker flushes its queue — ZERO dropped requests), and drop
        it from the fleet. Returns the removed replica's name."""
        deadline = time.monotonic() + timeout
        with self._cond:
            ready = [x for x in self._workers if x.state == "ready"]
            if idx is None:
                if len(ready) <= 1:
                    raise RuntimeError(
                        "refusing to remove the last ready replica")
                w = min(ready, key=lambda x: len(x.outstanding))
            else:
                w = self._workers[idx]
                # the guard holds on the explicit-index path too: an
                # emptied fleet wedges every later submit (nothing will
                # ever serve, and no error is coming)
                if w.state == "ready" and len(ready) <= 1:
                    raise RuntimeError(
                        "refusing to remove the last ready replica")
        pending = self._drain_out(w, deadline)
        if pending:
            with self._cond:  # put it back in service rather than leak
                if w.state == "draining":
                    w.state = "ready"
                self._cond.notify_all()
            self._refresh_worker_gauge()
            raise RuntimeError(
                "replica %s still has %d outstanding requests after "
                "%.0fs; returned to service" % (w.name, pending, timeout))
        self._stop_worker(w, deadline)
        with self._cond:
            if w in self._workers:
                self._workers.remove(w)
            self._cond.notify_all()
        self._refresh_worker_gauge()
        return w.name

    def reap_dead(self) -> List[str]:
        """Drop crashed replicas from the fleet list (their in-flight
        frames were already requeued by the reader's exit path). Returns
        the reaped names — the autoscaler heals by spawning that many
        replacements."""
        with self._cond:
            dead = [w for w in self._workers if w.state == "dead"]
            for w in dead:
                self._workers.remove(w)
            self._cond.notify_all()
        for w in dead:
            if w.proc is not None:
                w.proc.join(timeout=5)
            try:
                w.conn.close()
            except (OSError, ValueError):
                pass
        self._refresh_worker_gauge()
        return [w.name for w in dead]

    def stats(self) -> Dict:
        """The autoscaler's one-call signal snapshot: replica states,
        total in-flight work, the per-replica window, and the dispatch
        queue depth."""
        with self._cond:
            states: Dict[str, int] = {}
            for w in self._workers:
                states[w.state] = states.get(w.state, 0) + 1
            return {
                "replicas": len(self._workers),
                "ready": states.get("ready", 0),
                "starting": states.get("starting", 0),
                "draining": states.get("draining", 0),
                "dead": states.get("dead", 0),
                "outstanding": sum(len(w.outstanding)
                                   for w in self._workers),
                "max_outstanding": self.max_outstanding,
                "pending": self._pending_depth,
                "queued": self._chan.qsize(),
                "shed": self._shed_count,
            }

    def _stop_worker(self, w: _Worker, deadline=None):
        if w.proc is not None and not w.proc.is_alive():
            # already dead (crashed replica, failed respawn): there is
            # no "stopped" status to wait for — reap without eating the
            # drain deadline
            w.proc.join(timeout=5)
            with self._cond:
                self._cond.notify_all()
            if w.reader is not None:
                w.reader.join(timeout=5)
            return
        try:
            with w.send_lock:
                w.conn.send_bytes(b"C" + pickle.dumps({"cmd": "stop"},
                                                      protocol=4))
        except (OSError, ValueError):
            pass
        remaining = (max(1.0, deadline - time.monotonic())
                     if deadline else 30.0)
        w.stopped_ev.wait(remaining)
        if w.proc is not None:
            w.proc.join(timeout=remaining)
            if w.proc.is_alive():
                w.proc.terminate()
                w.proc.join(timeout=5)
        with self._cond:
            if w.state != "dead":
                w.state = "stopped"
            self._cond.notify_all()
        if w.reader is not None:
            w.reader.join(timeout=5)

    def stop(self):
        """Drain the front channel through the fleet, then stop every
        replica gracefully (flushing their queues) and reap processes."""
        self.stop_http()
        self._watch_stop.set()
        if self._watch_thread is not None:
            self._watch_thread.join(timeout=5)
            self._watch_thread = None
        with self._cond:
            already = self._stopping and self._dispatch_thread is None
        if already:
            return
        self._chan.close()
        if self._dispatch_thread is not None:
            # the dispatch loop finishes routing everything already
            # accepted, then sees the closed+drained channel and exits
            self._dispatch_thread.join(timeout=60)
            self._dispatch_thread = None
        # wait for in-flight responses BEFORE stopping workers: nothing
        # accepted is dropped
        with self._cond:
            deadline = time.monotonic() + 60
            while (any(w.outstanding for w in self._workers)
                   and time.monotonic() < deadline):
                self._cond.wait(0.5)
            self._stopping = True
            self._cond.notify_all()
        for w in self._workers:
            if w.state in ("ready", "draining", "starting"):
                self._stop_worker(w)
        self._refresh_worker_gauge()

    # -- introspection -----------------------------------------------------
    def _refresh_worker_gauge(self):
        counts: Dict[str, int] = {}
        for w in self._workers:
            counts[w.state] = counts.get(w.state, 0) + 1
        for state in ("starting", "ready", "draining", "wedged",
                      "stopped", "dead"):
            obs.FLEET_WORKERS.set(counts.get(state, 0), state=state)

    def health(self) -> List[Dict]:
        """Per-replica view: state, version, pid, outstanding depth,
        dispatch count, metrics port, heartbeat age + served count."""
        now = time.monotonic()
        with self._cond:
            return [{"replica": w.name, "state": w.state,
                     "version": w.version, "pid": w.pid,
                     "outstanding": len(w.outstanding),
                     "dispatched": w.dispatched,
                     "metrics_port": w.metrics_port,
                     "heartbeat_age_s": (None if w.last_hb is None
                                         else now - w.last_hb),
                     "served": w.hb_served,
                     "shard": self.shard}
                    for w in self._workers]

    def _worker_call(self, w: _Worker, cmd: str, timeout: float = 30.0,
                     **extra):
        """One control round trip (ping/metrics/probe). ``extra`` fields
        ride the command dict (e.g. the probe frame bytes). The whole
        round trip holds ``ctrl_lock`` and starts by draining stale
        replies (a previous caller that timed out leaves its late reply
        in the queue) — the status queue carries no correlation ids, so
        serialization + drain IS the correlation."""
        try:
            with w.ctrl_lock:
                while True:  # discard replies abandoned by timeouts
                    try:
                        w.status_q.get_nowait()
                    except queue.Empty:
                        break
                with w.send_lock:
                    w.conn.send_bytes(b"C" + pickle.dumps(
                        dict(extra, cmd=cmd), protocol=4))
                return w.status_q.get(timeout=timeout)
        except (OSError, ValueError, queue.Empty):
            return None

    def fleet_metrics(self, timeout: float = 30.0) -> Dict:
        """Aggregated registry across the fleet: every live worker's
        JSON snapshot (pulled over the control pipe, each labeled by its
        ``replica``) merged with the router's own via
        ``export.merge_json_snapshots``."""
        from ..observability import export

        snaps = [export.to_json(include_timeline=False)]
        with self._cond:
            live = [w for w in self._workers if w.state == "ready"]
        for w in live:
            st = self._worker_call(w, "metrics", timeout=timeout)
            if st and "metrics" in st:
                snaps.append(st["metrics"])
        return export.merge_json_snapshots(snaps)

    def fleet_trace(self, timeout: float = 30.0) -> Dict:
        """One merged span list across the fleet: every live worker's
        flight-recorder snapshot (pulled over the control pipe, the
        ``fleet_metrics`` pattern) plus the router's own, ts-sorted per
        trace so a single request reads as a waterfall
        (``tracing.merge_snapshots``). Served at ``GET /trace.json``."""
        snaps = [_tracing.snapshot()]
        with self._cond:
            live = [w for w in self._workers if w.state == "ready"]
        for w in live:
            st = self._worker_call(w, "trace", timeout=timeout)
            if st and "trace" in st:
                snaps.append(st["trace"])
        return _tracing.merge_snapshots(snaps)

    # -- HTTP --------------------------------------------------------------
    def start_http(self, port: int = 0, host: str = "127.0.0.1") -> int:
        """Fleet observability endpoint: ``GET /metrics`` (router
        process, Prometheus text), ``GET /health.json`` (per-replica
        states), ``GET /fleet.json`` (health + merged fleet registry),
        ``GET /trace.json`` (merged flight-recorder spans). port=0
        picks a free port; returns the bound port."""
        if self._http is not None:
            return self._http.server_address[1]
        import json as _json
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        from ..observability import export

        router = self

        class _Handler(BaseHTTPRequestHandler):
            def do_GET(h):  # noqa: N805 — BaseHTTPRequestHandler idiom
                path = h.path.split("?", 1)[0]
                if path == "/metrics":
                    body = export.to_prometheus().encode("utf-8")
                    ctype = "text/plain; version=0.0.4; charset=utf-8"
                elif path == "/health.json":
                    body = _json.dumps(router.health(),
                                       indent=2).encode("utf-8")
                    ctype = "application/json"
                elif path == "/fleet.json":
                    body = _json.dumps(
                        {"health": router.health(),
                         "metrics": router.fleet_metrics()},
                        indent=2, sort_keys=True).encode("utf-8")
                    ctype = "application/json"
                elif path == "/trace.json":
                    body = _json.dumps(
                        router.fleet_trace(), indent=2,
                        sort_keys=True).encode("utf-8")
                    ctype = "application/json"
                else:
                    h.send_response(404)
                    h.end_headers()
                    return
                h.send_response(200)
                h.send_header("Content-Type", ctype)
                h.send_header("Content-Length", str(len(body)))
                h.end_headers()
                h.wfile.write(body)

            def log_message(self, *args):  # scrape spam stays off stderr
                pass

        self._http = ThreadingHTTPServer((host, port), _Handler)
        self._http_thread = threading.Thread(
            target=self._http.serve_forever, daemon=True,
            name="ptpu-router-http")
        self._http_thread.start()
        return self._http.server_address[1]

    def stop_http(self):
        if self._http is None:
            return
        self._http.shutdown()
        self._http.server_close()
        if self._http_thread is not None:
            self._http_thread.join(timeout=5)
            self._http_thread = None
        self._http = None
