"""Pipe message coalescing for the router <-> worker hop.

One multiprocessing ``send_bytes`` is one syscall plus a GIL round trip
on each side; at fleet throughput the per-REQUEST pipe hop dominates
the router process. Frames travelling together are therefore packed
into one ``b"M"``-prefixed multi-message:

    b"M" | (u32 length | payload)*

``pack`` returns a lone message unwrapped (no overhead for the common
low-load case); ``iter_messages`` yields the constituent payloads of
either form, as memoryview slices over the received buffer (zero copy —
request frames decode straight out of them).
"""
from __future__ import annotations

import struct
from typing import Iterator, List, Sequence

__all__ = ["pack", "iter_messages"]

_MULTI = 0x4D  # b"M"
_LEN = struct.Struct("<I")


def pack(msgs: Sequence[bytes]) -> bytes:
    """One pipe payload carrying every message in `msgs` (order kept)."""
    if len(msgs) == 1:
        return msgs[0]
    parts: List[bytes] = [b"M"]
    for m in msgs:
        parts.append(_LEN.pack(len(m)))
        parts.append(bytes(m) if not isinstance(m, (bytes, bytearray))
                     else m)
    return b"".join(parts)


def iter_messages(payload) -> Iterator:
    """The messages inside a pipe payload (one, or a packed batch)."""
    if payload[:1] != b"M":
        yield payload
        return
    mv = memoryview(payload)
    off = 1
    end = len(mv)
    while off < end:
        (n,) = _LEN.unpack_from(mv, off)
        off += _LEN.size
        yield mv[off:off + n]
        off += n
