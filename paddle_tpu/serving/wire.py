"""Pipe message coalescing + SLO framing for the router <-> worker hop.

One multiprocessing ``send_bytes`` is one syscall plus a GIL round trip
on each side; at fleet throughput the per-REQUEST pipe hop dominates
the router process. Frames travelling together are therefore packed
into one ``b"M"``-prefixed multi-message:

    b"M" | (u32 length | payload)*

``pack`` returns a lone message unwrapped (no overhead for the common
low-load case); ``iter_messages`` yields the constituent payloads of
either form, as memoryview slices over the received buffer (zero copy —
request frames decode straight out of them).

SLO header (``pack_slo`` / ``read_slo``): a request submitted with a
priority/deadline/class carries them ON the wire frame — the request is
self-describing through the front channel and across a crash-requeue,
so the dispatch loop's priority queues and deadline shedding never need
a side table keyed by request id:

    b"Q" | u8 priority | u8 class_len | class ascii | f64 deadline | frame

``deadline`` is an absolute ``time.monotonic()`` timestamp (0.0 = no
deadline) — the header only ever travels within the router process
(submit -> channel -> dispatch; workers receive the INNER frame), so a
process-local clock is the right one. A bare (un-prefixed) frame means
default class / default priority / no deadline — the pre-SLO wire form
is still valid, byte for byte.

Trace header (``pack_trace`` / ``read_trace``): a request sampled for
distributed tracing (observability/tracing.py) carries its trace_id on
the wire the same way:

    b"T" | u8 id_len | trace_id ascii | frame

Canonical nesting when both headers ride one frame is Q(T(frame)) — the
SLO header outermost, matching the parse order the router already uses
(``read_slo`` first). Unlike the SLO header the trace header DOES cross
the process boundary to workers (that is the point — the id correlates
spans fleet-wide), and workers strip it defensively exactly like a
stray ``b"Q"``. An un-sampled request never grows a header: the
pre-trace wire form stays valid byte for byte.
"""
from __future__ import annotations

import struct
from typing import Iterator, List, Optional, Sequence, Tuple

__all__ = ["pack", "iter_messages", "pack_slo", "read_slo",
           "pack_trace", "read_trace", "WireError"]


class WireError(ValueError):
    """A malformed wire payload (truncated multi-message, torn SLO
    header). Parse paths raise THIS instead of a bare struct.error so
    the router/worker loops can give the frame a structured reject —
    never crash a serving thread, never silently misparse."""

_MULTI = 0x4D  # b"M"
_LEN = struct.Struct("<I")
_SLO = b"Q"
_SLO_HDR = struct.Struct("<BB")  # priority, class name length
_SLO_DL = struct.Struct("<d")    # absolute monotonic deadline (0 = none)
_TRACE = b"T"
_TRACE_HDR = struct.Struct("<B")  # trace_id length


def pack(msgs: Sequence[bytes]) -> bytes:
    """One pipe payload carrying every message in `msgs` (order kept)."""
    if len(msgs) == 1:
        return msgs[0]
    parts: List[bytes] = [b"M"]
    for m in msgs:
        parts.append(_LEN.pack(len(m)))
        parts.append(bytes(m) if not isinstance(m, (bytes, bytearray))
                     else m)
    return b"".join(parts)


def iter_messages(payload) -> Iterator:
    """The messages inside a pipe payload (one, or a packed batch).
    Raises ``WireError`` on a truncated/overrunning length prefix — a
    torn multi-message must surface as one structured parse error, not
    as N-1 good frames plus silent garbage."""
    if payload[:1] != b"M":
        yield payload
        return
    mv = memoryview(payload)
    off = 1
    end = len(mv)
    while off < end:
        if end - off < _LEN.size:
            raise WireError(
                "truncated multi-message: %d trailing byte(s) where a "
                "length prefix belongs" % (end - off))
        (n,) = _LEN.unpack_from(mv, off)
        off += _LEN.size
        if n > end - off:
            raise WireError(
                "truncated multi-message: length prefix says %d bytes "
                "but only %d remain" % (n, end - off))
        yield mv[off:off + n]
        off += n


def pack_slo(frame: bytes, priority: int, deadline: Optional[float],
             klass: str) -> bytes:
    """Prefix a request frame with its SLO header (see module doc)."""
    k = klass.encode("ascii")
    if len(k) > 255:
        raise ValueError("SLO class name too long: %r" % klass)
    if not 0 <= int(priority) <= 255:
        # a u8 on the wire: masking would silently INVERT dispatch
        # order (-1 -> 255 dispatches last, 256 -> 0 dispatches first)
        raise ValueError("SLO priority must be in [0, 255], got %r"
                         % (priority,))
    return (_SLO + _SLO_HDR.pack(int(priority), len(k)) + k
            + _SLO_DL.pack(float(deadline) if deadline else 0.0) + frame)


def read_slo(msg) -> Tuple[Optional[int], Optional[float], Optional[str],
                           object]:
    """``(priority, deadline, class, inner_frame)`` from a request
    message. A bare frame (no ``b"Q"`` prefix) returns
    ``(None, None, None, msg)`` — the caller applies its defaults. The
    inner frame is a zero-copy memoryview slice."""
    if bytes(msg[:1]) != _SLO:
        return None, None, None, msg
    mv = memoryview(msg)
    if len(mv) < 1 + _SLO_HDR.size:
        raise WireError(
            "truncated SLO header: %d byte(s), need at least %d"
            % (len(mv), 1 + _SLO_HDR.size))
    prio, klen = _SLO_HDR.unpack_from(mv, 1)
    off = 1 + _SLO_HDR.size
    if len(mv) < off + klen + _SLO_DL.size:
        raise WireError(
            "truncated SLO header: class+deadline need %d bytes, %d "
            "remain" % (klen + _SLO_DL.size, len(mv) - off))
    try:
        klass = bytes(mv[off:off + klen]).decode("ascii")
    except UnicodeDecodeError as e:
        raise WireError("non-ascii SLO class name: %s" % e) from e
    off += klen
    (deadline,) = _SLO_DL.unpack_from(mv, off)
    off += _SLO_DL.size
    return prio, (deadline if deadline > 0.0 else None), klass, mv[off:]


def pack_trace(frame: bytes, trace_id: str) -> bytes:
    """Prefix a request frame with its trace_id (see module doc)."""
    t = trace_id.encode("ascii")
    if not t or len(t) > 255:
        raise ValueError("trace id must be 1..255 ascii bytes, got %r"
                         % (trace_id,))
    return _TRACE + _TRACE_HDR.pack(len(t)) + t + frame


def read_trace(msg) -> Tuple[Optional[str], object]:
    """``(trace_id, inner_frame)`` from a request message. A bare frame
    (no ``b"T"`` prefix) returns ``(None, msg)`` — the request is simply
    not traced. The inner frame is a zero-copy memoryview slice."""
    if bytes(msg[:1]) != _TRACE:
        return None, msg
    mv = memoryview(msg)
    if len(mv) < 1 + _TRACE_HDR.size:
        raise WireError("truncated trace header: no id length byte")
    (tlen,) = _TRACE_HDR.unpack_from(mv, 1)
    off = 1 + _TRACE_HDR.size
    if tlen == 0 or len(mv) < off + tlen:
        raise WireError(
            "truncated trace header: id needs %d bytes, %d remain"
            % (tlen, len(mv) - off))
    try:
        trace_id = bytes(mv[off:off + tlen]).decode("ascii")
    except UnicodeDecodeError as e:
        raise WireError("non-ascii trace id: %s" % e) from e
    return trace_id, mv[off + tlen:]
