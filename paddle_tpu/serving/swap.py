"""Hot model swap: version N -> N+1 in a live fleet, zero downtime.

The online-learning loop's serving half (ROADMAP item 6): a streaming
trainer keeps exporting model versions, and the fleet must pick each one
up WITHOUT dropping a request and WITHOUT ever interleaving version-N
and version-N+1 rows to one client. All of the machinery already
exists in the Router — this module only sequences it:

1. **Load behind the running version.** ``Router.set_model_dir`` points
   future spawns at the new export; one surge replica per currently-
   ready replica boots on it (``add_replica`` — the shared persistent
   AOT cache makes the spawn nearly compile-free for a same-architecture
   export, and each worker's ``PredictorServer.start()`` pre-warms every
   padding bucket before reporting ready). Sticky per-version routing
   means the new replicas are READY but UNROUTABLE: the active version
   still owns all traffic.
2. **Canary (optional).** Up to ``canary`` recent LIVE request frames
   (the Router's tap) — or caller-provided ``canary_samples`` — are
   probed through BOTH versions via the worker control pipe. The new
   version must answer with finite, shape-compatible rows; with
   ``canary_tol`` set, max-abs logits drift beyond it is a failed
   canary. Any failure rolls the swap back.
3. **Atomic flip.** ``Router.set_version`` makes the new replicas
   routable and the old ones unroutable in one move. Requests already
   dispatched to old replicas complete under the version they were
   routed under (zero misversioned, by the same sticky contract
   drain_restart relies on); everything queued or new goes to N+1.
4. **Retire.** Old replicas drain their in-flight responses and stop
   gracefully (flushing their queues — zero drops), then leave the
   fleet.

Any failure BEFORE the flip rolls back completely: surge replicas are
destroyed, the router's spawn options are restored, and the old version
never stopped serving — ``paddle_tpu_swap_total{result="rollback"}``.
The flip is the commit point: a post-flip retire problem raises but the
swap stands (the new version is serving; the stuck old replica stays
visible in ``health()`` for ``reap_dead``/the autoscaler).

Chaos barriers (``checkpoint/faults.py``): ``swap.before_spawn``,
``swap.before_canary``, ``swap.before_flip``, ``swap.before_retire``
cross in the controller (arm DELAY/IO specs to widen windows or force a
rollback at an exact instant), and ``swap.worker_boot`` crosses inside
each INCOMING surge replica (arm KILL to SIGKILL the new version
mid-swap — the old version must keep serving, test-pinned).

``tools/swap_ctl.py`` wraps this in a watcher that polls a streaming
trainer's export root and swaps each new complete export in.
"""
from __future__ import annotations

import os
import time
from typing import Dict, List, Optional, Sequence

import numpy as np

from .. import observability as obs
from ..checkpoint.faults import fault_point

__all__ = ["SwapController", "SwapError"]


class SwapError(RuntimeError):
    """A hot swap that could not commit (validation, surge spawn,
    canary, or flip failure). ``rolled_back`` tells whether the fleet
    was restored to the old version (True for every pre-flip failure)
    or the swap COMMITTED and only the old-replica retire struggled
    (False — the new version is serving)."""

    def __init__(self, msg: str, rolled_back: bool = True):
        super().__init__(msg)
        self.rolled_back = rolled_back


class SwapController:
    """
    ctl = SwapController(router)
    ctl.swap("/models/ctr/checkpoint_42")          # flip + retire
    ctl.swap(d, canary=8, canary_tol=1e-3)         # live-parity gated
    """

    def __init__(self, router, probe_timeout: float = 120.0,
                 tap_frames: int = 32):
        self.router = router
        self.probe_timeout = float(probe_timeout)
        # arm the router's live-request tap NOW (it is off by default —
        # a per-request frame copy only swap-bound fleets should pay),
        # so traffic between controller construction and swap() builds
        # the canary probe set
        if tap_frames:
            router.enable_tap(tap_frames)

    # -- canary ------------------------------------------------------------
    def _canary_frames(self, canary: int,
                       canary_samples: Optional[Sequence]) -> List[bytes]:
        from ..inference import _encode_sample

        if canary_samples is not None:
            return [_encode_sample(0, s) for s in canary_samples]
        tap = getattr(self.router, "_tap", None)
        if not canary or tap is None:
            return []
        frames = list(tap)
        return frames[-int(canary):]

    def _probe(self, worker, frame: bytes):
        """(rows, error) from one worker probe round trip."""
        st = self.router._worker_call(worker, "probe", frame=frame,
                                      timeout=self.probe_timeout)
        if st is None:
            return None, "probe timed out / pipe lost"
        if "probe_error" in st:
            return None, st["probe_error"]
        if not isinstance(st, dict) or "probe" not in st:
            # the status queue is uncorrelated: a concurrent
            # ping/metrics reply (a /fleet.json scrape mid-swap) can be
            # cross-read here — an unrecognizable reply is a probe
            # FAILURE to report, never a None to crash on
            return None, ("unrecognizable probe reply (concurrent "
                          "control call?): %r" % (st,))
        return st["probe"], None

    def _run_canary(self, old_workers, new_workers, frames,
                    canary_tol: Optional[float]):
        """Probe each frame through one old and one new replica. The
        old side is the reference: an old-side probe failure makes that
        frame inconclusive (skipped), a NEW-side failure or a gate
        violation fails the canary. Returns the number of frames
        actually compared."""
        compared = 0
        for i, frame in enumerate(frames):
            ref, ref_err = self._probe(old_workers[i % len(old_workers)],
                                       frame)
            got, got_err = self._probe(new_workers[i % len(new_workers)],
                                       frame)
            if got_err is not None:
                raise SwapError(
                    "canary %d/%d: new version failed to answer: %s"
                    % (i + 1, len(frames), got_err))
            for o in got:
                if not np.isfinite(np.asarray(o, np.float64)).all():
                    raise SwapError(
                        "canary %d/%d: new version produced non-finite "
                        "outputs" % (i + 1, len(frames)))
            if ref_err is not None or ref is None:
                continue  # inconclusive: reference side unavailable
            if len(got) != len(ref) or any(
                    np.asarray(g).shape != np.asarray(r).shape
                    for g, r in zip(got, ref)):
                raise SwapError(
                    "canary %d/%d: output arity/shape changed: %s vs %s"
                    % (i + 1, len(frames),
                       [np.asarray(g).shape for g in got],
                       [np.asarray(r).shape for r in ref]))
            if canary_tol is not None:
                diff = max(float(np.max(np.abs(
                    np.asarray(g, np.float64) - np.asarray(r, np.float64)
                ))) if np.asarray(g).size else 0.0
                    for g, r in zip(got, ref))
                if diff > canary_tol:
                    raise SwapError(
                        "canary %d/%d: logits drifted %.3g > tol %.3g "
                        "between versions" % (i + 1, len(frames), diff,
                                              canary_tol))
            compared += 1
        return compared

    # -- the swap ----------------------------------------------------------
    def swap(self, model_dir: str, version: Optional[str] = None,
             canary: int = 0, canary_tol: Optional[float] = None,
             canary_samples: Optional[Sequence] = None,
             spawn_timeout: Optional[float] = None,
             retire_timeout: float = 300.0) -> Dict:
        """Swap the fleet onto the export at ``model_dir``. Returns
        ``{"version", "previous", "replicas", "canaried", "retired"}``
        on success; raises ``SwapError`` (with the fleet restored, see
        ``rolled_back``) otherwise."""
        router = self.router
        t_total = time.perf_counter()
        if version is None:
            version = os.path.basename(os.path.normpath(model_dir))
        # -- admission validation: cheap, before any fleet mutation ------
        try:
            fault_point("swap.before_spawn")
            if not os.path.isfile(os.path.join(model_dir, "__model__")):
                raise SwapError(
                    "swap target %r is not an exported model directory "
                    "(no __model__)" % model_dir)
            with router._cond:
                if version == router.active_version:
                    raise SwapError(
                        "fleet is already serving version %r" % version)
                old_workers = [w for w in router._workers
                               if w.state == "ready"]
            if not old_workers:
                raise SwapError("no ready replica to swap behind")
            want_canary = bool(canary or canary_samples)
            if want_canary and router._opts.get("decode"):
                raise SwapError(
                    "canary probes are a dense-predictor surface; swap "
                    "decode fleets with canary=0")
        except Exception as e:
            obs.SWAP_TOTAL.inc(result="rollback")
            obs.SWAP_MS.observe(
                (time.perf_counter() - t_total) * 1e3, phase="total")
            if isinstance(e, SwapError):
                raise
            raise SwapError("swap validation failed: %s" % e) from e

        old_dir = router.model_dir
        old_ver_opt = router._opts.get("version")
        old_active = router.active_version
        new_names: List[str] = []
        compared = 0
        try:
            router.set_model_dir(model_dir, version)
            router._opts["swap_boot"] = True
            # -- surge: one new-version replica per ready old one -------
            t0 = time.perf_counter()
            for _ in range(len(old_workers)):
                new_names.append(router.add_replica(timeout=spawn_timeout))
            obs.SWAP_MS.observe((time.perf_counter() - t0) * 1e3,
                                phase="spawn")
            with router._cond:
                new_workers = [w for w in router._workers
                               if w.name in set(new_names)]
            bad = [w.name for w in new_workers if w.version != version]
            if bad:
                raise SwapError(
                    "surge replicas %s came up on the wrong version"
                    % bad)
            # -- canary -------------------------------------------------
            fault_point("swap.before_canary")
            if want_canary:
                t0 = time.perf_counter()
                frames = self._canary_frames(canary, canary_samples)
                if not frames:
                    # a requested gate that validated NOTHING must not
                    # silently pass — no tapped traffic and no samples
                    # means the operator's canary never ran
                    raise SwapError(
                        "canary requested but there is nothing to "
                        "probe: no live request frames tapped (is the "
                        "tap enabled? has the fleet served traffic?) "
                        "and no canary_samples given")
                compared = self._run_canary(old_workers, new_workers,
                                            frames, canary_tol)
                obs.SWAP_MS.observe((time.perf_counter() - t0) * 1e3,
                                    phase="canary")
            # -- atomic flip --------------------------------------------
            fault_point("swap.before_flip")
            router.set_version(version)
        except BaseException as e:
            # rollback: the old version never stopped serving — destroy
            # the surge replicas, restore the spawn options, re-assert
            # the old active version
            router._opts["swap_boot"] = False
            router.set_model_dir(old_dir, old_ver_opt)
            router._opts["version"] = old_ver_opt  # set_model_dir defaults
            if router.active_version != old_active:
                router.set_version(old_active)
            with router._cond:
                doomed = [w for w in router._workers
                          if w.name in set(new_names)]
                for w in doomed:
                    router._workers.remove(w)
                router._cond.notify_all()
            router._abort_workers(doomed)
            obs.SWAP_TOTAL.inc(result="rollback")
            obs.SWAP_MS.observe(
                (time.perf_counter() - t_total) * 1e3, phase="total")
            if not isinstance(e, Exception):
                raise  # KeyboardInterrupt/SystemExit: rolled back, but
                # the interrupt must still stop the caller (a watcher
                # catching SwapError would otherwise swallow Ctrl-C)
            if isinstance(e, SwapError):
                raise
            raise SwapError("hot swap to %r rolled back: %s"
                            % (version, e)) from e
        finally:
            router._opts["swap_boot"] = False
        # -- committed: retire the old version --------------------------
        obs.SWAP_TOTAL.inc(result="ok")
        retired, retire_errs = [], []
        t0 = time.perf_counter()
        try:
            fault_point("swap.before_retire")
            for w in old_workers:
                try:
                    retired.append(router.retire_worker(
                        w, timeout=retire_timeout))
                except Exception as e:  # noqa: BLE001 — collected below
                    retire_errs.append("%s: %s" % (w.name, e))
        except Exception as e:  # a barrier fault is a retire failure
            retire_errs.append(str(e))
        obs.SWAP_MS.observe((time.perf_counter() - t0) * 1e3,
                            phase="retire")
        obs.SWAP_MS.observe((time.perf_counter() - t_total) * 1e3,
                            phase="total")
        if retire_errs:
            # post-commit: the new version IS serving — surface the
            # cleanup failure as SwapError(rolled_back=False) so
            # callers (SwapWatcher) advance past this serial instead of
            # re-swapping it
            raise SwapError(
                "swap to %r COMMITTED (new version serving), but "
                "retiring the old replicas failed: %s — reap_dead()/the "
                "autoscaler can finish the cleanup"
                % (version, "; ".join(retire_errs)), rolled_back=False)
        return {"version": version, "previous": old_active,
                "replicas": new_names, "canaried": compared,
                "retired": retired}
