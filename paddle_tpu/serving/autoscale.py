"""Autoscaler: grow, drain-shrink, and heal the serving fleet by load.

The Router already exposes every primitive — ``add_replica()`` (cheap:
workers warm-start from the shared AOT cache, PR-5/PR-10 measured
3.7–4.3x faster time-to-first-step), ``remove_replica()`` (zero-drop
drain-shrink), ``reap_dead()`` — and every signal
(``paddle_tpu_fleet_*`` series / ``Router.stats()``). The Autoscaler is
the control loop over them:

- **utilization** = (outstanding + pending + queued) / (ready replicas
  x max_outstanding): the fraction of the fleet's in-flight window in
  use, with the dispatch backlog counted as demand the window cannot
  absorb. >= ``high_util`` for ``up_ticks`` consecutive ticks (or ANY
  load shedding this tick — sheds mean deadlines are already being
  sacrificed) scales up; <= ``low_util`` for ``down_ticks`` ticks
  drain-shrinks.
- **hysteresis**: the up/down watermark gap plus the consecutive-tick
  streaks mean a diurnal ramp scales once, not every tick, and a burst
  that ends mid-drain doesn't thrash spawn/stop cycles.
- **cooldown**: after any action, decisions pause for ``cooldown_s``
  (streaks keep accumulating) so a freshly added replica gets to absorb
  load before the next decision reads the signals it just changed.
- **healing**: a SIGKILLed replica (state ``dead``) is reaped and
  replaced whenever the ready count is below ``min_replicas`` — the
  crash-requeue path already saved its in-flight work; healing restores
  capacity. Healing ignores the cooldown: restoring the floor is never
  thrash.

``tick()`` is a pure step (call it from a test for determinism);
``start()`` runs it on a daemon thread every ``interval_s``. Actions
count into ``paddle_tpu_fleet_autoscale_total{direction=up|down|heal}``.
"""
from __future__ import annotations

import threading
import time
from typing import Optional

from .. import observability as obs

__all__ = ["Autoscaler"]


class Autoscaler:
    """
    scaler = Autoscaler(router, min_replicas=1, max_replicas=4)
    scaler.start()          # control thread, one tick per interval_s
    ...
    scaler.stop()
    """

    def __init__(self, router, min_replicas: int = 1,
                 max_replicas: int = 4, interval_s: float = 1.0,
                 high_util: float = 0.75, low_util: float = 0.20,
                 up_ticks: int = 2, down_ticks: int = 5,
                 cooldown_s: float = 10.0, heal: bool = True,
                 spawn_timeout: Optional[float] = None):
        if min_replicas < 1:
            raise ValueError("min_replicas must be >= 1, got %d"
                             % min_replicas)
        if max_replicas < min_replicas:
            raise ValueError("max_replicas (%d) < min_replicas (%d)"
                             % (max_replicas, min_replicas))
        if not (0.0 <= low_util < high_util):
            raise ValueError(
                "need 0 <= low_util < high_util (the watermark gap IS "
                "the hysteresis), got low=%r high=%r"
                % (low_util, high_util))
        self.router = router
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.interval_s = float(interval_s)
        self.high_util = float(high_util)
        self.low_util = float(low_util)
        self.up_ticks = max(1, int(up_ticks))
        self.down_ticks = max(1, int(down_ticks))
        self.cooldown_s = float(cooldown_s)
        self.heal = bool(heal)
        self.spawn_timeout = spawn_timeout
        self._hi = 0
        self._lo = 0
        self._last_action_t: Optional[float] = None
        # THIS router's shed count (stats()["shed"]) — the process-wide
        # obs series would let another fleet's sheds scale this one.
        # None until the first tick: pre-attach sheds are not a signal
        self._last_shed: Optional[int] = None
        self._thread: Optional[threading.Thread] = None
        self._stop_ev = threading.Event()
        self.actions: list = []  # (monotonic t, direction) history

    # -- signals -----------------------------------------------------------
    def utilization(self, st: Optional[dict] = None) -> float:
        """In-flight window usage incl. the dispatch backlog, in [0, inf):
        1.0 = every ready replica's window is full and nothing queues."""
        st = st or self.router.stats()
        cap = max(1, st["ready"]) * max(1, st["max_outstanding"])
        return (st["outstanding"] + st["pending"] + st["queued"]) / cap

    # -- the control step --------------------------------------------------
    def tick(self, now: Optional[float] = None) -> Optional[str]:
        """One decision step. Returns the action taken ("up" | "down" |
        "heal") or None. Never raises past a failed spawn/drain — the
        control loop must not die while the fleet serves."""
        now = time.monotonic() if now is None else now
        st = self.router.stats()
        # 1) heal: reap crashed replicas, restore the floor (no cooldown
        # — a fleet below min_replicas is an availability incident)
        if self.heal and (st["dead"] or st["ready"] + st["starting"]
                          < self.min_replicas):
            self.router.reap_dead()
            st = self.router.stats()
            if st["ready"] + st["starting"] < self.min_replicas:
                if self._act("heal", now):
                    return "heal"
        # 2) streaks: sheds are an immediate overload signal, utilization
        # a smoothed one
        shed_total = st.get("shed", 0)
        shed_delta = (0 if self._last_shed is None
                      else shed_total - self._last_shed)
        self._last_shed = shed_total
        util = self.utilization(st)
        if shed_delta > 0 or util >= self.high_util:
            self._hi += 1
            self._lo = 0
        elif util <= self.low_util:
            self._lo += 1
            self._hi = 0
        else:
            self._hi = 0
            self._lo = 0
        # 3) cooldown gates ACTIONS, not signal accumulation
        if (self._last_action_t is not None
                and now - self._last_action_t < self.cooldown_s):
            return None
        total = st["ready"] + st["starting"]
        if self._hi >= self.up_ticks and total < self.max_replicas:
            if self._act("up", now):
                return "up"
        elif (self._lo >= self.down_ticks and st["ready"] > self.min_replicas
              and st["ready"] > 1):
            if self._act("down", now):
                return "down"
        return None

    def _act(self, direction: str, now: float) -> bool:
        try:
            if direction == "down":
                self.router.remove_replica()
            else:  # up / heal both spawn
                self.router.add_replica(timeout=self.spawn_timeout)
        except Exception:
            # a failed action must not kill the control loop; the next
            # tick re-reads the signals and retries if still warranted
            return False
        self._last_action_t = now
        self._hi = 0
        self._lo = 0
        self.actions.append((now, direction))
        obs.FLEET_AUTOSCALE.inc(direction=direction)
        return True

    # -- thread lifecycle --------------------------------------------------
    def start(self):
        if self._thread is not None:
            return
        if self.heal and hasattr(self.router, "hold_when_dead"):
            # while the healer RUNS, an all-dead fleet is a transient:
            # the router holds requests (deadline sheds still bound
            # their wait) instead of failing them. Armed here and
            # disarmed in stop() — a constructed-but-stopped scaler
            # must not revoke the router's fast-fail contract
            self.router.hold_when_dead = True
        self._stop_ev.clear()
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="ptpu-autoscaler")
        self._thread.start()

    def _loop(self):
        while not self._stop_ev.wait(self.interval_s):
            try:
                self.tick()
            except Exception:
                # stats() during router.stop() can race worker teardown;
                # the scaler outliving one bad tick beats taking down
                # the process that owns the fleet
                pass

    def stop(self):
        if self._thread is None:
            return
        self._stop_ev.set()
        self._thread.join(timeout=max(5.0, 2 * self.interval_s))
        self._thread = None
        if self.heal and hasattr(self.router, "hold_when_dead"):
            # no healer any more: restore fast-fail for an all-dead
            # fleet. Gated on self.heal exactly like the arming — a
            # heal=False scaler never armed the flag and must not
            # revoke a hold the operator armed themselves
            self.router.hold_when_dead = False
