"""Fleet worker: one serving replica = engine + channel loop.

``worker_main`` is the process entry the Router spawns (spawn/forkserver
start methods — fork from a jax-threaded parent deadlocks children, the
PR-3 DataLoader lesson). It builds a ``Predictor`` (or a tp
``ShardedPredictor``) over the SHARED persistent AOT cache — so N
replicas deserialize the executables one process compiled, making a
warm fleet spawn nearly compile-free — wraps it in the PR-2 pipelined
``PredictorServer``, and shuttles binary frames between the router pipe
and the server's C++ channel.

Pipe wire protocol (each message one ``send_bytes`` payload):

router -> worker
    ``b"Z..."`` / ``b"P..."``  request frame, forwarded VERBATIM from
                               the client (the embedded tag is the
                               router-minted request id); a traced
                               request arrives ``b"T"``-prefixed
                               (wire.pack_trace) — the worker strips
                               the header, binds rid -> trace_id, and
                               records recv/stack/device/reply spans
                               into its flight recorder
    ``b"C" + pickle(dict)``    control: {"cmd": "stop" | "ping" |
                               "metrics" | "trace" | "probe"}

worker -> router
    ``b"S" + pickle(dict)``    status: ready/pong/metrics/stopped
    ``b"R" + u8 vlen + version + frame``
                               response: version = this replica's
                               program fingerprint (the router checks
                               it against the version the request was
                               dispatched under — mis-versioned
                               responses must be impossible, and are
                               counted if they ever happen); frame =
                               encoded (rid, fetch rows)
    ``b"E" + pickle((rid, exc))``  per-request failure

Responses stream back from ``_Future.add_done_callback`` (the server's
device/stacking threads), serialized by a send lock. On "stop" the
worker calls ``server.stop()``, which flushes everything still queued
in the stacking stage (the drain contract pinned by
``tests/test_serving_pipeline.py::test_stop_flushes_queued_requests``),
so every response is on the pipe before the final "stopped" status —
the zero-dropped-requests half of the fleet drain story.
"""
from __future__ import annotations

import os
import pickle
import struct
import threading
import time
import traceback

__all__ = ["worker_main"]


def _apply_env(options):
    """Environment overrides BEFORE jax is imported (spawned children
    import everything inside this function for exactly this reason):
    virtual-device XLA_FLAGS for tp-on-CPU tests, cache dirs, etc."""
    for k, v in (options.get("env") or {}).items():
        os.environ[k] = str(v)
    platform = options.get("jax_platform")
    if platform:
        os.environ["JAX_PLATFORMS"] = platform


def worker_main(conn, options):
    """Run one replica until the pipe closes or a stop command arrives.
    ``conn`` is the router end of a duplex multiprocessing Pipe;
    ``options`` is a plain picklable dict (see Router._spawn)."""
    _apply_env(options)

    # chaos barriers (checkpoint/faults.py, armed via PADDLE_TPU_FAULT_*
    # in worker_env): "serving.worker_boot" models a replica dying
    # during bootstrap (the drain_restart double-fault), and
    # "serving.request" — armed with a DELAY — models a slow replica so
    # shedding/priority tests are deterministic instead of racing the
    # scheduler. The env is fixed at spawn for a worker process, so an
    # unarmed worker skips the barrier entirely (zero hot-path cost).
    from ..checkpoint.faults import fault_point

    faults_armed = any(
        os.environ.get(k) for k in ("PADDLE_TPU_FAULT_KILL",
                                    "PADDLE_TPU_FAULT_DELAY",
                                    "PADDLE_TPU_FAULT_IO"))
    if faults_armed:
        fault_point("serving.worker_boot")
        if options.get("swap_boot"):
            # this spawn is a hot-swap's INCOMING replica: a swap.*-
            # scoped chaos spec (SIGKILL/delay the new version mid-swap)
            # fires here without touching regular boots of the same
            # fleet — the rollback-leaves-old-serving contract's barrier
            fault_point("swap.worker_boot")

    import jax

    if options.get("jax_platform"):
        # a sitecustomize-installed PJRT plugin can override
        # JAX_PLATFORMS at import time (tests/conftest.py precedent):
        # pin the platform after import too
        jax.config.update("jax_platforms", options["jax_platform"])

    from .. import observability as obs
    from ..inference import Predictor, PredictorServer, _encode_sample
    from ..observability import tracing as _tracing

    from . import wire

    name = options.get("name") or "worker%d" % os.getpid()
    obs.set_replica(name)

    # outbound coalescing: responses fire from the server's device /
    # stacking threads one future at a time; a dedicated sender drains
    # them and ships everything queued as ONE pipe message (wire.pack),
    # so the per-request syscall disappears under load
    import queue as _queue

    out_q: "_queue.Queue" = _queue.Queue()
    _SENDER_STOP = object()

    def _sender_loop():
        while True:
            item = out_q.get()
            if item is _SENDER_STOP:
                return
            items = [item]
            while True:
                try:
                    nxt = out_q.get_nowait()
                except _queue.Empty:
                    break
                if nxt is _SENDER_STOP:
                    out_q.put(nxt)  # re-deliver after this flush
                    break
                items.append(nxt)
            try:
                conn.send_bytes(wire.pack(items))
            except (OSError, ValueError, BrokenPipeError):
                return  # router gone: nothing left to tell it

    sender = threading.Thread(target=_sender_loop, daemon=True,
                              name="ptpu-worker-send")
    sender.start()

    def send(payload: bytes):
        out_q.put(payload)

    try:
        shard = int(options.get("shard") or 1)
        if options.get("decode"):
            # decode replica: DecodePredictor + continuous-batching
            # DecodeServer — same submit_frame/stop/start_http surface,
            # so the rest of the worker (and the whole Router) is
            # mode-agnostic
            if shard > 1:  # Router raises first; belt for direct callers
                raise ValueError(
                    "decode mode does not support shard > 1")
            from .decode import DecodePredictor, DecodeServer

            pred = DecodePredictor(
                options["model_dir"],
                strategy=options.get("strategy") or "greedy",
                draft_n_layer=options.get("decode_draft_layers"))
            version = options.get("version") or pred.fingerprint()
            server = DecodeServer(
                pred,
                slots=int(options.get("decode_slots", 4)),
                max_seq=options.get("decode_max_seq"),
                max_new_tokens=int(options.get("max_new_tokens", 32)),
                capacity=int(options.get("capacity", 256)),
                speculative=bool(options.get("decode_speculative")),
                spec_k=int(options.get("decode_spec_k", 4)),
                prefix_cache=bool(options.get("decode_prefix_cache")))
        else:
            if shard > 1:
                from .sharded import ShardedPredictor

                pred = ShardedPredictor(options["model_dir"], shard=shard)
            else:
                pred = Predictor(options["model_dir"])
            # the MODEL version label (hot swap: distinct exports of one
            # architecture share a program fingerprint, so the router
            # hands each spawn an explicit label); fingerprint fallback
            # keeps pre-swap fleets byte-identical in behavior
            version = options.get("version") or pred._engine.fingerprint()
            server = PredictorServer(
                pred,
                max_batch=int(options.get("max_batch", 8)),
                max_wait_ms=float(options.get("max_wait_ms", 0.0)),
                in_flight=int(options.get("in_flight", 2)),
                capacity=int(options.get("capacity", 256)))
        server.start()
        port = server.start_http(0) if options.get("http") else 0
    except Exception as e:
        # a replica that cannot come up reports WHY before dying — the
        # router surfaces this instead of a bare dead-pipe error
        send(b"S" + pickle.dumps(
            {"ready": False, "error": repr(e),
             "traceback": traceback.format_exc()}, protocol=4))
        return
    vtag = version.encode("ascii")
    send(b"S" + pickle.dumps(
        {"ready": True, "version": version, "pid": os.getpid(),
         "name": name, "metrics_port": port, "shard": shard}, protocol=4))

    served = [0]  # responses sent (rides each heartbeat)

    def respond(rid, fut, tid=None, t0=0.0):
        try:
            rows = fut.result(timeout=0)
            send(b"R" + struct.pack("<B", len(vtag)) + vtag
                 + _encode_sample(rid, rows))
        except Exception as e:
            send(b"E" + _pickle_error(rid, e))
        if tid is not None:
            # the whole worker residency, channel recv -> reply queued
            _tracing.record_span(tid, "worker.reply", ts=t0,
                                 dur_ms=(time.time() - t0) * 1e3, rid=rid)
        served[0] += 1

    # heartbeats through the control pipe: a dedicated thread, so a
    # main loop stuck in a device dispatch (or a chaos DELAY barrier)
    # still proves pipe/process liveness while the served count exposes
    # the STALL — the router's watchdog reaps live-but-hung replicas on
    # exactly that signal (wedge_timeout_s)
    hb_stop = threading.Event()
    hb_interval = float(options.get("heartbeat_s", 1.0) or 0)

    def _hb_loop():
        while not hb_stop.wait(hb_interval):
            send(b"S" + pickle.dumps(
                {"hb": True, "served": served[0],
                 "depth": len(server._results)}, protocol=4))

    hb_thread = None
    if hb_interval > 0:
        hb_thread = threading.Thread(target=_hb_loop, daemon=True,
                                     name="ptpu-worker-hb")
        hb_thread.start()

    def _pickle_error(rid, e):
        """An error response must ALWAYS reach the router — an exception
        whose state cannot pickle (locks, device handles, tracers) or
        whose class cannot reconstruct degrades to a plain RuntimeError
        carrying its repr, never a silently dropped response (which
        would strand the router's outstanding entry forever)."""
        try:
            payload = pickle.dumps((rid, e), protocol=4)
            pickle.loads(payload)  # reconstruction must work router-side
            return payload
        except Exception:
            return pickle.dumps(
                (rid, RuntimeError("replica error (unpicklable): %r" % (e,))),
                protocol=4)

    from ..runtime import recordio as _rio

    def _probe(cmd):
        """Hot-swap canary probe: run ONE request frame straight
        through the predictor (bypassing the serving queue — the probe
        must not consume a router-minted tag namespace or a batch
        slot) and reply with the output rows over the status pipe."""
        try:
            if options.get("decode"):
                raise RuntimeError(
                    "canary probe is a dense-predictor surface (decode "
                    "replicas generate, they don't score a fixed row)")
            import numpy as _np

            _rid, rows = _rio.decode_frame(memoryview(cmd["frame"]))
            # under the server's device lock: every predictor dispatch
            # is serialized through it (inference.py's single-threaded
            # device invariant) — a probe racing the live device stage
            # would otherwise run/compile concurrently with traffic
            with server._dev_lock:
                outs = pred.run([_np.asarray(r)[None] for r in rows])
            send(b"S" + pickle.dumps(
                {"probe": [_np.asarray(o) for o in outs]}, protocol=4))
        except Exception as e:
            send(b"S" + pickle.dumps({"probe_error": repr(e)},
                                     protocol=4))

    try:
        stop = False
        while not stop:
            try:
                payload = conn.recv_bytes()
            except (EOFError, OSError):
                break  # router gone: drain and exit
            try:
                msgs = list(wire.iter_messages(payload))
            except wire.WireError:
                # a torn multi-message: count, survive, keep serving
                obs.PREDICT_FAILURES.inc(path="wire")
                continue
            for msg in msgs:
                kind = bytes(msg[:1])
                if kind == b"C":
                    try:
                        cmd = pickle.loads(msg[1:])
                        op = cmd.get("cmd")
                    except Exception:
                        # a b"C"-prefixed frame that isn't a pickled
                        # dict must cost a counted drop, not the
                        # replica (same contract as every other frame
                        # kind)
                        obs.PREDICT_FAILURES.inc(path="wire")
                        continue
                    if op == "stop":
                        stop = True
                        break
                    if op == "ping":
                        send(b"S" + pickle.dumps(
                            {"pong": True, "version": version,
                             "pid": os.getpid(),
                             "depth": len(server._results)}, protocol=4))
                    elif op == "metrics":
                        from ..observability import export

                        send(b"S" + pickle.dumps(
                            {"metrics": export.to_json(
                                include_timeline=False)}, protocol=4))
                    elif op == "trace":
                        send(b"S" + pickle.dumps(
                            {"trace": _tracing.snapshot()}, protocol=4))
                    elif op == "probe":
                        _probe(cmd)
                    continue
                if kind == b"Q":
                    # belt-and-braces: the router strips the SLO header
                    # before forwarding, but a direct caller (or a
                    # future router that forwards deadlines) must not
                    # wedge the replica on an unknown prefix
                    try:
                        msg = wire.read_slo(msg)[3]
                    except wire.WireError:
                        obs.PREDICT_FAILURES.inc(path="wire")
                        continue
                tid = None
                if bytes(msg[:1]) == b"T":
                    # traced request: strip the header (defensively,
                    # like b"Q") and remember the id — spans below and
                    # in the server stages correlate through it
                    try:
                        tid, msg = wire.read_trace(msg)
                    except wire.WireError:
                        obs.PREDICT_FAILURES.inc(path="wire")
                        continue
                if faults_armed:
                    fault_point("serving.request")
                # request frame: submit as-is (bytes — the C channel
                # copies from a bytes payload); the response streams
                # back from the completing server thread via the done
                # callback
                msg = bytes(msg)
                try:
                    rid = _rio.frame_tag(msg)
                except Exception:
                    # malformed frame with no recoverable tag: nothing
                    # to address a structured reject TO — count it and
                    # keep the replica alive (the router side gives the
                    # tagless frame's future its reject, when one
                    # exists)
                    obs.PREDICT_FAILURES.inc(path="wire")
                    continue
                t_recv = 0.0
                if tid is not None:
                    t_recv = time.time()
                    _tracing.bind_rid(rid, tid)
                    _tracing.record_span(tid, "worker.recv", ts=t_recv,
                                         rid=rid)
                try:
                    fut = server.submit_frame(msg)
                except Exception as e:
                    if tid is not None:
                        _tracing.pop_rid(rid)
                    send(b"E" + _pickle_error(rid, e))
                    continue
                fut.add_done_callback(
                    lambda f, rid=rid, tid=tid, t0=t_recv:
                    respond(rid, f, tid, t0))
    finally:
        # stop() drains the stacking queue (never drops): every
        # outstanding future completes -> every response is queued
        # BEFORE the stopped status below, and the sender flushes the
        # queue in order before exiting
        hb_stop.set()
        if hb_thread is not None:
            hb_thread.join(timeout=5)
        server.stop()
        send(b"S" + pickle.dumps({"stopped": True}, protocol=4))
        out_q.put(_SENDER_STOP)
        sender.join(timeout=30)
        conn.close()
