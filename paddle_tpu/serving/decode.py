"""KV-cache autoregressive decode serving: DecodePredictor + DecodeServer.

Serving an LM before this module meant full forward passes: generating N
tokens re-ran the whole prefix N times — O(T^2) work the training-side
flash attention cannot hide. This module is the incremental path:

- ``save_decode_model`` exports a trained ``models.transformer.
  transformer_lm`` scope as a decode-servable directory: the canonical
  prefill graph goes through ``save_inference_model`` (so the plain
  ``Predictor`` can still serve it), plus a ``__decode__.json`` manifest
  with the architecture config the decode-side builders need.

- ``DecodePredictor`` loads that directory and compiles TWO kinds of
  executables through the shared PR-8 ``Engine`` (both land in the PR-5
  AOT disk cache next to the model): a PREFILL step (the existing
  flash-attention forward over the padded prompt, emitting last-position
  logits plus per-layer K/V slabs) and a per-token DECODE step
  (single-query ``decode_attention`` against the slabs, ``cache_append``
  of the fresh K/V row, and in-graph greedy/top-k/top-p sampling so only
  token ids cross the host boundary). Shapes are static: batch and slab
  length bucket to powers of two (the PR-2 batch-bucket trick applied to
  the sequence axis), so the executable count stays bounded at
  O(log B x log S) per strategy.

- ``DecodeServer`` is the continuous-batching serving loop (Orca-style
  iteration-level scheduling): requests enter the same C++ bounded
  channel as every other server, but instead of padding whole batches,
  new requests are admitted into FREE CACHE SLOTS between decode steps
  (prefilled as a power-of-two sub-batch, scattered into the resident
  slab) and finished sequences retire eagerly, freeing their slot
  mid-flight. One compiled decode signature — (slots, S) — serves the
  whole lifetime of the server. ``continuous=False`` degrades to static
  batching (admit a batch, run it to completion) for A/B measurement.

The fleet path reuses all of it: ``serving.worker`` builds a
DecodeServer when the Router is constructed with ``decode=True``, and
the zero-drop drain/restart contract extends to in-flight decode
sequences (``stop()`` finishes every admitted generation and admits
everything still queued before exiting).
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, List, Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp

from .. import observability as obs
from ..observability import tracing as _tracing
from ..runtime import aot_cache as _aot
from ..runtime import recordio as _rio

__all__ = ["DecodeConfig", "save_decode_model", "DecodePredictor",
           "DecodeServer", "kv_slab_slots"]

_DECODE_MANIFEST = "__decode__.json"
_AOT_DIR = "__aot_cache__"


def _pow2_bucket(n: int, floor: int = 1) -> int:
    b = max(int(floor), 1)
    while b < n:
        b *= 2
    return b


# bytes per slab element by kv dtype (int8 additionally pays a float32
# scale PER (slot, position) — 4 bytes per seq position per K/V slab)
_KV_ITEMSIZE = {"float32": 4, "bfloat16": 2, "int8": 1}


def kv_slab_slots(budget_bytes: int, config: "DecodeConfig", seq: int,
                  kv_dtype: str = "float32") -> int:
    """How many cache slots one KV slab byte budget holds at ``seq``
    positions — the continuous-batching capacity arithmetic behind the
    int8 slab: per slot, 2*n_layer slabs of seq*n_head*d_head elements
    (plus the per-position scales when int8). int8 rows cost 1 byte +
    4/(n_head*d_head) of scale vs bf16's 2 — at realistic head widths
    one budget holds ~2x the sequences."""
    if kv_dtype not in _KV_ITEMSIZE:
        raise ValueError("kv_dtype must be one of %s, got %r"
                         % (sorted(_KV_ITEMSIZE), kv_dtype))
    per_pos = config.n_head * config.d_head * _KV_ITEMSIZE[kv_dtype]
    if kv_dtype == "int8":
        per_pos += 4  # the (slot, position) float32 scale
    per_slot = 2 * config.n_layer * int(seq) * per_pos
    return max(int(budget_bytes) // per_slot, 0)


def _kv_dtype_from_env() -> str:
    """PADDLE_TPU_QUANT=kv8|int8 opts DecodeServer slabs into int8."""
    raw = (os.environ.get("PADDLE_TPU_QUANT") or "").strip().lower()
    return "int8" if raw in ("kv8", "int8") else "float32"


class DecodeConfig:
    """Architecture manifest for the decode-side graph builders — the
    arguments ``models.transformer.transformer_lm`` was trained with.
    Everything else (batch, slab length, strategy) is a serving-time
    choice and deliberately NOT part of the manifest."""

    FIELDS = ("vocab_size", "n_layer", "n_head", "d_model", "d_inner",
              "max_len", "tie_embeddings", "prefix", "eos_id")

    def __init__(self, vocab_size, n_layer=4, n_head=8, d_model=512,
                 d_inner=2048, max_len=2048, tie_embeddings=False,
                 prefix="lm", eos_id=None):
        self.vocab_size = int(vocab_size)
        self.n_layer = int(n_layer)
        self.n_head = int(n_head)
        self.d_model = int(d_model)
        self.d_inner = int(d_inner)
        self.max_len = int(max_len)
        self.tie_embeddings = bool(tie_embeddings)
        self.prefix = str(prefix)
        self.eos_id = None if eos_id is None else int(eos_id)

    @property
    def d_head(self) -> int:
        return self.d_model // self.n_head

    def to_dict(self) -> Dict:
        return {f: getattr(self, f) for f in self.FIELDS}

    @classmethod
    def from_dict(cls, d: Dict) -> "DecodeConfig":
        return cls(**{f: d[f] for f in cls.FIELDS if f in d})


def save_decode_model(dirname: str, config: DecodeConfig, executor,
                      scope=None, export_batch: int = 1,
                      export_seq: Optional[int] = None) -> None:
    """Export a trained transformer_lm scope for decode serving.

    Builds the canonical prefill graph (full flash-attention forward,
    last-position logits as the fetch target) and writes it through
    ``save_inference_model`` — the directory stays loadable by the plain
    ``Predictor`` — plus the ``__decode__.json`` manifest. Parameters
    come from ``scope`` (or the current global scope), exactly as
    ``save_inference_model`` resolves them; a parameter the decode
    builders expect but the scope lacks fails HERE, not at first
    request."""
    from .. import Program, io as fluid_io, program_guard, unique_name
    from ..models import transformer as _T

    export_seq = int(export_seq or min(config.max_len, 128))
    prog, startup = Program(), Program()
    with program_guard(prog, startup):
        with unique_name.guard():
            from .. import layers

            tokens = layers.data(name="tokens",
                                 shape=[export_batch, export_seq],
                                 dtype="int64", append_batch_size=False)
            lengths = layers.data(name="lengths", shape=[export_batch],
                                  dtype="int32", append_batch_size=False)
            last_logits, _caches = _T.transformer_lm_prefill(
                tokens, lengths, config.vocab_size,
                n_layer=config.n_layer, n_head=config.n_head,
                d_model=config.d_model, d_inner=config.d_inner,
                max_len=config.max_len,
                tie_embeddings=config.tie_embeddings,
                prefix=config.prefix)
    fluid_io.save_inference_model(
        dirname, ["tokens", "lengths"], [last_logits], executor,
        main_program=prog, scope=scope)
    with open(os.path.join(dirname, _DECODE_MANIFEST), "w") as f:
        json.dump(config.to_dict(), f, indent=2, sort_keys=True)


class DecodePredictor:
    """Incremental-decode predictor over an exported decode model.

    pred = DecodePredictor(model_dir)
    outs = pred.generate([np.array([5, 3, 9])], max_new_tokens=16)

    Compiled executables are acquired through the shared ``Engine``
    (kind="prefill" | "decode") and persist in the model directory's AOT
    disk cache — a fresh process warm-starts every bucket it has served
    before. ``generate`` is the static-batch surface (one call, one
    bucketed batch, run to completion); ``DecodeServer`` drives the same
    executables with continuous batching.
    """

    def __init__(self, model_dir: str, place=None, aot_cache: bool = True,
                 cache_dir: Optional[str] = None, strategy: str = "greedy",
                 sample_k: int = 40, sample_p: float = 0.9,
                 temperature: float = 1.0, eos_id: Optional[int] = None,
                 draft_n_layer: Optional[int] = None,
                 ring_prefill_min_seq: Optional[int] = None):
        from .. import io as fluid_io
        from ..executor import Executor, analyze_state
        from ..framework.scope import Scope

        with open(os.path.join(model_dir, _DECODE_MANIFEST)) as f:
            self.config = DecodeConfig.from_dict(json.load(f))
        self.model_dir = model_dir
        self.strategy = strategy
        self.sample_k = int(sample_k)
        self.sample_p = float(sample_p)
        self.temperature = float(temperature)
        self.eos_id = eos_id if eos_id is not None else self.config.eos_id
        # speculative decoding: the draft is the target's FIRST
        # draft_n_layer layers driven through the same loaded state
        # (self-drafting — no second parameter set to ship); default
        # half depth, floor 1
        self.draft_n_layer = (int(draft_n_layer)
                              if draft_n_layer is not None
                              else max(1, self.config.n_layer // 2))
        if not 1 <= self.draft_n_layer <= self.config.n_layer:
            raise ValueError(
                "draft_n_layer must be in [1, %d], got %d"
                % (self.config.n_layer, self.draft_n_layer))
        # long-context prefill: prompt buckets at or past this length
        # build their prefill graph with ring attention (sequence-
        # parallel under an sp mesh; exact-attention fallback on one
        # device, so the knob is portable). None = always dense.
        env_ring = os.environ.get("PADDLE_TPU_RING_PREFILL_MIN_SEQ")
        if ring_prefill_min_seq is None and env_ring:
            ring_prefill_min_seq = int(env_ring)
        self.ring_prefill_min_seq = (None if not ring_prefill_min_seq
                                     else int(ring_prefill_min_seq))
        self._scope = Scope()
        exe = Executor(place)
        if not aot_cache:
            exe._disk.enabled = False
        # the canonical prefill program: parameter loading + the stable
        # model fingerprint the fleet's sticky version routing keys on
        self._program, self._feed_names, _fetch = (
            fluid_io.load_inference_model(model_dir, exe,
                                          scope=self._scope))
        self._disk = _aot.AotDiskCache(
            cache_dir=cache_dir or os.path.join(model_dir, _AOT_DIR),
            enabled=aot_cache)
        _aot.maybe_enable_jax_cache()
        state_in, _ = analyze_state(self._program, set(self._feed_names))
        dev = jax.devices()[0]
        self._state = {}
        for n in state_in:
            val = self._scope.find_var(n)
            if val is None:
                raise RuntimeError(
                    "decode model is missing persistable %r" % n)
            self._state[n] = jax.device_put(np.asarray(val), dev)
        self._compiled: Dict = {}
        self._lock = threading.Lock()
        self.traces = 0

    def fingerprint(self) -> str:
        """Stable model identity (program content fingerprint of the
        canonical prefill graph) — the fleet's program version."""
        return obs.program_fp(self._program)

    # -- graph building ---------------------------------------------------
    def _build(self, kind: str, batch: int, seq: int, strategy: str,
               kv_dtype: str = "float32", window: int = 0,
               use_ring: bool = False):
        """Build the (batch, seq) Program for one executable kind;
        returns (program, feed_names, fetch_names). Deterministic for
        given arguments, so the program content fingerprint (and with
        it the AOT key) is stable across processes.

        Kinds: "prefill" (full causal forward; ``use_ring=True`` swaps
        flash attention for the sequence-parallel ring — the
        long-context path), "decode" (one token per step;
        ``kv_dtype="int8"`` builds the quantized-slab variant with
        per-layer scale feeds), "draft" (the decode step at
        ``draft_n_layer`` depth — the speculative proposer, driven by
        the same loaded state), and "verify" (the ``window``-token
        speculative verify / prefix suffix-extension step: window
        appends + staircase attention + in-graph accept)."""
        from .. import Program, layers, program_guard, unique_name
        from ..models import transformer as _T

        cfg = self.config
        prog, startup = Program(), Program()
        with program_guard(prog, startup):
            with unique_name.guard():
                if kind == "prefill":
                    tokens = layers.data(name="tokens", shape=[batch, seq],
                                         dtype="int64",
                                         append_batch_size=False)
                    lengths = layers.data(name="lengths", shape=[batch],
                                          dtype="int32",
                                          append_batch_size=False)
                    logits, caches = _T.transformer_lm_prefill(
                        tokens, lengths, cfg.vocab_size,
                        n_layer=cfg.n_layer, n_head=cfg.n_head,
                        d_model=cfg.d_model, d_inner=cfg.d_inner,
                        max_len=cfg.max_len,
                        tie_embeddings=cfg.tie_embeddings,
                        prefix=cfg.prefix,
                        use_ring_attention=use_ring)
                    feeds = ["tokens", "lengths"]
                    fetches = [logits.name] + [
                        c.name for pair in caches for c in pair]
                elif kind == "verify":
                    tokens = layers.data(name="tokens",
                                         shape=[batch, window],
                                         dtype="int64",
                                         append_batch_size=False)
                    positions = layers.data(name="positions",
                                            shape=[batch, window],
                                            dtype="int64",
                                            append_batch_size=False)
                    lengths = layers.data(name="lengths", shape=[batch],
                                          dtype="int32",
                                          append_batch_size=False)
                    last_idx = layers.data(name="last_idx", shape=[batch],
                                           dtype="int32",
                                           append_batch_size=False)
                    kc, vc = [], []
                    for i in range(cfg.n_layer):
                        kc.append(layers.data(
                            name="kcache_%d" % i,
                            shape=[batch, seq, cfg.n_head, cfg.d_head],
                            dtype="float32", append_batch_size=False))
                        vc.append(layers.data(
                            name="vcache_%d" % i,
                            shape=[batch, seq, cfg.n_head, cfg.d_head],
                            dtype="float32", append_batch_size=False))
                    next_ids, accept, last_logits, ncaches = (
                        _T.transformer_lm_verify(
                            tokens, positions, lengths, last_idx, kc, vc,
                            cfg.vocab_size, n_layer=cfg.n_layer,
                            n_head=cfg.n_head, d_model=cfg.d_model,
                            d_inner=cfg.d_inner, max_len=cfg.max_len,
                            tie_embeddings=cfg.tie_embeddings,
                            prefix=cfg.prefix))
                    feeds = (["tokens", "positions", "lengths",
                              "last_idx"]
                             + [v.name for v in kc]
                             + [v.name for v in vc])
                    fetches = ([next_ids.name, accept.name,
                                last_logits.name]
                               + [c.name for pair in ncaches
                                  for c in pair])
                else:
                    tokens = layers.data(name="tokens", shape=[batch, 1],
                                         dtype="int64",
                                         append_batch_size=False)
                    positions = layers.data(name="positions",
                                            shape=[batch, 1], dtype="int64",
                                            append_batch_size=False)
                    lengths = layers.data(name="lengths", shape=[batch],
                                          dtype="int32",
                                          append_batch_size=False)
                    seed = layers.data(name="seed", shape=[1],
                                       dtype="int64",
                                       append_batch_size=False)
                    cache_dt = ("int8" if kv_dtype == "int8"
                                else "float32")
                    n_layer = (self.draft_n_layer if kind == "draft"
                               else cfg.n_layer)
                    kc, vc, ks, vs = [], [], [], []
                    for i in range(n_layer):
                        kc.append(layers.data(
                            name="kcache_%d" % i,
                            shape=[batch, seq, cfg.n_head, cfg.d_head],
                            dtype=cache_dt, append_batch_size=False))
                        vc.append(layers.data(
                            name="vcache_%d" % i,
                            shape=[batch, seq, cfg.n_head, cfg.d_head],
                            dtype=cache_dt, append_batch_size=False))
                        if kv_dtype == "int8":
                            ks.append(layers.data(
                                name="kscale_%d" % i, shape=[batch, seq],
                                dtype="float32",
                                append_batch_size=False))
                            vs.append(layers.data(
                                name="vscale_%d" % i, shape=[batch, seq],
                                dtype="float32",
                                append_batch_size=False))
                    next_ids, logits, ncaches = _T.transformer_lm_decode(
                        tokens, positions, lengths, kc, vc, cfg.vocab_size,
                        n_layer=n_layer, n_head=cfg.n_head,
                        d_model=cfg.d_model, d_inner=cfg.d_inner,
                        max_len=cfg.max_len,
                        tie_embeddings=cfg.tie_embeddings,
                        prefix=cfg.prefix, strategy=strategy, seed=seed,
                        sample_k=self.sample_k, sample_p=self.sample_p,
                        temperature=self.temperature,
                        k_scales=ks or None, v_scales=vs or None)
                    feeds = (["tokens", "positions", "lengths", "seed"]
                             + [v.name for v in kc]
                             + [v.name for v in vc]
                             + [v.name for v in ks]
                             + [v.name for v in vs])
                    fetches = [logits.name] + [
                        c.name for tup in ncaches for c in tup]
                    if next_ids is not None:
                        fetches = [next_ids.name] + fetches
        return prog, feeds, fetches

    # -- compilation ------------------------------------------------------
    def _feed_structs(self, program, feed_names):
        from ..framework.dtypes import as_numpy_dtype

        structs = {}
        for name in feed_names:
            var = program.global_block().var(name)
            structs[name] = jax.ShapeDtypeStruct(
                tuple(var.shape), np.dtype(as_numpy_dtype(var.dtype)))
        return structs

    def acquire(self, kind: str, batch: int, seq: int,
                strategy: Optional[str] = None,
                kv_dtype: str = "float32", window: int = 0):
        """Executable for one (kind, batch, seq, strategy, kv_dtype,
        window) signature: memory hit, else the shared Engine's
        disk-load-or-compile path. Returns (executable, fetch_names).
        ``kv_dtype`` only shapes decode steps (int8 slabs + scale
        feeds); prefill always emits float slabs the caller quantizes
        at scatter time. ``window`` is the verify kind's token width
        (spec_k proposals + the committed token); "draft" builds the
        decode step at ``draft_n_layer`` depth. Prefill buckets at or
        past ``ring_prefill_min_seq`` build with ring attention —
        their programs fingerprint differently, so dense and ring
        prefills coexist in the AOT cache."""
        strategy = strategy or self.strategy
        if kind not in ("decode", "draft"):
            kv_dtype = "float32"
        if kind == "draft":
            strategy = "greedy"  # proposals are always argmax
        use_ring = bool(kind == "prefill"
                        and self.ring_prefill_min_seq is not None
                        and seq >= self.ring_prefill_min_seq)
        ck = (kind, batch, seq,
              strategy if kind in ("decode", "draft") else "",
              kv_dtype, int(window),
              self.draft_n_layer if kind == "draft" else 0, use_ring)
        with self._lock:
            hit = self._compiled.get(ck)
        if hit is not None:
            obs.CACHE_HITS.inc(kind=kind, tier="memory",
                               program=self.fingerprint())
            return hit
        from .engine import Engine
        from ..framework.trace import RngStream, trace_block

        program, feed_names, fetch_names = self._build(
            kind, batch, seq, strategy, kv_dtype=kv_dtype,
            window=window, use_ring=use_ring)
        engine = Engine(program, disk=self._disk, feed_names=feed_names,
                        fetch_names=fetch_names)
        feed_structs = self._feed_structs(program, feed_names)
        feed_sig = tuple((n, tuple(s.shape), str(np.dtype(s.dtype)))
                         for n, s in sorted(feed_structs.items()))
        key = engine.key(kind, feed_sig, tuple(fetch_names))

        def step_fn(feeds, state):
            self.traces += 1
            env = dict(state)
            env.update(feeds)
            rng = RngStream(jax.random.PRNGKey(0))
            trace_block(program.global_block(), env, rng)
            return tuple(env[n] for n in fetch_names)

        def lower():
            # donate the feeds (the KV slabs dominate them) so XLA
            # appends cache rows IN PLACE on device backends; CPU
            # ignores donation with a warning, so keep it off there.
            # NEVER donate the draft step's feeds: the speculative
            # round re-feeds the SAME committed target slabs to the
            # verify executable after drafting — donation would consume
            # them (the draft's appended rows are hypotheses; its
            # returned slabs are discarded each round)
            donate = ()
            try:
                if kind != "draft" \
                        and jax.default_backend() not in ("cpu",):
                    donate = (0,)
            except Exception:  # pragma: no cover
                pass
            fn = jax.jit(step_fn, donate_argnums=donate)
            state_structs = {n: jax.ShapeDtypeStruct(a.shape, a.dtype)
                             for n, a in self._state.items()}
            return fn.lower(feed_structs, state_structs)

        loaded, path, timings = engine.acquire(
            kind, key, lower,
            meta=engine.meta(kind, feed_sig, tuple(fetch_names)))
        if path == "cold":
            obs.COMPILE_TOTAL.inc(kind=kind)
            obs.COMPILE_LATENCY_MS.observe(
                timings["trace_ms"] + timings["xla_ms"], kind=kind)
        with self._lock:
            self._compiled[ck] = (loaded, fetch_names)
        return loaded, fetch_names

    # -- host-side sampling (first token, from prefill logits) ------------
    def _sample_host(self, logits, strategy: str, seed: int):
        from ..ops import sampling as _S

        if strategy in ("greedy", "logits", "beam"):
            return np.asarray(_S.greedy_sample(logits))
        seed_arr = jnp.asarray([seed], jnp.int32)
        if strategy == "topk":
            return np.asarray(_S.top_k_sample(
                logits, seed_arr, self.sample_k, self.temperature))
        if strategy == "topp":
            return np.asarray(_S.top_p_sample(
                logits, seed_arr, self.sample_p, self.temperature))
        raise ValueError("unknown decode strategy %r" % strategy)

    def _bucketed(self, prompts: Sequence[np.ndarray], max_new: int,
                  batch_floor: int = 1, seq: Optional[int] = None):
        """Pad a prompt list into bucketed (tokens, lengths) arrays.
        Pad rows (beyond the real batch) carry one dummy token so the
        prefill's last-position gather stays in range."""
        b = len(prompts)
        plens = [int(len(p)) for p in prompts]
        if min(plens) < 1:
            raise ValueError("empty prompt (decode needs >= 1 token)")
        need = max(plens) + max_new
        if need > self.config.max_len:
            raise ValueError(
                "prompt %d + max_new_tokens %d exceeds the model's "
                "max_len %d" % (max(plens), max_new, self.config.max_len))
        s = seq if seq is not None else _pow2_bucket(need, floor=16)
        s = min(s, _pow2_bucket(self.config.max_len))
        if s > self.config.max_len:
            s = self.config.max_len  # max_len itself may not be pow2
        bb = _pow2_bucket(b, floor=batch_floor)
        tokens = np.zeros((bb, s), np.int64)
        lens = np.ones((bb,), np.int32)
        for i, p in enumerate(prompts):
            tokens[i, :plens[i]] = np.asarray(p, np.int64).reshape(-1)
            lens[i] = plens[i]
        return tokens, lens, b, s

    def _prefill(self, tokens, lens, slab_seq):
        """Run prefill at the PROMPTS' own pow2 sequence bucket, then
        zero-pad the returned K/V rows out to the slab length — prompt
        cost scales with the prompt, not with the decode budget."""
        bb = tokens.shape[0]
        sp = min(_pow2_bucket(int(lens.max()), floor=16), slab_seq)
        pexe, _ = self.acquire("prefill", bb, sp)
        t0 = time.perf_counter()
        outs = pexe({"tokens": tokens[:, :sp], "lengths": lens},
                    self._state)
        obs.DECODE_STEP_MS.observe((time.perf_counter() - t0) * 1e3,
                                   stage="prefill")
        caches = list(outs[1:])
        if sp < slab_seq:
            pad = [(0, 0), (0, slab_seq - sp), (0, 0), (0, 0)]
            caches = [jnp.pad(jnp.asarray(c), pad) for c in caches]
        return outs, caches

    # -- generation (static batch, run to completion) ----------------------
    def generate(self, prompts: Sequence[np.ndarray],
                 max_new_tokens: int = 32, strategy: Optional[str] = None,
                 seed: int = 0, eos_id: Optional[int] = None,
                 beam_size: int = 4, speculative: bool = False,
                 spec_k: int = 4) -> List[np.ndarray]:
        """Generate up to ``max_new_tokens`` per prompt (stopping a row
        early at ``eos_id``). Returns one int64 array of generated ids
        per prompt. ``strategy`` overrides the constructor's
        ("greedy" | "topk" | "topp" | "beam").

        ``speculative=True`` (greedy only) runs draft-verify rounds:
        the ``draft_n_layer``-deep draft proposes ``spec_k`` tokens,
        the target checks all of them in ONE verify call — output is
        token-for-token identical to plain greedy (the lossless
        property), up to spec_k+1 tokens per target-model call."""
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1, got %d"
                             % max_new_tokens)
        strategy = strategy or self.strategy
        eos = eos_id if eos_id is not None else self.eos_id
        if speculative:
            if strategy != "greedy":
                raise ValueError(
                    "speculative decoding is lossless for greedy only; "
                    "got strategy %r" % (strategy,))
            return self.generate_speculative(
                prompts, max_new_tokens, spec_k=spec_k, eos_id=eos)
        if strategy == "beam":
            return self.generate_beam(prompts, max_new_tokens,
                                      beam_size=beam_size, eos_id=eos)
        if strategy not in ("greedy", "topk", "topp"):
            # "logits" builds a sampler-less step whose fetch layout
            # (no next_ids) this loop cannot drive — it is the
            # generate_beam/acquire surface, not a generate strategy
            raise ValueError(
                "unknown decode strategy %r (greedy | topk | topp | "
                "beam)" % (strategy,))
        tokens, lens, b, s = self._bucketed(prompts, max_new_tokens)
        bb = tokens.shape[0]
        outs, caches = self._prefill(tokens, lens, s)
        obs.DECODE_TOKENS.inc(int(lens[:b].sum()), kind="prefill")
        cur = np.array(self._sample_host(outs[0], strategy, seed))
        generated = [[int(cur[i])] for i in range(b)]
        finished = np.array([eos is not None and int(cur[i]) == eos
                             for i in range(b)])
        obs.DECODE_TOKENS.inc(b, kind="decode")
        if max_new_tokens > 1 and not finished.all():
            dexe, _fetch_names = self.acquire("decode", bb, s, strategy)
            self._plain_decode_steps(dexe, caches, cur, lens.copy(),
                                     generated, finished, b, s, eos,
                                     max_new_tokens, seed)
        return [np.asarray(g, np.int64) for g in generated]

    def _plain_decode_steps(self, dexe, caches, cur, lens, generated,
                            finished, b, s, eos, max_new_tokens,
                            seed) -> list:
        """THE one-token-per-iteration step loop, shared by
        ``generate()`` (the whole decode after the first sample) and
        ``generate_speculative()`` (the slab-headroom tail once a
        verify window no longer fits). Mutates ``cur`` / ``lens`` /
        ``generated`` / ``finished`` per ROW — a finished row's slot
        state freezes (its re-fed token and parked length only touch
        its own independent slab row, masked from every live row) —
        and returns the final caches. A row stops at eos, at its token
        budget, or when its slab row is full."""
        bb = cur.shape[0]
        step = 0
        while not finished.all():
            step += 1
            feeds = {"tokens": cur.reshape(bb, 1).astype(np.int64),
                     "positions": lens.reshape(bb, 1).astype(np.int64),
                     "lengths": lens,
                     "seed": np.array([seed + step], np.int64)}
            for i in range(self.config.n_layer):
                feeds["kcache_%d" % i] = caches[2 * i]
                feeds["vcache_%d" % i] = caches[2 * i + 1]
            t0 = time.perf_counter()
            outs = dexe(feeds, self._state)
            obs.DECODE_STEP_MS.observe(
                (time.perf_counter() - t0) * 1e3, stage="step")
            nxt = np.asarray(outs[0]).astype(np.int64)
            caches = list(outs[2:])
            emitted = 0
            for i in range(b):
                if finished[i]:
                    continue
                tok = int(nxt[i])
                generated[i].append(tok)
                cur[i] = tok
                lens[i] += 1
                emitted += 1
                if (eos is not None and tok == eos) \
                        or len(generated[i]) >= max_new_tokens \
                        or lens[i] + 1 >= s:
                    finished[i] = True
            obs.DECODE_TOKENS.inc(emitted, kind="decode")
        return caches

    # -- speculative decoding (draft-verify rounds, greedy/lossless) -------
    def draft_window(self, drexe, caches, cur, lens, spec_k):
        """One speculative round's DRAFT half, shared by
        ``generate_speculative`` and ``DecodeServer._spec_round``:
        run ``spec_k`` reduced-depth steps over the committed slabs'
        first ``draft_n_layer`` layers (the draft executable never
        donates, so the committed arrays stay valid for the verify
        feed; its returned slabs are hypotheses, dropped here) and
        build the verify window. Returns (window_tokens (B, spec_k+1),
        positions (B, spec_k+1)) — positions clipped to max_len-1 so
        window slots past a row's reach still embed in range."""
        bb = cur.shape[0]
        dn = self.draft_n_layer
        max_len = self.config.max_len
        dcaches = caches[:2 * dn]
        dcur, dlens = cur.copy(), lens.copy()
        zeros_seed = np.zeros((1,), np.int64)
        proposals = []
        t0 = time.perf_counter()
        for _ in range(spec_k):
            feeds = {"tokens": dcur.reshape(bb, 1).astype(np.int64),
                     "positions": np.minimum(
                         dlens, max_len - 1).reshape(bb, 1).astype(
                             np.int64),
                     "lengths": dlens, "seed": zeros_seed}
            for i in range(dn):
                feeds["kcache_%d" % i] = dcaches[2 * i]
                feeds["vcache_%d" % i] = dcaches[2 * i + 1]
            douts = drexe(feeds, self._state)
            dcur = np.asarray(douts[0]).astype(np.int64)
            dcaches = list(douts[2:])
            proposals.append(dcur)
            dlens = dlens + 1
        obs.DECODE_STEP_MS.observe((time.perf_counter() - t0) * 1e3,
                                   stage="draft")
        window = np.stack([cur] + proposals, axis=1)
        positions = np.minimum(
            lens[:, None].astype(np.int64)
            + np.arange(spec_k + 1, dtype=np.int64)[None, :],
            max_len - 1)
        return window, positions

    def generate_speculative(self, prompts: Sequence[np.ndarray],
                             max_new_tokens: int = 32, spec_k: int = 4,
                             eos_id: Optional[int] = None
                             ) -> List[np.ndarray]:
        """Greedy speculative decode: per round, ``spec_k`` draft steps
        (the target's first ``draft_n_layer`` layers — self-drafting,
        same loaded state) propose tokens, then ONE verify window call
        checks them all against the full target and emits
        accept+1 tokens per row. Token-for-token identical to
        ``generate(strategy="greedy")``; when the window would overrun
        the slab, the tail finishes on plain decode steps."""
        if spec_k < 1:
            raise ValueError("spec_k must be >= 1, got %d" % spec_k)
        eos = eos_id if eos_id is not None else self.eos_id
        tokens, lens, b, s = self._bucketed(prompts, max_new_tokens)
        bb = tokens.shape[0]
        outs, caches = self._prefill(tokens, lens, s)
        obs.DECODE_TOKENS.inc(int(lens[:b].sum()), kind="prefill")
        cur = np.array(self._sample_host(outs[0], "greedy", 0))  # writable
        generated = [[int(cur[i])] for i in range(b)]
        finished = np.array([(eos is not None and int(cur[i]) == eos)
                             or max_new_tokens <= 1 for i in range(b)])
        obs.DECODE_TOKENS.inc(b, kind="decode")
        lens = lens.copy().astype(np.int32)
        T = spec_k + 1
        if not finished.all():
            dexe, _ = self.acquire("draft", bb, s)
            vexe, _ = self.acquire("verify", bb, s, window=T)
        zeros_idx = np.zeros((bb,), np.int32)
        while not finished.all() and int(lens.max()) + T <= s:
            window, positions = self.draft_window(dexe, caches, cur,
                                                  lens, spec_k)
            feeds = {"tokens": window, "positions": positions,
                     "lengths": lens, "last_idx": zeros_idx}
            for i in range(self.config.n_layer):
                feeds["kcache_%d" % i] = caches[2 * i]
                feeds["vcache_%d" % i] = caches[2 * i + 1]
            t0 = time.perf_counter()
            vouts = vexe(feeds, self._state)
            obs.DECODE_STEP_MS.observe(
                (time.perf_counter() - t0) * 1e3, stage="verify")
            next_ids = np.asarray(vouts[0]).astype(np.int64)
            accept = np.asarray(vouts[1]).astype(np.int64)
            caches = list(vouts[3:])
            live = int((~finished[:b]).sum()) if b else 0
            obs.DECODE_SPEC_PROPOSED.inc(spec_k * live)
            emitted = 0
            for i in range(b):
                if finished[i]:
                    continue
                a = int(accept[i])
                obs.DECODE_SPEC_ACCEPTED.inc(a)
                take = min(a + 1,
                           max_new_tokens - len(generated[i]))
                for j in range(take):
                    tok = int(next_ids[i, j])
                    generated[i].append(tok)
                    emitted += 1
                    if eos is not None and tok == eos:
                        finished[i] = True
                        break
                if len(generated[i]) >= max_new_tokens:
                    finished[i] = True
                if not finished[i]:
                    # rollback by truncation: rows past lens+a are
                    # rejected-window garbage, masked by length and
                    # overwritten by later appends
                    lens[i] += a + 1
                    cur[i] = next_ids[i, a]
            obs.DECODE_TOKENS.inc(emitted, kind="decode")
        if not finished.all():
            # slab headroom exhausted: finish the tail on the SAME
            # plain step loop generate() runs (greedy ignores the seed
            # feed, so the shared loop's seed+step stream is
            # token-for-token the old constant-zero feed)
            dexe2, _ = self.acquire("decode", bb, s, "greedy")
            self._plain_decode_steps(dexe2, caches, cur, lens,
                                     generated, finished, b, s, eos,
                                     max_new_tokens, seed=0)
        return [np.asarray(g, np.int64) for g in generated]

    # -- beam-search strategy (ops-layer beam step between decode execs) ---
    def generate_beam(self, prompts: Sequence[np.ndarray],
                      max_new_tokens: int = 32, beam_size: int = 4,
                      eos_id: Optional[int] = None,
                      return_all: bool = False):
        """Beam-search decode: the compiled decode step runs with
        strategy="logits" (no sampler) and the ops-layer
        ``beam_search_step`` / ``beam_search_backtrack`` kernels
        (ops/decode.py — the same math contrib's BeamSearchDecoder scans
        with) pick continuations and reorder the KV slabs by parent via
        ``cache_gather`` between steps. Returns the best beam's ids per
        prompt (or, with return_all, (ids (B, K, T), lengths, scores))."""
        from ..ops.decode import beam_search_backtrack, beam_search_step
        from ..ops.kv_cache import cache_gather

        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1, got %d"
                             % max_new_tokens)
        k = int(beam_size)
        eos = eos_id if eos_id is not None else self.eos_id
        end_id = -1 if eos is None else int(eos)
        tokens, lens, b, s = self._bucketed(prompts, max_new_tokens)
        outs, pcaches = self._prefill(tokens, lens, s)
        obs.DECODE_TOKENS.inc(int(lens[:b].sum()), kind="prefill")
        lp = jax.nn.log_softmax(
            jnp.asarray(outs[0][:b]).astype(jnp.float32), axis=-1)
        pre_scores, pre_ids = jax.lax.top_k(lp, k)     # (B, K) each
        pre_ids = pre_ids.astype(jnp.int32)

        bk = _pow2_bucket(b * k)
        # beam-expand the caches: slab row b*K+j starts as prompt b's
        pad = np.zeros(bk - b * k, np.int32)
        expand = np.concatenate(
            [np.repeat(np.arange(b, dtype=np.int32), k), pad])
        caches = [cache_gather(c, jnp.asarray(expand)) for c in pcaches]
        lens_k = np.concatenate(
            [np.repeat(lens[:b], k), np.ones(bk - b * k, np.int32)]
        ).astype(np.int32)
        step_ids = [pre_ids]
        step_parents = [jnp.broadcast_to(
            jnp.arange(k, dtype=jnp.int32)[None, :], (b, k))]
        dexe, _ = self.acquire("decode", bk, s, "logits")
        for _step in range(1, max_new_tokens):
            cur = np.zeros((bk,), np.int64)
            cur[:b * k] = np.asarray(pre_ids).reshape(-1)
            feeds = {"tokens": cur.reshape(bk, 1),
                     "positions": lens_k.reshape(bk, 1).astype(np.int64),
                     "lengths": lens_k,
                     "seed": np.zeros((1,), np.int64)}
            for i in range(self.config.n_layer):
                feeds["kcache_%d" % i] = caches[2 * i]
                feeds["vcache_%d" % i] = caches[2 * i + 1]
            t0 = time.perf_counter()
            outs = dexe(feeds, self._state)
            obs.DECODE_STEP_MS.observe((time.perf_counter() - t0) * 1e3,
                                       stage="step")
            logits = jnp.asarray(outs[0][:b * k]).astype(jnp.float32)
            lp = jax.nn.log_softmax(logits, axis=-1).reshape(
                b, k, self.config.vocab_size)
            total = pre_scores[:, :, None] + lp
            sel_ids, sel_scores, parents = beam_search_step(
                pre_ids, pre_scores, total, None, k, end_id)
            # reorder the APPENDED slabs by parent so each surviving
            # beam carries its parent's full lineage
            flat_parent = np.concatenate([
                (np.arange(b, dtype=np.int32)[:, None] * k
                 + np.asarray(parents)).reshape(-1), pad])
            caches = [cache_gather(c, jnp.asarray(flat_parent))
                      for c in outs[1:]]
            pre_ids, pre_scores = sel_ids, sel_scores
            step_ids.append(sel_ids)
            step_parents.append(parents)
            lens_k = lens_k + 1
            obs.DECODE_TOKENS.inc(b * k, kind="decode")
            if eos is not None and bool(
                    (np.asarray(sel_ids) == end_id).all()):
                break
        sent, slens = beam_search_backtrack(
            jnp.stack(step_ids), jnp.stack(step_parents), end_id)
        sent = np.asarray(sent)
        slens = np.asarray(slens)
        if return_all:
            return sent, slens, np.asarray(pre_scores)
        return [np.asarray(sent[i, 0, :slens[i, 0]], np.int64)
                for i in range(b)]


class DecodeServer:
    """Continuous-batching decode serving loop.

    server = DecodeServer(DecodePredictor(model_dir), slots=8)
    server.start()
    fut = server.submit((prompt_ids,))            # or (ids, [max_new])
    (generated,) = fut.result()
    server.stop()

    One resident KV slab of ``slots`` rows serves every request: the
    loop admits queued prompts into free rows BETWEEN decode steps (a
    bucketed prefill sub-batch, scattered into the slab), steps every
    active row one token per iteration through ONE compiled (slots, S)
    executable, and retires finished rows eagerly — a long sequence
    never holds short ones hostage, and a fresh request starts decoding
    mid-flight instead of waiting for the batch to drain
    (``continuous=False`` restores gang scheduling for A/B runs).

    Requests ride the same zero-copy channel frames as PredictorServer
    (slot 0: int prompt ids; optional slot 1: [max_new_tokens] or
    [max_new_tokens, seed] int64), and the response is one int64 array
    of generated ids — so the PR-8 Router forwards decode traffic
    verbatim and ``stop()`` keeps the zero-drop contract: everything
    admitted OR still queued finishes before the loop exits. A
    per-request ``seed`` seeds that request's FIRST sampled token;
    later steps draw from the server's stream (steps are shared across
    slots), so fully seeded reproducible sampling is
    ``DecodePredictor.generate``'s surface — greedy traffic is
    deterministic either way.
    """

    def __init__(self, predictor: DecodePredictor, slots: int = 4,
                 max_seq: Optional[int] = None, max_new_tokens: int = 32,
                 strategy: Optional[str] = None, capacity: int = 256,
                 eos_id: Optional[int] = None, continuous: bool = True,
                 prewarm: bool = True, kv_dtype: Optional[str] = None,
                 speculative: bool = False, spec_k: int = 4,
                 prefix_cache: bool = False, prefix_block: int = 16,
                 prefix_max_bytes: Optional[int] = None,
                 prefix_store=None):
        from ..runtime.recordio import Channel

        if slots < 1:
            raise ValueError("slots must be >= 1, got %d" % slots)
        self.predictor = predictor
        self.slots = int(slots)
        # int8 KV slabs (opt-in; PADDLE_TPU_QUANT=kv8 is the env knob):
        # rows quantize at append against per-(slot, position) scales
        # and dequantize on attention read — slab bytes drop 2x vs bf16
        # (4x vs these float32 slabs), so one slab budget holds 2x the
        # sequences (kv_slab_slots has the arithmetic)
        self.kv_dtype = kv_dtype if kv_dtype is not None \
            else _kv_dtype_from_env()
        if self.kv_dtype not in ("float32", "int8"):
            raise ValueError(
                "kv_dtype must be 'float32' or 'int8', got %r"
                % (self.kv_dtype,))
        cfg = predictor.config
        want = max_seq or cfg.max_len
        self.seq = min(_pow2_bucket(want, floor=16),
                       _pow2_bucket(cfg.max_len))
        if self.seq > cfg.max_len:
            self.seq = cfg.max_len
        self.max_new_tokens = int(max_new_tokens)
        self.strategy = strategy or predictor.strategy
        if self.strategy in ("beam", "logits"):
            raise ValueError(
                "DecodeServer streams one token per step; strategy %r "
                "is a DecodePredictor.generate-only mode" % self.strategy)
        self.eos_id = eos_id if eos_id is not None else predictor.eos_id
        self.continuous = bool(continuous)
        self._prewarm = prewarm
        # speculative decoding: per loop iteration, spec_k draft steps
        # (the target's first draft_n_layer layers) propose tokens and
        # ONE verify window call checks them — each active slot
        # advances by accept+1 tokens per round, token-for-token
        # identical to the plain greedy loop (lossless)
        self.speculative = bool(speculative)
        self.spec_k = int(spec_k)
        if self.speculative and self.strategy != "greedy":
            raise ValueError(
                "speculative decoding is lossless for greedy only; the "
                "server strategy is %r" % (self.strategy,))
        if (self.speculative or prefix_cache or prefix_store is not None) \
                and self.spec_k < 1:
            # prefix-only servers still size their suffix-extension
            # window off spec_k (_win below) — fail HERE, not as a
            # cryptic "verify windows need T >= 2" mid-admission
            raise ValueError("spec_k must be >= 1, got %d" % self.spec_k)
        # shared-prefix KV: admission hashes prompts against a
        # refcounted store of prefilled rows — N users of one prompt
        # pay ONE prefill; prompts sharing an aligned header seed from
        # the cached rows and extend only their suffix
        if prefix_store is not None:
            self._prefix = prefix_store
        elif prefix_cache:
            from .prefix import PrefixStore

            self._prefix = PrefixStore(max_bytes=prefix_max_bytes,
                                       block=prefix_block)
        else:
            self._prefix = None
        if (self.speculative or self._prefix is not None) \
                and self.kv_dtype == "int8":
            raise ValueError(
                "speculative decoding / prefix sharing run float32 "
                "slabs (int8 scatter-quantized windows are a device-"
                "window follow-up); drop kv_dtype='int8' or the lever")
        # the shared verify-window width: spec rounds AND prefix suffix
        # extension ride one compiled (slots, S, T) signature
        self._win = self.spec_k + 1
        # prefill-execution count — the test-pinned "N users of one
        # prompt pay ONE prefill" observable
        self.prefill_executions = 0
        self._chan = Channel(capacity)
        self._results: Dict[int, "_DecodeFuture"] = {}
        self._next_id = 0
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._http = None
        self._http_thread = None
        self._seed_ctr = 0
        # diagnostic: per-iteration active-slot counts (the continuous-
        # vs-static fill story; bench_decode reads it). BOUNDED: a
        # long-lived server must not grow an entry per decode step
        # forever — 100k covers any bench window
        import collections

        self.step_active_counts: "collections.deque" = collections.deque(
            maxlen=100_000)
        # cache feed names in the SAME per-layer order the decode
        # graph's fetch list flattens its updated tensors: (k, v) per
        # layer, plus (kscale, vscale) when the slab is int8 — so
        # zip(self._cache_feed_names, outs[2:]) rethreads each step
        names = []
        for i in range(cfg.n_layer):
            names += ["kcache_%d" % i, "vcache_%d" % i]
            if self.kv_dtype == "int8":
                names += ["kscale_%d" % i, "vscale_%d" % i]
        self._cache_feed_names = names
        self._cache_per_layer = 4 if self.kv_dtype == "int8" else 2

    # -- submission (PredictorServer-compatible surface) -------------------
    def submit(self, sample: Sequence[np.ndarray]):
        from ..inference import _Future, _encode_sample

        fut = _Future()
        fut._t0 = time.perf_counter()
        with self._lock:
            rid = self._next_id
            self._next_id += 1
            self._results[rid] = fut
        fut._bind(self, rid)
        tid = _tracing.maybe_start()
        if tid is not None:
            # standalone-server client edge (the PredictorServer.submit
            # pattern): no wire hop, bind straight into the stage table
            _tracing.bind_rid(rid, tid)
            _tracing.record_span(tid, "client.submit", rid=rid)
        try:
            sent = self._chan.send(_encode_sample(rid, sample))
        except BaseException:
            with self._lock:
                self._results.pop(rid, None)
            _tracing.pop_rid(rid)
            raise
        if not sent:
            with self._lock:
                self._results.pop(rid, None)
            _tracing.pop_rid(rid)
            raise RuntimeError("decode server is stopped")
        return fut

    def submit_frame(self, msg):
        """Router fan-in: an already-encoded frame, tag = request id."""
        from ..inference import _Future

        rid = _rio.frame_tag(msg)
        fut = _Future()
        fut._t0 = time.perf_counter()
        with self._lock:
            if rid in self._results:
                raise ValueError("request tag %d is already in flight"
                                 % rid)
            self._results[rid] = fut
        fut._bind(self, rid)
        if not self._chan.send(msg):
            with self._lock:
                self._results.pop(rid, None)
            raise RuntimeError("decode server is stopped")
        return fut

    def _pop(self, rid):
        # every future exit path funnels here: the trace binding a
        # traced request carried can never leak
        _tracing.pop_rid(rid)
        with self._lock:
            return self._results.pop(rid, None)

    # -- lifecycle ---------------------------------------------------------
    def start(self):
        if self._thread is not None and self._thread.is_alive():
            return
        if self._prewarm:
            # the steady-state signatures compile/AOT-load BEFORE the
            # first request: the ONE (slots, S) decode step, plus the
            # single-request and full-burst admission prefills at the
            # floor PROMPT bucket (_admit prefills at the prompts' own
            # pow2 bucket, so the floor is what typical short-prompt
            # traffic actually hits — longer prompts lazily warm their
            # own bucket on first arrival)
            t0 = time.perf_counter()
            self.predictor.acquire("decode", self.slots, self.seq,
                                   self.strategy, kv_dtype=self.kv_dtype)
            sp = min(16, self.seq)
            self.predictor.acquire("prefill", 1, sp)
            if self.slots > 1:
                self.predictor.acquire("prefill",
                                       _pow2_bucket(self.slots), sp)
            if self.speculative:
                self.predictor.acquire("draft", self.slots, self.seq)
            if self.speculative or self._prefix is not None:
                self.predictor.acquire("verify", self.slots, self.seq,
                                       window=self._win)
            obs.SERVER_STAGE_MS.observe(
                (time.perf_counter() - t0) * 1e3, stage="prewarm")
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="ptpu-decode-loop")
        self._thread.start()

    def stop(self):
        """Zero-drop stop: close the intake, then the loop admits
        everything still queued (as slots free) and finishes every
        in-flight generation before exiting."""
        self.stop_http()
        self._chan.close()
        if self._thread is not None:
            self._thread.join(timeout=300)
            self._thread = None

    # metrics endpoint: same handler as the PR-2 server (self._http/
    # self._http_thread are the only state it touches)
    from ..inference import PredictorServer as _PS

    start_http = _PS.start_http
    stop_http = _PS.stop_http
    del _PS

    # -- serving loop ------------------------------------------------------
    def _decode_request(self, msg):
        from ..inference import _decode_request

        rid, rows = _decode_request(msg)
        prompt = np.asarray(rows[0]).reshape(-1).astype(np.int64)
        max_new = self.max_new_tokens
        seed = None
        if len(rows) > 1:
            opts = np.asarray(rows[1]).reshape(-1)
            if opts.size >= 1:
                if int(opts[0]) < 1:
                    raise ValueError(
                        "max_new_tokens must be >= 1, got %d"
                        % int(opts[0]))
                max_new = min(int(opts[0]), self.max_new_tokens)
            if opts.size >= 2:
                seed = int(opts[1])
        return rid, prompt, max_new, seed

    def _set_slot_gauges(self, n_active: int):
        obs.DECODE_SLOTS.set(n_active, state="active")
        obs.DECODE_SLOTS.set(self.slots - n_active, state="free")

    def _fail(self, rid, exc):
        fut = self._pop(rid)
        if fut is not None:
            obs.PREDICT_FAILURES.inc(path="decode")
            fut.set_exception(exc)

    def _retire(self, slot_state):
        rid = slot_state["rid"]
        # span BEFORE _pop — _pop drops the trace binding
        _tracing.rid_span(rid, "decode.retire",
                          tokens=int(slot_state["count"]))
        fut = self._pop(rid)
        obs.DECODE_REQUESTS.inc(kind="retired")
        if self._prefix is not None:
            # refcount release: the retired sequence no longer pins its
            # prefix entry against eviction
            self._prefix.release(slot_state.get("prefix_entry"))
        if fut is not None:  # abandoned via cancel/timeout otherwise
            fut.set_result([np.asarray(slot_state["generated"], np.int64)])
            obs.PREDICT_LATENCY_MS.observe(
                (time.perf_counter() - fut._t0) * 1e3, path="decode")
            obs.PREDICT_REQUESTS.inc(path="decode")

    def _prefill_prompts(self, prompts):
        """The ONE admission-prefill recipe (shared by ``_admit`` and
        ``_admit_prefix``): bucket the prompts to a pow2 batch and
        their OWN pow2 sequence length — not the slab length: admitting
        a 16-token prompt into a 1024-token slab must cost a 16-token
        forward (this is what lets continuous admission beat gang
        scheduling — a slab-sized prefill per admission would eat the
        win) — run the prefill executable, and account it. Returns
        ``(outs, sp)``: the raw executable outputs (logits + per-layer
        float K/V sub-slabs) and the sequence bucket they are shaped
        at. Raises what the acquire/execute raises — the caller owns
        the admission-failure contract."""
        bb = _pow2_bucket(len(prompts))
        sp = min(_pow2_bucket(max(len(p) for p in prompts), floor=16),
                 self.seq)
        tokens = np.zeros((bb, sp), np.int64)
        plens = np.ones((bb,), np.int32)
        for i, p in enumerate(prompts):
            tokens[i, :len(p)] = p
            plens[i] = len(p)
        pexe, _ = self.predictor.acquire("prefill", bb, sp)
        t0 = time.perf_counter()
        outs = pexe({"tokens": tokens, "lengths": plens},
                    self.predictor._state)
        self.prefill_executions += 1
        obs.DECODE_STEP_MS.observe((time.perf_counter() - t0) * 1e3,
                                   stage="prefill")
        obs.DECODE_TOKENS.inc(int(plens[:len(prompts)].sum()),
                              kind="prefill")
        return outs, sp

    def _admit(self, pending, caches, lens, active):
        """Prefill a sub-batch of queued requests into free slots.
        ``pending`` entries are (rid, prompt, max_new, seed); returns
        the updated caches (slab rows replaced via one scatter per
        tensor). With a prefix store attached, admission first hashes
        each prompt against it — hits seed from cached rows (full hit:
        no model call at all; partial hit: suffix-only extension
        through the verify window) and identical prompts inside one
        sub-batch dedupe to a single prefill row."""
        free = [i for i in range(self.slots) if active[i] is None]
        batch = pending[:len(free)]
        del pending[:len(batch)]
        if self._prefix is not None:
            return self._admit_prefix(batch, free, caches, lens, active)
        n = len(batch)
        try:
            t_pf = time.perf_counter()
            outs, sp = self._prefill_prompts([b[1] for b in batch])
            pf_ms = (time.perf_counter() - t_pf) * 1e3
        except Exception as e:
            # an admission that cannot prefill (compile error, device
            # OOM) fails ITS requests and leaves the server serving —
            # the already-admitted slots and the queue are untouched
            for rid, _p, _mn, _seed in batch:
                self._fail(rid, e)
            return caches
        first = np.array(self.predictor._sample_host(
            outs[0], self.strategy, self._seed_ctr))  # writable copy
        self._seed_ctr += 1
        # a request that carried its own seed gets ITS first token from
        # that seed (matching DecodePredictor.generate(..., seed=s) for
        # the first sample); later steps draw from the server's stream —
        # full per-request reproducibility under continuous batching is
        # a greedy/direct-predictor property, not a server one
        for i, (_rid, _p, _mn, seed) in enumerate(batch):
            if seed is not None and self.strategy not in ("greedy",):
                first[i] = self.predictor._sample_host(
                    outs[0][i:i + 1], self.strategy, seed)[0]
        slot_idx = jnp.asarray(np.array(free[:n], np.int32))
        sub = list(outs[1:])  # (k, v) float sub-slabs per layer
        # scatter the (n, sp, H, Dh) prefill rows into the slab's first
        # sp positions; rows past sp keep old garbage, masked by length
        if self.kv_dtype == "int8":
            # prefill emits float rows; quantize per (slot, position)
            # at scatter time — the same row-scale scheme the in-graph
            # cache_append_quant applies to decoded rows
            from ..ops.quant import quantize_kv_rows

            per = self._cache_per_layer
            caches = list(caches)
            for li in range(len(sub) // 2):
                for j in (0, 1):  # K then V
                    rows = jnp.asarray(sub[2 * li + j])[:n]
                    q, sc = quantize_kv_rows(rows)
                    caches[per * li + j] = (
                        caches[per * li + j].at[slot_idx, :sp].set(q))
                    caches[per * li + 2 + j] = (
                        caches[per * li + 2 + j].at[slot_idx, :sp]
                        .set(sc))
        else:
            caches = [c.at[slot_idx, :sp].set(jnp.asarray(s)[:n])
                      for c, s in zip(caches, sub)]
        for i, (rid, prompt, max_new, seed) in enumerate(batch):
            slot = free[i]
            tok = int(first[i])
            st = {"rid": rid, "generated": [tok], "max_new": max_new,
                  "cur": tok, "count": 1}
            lens[slot] = len(prompt)
            active[slot] = st
            _tracing.rid_span(rid, "decode.admit", kind="fresh",
                              prompt_len=len(prompt),
                              prefill_ms=round(pf_ms, 3))
            obs.DECODE_REQUESTS.inc(kind="admitted")
            obs.DECODE_TOKENS.inc(kind="decode")
            if (self.eos_id is not None and tok == self.eos_id) \
                    or max_new <= 1:
                self._retire(st)
                active[slot] = None
                lens[slot] = 0
        return caches

    def _first_token(self, logits_row, seed):
        """First sampled token for one admitted sequence, honoring the
        per-request seed contract the plain admission path applies."""
        first = int(self.predictor._sample_host(
            logits_row.reshape(1, -1), self.strategy, self._seed_ctr)[0])
        self._seed_ctr += 1
        if seed is not None and self.strategy not in ("greedy",):
            first = int(self.predictor._sample_host(
                logits_row.reshape(1, -1), self.strategy, seed)[0])
        return first

    def _activate(self, slot, rid, prompt, max_new, first, lens, active,
                  entry_id):
        """Mark one slot live after its rows are resident (common tail
        of every admission flavor)."""
        st = {"rid": rid, "generated": [first], "max_new": max_new,
              "cur": first, "count": 1, "prefix_entry": entry_id}
        if entry_id is not None:
            self._prefix.acquire(entry_id)
        lens[slot] = len(prompt)
        active[slot] = st
        obs.DECODE_REQUESTS.inc(kind="admitted")
        obs.DECODE_TOKENS.inc(kind="decode")
        if (self.eos_id is not None and first == self.eos_id) \
                or max_new <= 1:
            self._retire(st)
            active[slot] = None
            lens[slot] = 0

    def _admit_prefix(self, batch, free, caches, lens, active):
        """Prefix-aware admission: hash each prompt against the store;
        full hits admit with ZERO model calls, partial hits seed the
        cached header rows and extend only their suffix through the
        verify window, misses (deduped within the sub-batch) prefill
        once and populate the store. Any failure fails THIS batch and
        leaves the server serving."""
        from .prefix import prefix_hash

        n = len(batch)
        if n == 0:
            return caches
        plan: List[dict] = []
        uniq_prompts: List[np.ndarray] = []
        uniq_map: Dict[str, int] = {}
        for rid, prompt, _mn, _seed in batch:
            eid, L, rows, logits = self._prefix.lookup(prompt)
            if eid is not None and L == len(prompt):
                plan.append({"kind": "full", "eid": eid, "L": L,
                             "rows": rows, "logits": logits})
            elif eid is not None:
                plan.append({"kind": "partial", "eid": eid, "L": L,
                             "rows": rows})
            else:
                h = prefix_hash(prompt)
                if h in uniq_map:
                    obs.DECODE_PREFIX_HITS.inc(kind="batch")
                    plan.append({"kind": "dup", "uniq": uniq_map[h]})
                else:
                    uniq_map[h] = len(uniq_prompts)
                    uniq_prompts.append(prompt)
                    plan.append({"kind": "miss", "uniq": uniq_map[h]})
        try:
            # ONE prefill over the deduped misses
            uniq_rows: List[List[np.ndarray]] = []
            uniq_logits: List[np.ndarray] = []
            uniq_eids: List[Optional[int]] = []
            pf_ms = 0.0
            if uniq_prompts:
                t_pf = time.perf_counter()
                outs, _sp = self._prefill_prompts(uniq_prompts)
                pf_ms = (time.perf_counter() - t_pf) * 1e3
                sub = [np.asarray(c) for c in outs[1:]]
                logits_all = np.asarray(outs[0])
                for i, p in enumerate(uniq_prompts):
                    rows = [s[i, :len(p)] for s in sub]
                    uniq_rows.append(rows)
                    uniq_logits.append(logits_all[i])
                    uniq_eids.append(self._prefix.insert(
                        p, rows, logits_all[i]))
            # scatter every request's resident prefix rows in ONE pass
            # per cache tensor (a per-request scatter would copy the
            # whole slab once per request — the plain path pays one
            # copy per admission WAVE, and so must this one). Rows
            # shorter than the wave's max length zero-pad: the padded
            # positions sit beyond each slot's valid length, masked by
            # every read and overwritten by later appends.
            ext_jobs = []   # (idx-in-batch, slot, suffix, eid)
            seeds_rows = []  # (slot, rows, L) for the batched scatter
            for i, ((rid, prompt, max_new, seed), p) in enumerate(
                    zip(batch, plan)):
                slot = free[i]
                # prefix-aware admission span: the kind says whether
                # this sequence paid a prefill (miss/dup share the
                # deduped one) or rode cached rows (full/partial)
                _tracing.rid_span(
                    rid, "decode.admit", kind="prefix_" + p["kind"],
                    prompt_len=len(prompt),
                    prefill_ms=(round(pf_ms, 3)
                                if p["kind"] in ("miss", "dup") else 0.0))
                if p["kind"] in ("miss", "dup"):
                    rows = uniq_rows[p["uniq"]]
                    logits = uniq_logits[p["uniq"]]
                    eid = uniq_eids[p["uniq"]]
                    L = len(prompt)
                elif p["kind"] == "full":
                    rows, logits, eid, L = (p["rows"], p["logits"],
                                            p["eid"], p["L"])
                else:
                    rows, logits, eid, L = p["rows"], None, p["eid"], \
                        p["L"]
                seeds_rows.append((slot, rows, L))
                if p["kind"] == "partial":
                    lens[slot] = L  # extension advances it to len(prompt)
                    ext_jobs.append((i, slot, np.asarray(
                        prompt[L:], np.int64), eid))
                else:
                    first = self._first_token(logits, seed)
                    self._activate(slot, rid, prompt, max_new, first,
                                   lens, active, eid)
            caches = list(caches)
            lmax = max(L for _s, _r, L in seeds_rows)
            slot_idx = jnp.asarray(np.array(
                [s for s, _r, _l in seeds_rows], np.int32))
            for j in range(len(caches)):
                stacked = np.zeros(
                    (len(seeds_rows), lmax) + tuple(caches[j].shape[2:]),
                    np.float32)
                for i, (_s, rows, L) in enumerate(seeds_rows):
                    stacked[i, :L] = rows[j]
                caches[j] = caches[j].at[slot_idx, :lmax].set(
                    jnp.asarray(stacked))
        except Exception as e:
            # pre-extension admission failed (prefill compile/run,
            # store insert, host scatter): fail THIS batch, free its
            # slots, release any refs it took; already-active slots
            # keep serving — everything up to here is host-side or a
            # non-donating scatter, so their resident rows are intact
            for (rid, _p, _mn, _seed), slot in zip(
                    batch, free[:len(batch)]):
                st = active[slot]
                if st is not None and st["rid"] == rid:
                    if self._prefix is not None:
                        self._prefix.release(st.get("prefix_entry"))
                    active[slot] = None
                self._fail(rid, e)
                lens[slot] = 0
            return caches
        if ext_jobs:
            try:
                caches = self._extend_suffixes(ext_jobs, batch, caches,
                                               lens, active)
            except Exception as e:
                # a failed verify call may have CONSUMED the fed slabs
                # under donation (device backends) — the pre-extension
                # cache list is not reusable, so this is the
                # step-failure contract, not the admission one: fail
                # the extension jobs AND every active sequence, hand
                # back fresh slabs. No ref release for the ext jobs
                # here: acquire happens only in _activate (after a
                # SUCCESSFUL extension) — releasing un-acquired refs
                # would steal another live holder's pin; jobs that DID
                # activate are in `active`, released by the line below
                for i, slot, _suf, _eid in ext_jobs:
                    self._fail(batch[i][0], e)
                    lens[slot] = 0
                caches = self._fail_all_active(active, lens, e)
        return caches

    def _extend_suffixes(self, ext_jobs, batch, caches, lens, active):
        """Drive partial-hit suffixes through the shared verify-window
        executable, chunk by chunk — multi-token cached prefill on the
        RESIDENT slab. Non-extending slots ride along untouched: their
        window rows land past their valid lengths (masked, then
        overwritten by their own later appends)."""
        cfg = self.predictor.config
        T = self._win
        vexe, _ = self.predictor.acquire("verify", self.slots, self.seq,
                                         window=T)
        remaining = {slot: suf for _i, slot, suf, _e in ext_jobs}
        offset = {slot: 0 for _i, slot, _s, _e in ext_jobs}
        final_logits: Dict[int, np.ndarray] = {}
        while remaining:
            tokens = np.zeros((self.slots, T), np.int64)
            positions = np.zeros((self.slots, T), np.int64)
            last_idx = np.zeros((self.slots,), np.int32)
            chunk_lens = {}
            for slot, suf in remaining.items():
                off = offset[slot]
                chunk = suf[off:off + T]
                cl = len(chunk)
                tokens[slot, :cl] = chunk
                positions[slot] = np.minimum(
                    lens[slot] + np.arange(T), cfg.max_len - 1)
                last_idx[slot] = cl - 1
                chunk_lens[slot] = cl
            feeds = {"tokens": tokens, "positions": positions,
                     "lengths": lens.copy(), "last_idx": last_idx}
            feeds.update(zip(self._cache_feed_names, caches))
            t0 = time.perf_counter()
            vouts = vexe(feeds, self.predictor._state)
            obs.DECODE_STEP_MS.observe(
                (time.perf_counter() - t0) * 1e3, stage="extend")
            last_logits = np.asarray(vouts[2])
            caches = list(vouts[3:])
            done = []
            for slot, cl in chunk_lens.items():
                lens[slot] += cl
                offset[slot] += cl
                obs.DECODE_TOKENS.inc(cl, kind="prefill")
                if offset[slot] >= len(remaining[slot]):
                    final_logits[slot] = last_logits[slot]
                    done.append(slot)
            for slot in done:
                del remaining[slot]
        for i, slot, _suf, eid in ext_jobs:
            rid, prompt, max_new, seed = batch[i]
            first = self._first_token(final_logits[slot], seed)
            self._activate(slot, rid, prompt, max_new, first, lens,
                           active, eid)
        return caches

    def _fresh_slabs(self):
        """Zeroed cache arrays in ``self._cache_feed_names`` order."""
        cfg = self.predictor.config
        shape = (self.slots, self.seq, cfg.n_head, cfg.d_head)
        dt = jnp.int8 if self.kv_dtype == "int8" else jnp.float32
        arrs = []
        for _ in range(cfg.n_layer):
            arrs.append(jnp.zeros(shape, dt))
            arrs.append(jnp.zeros(shape, dt))
            if self.kv_dtype == "int8":
                arrs.append(jnp.zeros((self.slots, self.seq),
                                      jnp.float32))
                arrs.append(jnp.zeros((self.slots, self.seq),
                                      jnp.float32))
        return arrs

    def _fail_all_active(self, active, lens, exc):
        """Shared step-failure recovery: a decode/draft/verify call
        that dies (device OOM, donated-buffer misuse, backend loss)
        must not kill the serving loop and strand every future — fail
        the ACTIVE sequences (their cache state is no longer
        trustworthy), release their prefix refs, free the slots, and
        hand back FRESH slabs (the failed call may have CONSUMED the
        fed ones under donation; lengths are all 0 now, so zeros are
        correct)."""
        for i, st in enumerate(active):
            if st is not None:
                if self._prefix is not None:
                    self._prefix.release(st.get("prefix_entry"))
                self._fail(st["rid"], exc)
                obs.DECODE_REQUESTS.inc(kind="retired")
                active[i] = None
                lens[i] = 0
        return self._fresh_slabs()

    def _spec_round(self, drexe, vexe, caches, lens, active, n_active):
        """One speculative round across every active slot: spec_k draft
        steps propose, ONE verify window call checks, each slot
        advances by its accept+1 tokens (capped by budget and slab
        room). Greedy-lossless: the emitted tokens are the target's own
        argmaxes, token-for-token what the plain loop would emit."""
        k, T = self.spec_k, self._win
        cur = np.zeros((self.slots,), np.int64)
        for i, st in enumerate(active):
            if st is not None:
                cur[i] = st["cur"]
        try:
            window, positions = self.predictor.draft_window(
                drexe, caches, cur, lens, k)
            feeds = {"tokens": window, "positions": positions,
                     "lengths": lens.copy(),
                     "last_idx": np.zeros((self.slots,), np.int32)}
            feeds.update(zip(self._cache_feed_names, caches))
            t0 = time.perf_counter()
            vouts = vexe(feeds, self.predictor._state)
            next_ids = np.asarray(vouts[0]).astype(np.int64)
            accept = np.asarray(vouts[1]).astype(np.int64)
        except Exception as e:
            return self._fail_all_active(active, lens, e)
        obs.DECODE_STEP_MS.observe((time.perf_counter() - t0) * 1e3,
                                   stage="verify")
        self.step_active_counts.append(n_active)
        caches = list(vouts[3:])
        obs.DECODE_SPEC_PROPOSED.inc(k * n_active)
        emitted = 0
        traced = _tracing.bound()
        for i, st in enumerate(active):
            if st is None:
                continue
            a = int(accept[i])
            obs.DECODE_SPEC_ACCEPTED.inc(a)
            if traced:
                _tracing.rid_span(st["rid"], "decode.spec_round",
                                  accepted=a, proposed=k)
            # cap by budget and slab room: window position j needs rows
            # lens..lens+j resident, so at most seq - lens tokens
            take = min(a + 1, st["max_new"] - st["count"],
                       self.seq - int(lens[i]))
            consumed = take
            stopped = False
            for j in range(take):
                tok = int(next_ids[i, j])
                st["generated"].append(tok)
                st["cur"] = tok
                st["count"] += 1
                emitted += 1
                if self.eos_id is not None and tok == self.eos_id:
                    stopped = True
                    consumed = j + 1
                    break
            lens[i] += consumed
            if stopped or st["count"] >= st["max_new"] \
                    or lens[i] + 1 >= self.seq:
                self._retire(st)
                active[i] = None
                lens[i] = 0
        obs.DECODE_TOKENS.inc(emitted, kind="decode")
        return caches

    def _loop(self):
        caches = self._fresh_slabs()
        lens = np.zeros((self.slots,), np.int32)
        active: List[Optional[dict]] = [None] * self.slots
        pending: List[tuple] = []
        dexe, _ = self.predictor.acquire("decode", self.slots, self.seq,
                                         self.strategy,
                                         kv_dtype=self.kv_dtype)
        if self.speculative:
            drexe, _ = self.predictor.acquire("draft", self.slots,
                                              self.seq)
            vexe, _ = self.predictor.acquire("verify", self.slots,
                                             self.seq, window=self._win)
        closed = False
        while True:
            n_active = sum(1 for a in active if a is not None)
            free = self.slots - n_active
            if not closed:
                if n_active == 0 and not pending:
                    # idle: park on the channel until work (or close)
                    batch = self._chan.recv_batch(self.slots, None)
                elif free > 0 and (self.continuous or n_active == 0):
                    # mid-flight admission: non-blocking drain, bounded
                    # by the free slots (leaving the rest in the channel
                    # keeps submit()'s backpressure intact)
                    batch = self._chan.recv_batch(free, 0)
                else:
                    batch = []
                if batch is None:
                    closed = True
                    batch = []
            else:
                batch = []
            for msg in batch:
                try:
                    rid, prompt, max_new, seed = self._decode_request(msg)
                    if len(prompt) + max_new > self.seq:
                        raise ValueError(
                            "prompt %d + max_new %d exceeds the server's "
                            "%d-token slab" % (len(prompt), max_new,
                                               self.seq))
                    if len(prompt) < 1:
                        raise ValueError("empty prompt")
                    pending.append((rid, prompt, max_new, seed))
                except Exception as e:
                    try:
                        self._fail(_rio.frame_tag(bytes(msg)), e)
                    except Exception:
                        pass
            admit_ok = (free > 0 and pending
                        and (self.continuous or n_active == 0))
            if admit_ok:
                caches = self._admit(pending, caches, lens, active)
                n_active = sum(1 for a in active if a is not None)
            self._set_slot_gauges(n_active)
            if n_active == 0:
                if closed and not pending:
                    return
                continue
            if self.speculative:
                caches = self._spec_round(drexe, vexe, caches, lens,
                                          active, n_active)
                self._set_slot_gauges(
                    sum(1 for a in active if a is not None))
                continue
            # one token across every active slot
            cur = np.zeros((self.slots,), np.int64)
            for i, st in enumerate(active):
                if st is not None:
                    cur[i] = st["cur"]
            feeds = {"tokens": cur.reshape(self.slots, 1),
                     "positions": lens.reshape(self.slots, 1).astype(
                         np.int64),
                     "lengths": lens.copy(),
                     "seed": np.array([self._seed_ctr], np.int64)}
            self._seed_ctr += 1
            feeds.update(zip(self._cache_feed_names, caches))
            try:
                t0 = time.perf_counter()
                outs = dexe(feeds, self.predictor._state)
                nxt = np.asarray(outs[0]).astype(np.int64)
            except Exception as e:
                # a decode step that dies (device OOM, donated-buffer
                # misuse, backend loss) must not kill the serving loop
                # and strand every future: fail the ACTIVE sequences
                # (their cache state is no longer trustworthy), free the
                # slots, keep serving the queue
                caches = self._fail_all_active(active, lens, e)
                self._set_slot_gauges(0)
                continue
            obs.DECODE_STEP_MS.observe((time.perf_counter() - t0) * 1e3,
                                       stage="step")
            self.step_active_counts.append(n_active)
            caches = list(outs[2:])
            emitted = 0
            for i, st in enumerate(active):
                if st is None:
                    continue
                lens[i] += 1
                tok = int(nxt[i])
                st["generated"].append(tok)
                st["cur"] = tok
                st["count"] += 1
                emitted += 1
                if (self.eos_id is not None and tok == self.eos_id) \
                        or st["count"] >= st["max_new"] \
                        or lens[i] + 1 >= self.seq:
                    self._retire(st)
                    active[i] = None
                    lens[i] = 0
            obs.DECODE_TOKENS.inc(emitted, kind="decode")
            # refresh occupancy AFTER retirements: an idle server must
            # scrape as 0 active, not as its pre-retirement count (the
            # next iteration may park on the channel before updating)
            self._set_slot_gauges(
                sum(1 for a in active if a is not None))
