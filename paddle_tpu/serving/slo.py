"""SLO classes and structured load-shedding rejects for the fleet.

A production front door cannot promise every caller the same latency:
an interactive user request, a background re-rank, and a bulk backfill
have different urgency AND different tolerance for being turned away.
An ``SLOClass`` names that contract — a dispatch priority (lower number
dispatches first) and an optional default deadline — and the Router
carries both on the wire frame (``wire.pack_slo``) so its dispatch loop
can run strict-priority queues and bounded-latency shedding without a
side table.

The shedding contract: a request the fleet can no longer serve within
its deadline is REJECTED with a structured ``RejectedError`` the moment
that becomes knowable — at admission (deadline already expired), or in
the dispatch loop's sweep (expired while queued, or the remaining
budget is below the observed service time). The client gets queue-depth
context and a decision point immediately instead of a timeout later;
``paddle_tpu_fleet_shed_total{class=...}`` counts every shed. A shed is
an explicit answer, not a failure — it does not touch
``paddle_tpu_predict_failures_total``.
"""
from __future__ import annotations

from typing import Dict, Optional

__all__ = ["SLOClass", "RejectedError", "default_classes",
           "DEFAULT_CLASS", "rejected"]

# the class an un-annotated submit() resolves to: mid priority, no
# deadline — pre-SLO callers see byte-identical wire frames and can
# never be shed
DEFAULT_CLASS = "standard"


class SLOClass:
    """One latency contract: ``priority`` orders dispatch (0 is most
    urgent), ``deadline_ms`` (optional) arms shedding for every request
    submitted under the class unless the caller overrides per call."""

    __slots__ = ("name", "priority", "deadline_ms")

    def __init__(self, name: str, priority: int,
                 deadline_ms: Optional[float] = None):
        self.name = str(name)
        self.priority = int(priority)
        self.deadline_ms = None if deadline_ms is None else float(deadline_ms)

    def __repr__(self):
        return ("SLOClass(%r, priority=%d, deadline_ms=%r)"
                % (self.name, self.priority, self.deadline_ms))


def default_classes() -> Dict[str, SLOClass]:
    """The stock three-tier ladder. ``interactive`` preempts everything
    in the dispatch queue; ``batch`` yields to both and never sheds
    (no deadline) — it absorbs the queueing that shedding protects the
    urgent tiers from. Deadlines default to None everywhere: shedding
    is armed per class or per request, never by surprise."""
    return {
        "interactive": SLOClass("interactive", 0),
        "standard": SLOClass("standard", 1),
        "batch": SLOClass("batch", 2),
    }


class RejectedError(RuntimeError):
    """Structured load-shed reject (NOT a timeout, NOT a server error).

    Raised from ``future.result()`` for a request the fleet declined —
    the deadline expired while queued, or the remaining budget is below
    what service currently takes. Fields give the client enough context
    to decide (back off, relax the deadline, drop the work):

    - ``slo`` / ``priority``: the class the request was submitted under
    - ``reason``: ``"expired"`` (deadline passed while queued) or
      ``"hopeless"`` (budget < observed service time — rejecting now
      beats timing out later)
    - ``deadline_remaining_ms``: budget left at shed time (<= 0 for
      ``expired``)
    - ``queue_depth`` / ``outstanding``: fleet pressure at shed time
    """

    def __init__(self, message: str = "request shed",
                 slo: Optional[str] = None,
                 priority: Optional[int] = None,
                 reason: str = "overload",
                 deadline_remaining_ms: Optional[float] = None,
                 queue_depth: Optional[int] = None,
                 outstanding: Optional[int] = None):
        super().__init__(message)
        self.slo = slo
        self.priority = priority
        self.reason = reason
        self.deadline_remaining_ms = deadline_remaining_ms
        self.queue_depth = queue_depth
        self.outstanding = outstanding


def rejected(klass: str, priority: int, reason: str,
             deadline_remaining_ms: Optional[float],
             queue_depth: int, outstanding: int) -> RejectedError:
    """Build the structured reject with a message that carries the whole
    context (the exception repr is what most clients will log)."""
    if reason == "expired":
        why = "deadline exceeded while queued"
    else:
        why = "remaining deadline budget is below the current service time"
    remaining = ("" if deadline_remaining_ms is None
                 else ", %.1fms of deadline remaining" % deadline_remaining_ms)
    return RejectedError(
        "request shed (%s): class %r %s (queue depth %d, %d requests "
        "outstanding%s) — lower the offered load, relax the deadline, or "
        "scale the fleet" % (reason, klass, why, queue_depth, outstanding,
                             remaining),
        slo=klass, priority=priority, reason=reason,
        deadline_remaining_ms=deadline_remaining_ms,
        queue_depth=queue_depth, outstanding=outstanding)
