"""Exposition: Prometheus text format + JSON snapshots of the registry.

``to_prometheus()`` renders the registry in the Prometheus text exposition
format (version 0.0.4) — the payload ``PredictorServer``'s ``/metrics``
endpoint serves and a scrape job ingests directly. ``to_json()`` bundles
the same data with the step timeline for humans and dashboards.
``counters_state``/``delta_state`` give cheap before/after diffs so a
caller (bench.py phases) can attach "what this block of work cost" without
resetting anyone else's metrics.
"""
from __future__ import annotations

import json
import math
from typing import Dict, Optional

from .metrics import (Counter, Gauge, Histogram, MetricRegistry, Summary,
                      REGISTRY, process_labels)
from .timeline import TIMELINE, StepTimeline

__all__ = [
    "to_prometheus", "to_json", "dumps_json",
    "counters_state", "delta_state", "merge_json_snapshots",
]


def _escape(value: str) -> str:
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _labels_str(labels: Dict[str, str], extra: Optional[Dict] = None) -> str:
    items = dict(labels)
    if extra:
        items.update(extra)
    if not items:
        return ""
    body = ",".join('%s="%s"' % (k, _escape(v))
                    for k, v in sorted(items.items()))
    return "{%s}" % body


def _fmt(v: float) -> str:
    if isinstance(v, float) and math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    f = float(v)
    return repr(int(f)) if f == int(f) else repr(f)


def to_prometheus(registry: Optional[MetricRegistry] = None) -> str:
    """Prometheus text exposition of every registered metric. Metrics with
    no series yet still emit HELP/TYPE plus (for unlabeled counters and
    gauges) an explicit 0 sample, so scrape dashboards see the full
    catalogue from the first scrape."""
    registry = registry or REGISTRY
    proc = process_labels()  # replica identity, when set (fleet workers)
    out = []
    for m in registry.collect():
        samples = [(dict(proc, **labels), v) for labels, v in m.samples()]
        kind = "summary" if isinstance(m, Summary) else m.kind
        out.append("# HELP %s %s" % (m.name, _escape(m.help or m.name)))
        out.append("# TYPE %s %s" % (m.name, kind))
        if isinstance(m, (Counter, Gauge)):
            if not samples:
                out.append("%s%s 0" % (m.name, _labels_str(proc)))
            for labels, value in samples:
                out.append("%s%s %s" % (m.name, _labels_str(labels),
                                        _fmt(value)))
        elif isinstance(m, Histogram):
            for labels, v in samples:
                cum = 0
                for ub, n in zip(m.buckets, v[:len(m.buckets)]):
                    cum += n
                    out.append("%s_bucket%s %d" % (
                        m.name, _labels_str(labels, {"le": _fmt(ub)}), cum))
                cum += v[len(m.buckets)]  # overflow
                out.append("%s_bucket%s %d" % (
                    m.name, _labels_str(labels, {"le": "+Inf"}), cum))
                out.append("%s_sum%s %s" % (m.name, _labels_str(labels),
                                            _fmt(v[-2])))
                out.append("%s_count%s %d" % (m.name, _labels_str(labels),
                                              v[-1]))
        elif isinstance(m, Summary):
            for labels, v in samples:
                ls = _labels_str(labels)
                out.append("%s_count%s %d" % (m.name, ls, v[0]))
                out.append("%s_sum%s %s" % (m.name, ls, _fmt(v[1])))
                out.append("%s_min%s %s" % (m.name, ls, _fmt(v[2])))
                out.append("%s_max%s %s" % (m.name, ls, _fmt(v[3])))
    return "\n".join(out) + "\n"


def to_json(registry: Optional[MetricRegistry] = None,
            timeline: Optional[StepTimeline] = None,
            include_timeline: bool = True) -> Dict:
    """JSON-able snapshot: {"metrics": {name: {kind, help, series}},
    "timeline": <timeline snapshot>}."""
    registry = registry or REGISTRY
    proc = process_labels()
    metrics = {}
    for m in registry.collect():
        series = []
        for labels, v in m.samples():
            labels = dict(proc, **labels)
            if isinstance(m, Histogram):
                series.append({"labels": labels,
                               "buckets": dict(zip(
                                   [_fmt(b) for b in m.buckets] + ["+Inf"],
                                   v[:len(m.buckets) + 1])),
                               "sum": v[-2], "count": v[-1]})
            elif isinstance(m, Summary):
                series.append({"labels": labels, "count": v[0], "sum": v[1],
                               "min": v[2], "max": v[3]})
            else:
                series.append({"labels": labels, "value": v})
        metrics[m.name] = {"kind": m.kind, "help": m.help, "series": series}
    out = {"metrics": metrics}
    if proc:
        out["replica"] = proc.get("replica")
    if include_timeline:
        out["timeline"] = (timeline or TIMELINE).snapshot()
    return out


def dumps_json(registry: Optional[MetricRegistry] = None,
               timeline: Optional[StepTimeline] = None,
               indent: Optional[int] = None,
               include_timeline: bool = True) -> str:
    return json.dumps(to_json(registry, timeline, include_timeline),
                      indent=indent, sort_keys=True)


def merge_json_snapshots(snapshots) -> Dict:
    """Aggregate several ``to_json()`` snapshots (one per fleet worker /
    per dump file) into one: series whose label sets match are SUMMED
    (counters, gauges, histogram buckets, summary count/sum; summary
    min/max take the min/max), distinct label sets stay distinct — so
    dumps whose series carry a ``replica`` label merge collision-free
    while the per-metric totals a dashboard wants come from summing the
    label dimension away downstream, exactly the Prometheus model.
    Timelines are per-process and are NOT merged (dropped); the output
    records the source replicas under ``"replicas"``."""
    merged: Dict = {"metrics": {}, "replicas": []}
    out_metrics = merged["metrics"]
    for snap in snapshots:
        rep = snap.get("replica")
        if rep is not None:
            merged["replicas"].append(rep)
        for name, m in (snap.get("metrics") or {}).items():
            om = out_metrics.setdefault(
                name, {"kind": m.get("kind"), "help": m.get("help"),
                       "series": []})
            index = {tuple(sorted((s.get("labels") or {}).items())): s
                     for s in om["series"]}
            for s in m.get("series") or ():
                key = tuple(sorted((s.get("labels") or {}).items()))
                dst = index.get(key)
                if dst is None:
                    import copy

                    dst = copy.deepcopy(s)
                    om["series"].append(dst)
                    index[key] = dst
                    continue
                if "buckets" in s:  # histogram
                    for ub, n in (s.get("buckets") or {}).items():
                        dst["buckets"][ub] = dst["buckets"].get(ub, 0) + n
                    dst["sum"] += s.get("sum", 0)
                    dst["count"] += s.get("count", 0)
                elif "min" in s:  # summary
                    dst["count"] += s.get("count", 0)
                    dst["sum"] += s.get("sum", 0)
                    dst["min"] = min(dst["min"], s.get("min", dst["min"]))
                    dst["max"] = max(dst["max"], s.get("max", dst["max"]))
                else:  # counter / gauge
                    dst["value"] = dst.get("value", 0) + s.get("value", 0)
    return merged


def counters_state(registry: Optional[MetricRegistry] = None) -> Dict[str, float]:
    """Flat {"name{a=b}": value} state of counters plus histogram/summary
    sums and counts — the before-image for delta_state()."""
    registry = registry or REGISTRY
    state: Dict[str, float] = {}
    for m in registry.collect():
        for labels, v in m.samples():
            key = m.name + _labels_str(labels)
            if isinstance(m, Counter):
                state[key] = float(v)
            elif isinstance(m, (Histogram, Summary)):
                if isinstance(m, Summary):
                    count, total = v[0], v[1]
                else:
                    count, total = v[-1], v[-2]
                state[key + "#count"] = float(count)
                state[key + "#sum"] = float(total)
    return state


def delta_state(before: Dict[str, float],
                registry: Optional[MetricRegistry] = None) -> Dict[str, float]:
    """What moved since ``before`` (a counters_state snapshot): only
    positive deltas are returned (a mid-phase registry reset would
    otherwise surface as a wall of negative counters)."""
    after = counters_state(registry)
    out = {}
    for key, val in after.items():
        d = val - before.get(key, 0.0)
        if d > 0:
            out[key] = d
    return out
