"""Structured metrics registry: counters, gauges, histograms, summaries.

The reference's fluid profiler answers "which op kernel is slow" by timing
every launch; on TPU a whole Program runs as ONE fused XLA computation, so
the production questions are different — compile counts, compile-cache
behavior, per-step latency distributions, serving latency — and they need
to be answerable at any moment, not only inside a start/stop profiling
window. This registry is the always-on substrate: recording is a dict
update under a lock (sub-microsecond), nothing is formatted or aggregated
until somebody reads (export.py), and the whole thing resets in O(metrics).

Metric types:
- Counter: monotonically increasing float (``.inc()``).
- Gauge: last-write-wins float (``.set()`` / ``.inc()``).
- Histogram: fixed-bucket latency/size distribution (``.observe()``);
  buckets are upper bounds, +Inf is implicit. Default buckets are
  latency-in-ms shaped.
- Summary: exact count/sum/min/max (``.observe()``) — what the legacy
  profiler report needs (per-event min/max cannot be recovered from
  histogram buckets).

All types take free-form labels as keyword arguments; each distinct label
combination is an independent series, exactly the Prometheus data model.
"""
from __future__ import annotations

import os
import threading
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

__all__ = [
    "Counter", "Gauge", "Histogram", "Summary", "MetricRegistry",
    "REGISTRY", "get_registry", "DEFAULT_LATENCY_BUCKETS_MS",
    "DEFAULT_SIZE_BUCKETS", "set_replica", "process_labels",
]

# -- process identity ------------------------------------------------------
#
# N fleet workers all export the same metric names; a scrape/merge of
# their payloads needs a per-process label or the series collide. When a
# replica identity is set — via PADDLE_TPU_REPLICA at import, or
# set_replica() at runtime — every exported series (export.py) carries
# ``replica="<name|pid>"``. Unset (the default, every pre-fleet process)
# the exposition is byte-identical to before.

_PROCESS_LABELS: Dict[str, str] = {}
if os.environ.get("PADDLE_TPU_REPLICA"):
    _PROCESS_LABELS["replica"] = os.environ["PADDLE_TPU_REPLICA"]


def set_replica(name: Optional[str] = None):
    """Tag this process's metric exports with ``replica=name`` (the pid
    when name is None) — call once at fleet-worker startup."""
    _PROCESS_LABELS["replica"] = (str(name) if name is not None
                                  else str(os.getpid()))


def process_labels() -> Dict[str, str]:
    """Constant labels stamped onto every exported series ({} unless a
    replica identity was set)."""
    return dict(_PROCESS_LABELS)

# latency buckets in milliseconds: sub-ms serving hits through multi-minute
# XLA compiles all land in a finite bucket before +Inf
DEFAULT_LATENCY_BUCKETS_MS = (
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0, 500.0,
    1000.0, 2500.0, 5000.0, 10000.0, 30000.0, 60000.0, 300000.0,
)

# power-of-two-ish count buckets (batch sizes, window lengths)
DEFAULT_SIZE_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024)


def _label_key(labels: Dict[str, str]) -> Tuple[Tuple[str, str], ...]:
    if not labels:
        return ()
    return tuple(sorted((k, str(v)) for k, v in labels.items()))


class _Metric:
    kind = "untyped"

    def __init__(self, name: str, help: str, lock: threading.Lock):
        self.name = name
        self.help = help
        self._lock = lock
        self._series: Dict[Tuple[Tuple[str, str], ...], object] = {}

    def _reset(self):
        self._series.clear()

    def remove(self, **labels):
        """Retire one label series (e.g. a closed Executor's depth gauge
        — without this, per-instance series outlive their instance and
        grow the registry and every exposition payload unboundedly)."""
        with self._lock:
            self._series.pop(_label_key(labels), None)

    def samples(self) -> List[Tuple[Dict[str, str], object]]:
        """[(labels_dict, value)] snapshot; value shape depends on kind."""
        with self._lock:
            return [(dict(k), self._copy_value(v))
                    for k, v in self._series.items()]

    @staticmethod
    def _copy_value(v):
        return v


class Counter(_Metric):
    kind = "counter"

    def inc(self, amount: float = 1.0, **labels):
        if amount < 0:
            raise ValueError("counters only go up (got %r)" % amount)
        key = _label_key(labels)
        with self._lock:
            self._series[key] = self._series.get(key, 0.0) + amount

    def value(self, **labels) -> float:
        with self._lock:
            return float(self._series.get(_label_key(labels), 0.0))

    def total(self) -> float:
        """Sum over every label combination."""
        with self._lock:
            return float(sum(self._series.values()))


class Gauge(_Metric):
    kind = "gauge"

    def set(self, value: float, **labels):
        with self._lock:
            self._series[_label_key(labels)] = float(value)

    def inc(self, amount: float = 1.0, **labels):
        key = _label_key(labels)
        with self._lock:
            self._series[key] = self._series.get(key, 0.0) + amount

    def value(self, **labels) -> float:
        with self._lock:
            return float(self._series.get(_label_key(labels), 0.0))


class Histogram(_Metric):
    """Fixed upper-bound buckets; per-series value is
    [bucket_counts..., overflow_count, sum, count]."""

    kind = "histogram"

    def __init__(self, name, help, lock,
                 buckets: Sequence[float] = DEFAULT_LATENCY_BUCKETS_MS):
        super().__init__(name, help, lock)
        bs = tuple(sorted(float(b) for b in buckets))
        if not bs:
            raise ValueError("histogram %r needs at least one bucket" % name)
        self.buckets = bs

    def observe(self, value: float, **labels):
        key = _label_key(labels)
        with self._lock:
            v = self._series.get(key)
            if v is None:
                v = [0] * (len(self.buckets) + 1) + [0.0, 0]
                self._series[key] = v
            i = 0
            for i, ub in enumerate(self.buckets):
                if value <= ub:
                    break
            else:
                i = len(self.buckets)  # overflow (+Inf only)
            v[i] += 1
            v[-2] += float(value)
            v[-1] += 1

    @staticmethod
    def _copy_value(v):
        return list(v)

    def stats(self, **labels) -> Dict[str, float]:
        """{'count', 'sum', 'mean'} for one series ({} labels = unlabeled)."""
        with self._lock:
            v = self._series.get(_label_key(labels))
        if v is None:
            return {"count": 0, "sum": 0.0, "mean": 0.0}
        count, total = v[-1], v[-2]
        return {"count": count, "sum": total,
                "mean": (total / count) if count else 0.0}


class Summary(_Metric):
    """Exact count/sum/min/max per series — the legacy profiler's event
    table (min/max cannot be reconstructed from histogram buckets)."""

    kind = "summary"

    def observe(self, value: float, **labels):
        key = _label_key(labels)
        value = float(value)
        with self._lock:
            v = self._series.get(key)
            if v is None:
                self._series[key] = [1, value, value, value]
            else:
                v[0] += 1
                v[1] += value
                if value < v[2]:
                    v[2] = value
                if value > v[3]:
                    v[3] = value

    @staticmethod
    def _copy_value(v):
        return list(v)

    def stats(self, **labels) -> Dict[str, float]:
        with self._lock:
            v = self._series.get(_label_key(labels))
        if v is None:
            return {"count": 0, "sum": 0.0, "min": 0.0, "max": 0.0}
        return {"count": v[0], "sum": v[1], "min": v[2], "max": v[3]}


class MetricRegistry:
    """Process-wide metric namespace. Registration is idempotent: asking
    for an existing name returns the existing metric (executors and
    predictors are constructed freely; their metrics are shared), but a
    kind mismatch on an existing name is a hard error."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, _Metric] = {}

    def _get_or_create(self, cls, name, help, **kwargs):
        with self._lock:
            m = self._metrics.get(name)
            if m is not None:
                if not isinstance(m, cls):
                    raise TypeError(
                        "metric %r already registered as %s, not %s"
                        % (name, m.kind, cls.kind))
                return m
            m = cls(name, help, self._lock, **kwargs)
            self._metrics[name] = m
            return m

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get_or_create(Counter, name, help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get_or_create(Gauge, name, help)

    def histogram(self, name: str, help: str = "",
                  buckets: Sequence[float] = DEFAULT_LATENCY_BUCKETS_MS
                  ) -> Histogram:
        return self._get_or_create(Histogram, name, help, buckets=buckets)

    def summary(self, name: str, help: str = "") -> Summary:
        return self._get_or_create(Summary, name, help)

    def get(self, name: str) -> Optional[_Metric]:
        with self._lock:
            return self._metrics.get(name)

    def collect(self) -> Iterable[_Metric]:
        """Metrics in registration order (stable exposition output)."""
        with self._lock:
            return list(self._metrics.values())

    def reset(self):
        """Zero every series; registered metrics stay registered."""
        with self._lock:
            for m in self._metrics.values():
                m._reset()


REGISTRY = MetricRegistry()


def get_registry() -> MetricRegistry:
    return REGISTRY
