"""Step timeline: a bounded ring buffer of per-step and per-compile events.

Where metrics.py answers "how many / how fast on average", the timeline
answers "what happened around step N": each Executor.run / run_loop /
ParallelExecutor.run dispatch appends one step event carrying wall time,
optional block-until-ready device time, feed/fetch byte volumes, and the
program fingerprint; every compile (executor AND Predictor) appends a
compile event with trace/XLA-compile timings and (when available) XLA
cost-analysis FLOPs/bytes estimates — the same numbers
tools/hlo_stats.py extracts from an xprof capture, obtained here
straight from the compiled executable. Per-request serving latency is
NOT a timeline event; it lives in the registry's
``paddle_tpu_predict_latency_ms`` histogram.

The buffer is a ``collections.deque(maxlen=...)``: recording is an O(1)
append and memory is bounded no matter how long the process serves.
Recording is on by default (an append costs ~1 µs); the DEVICE-time fence
is opt-in (``set_device_time(True)``) because a block-until-ready per step
would serialize the async dispatch pipeline the executor is built around.
"""
from __future__ import annotations

import collections
import json
import os
import threading
import time
from typing import Dict, List, Optional

from . import tracing

__all__ = ["StepTimeline", "TIMELINE", "get_timeline", "hlo_cost_stats"]

_DEFAULT_CAP = 1024


def hlo_cost_stats(compiled) -> Optional[Dict[str, float]]:
    """FLOPs / bytes-accessed estimates from a ``jax.stages.Compiled``
    (the numbers tools/hlo_stats.py derives from a trace, minus the
    runtime). Returns None when the backend exposes no cost analysis."""
    try:
        cost = compiled.cost_analysis()
        # some jax versions return a list with one dict per computation
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else None
        if not isinstance(cost, dict):
            return None
        out = {}
        if "flops" in cost:
            out["flops"] = float(cost["flops"])
        if "bytes accessed" in cost:
            out["bytes_accessed"] = float(cost["bytes accessed"])
        return out or None
    except Exception:  # pragma: no cover - backend-dependent
        return None


class StepTimeline:
    def __init__(self, capacity: Optional[int] = None):
        if capacity is None:
            try:
                capacity = int(os.environ.get("PADDLE_TPU_TIMELINE_CAP",
                                              _DEFAULT_CAP))
            except ValueError:
                capacity = _DEFAULT_CAP
        self._lock = threading.Lock()
        self._events = collections.deque(maxlen=max(1, capacity))
        self._seq = 0          # total events ever recorded
        self._device_time = False
        self._hlo_cost = False

    # -- switches --------------------------------------------------------
    def set_device_time(self, on: bool):
        """Fence (block-until-ready) each step so events carry true device
        time. Serializes async dispatch — debugging/measurement only."""
        self._device_time = bool(on)

    def device_time_enabled(self) -> bool:
        return self._device_time

    def set_hlo_cost(self, on: bool):
        """Make Executor compiles pay an extra explicit lower+compile to
        split trace/lowering time and attach XLA cost-analysis estimates
        (Predictor compiles get them for free — they are AOT already)."""
        self._hlo_cost = bool(on)

    def hlo_cost_enabled(self) -> bool:
        return self._hlo_cost

    # -- recording -------------------------------------------------------
    def _append(self, ev: Dict):
        with self._lock:
            ev["seq"] = self._seq
            self._seq += 1
            self._events.append(ev)

    def record_step(self, kind: str, wall_ms: float, *, steps: int = 1,
                    program: Optional[str] = None,
                    device_ms: Optional[float] = None,
                    feed_bytes: int = 0, fetch_bytes: int = 0):
        ev = {"type": "step", "ts": time.time(), "kind": kind,
              "wall_ms": round(wall_ms, 4), "steps": steps,
              "feed_bytes": int(feed_bytes), "fetch_bytes": int(fetch_bytes)}
        if program is not None:
            ev["program"] = program
        if device_ms is not None:
            ev["device_ms"] = round(device_ms, 4)
        self._append(ev)
        # mirror into the distributed-tracing flight recorder (rate-
        # sampled like request traces, under the process-scoped id) so a
        # trainer's steps land on the same trace_dump waterfall/clock as
        # the serving spans; free when PADDLE_TPU_TRACE_SAMPLE is 0
        if tracing.sampled():
            tracing.record_span(tracing.process_trace_id(), "train.step",
                                dur_ms=wall_ms, kind=kind, steps=steps)

    def record_compile(self, kind: str, program: Optional[str] = None, *,
                       wall_ms: Optional[float] = None,
                       trace_ms: Optional[float] = None,
                       xla_ms: Optional[float] = None,
                       cache: str = "miss",
                       flops: Optional[float] = None,
                       bytes_accessed: Optional[float] = None):
        """``trace_ms`` is jax trace + StableHLO lowering (``fn.lower()``);
        ``xla_ms`` is the XLA backend compile (``lowered.compile()``) —
        usually the dominant term, and the one to blame for a slow first
        step."""
        ev = {"type": "compile", "ts": time.time(), "kind": kind,
              "cache": cache}
        if program is not None:
            ev["program"] = program
        for name, val in (("wall_ms", wall_ms), ("trace_ms", trace_ms),
                          ("xla_ms", xla_ms)):
            if val is not None:
                ev[name] = round(val, 4)
        if flops is not None:
            ev["flops"] = flops
        if bytes_accessed is not None:
            ev["bytes_accessed"] = bytes_accessed
        self._append(ev)

    # -- reading ---------------------------------------------------------
    def snapshot(self) -> Dict:
        """JSON-able view: events oldest-first plus ring-buffer accounting
        (`dropped` = events that aged out of the buffer)."""
        with self._lock:
            events = [dict(e) for e in self._events]
            return {"capacity": self._events.maxlen,
                    "recorded": self._seq,
                    "dropped": self._seq - len(events),
                    "events": events}

    def events(self, type: Optional[str] = None) -> List[Dict]:
        with self._lock:
            evs = [dict(e) for e in self._events]
        if type is not None:
            evs = [e for e in evs if e["type"] == type]
        return evs

    def to_json(self, indent: Optional[int] = None) -> str:
        return json.dumps(self.snapshot(), indent=indent, sort_keys=True)

    def reset(self):
        with self._lock:
            self._events.clear()
            self._seq = 0


TIMELINE = StepTimeline()


def get_timeline() -> StepTimeline:
    return TIMELINE
