"""paddle_tpu.observability — structured metrics + step timeline.

The signals that matter for a framework whose whole Program executes as
ONE fused XLA computation: compile events and compile-cache behavior
(executor.py), per-step host/device time and feed/fetch volumes
(Executor.run / run_loop / ParallelExecutor.run), serving latency and
batch-size distribution (Predictor / PredictorServer), and bench phase
accounting (bench.py). Everything records into one process-wide
``MetricRegistry`` (metrics.py) and one bounded ``StepTimeline``
(timeline.py); export.py renders Prometheus text / JSON, and
``PredictorServer.start_http()`` serves it at ``GET /metrics``.

The legacy ``paddle_tpu.profiler`` module is a compatibility shim over
this registry (its event table lives in the
``paddle_tpu_profiler_event_ms`` summary).
"""
from __future__ import annotations

import weakref
from typing import Optional

from . import export, metrics, timeline, tracing  # noqa: F401
from .metrics import (  # noqa: F401
    DEFAULT_SIZE_BUCKETS, MetricRegistry, REGISTRY, get_registry,
    process_labels, set_replica,
)
from .timeline import TIMELINE, StepTimeline, get_timeline, hlo_cost_stats  # noqa: F401

__all__ = [
    "REGISTRY", "TIMELINE", "get_registry", "get_timeline",
    "MetricRegistry", "StepTimeline", "metrics", "timeline", "export",
    "program_fp", "observe_run", "reset_all", "hlo_cost_stats", "nbytes_of",
    # shared instruments
    "COMPILE_TOTAL", "COMPILE_LATENCY_MS", "CACHE_HITS", "CACHE_MISSES",
    "CACHE_EVICTIONS", "STEP_LATENCY_MS", "STEPS_TOTAL", "FEED_BYTES",
    "FETCH_BYTES", "RUN_LOOP_WINDOW_STEPS", "READER_PREFETCH_EVENTS",
    "READER_PREFETCH_DEPTH", "READER_PULL_MS", "LOADER_BATCHES",
    "LOADER_BLOCKED_MS", "LOADER_WORKER_BUSY_MS", "LOADER_QUEUE_DEPTH",
    "LOADER_WORKERS", "PREDICT_LATENCY_MS", "PREDICT_REQUESTS",
    "PREDICT_BATCH_ROWS", "PREDICT_FAILURES", "PROFILER_EVENT_MS",
    "BENCH_ANOMALY_RETRIES", "SERVER_ROWS", "SERVER_BUCKET_FILL",
    "SERVER_INFLIGHT_DEPTH", "SERVER_STAGE_MS", "AOT_CACHE_BYTES",
    "AOT_CACHE_WRITTEN_BYTES", "AOT_CACHE_EVICTIONS", "AOT_CACHE_CORRUPT",
    "AOT_CACHE_ERRORS", "AOT_COMPILE_MS", "ANALYSIS_ISSUES",
    "ANALYSIS_COVERAGE", "set_replica", "process_labels",
    "FLEET_WORKERS", "FLEET_OUTSTANDING", "FLEET_DISPATCHES",
    "FLEET_REQUEUED", "FLEET_MISVERSIONED", "FLEET_BACKPRESSURE_MS",
    "FLEET_SHED", "FLEET_PENDING", "FLEET_AUTOSCALE",
    "DECODE_TOKENS", "DECODE_SLOTS", "DECODE_STEP_MS", "DECODE_REQUESTS",
    "DECODE_PREFIX_QUERIES", "DECODE_PREFIX_HITS", "DECODE_PREFIX_BYTES",
    "DECODE_SPEC_PROPOSED", "DECODE_SPEC_ACCEPTED",
    "CKPT_SAVES", "CKPT_BYTES", "CKPT_PENDING", "CKPT_SAVE_MS",
    "CKPT_RESTORE_MS", "CKPT_RETRIES", "CKPT_FAILURES",
    "SWAP_TOTAL", "SWAP_MS", "TRAIN_SKIPPED_BATCHES", "FLEET_WEDGED",
    "REQUEST_PHASE_MS", "TRACE_SPANS", "tracing",
    "TRANSPILE_OPS_REMOVED", "TRANSPILE_OPS_FUSED", "TRANSPILE_PASS_MS",
    "QUANT_CALIB_BATCHES", "QUANT_OPS", "QUANT_PARITY",
]

# -- the shared instrument set (registered once, process-wide) -----------

COMPILE_TOTAL = REGISTRY.counter(
    "paddle_tpu_compile_total",
    "Program compilations (trace + XLA compile), by executor kind")
COMPILE_LATENCY_MS = REGISTRY.histogram(
    "paddle_tpu_compile_latency_ms",
    "Wall time of each compilation (first call: trace+compile+run)")
CACHE_HITS = REGISTRY.counter(
    "paddle_tpu_compile_cache_hits_total",
    "Compile-cache hits, by kind, program fingerprint, and "
    "tier=memory|disk (disk = persistent AOT executable store)")
CACHE_MISSES = REGISTRY.counter(
    "paddle_tpu_compile_cache_misses_total",
    "Compile-cache misses, by kind, program fingerprint, and "
    "tier=memory|disk")
CACHE_EVICTIONS = REGISTRY.counter(
    "paddle_tpu_compile_cache_evictions_total",
    "Compile-cache LRU evictions (cap: PADDLE_TPU_COMPILE_CACHE_MAX)")
STEP_LATENCY_MS = REGISTRY.histogram(
    "paddle_tpu_step_latency_ms",
    "Wall time per executor dispatch (run: one step; loop: one window)")
STEPS_TOTAL = REGISTRY.counter(
    "paddle_tpu_steps_total", "Training/inference steps executed")
FEED_BYTES = REGISTRY.counter(
    "paddle_tpu_feed_bytes_total", "Bytes fed into executed programs")
FETCH_BYTES = REGISTRY.counter(
    "paddle_tpu_fetch_bytes_total", "Bytes fetched out of executed programs")
RUN_LOOP_WINDOW_STEPS = REGISTRY.histogram(
    "paddle_tpu_run_loop_window_steps",
    "Per-call reader/loop window length (truncation shows up as mass "
    "below `steps`)", buckets=DEFAULT_SIZE_BUCKETS)
READER_PREFETCH_EVENTS = REGISTRY.counter(
    "paddle_tpu_reader_prefetch_events_total",
    "Reader double-buffer lifecycle: staged / used / flushed / error")
READER_PREFETCH_DEPTH = REGISTRY.gauge(
    "paddle_tpu_reader_prefetch_depth",
    "Programs with a device-staged next window right now")
READER_PULL_MS = REGISTRY.counter(
    "paddle_tpu_reader_pull_ms_total",
    "Host time the executor spent pulling reader batches before dispatch, "
    "by kind=run|loop (input-bound when this rivals step latency)")
LOADER_BATCHES = REGISTRY.counter(
    "paddle_tpu_loader_batches_total",
    "DataLoader batches delivered, by loader and transport="
    "shm|pickle|inline (pickle = batch outgrew the slot or object dtype)")
LOADER_BLOCKED_MS = REGISTRY.counter(
    "paddle_tpu_loader_blocked_ms_total",
    "Time DataLoader consumers spent blocked in next() (starvation "
    "fraction = this / wall time)")
LOADER_WORKER_BUSY_MS = REGISTRY.counter(
    "paddle_tpu_loader_worker_busy_ms_total",
    "Summed DataLoader worker decode+assemble time (utilization = this / "
    "(workers x wall time))")
LOADER_QUEUE_DEPTH = REGISTRY.gauge(
    "paddle_tpu_loader_queue_depth",
    "Ready DataLoader batches buffered consumer-side right now "
    "(0 while blocked = workers can't keep up)")
LOADER_WORKERS = REGISTRY.gauge(
    "paddle_tpu_loader_workers", "Worker processes per running DataLoader")
PREDICT_LATENCY_MS = REGISTRY.histogram(
    "paddle_tpu_predict_latency_ms",
    "Predictor request latency (path=direct|server; server includes queue "
    "wait)")
PREDICT_REQUESTS = REGISTRY.counter(
    "paddle_tpu_predict_requests_total", "Predictor requests served")
PREDICT_BATCH_ROWS = REGISTRY.histogram(
    "paddle_tpu_predict_batch_rows",
    "Rows per executed predict batch (server: dynamic batch fill)",
    buckets=DEFAULT_SIZE_BUCKETS)
PREDICT_FAILURES = REGISTRY.counter(
    "paddle_tpu_predict_failures_total",
    "Predict requests completed with an error, by path (error rate = "
    "this / paddle_tpu_predict_requests_total)")
SERVER_ROWS = REGISTRY.counter(
    "paddle_tpu_server_rows_total",
    "Rows through the serving device stage, kind=real|pad "
    "(pad-waste ratio = pad / (real + pad))")
SERVER_BUCKET_FILL = REGISTRY.histogram(
    "paddle_tpu_server_bucket_fill",
    "Real rows per executed server batch, labeled by the padded bucket "
    "size it ran at (fill efficiency per compiled signature)",
    buckets=DEFAULT_SIZE_BUCKETS)
SERVER_INFLIGHT_DEPTH = REGISTRY.gauge(
    "paddle_tpu_server_inflight_depth",
    "Stacked batches waiting for the serving device stage right now "
    "(0 = device-bound, at capacity = host-bound)")
SERVER_STAGE_MS = REGISTRY.histogram(
    "paddle_tpu_server_stage_ms",
    "Per-batch wall time of each serving pipeline stage "
    "(stage=stack|device)")
AOT_CACHE_BYTES = REGISTRY.gauge(
    "paddle_tpu_aot_cache_bytes",
    "On-disk size of the persistent AOT executable cache after the last "
    "store/GC, by cache dir")
AOT_CACHE_WRITTEN_BYTES = REGISTRY.counter(
    "paddle_tpu_aot_cache_written_bytes_total",
    "Serialized executable bytes written to the AOT disk cache")
AOT_CACHE_EVICTIONS = REGISTRY.counter(
    "paddle_tpu_aot_cache_evictions_total",
    "AOT disk-cache entries evicted by the mtime-LRU GC "
    "(bound: PADDLE_TPU_AOT_CACHE_MAX_BYTES)")
AOT_CACHE_CORRUPT = REGISTRY.counter(
    "paddle_tpu_aot_cache_corrupt_total",
    "Unreadable AOT cache payloads, reason=blob|sidecar (blobs are "
    "quarantined *.corrupt and recompiled — never a crash)")
AOT_CACHE_ERRORS = REGISTRY.counter(
    "paddle_tpu_aot_cache_errors_total",
    "AOT disk-cache operations that degraded to compile-only, by "
    "op=serialize|store (e.g. read-only cache dir)")
AOT_COMPILE_MS = REGISTRY.histogram(
    "paddle_tpu_aot_compile_ms",
    "Executable acquisition wall time on the AOT path, by kind and "
    "path=cold (explicit lower+XLA compile) | warm (disk deserialize) — "
    "the cold-start-vs-warm-start distribution")
ANALYSIS_ISSUES = REGISTRY.counter(
    "paddle_tpu_analysis_issues_total",
    "Static-analyzer findings, by diagnostic code and severity "
    "(analysis/: shape-mismatch, use-before-def, tpu-dynamic-shape, "
    "recompile-risk, dead-op, ...)")
ANALYSIS_COVERAGE = REGISTRY.gauge(
    "paddle_tpu_analysis_infer_coverage",
    "Fraction of a program's op instances covered by a registered "
    "shape/dtype inference rule, per program fingerprint")
TRANSPILE_OPS_REMOVED = REGISTRY.counter(
    "paddle_tpu_transpile_ops_removed_total",
    "Ops deleted by the optimizing transpiler, by pass="
    "constant_fold|cse|dce|conv_bn_fold (transpiler/passes/)")
TRANSPILE_OPS_FUSED = REGISTRY.counter(
    "paddle_tpu_transpile_ops_fused_total",
    "Source ops folded INTO a fused op by the fusion passes, by pass "
    "(3 means mul+elementwise_add+relu became one fused_fc)")
TRANSPILE_PASS_MS = REGISTRY.histogram(
    "paddle_tpu_transpile_passes_ms",
    "Wall time per optimizing-transpiler pass invocation, by pass")
QUANT_CALIB_BATCHES = REGISTRY.counter(
    "paddle_tpu_quant_calib_batches_total",
    "Sample batches streamed through quant.calibrate (activation-amax "
    "collection for int8 post-training quantization)")
QUANT_OPS = REGISTRY.counter(
    "paddle_tpu_quant_quantized_ops_total",
    "Ops the level-3 quantize pass rewrote onto int8 kernels, by the "
    "source op type (op=mul|matmul|fused_fc|conv2d)")
QUANT_PARITY = REGISTRY.gauge(
    "paddle_tpu_quant_parity_max_abs_diff",
    "Max abs logits difference of the last quant.parity_report run "
    "(quantized vs float on the same feeds) — the drift the int8 tier "
    "is currently serving at")
FLEET_WORKERS = REGISTRY.gauge(
    "paddle_tpu_fleet_workers",
    "Router view of worker replicas by state=starting|ready|draining|"
    "stopped|dead (recorded in the ROUTER process)")
FLEET_OUTSTANDING = REGISTRY.gauge(
    "paddle_tpu_fleet_outstanding",
    "Requests dispatched to a replica and not yet answered, by replica "
    "(at max_outstanding on every replica = fleet saturated, router "
    "backpressures)")
FLEET_DISPATCHES = REGISTRY.counter(
    "paddle_tpu_fleet_dispatches_total",
    "Request frames the router forwarded, by replica (balance skew = "
    "max/min across replicas)")
FLEET_REQUEUED = REGISTRY.counter(
    "paddle_tpu_fleet_requeued_total",
    "In-flight frames re-dispatched after their worker died (predict is "
    "stateless/idempotent, so replay is safe)")
FLEET_MISVERSIONED = REGISTRY.counter(
    "paddle_tpu_fleet_misversioned_total",
    "Responses whose program version differed from the one their "
    "request was dispatched under (must stay 0 through drain/restart "
    "and hot swaps)")
FLEET_BACKPRESSURE_MS = REGISTRY.counter(
    "paddle_tpu_fleet_backpressure_ms_total",
    "Router dispatch time blocked because every routable replica was at "
    "max_outstanding (rivaling wall time = add replicas or raise the "
    "window)")
FLEET_SHED = REGISTRY.counter(
    "paddle_tpu_fleet_shed_total",
    "Requests rejected by bounded-latency load shedding, by SLO class — "
    "every shed is an explicit structured RejectedError to the client, "
    "never a timeout (nonzero = the fleet is declining work to protect "
    "deadlines: add replicas or lower the offered load)")
FLEET_PENDING = REGISTRY.gauge(
    "paddle_tpu_fleet_pending",
    "Requests waiting in the router's priority dispatch queue right now, "
    "by SLO class (growing while replicas idle = dispatch-bound; growing "
    "at max_outstanding everywhere = fleet saturated)")
FLEET_AUTOSCALE = REGISTRY.counter(
    "paddle_tpu_fleet_autoscale_total",
    "Autoscaler actions, by direction=up (replica added) | down "
    "(drain-shrink) | heal (dead replica reaped and replaced)")
DECODE_TOKENS = REGISTRY.counter(
    "paddle_tpu_decode_tokens_total",
    "Tokens generated by the KV-cache decode path, by kind=prefill "
    "(prompt tokens absorbed) | decode (sampled tokens)")
DECODE_SLOTS = REGISTRY.gauge(
    "paddle_tpu_decode_slots",
    "Continuous-batching cache-slot occupancy, state=active|free "
    "(active at the slot cap with a non-empty admission queue = grow "
    "slots or add replicas)")
DECODE_STEP_MS = REGISTRY.histogram(
    "paddle_tpu_decode_step_ms",
    "Wall time per decode iteration, stage=prefill (one admission "
    "sub-batch) | step (one token across every active slot)")
DECODE_REQUESTS = REGISTRY.counter(
    "paddle_tpu_decode_requests_total",
    "Decode-serving sequences, kind=admitted (entered a cache slot) | "
    "retired (finished and freed it); admitted - retired = in flight")
DECODE_PREFIX_QUERIES = REGISTRY.counter(
    "paddle_tpu_decode_prefix_queries_total",
    "Shared-prefix store lookups at admission (one per admitted "
    "prompt when prefix sharing is on)")
DECODE_PREFIX_HITS = REGISTRY.counter(
    "paddle_tpu_decode_prefix_hits_total",
    "Shared-prefix store hits, by kind=full (whole prompt served from "
    "cached K/V rows) | partial (cached header + suffix extension) | "
    "batch (deduped against an identical prompt admitted in the same "
    "sub-batch); hit rate = hits / queries — the ROADMAP-named signal")
DECODE_PREFIX_BYTES = REGISTRY.gauge(
    "paddle_tpu_decode_prefix_bytes",
    "Resident bytes of prefilled K/V rows in the shared-prefix store "
    "(bounded by PADDLE_TPU_PREFIX_CACHE_MAX_BYTES; refcounted entries "
    "are eviction-exempt while sequences decode from them)")
DECODE_SPEC_PROPOSED = REGISTRY.counter(
    "paddle_tpu_decode_spec_proposed_total",
    "Draft tokens proposed to speculative verify windows")
DECODE_SPEC_ACCEPTED = REGISTRY.counter(
    "paddle_tpu_decode_spec_accepted_total",
    "Draft tokens the target accepted; acceptance rate = accepted / "
    "proposed — the signal that decides whether speculation pays "
    "(each verified round also emits one bonus token not counted here)")
CKPT_SAVES = REGISTRY.counter(
    "paddle_tpu_ckpt_saves_total",
    "Checkpoint saves, by mode=async|sync and result=ok|error (async = "
    "background writer off the step path; sync = degraded or explicit)")
CKPT_BYTES = REGISTRY.counter(
    "paddle_tpu_ckpt_bytes",
    "Bytes durably written into complete checkpoints (persistables npz "
    "+ meta + sentinel)")
CKPT_PENDING = REGISTRY.gauge(
    "paddle_tpu_ckpt_pending",
    "Snapshots queued for the background checkpoint writer right now "
    "(at max_pending = the trainer blocks: bounded staleness, never "
    "dropped saves)")
CKPT_SAVE_MS = REGISTRY.histogram(
    "paddle_tpu_ckpt_save_ms",
    "Wall time per checkpoint write, by mode=async (inside the writer "
    "thread, off the step path) | sync (paid by the training step) | "
    "snapshot (the on-step-path state copy an async save starts with)")
CKPT_RESTORE_MS = REGISTRY.histogram(
    "paddle_tpu_ckpt_restore_ms",
    "Wall time to load the newest complete checkpoint at resume")
CKPT_RETRIES = REGISTRY.counter(
    "paddle_tpu_ckpt_retries_total",
    "Checkpoint write attempts retried after a transient IO error "
    "(exponential backoff; exhaustion degrades the manager to "
    "synchronous saves)")
CKPT_FAILURES = REGISTRY.counter(
    "paddle_tpu_ckpt_failures_total",
    "Checkpoint saves that failed every retry — surfaced as a warning "
    "+ degraded mode, never silently skipped")
SWAP_TOTAL = REGISTRY.counter(
    "paddle_tpu_swap_total",
    "Hot model swaps through serving.swap.SwapController, by "
    "result=ok (version flipped, old replicas retired) | rollback "
    "(validation/spawn/canary/flip failure — the old version never "
    "stopped serving and the fleet is restored)")
SWAP_MS = REGISTRY.histogram(
    "paddle_tpu_swap_ms",
    "Wall time of hot-swap phases, phase=spawn (surge replicas on the "
    "new version, warm-AOT) | canary (live-request parity probes) | "
    "retire (drain + stop the old version) | total")
TRAIN_SKIPPED_BATCHES = REGISTRY.counter(
    "paddle_tpu_train_skipped_batches_total",
    "Input the hardened training data plane dropped instead of "
    "crashing or poisoning parameters, by reason=nonfinite (in-graph "
    "NaN/Inf sentinel zeroed the update and quarantined the batch) | "
    "corrupt_chunk (tolerant recordio chunk skip+resync) | "
    "corrupt_record (record whose payload no longer unpickles)")
FLEET_WEDGED = REGISTRY.counter(
    "paddle_tpu_fleet_wedged_total",
    "Live-but-hung replicas the router's watchdog reaped: outstanding "
    "work with no completion past wedge_timeout_s — the worker is "
    "SIGKILLed and its in-flight frames requeue exactly like a crash "
    "(nonzero = raise wedge_timeout_s or investigate stuck device "
    "dispatches)")
REQUEST_PHASE_MS = REGISTRY.histogram(
    "paddle_tpu_request_phase_ms",
    "Per-phase latency attribution of TRACED serving requests, by "
    "phase=queue (router admission -> dispatch) | service (dispatch -> "
    "reply, the whole worker round trip) | stack | device (the worker-"
    "side stages) | total (submit -> reply). Folded from trace spans as "
    "requests complete, so mass appears only while "
    "PADDLE_TPU_TRACE_SAMPLE > 0 — the attributed view of "
    "paddle_tpu_predict_latency_ms")
TRACE_SPANS = REGISTRY.counter(
    "paddle_tpu_trace_spans_total",
    "Trace spans recorded by this process's flight recorder, by "
    "phase=span name (client.submit, router.dispatch, worker.recv, "
    "server.device, decode.retire, ...) — nonzero means sampling is "
    "live; compare against the recorder's dropped count in /trace.json")
tracing._SPANS_TOTAL = TRACE_SPANS
PROFILER_EVENT_MS = REGISTRY.summary(
    "paddle_tpu_profiler_event_ms",
    "Legacy profiler event table (exact count/sum/min/max per event)")
BENCH_ANOMALY_RETRIES = REGISTRY.counter(
    "paddle_tpu_bench_anomaly_retry_total",
    "bench.py transient-contention re-measurements, by phase")


# -- helpers -------------------------------------------------------------

# fingerprint cache: Program.fingerprint() json-serializes the whole
# program — fine once per compile, far too hot for once per step. Weak
# keys so a dead program's entry dies with it (same reasoning as the
# executor's per-program step counters).
_FP_CACHE: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()


def program_fp(program) -> str:
    """Short (8-hex) fingerprint of a Program, cached per version."""
    try:
        entry = _FP_CACHE.get(program)
        version = getattr(program, "_version", None)
        if entry is None or entry[0] != version:
            entry = (version, program.fingerprint()[:8])
            _FP_CACHE[program] = entry
        return entry[1]
    except Exception:  # fingerprinting must never break execution
        return "%08x" % (id(program) & 0xFFFFFFFF)


def observe_run(kind: str, wall_s: float, *, steps: int = 1,
                program: Optional[str] = None, compiled: bool = False,
                hlo: Optional[dict] = None,
                feed_bytes: int = 0, fetch_bytes: int = 0,
                device_ms: Optional[float] = None):
    """One executor dispatch -> registry + timeline, in one call (keeps
    the executor hot path to a single function call). ``compiled=True``
    marks a first call (the lazy jit's trace+compile happened inside it);
    ``hlo`` carries the opt-in trace/lower split and cost estimates from
    ``Executor._hlo_compile_stats``."""
    wall_ms = wall_s * 1e3
    STEP_LATENCY_MS.observe(wall_ms, kind=kind)
    STEPS_TOTAL.inc(steps, kind=kind)
    if feed_bytes:
        FEED_BYTES.inc(feed_bytes, kind=kind)
    if fetch_bytes:
        FETCH_BYTES.inc(fetch_bytes, kind=kind)
    if compiled:
        COMPILE_TOTAL.inc(kind=kind)
        COMPILE_LATENCY_MS.observe(wall_ms, kind=kind)
        TIMELINE.record_compile(kind, program, wall_ms=wall_ms,
                                **(hlo or {}))
    TIMELINE.record_step(kind, wall_ms, steps=steps, program=program,
                         device_ms=device_ms, feed_bytes=feed_bytes,
                         fetch_bytes=fetch_bytes)


def nbytes_of(values) -> int:
    """Total nbytes across an iterable of arrays (jax or numpy); values
    without a known size count 0 — accounting must never throw."""
    total = 0
    for v in values:
        n = getattr(v, "nbytes", None)
        if n is None:
            size = getattr(v, "size", None)
            itemsize = getattr(getattr(v, "dtype", None), "itemsize", None)
            n = size * itemsize if size is not None and itemsize else 0
        total += int(n)
    return total


def reset_all():
    """Zero the registry and clear the timeline + trace recorder (the
    registry-wide reset the legacy ``profiler.reset_profiler``
    delegates to)."""
    REGISTRY.reset()
    TIMELINE.reset()
    tracing.reset()
