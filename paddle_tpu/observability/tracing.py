"""Distributed request tracing: per-request spans + a flight recorder.

The fleet's histograms say *how slow*; a trace says *where the time
went*. A trace is a ``trace_id`` (16 hex chars, minted once at the
client edge) plus the spans every process records against it while the
request moves client -> router queue -> dispatch -> worker channel ->
stacking -> device step -> reply. The id rides the serving wire as an
optional ``b"T"`` header (``wire.pack_trace``), so a crash-requeue
re-dispatches the ORIGINAL header-carrying bytes and the trace survives
a SIGKILL for free; a bare pre-trace frame is still valid byte for
byte, and workers strip the header defensively like the SLO one.

Sampling is decided ONCE, at the client edge (``maybe_start``): the
``PADDLE_TPU_TRACE_SAMPLE`` rate (default 0.0 — tracing is OFF and the
wire is byte-identical to the pre-trace form; the PR-15 tap-cost
lesson). Downstream processes never consult the rate — they record
spans iff the header arrived, which is what makes the worker side
zero-config: an un-sampled request takes the exact pre-trace code path.

Each process keeps ONE bounded ``TraceRecorder`` ring (the StepTimeline
pattern: O(1) append, ``dropped`` accounting, never unbounded memory).
``Router.fleet_trace()`` pulls every worker's ring over the existing
control pipe (the ``fleet_metrics()`` pattern) and merges them into a
single span list — exported at ``GET /trace.json`` and rendered by
``tools/trace_dump.py`` as a per-request text waterfall or Chrome
trace-event JSON (Perfetto-loadable).

Span timestamps are wall-clock ``time.time()`` starts: the fleet's
processes share one machine/clock, so cross-process ordering within a
trace is meaningful (to clock granularity). ``ts`` is the span START;
``dur_ms`` may be 0 for instant events.

Multi-stage servers (worker recv -> PredictorServer stack -> device ->
reply) correlate through a process-local ``rid -> trace_id`` binding
table: the ingress path binds, every stage records via ``rid_span``
(a dict probe when tracing is live, one falsy check when it is not),
and the future fan-out pops. The ``paddle_tpu_trace_spans_total``
counter hook is injected by ``observability/__init__`` after instrument
registration — tracing.py itself imports nothing above ``metrics``.
"""
from __future__ import annotations

import collections
import os
import random
import threading
import time
from typing import Dict, Iterable, List, Optional

from .metrics import process_labels

__all__ = [
    "TraceRecorder", "RECORDER", "get_recorder", "new_trace_id",
    "sample_rate", "set_sample_rate", "sampled", "maybe_start",
    "record_span",
    "bind_rid", "rid_trace", "pop_rid", "rid_span", "bound",
    "process_trace_id", "snapshot", "merge_snapshots", "reset",
]

_DEFAULT_CAP = 4096


def _env_rate() -> float:
    try:
        rate = float(os.environ.get("PADDLE_TPU_TRACE_SAMPLE", "0") or 0.0)
    except ValueError:
        return 0.0
    return min(1.0, max(0.0, rate))


class TraceRecorder:
    """Bounded ring of span records (one per process; see module doc)."""

    def __init__(self, capacity: Optional[int] = None):
        if capacity is None:
            try:
                capacity = int(os.environ.get("PADDLE_TPU_TRACE_CAP",
                                              _DEFAULT_CAP))
            except ValueError:
                capacity = _DEFAULT_CAP
        self._lock = threading.Lock()
        self._spans = collections.deque(maxlen=max(1, capacity))
        self._seq = 0  # total spans ever recorded

    def record(self, trace_id: str, name: str, *,
               ts: Optional[float] = None, dur_ms: float = 0.0,
               **attrs) -> None:
        """Append one span. ``ts`` defaults to ``now - dur`` (the span
        START; callers time a phase then record it after the fact)."""
        if ts is None:
            ts = time.time() - dur_ms / 1e3
        span = {"trace_id": trace_id, "name": name, "ts": ts,
                "dur_ms": round(float(dur_ms), 4)}
        if attrs:
            span.update(attrs)
        with self._lock:
            span["seq"] = self._seq
            self._seq += 1
            self._spans.append(span)
        if _SPANS_TOTAL is not None:
            _SPANS_TOTAL.inc(phase=name)

    def snapshot(self) -> Dict:
        """JSON-able view: spans oldest-first plus ring accounting
        (``dropped`` = spans that aged out), stamped with this process's
        replica identity (empty string in an unlabeled process)."""
        with self._lock:
            spans = [dict(s) for s in self._spans]
            return {"capacity": self._spans.maxlen,
                    "recorded": self._seq,
                    "dropped": self._seq - len(spans),
                    "replica": process_labels().get("replica", ""),
                    "spans": spans}

    def spans(self, trace_id: Optional[str] = None) -> List[Dict]:
        with self._lock:
            spans = [dict(s) for s in self._spans]
        if trace_id is not None:
            spans = [s for s in spans if s["trace_id"] == trace_id]
        return spans

    def reset(self) -> None:
        with self._lock:
            self._spans.clear()
            self._seq = 0


RECORDER = TraceRecorder()

# paddle_tpu_trace_spans_total counter, injected by observability/__init__
# after instrument registration (avoids a circular import at load time)
_SPANS_TOTAL = None

_rate = _env_rate()
_rand = random.Random()

# rid -> trace_id for requests in flight through a multi-stage server in
# THIS process. Bounded by the server's own in-flight bound (futures are
# popped on completion/failure, and _pop hooks pop the binding too).
_rids: Dict[int, str] = {}
_rids_lock = threading.Lock()

# lazily-minted stable id for process-scoped spans (trainer steps,
# profiler events) that belong to no request
_proc_tid: Optional[str] = None


def get_recorder() -> TraceRecorder:
    return RECORDER


def new_trace_id() -> str:
    """16 hex chars of OS entropy — unique across the fleet's processes
    (a PRNG seeded identically in forked workers would collide)."""
    return os.urandom(8).hex()


def sample_rate() -> float:
    return _rate


def set_sample_rate(rate: float) -> None:
    """Runtime override of ``PADDLE_TPU_TRACE_SAMPLE`` for THIS process.
    Only processes that mint traces (clients / the router) need it —
    workers record on header arrival and never consult the rate."""
    global _rate
    _rate = min(1.0, max(0.0, float(rate)))


def sampled() -> bool:
    """One rate check with no id minting — for process-scoped spans
    (trainer steps) that rate-sample individually and record under
    ``process_trace_id()`` instead of a per-request trace."""
    if _rate <= 0.0:
        return False
    return _rate >= 1.0 or _rand.random() < _rate


def maybe_start() -> Optional[str]:
    """The ONE sampling decision, at the client edge: a fresh trace_id
    at the configured rate, else None (request travels untraced on the
    byte-identical pre-trace wire form)."""
    if _rate <= 0.0:
        return None
    if _rate < 1.0 and _rand.random() >= _rate:
        return None
    return new_trace_id()


def record_span(trace_id: str, name: str, *, ts: Optional[float] = None,
                dur_ms: float = 0.0, **attrs) -> None:
    RECORDER.record(trace_id, name, ts=ts, dur_ms=dur_ms, **attrs)


def process_trace_id() -> str:
    """Stable trace_id for process-scoped spans (train steps, profiler
    events) — one synthetic 'trace' per process lifetime."""
    global _proc_tid
    if _proc_tid is None:
        _proc_tid = "proc" + new_trace_id()[:12]
    return _proc_tid


# -- rid binding (multi-stage servers) -----------------------------------

def bind_rid(rid: int, trace_id: str) -> None:
    with _rids_lock:
        _rids[rid] = trace_id


def rid_trace(rid: int) -> Optional[str]:
    if not _rids:  # the common untraced case: one falsy check, no lock
        return None
    with _rids_lock:
        return _rids.get(rid)


def pop_rid(rid: int) -> Optional[str]:
    if not _rids:
        return None
    with _rids_lock:
        return _rids.pop(rid, None)


def bound() -> bool:
    """True iff any in-flight request in this process is traced — the
    cheap gate server stage loops check before doing span bookkeeping."""
    return bool(_rids)


def rid_span(rid: int, name: str, *, dur_ms: float = 0.0,
             **attrs) -> None:
    """Record a span against the trace bound to ``rid``, if any. The
    untraced fast path is one falsy dict check."""
    tid = rid_trace(rid)
    if tid is not None:
        RECORDER.record(tid, name, dur_ms=dur_ms, **attrs)


# -- snapshots / fleet merge ---------------------------------------------

def snapshot() -> Dict:
    return RECORDER.snapshot()


def merge_snapshots(snaps: Iterable[Dict]) -> Dict:
    """One fleet-wide span list from per-process recorder snapshots
    (the ``merge_json_snapshots`` idea, for traces): each span is
    stamped with its origin replica, the whole list is ts-sorted so a
    single trace reads as a waterfall, and ring accounting sums."""
    spans: List[Dict] = []
    replicas: List[str] = []
    recorded = dropped = 0
    for snap in snaps:
        if not snap:
            continue
        replica = snap.get("replica", "") or "router"
        replicas.append(replica)
        recorded += int(snap.get("recorded", 0))
        dropped += int(snap.get("dropped", 0))
        for s in snap.get("spans", ()):
            s = dict(s)
            s.setdefault("replica", replica)
            spans.append(s)
    spans.sort(key=lambda s: (s["trace_id"], s["ts"], s.get("seq", 0)))
    return {"replicas": replicas, "recorded": recorded,
            "dropped": dropped, "spans": spans}


def reset() -> None:
    """Clear the ring AND the rid binding table (test isolation; the
    ``observability.reset_all()`` hook)."""
    RECORDER.reset()
    with _rids_lock:
        _rids.clear()
