"""LayerHelper (reference: python/paddle/fluid/layer_helper.py).

Shared machinery for all `layers.*` functions: creates parameters in both
the main program (as Parameter vars) and the startup program (with their
init ops), creates temp output variables, and appends bias/activation ops.
"""
from __future__ import annotations

import numpy as np

from .framework import unique_name
from .framework.core import default_main_program, default_startup_program
from .initializer import ConstantInitializer, XavierInitializer
from .param_attr import ParamAttr

__all__ = ["LayerHelper"]


class LayerHelper:
    def __init__(self, layer_type: str, **kwargs):
        self.kwargs = kwargs
        self.layer_type = layer_type
        name = self.kwargs.get("name")
        if name is None:
            self.kwargs["name"] = unique_name.generate(layer_type)

    @property
    def name(self):
        return self.kwargs["name"]

    @property
    def main_program(self):
        return default_main_program()

    @property
    def startup_program(self):
        return default_startup_program()

    # -- parameters ------------------------------------------------------
    def create_parameter(self, attr, shape, dtype, is_bias=False, default_initializer=None):
        attr = ParamAttr._to_attr(attr)
        if attr is False:
            return None
        if default_initializer is None:
            if is_bias:
                attr.set_default_bias_initializer()
            else:
                attr.set_default_param_initializer()
        else:
            attr.set_default_initializer(default_initializer)
        if attr.name is None:
            suffix = "b" if is_bias else "w"
            attr.name = unique_name.generate(".".join([self.name, suffix]))

        shape = [int(s) for s in shape]
        main_block = self.main_program.current_block()
        param = main_block.create_parameter(
            name=attr.name, shape=shape, dtype=dtype, **{
                k: v for k, v in attr._to_kwargs().items() if k != "name"
            }
        )
        # mirrored in the startup program with its init op
        startup_block = self.startup_program.global_block()
        if attr.name not in startup_block.vars:
            svar = startup_block.create_parameter(
                name=attr.name, shape=shape, dtype=dtype, trainable=attr.trainable
            )
            attr.initializer(svar, startup_block)
        return param

    def create_variable_for_type_inference(self, dtype, shape=(), stop_gradient=False):
        return self.main_program.current_block().create_var(
            name=unique_name.generate(".".join([self.name, "tmp"])),
            dtype=dtype,
            shape=shape,
            stop_gradient=stop_gradient,
        )

    # old paddle name
    create_tmp_variable = create_variable_for_type_inference

    def create_variable(self, **kwargs):
        return self.main_program.current_block().create_var(**kwargs)

    def create_global_variable(self, persistable=False, *args, **kwargs):
        return self.main_program.global_block().create_var(
            *args, persistable=persistable, **kwargs
        )

    def set_variable_initializer(self, var, initializer):
        """Create the same var in startup program and init it there."""
        startup_block = self.startup_program.global_block()
        if var.name not in startup_block.vars:
            svar = startup_block.create_var(
                name=var.name, shape=var.shape, dtype=var.dtype, persistable=True
            )
            initializer(svar, startup_block)
        return var

    def append_op(self, **kwargs):
        return self.main_program.current_block().append_op(**kwargs)

    # -- common input handling -------------------------------------------
    def input(self, input_param_name="input"):
        inputs = self.kwargs.get(input_param_name, [])
        if not isinstance(inputs, (list, tuple)):
            inputs = [inputs]
        if len(inputs) != 1:
            raise ValueError("expected exactly one input for %s" % self.layer_type)
        return inputs[0]

    def multiple_input(self, input_param_name="input"):
        inputs = self.kwargs.get(input_param_name, [])
        if not isinstance(inputs, (list, tuple)):
            inputs = [inputs]
        return list(inputs)

    @property
    def param_attr(self):
        return ParamAttr._to_attr(self.kwargs.get("param_attr", None))

    @property
    def bias_attr(self):
        return ParamAttr._to_attr(self.kwargs.get("bias_attr", None))

    def input_dtype(self, input_param_name="input"):
        inputs = self.multiple_input(input_param_name)
        dtype = None
        for each in inputs:
            if dtype is None:
                dtype = each.dtype
            elif dtype != each.dtype:
                raise ValueError("mismatched input dtypes for %s" % self.layer_type)
        return dtype

    # -- bias & activation ------------------------------------------------
    def append_bias_op(self, input_var, dim_start=1, dim_end=None):
        size = list(input_var.shape[dim_start:dim_end])
        bias_attr = self.bias_attr
        if bias_attr is False:
            return input_var
        b = self.create_parameter(attr=bias_attr, shape=size, dtype=input_var.dtype, is_bias=True)
        tmp = self.create_variable_for_type_inference(
            dtype=input_var.dtype, shape=input_var.shape
        )
        self.append_op(
            type="elementwise_add",
            inputs={"X": [input_var], "Y": [b]},
            outputs={"Out": [tmp]},
            attrs={"axis": dim_start},
        )
        return tmp

    def append_activation(self, input_var):
        act = self.kwargs.get("act", None)
        if act is None:
            return input_var
        if isinstance(act, str):
            act = {"type": act}
        act_type = act.pop("type")
        tmp = self.create_variable_for_type_inference(
            dtype=input_var.dtype, shape=input_var.shape
        )
        self.append_op(
            type=act_type, inputs={"X": [input_var]}, outputs={"Out": [tmp]}, attrs=act
        )
        return tmp
