"""Program / Block / Operator / Variable IR.

This is the declarative graph IR at the heart of the framework, playing the
role of the reference's ProgramDesc/BlockDesc/OpDesc/VarDesc protobuf stack
(reference: paddle/fluid/framework/framework.proto, program_desc.cc,
block_desc.cc, op_desc.cc and python/paddle/fluid/framework.py).

Differences from the reference, by design (TPU-first):
- Pure-Python dataclass-style IR with JSON serialization instead of protobuf;
  the IR is only ever consumed by our own tracer, which lowers a whole Block
  into ONE jitted XLA computation (see trace.py). There are no per-op kernels
  to dispatch, so there is no need for a C++ desc mirror.
- No LoD: variable-length sequence data is carried as dense padded tensors
  plus explicit integer length tensors (TPU/XLA want static shapes).
  ``Variable.lod_level > 0`` simply marks that a companion ``<name>.lens``
  variable exists.
"""
from __future__ import annotations

import contextlib
import copy
import hashlib
import json
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from . import unique_name
from .dtypes import convert_dtype

__all__ = [
    "Variable",
    "Parameter",
    "Operator",
    "Block",
    "Program",
    "default_main_program",
    "default_startup_program",
    "switch_main_program",
    "switch_startup_program",
    "program_guard",
    "name_scope",
    "grad_var_name",
]

GRAD_SUFFIX = "@GRAD"


def grad_var_name(name: str) -> str:
    return name + GRAD_SUFFIX


class Variable:
    """A symbolic tensor in a Block.

    Mirrors VarDesc + python Variable (reference:
    python/paddle/fluid/framework.py:Variable). ``shape`` may contain -1 for
    dimensions only known at feed time (typically batch).
    """

    def __init__(
        self,
        block: "Block",
        name: Optional[str] = None,
        shape: Optional[Sequence[int]] = None,
        dtype: Any = "float32",
        lod_level: int = 0,
        persistable: bool = False,
        stop_gradient: bool = False,
        is_data: bool = False,
        **kwargs,
    ):
        self.block = block
        if name is None:
            name = unique_name.generate("_generated_var")
        self.name = name
        self.shape = tuple(int(s) for s in shape) if shape is not None else ()
        self.dtype = convert_dtype(dtype)
        self.lod_level = lod_level
        self.persistable = persistable
        self.stop_gradient = stop_gradient
        self.is_data = is_data
        self.op: Optional[Operator] = None  # producing op, set by append_op

    # -- numpy-ish sugar -------------------------------------------------
    @property
    def ndim(self) -> int:
        return len(self.shape)

    def astype(self, dtype):
        from ..layers import tensor as tensor_layers

        return tensor_layers.cast(self, dtype)

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "shape": list(self.shape),
            "dtype": self.dtype,
            "lod_level": self.lod_level,
            "persistable": self.persistable,
            "stop_gradient": self.stop_gradient,
            "is_data": self.is_data,
            "is_parameter": isinstance(self, Parameter),
            "trainable": getattr(self, "trainable", False),
        }

    def __repr__(self):
        return "Variable(name=%s, shape=%s, dtype=%s)" % (
            self.name,
            self.shape,
            self.dtype,
        )

    __str__ = __repr__


class Parameter(Variable):
    """A trainable, persistable Variable (reference:
    python/paddle/fluid/framework.py:Parameter)."""

    def __init__(self, block, shape, dtype, **kwargs):
        kwargs.setdefault("persistable", True)
        self.trainable = kwargs.pop("trainable", True)
        self.optimize_attr = kwargs.pop("optimize_attr", {"learning_rate": 1.0})
        self.regularizer = kwargs.pop("regularizer", None)
        self.gradient_clip_attr = kwargs.pop("gradient_clip_attr", None)
        self.do_model_average = kwargs.pop("do_model_average", None)
        super().__init__(block, shape=shape, dtype=dtype, **kwargs)
        if any(s < 0 for s in self.shape):
            raise ValueError("Parameter shape must be fully static: %s" % (shape,))


class Operator:
    """One node in a Block (reference: OpDesc / framework.py:Operator).

    inputs/outputs map slot names ("X", "Out", ...) to lists of variable
    names. attrs are JSON-serializable Python values; sub-blocks for control
    flow are referenced by block index via the ``sub_block`` attr.
    """

    def __init__(
        self,
        block: "Block",
        type: str,
        inputs: Optional[Dict[str, Any]] = None,
        outputs: Optional[Dict[str, Any]] = None,
        attrs: Optional[Dict[str, Any]] = None,
    ):
        self.block = block
        self.type = type
        self.inputs = {k: _to_name_list(v) for k, v in (inputs or {}).items()}
        self.outputs = {k: _to_name_list(v) for k, v in (outputs or {}).items()}
        self.attrs = dict(attrs or {})

    def input(self, slot: str) -> List[str]:
        return self.inputs.get(slot, [])

    def output(self, slot: str) -> List[str]:
        return self.outputs.get(slot, [])

    @property
    def input_arg_names(self) -> List[str]:
        return [n for v in self.inputs.values() for n in v]

    @property
    def output_arg_names(self) -> List[str]:
        return [n for v in self.outputs.values() for n in v]

    def attr(self, name: str, default=None):
        return self.attrs.get(name, default)

    def set_attr(self, name: str, val):
        self.attrs[name] = val

    def to_dict(self) -> dict:
        return {
            "type": self.type,
            "inputs": self.inputs,
            "outputs": self.outputs,
            "attrs": _jsonable_attrs(self.attrs),
        }

    def __repr__(self):
        return "Op(%s, inputs=%s, outputs=%s)" % (self.type, self.inputs, self.outputs)


def _to_name_list(v) -> List[str]:
    if v is None:
        return []
    if isinstance(v, (Variable, str)):
        v = [v]
    out = []
    for item in v:
        out.append(item.name if isinstance(item, Variable) else str(item))
    return out


def _jsonable_attrs(attrs: dict) -> dict:
    out = {}
    for k, v in attrs.items():
        if isinstance(v, np.ndarray):
            out[k] = {"__ndarray__": v.tolist(), "dtype": str(v.dtype)}
        elif isinstance(v, Block):
            out[k] = {"__block__": v.idx}
        elif isinstance(v, (np.integer,)):
            out[k] = int(v)
        elif isinstance(v, (np.floating,)):
            out[k] = float(v)
        else:
            out[k] = v
    return out


class Block:
    """An ordered list of Operators plus the Variables they reference
    (reference: BlockDesc / framework.py:Block)."""

    def __init__(self, program: "Program", idx: int, parent_idx: int = -1):
        self.program = program
        self.idx = idx
        self.parent_idx = parent_idx
        self.vars: Dict[str, Variable] = {}
        self.ops: List[Operator] = []

    @property
    def parent_block(self) -> Optional["Block"]:
        if self.parent_idx < 0:
            return None
        return self.program.block(self.parent_idx)

    # -- variables -------------------------------------------------------
    def create_var(self, **kwargs) -> Variable:
        name = kwargs.get("name")
        if name is not None and name in self.vars:
            return self.vars[name]
        var = Variable(self, **kwargs)
        self.vars[var.name] = var
        return var

    def create_parameter(self, **kwargs) -> Parameter:
        # Parameters always live in the top-level (global) block, like the
        # reference's global_block parameters.
        global_block = self.program.global_block()
        name = kwargs.get("name")
        if name is not None and name in global_block.vars:
            return global_block.vars[name]
        param = Parameter(global_block, **{k: v for k, v in kwargs.items() if k != "block"})
        global_block.vars[param.name] = param
        return param

    def var(self, name: str) -> Variable:
        v = self._find_var_recursive(name)
        if v is None:
            raise ValueError("variable %r not found in block %d" % (name, self.idx))
        return v

    def has_var(self, name: str) -> bool:
        return self._find_var_recursive(name) is not None

    def _find_var_recursive(self, name: str) -> Optional[Variable]:
        block: Optional[Block] = self
        while block is not None:
            if name in block.vars:
                return block.vars[name]
            block = block.parent_block
        return None

    def all_parameters(self) -> List[Parameter]:
        return [v for v in self.vars.values() if isinstance(v, Parameter)]

    # -- ops -------------------------------------------------------------
    def _note_writes(self, op: Operator):
        """Track each output var's producing op and write count (used for
        static folding: a var is only foldable while it has ONE writer)."""
        for name in op.output_arg_names:
            v = self._find_var_recursive(name)
            if v is not None:
                v.op = op
                v._writers = getattr(v, "_writers", 0) + 1

    def append_op(self, type: str, inputs=None, outputs=None, attrs=None) -> Operator:
        op = Operator(self, type=type, inputs=inputs, outputs=outputs, attrs=attrs)
        self.ops.append(op)
        self.program._bump()
        self._note_writes(op)
        return op

    def prepend_op(self, type: str, inputs=None, outputs=None, attrs=None) -> Operator:
        op = Operator(self, type=type, inputs=inputs, outputs=outputs, attrs=attrs)
        self.ops.insert(0, op)
        self.program._bump()
        self._note_writes(op)
        return op

    def insert_op(self, index: int, type: str, inputs=None, outputs=None, attrs=None) -> Operator:
        op = Operator(self, type=type, inputs=inputs, outputs=outputs, attrs=attrs)
        self.ops.insert(index, op)
        self.program._bump()
        self._note_writes(op)
        return op

    def to_dict(self) -> dict:
        return {
            "idx": self.idx,
            "parent_idx": self.parent_idx,
            "vars": [v.to_dict() for v in self.vars.values()],
            "ops": [op.to_dict() for op in self.ops],
        }


class Program:
    """A list of Blocks; block 0 is global (reference: ProgramDesc /
    framework.py:Program)."""

    def __init__(self):
        self.blocks: List[Block] = [Block(self, 0)]
        self.current_block_idx = 0
        self._seed = 0
        self.random_seed = 0
        self._version = 0  # bumped on every mutation; part of the fingerprint
        self._amp = False  # mixed-precision trace mode (see trace.py)
        self._amp_level = "O1"  # O1: matmul-class bf16; O2: + elementwise

    # -- block management ------------------------------------------------
    def global_block(self) -> Block:
        return self.blocks[0]

    def block(self, idx: int) -> Block:
        return self.blocks[idx]

    def current_block(self) -> Block:
        return self.blocks[self.current_block_idx]

    def _create_block(self, parent_idx: Optional[int] = None) -> Block:
        parent = self.current_block_idx if parent_idx is None else parent_idx
        b = Block(self, len(self.blocks), parent)
        self.blocks.append(b)
        self.current_block_idx = b.idx
        return b

    def _rollback(self):
        self.current_block_idx = self.current_block().parent_idx

    @property
    def num_blocks(self) -> int:
        return len(self.blocks)

    # -- mutation tracking ----------------------------------------------
    def _bump(self):
        self._version += 1

    def fingerprint(self) -> str:
        payload = json.dumps(self.to_dict(), sort_keys=True).encode()
        return hashlib.sha1(payload).hexdigest()

    def enable_mixed_precision(self, enabled: bool = True,
                               level: Optional[str] = None) -> "Program":
        """Run matmul-class ops in bf16 with fp32 master weights (TPU AMP;
        see trace.py _AMP_BF16_OPS / _AMP_FP32_OPS). No reference twin —
        fluid 0.14 predates AMP; exposed because bf16 is the TPU MXU's
        native fast path.

        level="O2" additionally keeps the elementwise path (bias/residual
        adds, activations, dropout, embedding lookup, layer_norm in/out)
        in bf16, so activations stay bf16 BETWEEN ops instead of being
        re-promoted to fp32 by every bias add — half the activation HBM
        traffic. Softmax/losses/reductions stay fp32-pinned, and
        layer_norm still computes its statistics in fp32 internally."""
        if level is not None:
            if level not in ("O1", "O2"):
                raise ValueError("AMP level must be 'O1' or 'O2', got %r"
                                 % (level,))
            self._amp_level = level
        # a no-level call (incl. enable_mixed_precision(False)) keeps the
        # previously configured level instead of silently resetting to O1
        self._amp = bool(enabled)
        self._bump()
        return self

    # -- parity APIs -----------------------------------------------------
    def clone(self, for_test: bool = False) -> "Program":
        """Deep-copies the program. With for_test=True, flips train-only ops
        (dropout, batch_norm) into inference mode like the reference's
        Program.clone(for_test=True) (reference framework.py:1241)."""
        p = copy.deepcopy(self)
        if for_test:
            for block in p.blocks:
                for op in block.ops:
                    if "is_test" in _TRAIN_TEST_OPS.get(op.type, ()):
                        op.attrs["is_test"] = True
                    if op.type == "dropout":
                        op.attrs["is_test"] = True
        return p

    def list_vars(self):
        for block in self.blocks:
            for v in block.vars.values():
                yield v

    def all_parameters(self) -> List[Parameter]:
        return self.global_block().all_parameters()

    def to_dict(self) -> dict:
        d = {
            "version": 1,
            "random_seed": self.random_seed,
            "amp": self._amp,
            "amp_level": getattr(self, "_amp_level", "O1"),
            "blocks": [b.to_dict() for b in self.blocks],
        }
        # bucketization stamp (transpiler/passes/bucketize.py): present
        # only on stamped programs, so unoptimized programs keep their
        # exact pre-existing serialization (and content fingerprints —
        # the AOT cache keys hash this dict)
        bkt = getattr(self, "_bucketize", None)
        if bkt:
            d["bucketize"] = bkt
        # quantization stamp (transpiler/passes/quantize.py): rides the
        # JSON so an exported int8 model is identifiable wherever it is
        # served (Engine.meta tier, aot_cache_ls); same present-only
        # contract as the bucketize stamp
        q = getattr(self, "_quantized", None)
        if q:
            d["quantized"] = q
        return d

    def to_json(self) -> str:
        return json.dumps(self.to_dict())

    @staticmethod
    def from_dict(d: dict) -> "Program":
        p = Program()
        p.random_seed = d.get("random_seed", 0)
        p._amp = bool(d.get("amp", False))
        lvl = str(d.get("amp_level", "O1"))
        if lvl not in ("O1", "O2"):
            raise ValueError(
                "serialized program has invalid amp_level %r" % (lvl,))
        p._amp_level = lvl
        if d.get("bucketize"):
            p._bucketize = d["bucketize"]
        if d.get("quantized"):
            p._quantized = d["quantized"]
        # first pass: blocks
        p.blocks = []
        for bd in d["blocks"]:
            b = Block(p, bd["idx"], bd["parent_idx"])
            p.blocks.append(b)
        for bd, b in zip(d["blocks"], p.blocks):
            for vd in bd["vars"]:
                cls = Parameter if vd.get("is_parameter") else Variable
                kwargs = dict(
                    name=vd["name"],
                    shape=vd["shape"],
                    dtype=vd["dtype"],
                    lod_level=vd.get("lod_level", 0),
                    persistable=vd.get("persistable", False),
                    stop_gradient=vd.get("stop_gradient", False),
                    is_data=vd.get("is_data", False),
                )
                if cls is Parameter:
                    kwargs["trainable"] = vd.get("trainable", True)
                    var = Parameter(b, **kwargs)
                else:
                    var = Variable(b, **kwargs)
                b.vars[var.name] = var
            for od in bd["ops"]:
                attrs = dict(od["attrs"])
                for k, v in attrs.items():
                    if isinstance(v, dict) and "__ndarray__" in v:
                        attrs[k] = np.array(v["__ndarray__"], dtype=v["dtype"])
                b.append_op(type=od["type"], inputs=od["inputs"], outputs=od["outputs"], attrs=attrs)
        p.current_block_idx = 0
        return p

    @staticmethod
    def from_json(s: str) -> "Program":
        return Program.from_dict(json.loads(s))


# ops whose behavior differs between train and test
_TRAIN_TEST_OPS = {
    "dropout": ("is_test",),
    "batch_norm": ("is_test",),
    "fused_attention": ("is_test",),  # attention dropout off at test time
    "ring_attention": ("is_test",),  # same: dropout off at test time
}

# -- default programs ----------------------------------------------------
_main_program_ = Program()
_startup_program_ = Program()


def default_main_program() -> Program:
    return _main_program_


def default_startup_program() -> Program:
    return _startup_program_


def switch_main_program(program: Program) -> Program:
    global _main_program_
    prev, _main_program_ = _main_program_, program
    return prev


def switch_startup_program(program: Program) -> Program:
    global _startup_program_
    prev, _startup_program_ = _startup_program_, program
    return prev


@contextlib.contextmanager
def program_guard(main_program: Program, startup_program: Optional[Program] = None):
    """Reference: python/paddle/fluid/framework.py:program_guard."""
    prev_main = switch_main_program(main_program)
    prev_startup = None
    if startup_program is not None:
        prev_startup = switch_startup_program(startup_program)
    try:
        yield
    finally:
        switch_main_program(prev_main)
        if prev_startup is not None:
            switch_startup_program(prev_startup)


_name_scope_stack: List[str] = []


@contextlib.contextmanager
def name_scope(prefix: str):
    _name_scope_stack.append(prefix)
    try:
        yield
    finally:
        _name_scope_stack.pop()
