"""Static Program verifier: use-before-def + write-once (SSA-ish) checks.

SURVEY aux: the TPU-native stand-in for the reference's data-race surface —
the reference's multi-stream SSA executor (paddle/fluid/framework/details)
can race on vars written twice without a dependency edge; our programs run
as one XLA computation, so the analogous bug is a Program whose op list
reads a value before any op produces it, or silently overwrites an
intermediate. Runs before compile; errors carry the op index + repr.
"""
from __future__ import annotations

from typing import List, Optional

__all__ = ["verify_program", "ProgramVerifyError"]

# ops that legitimately rewrite an existing var (loop counters, tensor
# arrays, in-place scatter updates, optimizer-style accumulators)
_REWRITE_OK = {
    "increment", "write_to_array", "assign", "scatter", "fill_constant",
    "sums", "sum",
}


class ProgramVerifyError(ValueError):
    pass


def _verify_block(block, defined: set, issues: List[str], feed_names: set,
                  is_sub: bool = False):
    local_defined = set(defined)
    written_by = {}
    for op_idx, op in enumerate(block.ops):
        if op.type == "feed":
            for name in op.output_arg_names:
                local_defined.add(name)
            continue
        if op.type == "read":
            # reader handle is bound host-side (layers/io.py reader
            # pipeline); outputs are injected as feeds by the executor
            for name in op.output_arg_names:
                local_defined.add(name)
            continue
        for name in op.input_arg_names:
            if name in local_defined or name in feed_names:
                continue
            var = block._find_var_recursive(name)
            if var is None:
                issues.append((
                    "undeclared",
                    "block %d op %d (%s): input %r is not declared anywhere"
                    % (block.idx, op_idx, op.type, name)))
            elif not var.persistable and name not in written_by and not is_sub:
                # sub-blocks get loop carries / step inputs injected by the
                # parent control-flow op at trace time, so use-before-def
                # is only decidable statically at the top level
                issues.append((
                    "use-before-def",
                    "block %d op %d (%s): input %r is read before any op "
                    "defines it (use-before-def)"
                    % (block.idx, op_idx, op.type, name)))
        sub_idx = op.attr("sub_block")
        if sub_idx is not None:
            sub = block.program.blocks[int(sub_idx)]
            _verify_block(sub, local_defined | set(written_by), issues,
                          feed_names, is_sub=True)
        for name in op.output_arg_names:
            var = block._find_var_recursive(name)
            persistable = var is not None and var.persistable
            if (name in written_by and not persistable
                    and op.type not in _REWRITE_OK
                    and written_by[name][1] not in _REWRITE_OK
                    # control-flow ops legitimately rewrite their loop
                    # carries / condition vars
                    and sub_idx is None):
                issues.append((
                    "write-once",
                    "block %d op %d (%s): output %r was already written by "
                    "op %d (%s) — write-once violation (would be a race in "
                    "a parallel executor)"
                    % (block.idx, op_idx, op.type, name,
                       written_by[name][0], written_by[name][1])))
            written_by[name] = (op_idx, op.type)
            local_defined.add(name)


def verify_program(program, feed_names=(), raise_on_error: bool = True):
    """Check every block; returns a list of (kind, message) issues
    (empty = clean). Hard errors (undeclared / use-before-def) raise when
    raise_on_error; write-once findings are advisory (the caller warns).

    feed_names: vars supplied externally at run time (executor feeds).
    Persistable vars are assumed initialized by the startup program.
    """
    issues: List[tuple] = []
    gb = program.global_block()
    defined = {name for name, var in gb.vars.items() if var.persistable}
    _verify_block(gb, defined, issues, set(feed_names))
    hard = [msg for kind, msg in issues
            if kind in ("undeclared", "use-before-def")]
    if hard and raise_on_error:
        raise ProgramVerifyError(
            "program verification failed:\n  " + "\n  ".join(hard))
    return issues
