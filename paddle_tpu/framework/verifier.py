"""Static Program verifier — thin shim over ``paddle_tpu.analysis``.

Historically this module held the use-before-def / write-once (SSA-ish)
checks itself; they now live as lint rules in ``analysis/lints.py``
(``def-use``), alongside the full shape/dtype inference pass and the
TPU-specific lints. This shim keeps the old call surface — every compile
still runs the cheap def-use subset through ``verify_program`` — and the
old exception type. For the full analyzer (shape/dtype inference,
dead-code, TPU static-shape and recompile-risk lints) set
``PADDLE_TPU_VERIFY=1`` (or ``strict``), or call
``paddle_tpu.analysis.analyze_program`` directly.
"""
from __future__ import annotations

from typing import List

__all__ = ["verify_program", "ProgramVerifyError"]


class ProgramVerifyError(ValueError):
    pass


def verify_program(program, feed_names=(), raise_on_error: bool = True):
    """Check every block; returns a list of (kind, message) issues
    (empty = clean). Hard errors (undeclared / use-before-def) raise when
    raise_on_error; write-once findings are advisory (the caller warns).

    feed_names: vars supplied externally at run time (executor feeds).
    Persistable vars are assumed initialized by the startup program.
    """
    from ..analysis import analyze_program

    analysis = analyze_program(program, feed_names=feed_names,
                               level="verify", observe=False)
    issues: List[tuple] = [(d.code, d.message)
                           for d in analysis.report
                           if d.code in ("undeclared", "use-before-def",
                                         "write-once")]
    hard = [msg for kind, msg in issues
            if kind in ("undeclared", "use-before-def")]
    if hard and raise_on_error:
        raise ProgramVerifyError(
            "program verification failed:\n  " + "\n  ".join(hard))
    return issues
