"""TensorArray runtime value (reference: LoDTensorArray,
paddle/fluid/framework/lod_tensor_array.h + write_to_array / read_from_array
ops in paddle/fluid/operators/tensor_array_read_write_op.cc).

The reference's LoDTensorArray is a std::vector<LoDTensor> mutated
imperatively by array ops inside While loops. XLA has no growable
containers, so a TensorArray here has two trace-time modes:

- **list mode** — outside any `lax.while_loop`, writes at concrete (python
  int) indices are kept as a plain Python list of arrays. This is free and
  exact.
- **buffer mode** — when a TensorArray is carried through a `while` op, it
  is converted to a fixed-capacity device buffer ``(capacity, *elem)`` plus
  an int32 ``size`` scalar; reads/writes use ``lax.dynamic_*_index_in_dim``.
  Capacity = current length + the while op's ``max_iters`` bound.

Registered as a JAX pytree so it can ride inside while-loop carries.
"""
from __future__ import annotations

from typing import List, Optional

import jax
import jax.numpy as jnp
from jax import lax


class TensorArrayVal:
    def __init__(self, items: Optional[List] = None, buffer=None, size=None):
        self.items = items if items is not None else []
        self.buffer = buffer
        self.size = size

    @property
    def is_buffer(self) -> bool:
        return self.buffer is not None

    # -- list <-> buffer -------------------------------------------------
    def to_buffer(self, capacity: int) -> "TensorArrayVal":
        """Capacity of the result = current length + `capacity` extra slots
        (a while loop carrying this array may write up to its max_iters new
        elements past the existing ones)."""
        if self.is_buffer:
            return self
        if not self.items:
            raise ValueError(
                "cannot carry an empty TensorArray into a while loop: write "
                "at least one element before the loop so its element "
                "shape/dtype is known"
            )
        stacked = jnp.stack(self.items)
        n = len(self.items)
        cap = n + capacity
        buf = jnp.zeros((cap,) + stacked.shape[1:], stacked.dtype)
        buf = lax.dynamic_update_slice_in_dim(buf, stacked, 0, axis=0)
        return TensorArrayVal(buffer=buf, size=jnp.asarray(n, jnp.int32))

    # -- ops -------------------------------------------------------------
    def write(self, i, x, static_index=None) -> "TensorArrayVal":
        """Outside while loops (list mode) the index must be statically
        known — either concrete, or folded from the program graph
        (fill_constant producer) by the write_to_array kernel. Failing
        both, the write is treated as an append (i == len), which is how
        every fluid program uses arrays outside loops (counter from 0)."""
        if not self.is_buffer:
            ci = _concrete_index(i)
            if ci is None:
                ci = static_index
            items = list(self.items)
            if ci is None:
                items.append(x)
                return TensorArrayVal(items=items)
            while len(items) <= ci:
                items.append(jnp.zeros_like(x))
            items[ci] = x
            return TensorArrayVal(items=items)
        i = jnp.asarray(i, jnp.int32).reshape(())
        buf = lax.dynamic_update_index_in_dim(self.buffer, x, i, axis=0)
        size = jnp.maximum(self.size, i + 1)
        return TensorArrayVal(buffer=buf, size=size)

    def read(self, i, static_index=None):
        if not self.is_buffer:
            ci = _concrete_index(i)
            if ci is None:
                ci = static_index
            if ci is not None:
                return self.items[ci]
            stacked = jnp.stack(self.items)
            i = jnp.asarray(i, jnp.int32).reshape(())
            return lax.dynamic_index_in_dim(stacked, i, axis=0, keepdims=False)
        i = jnp.asarray(i, jnp.int32).reshape(())
        return lax.dynamic_index_in_dim(self.buffer, i, axis=0, keepdims=False)

    def length(self):
        if not self.is_buffer:
            return jnp.asarray(len(self.items), jnp.int32)
        return self.size

    def stack(self):
        """Dense (n, *elem) view; buffer mode returns the full capacity
        buffer (valid prefix = length())."""
        if not self.is_buffer:
            return jnp.stack(self.items) if self.items else jnp.zeros((0,))
        return self.buffer


def _concrete_index(i):
    try:
        return int(jnp.asarray(i).reshape(()))
    except (TypeError, jax.errors.ConcretizationTypeError, jax.errors.TracerArrayConversionError):
        return None


def _flatten(ta: TensorArrayVal):
    if ta.is_buffer:
        return (ta.buffer, ta.size), "buffer"
    return tuple(ta.items), ("list", len(ta.items))


def _unflatten(aux, children):
    if aux == "buffer":
        return TensorArrayVal(buffer=children[0], size=children[1])
    return TensorArrayVal(items=list(children))


jax.tree_util.register_pytree_node(TensorArrayVal, _flatten, _unflatten)
