"""Arithmetic operator sugar on Variable (``x + y``, ``x * 0.5``, ...).

Reference: python/paddle/fluid/layers/math_op_patch.py:22
(monkey_patch_variable). Binary arithmetic with another Variable appends the
matching elementwise op; with a python scalar it appends `scale` (for the
linear cases, one fused multiply-add instead of materializing a constant
tensor) or a broadcast constant + elementwise op (for pow/rdiv, which are
not affine). Comparison and __eq__ are deliberately NOT patched (Variables
are used as dict keys / in fetch lists; identity semantics must survive).
"""
from __future__ import annotations

from .core import Variable

_PATCHED = False


def _scalar_scale(var, scale, bias):
    from ..layers import ops as ops_layers

    return ops_layers.scale(var, scale=float(scale), bias=float(bias))


def _const_like(var, value):
    """A constant tensor broadcastable against `var` (batch-size aware)."""
    from ..layers import tensor as tensor_layers

    shape = list(var.shape)
    if any(s < 0 for s in shape):
        return tensor_layers.fill_constant_batch_size_like(
            input=var, shape=shape, dtype=var.dtype, value=float(value))
    return tensor_layers.fill_constant(
        shape=shape or [1], dtype=var.dtype, value=float(value))


def _elementwise(op_type, x, y):
    from ..layer_helper import LayerHelper

    helper = LayerHelper(op_type)
    shape = y.shape if len(y.shape) > len(x.shape) else x.shape
    out = helper.create_variable_for_type_inference(dtype=x.dtype,
                                                    shape=shape)
    helper.append_op(type=op_type, inputs={"X": [x], "Y": [y]},
                     outputs={"Out": [out]}, attrs={"axis": -1})
    return out


def _binary(op_type, scalar_fn=None, reverse=False):
    def method(self, other):
        if isinstance(other, (int, float)):
            if scalar_fn is not None:
                return scalar_fn(self, other)
            other = _const_like(self, other)
        elif not isinstance(other, Variable):
            return NotImplemented
        x, y = (other, self) if reverse else (self, other)
        return _elementwise(op_type, x, y)

    method.__name__ = ("__r" if reverse else "__") + op_type.split("_")[-1] + "__"
    return method


def monkey_patch_variable():
    """Install the operator methods on Variable (idempotent)."""
    global _PATCHED
    if _PATCHED:
        return
    _PATCHED = True

    Variable.__add__ = _binary(
        "elementwise_add", lambda v, s: _scalar_scale(v, 1.0, s))
    Variable.__radd__ = _binary(
        "elementwise_add", lambda v, s: _scalar_scale(v, 1.0, s),
        reverse=True)
    Variable.__sub__ = _binary(
        "elementwise_sub", lambda v, s: _scalar_scale(v, 1.0, -s))
    Variable.__rsub__ = _binary(
        "elementwise_sub", lambda v, s: _scalar_scale(v, -1.0, s),
        reverse=True)
    Variable.__mul__ = _binary(
        "elementwise_mul", lambda v, s: _scalar_scale(v, s, 0.0))
    Variable.__rmul__ = _binary(
        "elementwise_mul", lambda v, s: _scalar_scale(v, s, 0.0),
        reverse=True)
    Variable.__truediv__ = _binary(
        "elementwise_div", lambda v, s: _scalar_scale(v, 1.0 / s, 0.0))
    Variable.__rtruediv__ = _binary("elementwise_div", reverse=True)
    Variable.__div__ = Variable.__truediv__
    Variable.__rdiv__ = Variable.__rtruediv__
    Variable.__pow__ = _binary("elementwise_pow")
    Variable.__rpow__ = _binary("elementwise_pow", reverse=True)
    Variable.__neg__ = lambda self: _scalar_scale(self, -1.0, 0.0)
