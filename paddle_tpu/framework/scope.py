"""Scope: runtime variable store, and Place: device abstraction.

Reference: paddle/fluid/framework/scope.h (Scope holds Variables by name,
hierarchical) and paddle/fluid/platform/place.h (CPUPlace / CUDAPlace).

TPU-native: a Scope maps names to live ``jax.Array``s (device-resident,
possibly sharded across a Mesh). Memory is owned by XLA — there is no buddy
allocator to port; donation in the executor gives in-place parameter update
semantics without copies.
"""
from __future__ import annotations

from typing import Dict, Optional

__all__ = ["Scope", "CPUPlace", "TPUPlace", "CUDAPlace", "CUDAPinnedPlace",
           "global_scope", "scope_guard"]


class Scope:
    def __init__(self, parent: Optional["Scope"] = None):
        self.parent = parent
        self.vars: Dict[str, object] = {}
        self.kids = []

    def var(self, name: str):
        """Find or create (as None placeholder) a variable slot."""
        if name not in self.vars and (self.parent is None or self.parent.find_var(name) is None):
            self.vars[name] = None
        return self.find_var(name)

    def find_var(self, name: str):
        if name in self.vars:
            return self.vars[name]
        if self.parent is not None:
            return self.parent.find_var(name)
        return None

    def has_var(self, name: str) -> bool:
        return name in self.vars or (self.parent is not None and self.parent.has_var(name))

    def set_var(self, name: str, value):
        self.vars[name] = value

    def erase(self, name: str):
        self.vars.pop(name, None)

    def new_scope(self) -> "Scope":
        kid = Scope(self)
        self.kids.append(kid)
        return kid

    def drop_kids(self):
        """Release all child scopes (reference Scope::DropKids); their
        arrays are freed once no fetched value references them."""
        self.kids = []

    def local_var_names(self):
        return list(self.vars.keys())


class Place:
    """Base device place. Resolves to a concrete jax.Device."""

    _kind = "cpu"

    def __init__(self, device_id: int = 0):
        self.device_id = device_id

    def jax_device(self):
        import jax

        devs = [d for d in jax.devices() if d.platform == self._kind]
        if not devs:  # fall back to default backend (e.g. tests force CPU)
            devs = jax.devices()
        return devs[self.device_id % len(devs)]

    def __eq__(self, other):
        return type(self) is type(other) and self.device_id == other.device_id

    def __hash__(self):
        return hash((type(self).__name__, self.device_id))

    def __repr__(self):
        return "%s(%d)" % (type(self).__name__, self.device_id)


class CPUPlace(Place):
    _kind = "cpu"


class TPUPlace(Place):
    _kind = "tpu"


# The reference's CUDAPlace; maps to the accelerator (TPU) so that reference
# scripts using CUDAPlace run unchanged.
class CUDAPlace(TPUPlace):
    pass


# Pinned (page-locked) host memory is a CUDA transfer optimization; on TPU
# feeds stage through the C++ arena instead, so this is plain host memory.
class CUDAPinnedPlace(CPUPlace):
    pass


_global_scope = Scope()


def global_scope() -> Scope:
    return _global_scope


import contextlib


@contextlib.contextmanager
def scope_guard(scope: Scope):
    global _global_scope
    prev, _global_scope = _global_scope, scope
    try:
        yield
    finally:
        _global_scope = prev
