"""Block tracer: lowers a Program Block to one pure JAX function.

This replaces the reference's per-op dispatch loop (reference:
paddle/fluid/framework/executor.cc:Executor::RunPreparedContext — creates an
OperatorBase per OpDesc and launches a kernel per op). Here the whole block
is traced symbolically once and handed to XLA as a single computation, so
op boundaries vanish: XLA fuses elementwise chains into matmul/conv
epilogues and schedules the entire step.

The ``autodiff`` pseudo-op (inserted by backward.append_backward) is handled
specially: the forward prefix of the block is replayed inside ``jax.vjp`` so
XLA differentiates the whole graph at once — the traced training step
contains forward+backward+optimizer in one XLA program. Several autodiff
ops in one block (e.g. two optimizers on two losses) are supported: each
replays the forward ops before it; identical replayed subcomputations are
CSE'd by XLA, and per-op keyed RNG keeps any dropout masks identical across
replays.
"""
from __future__ import annotations

from typing import Callable, Dict, List

import jax
import jax.numpy as jnp

from ..ops.registry import OpContext, get_kernel
from .core import Block, Operator, grad_var_name

# op types the tracer interprets (or skips) itself rather than via a kernel:
# autodiff is expanded into a vjp; feed/fetch (present in reference-style
# serialized programs) are no-ops because the executor feeds/fetches
# directly. `read` ops are resolved by the executor too: it pulls the next
# batch from the reader pipeline and injects the op's outputs as feeds
# before tracing (the jitted step stays pure).
_SKIP_OPS = {"feed", "fetch", "read"}

# Per-op RNG keys derive from the op's block position — which the
# optimizing transpiler perturbs when it deletes or fuses ops. Before its
# first rewrite, the pass manager stamps every op's PRE-optimization
# position as this attr (transpiler/passes/manager.py) and the tracer
# prefers it, so an optimized program draws the exact PRNG stream the
# original would (parity gating requires bit-equal dropout masks).
_RNG_IDX_ATTR = "__rng_idx__"


def _rng_idx(op: Operator, op_idx: int) -> int:
    return op.attrs.get(_RNG_IDX_ATTR, op_idx)

# Mixed precision (program.enable_mixed_precision()): matmul-class ops run
# their float inputs in bf16 — MXU native, half the HBM traffic — while
# numerically sensitive ops are pinned to fp32. Parameters and optimizer
# state stay fp32 (master weights); the casts live inside the traced graph,
# so vjp returns fp32 gradients and XLA dedups repeated casts. bf16 shares
# fp32's exponent range, so no loss scaling is needed (unlike fp16 AMP).
_AMP_BF16_OPS = {
    "mul", "matmul", "conv2d", "conv3d", "conv2d_transpose",
    "conv3d_transpose", "sequence_conv", "fused_attention",
    "fused_lm_head_loss", "fused_fc",
}
_AMP_FP32_OPS = {
    "softmax_with_cross_entropy", "cross_entropy", "layer_norm",
    "softmax", "sequence_softmax", "reduce_mean",
    "reduce_sum", "mean", "exp", "log", "linear_chain_crf", "warpctc",
    "nce", "hierarchical_sigmoid", "l2_normalize",
}
# AMP level O2 (enable_mixed_precision(level="O2")): the elementwise path
# joins the bf16 set, so activations stay bf16 BETWEEN matmuls instead of
# being re-promoted to fp32 by every f32-bias add / residual add (under
# O1 the profile shows f32 (tokens, d_inner) tensors streaming HBM).
# layer_norm moves from the fp32 pin to bf16 in/out — its kernel computes
# statistics in fp32 internally regardless of input dtype.
# Only ACTIVATION-STREAM instances are cast: an op that names a @GRAD
# var or writes a persistable var is gradient/optimizer-state plumbing
# (regularizer decay adds, clip scaling, ModelAverage accumulation) and
# must keep the fp32 master-weight contract — see _o2_eligible().
_AMP_BF16_O2_OPS = {
    "elementwise_add", "elementwise_sub", "elementwise_mul",
    "relu", "tanh", "sigmoid", "swish", "leaky_relu", "relu6",
    "brelu", "dropout", "lookup_table", "layer_norm",
}


def _o2_eligible(op, block) -> bool:
    """True when an _AMP_BF16_O2_OPS instance sits on the activation
    stream: no @GRAD input/output (gradient math stays fp32) and no
    persistable output (optimizer/EMA state stays fp32)."""
    for name in op.input_arg_names:
        if name.endswith("@GRAD") or "@GRAD@" in name:
            return False
    for name in op.output_arg_names:
        if name.endswith("@GRAD") or "@GRAD@" in name:
            return False
        var = block._find_var_recursive(name)
        if var is not None and var.persistable:
            return False
    return True
# batch_norm is deliberately NOT fp32-pinned: the kernel computes its
# statistics in fp32 internally while keeping the (huge) activation tensors
# in the incoming dtype — pinning it would stream fp32 copies of every
# activation through HBM between bf16 convs (profiled on ResNet-50).


import contextlib
import threading

# Mesh the step is being traced under (set by ParallelExecutor around the
# first call of its jitted step). Kernels that have a distributed
# implementation (ring_attention) consult this to decide between the
# collective path and the single-device fallback. Thread-local: two
# ParallelExecutors first-running on different threads must not see each
# other's mesh.
_TRACE_MESH = threading.local()


@contextlib.contextmanager
def mesh_context(mesh):
    stack = getattr(_TRACE_MESH, "stack", None)
    if stack is None:
        stack = _TRACE_MESH.stack = []
    stack.append(mesh)
    try:
        yield
    finally:
        stack.pop()


def current_trace_mesh():
    stack = getattr(_TRACE_MESH, "stack", None)
    return stack[-1] if stack else None


class RngStream:
    """Deterministic PRNG stream keyed on (block idx, op position, draw #):
    replaying an op (e.g. inside an autodiff vjp) yields the same bits, and
    adding ops elsewhere never perturbs other ops' streams.

    ``salts`` holds loop-iteration indices (possibly traced) pushed by
    control-flow kernels while tracing their sub-blocks, so an RNG-drawing
    op inside lax.scan / lax.while_loop gets fresh bits every iteration
    (the key becomes a function of the loop counter instead of a loop
    constant)."""

    def __init__(self, base_key):
        self.base_key = base_key
        self.salts: List = []

    def for_op(self, block_idx: int, op_idx: int) -> Callable:
        draws = [0]
        salts = list(self.salts)

        def next_key():
            k = jax.random.fold_in(self.base_key, block_idx * 1000003 + op_idx)
            for s in salts:
                k = jax.random.fold_in(k, jnp.asarray(s, jnp.uint32).reshape(()))
            k = jax.random.fold_in(k, draws[0])
            draws[0] += 1
            return k

        return next_key


class TraceError(RuntimeError):
    """Carries the failing op's context, mirroring the reference's enforce
    messages that name the op and its inputs."""


def _apply_outputs(op: Operator, block: Block, env: Dict, result: Dict):
    for slot, names in op.outputs.items():
        if slot not in result:
            continue
        vals = result[slot]
        if not isinstance(vals, (list, tuple)):
            vals = [vals]
        for name, val in zip(names, vals):
            var = block._find_var_recursive(name)
            if var is not None and var.stop_gradient and not var.persistable:
                val = jax.lax.stop_gradient(val)
            env[name] = val


def trace_op(op: Operator, block: Block, env: Dict, rng_fn, subblock_fn=None):
    kernel = get_kernel(op.type)
    view = _EnvView(env, op)
    if getattr(block.program, "_amp", False):
        o2 = getattr(block.program, "_amp_level", "O1") == "O2"
        if op.type in _AMP_BF16_OPS or (
                o2 and op.type in _AMP_BF16_O2_OPS
                and _o2_eligible(op, block)):
            view = _CastEnvView(env, op, jnp.bfloat16)
        elif op.type in _AMP_FP32_OPS:
            view = _CastEnvView(env, op, jnp.float32)
    ctx = OpContext(op, view, rng_fn, subblock_fn, block)
    try:
        result = kernel(ctx)
    except (NotImplementedError,):
        raise
    except Exception as e:
        in_shapes = {
            slot: [getattr(env.get(n), "shape", None) for n in names]
            for slot, names in op.inputs.items()
        }
        err = TraceError(
            "error while lowering op %r (inputs %s, attrs %s): %s"
            % (op.type, in_shapes, op.attrs, e)
        )
        # op provenance for the static analyzer's post-mortem: the
        # executor re-renders trace failures with the analyzer's per-op
        # shape/dtype facts (analysis.explain_trace_error) keyed on these
        err.pt_op_type = op.type
        err.pt_block_idx = block.idx
        try:
            err.pt_op_idx = block.ops.index(op)
        except ValueError:  # op replayed from a detached copy
            err.pt_op_idx = None
        raise err from e
    _apply_outputs(op, block, env, result)


class _EnvView(dict):
    """Env lookup that raises with op context for variables that were never
    produced. (Optional inputs never reach here: layers omit the slot
    entirely, so OpContext.input() returns the default before lookup.)"""

    def __init__(self, env, op):
        super().__init__()
        self._env = env
        self._op = op

    def __getitem__(self, name):
        if name in self._env:
            return self._env[name]
        raise KeyError(
            "variable %r (input of op %r) has no value: not a feed, not "
            "persistable state, and not produced by any earlier op"
            % (name, self._op.type)
        )

    def __contains__(self, name):
        return name in self._env

    def snapshot(self):
        return dict(self._env)


class _CastEnvView(_EnvView):
    """Env view that casts float inputs to the op's AMP compute dtype."""

    def __init__(self, env, op, dtype):
        super().__init__(env, op)
        self._amp_dtype = dtype

    def __getitem__(self, name):
        v = super().__getitem__(name)
        dt = getattr(v, "dtype", None)
        if dt in (jnp.float32, jnp.bfloat16) and dt != self._amp_dtype:
            return v.astype(self._amp_dtype)
        return v


def trace_block(block: Block, env: Dict, rng: RngStream) -> Dict:
    """Trace all ops of `block` into `env` (mutated in place and returned)."""
    program = block.program

    def subblock_fn(block_idx: int, sub_env: Dict, salt=None) -> Dict:
        if salt is None:
            return trace_block(program.block(block_idx), sub_env, rng)
        rng.salts.append(salt)
        try:
            return trace_block(program.block(block_idx), sub_env, rng)
        finally:
            rng.salts.pop()

    env_start = dict(env)
    # (op, op_idx) pairs replayed inside each vjp. Frozen at the first
    # autodiff: ops after it (optimizer/clip/regularizer updates, metrics)
    # are not part of any loss's forward graph. In fluid programs every
    # forward op precedes the first minimize(), so all losses are covered.
    #
    # Ops BEFORE the first autodiff are not traced eagerly: they are traced
    # exactly once, inside the first autodiff's jax.vjp, and their outputs
    # reach `env` through the vjp's aux (`fenv`). Tracing them both eagerly
    # and in the vjp would double the HLO (and with a remat policy set the
    # two copies are not CSE-able — one is checkpointed).
    forward_ops: List[tuple] = []
    first_ad = next(
        (i for i, o in enumerate(block.ops) if o.type == "autodiff"), None
    )

    for op_idx, op in enumerate(block.ops):
        if op.type in _SKIP_OPS:
            continue
        if op.type != "autodiff":
            if first_ad is not None and op_idx < first_ad:
                # deferred to the vjp (RNG key by pre-optimization stamp)
                forward_ops.append((op, _rng_idx(op, op_idx)))
                continue
            trace_op(op, block, env, rng.for_op(block.idx,
                                                _rng_idx(op, op_idx)),
                     subblock_fn)
            continue

        # -- autodiff: differentiate loss wrt params over the full forward
        # prefix (all non-autodiff ops so far), replayed under jax.vjp.
        loss_name = op.attr("loss_name")
        param_names: List[str] = list(op.attr("param_names"))
        replay = list(forward_ops)

        def forward(pvals: Dict[str, jnp.ndarray]):
            fenv = dict(env_start)
            fenv.update(pvals)
            for fop, fidx in replay:
                trace_op(fop, block, fenv, rng.for_op(block.idx, fidx), subblock_fn)
            if loss_name not in fenv:
                raise TraceError(
                    "loss %r is not computed by the forward ops preceding "
                    "the first backward pass; differentiating a loss built "
                    "between two minimize() calls is unsupported" % loss_name
                )
            loss = fenv[loss_name]
            return jnp.sum(loss), fenv

        # gradients are taken at the values the forward pass actually saw
        # (env_start — the block's entry state), matching the reference's
        # sequential semantics: backward ops read the activations stored by
        # the one forward execution, so a second minimize()'s grads are
        # NOT affected by the first optimizer's in-between param updates.
        pvals = {}
        for name in param_names:
            if name in env_start:
                pvals[name] = env_start[name]
            elif name in env:
                pvals[name] = env[name]
            else:
                raise TraceError(
                    "parameter %r has no value in scope — run the startup "
                    "program first" % name
                )

        # memory_optimize() (transpiler/memory_optimizer.py) sets a remat
        # policy: the replayed forward is checkpointed so the backward
        # recomputes activations instead of saving them (HBM for FLOPs).
        policy_name = getattr(block.program, "_remat_policy", None)
        fwd_fn = forward
        if policy_name:
            fwd_fn = jax.checkpoint(
                forward, policy=getattr(jax.checkpoint_policies, policy_name)
            )
        loss_val, vjp_fn, fenv = jax.vjp(fwd_fn, pvals, has_aux=True)
        (grads,) = vjp_fn(jnp.ones_like(loss_val))

        # adopt from fenv only what the replayed forward PRODUCED: copying
        # all of fenv would revert state a previous autodiff section's
        # optimizer ops already updated (fenv's params are env_start
        # values), silently un-training earlier losses in multi-minimize
        # (e.g. GAN-style) programs.
        produced = set()
        for fop, _ in replay:
            produced.update(fop.output_arg_names)
        for name in produced:
            if name in fenv:
                env[name] = fenv[name]
        for name in param_names:
            env[grad_var_name(name)] = grads[name]

    return env
