from .core import (
    Block,
    Operator,
    Parameter,
    Program,
    Variable,
    default_main_program,
    default_startup_program,
    grad_var_name,
    name_scope,
    program_guard,
    switch_main_program,
    switch_startup_program,
)
from .scope import CPUPlace, CUDAPlace, Scope, TPUPlace, global_scope, scope_guard
from . import unique_name
