"""Dtype system for paddle_tpu.

Mirrors the reference's VarType dtype enum (reference:
paddle/fluid/framework/framework.proto:91-115, data_type.h) but maps directly
onto JAX/numpy dtypes. bfloat16 is first-class because it is the native MXU
input type on TPU.
"""
from __future__ import annotations

import numpy as np

try:
    import jax.numpy as jnp

    _BF16 = jnp.bfloat16
except Exception:  # pragma: no cover
    _BF16 = None

# Canonical string names -> numpy dtype objects.
_STR2DTYPE = {
    "bool": np.dtype(np.bool_),
    "int8": np.dtype(np.int8),
    "uint8": np.dtype(np.uint8),
    "int16": np.dtype(np.int16),
    "int32": np.dtype(np.int32),
    "int64": np.dtype(np.int64),
    "float16": np.dtype(np.float16),
    "float32": np.dtype(np.float32),
    "float64": np.dtype(np.float64),
}
if _BF16 is not None:
    _STR2DTYPE["bfloat16"] = np.dtype(_BF16)


def convert_dtype(dtype) -> str:
    """Normalize a dtype spec (string / numpy dtype / jnp dtype) to its
    canonical string name."""
    if dtype is None:
        return "float32"
    if isinstance(dtype, str):
        name = dtype
    else:
        name = np.dtype(dtype).name
        if name == "bfloat16" and "bfloat16" not in _STR2DTYPE:
            raise TypeError("bfloat16 requires jax")
    if name not in _STR2DTYPE:
        raise TypeError("unsupported dtype: %r" % (dtype,))
    return name


def as_numpy_dtype(dtype) -> np.dtype:
    return _STR2DTYPE[convert_dtype(dtype)]


def is_float(dtype) -> bool:
    return convert_dtype(dtype) in ("float16", "bfloat16", "float32", "float64")


def is_integer(dtype) -> bool:
    return convert_dtype(dtype) in ("int8", "uint8", "int16", "int32", "int64")
