"""Image preprocessing utilities (reference: python/paddle/dataset/image.py
— cv2-backed resize/crop/flip/transpose helpers used by the vision
readers). Implemented on numpy (no cv2 in this image): bilinear resize,
center/random crop, horizontal flip, CHW conversion.
"""
from __future__ import annotations

import numpy as np

__all__ = [
    "resize_short", "to_chw", "center_crop", "random_crop", "left_right_flip",
    "simple_transform", "SimpleTransform",
]


def _bilinear_resize(im: np.ndarray, h: int, w: int) -> np.ndarray:
    """im: HWC (or HW grayscale) float/uint8 -> (h, w[, C])."""
    gray = im.ndim == 2
    if gray:
        im = im[:, :, None]
    H, W = im.shape[:2]
    ys = (np.arange(h) + 0.5) * H / h - 0.5
    xs = (np.arange(w) + 0.5) * W / w - 0.5
    y0 = np.clip(np.floor(ys).astype(int), 0, H - 1)
    x0 = np.clip(np.floor(xs).astype(int), 0, W - 1)
    y1 = np.clip(y0 + 1, 0, H - 1)
    x1 = np.clip(x0 + 1, 0, W - 1)
    wy = np.clip(ys - y0, 0, 1)[:, None, None]
    wx = np.clip(xs - x0, 0, 1)[None, :, None]
    im = im.astype(np.float32)
    top = im[y0][:, x0] * (1 - wx) + im[y0][:, x1] * wx
    bot = im[y1][:, x0] * (1 - wx) + im[y1][:, x1] * wx
    out = top * (1 - wy) + bot * wy
    return out[:, :, 0] if gray else out


def resize_short(im: np.ndarray, size: int) -> np.ndarray:
    """Reference: image.py:resize_short — scale so the short side == size."""
    h, w = im.shape[:2]
    if h < w:
        return _bilinear_resize(im, size, int(round(w * size / h)))
    return _bilinear_resize(im, int(round(h * size / w)), size)


def to_chw(im: np.ndarray, order=(2, 0, 1)) -> np.ndarray:
    """Reference: image.py:to_chw."""
    return im.transpose(order)


def center_crop(im: np.ndarray, size: int, is_color: bool = True) -> np.ndarray:
    h, w = im.shape[:2]
    sh, sw = max((h - size) // 2, 0), max((w - size) // 2, 0)
    return im[sh:sh + size, sw:sw + size]


def random_crop(im: np.ndarray, size: int, is_color: bool = True,
                rng: np.random.RandomState = None) -> np.ndarray:
    rng = rng or np.random
    h, w = im.shape[:2]
    sh = rng.randint(0, max(h - size, 0) + 1)
    sw = rng.randint(0, max(w - size, 0) + 1)
    return im[sh:sh + size, sw:sw + size]


def left_right_flip(im: np.ndarray, is_color: bool = True) -> np.ndarray:
    return im[:, ::-1]


class SimpleTransform:
    """Picklable simple_transform closure for worker processes: the
    io.DataLoader (and spawn/forkserver multiprocessing generally) must
    pickle the per-sample mapper, which a lambda or nested function
    cannot cross. Maps ``(image, label) -> (chw_float32, label)``; extra
    tuple elements pass through untouched.

        mapper = image.SimpleTransform(256, 224, is_train=True, seed=1)
        loader.decorate_sample_reader(raw_reader, batch_size, mapper=mapper)

    Augmentation randomness is seeded per PROCESS (seed mixed with the
    pid), so parallel workers don't replay identical crop/flip draws.
    """

    def __init__(self, resize_size: int, crop_size: int, is_train: bool,
                 is_color: bool = True, mean=None, seed=None):
        self.resize_size = resize_size
        self.crop_size = crop_size
        self.is_train = is_train
        self.is_color = is_color
        self.mean = None if mean is None else np.asarray(mean, np.float32)
        self.seed = seed
        self._rng = None  # created lazily, per process
        self._rng_pid = None

    def __getstate__(self):
        state = dict(self.__dict__)
        state["_rng"] = None  # RandomState must not cross the boundary
        state["_rng_pid"] = None
        return state

    def _rng_for_process(self):
        import os

        pid = os.getpid()
        if self._rng is None or self._rng_pid != pid:
            # keyed on the CURRENT pid, not just lazily created: a
            # fork-started worker inherits an already-initialized _rng
            # (fork skips __getstate__), and siblings replaying the
            # parent's stream would emit identical augmentation draws
            base = self.seed if self.seed is not None else 0
            self._rng = np.random.RandomState(
                (base * 1000003 + pid) % (2 ** 31))
            self._rng_pid = pid
        return self._rng

    def __call__(self, sample):
        if isinstance(sample, tuple):
            im, rest = sample[0], sample[1:]
        else:
            im, rest = sample, ()
        out = simple_transform(np.asarray(im), self.resize_size,
                               self.crop_size, self.is_train,
                               is_color=self.is_color, mean=self.mean,
                               rng=self._rng_for_process())
        return (out,) + rest


def simple_transform(im: np.ndarray, resize_size: int, crop_size: int,
                     is_train: bool, is_color: bool = True, mean=None,
                     rng=None) -> np.ndarray:
    """Reference: image.py:simple_transform — resize-short, crop, maybe
    flip, HWC->CHW, mean-subtract."""
    im = resize_short(im, resize_size)
    if is_train:
        im = random_crop(im, crop_size, rng=rng)
        if (rng or np.random).randint(2) == 1:
            im = left_right_flip(im)
    else:
        im = center_crop(im, crop_size)
    if im.ndim == 2:  # grayscale: add the channel axis before CHW
        im = im[:, :, None]
    im = to_chw(im).astype(np.float32)
    if mean is not None:
        im -= np.asarray(mean, np.float32).reshape(-1, 1, 1)
    return im
