"""Pascal VOC2012 segmentation (reference: python/paddle/dataset/voc2012.py).

Samples: (image uint8[H, W, 3] HWC, label uint8[H, W]) with 21 classes
(0 = background) plus 255 border pixels, like the reference's decoded
png pairs. Synthetic source: rectangular object blobs whose class id
paints both the image hue and the label map, so segmentation models learn
a real (color -> class) mapping.
"""
from __future__ import annotations

import numpy as np

from .common import rng_for, synthetic_size

__all__ = ["train", "test", "val"]

_H = _W = 224
N_CLASSES = 21


def _sample(rng):
    img = (rng.rand(_H, _W, 3) * 40).astype(np.uint8)  # dark noise floor
    label = np.zeros((_H, _W), np.uint8)
    for _ in range(int(rng.randint(1, 4))):
        cls = int(rng.randint(1, N_CLASSES))
        h, w = int(rng.randint(40, 140)), int(rng.randint(40, 140))
        y, x = int(rng.randint(0, _H - h)), int(rng.randint(0, _W - w))
        color = ((cls * 11) % 256, (cls * 47) % 256, (cls * 83) % 256)
        img[y:y + h, x:x + w] = np.asarray(color, np.uint8)
        label[y:y + h, x:x + w] = cls
        # 2px border ring marked 255 (the reference's "void" pixels)
        label[y:y + h, x:min(x + 2, _W)] = 255
        label[y:min(y + 2, _H), x:x + w] = 255
    return img, label


def _reader(split: str, n: int):
    def reader():
        rng = rng_for("voc2012", split)
        for _ in range(n):
            yield _sample(rng)

    return reader


def train():
    """Reference: voc2012.py:train (trainval split)."""
    return _reader("trainval", synthetic_size("voc_train", 512))


def test():
    return _reader("train", synthetic_size("voc_test", 128))


def val():
    return _reader("val", synthetic_size("voc_val", 128))
