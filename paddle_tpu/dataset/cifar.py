"""CIFAR-10/100 (reference: python/paddle/dataset/cifar.py).

Samples: (image float32[3072] in [0, 1], label int). Synthetic source:
per-class color/texture template + noise (see common.py rationale).
"""
from __future__ import annotations

import numpy as np

from .common import rng_for, synthetic_size

__all__ = ["train10", "test10", "train100", "test100"]


def _synthetic_reader(n_classes: int, split: str, n: int):
    tmpl_rng = rng_for("cifar%d" % n_classes, "templates")
    templates = tmpl_rng.rand(n_classes, 3, 32, 32).astype(np.float32)
    for _ in range(2):
        templates = (templates + np.roll(templates, 1, 2)
                     + np.roll(templates, 1, 3)) / 3.0

    def reader():
        rng = rng_for("cifar%d" % n_classes, split)
        for _ in range(n):
            label = int(rng.randint(n_classes))
            img = templates[label] + rng.randn(3, 32, 32).astype(np.float32) * 0.15
            yield np.clip(img, 0.0, 1.0).reshape(3072), label

    return reader


def train10():
    """Reference: cifar.py:train10."""
    return _synthetic_reader(10, "train", synthetic_size("cifar_train", 4096))


def test10():
    return _synthetic_reader(10, "test", synthetic_size("cifar_test", 512))


def train100():
    return _synthetic_reader(100, "train", synthetic_size("cifar_train", 4096))


def test100():
    return _synthetic_reader(100, "test", synthetic_size("cifar_test", 512))
