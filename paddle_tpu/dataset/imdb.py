"""IMDB sentiment (reference: python/paddle/dataset/imdb.py).

Samples: (word-id sequence list[int], label 0/1). Synthetic source: two
sentiment-biased unigram distributions over a shared vocab — positive
reviews over-sample the "positive" half of the vocab, so bag-of-words and
LSTM classifiers genuinely separate the classes.
"""
from __future__ import annotations

import numpy as np

from .common import rng_for, synthetic_size

__all__ = ["build_dict", "train", "test", "word_dict"]

_VOCAB_SIZE = 5148  # mirrors the reference's cutoff-150 dict size ballpark


def word_dict():
    """Reference: imdb.py:word_dict — word -> id, highest frequency first;
    '<unk>' is the last id."""
    d = {"w%04d" % i: i for i in range(_VOCAB_SIZE - 1)}
    d["<unk>"] = _VOCAB_SIZE - 1
    return d


def build_dict(pattern=None, cutoff=150):
    """Reference parity; the synthetic corpus has a fixed vocab."""
    return word_dict()


def _reader_creator(word_idx, split: str, n: int, epoch: int = 1):
    vocab = len(word_idx)
    half = vocab // 2

    def reader():
        rng = rng_for("imdb", split)
        for _ in range(n * epoch):
            label = int(rng.randint(2))
            length = int(rng.randint(16, 200))
            # sentiment-biased mixture: 70% from the class's half
            biased = rng.randint(0, half, size=length)
            uniform = rng.randint(0, vocab, size=length)
            take = rng.rand(length) < 0.7
            ids = np.where(take, biased + (half if label else 0), uniform)
            yield list(map(int, ids)), label

    return reader


def train(word_idx):
    """Reference: imdb.py:train(word_idx)."""
    return _reader_creator(word_idx, "train", synthetic_size("imdb_train", 2000))


def test(word_idx):
    return _reader_creator(word_idx, "test", synthetic_size("imdb_test", 400))
