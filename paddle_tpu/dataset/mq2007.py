"""MQ2007 LETOR learning-to-rank (reference: python/paddle/dataset/mq2007.py).

Query groups of (relevance in {0,1,2}, feature float32[46]) documents,
yielded in the reference's four formats:

- "pointwise": (score float, features (46,)) per document
- "pairwise":  (label [1], better_doc (46,), worse_doc (46,)) per ordered pair
- "listwise":  (scores (n,1), features (n,46)) per query
- "plain_txt": (query_id, score, features (46,)) per document

Synthetic source: a hidden per-query weight vector scores documents, so
rankers genuinely learn (see common.py rationale). Queries whose documents
all have relevance 0 are filtered like the reference's ``query_filter``.
"""
from __future__ import annotations

import functools

import numpy as np

from .common import rng_for, synthetic_size

__all__ = ["train", "test"]

FEATURE_DIM = 46
_DOCS_PER_QUERY = (8, 24)


def _query_group(rng):
    n = int(rng.randint(*_DOCS_PER_QUERY))
    feats = rng.rand(n, FEATURE_DIM).astype(np.float32)
    w = rng.randn(FEATURE_DIM).astype(np.float32)
    raw = feats @ w
    # bucket the latent score into relevance grades 0..2
    cut = np.percentile(raw, [60, 85])
    rel = np.digitize(raw, cut).astype(np.float64)
    return rel, feats


def _gen_pairwise(rel, feats):
    n = len(rel)
    for i in range(n):
        for j in range(i + 1, n):
            if rel[i] > rel[j]:
                yield np.array([1.0]), feats[i], feats[j]
            elif rel[i] < rel[j]:
                yield np.array([1.0]), feats[j], feats[i]


def _reader(split: str, format: str = "pairwise", shuffle: bool = False,
            fill_missing: int = -1):
    n_queries = synthetic_size("mq2007_" + split, 128)

    def reader():
        rng = rng_for("mq2007", split)
        for qid in range(n_queries):
            rel, feats = _query_group(rng)
            if rel.sum() == 0.0:  # reference query_filter
                continue
            if format == "pointwise":
                for r, f in zip(rel, feats):
                    yield float(r), f
            elif format == "pairwise":
                for pair in _gen_pairwise(rel, feats):
                    yield pair
            elif format == "listwise":
                yield rel.reshape(-1, 1), feats
            elif format == "plain_txt":
                for r, f in zip(rel, feats):
                    yield qid, float(r), f
            else:
                raise ValueError("unknown format %r" % format)

    return reader


train = functools.partial(_reader, "train")
test = functools.partial(_reader, "test")
