"""Flowers-102 (reference: python/paddle/dataset/flowers.py).

Samples: (image float32[3, 224, 224] in [0, 1] CHW, label int in [0, 102)).
Synthetic source: per-class hue template + blob texture (see common.py
rationale). The reference pipeline decodes jpegs and applies
``train_mapper``/``test_mapper`` (resize/crop/flip); synthetic samples are
generated already-transformed, so custom ``mapper``/``use_xmap`` arguments
are accepted for API parity but not applied.
"""
from __future__ import annotations

import numpy as np

from .common import rng_for, synthetic_size

__all__ = ["train", "test", "valid"]

N_CLASSES = 102
_SHAPE = (3, 224, 224)


def _templates():
    rng = rng_for("flowers", "templates")
    # low-frequency color fields: start from coarse 8x8 noise, upsample
    coarse = rng.rand(N_CLASSES, 3, 8, 8).astype(np.float32)
    return coarse


def _upsample(t):
    return np.repeat(np.repeat(t, 28, axis=-2), 28, axis=-1)


def _reader(split: str, n: int, cycle: bool = False):
    coarse = _templates()

    def reader():
        while True:
            rng = rng_for("flowers", split)
            for _ in range(n):
                label = int(rng.randint(N_CLASSES))
                img = _upsample(coarse[label])
                img = img + rng.randn(*_SHAPE).astype(np.float32) * 0.1
                yield np.clip(img, 0.0, 1.0), label
            if not cycle:
                break

    return reader


def train(mapper=None, buffered_size=1024, use_xmap=True, cycle=False):
    """Reference: flowers.py:train."""
    return _reader("train", synthetic_size("flowers_train", 2048), cycle)


def test(mapper=None, buffered_size=1024, use_xmap=True, cycle=False):
    return _reader("test", synthetic_size("flowers_test", 256), cycle)


def valid(mapper=None, buffered_size=1024, use_xmap=True):
    return _reader("valid", synthetic_size("flowers_valid", 256))
