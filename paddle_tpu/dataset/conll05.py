"""CoNLL-2005 SRL schema (reference: python/paddle/dataset/conll05.py).

Samples: 9 slots — (word_ids, ctx_n2, ctx_n1, ctx_0, ctx_p1, ctx_p2,
verb_ids, mark, label_ids) as consumed by the label_semantic_roles book
example. Synthetic source ties labels to (word, mark) structure so the
CRF/SRL pipeline trains.
"""
from __future__ import annotations

import numpy as np

from .common import rng_for, synthetic_size

__all__ = ["get_dict", "test", "get_embedding"]

_WORD_VOCAB = 4000
_VERB_VOCAB = 300
_N_LABELS = 59  # reference label dict size (B-/I-/O tags)


def get_dict():
    """Reference: conll05.py:get_dict -> (word_dict, verb_dict, label_dict)."""
    word_dict = {"w%04d" % i: i for i in range(_WORD_VOCAB)}
    verb_dict = {"v%03d" % i: i for i in range(_VERB_VOCAB)}
    label_dict = {"L%02d" % i: i for i in range(_N_LABELS)}
    return word_dict, verb_dict, label_dict


def get_embedding():
    """Reference parity: pretrained word embedding matrix."""
    rng = rng_for("conll05", "emb")
    return rng.randn(_WORD_VOCAB, 32).astype(np.float32)


def test():
    """Reference: conll05.py:test (the reference only ships test data)."""
    n = synthetic_size("conll05_test", 400)

    def reader():
        rng = rng_for("conll05", "test")
        for _ in range(n):
            length = int(rng.randint(5, 40))
            words = rng.randint(0, _WORD_VOCAB, size=length)
            verb_pos = int(rng.randint(length))
            verb = int(rng.randint(_VERB_VOCAB))
            mark = np.zeros(length, np.int64)
            mark[verb_pos] = 1
            # label correlates with distance to the verb (learnable)
            dist = np.abs(np.arange(length) - verb_pos)
            labels = (words * 7 + dist * 3) % _N_LABELS

            def ctx(off):
                idx = np.clip(np.arange(length) + off, 0, length - 1)
                return list(map(int, words[idx]))

            yield (list(map(int, words)), ctx(-2), ctx(-1), ctx(0), ctx(1),
                   ctx(2), [verb] * length, list(map(int, mark)),
                   list(map(int, labels)))

    return reader
