"""Movie-review sentiment (reference: python/paddle/dataset/sentiment.py,
NLTK-backed in the reference). Same (word ids, label) schema as imdb with
a smaller vocab.
"""
from __future__ import annotations

from . import imdb
from .common import synthetic_size

__all__ = ["get_word_dict", "train", "test"]

_VOCAB = 2000


def get_word_dict():
    """Reference: sentiment.py:get_word_dict."""
    d = {"w%04d" % i: i for i in range(_VOCAB - 1)}
    d["<unk>"] = _VOCAB - 1
    return d


def train():
    """Reference: sentiment.py:train (no word_idx arg — fixed dict)."""
    return imdb._reader_creator(get_word_dict(), "sent_train",
                                synthetic_size("sentiment_train", 1600))


def test():
    return imdb._reader_creator(get_word_dict(), "sent_test",
                                synthetic_size("sentiment_test", 400))
