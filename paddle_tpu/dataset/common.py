"""Dataset infrastructure.

Reference: python/paddle/dataset/common.py — download() with md5 checks
into ~/.cache/paddle/dataset. This environment has zero network egress, so
every dataset here is backed by a DETERMINISTIC SYNTHETIC generator with
the exact sample schema of its reference twin (same tuple layout, dtypes,
ranges, vocab handling). Real data dropped into DATA_HOME by the user is
picked up by the modules that support it (mnist idx files, uci_housing
data); otherwise the synthetic source is used transparently.

Synthetic data is class-conditional (not pure noise) so models genuinely
train on it: convergence tests and benchmarks exercise the same code paths
as real data.
"""
from __future__ import annotations

import os

import numpy as np

__all__ = ["DATA_HOME", "data_home", "rng_for", "synthetic_size"]

DATA_HOME = os.path.expanduser(
    os.environ.get("PADDLE_TPU_DATA_HOME", "~/.cache/paddle_tpu/dataset"))


def data_home(*parts: str) -> str:
    return os.path.join(DATA_HOME, *parts)


def rng_for(dataset: str, split: str) -> np.random.RandomState:
    """Deterministic per-(dataset, split) stream: every process sees the
    same data, every epoch replays identically (like files on disk)."""
    import zlib

    seed = zlib.crc32(("%s/%s" % (dataset, split)).encode()) & 0x7FFFFFFF
    return np.random.RandomState(seed)


def synthetic_size(name: str, default: int) -> int:
    """Sample counts are env-tunable (PADDLE_TPU_SYNTH_<NAME>) so CI stays
    fast while benchmarks can scale up."""
    return int(os.environ.get("PADDLE_TPU_SYNTH_" + name.upper(), default))
