"""PTB-style n-gram LM data (reference: python/paddle/dataset/imikolov.py).

train(word_idx, n) yields n-gram tuples of word ids (the word2vec book
example's input); NGRAM mode matches the reference's DataType.NGRAM.
Synthetic source: an order-1 Markov chain over the vocab so n-gram models
have real structure to learn.
"""
from __future__ import annotations

import numpy as np

from .common import rng_for, synthetic_size

__all__ = ["build_dict", "train", "test"]

_VOCAB_SIZE = 2074  # reference's min_word_freq=50 PTB dict size ballpark


def build_dict(min_word_freq: int = 50):
    """Reference: imikolov.py:build_dict — '<s>', '<e>', '<unk>' included."""
    d = {"w%04d" % i: i for i in range(_VOCAB_SIZE - 3)}
    d["<s>"] = _VOCAB_SIZE - 3
    d["<e>"] = _VOCAB_SIZE - 2
    d["<unk>"] = _VOCAB_SIZE - 1
    return d


def _markov_sentence(rng, vocab: int, length: int, trans_seed):
    # shared low-rank transition structure: next ~ (cur * a + b) mod vocab
    a, b = trans_seed
    ids = [int(rng.randint(vocab))]
    for _ in range(length - 1):
        if rng.rand() < 0.8:
            ids.append((ids[-1] * a + b + int(rng.randint(3))) % vocab)
        else:
            ids.append(int(rng.randint(vocab)))
    return ids


def _reader_creator(word_idx, n: int, split: str, count: int):
    vocab = len(word_idx)

    def reader():
        rng = rng_for("imikolov", split)
        for _ in range(count):
            length = int(rng.randint(n + 2, 40))
            sent = _markov_sentence(rng, vocab, length, (31, 7))
            for i in range(n - 1, len(sent)):
                yield tuple(sent[i - n + 1:i + 1])

    return reader


def train(word_idx, n):
    """Reference: imikolov.py:train(word_idx, n) — yields n-word windows."""
    return _reader_creator(word_idx, n, "train", synthetic_size("imikolov_train", 1000))


def test(word_idx, n):
    return _reader_creator(word_idx, n, "test", synthetic_size("imikolov_test", 200))
