"""Datasets with the reference schemas (reference: python/paddle/dataset).

All sources are deterministic synthetic generators with the exact sample
layout of their reference twins (see common.py — zero network egress);
modules pick up real files from DATA_HOME when present.
"""
from . import cifar  # noqa: F401
from . import common  # noqa: F401
from . import conll05  # noqa: F401
from . import flowers  # noqa: F401
from . import image  # noqa: F401
from . import imdb  # noqa: F401
from . import imikolov  # noqa: F401
from . import mnist  # noqa: F401
from . import movielens  # noqa: F401
from . import mq2007  # noqa: F401
from . import sentiment  # noqa: F401
from . import uci_housing  # noqa: F401
from . import voc2012  # noqa: F401
from . import wmt14  # noqa: F401
from . import wmt16  # noqa: F401

__all__ = [
    "mnist", "cifar", "uci_housing", "imdb", "imikolov", "movielens",
    "conll05", "sentiment", "wmt14", "wmt16", "image", "common",
    "flowers", "mq2007", "voc2012",
]
