"""UCI housing regression (reference: python/paddle/dataset/uci_housing.py).

Samples: (features float32[13] normalized, price float32[1]). Synthetic
source is an exact linear model + noise over normalized features, so
linear regression fits it to near-zero loss (the book example's behavior).
Real `housing.data` in DATA_HOME/uci_housing is used when present.
"""
from __future__ import annotations

import os

import numpy as np

from .common import data_home, rng_for, synthetic_size

__all__ = ["train", "test"]

feature_names = [
    "CRIM", "ZN", "INDUS", "CHAS", "NOX", "RM", "AGE", "DIS", "RAD", "TAX",
    "PTRATIO", "B", "LSTAT",
]

UCI_TRAIN_RATIO = 0.8


def _load_real():
    path = data_home("uci_housing", "housing.data")
    if not os.path.exists(path):
        return None
    data = np.loadtxt(path).astype(np.float32)
    feats, target = data[:, :13], data[:, 13:14]
    feats = (feats - feats.mean(0)) / (feats.std(0) + 1e-8)
    return feats, target


def _synthetic(split: str):
    n = synthetic_size("uci_%s" % split, 404 if split == "train" else 102)
    rng = rng_for("uci_housing", split)
    w = rng_for("uci_housing", "weights").randn(13, 1).astype(np.float32)
    feats = rng.randn(n, 13).astype(np.float32)
    target = feats @ w + 0.1 * rng.randn(n, 1).astype(np.float32) + 22.5
    return feats, target


def _reader_creator(split: str):
    def reader():
        real = _load_real()
        if real is not None:
            feats, target = real
            cut = int(len(feats) * UCI_TRAIN_RATIO)
            if split == "train":
                feats, target = feats[:cut], target[:cut]
            else:
                feats, target = feats[cut:], target[cut:]
        else:
            feats, target = _synthetic(split)
        for f, t in zip(feats, target):
            yield f, t

    return reader


def train():
    """Reference: uci_housing.py:train."""
    return _reader_creator("train")


def test():
    return _reader_creator("test")
