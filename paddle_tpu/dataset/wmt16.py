"""WMT16 en-de NMT schema (reference: python/paddle/dataset/wmt16.py).

Samples: (src ids, trg ids with <s> prefix, trg_next ids with <e> suffix).
Synthetic source: the "target" is a deterministic re-mapping of the source
sequence (a learnable toy translation), so seq2seq/transformer models fit
it and BLEU-ish overlap rises during training.
"""
from __future__ import annotations

import numpy as np

from .common import rng_for, synthetic_size

__all__ = ["train", "test", "validation", "get_dict"]

_DEFAULT_SRC_VOCAB = 10000
_DEFAULT_TRG_VOCAB = 10000

_BOS, _EOS, _UNK = 0, 1, 2


def get_dict(lang: str, dict_size: int, reverse: bool = False):
    """Reference: wmt16.py:get_dict. ids 0/1/2 = <s>/<e>/<unk>."""
    words = ["<s>", "<e>", "<unk>"] + [
        "%s_w%05d" % (lang, i) for i in range(dict_size - 3)]
    if reverse:
        return {i: w for i, w in enumerate(words)}
    return {w: i for i, w in enumerate(words)}


def _translate(src_ids, trg_vocab):
    # deterministic affine remap: the structure a model can learn
    return [(3 + ((w * 17 + 5) % (trg_vocab - 3))) for w in src_ids]


def _reader_creator(split, n, src_dict_size, trg_dict_size, src_lang):
    def reader():
        rng = rng_for("wmt16", split)
        for _ in range(n):
            length = int(rng.randint(4, 30))
            src = [int(x) for x in rng.randint(3, src_dict_size, size=length)]
            trg = _translate(src, trg_dict_size)
            yield src, [_BOS] + trg, trg + [_EOS]

    return reader


def train(src_dict_size=_DEFAULT_SRC_VOCAB, trg_dict_size=_DEFAULT_TRG_VOCAB,
          src_lang="en"):
    """Reference: wmt16.py:train."""
    return _reader_creator("train", synthetic_size("wmt16_train", 2000),
                           src_dict_size, trg_dict_size, src_lang)


def test(src_dict_size=_DEFAULT_SRC_VOCAB, trg_dict_size=_DEFAULT_TRG_VOCAB,
         src_lang="en"):
    return _reader_creator("test", synthetic_size("wmt16_test", 400),
                           src_dict_size, trg_dict_size, src_lang)


def validation(src_dict_size=_DEFAULT_SRC_VOCAB, trg_dict_size=_DEFAULT_TRG_VOCAB,
               src_lang="en"):
    return _reader_creator("val", synthetic_size("wmt16_val", 400),
                           src_dict_size, trg_dict_size, src_lang)
