"""MovieLens-1M schema (reference: python/paddle/dataset/movielens.py).

Samples: (user_id, gender_id, age_id, job_id, movie_id, category_ids,
title_ids, rating) — the recommender book example's 8 slots. Synthetic
source: latent-factor ratings (user/movie embeddings drawn once), so
factorization models can actually fit.
"""
from __future__ import annotations

import numpy as np

from .common import rng_for, synthetic_size

__all__ = [
    "train", "test", "get_movie_title_dict", "max_movie_id", "max_user_id",
    "max_job_id", "age_table", "movie_categories", "user_info", "movie_info",
]

_N_USERS = 600
_N_MOVIES = 400
_N_CATEGORIES = 18
_TITLE_VOCAB = 1000
_N_JOBS = 21

age_table = [1, 18, 25, 35, 45, 50, 56]


def max_user_id():
    """Reference: movielens.py:max_user_id."""
    return _N_USERS


def max_movie_id():
    return _N_MOVIES


def max_job_id():
    return _N_JOBS - 1


def movie_categories():
    return {"cat%02d" % i: i for i in range(_N_CATEGORIES)}


def get_movie_title_dict():
    return {"t%03d" % i: i for i in range(_TITLE_VOCAB)}


class MovieInfo:
    def __init__(self, index, categories, title):
        self.index = index
        self.categories = categories
        self.title = title


class UserInfo:
    def __init__(self, index, gender, age, job_id):
        self.index = index
        self.is_male = gender == "M"
        self.age = age
        self.job_id = job_id


def _factors():
    r = rng_for("movielens", "factors")
    uf = r.randn(_N_USERS + 1, 8).astype(np.float32)
    mf = r.randn(_N_MOVIES + 1, 8).astype(np.float32)
    return uf, mf


def movie_info():
    r = rng_for("movielens", "movies")
    out = {}
    for m in range(1, _N_MOVIES + 1):
        cats = list(map(int, r.choice(_N_CATEGORIES, size=r.randint(1, 4),
                                      replace=False)))
        title = list(map(int, r.randint(0, _TITLE_VOCAB, size=r.randint(1, 6))))
        out[m] = MovieInfo(m, cats, title)
    return out


def user_info():
    r = rng_for("movielens", "users")
    out = {}
    for u in range(1, _N_USERS + 1):
        out[u] = UserInfo(u, "M" if r.rand() < 0.5 else "F",
                          int(r.choice(age_table)), int(r.randint(_N_JOBS)))
    return out


def _reader_creator(split: str, n: int):
    def reader():
        rng = rng_for("movielens", split)
        uf, mf = _factors()
        movies, users = movie_info(), user_info()
        ages = {a: i for i, a in enumerate(age_table)}
        for _ in range(n):
            u = int(rng.randint(1, _N_USERS + 1))
            m = int(rng.randint(1, _N_MOVIES + 1))
            raw = float(uf[u] @ mf[m]) * 0.5 + 3.0 + rng.randn() * 0.3
            rating = float(np.clip(round(raw), 1, 5))
            usr, mov = users[u], movies[m]
            yield (u, int(usr.is_male), ages[usr.age], usr.job_id,
                   m, mov.categories, mov.title, rating)

    return reader


def train():
    """Reference: movielens.py:train."""
    return _reader_creator("train", synthetic_size("movielens_train", 4000))


def test():
    return _reader_creator("test", synthetic_size("movielens_test", 800))
