"""WMT14 fr-en NMT schema (reference: python/paddle/dataset/wmt14.py).

Same 3-slot sample layout as wmt16 with the reference's 30k default dicts.
"""
from __future__ import annotations

from . import wmt16
from .common import synthetic_size

__all__ = ["train", "test", "get_dict"]

_DEFAULT_VOCAB = 30000


def get_dict(dict_size: int = _DEFAULT_VOCAB, reverse: bool = False):
    """Reference: wmt14.py:get_dict returns (src_dict, trg_dict)."""
    return (wmt16.get_dict("fr", dict_size, reverse),
            wmt16.get_dict("en", dict_size, reverse))


def train(dict_size: int = _DEFAULT_VOCAB):
    """Reference: wmt14.py:train."""
    return wmt16._reader_creator("train14", synthetic_size("wmt14_train", 2000),
                                 dict_size, dict_size, "fr")


def test(dict_size: int = _DEFAULT_VOCAB):
    return wmt16._reader_creator("test14", synthetic_size("wmt14_test", 400),
                                 dict_size, dict_size, "fr")
