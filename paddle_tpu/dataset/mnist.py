"""MNIST (reference: python/paddle/dataset/mnist.py).

Samples: (image float32[784] scaled to [-1, 1], label int in 0..9) — the
reference's normalization (mnist.py:reader_creator divides by 255*2 - 1).
Real idx files in DATA_HOME/mnist are used when present; otherwise a
class-conditional synthetic source (fixed per-digit template + noise) that
MLPs/convnets learn to >95% accuracy.
"""
from __future__ import annotations

import gzip
import os
import struct

import numpy as np

from .common import data_home, rng_for, synthetic_size

__all__ = ["train", "test", "convert"]


def _real_reader(images_path, labels_path):
    def reader():
        with gzip.open(images_path, "rb") as f:
            magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
            images = np.frombuffer(f.read(), np.uint8).reshape(n, rows * cols)
        with gzip.open(labels_path, "rb") as f:
            struct.unpack(">II", f.read(8))
            labels = np.frombuffer(f.read(), np.uint8)
        for img, lbl in zip(images, labels):
            yield img.astype(np.float32) / 127.5 - 1.0, int(lbl)

    return reader


def _synthetic_reader(split: str, n: int):
    # one fixed blurred template per digit; samples = template + noise
    tmpl_rng = rng_for("mnist", "templates")
    templates = tmpl_rng.rand(10, 784).astype(np.float32)
    for _ in range(3):  # cheap blur -> low-frequency class structure
        t = templates.reshape(10, 28, 28)
        t = (t + np.roll(t, 1, 1) + np.roll(t, -1, 1)
             + np.roll(t, 1, 2) + np.roll(t, -1, 2)) / 5.0
        templates = t.reshape(10, 784)
    templates = (templates - templates.mean()) * 4.0

    def reader():
        rng = rng_for("mnist", split)
        for _ in range(n):
            label = int(rng.randint(10))
            img = templates[label] + rng.randn(784).astype(np.float32) * 0.3
            yield np.clip(img, -1.0, 1.0).astype(np.float32), label

    return reader


def train():
    """Reference: mnist.py:train."""
    imgs = data_home("mnist", "train-images-idx3-ubyte.gz")
    lbls = data_home("mnist", "train-labels-idx1-ubyte.gz")
    if os.path.exists(imgs) and os.path.exists(lbls):
        return _real_reader(imgs, lbls)
    return _synthetic_reader("train", synthetic_size("mnist_train", 8192))


def test():
    imgs = data_home("mnist", "t10k-images-idx3-ubyte.gz")
    lbls = data_home("mnist", "t10k-labels-idx1-ubyte.gz")
    if os.path.exists(imgs) and os.path.exists(lbls):
        return _real_reader(imgs, lbls)
    return _synthetic_reader("test", synthetic_size("mnist_test", 1024))


def convert(path):
    """Reference parity (recordio conversion) — see runtime.recordio."""
    from ..runtime import recordio_convert

    recordio_convert(train(), os.path.join(path, "mnist_train"))
    recordio_convert(test(), os.path.join(path, "mnist_test"))
