"""Graph-building evaluators (deprecated in the reference in favor of
fluid.metrics, kept for API parity).

Reference: python/paddle/fluid/evaluator.py. The reference versions allocate
accumulator *variables inside the program* and append sum ops; here the
layer already returns per-batch counts as fetches, and accumulation happens
host-side (the TPU step stays a pure function — mutable accumulators inside
the graph would force un-donated state for a metric).
"""
from __future__ import annotations

import warnings

import numpy as np

from . import layers

__all__ = ["ChunkEvaluator", "EditDistance"]


class Evaluator(object):
    """Warn-on-use base matching evaluator.py:Evaluator."""

    def __init__(self, name, **kwargs):
        warnings.warn(
            "fluid.evaluator.%s is deprecated, please use fluid.metrics.%s "
            "instead." % (self.__class__.__name__, self.__class__.__name__))
        self._name = name
        self.states = []
        self.metrics = []

    def reset(self, executor, reset_program=None):
        for s in self.states:
            s.fill(0)

    def eval(self, executor, eval_program=None):
        raise NotImplementedError()


class ChunkEvaluator(Evaluator):
    """Builds a chunk_eval layer; update by fetching `self.metrics` each
    step and passing the three counts to `update()`; `eval()` returns
    (precision, recall, f1)."""

    def __init__(self, input, label, chunk_scheme, num_chunk_types,
                 excluded_chunk_types=None, sequence_length=None):
        super().__init__("chunk_eval")
        (precision, recall, f1_score, num_infer_chunks, num_label_chunks,
         num_correct_chunks) = layers.chunk_eval(
            input=input, label=label, chunk_scheme=chunk_scheme,
            num_chunk_types=num_chunk_types,
            excluded_chunk_types=excluded_chunk_types,
            sequence_length=sequence_length)
        self.metrics = [num_infer_chunks, num_label_chunks, num_correct_chunks]
        self.precision = precision
        self.recall = recall
        self.f1_score = f1_score
        self._acc = np.zeros(3, np.int64)
        self.states = [self._acc]

    def update(self, num_infer_chunks, num_label_chunks, num_correct_chunks):
        self._acc += np.array(
            [int(np.asarray(v).reshape(-1)[0])
             for v in (num_infer_chunks, num_label_chunks, num_correct_chunks)],
            np.int64)

    def eval(self, executor=None, eval_program=None):
        ni, nl, nc = (int(v) for v in self._acc)
        precision = float(nc) / ni if ni else 0.0
        recall = float(nc) / nl if nl else 0.0
        f1 = 2 * precision * recall / (precision + recall) if nc else 0.0
        return precision, recall, f1


class EditDistance(Evaluator):
    """Builds an edit_distance layer; fetch `self.metrics` per step into
    `update()`; `eval()` returns (avg distance, instance error rate)."""

    def __init__(self, input, label, ignored_tokens=None, input_length=None,
                 label_length=None):
        super().__init__("edit_distance")
        distances, seq_num = layers.edit_distance(
            input=input, label=label, ignored_tokens=ignored_tokens,
            input_length=input_length, label_length=label_length)
        self.metrics = [distances, seq_num]
        self._total = np.zeros(3, np.float64)  # distance, seq_num, errors
        self.states = [self._total]

    def update(self, distances, seq_num):
        d = np.asarray(distances, np.float64).reshape(-1)
        n = int(np.asarray(seq_num).reshape(-1)[0])
        self._total += np.array(
            [float(d.sum()), n, n - int((d == 0).sum())], np.float64)

    def eval(self, executor=None, eval_program=None):
        dist, num, err = self._total
        if num == 0:
            raise ValueError("no data accumulated in EditDistance evaluator")
        return dist / num, err / num
