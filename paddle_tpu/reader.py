"""Reader decorators: composable python-generator data pipelines.

Reference: python/paddle/reader/decorator.py (map_readers/buffered/compose/
chain/shuffle/firstn/xmap_readers/PipeReader) and python/paddle/batch.py
(batch). A *reader creator* is a zero-arg callable returning an iterator of
samples; decorators wrap creators and stay lazy.

The threaded decorators (buffered, xmap_readers) keep the host-side
pipeline ahead of the device: on TPU the jitted step consumes a batch in
one transfer, so a couple of worker threads is enough to hide cheap IO.
The heavier double-buffer path is io/reader.py's DoubleBufferReader over
the C++ bounded channel/prefetch in runtime/runtime.cc; when per-sample
decode is heavy enough to serialize these THREADS on the GIL (PIL/cv2
style transforms), use io/dataloader.py's DataLoader — worker PROCESSES
feeding batches through shared memory.
"""
from __future__ import annotations

import itertools
import random as _random
from queue import Queue
from threading import Condition, Thread

class _RaiseSignal:
    """Carries a worker-thread exception to the consuming generator."""

    def __init__(self, exc):
        self.exc = exc


_Raise = _RaiseSignal

__all__ = [
    "map_readers",
    "buffered",
    "compose",
    "chain",
    "shuffle",
    "ComposeNotAligned",
    "firstn",
    "xmap_readers",
    "PipeReader",
    "cache",
    "batch",
]


class ComposeNotAligned(ValueError):
    pass


def map_readers(func, *readers):
    """Creator yielding func applied across the component readers' samples
    (reference decorator.py:map_readers)."""

    def reader():
        rs = [r() for r in readers]
        for vals in zip(*rs):
            yield func(*vals)

    return reader


def shuffle(reader, buf_size):
    """Shuffle within a sliding buffer (reference decorator.py:shuffle)."""

    def data_reader():
        buf = []
        for e in reader():
            buf.append(e)
            if len(buf) >= buf_size:
                _random.shuffle(buf)
                for b in buf:
                    yield b
                buf = []
        if buf:
            _random.shuffle(buf)
            for b in buf:
                yield b

    return data_reader


def chain(*readers):
    """Concatenate readers back-to-back (reference decorator.py:chain)."""

    def reader():
        return itertools.chain(*[r() for r in readers])

    return reader


def compose(*readers, **kwargs):
    """Zip readers into combined samples (reference decorator.py:compose).
    check_alignment=True raises ComposeNotAligned on length mismatch."""
    check_alignment = kwargs.pop("check_alignment", True)

    def make_tuple(x):
        if isinstance(x, tuple):
            return x
        return (x,)

    def reader():
        rs = [r() for r in readers]
        if not check_alignment:
            for outputs in zip(*rs):
                yield sum(list(map(make_tuple, outputs)), ())
        else:
            for outputs in itertools.zip_longest(*rs):
                if any(o is None for o in outputs):
                    raise ComposeNotAligned(
                        "outputs of readers are not aligned")
                yield sum(list(map(make_tuple, outputs)), ())

    return reader


def buffered(reader, size):
    """Worker thread keeps up to `size` samples decoded ahead of the
    consumer (reference decorator.py:buffered)."""

    class _End:
        pass

    def data_reader():
        r = reader()
        q = Queue(maxsize=size)

        def read_worker():
            # a reader exception must reach the consumer, not kill the
            # thread silently (which would leave the consumer blocked)
            try:
                for d in r:
                    q.put(d)
                q.put(_End)
            except BaseException as exc:  # noqa: B036
                q.put(_Raise(exc))

        t = Thread(target=read_worker)
        t.daemon = True
        t.start()
        e = q.get()
        while e is not _End:
            if isinstance(e, _Raise):
                raise e.exc
            yield e
            e = q.get()

    return data_reader


def firstn(reader, n):
    """First n samples only (reference decorator.py:firstn)."""

    def firstn_reader():
        for i, item in enumerate(reader()):
            if i == n:
                break
            yield item

    return firstn_reader


def xmap_readers(mapper, reader, process_num, buffer_size, order=False):
    """Parallel map over samples with `process_num` worker THREADS
    (reference decorator.py:xmap_readers uses threads too, despite the
    name). With order=True output order matches input order."""
    end = XmapEndSignal()

    def read_worker(r, in_queue):
        try:
            for i in r():
                in_queue.put(i)
            in_queue.put(end)
        except BaseException as exc:  # noqa: B036
            in_queue.put(_Raise(exc))

    def order_read_worker(r, in_queue):
        try:
            for i, d in enumerate(r()):
                in_queue.put((i, d))
            in_queue.put(end)
        except BaseException as exc:  # noqa: B036
            in_queue.put(_Raise(exc))

    def _relay(signal, in_queue, out_queue):
        # out_queue FIRST (the consumer must unblock even if in_queue is
        # full and no sibling will ever drain it); the in_queue relay to
        # sibling workers is best-effort
        out_queue.put(signal)
        try:
            in_queue.put_nowait(signal)
        except Exception:
            pass

    def handle_worker(in_queue, out_queue):
        sample = in_queue.get()
        try:
            while not isinstance(sample, (XmapEndSignal, _Raise)):
                out_queue.put(mapper(sample))
                sample = in_queue.get()
        except BaseException as exc:  # noqa: B036
            sample = _Raise(exc)
        _relay(sample if isinstance(sample, _Raise) else end,
               in_queue, out_queue)

    def order_handle_worker(in_queue, out_queue, out_order, err, turn):
        # `turn` (a Condition over out_order) replaces the old
        # _time.sleep(0) busy-spin: a worker whose sample is done but
        # whose turn hasn't come SLEPT on the scheduler, burning a full
        # core per waiting worker. Only the current-turn worker can
        # advance out_order, so emitting outside the lock is safe.
        ins = in_queue.get()
        try:
            while not isinstance(ins, (XmapEndSignal, _Raise)):
                order, sample = ins
                result = mapper(sample)
                with turn:
                    while order != out_order[0] and err[0] is None:
                        turn.wait()
                    if err[0] is not None:
                        break
                out_queue.put(result)
                with turn:
                    out_order[0] += 1
                    turn.notify_all()
                ins = in_queue.get()
        except BaseException as exc:  # noqa: B036
            ins = _Raise(exc)
        if isinstance(ins, _Raise):
            with turn:
                err[0] = ins.exc  # releases siblings waiting on out_order
                turn.notify_all()
        _relay(ins if isinstance(ins, _Raise) else end, in_queue, out_queue)

    def xreader():
        in_queue = Queue(buffer_size)
        out_queue = Queue(buffer_size)
        out_order = [0]
        target = order_read_worker if order else read_worker
        t = Thread(target=target, args=(reader, in_queue))
        t.daemon = True
        t.start()
        workers = []
        err = [None]
        turn = Condition()
        htarget = order_handle_worker if order else handle_worker
        hargs = ((in_queue, out_queue, out_order, err, turn) if order
                 else (in_queue, out_queue))
        for _ in range(process_num):
            w = Thread(target=htarget, args=hargs)
            w.daemon = True
            w.start()
            workers.append(w)
        finish = 0
        while finish < process_num:
            sample = out_queue.get()
            if isinstance(sample, _Raise):
                raise sample.exc
            if isinstance(sample, XmapEndSignal):
                finish += 1
            else:
                yield sample

    return xreader


class XmapEndSignal:
    pass


def cache(reader):
    """Materialize once, replay from memory thereafter."""
    all_data = []
    filled = [False]

    def cache_reader():
        if not filled[0]:
            for item in reader():
                all_data.append(item)
                yield item
            filled[0] = True
        else:
            for item in all_data:
                yield item

    return cache_reader


def batch(reader, batch_size, drop_last=False):
    """Group samples into lists of batch_size (reference:
    python/paddle/batch.py:batch)."""

    def batch_reader():
        r = reader()
        b = []
        for instance in r:
            b.append(instance)
            if len(b) == batch_size:
                yield b
                b = []
        if b and not drop_last:
            yield b

    return batch_reader


class PipeReader:
    """Stream records from a shell command's stdout (reference:
    python/paddle/reader/decorator.py:PipeReader) — the escape hatch for
    data living behind CLI tools (object stores, HDFS cat, curl). The
    "gzip" file_type transparently inflates the stream."""

    def __init__(self, command, bufsize: int = 8192, file_type: str = "plain"):
        import subprocess
        import zlib

        if not isinstance(command, str):
            raise TypeError("command must be a string")
        if file_type not in ("plain", "gzip"):
            raise TypeError("file_type %s is not allowed" % file_type)
        if file_type == "gzip":
            # wbits offset 32: auto-detect the gzip header
            self.dec = zlib.decompressobj(32 + zlib.MAX_WBITS)
        self.file_type = file_type
        self.bufsize = bufsize
        self.process = subprocess.Popen(
            command.split(" "), bufsize=bufsize, stdout=subprocess.PIPE)

    def get_line(self, cut_lines: bool = True, line_break: str = "\n"):
        """Yield decoded lines (or raw buffers with cut_lines=False).
        Decoding is incremental so a multi-byte UTF-8 character split
        across read() chunks survives (the reference decodes chunkwise
        and dies on that boundary). The subprocess is reaped when the
        stream ends."""
        import codecs

        decoder = codecs.getincrementaldecoder("utf-8")()
        remained = ""
        while True:
            buff = self.process.stdout.read(self.bufsize)
            final = not buff
            if self.file_type == "gzip":
                raw = self.dec.decompress(buff) if buff else self.dec.flush()
            else:
                raw = buff or b""
            decomp_buff = decoder.decode(raw, final)
            if cut_lines:
                lines = (remained + decomp_buff).split(line_break)
                remained = lines.pop()  # tail without a terminator yet
                for line in lines:
                    yield line
            elif decomp_buff:
                yield decomp_buff
            if final:
                break
        self.close()
        if remained:
            yield remained

    def close(self):
        """Close the pipe and reap the child (also called automatically
        when get_line drains the stream). A child that ignores the closed
        pipe (e.g. `tail -f` abandoned mid-stream) is terminated rather
        than waited on forever."""
        if self.process.stdout and not self.process.stdout.closed:
            self.process.stdout.close()
        try:
            self.process.wait(timeout=1.0)
        except Exception:
            self.process.terminate()
            try:
                self.process.wait(timeout=1.0)
            except Exception:
                self.process.kill()
                self.process.wait()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        self.close()
        return False
