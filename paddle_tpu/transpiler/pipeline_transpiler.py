"""PipelineTranspiler: program-level pipeline-parallel planning.

The reference's transpilers rewrite the ProgramDesc (reference:
python/paddle/fluid/transpiler/distribute_transpiler.py:159 splits
params/ops across workers and wires send/recv ops). TPU-native the
Program stays untouched: ``transpile()`` runs the structural stage-cut
pass (``parallel.pipeline_program.plan_pipeline``) and the result plugs
into ParallelExecutor via ``build_strategy()``. The pass itself — not a
hand-written ``stage_fn`` — decides where the stages cut, so the SAME
Program that runs dp/tp/sp also runs pp.
"""
from __future__ import annotations

from typing import Optional

from ..framework.core import Program, default_main_program
from ..parallel.pipeline_program import PipelinePlan, plan_pipeline

__all__ = ["PipelineTranspiler"]


class PipelineTranspiler:
    def __init__(self, num_stages: int, num_microbatches: int = 1,
                 pipeline_axis: str = "pp"):
        self.num_stages = int(num_stages)
        self.num_microbatches = int(num_microbatches)
        self.pipeline_axis = pipeline_axis
        self._plan: Optional[PipelinePlan] = None

    def transpile(self, program: Optional[Program] = None) -> PipelinePlan:
        """Plan the stage cut; raises PipelineError with a diagnosis when
        the program has no pipelineable layer structure."""
        program = program if program is not None else default_main_program()
        self._plan = plan_pipeline(program, self.num_stages)
        return self._plan

    @property
    def plan(self) -> PipelinePlan:
        if self._plan is None:
            raise RuntimeError("call transpile() first")
        return self._plan

    def build_strategy(self):
        """A BuildStrategy carrying this transpiler's pipeline config —
        pass to ParallelExecutor(build_strategy=...)."""
        from ..parallel.parallel_executor import BuildStrategy

        bs = BuildStrategy()
        bs.pipeline_stages = self.num_stages
        bs.pipeline_microbatches = self.num_microbatches
        bs.pipeline_axis = self.pipeline_axis
        return bs
