"""Program transpilers (reference: python/paddle/fluid/transpiler/).

- DistributeTranspiler: the reference rewrites the graph into trainer +
  pserver programs with gRPC send/recv ops. TPU-native it emits sharding
  plans (pserver param shards -> ZeRO-style sharded optimizer state).
- memory_optimize / release_memory: the reference does liveness-based
  var reuse; XLA owns buffer assignment here, so this exposes the
  rematerialization policy knob instead (see memory_optimizer.py).
- InferenceTranspiler: inference-time graph rewrites (BN fold) — now a
  shim over the optimizing transpiler's conv_bn fold.
- PipelineTranspiler: structural stage-cut pass — the SAME Program that
  runs dp/tp/sp runs pipelined under a pp mesh axis.
- passes/: the optimizing transpiler — a parity-gated pass manager
  (constant folding, CSE, dead-op elimination, fc/conv+bn fusion, feed
  bucketization) behind ``optimize_program`` and ``PADDLE_TPU_OPT``.
"""
from .distribute_transpiler import DistributeTranspiler, DistributeTranspilerConfig  # noqa: F401
from .memory_optimizer import memory_optimize, release_memory  # noqa: F401
from .inference_transpiler import InferenceTranspiler  # noqa: F401
from .pipeline_transpiler import PipelineTranspiler  # noqa: F401
from . import passes  # noqa: F401
from .passes import PassManager, optimize_program  # noqa: F401

__all__ = [
    "DistributeTranspiler",
    "DistributeTranspilerConfig",
    "memory_optimize",
    "release_memory",
    "InferenceTranspiler",
    "PipelineTranspiler",
    "PassManager",
    "optimize_program",
    "passes",
]
