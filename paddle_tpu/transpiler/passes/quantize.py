"""Level-3 quantize pass: rewrite fc/conv ops onto the int8 kernels.

Runs on the PR-11 pass manager AFTER the fusion passes (so every
``layers.fc`` chain arrives as one ``fused_fc`` and quantizes with its
bias/activation epilogue intact) and BEFORE bucketize (the stamped
program still proves row-wise through ``quantized_matmul``). The pass
only fires when the PassContext carries a :class:`CalibrationTable`
(``optimize_program(..., calib=table)`` /
``save_inference_model(quantize=table)``) — ``PADDLE_TPU_OPT=3``
without a table runs the level-2 pipeline and leaves precision alone.

Per rewritten op:

- the float weight quantizes symmetrically per OUTPUT channel over its
  flattened contraction layout; the int8 tensor materializes as a fresh
  persistable param (``<w>.int8``) through ``device_owned_tree`` — raw
  numpy in donated state is the PR-10 heap-corruption lesson;
- the per-tensor activation scale (calibrated amax) and the per-channel
  weight scales ride as op ATTRS, so the program JSON is
  self-contained;
- the replacement is 1:1 in place (same Out name, same block position,
  ``__rng_idx__`` preserved), so keep-set and RNG contracts hold
  trivially;
- the float weight's declaration is dropped from the optimized CLONE
  when nothing else reads it — ``save_inference_model`` then exports
  int8 weights only (the original program and Scope keep the float
  values untouched).

Tolerance parity, not bit parity: quantization rounds by design
(``exact=False``); ``quant/parity.py`` and ``tools/bench_quant.py``
gate the drift against float serving.
"""
from __future__ import annotations

import numpy as np

from ... import observability as obs
from .manager import RNG_IDX_ATTR, register_pass

# attr marking ops this pass emitted (idempotence: a re-run must not
# try to re-quantize its own output)
_QUANT_ATTR = "__quantized__"


def _fresh(block, name: str) -> str:
    cand = name
    while block._find_var_recursive(cand) is not None:
        cand += "_"
    return cand


def _owned(arrays):
    from ...checkpoint.manager import device_owned_tree

    return device_owned_tree(arrays)


def _materialize_int8(gb, scope, w_name: str, wq: np.ndarray) -> str:
    """Declare + store the int8 twin of ``w_name``; returns its name."""
    new_name = _fresh(gb, w_name + ".int8")
    gb.create_var(name=new_name, shape=tuple(wq.shape), dtype="int8",
                  persistable=True)
    scope.set_var(new_name, _owned({"w": wq})["w"])
    return new_name


def _quantize_fc(ctx, gb, op, idx, calib, scope) -> bool:
    """mul / matmul / fused_fc -> quantized_matmul (False = skipped)."""
    import math as _math

    from ...framework.core import Operator
    from ...ops.quant import quantize_weight_2d

    if op.type == "matmul" and (
            op.attr("transpose_X", False) or op.attr("transpose_Y", False)
            or op.attr("alpha", 1.0) != 1.0):
        return False
    if len(op.input("X")) != 1 or len(op.input("Y")) != 1 \
            or len(op.output("Out")) != 1:
        return False
    x_name, w_name = op.input("X")[0], op.input("Y")[0]
    wvar = gb._find_var_recursive(w_name)
    if wvar is None or not wvar.persistable:
        return False
    wval = scope.find_var(w_name)
    if wval is None:
        return False
    x_scale = calib.scale_for(x_name)
    if x_scale is None:
        return False
    w = np.asarray(wval)
    if w.dtype.kind != "f":
        return False  # already integer (or exotic) — nothing to gain
    matmul_kind = (op.type == "matmul"
                   or (op.type == "fused_fc"
                       and op.attr("kind", "mul") == "matmul"))
    if matmul_kind:
        # the fused flatten below equals jnp.matmul only for plain 2-D
        # operands; batched (rank>2) matmuls — bare OR fused into a
        # fused_fc(kind="matmul") — keep their float kernel
        xs = ctx.inference.shape(x_name)
        if w.ndim != 2 or xs is None or len(xs) != 2:
            return False
        xnc, ync = 1, 1
    else:
        xnc = int(op.attr("x_num_col_dims", 1))
        ync = int(op.attr("y_num_col_dims", 1))
        if xnc < 1 or ync < 1 or ync > w.ndim:
            return False
    # one int8 twin per (weight, flatten) even when several ops share
    # the weight (tied projections): re-materializing per reader would
    # ship N identical int8 copies
    memo_key = (w_name, ync)
    hit = ctx._int8_weights.get(memo_key)
    if hit is not None:
        wq_name, y_scale = hit
    else:
        w2 = w.reshape((_math.prod(w.shape[:ync]), -1))
        wq2, y_scale = quantize_weight_2d(w2)
        # calibrated weight amax (if present) must agree with the
        # stored value's layout; the scope value is authoritative
        wq = wq2.reshape(w.shape)
        wq_name = _materialize_int8(gb, scope, w_name, wq)
        ctx._int8_weights[memo_key] = (wq_name, y_scale)
    attrs = {
        "kind": "matmul" if matmul_kind else "mul",
        "x_num_col_dims": xnc,
        "y_num_col_dims": ync,
        "x_scale": float(x_scale),
        "y_scale": np.asarray(y_scale, np.float32),
        "axis": op.attr("axis", -1),
        "act": op.attr("act", "") if op.type == "fused_fc" else "",
        _QUANT_ATTR: True,
    }
    if RNG_IDX_ATTR in op.attrs:
        attrs[RNG_IDX_ATTR] = op.attrs[RNG_IDX_ATTR]
    inputs = {"X": op.input("X"), "Y": [wq_name]}
    if op.type == "fused_fc" and op.input("Bias"):
        inputs["Bias"] = op.input("Bias")
    new_op = Operator(gb, type="quantized_matmul", inputs=inputs,
                      outputs={"Out": op.output("Out")}, attrs=attrs)
    gb.ops[idx] = new_op
    gb._note_writes(new_op)
    return True


def _quantize_conv(ctx, gb, op, idx, calib, scope) -> bool:
    """conv2d -> quantized_conv2d (False = skipped)."""
    from ...framework.core import Operator
    from ...ops.quant import quantize_conv_filter

    if len(op.input("Input")) != 1 or len(op.input("Filter")) != 1 \
            or len(op.output("Output")) != 1:
        return False
    x_name, w_name = op.input("Input")[0], op.input("Filter")[0]
    wvar = gb._find_var_recursive(w_name)
    if wvar is None or not wvar.persistable:
        return False  # derived in-graph filter (the conv_bn_fold lesson)
    wval = scope.find_var(w_name)
    if wval is None:
        return False
    x_scale = calib.scale_for(x_name)
    if x_scale is None:
        return False
    w = np.asarray(wval)
    if w.dtype.kind != "f" or w.ndim != 4:
        return False
    wq, w_scale = quantize_conv_filter(w)
    wq_name = _materialize_int8(gb, scope, w_name, wq)
    attrs = {
        "strides": op.attr("strides", [1, 1]),
        "paddings": op.attr("paddings", [0, 0]),
        "dilations": op.attr("dilations", [1, 1]),
        "groups": op.attr("groups", 1),
        "data_format": op.attr("data_format", "NCHW"),
        "x_scale": float(x_scale),
        "w_scale": np.asarray(w_scale, np.float32),
        _QUANT_ATTR: True,
    }
    if RNG_IDX_ATTR in op.attrs:
        attrs[RNG_IDX_ATTR] = op.attrs[RNG_IDX_ATTR]
    new_op = Operator(gb, type="quantized_conv2d",
                      inputs={"Input": op.input("Input"),
                              "Filter": [wq_name]},
                      outputs={"Output": op.output("Output")}, attrs=attrs)
    gb.ops[idx] = new_op
    gb._note_writes(new_op)
    return True


@register_pass("quantize", level=3, exact=False, needs_scope=True)
def quantize(ctx) -> int:
    """Rewrite calibrated fc/conv ops in the global block onto the int8
    kernels; stamps ``program._quantized`` so the serving tier is
    visible (Engine.meta / aot_cache_ls) and the stamp rides the
    program JSON."""
    calib = getattr(ctx, "calib", None)
    if calib is None:
        return 0
    program = ctx.program
    if getattr(program, "_amp", False):
        # AMP rewrites precision at trace time; stacking int8 on top
        # would double-round unpredictably
        return 0
    gb = program.global_block()
    scope = ctx.scope
    # (weight name, flatten) -> (int8 name, scales): shared weights
    # materialize once per optimization run
    ctx._int8_weights = getattr(ctx, "_int8_weights", {})
    replaced_weights = []
    n = 0
    for idx, op in enumerate(list(gb.ops)):
        if op.attr(_QUANT_ATTR, False):
            continue
        if op.type in ("mul", "matmul", "fused_fc"):
            w_name = op.input("Y")[0] if op.input("Y") else None
            done = _quantize_fc(ctx, gb, op, idx, calib, scope)
        elif op.type == "conv2d":
            w_name = op.input("Filter")[0] if op.input("Filter") else None
            done = _quantize_conv(ctx, gb, op, idx, calib, scope)
        else:
            continue
        if done:
            n += 1
            replaced_weights.append(w_name)
            obs.QUANT_OPS.inc(op=op.type)
    if not n:
        return 0
    # drop float-weight declarations nothing reads anymore — the export
    # then ships int8 params only (the Scope keeps the float values; the
    # RAW program still uses them)
    still_read = set(ctx.keep_names())
    for block in program.blocks:
        for op in block.ops:
            still_read.update(op.input_arg_names)
    for w_name in replaced_weights:
        if w_name and w_name not in still_read:
            for block in program.blocks:
                if w_name in block.vars:
                    del block.vars[w_name]
    stamp = dict(getattr(program, "_quantized", None) or {})
    stamp["ops"] = int(stamp.get("ops", 0)) + n
    stamp["version"] = 1
    program._quantized = stamp
    program._bump()
    ctx.count("quantize", "ops_quantized", n)
    return n
