"""Constant folding: evaluate transpile-time-constant ops at build time.

An op folds when every input is a known constant: either a *scope
constant* — a persistable var with a Scope value that NO op in the
program writes (training params are optimizer-written, so they never
qualify in a training program) — or the output of an already-folded op
(``fill_constant``-style sources seed the lattice with zero inputs).
Folded ops are evaluated eagerly through their real kernels (the same
functions the tracer calls) and deleted; whole chains collapse in one
sweep.

Where the result lands depends on what ROOTED the chain, because parity
must be bit-exact:

- chains touching any scope constant are *runtime* values in both the
  raw program (state enters as an executor input) and the optimized one
  — the result materializes as a persistable parameter (XLA-owned
  buffer, the PR-10 donation lesson);
- chains rooted ONLY in attr-embedded constants were *compile-time*
  constants in the raw program (XLA constant-folds them into the
  computation), so they must STAY compile-time constants: the chain
  collapses to one ``assign_value`` op carrying the evaluated array as
  an attr. Materializing these as parameters instead measurably changes
  XLA's simplification (a state input can't be algebraically folded the
  way a literal can) — observed as last-ulp output drift.

Exactness: the whitelist is restricted to ops whose eager evaluation is
bit-identical to their in-graph execution (structural/elementwise/
reduction kernels). Under AMP, ops the tracer would cast (trace.py
bf16 sets) are excluded — folding would compute them at fp32.
"""
from __future__ import annotations

import numpy as np

from ... import observability as obs
from .manager import PLUMBING_OPS, register_pass

# evaluation-safe op set (no RNG, no side effects, no data-dependent
# output shapes beyond what the attrs pin, bit-stable eager-vs-traced)
FOLDABLE = {
    # sources
    "fill_constant", "fill", "assign_value", "fill_zeros_like",
    # structural
    "assign", "cast", "shape", "concat", "reshape", "transpose",
    "stack", "unstack", "squeeze", "unsqueeze", "split", "expand",
    "one_hot", "flatten", "reverse",
    # elementwise
    "elementwise_add", "elementwise_sub", "elementwise_mul",
    "elementwise_div", "elementwise_max", "elementwise_min",
    "elementwise_pow", "elementwise_mod", "scale", "clip", "sum",
    "sigmoid", "logsigmoid", "exp", "relu", "tanh", "tanh_shrink",
    "sqrt", "abs", "ceil", "floor", "cos", "sin", "round",
    "reciprocal", "square", "softplus", "softsign", "log", "sign",
    "relu6", "leaky_relu", "elu", "brelu", "soft_relu", "pow", "stanh",
    "hard_sigmoid", "swish", "thresholded_relu", "hard_shrink",
    "softshrink", "cumsum", "minus",
    # reductions (same shape eager and traced -> same reduction order)
    "reduce_sum", "reduce_mean", "reduce_max", "reduce_min",
    "reduce_prod",
}

# ops AMP never touches: the only foldable set when program._amp is on
_AMP_NEUTRAL = {
    "fill_constant", "fill", "assign_value", "fill_zeros_like", "assign",
    "cast", "shape", "concat", "reshape", "transpose", "stack",
    "unstack", "squeeze", "unsqueeze", "split", "expand", "one_hot",
    "flatten", "reverse",
}

# marker on the assign_value ops THIS pass emits, so a later run neither
# re-folds them (churn) nor seeds from them (already terminal)
_FOLDED_ATTR = "__folded__"

# don't materialize constants bigger than this (elements): a folded
# giant would bloat exports/attrs for a negligible per-step win
_MAX_FOLD_ELEMS = 1 << 22


def _no_rng():
    raise RuntimeError("foldable ops must not draw RNG")


@register_pass("constant_fold", level=1, exact=True, needs_scope=True)
def constant_fold(ctx) -> int:
    """One forward sweep over the global block; folded outputs become
    constants for later ops, so chains collapse in a single invocation
    (the manager's fixpoint loop catches anything order-dependent)."""
    import jax.numpy as jnp

    from ...framework.core import Operator
    from ...framework.trace import trace_op

    program, scope = ctx.program, ctx.scope
    gb = program.global_block()
    writers = ctx.writer_counts()
    keep = ctx.keep_names()
    allowed = _AMP_NEUTRAL if getattr(program, "_amp", False) else FOLDABLE

    # seed: persistable vars with a scope value and no writer in the
    # program (frozen state — the optimize_program docstring contract)
    const_vals, const_kind = {}, {}
    for block in program.blocks:
        for name, var in block.vars.items():
            if (var.persistable and writers.get(name, 0) == 0
                    and name not in ctx.feed_names):
                val = scope.find_var(name)
                if val is not None:
                    const_vals[name] = val
                    const_kind[name] = "state"

    folded_ops = 0
    new_ops = []
    produced = []  # folded names in production order
    for op in gb.ops:
        t = op.type
        foldable = (
            t in allowed and t not in PLUMBING_OPS
            and not op.attr(_FOLDED_ATTR, False)
            and op.attr("sub_block") is None
            # pure sources (fill_constant) have no inputs: all() is True
            and all(n in const_vals for n in op.input_arg_names)
            and all(writers.get(n, 0) == 1 for n in op.output_arg_names)
            and not any(
                gb._find_var_recursive(n) is not None
                and gb._find_var_recursive(n).persistable
                for n in op.output_arg_names)
            and op.output_arg_names
        )
        if not foldable:
            new_ops.append(op)
            continue
        env = {n: jnp.asarray(np.asarray(const_vals[n]))
               for n in op.input_arg_names}
        try:
            trace_op(op, gb, env, _no_rng)
        except Exception:
            # a kernel that can't evaluate eagerly (exotic attrs) simply
            # stays in the graph — folding is an optimization, not a
            # correctness requirement
            new_ops.append(op)
            continue
        outs = {n: np.asarray(env[n]) for n in op.output_arg_names
                if n in env}
        if (len(outs) != len(op.output_arg_names)
                or sum(v.size for v in outs.values()) > _MAX_FOLD_ELEMS):
            new_ops.append(op)
            continue
        kind = ("state" if any(const_kind[n] == "state"
                               for n in op.input_arg_names) else "attr")
        if kind == "state" and any(n in keep for n in op.output_arg_names):
            # a kept name (fetch target / sub-block closure) must stay
            # PRODUCED by the graph: state-kind results materialize as
            # scope values no op reads, which analyze_state would never
            # upload and the step could never fetch. Keep the terminal
            # op; its (const) inputs still fold upstream.
            new_ops.append(op)
            continue
        for name, val in outs.items():
            const_vals[name] = val
            const_kind[name] = kind
            produced.append(name)
        folded_ops += 1
    if not folded_ops:
        return 0

    # materialize the folded names something still reads
    still_read = set(keep)
    for op in new_ops:
        still_read.update(op.input_arg_names)
        if op.type == "autodiff":
            still_read.add(op.attr("loss_name"))
            still_read.update(op.attr("param_names") or ())
    from .manager import RNG_IDX_ATTR

    emitted = []
    state_names = []
    for name in produced:
        if name not in still_read:
            continue  # chain intermediate: vanishes entirely
        val = const_vals[name]
        if const_kind[name] == "state":
            state_names.append(name)
            var = gb._find_var_recursive(name)
            if var is not None:
                var.persistable = True
        else:
            emitted.append(Operator(
                gb, type="assign_value", inputs={},
                outputs={"Out": [name]},
                attrs={"values": np.asarray(val), "shape": list(val.shape),
                       "dtype": str(val.dtype), _FOLDED_ATTR: True,
                       # pre-stamped at the position it will occupy, so a
                       # re-run's stamping pass is a no-op (idempotence)
                       RNG_IDX_ATTR: len(emitted)}))
    if state_names:
        # runtime state the executor will DONATE: must be XLA-owned
        # buffers, never numpy-owned memory (the PR-10 heap-corruption
        # lesson — checkpoint/manager.py device_owned_tree)
        from ...checkpoint.manager import device_owned_tree

        owned = device_owned_tree({n: const_vals[n] for n in state_names})
        for name in state_names:
            scope.set_var(name, owned[name])
    gb.ops[:] = emitted + new_ops
    for op in emitted:
        gb._note_writes(op)
    program._bump()
    removed = folded_ops - len(emitted)
    ctx.count("constant_fold", "ops_removed", max(removed, 0))
    if removed > 0:
        obs.TRANSPILE_OPS_REMOVED.inc(removed, **{"pass": "constant_fold"})
    return folded_ops
