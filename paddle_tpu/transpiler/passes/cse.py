"""Common-subexpression elimination over the global block.

Two ops compute the same value when they have the same type, the same
attrs, and the same input VALUES. Input names stand in for values only
while every one of them has exactly one writer (the verifier's
write-once discipline makes this the common case); anything touched by
a rewriting op (``assign``/``increment``/scatter loops) is excluded, as
is anything impure (RNG, side effects, sub-blocks, persistable writes).

The duplicate op is deleted and all later references to its outputs are
renamed to the canonical op's outputs. A duplicate whose output name
must stay addressable (fetch target / sub-block closure) is rewritten
to a single ``assign`` from the canonical value instead — the value is
computed once either way (and XLA aliases the assign away).
"""
from __future__ import annotations

from typing import Dict

import numpy as np

from ... import observability as obs
from .manager import PLUMBING_OPS, register_pass, rewrite_inputs

# never CSE: nondeterministic, stateful, structural, or
# output-name-sensitive ops ("assign" is how WE preserve kept names — a
# second CSE round must not collapse two kept-name assigns into one)
_IMPURE = PLUMBING_OPS | {
    "autodiff", "assign", "print", "while", "conditional_block", "switch",
    "static_rnn", "dynamic_rnn", "beam_search", "write_to_array",
    "read_from_array", "create_array", "increment", "scatter",
    "dropout", "uniform_random", "gaussian_random",
    "uniform_random_batch_size_like", "gaussian_random_batch_size_like",
    "truncated_gaussian_random", "sampling_id", "random_crop",
    "top_k_sample", "top_p_sample", "load_file",
}

# attrs that are bookkeeping, not semantics
_KEY_IGNORED_ATTRS = {"__rng_idx__"}


def _attr_key(attrs: dict):
    items = []
    for k in sorted(attrs):
        if k in _KEY_IGNORED_ATTRS:
            continue
        v = attrs[k]
        if isinstance(v, np.ndarray):
            v = ("__nd__", str(v.dtype), v.shape, v.tobytes())
        elif isinstance(v, (list, tuple)):
            v = tuple(v)
        items.append((k, v))
    return tuple(items)


@register_pass("cse", level=1, exact=True)
def cse(ctx) -> int:
    program = ctx.program
    gb = program.global_block()
    writers = ctx.writer_counts()
    keep = ctx.keep_names()

    def persistable(name: str) -> bool:
        var = gb._find_var_recursive(name)
        return var is not None and var.persistable

    # write positions per name: the trace env is imperative, so two
    # identical reads are the same VALUE only if no write to any input
    # lands between them (optimizer ops rewriting a persistable — e.g. a
    # decayed learning rate — would otherwise be conflated across the
    # update; the verifier's write-once rule doesn't cover persistables)
    write_pos: Dict[str, list] = {}
    for idx, op in enumerate(gb.ops):
        for n in op.output_arg_names:
            write_pos.setdefault(n, []).append(idx)

    def value_stable(names, i_canon, i_dup):
        return not any(i_canon < p <= i_dup
                       for n in names for p in write_pos.get(n, ()))

    seen = {}
    rename = {}
    new_ops = []
    removed = 0
    for op_idx, op in enumerate(gb.ops):
        # apply pending renames to THIS op's inputs before keying it
        for slot, names in op.inputs.items():
            op.inputs[slot] = [rename.get(n, n) for n in names]
        eligible = (
            op.type not in _IMPURE
            and op.attr("sub_block") is None
            and all(writers.get(n, 0) <= 1 for n in op.input_arg_names)
            and all(writers.get(n, 0) == 1 and not persistable(n)
                    for n in op.output_arg_names)
            and op.output_arg_names
        )
        if not eligible:
            new_ops.append(op)
            continue
        key = (
            op.type,
            _attr_key(op.attrs),
            tuple((slot, tuple(op.inputs[slot]))
                  for slot in sorted(op.inputs)),
            tuple(sorted(op.outputs)),
            tuple(len(op.outputs[slot]) for slot in sorted(op.outputs)),
        )
        entry = seen.get(key)
        if entry is None:
            seen[key] = (op, op_idx)
            new_ops.append(op)
            continue
        canon, canon_idx = entry
        if not value_stable(op.input_arg_names, canon_idx, op_idx):
            new_ops.append(op)  # an input was rewritten in between
            continue
        kept_outs = [n for n in op.output_arg_names if n in keep]
        if kept_outs:
            if len(op.output_arg_names) != 1:
                new_ops.append(op)  # partial-keep multi-output: leave it
                continue
            # keep the name, drop the recompute: one assign from the
            # canonical value
            src = canon.output_arg_names[
                op.output_arg_names.index(kept_outs[0])]
            op.type = "assign"
            op.inputs = {"X": [src]}
            op.outputs = {"Out": [kept_outs[0]]}
            op.attrs = {k: v for k, v in op.attrs.items()
                        if k in _KEY_IGNORED_ATTRS}
            new_ops.append(op)
            removed += 1
            ctx.count("cse", "ops_deduped")
            obs.TRANSPILE_OPS_REMOVED.inc(**{"pass": "cse"})
            continue
        for slot in op.outputs:
            c_names = canon.outputs.get(slot, [])
            for dup_name, c_name in zip(op.outputs[slot], c_names):
                rename[dup_name] = rename.get(c_name, c_name)
        removed += 1
        ctx.count("cse", "ops_deduped")
        obs.TRANSPILE_OPS_REMOVED.inc(**{"pass": "cse"})
    if removed:
        gb.ops[:] = new_ops
        rewrite_inputs(gb, rename)
        # renamed-away outputs may appear in later fetch-independent
        # declarations only; dead-var pruning (dce) sweeps them
        program._bump()
    return removed
