"""Rematerialization knob (absorbed from transpiler/memory_optimizer.py).

Reference: python/paddle/fluid/transpiler/memory_optimization_transpiler.py
— liveness analysis + in-place var reuse inside the C++ executor's Scope.
On TPU, XLA's buffer assignment already does liveness-based reuse and the
executor donates state buffers, so the reference's pass is structurally
unnecessary (in-graph dead code is the optimizing transpiler's ``dce``
pass). What IS worth controlling is rematerialization: trading recompute
FLOPs for activation memory in the fused fwd+bwd step. ``memory_optimize``
maps the reference API onto a ``jax.checkpoint`` policy applied to the
autodiff replay (framework/trace.py honors ``program._remat_policy``).

Not a registered pass: the policy changes the backward's numerics
(recomputed activations round identically, but the HLO differs), it is a
memory/VRAM knob the user opts into per program — orthogonal to the
parity-gated PADDLE_TPU_OPT pipeline.
"""
from __future__ import annotations

from typing import Optional

from ...framework.core import Program, default_main_program

__all__ = ["memory_optimize", "release_memory"]

_POLICIES = {
    # level 0 (reference default): keep matmul/conv outputs, recompute the
    # cheap elementwise chains — the sweet spot on HBM-bound TPUs.
    0: "dots_with_no_batch_dims_saveable",
    # level 1: save nothing, recompute everything (max memory savings)
    1: "nothing_saveable",
}


def memory_optimize(
    input_program: Optional[Program] = None,
    skip_opt_set=None,
    print_log: bool = False,
    level: int = 0,
):
    """Enable rematerialization for the program's backward pass."""
    if level not in _POLICIES:
        raise ValueError("level must be 0 or 1, got %r" % level)
    program = input_program if input_program is not None else default_main_program()
    program._remat_policy = _POLICIES[level]
    program._bump()  # invalidate compile caches
    if print_log:
        print("memory_optimize: remat policy = %s" % program._remat_policy)
    return program


def release_memory(input_program: Optional[Program] = None, skip_opt_set=None):
    """Reference parity (transpiler/memory_optimization_transpiler.py:
    release_memory). Buffer release is XLA's job; this is a no-op kept so
    reference scripts run unchanged."""
    return input_program
