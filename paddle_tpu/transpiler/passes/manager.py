"""Pass manager over the Program IR: the optimizing transpiler core.

The reference ships graph REWRITE passes as one-off transpilers
(inference_transpiler.py conv+bn fold, memory_optimization_transpiler.py);
PR-6 rebuilt the ANALYSIS layer (analysis/: shape/dtype inference lattice
+ lints) but nothing could act on its findings. This module is the
transform engine on top of it: small registered passes that mutate a
Program in place, orchestrated to a fixpoint, with the analyzer's
inference facts as the legality oracle (a pass may only rewrite what the
lattice PROVES safe — unknown degrades to "don't touch").

Contracts every pass must honor:

- **Parity.** An optimized program must produce outputs exactly equal to
  the original (the OpTest/example/randomized batteries pin this).
  Passes that cannot be bit-exact (conv+bn constant refactoring changes
  float rounding) are marked ``exact=False`` and only run at level 2.
- **RNG stability.** The tracer keys each op's PRNG stream on its block
  position, so deleting/reordering ops would silently redraw every
  dropout mask downstream. Before the first mutation the manager stamps
  every op with ``__rng_idx__`` (its pre-optimization position);
  framework/trace.py prefers the stamp over the live index, so streams
  survive any structural rewrite.
- **Keep-set.** Fetch targets, feeds, vars read by sub-blocks, and loop
  carries keep their names: a pass may rewrite how a kept name is
  computed but never remove or rename it.
- **Idempotence.** Running the pipeline on its own output changes
  nothing (the randomized battery asserts optimize(optimize(p)) ==
  optimize(p) structurally).

Levels (``PADDLE_TPU_OPT`` / explicit API):

- 0: off;
- 1: bit-exact structural passes — constant folding, CSE, fc fusion,
  elementwise+activation fusion, dead-op/dead-var elimination;
- 2: level 1 + conv+bn folding (inference graphs, tolerance-parity) and
  feed bucketization (stamps pow2-bucket metadata the Executor/Predictor
  apply at the feed boundary);
- 3: level 2 + int8 post-training quantization (transpiler/passes/
  quantize.py) — only rewrites anything when the context carries a
  ``quant.CalibrationTable`` (``optimize_program(..., calib=table)`` /
  ``save_inference_model(quantize=table)``); the env knob alone never
  changes numerics.
"""
from __future__ import annotations

import os
import time
from typing import Callable, Dict, List, Optional, Sequence, Set, Tuple

from ... import observability as obs
from ...framework.core import Program
from ...framework.scope import Scope

__all__ = [
    "PassContext", "PassManager", "register_pass", "optimize_program",
    "opt_level_from_env", "PASSES", "RNG_IDX_ATTR",
]

# the op attr carrying an op's PRE-optimization block position: the
# tracer's per-op PRNG key derivation reads it (framework/trace.py), so
# removing/reordering ops cannot perturb any stochastic op's stream
RNG_IDX_ATTR = "__rng_idx__"

# ops the manager must never touch: executor plumbing + the autodiff
# pseudo-op (its replay set is positional; passes treat it as a barrier
# only DCE understands)
PLUMBING_OPS = {"feed", "fetch", "read"}


def opt_level_from_env(default: int = 0) -> int:
    """PADDLE_TPU_OPT=0|1|2|3 (malformed values fall back, never crash)."""
    raw = os.environ.get("PADDLE_TPU_OPT")
    if raw is None:
        return default
    try:
        lvl = int(raw)
    except ValueError:
        return default
    return min(max(lvl, 0), 3)


class _Pass:
    __slots__ = ("name", "fn", "level", "exact", "needs_scope")

    def __init__(self, name, fn, level, exact, needs_scope):
        self.name = name
        self.fn = fn
        self.level = level
        self.exact = exact
        self.needs_scope = needs_scope


# ordered: folding exposes CSE opportunities, fusion runs on the
# deduplicated graph, DCE sweeps the leftovers, bucketize stamps last
PASSES: "Dict[str, _Pass]" = {}
PASS_ORDER: List[str] = []


def register_pass(name: str, level: int = 1, exact: bool = True,
                  needs_scope: bool = False):
    """``@register_pass("cse")`` — fn(ctx) -> int (number of rewrites
    applied; 0 = fixpoint for this pass)."""

    def deco(fn):
        if name in PASSES:
            raise ValueError("duplicate pass %r" % name)
        PASSES[name] = _Pass(name, fn, level, exact, needs_scope)
        PASS_ORDER.append(name)
        return fn

    return deco


class PassContext:
    """Shared state for one optimization run over one Program."""

    def __init__(self, program: Program, scope: Optional[Scope],
                 feed_names: Sequence[str], fetch_names: Sequence[str],
                 level: int, calib=None):
        self.program = program
        self.scope = scope
        self.feed_names = set(feed_names)
        self.fetch_names = list(fetch_names)
        self.level = level
        # quant.CalibrationTable (or None): the level-3 quantize pass
        # only rewrites when calibration ranges are present
        self.calib = calib
        self.stats: Dict[str, Dict] = {}
        self.notes: List[str] = []
        self._inference = None
        self._inference_version = None

    # -- legality oracle --------------------------------------------------
    @property
    def inference(self):
        """The analyzer's whole-program (shape, dtype) facts, recomputed
        lazily whenever a pass mutated the program since the last look —
        a stale lattice must never prove a rewrite legal."""
        if (self._inference is None
                or self._inference_version != self.program._version):
            from ...analysis.infer import infer_program

            self._inference = infer_program(
                self.program, feed_names=tuple(self.feed_names),
                attach=False)
            self._inference_version = self.program._version
        return self._inference

    # -- graph views (recomputed per call: passes mutate freely) ----------
    def keep_names(self) -> Set[str]:
        """Names whose computed VALUE must stay addressable by that name:
        fetch targets, feeds, everything a sub-block reads (closure), and
        loop carries. Persistable vars are handled separately (their
        writes are liveness roots, but a pass may still rewire reads)."""
        keep = set(self.fetch_names) | set(self.feed_names)
        for block in self.program.blocks[1:]:
            for op in block.ops:
                keep.update(op.input_arg_names)
        for op in self.program.global_block().ops:
            if op.attr("sub_block") is not None:
                keep.update(op.attr("carried_names") or ())
                keep.update(op.input_arg_names)
                keep.update(op.output_arg_names)
        return keep

    def reader_counts(self) -> Dict[str, int]:
        """name -> number of reading ops across ALL blocks."""
        readers: Dict[str, int] = {}
        for block in self.program.blocks:
            for op in block.ops:
                for name in op.input_arg_names:
                    readers[name] = readers.get(name, 0) + 1
        for name in self.fetch_names:
            readers[name] = readers.get(name, 0) + 1
        return readers

    def writer_counts(self) -> Dict[str, int]:
        writers: Dict[str, int] = {}
        for block in self.program.blocks:
            for op in block.ops:
                for name in op.output_arg_names:
                    writers[name] = writers.get(name, 0) + 1
        return writers

    # -- bookkeeping ------------------------------------------------------
    def note(self, msg: str):
        self.notes.append(msg)

    def count(self, pass_name: str, key: str, n: int = 1):
        self.stats.setdefault(pass_name, {})[key] = (
            self.stats.get(pass_name, {}).get(key, 0) + n)


def stamp_rng_indices(program: Program) -> None:
    """Pin every op's pre-optimization position as ``__rng_idx__`` so the
    tracer's RNG keys survive structural rewrites. setdefault keeps the
    stamp stable across repeated optimization runs (idempotence)."""
    for block in program.blocks:
        for idx, op in enumerate(block.ops):
            op.attrs.setdefault(RNG_IDX_ATTR, idx)


def rewrite_inputs(block, rename: Dict[str, str], start: int = 0):
    """Rename op input references in ``block.ops[start:]``."""
    if not rename:
        return
    for op in block.ops[start:]:
        for slot, names in op.inputs.items():
            op.inputs[slot] = [rename.get(n, n) for n in names]


def prune_dead_vars(program: Program, keep: Set[str]) -> int:
    """Drop var DECLARATIONS nothing references: not persistable, not
    data, not in the keep set, and named by no op in any block. Purely a
    size/serialization win — values never existed for these names."""
    referenced: Set[str] = set(keep)
    for block in program.blocks:
        for op in block.ops:
            referenced.update(op.input_arg_names)
            referenced.update(op.output_arg_names)
            if op.type == "autodiff":
                referenced.add(op.attr("loss_name"))
                referenced.update(op.attr("param_names") or ())
    removed = 0
    for block in program.blocks:
        for name in list(block.vars):
            var = block.vars[name]
            if (name not in referenced and not var.persistable
                    and not var.is_data):
                del block.vars[name]
                removed += 1
    if removed:
        program._bump()
    return removed


class PassManager:
    """Runs the registered passes (filtered by level) to a fixpoint."""

    _MAX_ROUNDS = 5

    def __init__(self, level: int = 1,
                 passes: Optional[Sequence[str]] = None):
        self.level = int(level)
        if passes is None:
            names = [n for n in PASS_ORDER
                     if PASSES[n].level <= self.level]
        else:
            unknown = [n for n in passes if n not in PASSES]
            if unknown:
                raise ValueError(
                    "unknown passes %s (registered: %s)"
                    % (unknown, sorted(PASSES)))
            names = list(passes)
        self.pass_names = names

    def run(self, program: Program, scope: Optional[Scope] = None,
            feed_names: Sequence[str] = (),
            fetch_names: Sequence[str] = (), calib=None) -> PassContext:
        """Mutates ``program`` in place; returns the PassContext with
        per-pass stats. Use :func:`optimize_program` for the cloning
        front door."""
        ctx = PassContext(program, scope, feed_names, fetch_names,
                          self.level, calib=calib)
        if self.level <= 0 or not self.pass_names:
            return ctx
        stamp_rng_indices(program)
        for _round in range(self._MAX_ROUNDS):
            changed = 0
            for name in self.pass_names:
                p = PASSES[name]
                if p.needs_scope and ctx.scope is None:
                    continue
                t0 = time.perf_counter()
                n = p.fn(ctx)
                ms = (time.perf_counter() - t0) * 1e3
                st = ctx.stats.setdefault(name, {})
                st["ms"] = st.get("ms", 0.0) + ms
                st["applied"] = st.get("applied", 0) + int(n or 0)
                obs.TRANSPILE_PASS_MS.observe(ms, **{"pass": name})
                changed += int(n or 0)
            if not changed:
                break
        return ctx


def optimize_program(program: Program, scope: Optional[Scope] = None,
                     level: int = 1, feed_names: Sequence[str] = (),
                     fetch_names: Sequence[str] = (),
                     passes: Optional[Sequence[str]] = None,
                     calib=None,
                     ) -> Tuple[Program, PassContext]:
    """THE front door: returns an optimized CLONE of ``program`` (the
    original is untouched, so optimized and original executables coexist
    — they fingerprint differently, giving them distinct AOT-cache
    keys) plus the PassContext with per-pass stats.

    ``scope`` is where constant folding materializes evaluated results
    as parameters and where conv+bn folding reads batch-norm statistics;
    without one, scope-dependent passes skip. Fold freezes the CURRENT
    scope values of unwritten persistables into the optimized program —
    re-optimize after mutating such state out-of-band (the same contract
    as the reference InferenceTranspiler).

    ``calib`` (a ``quant.CalibrationTable``) arms the level-3 quantize
    pass; without it level 3 behaves exactly like level 2.
    """
    from . import fold, cse, fusion, dce, quantize, bucketize  # noqa: F401 — register

    optimized = program.clone()
    mgr = PassManager(level=level, passes=passes)
    ctx = mgr.run(optimized, scope=scope, feed_names=feed_names,
                  fetch_names=fetch_names, calib=calib)
    # tier marker for Engine.meta / tools/aot_cache_ls.py: which
    # transpile tier produced this clone (process-local; the quantize
    # and bucketize stamps additionally ride the serialized JSON)
    optimized._opt_level = int(level)
    return optimized, ctx
