"""Optimizing transpiler: a parity-gated pass manager over the Program
IR (reference: the one-off transpilers under python/paddle/fluid/
transpiler/, rebuilt as a composable pipeline on the PR-6 analyzer).

    from paddle_tpu.transpiler.passes import optimize_program
    optimized, ctx = optimize_program(program, scope=scope, level=1,
                                      fetch_names=[loss.name])

or implicitly: ``PADDLE_TPU_OPT=1|2`` makes Executor/Predictor optimize
every program they compile (keyed into the AOT cache by the optimized
program's own content fingerprint, so original and optimized
executables coexist).

Passes (manager.py has the level/parity contract):
level 1 — constant_fold, cse, fuse_fc, fuse_elemwise_act, dce (bit-exact);
level 2 — + conv_bn_fold (tolerance-parity), bucketize (pow2 feed
buckets, bit-exact on the real rows);
level 3 — + quantize (int8 post-training quantization; only rewrites
when ``optimize_program(..., calib=CalibrationTable)`` supplies
calibration ranges — see paddle_tpu/quant/).
"""
from .manager import (  # noqa: F401
    PASSES, PassContext, PassManager, RNG_IDX_ATTR, opt_level_from_env,
    optimize_program, register_pass,
)
# registration order = pass order within a manager round: quantize runs
# after the fusion passes (fc chains arrive fused) and before bucketize
# (the stamp must prove row-wise THROUGH quantized_matmul)
from . import fold, cse, dce, fusion, quantize, bucketize  # noqa: F401 — register
from .bucketize import next_pow2  # noqa: F401
from .fusion import fold_conv_bn  # noqa: F401

__all__ = [
    "PASSES", "PassContext", "PassManager", "RNG_IDX_ATTR",
    "opt_level_from_env", "optimize_program", "register_pass",
    "next_pow2", "fold_conv_bn",
]
