"""Build-time feed bucketization: turn feed-signature churn into pow2
buckets.

The recompile-risk lint (PR-6) flags dynamic-batch feeds because every
distinct batch size compiles — and AOT-caches — its own executable; the
PR-2 serving path already answers that at runtime by padding batches to
power-of-two buckets. This pass moves the answer to BUILD time for any
program: it proves, with the inference lattice as the legality oracle,
that every computation downstream of the dynamic feeds is *row-wise*
(output row i depends only on input row i — padding extra rows cannot
perturb real rows), then stamps the program with bucketization metadata
(``program._bucketize``, serialized in the program JSON). The
Executor/Predictor honor the stamp at the feed boundary: feeds pad with
zero rows up to the next power of two before signature derivation, and
batch-carrying fetches slice back to the real row count after execution
— so a workload feeding batches 3,5,6,7 compiles ONE bucket-8
executable instead of four.

Parity: real rows are MATHEMATICALLY unchanged (row-wise is proved, not
assumed), and on small graphs bitwise-identical too — but XLA's CPU
GEMM may pick a different reduction order for a different batch
dimension, so large matmul chains can drift by reduction-order ulps
(measured ≤3e-6 max-abs on the 200-wide mnist MLP, batch 9-in-16;
tools/bench_transpile.py reports the observed bound per run). That is
the same numerical class as running the identical rows at a different
batch size by hand; the parity gates compare padded-path outputs at
ulp tolerance and everything else exactly.

XLA's static-shape contract is why the pad/slice pair lives at the
executor boundary rather than as in-graph ops: an in-graph slice back
to the true row count would need a dynamic output shape, which TPU
compilation rejects. The stamp IS the in-graph artifact — it rides the
serialized program, so an exported model buckets wherever it is served.

Programs that mix rows anywhere on the dynamic-feed cone (batch-mean
losses, training-mode batch_norm, any ``autodiff``) are left unstamped,
with a note saying which op broke legality.
"""
from __future__ import annotations

from typing import Optional, Set

from .manager import register_pass

# elementwise / per-row op families (never mix rows along axis 0)
_ELEMWISE_BINARY = {
    "elementwise_add", "elementwise_sub", "elementwise_mul",
    "elementwise_div", "elementwise_max", "elementwise_min",
    "elementwise_pow", "elementwise_mod", "fused_elemwise_activation",
}
_ELEMWISE_UNARY = {
    "sigmoid", "logsigmoid", "exp", "relu", "tanh", "tanh_shrink",
    "sqrt", "abs", "ceil", "floor", "cos", "sin", "round", "reciprocal",
    "square", "softplus", "softsign", "log", "sign", "relu6",
    "leaky_relu", "elu", "brelu", "soft_relu", "pow", "stanh",
    "hard_sigmoid", "swish", "thresholded_relu", "hard_shrink",
    "softshrink", "scale", "clip", "label_smooth", "assign", "cast",
    "fill_zeros_like", "logical_not", "isfinite",
}
# per-row losses: every output row is a function of the matching input row
_ROW_LOSSES = {
    "cross_entropy", "softmax_with_cross_entropy",
    "sigmoid_cross_entropy_with_logits", "square_error_cost", "log_loss",
    "smooth_l1_loss", "huber_loss", "hinge_loss",
}


def next_pow2(n: int) -> int:
    b = 1
    while b < n:
        b *= 2
    return b


def _rank(ctx, name: str) -> Optional[int]:
    s = ctx.inference.shape(name)
    return None if s is None else len(s)


def _binary_pad_safe(ctx, op, carrying: Set[str], x_name: str,
                     y_name: str, axis) -> bool:
    """A binary op stays well-formed when the CARRYING operand's axis 0
    grows by padding: either both operands carry (padded together, equal
    known ranks), or the non-carrying one provably never aligns with
    axis 0 — a strict-smaller-rank span placed at axis > 0, or an equal-
    rank operand with dim0 == 1. A static batch-sized operand (N, d)
    against a dynamic feed would shape-error at the padded size."""
    xc, yc = x_name in carrying, y_name in carrying
    xs = ctx.inference.shape(x_name)
    ys = ctx.inference.shape(y_name)
    if xc and yc:
        return (xs is not None and ys is not None
                and len(xs) == len(ys))
    if yc and not xc:
        return False  # Y's axis 0 maps into a span of X, not X's rows
    # X carries, Y is batch-free: Y must never span axis 0
    if xs is None or ys is None:
        return False
    if len(ys) < len(xs):
        a = axis if isinstance(axis, int) and axis != -1 \
            else len(xs) - len(ys)
        return a > 0
    return len(ys) == len(xs) and ys[0] == 1


def _carrying_outputs(ctx, op, carrying: Set[str]) -> Optional[Set[str]]:
    """Which outputs of ``op`` carry the feed batch axis (axis 0), given
    the carrying inputs — or None when the op may MIX rows (illegal to
    pad). Unknown facts degrade to None: the oracle must prove safety,
    never assume it."""
    t = op.type
    ins = set(op.input_arg_names)
    outs = set(op.output_arg_names)
    c_ins = ins & carrying

    if t in _ELEMWISE_BINARY:
        if not _binary_pad_safe(ctx, op, carrying, op.input("X")[0],
                                op.input("Y")[0], op.attr("axis", -1)):
            return None
        return outs
    if t in _ROW_LOSSES:
        # loss inputs are batch-aligned rows: a static-shaped label
        # against a padded prediction would shape-error
        return outs if all(n in carrying for n in ins) else None
    if t in _ELEMWISE_UNARY:
        return outs
    if t in ("softmax", "log_softmax"):
        r = _rank(ctx, op.input("X")[0])
        return outs if r is not None and r >= 2 else None
    if t == "dropout":
        # test mode is a deterministic passthrough; train mode draws a
        # batch-shaped mask whose bits depend on the padded shape
        return outs if op.attr("is_test", False) else None
    if t == "batch_norm":
        if not op.attr("is_test", False):
            return None
        # only Y is batch-shaped; the (C,)-shaped stat outputs must NOT
        # be marked carrying (a stamped stat fetch would get row-sliced)
        return set(op.output("Y"))
    if t == "layer_norm":
        return outs if int(op.attr("begin_norm_axis", 1)) >= 1 else None
    if t in ("mul", "fused_fc", "quantized_matmul"):
        # quantized_matmul is row-wise exactly like fused_fc: the
        # per-tensor activation scale is an attr (pad rows quantize to
        # zero codes, contributing nothing), the int8 weight/bias are
        # batch-free state
        if op.input("Y")[0] in carrying or (
                op.input("Bias") and op.input("Bias")[0] in carrying):
            return None
        if op.input("X")[0] not in carrying:
            return None
        if int(op.attr("x_num_col_dims", 1)) < 1:
            return None
        if t in ("fused_fc", "quantized_matmul") and op.input("Bias"):
            # bias span must not touch the (growing) batch axis
            out_s = ctx.inference.shape(op.output("Out")[0])
            b_s = ctx.inference.shape(op.input("Bias")[0])
            if out_s is None or b_s is None:
                return None
            axis = op.attr("axis", -1)
            if len(b_s) < len(out_s):
                a = axis if isinstance(axis, int) and axis != -1 \
                    else len(out_s) - len(b_s)
                if a <= 0:
                    return None
            elif not (len(b_s) == len(out_s) and b_s[0] == 1):
                return None
        if t in ("fused_fc", "quantized_matmul") \
                and op.attr("kind", "mul") == "matmul":
            # the fusion pass only emits non-transposed matmuls, where
            # axis 0 stays the row axis at any known rank
            if _rank(ctx, op.input("X")[0]) is None:
                return None
        return outs
    if t == "matmul":
        if op.input("Y")[0] in carrying or op.input("X")[0] not in carrying:
            return None
        r = _rank(ctx, op.input("X")[0])
        if r is None:
            return None
        if r == 2 and op.attr("transpose_X", False):
            return None  # transpose would move batch into the contraction
        return outs
    if t in ("lookup_table", "one_hot"):
        first = op.input("Ids" if t == "lookup_table" else "X")
        if t == "lookup_table" and op.input("W")[0] in carrying:
            return None
        return outs if first and first[0] in carrying else None
    if t == "concat":
        axis = op.attr("axis", 0)
        if not isinstance(axis, int) or axis == 0:
            return None
        if axis < 0:
            r = _rank(ctx, op.input("X")[0])
            if r is None or axis % r == 0:
                return None
        return outs if all(n in carrying for n in op.input("X")) else None
    if t in ("reduce_sum", "reduce_mean", "reduce_max", "reduce_min",
             "reduce_prod"):
        if op.attr("reduce_all", False):
            return None
        r = _rank(ctx, op.input("X")[0])
        if r is None:
            return None
        dims = op.attr("dim", [0])
        dims = dims if isinstance(dims, (list, tuple)) else [dims]
        if any((int(d) % r) == 0 for d in dims):
            return None
        return outs
    if t == "reshape":
        shape = op.attr("shape")
        if not shape or shape[0] not in (-1, 0):
            return None
        if any(int(d) <= 0 for d in shape[1:]):
            return None
        s_in = ctx.inference.shape(op.input("X")[0])
        if s_in is None or any(d is None for d in s_in[1:]):
            return None
        import math as _math

        if _math.prod(int(d) for d in shape[1:]) != _math.prod(
                int(d) for d in s_in[1:]):
            return None  # rows would regroup across the batch axis
        return outs
    if t == "transpose":
        perm = op.attr("axis") or op.attr("perm")
        return outs if perm and int(perm[0]) == 0 else None
    if t in ("unsqueeze", "squeeze"):
        axes = op.attr("axes") or []
        r = _rank(ctx, op.input("X")[0])
        if r is None or any((int(a) % (r + (1 if t == "unsqueeze" else 0)))
                            == 0 for a in axes):
            return None
        return outs
    if t == "stack":
        return (outs if int(op.attr("axis", 0)) > 0
                and all(n in carrying for n in op.input("X")) else None)
    if t == "split":
        axis = op.attr("axis", op.attr("dim", 0))
        return outs if isinstance(axis, int) and axis > 0 else None
    if t == "slice":
        axes = op.attr("axes") or []
        return None if any(int(a) == 0 for a in axes) else outs
    if t == "top_k":
        r = _rank(ctx, op.input("X")[0])
        return outs if r is not None and r >= 2 else None
    if t == "gather":
        # out rows follow the Index rows; X must be batch-free state
        if op.input("Index") and op.input("Index")[0] in carrying \
                and op.input("X")[0] not in carrying:
            return outs
        return None
    return None  # unknown op: cannot prove row independence


@register_pass("bucketize", level=2, exact=True)
def bucketize(ctx) -> int:
    """Stamp ``program._bucketize`` when legal (see module docstring).
    Returns 1 the first time the stamp lands, 0 when already stamped or
    illegal — re-running never restamps differently (idempotent)."""
    program = ctx.program
    gb = program.global_block()

    dyn_feeds = sorted(
        name for name, var in gb.vars.items()
        if var.is_data and tuple(var.shape or ())
        and var.shape[0] < 0
        and all(d >= 0 for d in var.shape[1:]))
    if not dyn_feeds:
        return 0
    if any(op.type == "autodiff" for b in program.blocks for op in b.ops):
        ctx.note("bucketize: program trains (autodiff present) — "
                 "gradients mix rows, not stamped")
        return 0
    if len(program.blocks) > 1:
        # control flow could smuggle a carrying var into a sub-block
        # where this straight-line analysis can't follow it
        carried_into_sub = set()
        for block in program.blocks[1:]:
            for op in block.ops:
                carried_into_sub.update(op.input_arg_names)
    else:
        carried_into_sub = set()

    carrying: Set[str] = set(dyn_feeds)
    for op in gb.ops:
        if op.type in ("feed", "fetch", "read"):
            continue
        ins = set(op.input_arg_names)
        if not (ins & carrying):
            continue
        outs = _carrying_outputs(ctx, op, carrying)
        if outs is None:
            ctx.note("bucketize: op %r mixes rows (or cannot be proven "
                     "row-wise) — not stamped" % op.type)
            return 0
        for name in op.output_arg_names:
            var = gb._find_var_recursive(name)
            if var is not None and var.persistable:
                ctx.note("bucketize: %r writes persistable %r from a "
                         "batch-carrying input — not stamped"
                         % (op.type, name))
                return 0
        carrying |= outs
    if carrying & carried_into_sub:
        ctx.note("bucketize: batch-carrying var read by a sub-block — "
                 "not stamped")
        return 0

    stamp = {
        "feeds": dyn_feeds,
        "fetches": sorted(n for n in ctx.fetch_names if n in carrying),
    }
    if getattr(program, "_bucketize", None) == stamp:
        return 0
    program._bucketize = stamp
    program._bump()
    ctx.count("bucketize", "feeds_bucketized", len(dyn_feeds))
    return 1
