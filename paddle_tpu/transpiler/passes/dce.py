"""Dead-op / dead-var elimination: the PR-6 dead-code LINT as a
transform.

The liveness analysis is literally the lint's (analysis/lints.py:
backward_liveness — one shared function, so the finding and the fix can
never disagree): ops unreachable backward from any fetch target or
persistable write are deleted, then var declarations nothing references
are swept. Autodiff replay stays correct by the liveness contract — a
dead op is outside every loss's forward cone, so removing it from the
vjp replay prefix changes no gradient (and the ``__rng_idx__`` stamps
keep every surviving stochastic op's PRNG stream identical)."""
from __future__ import annotations

from ... import observability as obs
from .manager import prune_dead_vars, register_pass


@register_pass("dce", level=1, exact=True)
def dce(ctx) -> int:
    from ...analysis.lints import backward_liveness

    program = ctx.program
    gb = program.global_block()
    # fetch names root liveness; feeds are inputs, not roots — but an
    # explicitly kept name must survive even if nothing reads it
    anchored, dead_ops, _live = backward_liveness(program,
                                                  ctx.fetch_names)
    if not anchored:
        return 0
    keep = ctx.keep_names()
    dead_idx = {idx for idx, op in dead_ops
                if not (set(op.output_arg_names) & keep)}
    removed = 0
    if dead_idx:
        gb.ops[:] = [op for i, op in enumerate(gb.ops)
                     if i not in dead_idx]
        removed = len(dead_idx)
        program._bump()
        ctx.count("dce", "ops_removed", removed)
        obs.TRANSPILE_OPS_REMOVED.inc(removed, **{"pass": "dce"})
    swept = prune_dead_vars(program, keep)
    if swept:
        ctx.count("dce", "vars_removed", swept)
    # var sweeps alone must not extend the fixpoint loop (they cannot
    # unlock further rewrites)
    return removed
