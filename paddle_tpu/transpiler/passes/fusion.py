"""Fusion passes.

- ``fuse_fc`` (level 1, exact): ``mul``/``matmul`` -> ``elementwise_add``
  (-> activation) chains — what every ``layers.fc`` call emits — become
  ONE ``fused_fc`` op. The fused kernel (ops/math.py) composes the exact
  same jnp calls in the same order, so outputs and gradients are
  bit-identical; the win is transpile-side: fewer ops to trace, smaller
  HLO to compile, one op where three were.
- ``fuse_elemwise_act`` (level 1, exact): leftover
  ``elementwise_add|mul -> relu`` pairs become the reference's existing
  ``fused_elemwise_activation`` op.
- ``conv_bn_fold`` (level 2, tolerance-parity): the InferenceTranspiler
  conv+batch_norm fold generalized into a pass. Unlike the legacy
  in-place transpiler it does NOT mutate the original parameters — the
  folded filter/bias are materialized under fresh ``.bnfold`` names, so
  the unoptimized program (sharing the same Scope) keeps computing the
  original values and the two executables coexist.

Fusion is skipped under AMP: the tracer casts ``mul`` to bf16 but the
bias add stays fp32 at O1, so a fused kernel could not reproduce the
unfused rounding.
"""
from __future__ import annotations

import numpy as np

from ... import observability as obs
from .manager import RNG_IDX_ATTR, register_pass

# activations the fused_fc kernel reproduces exactly (ops/math.py _FC_ACTS)
FC_ACTS = ("relu", "tanh", "sigmoid", "relu6", "softplus", "leaky_relu",
           "swish", "square", "abs", "exp")


def _single_reader(ctx, readers, keep, writers, name: str) -> bool:
    """An intermediate a fusion may erase: single-written, read only by
    the next pattern op, not a kept name, and NOT persistable — erasing
    a persistable's producing op would silently freeze its scope value
    (persistable writes are liveness roots; cse/fold guard likewise)."""
    var = ctx.program.global_block()._find_var_recursive(name)
    return (readers.get(name, 0) == 1 and name not in keep
            and writers.get(name, 0) == 1
            and not (var is not None and var.persistable))


def _owned(val):
    """Scope values the executor may DONATE must be XLA-owned buffers,
    never numpy-owned memory (checkpoint/manager.py device_owned_tree —
    the PR-10 heap-corruption lesson)."""
    from ...checkpoint.manager import device_owned_tree

    return device_owned_tree({"v": val})["v"]


@register_pass("fuse_fc", level=1, exact=True)
def fuse_fc(ctx) -> int:
    program = ctx.program
    if getattr(program, "_amp", False):
        return 0
    gb = program.global_block()
    readers = ctx.reader_counts()
    writers = ctx.writer_counts()
    keep = ctx.keep_names()

    def batch_free_def(name: str, before: int) -> bool:
        """Bias must be usable at the matmul's position: persistable,
        data, or produced by an earlier op."""
        var = gb._find_var_recursive(name)
        if var is not None and (var.persistable or var.is_data):
            return True
        if name in ctx.feed_names:
            return True
        for idx, op in enumerate(gb.ops[:before]):
            if name in op.output_arg_names:
                return True
        return False

    fused = 0
    i = 0
    while i < len(gb.ops):
        m = gb.ops[i]
        if m.type not in ("mul", "matmul"):
            i += 1
            continue
        if m.type == "matmul" and (
                m.attr("transpose_X", False) or m.attr("transpose_Y", False)
                or m.attr("alpha", 1.0) != 1.0):
            i += 1
            continue
        if len(m.input("X")) != 1 or len(m.input("Y")) != 1 \
                or len(m.output("Out")) != 1:
            i += 1
            continue
        m_out = m.output("Out")[0]
        add = gb.ops[i + 1] if i + 1 < len(gb.ops) else None
        if (add is None or add.type != "elementwise_add"
                or add.input("X") != [m_out]
                or len(add.input("Y")) != 1
                or not _single_reader(ctx, readers, keep, writers, m_out)
                or not batch_free_def(add.input("Y")[0], i)):
            i += 1
            continue
        add_out = add.output("Out")[0]
        act = gb.ops[i + 2] if i + 2 < len(gb.ops) else None
        act_type = ""
        final_out = add_out
        drop = 2
        if (act is not None and act.type in FC_ACTS
                and act.input("X") == [add_out] and not act.attrs.keys()
                - {RNG_IDX_ATTR}
                and _single_reader(ctx, readers, keep, writers, add_out)):
            act_type = act.type
            final_out = act.output("Out")[0]
            drop = 3
        attrs = {
            "kind": m.type,
            "x_num_col_dims": m.attr("x_num_col_dims", 1),
            "y_num_col_dims": m.attr("y_num_col_dims", 1),
            "axis": add.attr("axis", -1),
            "act": act_type,
        }
        if RNG_IDX_ATTR in m.attrs:
            attrs[RNG_IDX_ATTR] = m.attrs[RNG_IDX_ATTR]
        from ...framework.core import Operator

        fused_op = Operator(
            gb, type="fused_fc",
            inputs={"X": m.input("X"), "Y": m.input("Y"),
                    "Bias": add.input("Y")},
            outputs={"Out": [final_out]}, attrs=attrs)
        gb.ops[i:i + drop] = [fused_op]
        gb._note_writes(fused_op)
        for name in (m_out, add_out):
            if name != final_out and name in gb.vars \
                    and not gb.vars[name].persistable:
                del gb.vars[name]
        program._bump()
        fused += 1
        ctx.count("fuse_fc", "ops_fused", drop)
        obs.TRANSPILE_OPS_FUSED.inc(drop, **{"pass": "fuse_fc"})
        i += 1
    return fused


@register_pass("fuse_elemwise_act", level=1, exact=True)
def fuse_elemwise_act(ctx) -> int:
    """Adjacent elementwise_add|mul -> relu pairs into the existing
    ``fused_elemwise_activation`` op (functor_list=["relu", binary]).
    The kernel composes the identical jnp calls, so this is exact."""
    program = ctx.program
    if getattr(program, "_amp", False):
        return 0
    gb = program.global_block()
    readers = ctx.reader_counts()
    writers = ctx.writer_counts()
    keep = ctx.keep_names()

    fused = 0
    i = 0
    while i < len(gb.ops):
        b = gb.ops[i]
        if b.type not in ("elementwise_add", "elementwise_mul") \
                or len(b.output("Out")) != 1:
            i += 1
            continue
        b_out = b.output("Out")[0]
        a = gb.ops[i + 1] if i + 1 < len(gb.ops) else None
        if (a is None or a.type != "relu" or a.input("X") != [b_out]
                or not _single_reader(ctx, readers, keep, writers, b_out)):
            i += 1
            continue
        from ...framework.core import Operator

        attrs = {"functor_list": ["relu", b.type],
                 "axis": b.attr("axis", -1), "scale": 1.0}
        if RNG_IDX_ATTR in b.attrs:
            attrs[RNG_IDX_ATTR] = b.attrs[RNG_IDX_ATTR]
        fused_op = Operator(
            gb, type="fused_elemwise_activation",
            inputs={"X": b.input("X"), "Y": b.input("Y")},
            outputs={"Out": [a.output("Out")[0]]}, attrs=attrs)
        gb.ops[i:i + 2] = [fused_op]
        gb._note_writes(fused_op)
        if b_out in gb.vars and not gb.vars[b_out].persistable:
            del gb.vars[b_out]
        program._bump()
        fused += 1
        ctx.count("fuse_elemwise_act", "ops_fused", 2)
        obs.TRANSPILE_OPS_FUSED.inc(2, **{"pass": "fuse_elemwise_act"})
        i += 1
    return fused


# -- conv + batch_norm folding --------------------------------------------


def fold_conv_bn(program, scope, keep=(), require_is_test: bool = True,
                 in_place_params: bool = False) -> int:
    """Fold conv2d (+bias add) + batch_norm pairs: the conv filter is
    pre-scaled by the bn's gamma/sqrt(var+eps) and the bn collapses into
    one bias add. Returns the number of bn ops folded.

    ``in_place_params=True`` is the legacy InferenceTranspiler contract:
    the existing filter/bias values are OVERWRITTEN in the Scope (the
    original program's numbers change with them). The pass-manager mode
    (False) materializes the folded values under fresh ``.bnfold``
    names, leaving the original parameters untouched.

    ``require_is_test`` gates folding to inference-mode bn ops — a
    training-mode bn computes batch statistics and updates running
    state, which no constant fold can reproduce. The legacy shim keeps
    its historical behavior (no gate; callers fold for_test clones).
    """
    block = program.global_block()
    keep = set(keep)

    readers = {}
    for op in block.ops:
        for name in op.input_arg_names:
            readers[name] = readers.get(name, 0) + 1

    def _bn_constants(bn):
        scale = np.asarray(scope.find_var(bn.input("Scale")[0]))
        beta = np.asarray(scope.find_var(bn.input("Bias")[0]))
        mean = np.asarray(scope.find_var(bn.input("Mean")[0]))
        var = np.asarray(scope.find_var(bn.input("Variance")[0]))
        k = scale / np.sqrt(var + bn.attr("epsilon", 1e-5))
        return k, beta, mean

    def _fresh(name: str) -> str:
        cand = name
        while block._find_var_recursive(cand) is not None:
            cand += "_"
        return cand

    folded = 0
    i = 0
    while i < len(block.ops):
        conv = block.ops[i]
        if conv.type != "conv2d":
            i += 1
            continue
        conv_out = conv.output("Output")[0]
        w_name = conv.input("Filter")[0]

        # pattern A: conv2d -> batch_norm
        # pattern B: conv2d -> elementwise_add(bias) -> batch_norm
        #            (layers.conv2d with bias_attr emits the add)
        nxt = block.ops[i + 1] if i + 1 < len(block.ops) else None
        nxt2 = block.ops[i + 2] if i + 2 < len(block.ops) else None
        if (
            nxt is not None
            and nxt.type == "batch_norm"
            and nxt.input("X") == [conv_out]
            and readers.get(conv_out, 0) == 1
            and conv_out not in keep
        ):
            bn, bn_idx, bias_name = nxt, i + 1, None
        elif (
            nxt is not None
            and nxt2 is not None
            and nxt.type == "elementwise_add"
            and nxt.input("X") == [conv_out]
            and nxt2.type == "batch_norm"
            and nxt2.input("X") == nxt.output("Out")
            and readers.get(conv_out, 0) == 1
            and readers.get(nxt.output("Out")[0], 0) == 1
            and conv_out not in keep
            and nxt.output("Out")[0] not in keep
        ):
            bn, bn_idx, bias_name = nxt2, i + 2, nxt.input("Y")[0]
        else:
            i += 1
            continue

        if require_is_test and not bn.attr("is_test", False):
            i = bn_idx + 1
            continue
        wvar = block._find_var_recursive(w_name)
        if wvar is not None and not wvar.persistable:
            # the Filter is a derived in-graph variable, not a stored
            # parameter (e.g. the ResNet space-to-depth stem transforms
            # its canonical 7x7 weight in-graph) — leave this BN unfused
            i = bn_idx + 1
            continue
        wval = scope.find_var(w_name)
        if wval is None:
            raise RuntimeError(
                "conv filter %r has no value in scope; run the startup "
                "program before transpiling" % w_name)
        k, beta, mean = _bn_constants(bn)
        w = np.asarray(wval)
        w_folded = (w * k[:, None, None, None]).astype(w.dtype)
        if in_place_params:
            scope.set_var(w_name, _owned(w_folded))
        else:
            new_w = _fresh(w_name + ".bnfold")
            block.create_var(name=new_w, shape=tuple(w.shape),
                             dtype=str(w.dtype), persistable=True)
            scope.set_var(new_w, _owned(w_folded))
            conv.inputs["Filter"] = [new_w]
        bn_out = bn.output("Y")[0]

        if bias_name is not None:
            # fold into the bias: y = (conv + b - mean)*k + beta
            b = np.asarray(scope.find_var(bias_name))
            b_folded = ((b - mean) * k + beta).astype(b.dtype)
            add = block.ops[bn_idx - 1]
            if in_place_params:
                scope.set_var(bias_name, _owned(b_folded))
            else:
                new_b = _fresh(bias_name + ".bnfold")
                block.create_var(name=new_b, shape=tuple(b.shape),
                                 dtype=str(b.dtype), persistable=True)
                scope.set_var(new_b, _owned(b_folded))
                add.inputs["Y"] = [new_b]
            add.outputs["Out"] = [bn_out]
            block.ops.pop(bn_idx)
        else:
            # biasless conv: add a folded-bias elementwise_add in the
            # bn's place
            new_b = _fresh(w_name + ".bnfold_bias")
            block.create_var(name=new_b, shape=(len(k),),
                             dtype="float32", persistable=True)
            scope.set_var(new_b, _owned((beta - mean * k).astype(np.float32)))
            rng_attr = ({RNG_IDX_ATTR: bn.attrs[RNG_IDX_ATTR]}
                        if RNG_IDX_ATTR in bn.attrs else {})
            block.ops.pop(bn_idx)
            block.insert_op(
                bn_idx,
                type="elementwise_add",
                inputs={"X": conv_out, "Y": new_b},
                outputs={"Out": bn_out},
                attrs=dict({"axis": 1}, **rng_attr),
            )
        program._bump()
        folded += 1
        i = bn_idx + 1
    return folded


@register_pass("conv_bn_fold", level=2, exact=False, needs_scope=True)
def conv_bn_fold(ctx) -> int:
    n = fold_conv_bn(ctx.program, ctx.scope, keep=ctx.keep_names(),
                     require_is_test=True, in_place_params=False)
    if n:
        ctx.count("conv_bn_fold", "bn_folded", n)
        obs.TRANSPILE_OPS_REMOVED.inc(n, **{"pass": "conv_bn_fold"})
    return n
