"""DistributeTranspiler: distributed-training planning.

Reference: python/paddle/fluid/transpiler/distribute_transpiler.py — splits
each parameter into blocks, round-robins them over parameter servers,
rewrites the trainer graph with send/recv ops and emits per-pserver
programs that run the optimizer for their shard (sync via barriers, async
without).

TPU-native, parameters never leave the chips: the transpiler's real
content — "which device owns which slice of which parameter's optimizer
state" — becomes a ShardingPlan. The "pserver" role maps to ZeRO-style
sharding: optimizer accumulators (and optionally the params) are sharded
over the data axis; GSPMD turns the grad all-reduce into
reduce-scatter + sharded update + all-gather on ICI, which is the same
communication volume as the reference's send/recv but without hosts in
the loop.

Sync vs async: the reference's sync_mode gates barriers between trainers.
On TPU every step IS a global program — sync by construction; async mode
has no TPU equivalent and is accepted but runs synchronously (documented
divergence).
"""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from ..framework.core import Parameter, Program, default_main_program

__all__ = ["DistributeTranspiler", "DistributeTranspilerConfig"]


class DistributeTranspilerConfig:
    """Reference parity: slice_var_up / min_block_size knobs."""

    def __init__(self):
        self.slice_var_up = True
        self.min_block_size = 8192
        self.split_method = "RoundRobin"


class PServerShard:
    """What one 'parameter server' owns: a set of param names whose
    optimizer state lives on that shard."""

    def __init__(self, endpoint: str, index: int):
        self.endpoint = endpoint
        self.index = index
        self.param_names: List[str] = []
        self.bytes = 0

    def __repr__(self):
        return "PServerShard(%s, params=%s)" % (self.endpoint, self.param_names)

    def _not_a_program(self):
        raise TypeError(
            "this is a PServerShard manifest, not a runnable Program: on "
            "TPU there is no separate parameter-server process — the "
            "optimizer state for these params is a SHARD of the one mesh-"
            "wide program. Migrate `exe.run(t.get_pserver_program(ep))` "
            "to `ParallelExecutor(..., plan=t.sharding_plan(mesh))`, "
            "which gives each device this shard's update work via GSPMD.")

    # reference-API call sites treat the pserver program like a Program;
    # fail with a migration message instead of an AttributeError
    def global_block(self):
        self._not_a_program()

    def block(self, idx):
        self._not_a_program()

    def clone(self, for_test=False):
        self._not_a_program()

    @property
    def blocks(self):
        self._not_a_program()


class DistributeTranspiler:
    def __init__(self, config: Optional[DistributeTranspilerConfig] = None):
        self.config = config or DistributeTranspilerConfig()
        self._shards: List[PServerShard] = []
        self._program: Optional[Program] = None
        self.trainer_id = 0
        self.trainers = 1
        self.sync_mode = True

    def transpile(
        self,
        trainer_id: int,
        program: Optional[Program] = None,
        pservers: str = "127.0.0.1:6170",
        trainers: int = 1,
        sync_mode: bool = True,
        startup_program: Optional[Program] = None,
    ):
        """Plan the distribution. Signature matches the reference
        (transpiler/distribute_transpiler.py:transpile)."""
        self._program = program if program is not None else default_main_program()
        self.trainer_id = trainer_id
        self.trainers = trainers
        self.sync_mode = sync_mode
        if not sync_mode:
            import warnings

            warnings.warn(
                "DistributeTranspiler(sync_mode=False): async SGD has no "
                "TPU equivalent — every step is one global XLA program, so "
                "training runs SYNCHRONOUSLY (gradients all-reduced each "
                "step). Remove sync_mode=False to silence this warning.",
                stacklevel=2)
        endpoints = [e.strip() for e in pservers.split(",") if e.strip()]
        self._shards = [PServerShard(ep, i) for i, ep in enumerate(endpoints)]

        # balanced assignment by parameter bytes (the reference's
        # slice_vars round-robin, at whole-param granularity: XLA shards
        # within a param via the PartitionSpec, so block-slicing is moot)
        params = [
            v for v in self._program.global_block().vars.values()
            if isinstance(v, Parameter) and v.trainable
        ]
        params.sort(key=lambda p: -int(np.prod(p.shape) or 1))
        for p in params:
            shard = min(self._shards, key=lambda s: s.bytes)
            shard.param_names.append(p.name)
            shard.bytes += int(np.prod(p.shape) or 1) * 4
        return self

    # -- reference-parity accessors --------------------------------------
    def get_trainer_program(self) -> Program:
        """The trainer program is the ORIGINAL program: collectives are
        inserted by the XLA partitioner at compile time, so no send/recv
        rewrite happens."""
        if self._program is None:
            raise RuntimeError("call transpile() first")
        return self._program

    def get_pserver_program(self, endpoint: str) -> PServerShard:
        """Returns the shard manifest for `endpoint` — the TPU equivalent
        of the reference's per-pserver optimizer program (which device-mesh
        shard owns these params' optimizer state)."""
        for s in self._shards:
            if s.endpoint == endpoint:
                return s
        raise ValueError("endpoint %r not in transpiled pserver list" % endpoint)

    def get_pserver_programs(self, endpoint: str):
        shard = self.get_pserver_program(endpoint)
        return shard, self.get_startup_program(endpoint, shard)

    def get_startup_program(self, endpoint: str, pserver_program=None) -> Program:
        """On TPU initialization is the ordinary startup program (params are
        born sharded via the plan); returned unchanged for parity."""
        from ..framework.core import default_startup_program

        return default_startup_program()

    # -- the TPU-native product ------------------------------------------
    def sharding_plan(self, mesh, axis: str = "dp"):
        """ZeRO-style plan from the pserver assignment: every assigned
        param's optimizer accumulators are sharded over `axis`. transpile()
        assigns every trainable param to some shard, so this is exactly
        parallel.sharding.zero_plan over the transpiled program."""
        if self._program is None:
            raise RuntimeError("call transpile() first")
        from ..parallel.sharding import zero_plan

        return zero_plan(mesh, self._program, axis=axis)
