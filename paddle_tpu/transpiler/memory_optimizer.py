"""Memory optimization — absorbed into the optimizing transpiler.

The implementation lives in ``transpiler/passes/remat.py`` (the
reference's memory_optimization_transpiler.py maps onto a jax.checkpoint
remat policy here; in-graph dead code is the pass manager's ``dce``
pass). This module survives as the import-compatible shim."""
from .passes.remat import memory_optimize, release_memory  # noqa: F401

__all__ = ["memory_optimize", "release_memory"]
