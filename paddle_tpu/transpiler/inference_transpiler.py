"""InferenceTranspiler: inference-time graph rewrites.

Reference: python/paddle/fluid/transpiler/inference_transpiler.py — folds
batch_norm into the preceding conv2d (adjusting the conv filter/bias in the
Scope) and drops the bn op, plus relu/bn reordering for MKLDNN.

On TPU the XLA fuser already fuses the bn arithmetic into the conv epilogue
at runtime, so the fold is a compile-time simplification rather than a
perf necessity — but it still shrinks the program and removes 4 state
tensors per conv, and keeps parity with reference deployment flows.
"""
from __future__ import annotations

from typing import Optional

import numpy as np

from ..framework.core import Program
from ..framework.scope import Scope, global_scope

__all__ = ["InferenceTranspiler"]


class InferenceTranspiler:
    def transpile(self, program: Program, place=None, scope: Optional[Scope] = None):
        """Fold conv2d+batch_norm pairs in-place (program AND scope params).

        Only folds when the conv output feeds exactly the bn and nothing
        else, mirroring the reference's adjacency check.
        """
        scope = scope if scope is not None else global_scope()
        block = program.global_block()

        # count readers of every var so we only fold single-consumer convs
        readers = {}
        for op in block.ops:
            for name in op.input_arg_names:
                readers[name] = readers.get(name, 0) + 1

        def _bn_constants(bn):
            scale = np.asarray(scope.find_var(bn.input("Scale")[0]))
            beta = np.asarray(scope.find_var(bn.input("Bias")[0]))
            mean = np.asarray(scope.find_var(bn.input("Mean")[0]))
            var = np.asarray(scope.find_var(bn.input("Variance")[0]))
            k = scale / np.sqrt(var + bn.attr("epsilon", 1e-5))
            return k, beta, mean

        i = 0
        while i < len(block.ops):
            conv = block.ops[i]
            if conv.type != "conv2d":
                i += 1
                continue
            conv_out = conv.output("Output")[0]
            w_name = conv.input("Filter")[0]

            # pattern A: conv2d -> batch_norm
            # pattern B: conv2d -> elementwise_add(bias) -> batch_norm
            #            (layers.conv2d with bias_attr emits the add)
            nxt = block.ops[i + 1] if i + 1 < len(block.ops) else None
            nxt2 = block.ops[i + 2] if i + 2 < len(block.ops) else None
            if (
                nxt is not None
                and nxt.type == "batch_norm"
                and nxt.input("X") == [conv_out]
                and readers.get(conv_out, 0) == 1
            ):
                bn, bn_idx, bias_name = nxt, i + 1, None
            elif (
                nxt is not None
                and nxt2 is not None
                and nxt.type == "elementwise_add"
                and nxt.input("X") == [conv_out]
                and nxt2.type == "batch_norm"
                and nxt2.input("X") == nxt.output("Out")
                and readers.get(conv_out, 0) == 1
                and readers.get(nxt.output("Out")[0], 0) == 1
            ):
                bn, bn_idx, bias_name = nxt2, i + 2, nxt.input("Y")[0]
            else:
                i += 1
                continue

            wvar = block._find_var_recursive(w_name)
            if wvar is not None and not wvar.persistable:
                # the Filter is a derived in-graph variable, not a stored
                # parameter (e.g. the ResNet space-to-depth stem transforms
                # its canonical 7x7 weight in-graph) — leave this BN unfused
                i = bn_idx + 1
                continue
            wval = scope.find_var(w_name)
            if wval is None:
                raise RuntimeError(
                    "conv filter %r has no value in scope; run the startup "
                    "program before transpiling" % w_name)
            k, beta, mean = _bn_constants(bn)
            w = np.asarray(wval)
            scope.set_var(w_name, (w * k[:, None, None, None]).astype(w.dtype))
            bn_out = bn.output("Y")[0]

            if bias_name is not None:
                # fold into the existing bias: y = (conv + b - mean)*k + beta
                b = np.asarray(scope.find_var(bias_name))
                scope.set_var(
                    bias_name, ((b - mean) * k + beta).astype(b.dtype))
                add = block.ops[bn_idx - 1]
                add.outputs["Out"] = [bn_out]
                block.ops.pop(bn_idx)
            else:
                # biasless conv: add a folded-bias elementwise_add in the
                # bn's place
                bias_name = w_name + ".bnfold_bias"
                block.create_var(name=bias_name, shape=(len(k),),
                                 dtype="float32", persistable=True)
                scope.set_var(bias_name, (beta - mean * k).astype(np.float32))
                block.ops.pop(bn_idx)
                block.insert_op(
                    bn_idx,
                    type="elementwise_add",
                    inputs={"X": conv_out, "Y": bias_name},
                    outputs={"Out": bn_out},
                    attrs={"axis": 1},
                )
            program._bump()
            i = bn_idx + 1
        return program
