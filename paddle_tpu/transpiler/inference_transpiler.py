"""InferenceTranspiler: inference-time graph rewrites (legacy shim).

Reference: python/paddle/fluid/transpiler/inference_transpiler.py — folds
batch_norm into the preceding conv2d (adjusting the conv filter/bias in
the Scope) and drops the bn op, plus relu/bn reordering for MKLDNN.

The fold now lives in the optimizing transpiler
(``transpiler/passes/fusion.py:fold_conv_bn``), where it also runs as the
pass-manager's ``conv_bn_fold`` pass (level 2) — there it materializes
folded weights under fresh ``.bnfold`` names so the original program
keeps working. THIS class keeps the reference's historical contract
exactly: it rewrites the given program in place AND overwrites the
existing filter/bias values in the Scope (test-pinned), with no
``is_test`` gate — callers fold ``clone(for_test=True)`` programs.
"""
from __future__ import annotations

from typing import Optional

from ..framework.core import Program
from ..framework.scope import Scope, global_scope

__all__ = ["InferenceTranspiler"]


class InferenceTranspiler:
    def transpile(self, program: Program, place=None, scope: Optional[Scope] = None):
        """Fold conv2d+batch_norm pairs in-place (program AND scope
        params). Only folds when the conv output feeds exactly the bn
        and nothing else, mirroring the reference's adjacency check."""
        from .passes.fusion import fold_conv_bn

        scope = scope if scope is not None else global_scope()
        fold_conv_bn(program, scope, keep=(), require_is_test=False,
                     in_place_params=True)
        return program
