"""Inference serving: AOT-compiled Predictor + C++-batched serving loop.

Reference: paddle/fluid/inference/api/api_impl.cc — NativePredictor loads a
saved inference model and runs batches from C++ with no graph rebuild.
TPU-native equivalents:

- `Predictor` loads a save_inference_model directory, traces the program
  ONCE per feed signature, AOT-compiles it (jit → lower → compile) and
  serializes the XLA executable to `<model_dir>/__aot_cache__/` through
  the SHARED persistent store (`runtime/aot_cache.py` — the same file
  layout, key derivation, corruption quarantine, and mtime-LRU GC the
  training `Executor` uses). A fresh process deserializes the executable
  and predicts with NO re-trace and NO re-compile — the reference's
  "load once, serve forever" cold-start story.
- `PredictorServer` is the serving loop, built as a two-stage pipeline:
  requests enter a C++ bounded channel (runtime.cc) as zero-copy binary
  frames; a STACKING stage drains them with dynamic batching
  (`ptrt_chan_recv_batch`: block for the first, collect up to
  `max_wait_ms` longer), stacks rows and pads to the next power-of-two
  bucket; a DEVICE stage runs the AOT predictor over a bounded in-flight
  queue so host-side assembly overlaps device execution. Responses fan
  back out by request id.
"""
from __future__ import annotations

import os
import pickle
import queue
import threading
import time
import warnings
from typing import Dict, List, Optional, Sequence

import numpy as np

import jax

from . import observability as obs
from .observability import tracing as _tracing
from .framework.core import Program
from .framework.scope import Scope
from .framework.trace import RngStream, trace_block
from .runtime import aot_cache as _aot
from .runtime import recordio as _rio

__all__ = ["Predictor", "PredictorServer", "create_paddle_predictor"]

_AOT_DIR = "__aot_cache__"


class Predictor:
    """NativePredictor analog (reference api_impl.cc:NativePaddlePredictor).

    predictor = Predictor(model_dir)
    outs = predictor.run({"img": batch})          # dict feed
    outs = predictor.run([batch])                 # positional feed
    """

    def __init__(self, model_dir: str, place=None, aot_cache: bool = True,
                 cache_dir: Optional[str] = None, preload: bool = True,
                 opt_level: Optional[int] = None):
        from . import io as fluid_io
        from .executor import Executor

        self.model_dir = model_dir
        self._scope = Scope()
        exe = Executor(place, opt_level=0)
        if not aot_cache:
            # aot_cache=False promises NO disk persistence — that covers
            # the loader Executor's own compiles (load/startup programs
            # would otherwise land in the training-side default cache)
            exe._disk.enabled = False
        self._program, self._feed_names, self._fetch_targets = (
            fluid_io.load_inference_model(model_dir, exe, scope=self._scope))
        self._fetch_names = [t.name for t in self._fetch_targets]
        # opt-in optimizing transpiler, same knob as the Executor
        # (PADDLE_TPU_OPT; explicit arg wins). The optimized program has
        # its own content fingerprint, so its executables coexist with
        # the raw model's in the model-local AOT cache — and a model
        # exported with save_inference_model(optimize=...) needs nothing
        # here (already optimized, already stamped).
        from .transpiler.passes import opt_level_from_env, optimize_program

        self.opt_level = (opt_level_from_env(0) if opt_level is None
                          else int(opt_level))
        if self.opt_level > 0:
            self._program, _opt_ctx = optimize_program(
                self._program, scope=self._scope, level=self.opt_level,
                feed_names=self._feed_names,
                fetch_names=self._fetch_names)
        self._cache_dir = cache_dir or os.path.join(model_dir, _AOT_DIR)
        # the shared persistent executable store (runtime/aot_cache.py):
        # same layout/GC/quarantine as the training Executor's cache, but
        # rooted at the model's own directory so the executables ship
        # with the model artifacts. aot_cache=False (or the global
        # PADDLE_TPU_AOT_CACHE=0 kill switch) turns it off.
        self._disk = _aot.AotDiskCache(cache_dir=self._cache_dir,
                                       enabled=aot_cache)
        _aot.maybe_enable_jax_cache()
        # the shared compile/execute core (serving.engine.Engine): the
        # SAME feed-plan + AOT-key + load-or-compile code path the
        # training Executor uses — the two can no longer diverge
        from .serving.engine import Engine

        self._engine = Engine(self._program, disk=self._disk,
                              feed_names=self._feed_names,
                              fetch_names=self._fetch_names)
        self._compiled: Dict = {}
        self._touched: set = set()  # sigs whose USE this process recorded
        # feed-conversion plan, computed ONCE: the model's feed set is
        # frozen at load, so the per-call var lookup + declared-dtype
        # resolution of the old run() path is pure steady-state overhead
        self._feed_plan = self._engine.feed_plan()
        # pre-trace static analysis, same knob as the Executor
        # (PADDLE_TPU_VERIFY=1|strict): a broken exported model fails at
        # LOAD with op-level provenance, not at the first predict call
        from .analysis import analyze_program, enforce, verify_mode

        mode = verify_mode()
        if mode:
            enforce(analyze_program(self._program,
                                    feed_names=self._feed_names,
                                    fetch_names=self._fetch_names),
                    strict=(mode == "strict"))
        # params are resident device state, uploaded once at load
        self._state_names, self._state = self._load_state()
        self.traces = 0  # diagnostic: number of program traces performed
        if aot_cache and preload:
            # deserialize every cached executable NOW: the first predict
            # call pays pure execution, not AOT deserialization (measured
            # at ~200 ms for the MLP predictor — dominating a <1 ms run)
            self._preload_executables()

    # -- state -----------------------------------------------------------
    def _load_state(self):
        from .executor import analyze_state

        state_in, _ = analyze_state(self._program, set(self._feed_names))
        dev = jax.devices()[0]
        state = {}
        for n in state_in:
            val = self._scope.find_var(n)
            if val is None:
                raise RuntimeError(
                    "inference model is missing persistable %r" % n)
            # params live on device from load time: only feeds transfer
            # per predict call
            state[n] = jax.device_put(np.asarray(val), dev)
        return state_in, state

    # -- compilation cache -------------------------------------------------
    def _key(self, feed_sig) -> str:
        """Shared-store key via the Engine: program + feeds + fetch ORDER
        (the executable returns outputs in this order) + the environment
        fingerprint — a toolchain change is a key miss, never a
        stale-blob load (field layout: Engine.key_fields)."""
        return self._engine.key("predict", feed_sig,
                                tuple(self._fetch_names))

    def _meta(self, feed_sig) -> Dict:
        return self._engine.meta("predict", feed_sig,
                                 tuple(self._fetch_names))

    def _step_fn(self):
        program = self._program
        fetch_names = self._fetch_names

        def fn(feeds, state):
            self.traces += 1
            env = dict(state)
            env.update(feeds)
            rng = RngStream(jax.random.PRNGKey(0))
            trace_block(program.global_block(), env, rng)
            return tuple(env[n] for n in fetch_names)

        return fn

    def _get_executable(self, feed_arrays):
        feed_sig = tuple((n, tuple(a.shape), str(a.dtype))
                         for n, a in sorted(feed_arrays.items()))
        fp = obs.program_fp(self._program)
        if feed_sig in self._compiled:
            # per-dispatch hit accounting, same contract as kind=run/loop
            # (the resident-executable path dominates a steady server)
            obs.CACHE_HITS.inc(kind="predict", tier="memory", program=fp)
            if feed_sig not in self._touched:
                # record USE (once per process per signature) so the
                # preload cap's recency ordering tracks traffic, not
                # write time
                self._touched.add(feed_sig)
                self._disk.touch(self._key(feed_sig))
            return self._compiled[feed_sig]
        obs.CACHE_MISSES.inc(kind="predict", tier="memory", program=fp)
        from .executor import Executor

        # fail fast with the variable name on an impossible feed shape
        Executor._check_feed_shapes(self._program, feed_sig)

        key = self._key(feed_sig)

        def lower():
            from .framework.trace import TraceError

            fn = jax.jit(self._step_fn())
            try:
                return fn.lower(
                    {n: jax.ShapeDtypeStruct(s, np.dtype(d))
                     for n, s, d in feed_sig},
                    {n: jax.ShapeDtypeStruct(a.shape, a.dtype)
                     for n, a in self._state.items()})
            except TraceError as e:
                # same analyzer post-mortem as Executor trace failures
                Executor._rethrow_with_provenance(
                    self._program, e, feed_names=tuple(self._feed_names),
                    fetch_names=tuple(self._fetch_names))

        # acquisition (disk-load-or-compile + the tier metrics contract)
        # goes through the shared Engine — the same code path the
        # training Executor's _aot_compile runs
        loaded, path, timings = self._engine.acquire(
            "predict", key, lower, meta=self._meta(feed_sig))
        if path == "warm":
            if self._disk.read_meta(key) is None:
                # missing OR unreadable sidecar next to a valid blob
                # (pre-sidecar cache, or a torn/corrupt .sig write):
                # rewrite it now so the NEXT process's preload finds
                # this executable instead of paying the lazy
                # first-call deserialization forever
                self._disk.write_meta(key, self._meta(feed_sig))
        else:
            # the predictor compiles AOT anyway, so the trace/XLA split
            # and cost-analysis estimates come for free here
            cost = obs.hlo_cost_stats(loaded) or {}
            wall_ms = timings["trace_ms"] + timings["xla_ms"]
            obs.COMPILE_TOTAL.inc(kind="predict")
            obs.COMPILE_LATENCY_MS.observe(wall_ms, kind="predict")
            obs.TIMELINE.record_compile(
                "predict", fp, wall_ms=wall_ms, **dict(timings, **cost))
        self._compiled[feed_sig] = loaded
        return loaded

    def _preload_executables(self):
        """Load cached executables for this (program, backend, jax) at
        construction (VERDICT r3 weak #4: first-call latency was
        dominated by lazy AOT deserialization). Signatures come from the
        shared store's sidecars; keys that don't re-hash to their
        filename belong to another program/backend/jax version and are
        skipped. Construction cost is bounded: only the
        PADDLE_TPU_PRELOAD_MAX (default 8) most-recently-used signatures
        preload — a deployment whose traffic produced many batch shapes
        pays lazily for the cold tail instead of deserializing
        everything up front."""
        try:
            cap = int(os.environ.get("PADDLE_TPU_PRELOAD_MAX", 8))
        except ValueError:
            # preload is best-effort, never a crash: a malformed value
            # falls back to the default (PADDLE_TPU_RING_CHUNK precedent)
            warnings.warn(
                "PADDLE_TPU_PRELOAD_MAX=%r is not an integer; using 8"
                % os.environ.get("PADDLE_TPU_PRELOAD_MAX"))
            cap = 8
        for key, meta in self._disk.sidecars_by_recency():
            if cap <= 0:
                break
            feed_sig = meta.get("feed_sig")
            if feed_sig is None or feed_sig in self._compiled:
                continue
            if self._key(feed_sig) != key:
                continue  # another program/backend/jax version
            loaded = self._disk.load(key)
            if loaded is not None:
                self._compiled[feed_sig] = loaded
                cap -= 1

    # -- pre-warm ----------------------------------------------------------
    def warm(self, batch_rows: int) -> bool:
        """Compile (or AOT-load) the executable for a ``batch_rows``-row
        batch of the model's DECLARED feed shapes without running it —
        ``PredictorServer.start()`` pre-warms every padding bucket this
        way so no live request ever eats an XLA compile. Returns False
        (no-op) when a declared feed shape has dynamic non-batch dims
        (batch signature unknowable up front) or a STATIC batch dim
        (only that one size can ever serve, so bucket warming would just
        crash into _check_feed_shapes)."""
        feed_arrays = {}
        for name, var, want in self._feed_plan:
            shape = tuple(getattr(var, "shape", None) or ())
            if (not shape or shape[0] not in (-1, None)
                    or any(d is None or d < 0 for d in shape[1:])):
                return False
            feed_arrays[name] = np.zeros(
                (batch_rows,) + shape[1:], want or np.float32)
        # bucketized models pad at run(): warm the signature run() will
        # actually use, not the raw row count
        from .executor import Executor as _Exe

        _Exe._bucketize_feeds(self._program, feed_arrays)
        self._get_executable(feed_arrays)
        return True

    # -- prediction --------------------------------------------------------
    def run(self, feed, return_numpy: bool = True,
            _obs_path: str = "direct") -> List[np.ndarray]:
        t0 = time.perf_counter()
        if isinstance(feed, (list, tuple)):
            feed = dict(zip(self._feed_names, feed))
        # conversion walks the precomputed plan (Engine.convert_feeds —
        # the one feed-plan code path, shared with the Executor's engine)
        feed_arrays = self._engine.convert_feeds(feed, self._feed_plan)
        # bucketize stamp (optimized/exported models): pad the batch
        # axis to its pow2 bucket so churny request sizes share one
        # executable; PredictorServer batches arrive pre-padded to a
        # bucket, making this a no-op on the serving path
        from .executor import Executor as _Exe

        bkt_rows = _Exe._bucketize_feeds(self._program, feed_arrays)
        exe = self._get_executable(feed_arrays)
        outs = exe(feed_arrays, self._state)
        if bkt_rows is not None:
            outs = _Exe._slice_bucketized(
                self._program, self._fetch_names, list(outs), bkt_rows)
        outs = ([np.asarray(o) for o in outs] if return_numpy
                else list(outs))
        # batch latency + fill distribution (per-request latency for the
        # server path is recorded by PredictorServer, queue wait included)
        first = next(iter(feed_arrays.values())) if feed_arrays else None
        rows = (first.shape[0] if first is not None and first.ndim else 1)
        obs.PREDICT_LATENCY_MS.observe((time.perf_counter() - t0) * 1e3,
                                       path=_obs_path)
        obs.PREDICT_REQUESTS.inc(path=_obs_path)
        obs.PREDICT_BATCH_ROWS.observe(rows, path=_obs_path)
        return outs

    predict = run  # api parity sugar

    @property
    def feed_names(self) -> List[str]:
        return list(self._feed_names)

    @property
    def fetch_names(self) -> List[str]:
        return list(self._fetch_names)


def create_paddle_predictor(config_or_dir, **kwargs) -> Predictor:
    """reference api.cc:CreatePaddlePredictor parity shim."""
    if isinstance(config_or_dir, str):
        return Predictor(config_or_dir, **kwargs)
    return Predictor(getattr(config_or_dir, "model_dir"), **kwargs)


# -- request wire format --------------------------------------------------
#
# Zero-copy frame (fast path): contiguous numeric sample arrays ride the
# channel as the shared array-frame layout from runtime/recordio.py
# (b"Z" | rid u64 | nslots u32 | per-slot dtype/shape/bytes — the SAME
# layout the DataLoader writes into its shared-memory slots). The
# stacking stage reconstructs each row as an ``np.frombuffer`` VIEW over
# the received message — no pickle object graph is built on either side
# of the channel. Samples the frame cannot carry (object / record
# dtypes) fall back to the pickled form, prefixed b"P".

_encode_request = _rio.encode_frame
_decode_request = _rio.decode_frame


def _encode_sample(rid: int, sample) -> bytes:
    """One request sample (per-slot arrays, no batch dim) -> wire frame:
    the zero-copy form when every slot has a buffer-exporting dtype, the
    pickled ``b"P"`` form otherwise. Shared by ``PredictorServer.submit``
    and the fleet ``Router.submit`` so the two front doors can never
    drift in what they put on the wire."""
    rows, fast = [], True
    for a in sample:
        if type(a) is not np.ndarray:
            a = np.asarray(a)
        if a.dtype.kind in "OVMm":
            # object graphs and datetime/timedelta (no buffer export)
            # can't ride the frame
            fast = False
        elif not a.flags["C_CONTIGUOUS"]:
            a = np.ascontiguousarray(a)
        rows.append(a)
    return (_encode_request(rid, rows) if fast
            else b"P" + pickle.dumps((rid, rows), protocol=4))


class PredictorServer:
    """Pipelined dynamic-batching serving loop (reference: the
    NativePredictor run loop, rebuilt as a two-stage pipeline).

    server = PredictorServer(predictor, max_batch=8)
    server.start()
    fut = server.submit((row0,))          # per-slot sample arrays
    outs = fut.result()                   # list of per-fetch rows
    server.stop()

    Requests enter a C++ bounded channel as zero-copy binary frames
    (pickle only for object-dtype samples). Two worker stages overlap:

    - the STACKING stage drains up to ``max_batch`` frames per iteration
      (``ptrt_chan_recv_batch``: block for the first, then collect up to
      ``max_wait_ms`` longer or until full), stacks rows into one batch,
      and pads it up to the next power-of-two BUCKET (not to max_batch —
      a 5-row batch runs at 8 rows, not 32);
    - the DEVICE stage pops stacked batches from a bounded in-flight
      queue (depth ``in_flight``) and runs the AOT predictor, so
      host-side decode/stack overlaps device execution.

    ``start()`` pre-warms every bucket's compiled signature (one
    ``Predictor.warm`` per bucket), so no live request ever pays an XLA
    compile. ``max_wait_ms`` is the latency/throughput knob: 0 (default)
    ships whatever is queued immediately; a few ms lets slow traffic
    coalesce into fuller buckets.

    ``server.start_http(port)`` additionally serves the process metrics
    (request latency histograms, bucket fill, pad-waste rows, in-flight
    depth, per-stage latency — see paddle_tpu.observability) at
    ``GET /metrics`` in Prometheus text format and ``GET /metrics.json``
    as a JSON snapshot.
    """

    def __init__(self, predictor: Predictor, max_batch: int = 8,
                 capacity: int = 256, pad_batches: bool = True,
                 max_wait_ms: float = 0.0, in_flight: int = 2,
                 buckets: Optional[Sequence[int]] = None,
                 prewarm: bool = True):
        from .runtime.recordio import Channel

        if max_batch < 1:
            raise ValueError("max_batch must be >= 1, got %d" % max_batch)
        self.predictor = predictor
        self.max_batch = max_batch
        # pad every dynamic batch up to its BUCKET (zero rows, sliced off
        # after predict): one compiled signature per bucket instead of
        # one per distinct batch size the traffic happens to produce,
        # without the old policy's pad-everything-to-max_batch waste
        self.pad_batches = pad_batches
        self.max_wait_ms = float(max_wait_ms)
        self.in_flight = max(1, int(in_flight))
        if buckets is None:
            buckets, b = [], 1
            while b < max_batch:
                buckets.append(b)
                b *= 2
        self.buckets = sorted({int(b) for b in buckets} | {max_batch})
        self._prewarm = prewarm
        self._prewarmed = False
        self._chan = Channel(capacity)
        self._inflight: "queue.Queue" = queue.Queue(self.in_flight)
        # serializes predictor execution between the device stage and the
        # stacking stage's idle-device inline fast path
        self._dev_lock = threading.Lock()
        self._stack_thread: Optional[threading.Thread] = None
        self._dev_thread: Optional[threading.Thread] = None
        self._results: Dict[int, "_Future"] = {}
        self._next_id = 0
        self._lock = threading.Lock()
        self._http = None
        self._http_thread: Optional[threading.Thread] = None
        # diagnostic: executed batches by REAL row count (device thread
        # writes, anyone may read; tests and the serving bench use it)
        self.batch_size_counts: Dict[int, int] = {}

    def _bucket_for(self, n: int) -> int:
        for b in self.buckets:
            if b >= n:
                return b
        return self.max_batch

    def start(self):
        if self._dev_thread is not None and self._dev_thread.is_alive():
            return
        if self.pad_batches and self._prewarm and not self._prewarmed:
            # compile/AOT-load every bucket signature BEFORE serving: a
            # cold bucket would stall its whole batch (and everything
            # queued behind it) for an XLA compile mid-traffic
            t0 = time.perf_counter()
            for b in self.buckets:
                if not self.predictor.warm(b):
                    break  # dynamic non-batch dims: bucket sigs stay lazy
            self._prewarmed = True
            obs.SERVER_STAGE_MS.observe(
                (time.perf_counter() - t0) * 1e3, stage="prewarm")
        self._stack_thread = threading.Thread(
            target=self._stack_loop, daemon=True)
        self._dev_thread = threading.Thread(
            target=self._device_loop, daemon=True)
        self._stack_thread.start()
        self._dev_thread.start()

    def submit(self, sample: Sequence[np.ndarray]) -> "_Future":
        """sample: one array per feed slot (a single row, no batch dim)."""
        fut = _Future()
        fut._t0 = time.perf_counter()  # request latency incl. queue wait
        with self._lock:
            rid = self._next_id
            self._next_id += 1
            self._results[rid] = fut
        fut._bind(self, rid)
        tid = _tracing.maybe_start()
        if tid is not None:
            # standalone-server client edge: no wire hop, so the id
            # binds straight into the stage-correlation table
            _tracing.bind_rid(rid, tid)
            _tracing.record_span(tid, "client.submit", rid=rid)
        try:
            sent = self._chan.send(_encode_sample(rid, sample))
        except BaseException:
            # an encode/convert failure must not leak the result-table
            # entry registered above
            with self._lock:
                self._results.pop(rid, None)
            _tracing.pop_rid(rid)
            raise
        if not sent:
            with self._lock:
                self._results.pop(rid, None)
            _tracing.pop_rid(rid)
            raise RuntimeError("predictor server is stopped")
        return fut

    def submit_frame(self, msg) -> "_Future":
        """Submit an ALREADY-ENCODED request frame (the fleet worker's
        fan-in path: the Router forwards the client's wire frame
        verbatim, so the worker re-encodes nothing). The frame's
        embedded tag becomes the request id — the caller owns the tag
        namespace and must not collide with ids minted by ``submit()``
        (a fleet worker only ever receives router-minted tags, so the
        two namespaces never mix in one server)."""
        rid = _rio.frame_tag(msg)
        fut = _Future()
        fut._t0 = time.perf_counter()
        with self._lock:
            if rid in self._results:
                raise ValueError("request tag %d is already in flight"
                                 % rid)
            self._results[rid] = fut
        fut._bind(self, rid)
        if not self._chan.send(msg):
            with self._lock:
                self._results.pop(rid, None)
            raise RuntimeError("predictor server is stopped")
        return fut

    @staticmethod
    def _assemble(rows, nreal: int, bucket: int):
        """Per-slot batch assembly in ONE pass: rows gather (C++ threaded
        memcpy for >=1 MiB payloads, Python loop below it) straight into
        a bucket-sized buffer whose pad tail is zeroed in place — the old
        np.stack + np.concatenate pair copied every padded batch twice.
        A lone unpadded row is returned as a VIEW (no copy at all)."""
        from .runtime.recordio import batch_assemble

        feed = []
        for j in range(len(rows[0])):
            r0 = rows[0][j]
            if nreal == 1 and bucket == 1:
                feed.append(r0[None])
                continue
            slot = [rows[i][j] for i in range(nreal)]
            dt = r0.dtype
            if any(r.dtype != dt for r in slot):
                # mixed-dtype rows promote like np.stack did — filling an
                # r0-typed buffer would silently truncate (0.7 -> 0)
                dt = np.result_type(*[r.dtype for r in slot])
            out = np.empty((bucket,) + r0.shape, dt)
            if not batch_assemble(slot, out[:nreal]):
                for i in range(nreal):
                    if slot[i].shape != r0.shape:
                        # np.stack used to raise here; a bare out[i]=
                        # assignment would silently BROADCAST a
                        # mismatched row into a wrong batch
                        raise ValueError(
                            "sample %d slot %d has shape %s; this batch "
                            "expects %s" % (i, j, slot[i].shape, r0.shape))
                    out[i] = slot[i]
            if bucket > nreal:
                out[nreal:] = 0
            feed.append(out)
        return feed

    # -- pipeline stages --------------------------------------------------
    def _stack_loop(self):
        max_wait_s = self.max_wait_ms / 1e3
        while True:
            batch = self._chan.recv_batch(
                self.max_batch, max_wait_s if max_wait_s > 0 else None)
            if batch is None:
                self._inflight.put(None)  # closed + drained: stop device
                return
            t0 = time.perf_counter()
            reqs = []
            for msg in batch:
                # per-MESSAGE decode: one malformed frame (fuzzed bytes,
                # a torn requeue) must not take down the well-formed
                # requests that happened to share its drain batch. A
                # frame whose HEADER survived still names its request —
                # that future gets a structured reject instead of
                # hanging to its caller's timeout; headerless garbage is
                # counted and dropped.
                try:
                    reqs.append(_decode_request(msg))
                except Exception as e:
                    obs.PREDICT_FAILURES.inc(path="server_decode")
                    try:
                        fut = self._pop(_rio.frame_tag(msg))
                    except Exception:
                        continue
                    if fut is not None:
                        fut.set_exception(ValueError(
                            "malformed request frame rejected: %s"
                            % (e,)))
            if not reqs:
                continue
            try:
                rows = [r[1] for r in reqs]
                nreal = len(rows)
                bucket = (self._bucket_for(nreal) if self.pad_batches
                          else nreal)
                feed = self._assemble(rows, nreal, bucket)
                obs.PREDICT_BATCH_ROWS.observe(nreal, path="server")
                obs.SERVER_BUCKET_FILL.observe(nreal, bucket=str(bucket))
                obs.SERVER_ROWS.inc(nreal, kind="real")
                if bucket > nreal:
                    obs.SERVER_ROWS.inc(bucket - nreal, kind="pad")
                stack_ms = (time.perf_counter() - t0) * 1e3
                obs.SERVER_STAGE_MS.observe(stack_ms, stage="stack")
                if _tracing.bound():
                    for rid, _ in reqs:
                        t_id = _tracing.rid_trace(rid)
                        if t_id is not None:
                            _tracing.record_span(
                                t_id, "server.stack", dur_ms=stack_ms,
                                rid=rid, rows=nreal, bucket=bucket)
                            obs.REQUEST_PHASE_MS.observe(stack_ms,
                                                         phase="stack")
            except Exception:
                # mixed slot counts / row shapes inside ONE drain batch
                # (a mangled-but-decodable frame riding with healthy
                # requests, or genuinely inconsistent clients): degrade
                # to per-request batches so only the offending request
                # fails — the old fan-out failed every co-batched
                # neighbour with the stranger's error
                self._queue_singly(reqs)
                continue
            # idle-device fast path: with nothing queued and the device
            # stage idle, the queue hop + thread wake would be pure added
            # latency — run the batch HERE (under the device lock), so
            # the pipeline collapses to a single stage at low load and
            # expands under load, where the hop pays for itself
            ran_inline = False
            if (self._inflight.empty()
                    and self._dev_lock.acquire(blocking=False)):
                try:
                    self._run_batch(reqs, feed)
                    ran_inline = True
                finally:
                    self._dev_lock.release()
            if not ran_inline:
                self._inflight.put((reqs, feed))
                obs.SERVER_INFLIGHT_DEPTH.set(self._inflight.qsize())

    def _queue_singly(self, reqs):
        """Batch-assembly failure fallback: each request becomes its own
        single-row batch, so assembly/shape errors fail exactly the
        request that caused them (the predictor's own feed checks catch
        arity/shape nonsense per request). The degraded path costs one
        dispatch per request — it only runs when a drain batch was
        internally inconsistent, which healthy uniform traffic never
        is."""
        for req in reqs:
            try:
                bucket = self._bucket_for(1) if self.pad_batches else 1
                feed = self._assemble([req[1]], 1, bucket)
            except Exception as e:
                self._fail([req], e)
                continue
            obs.PREDICT_BATCH_ROWS.observe(1, path="server")
            obs.SERVER_ROWS.inc(1, kind="real")
            self._inflight.put(([req], feed))
            obs.SERVER_INFLIGHT_DEPTH.set(self._inflight.qsize())

    def _device_loop(self):
        while True:
            item = self._inflight.get()
            if item is None:
                return
            obs.SERVER_INFLIGHT_DEPTH.set(self._inflight.qsize())
            reqs, feed = item
            with self._dev_lock:
                self._run_batch(reqs, feed)

    def _run_batch(self, reqs, feed):
        """Device-stage body: one predictor dispatch, responses fanned
        back out by request id. Caller holds ``_dev_lock``."""
        t0 = time.perf_counter()
        try:
            outs = self.predictor.run(feed, _obs_path="server_batch")
        except Exception as e:  # fan the error out; keep serving
            self._fail(reqs, e)
            return
        dev_ms = (time.perf_counter() - t0) * 1e3
        obs.SERVER_STAGE_MS.observe(dev_ms, stage="device")
        n = len(reqs)
        self.batch_size_counts[n] = self.batch_size_counts.get(n, 0) + 1
        now = time.perf_counter()
        traced = _tracing.bound()
        for i, (rid, _) in enumerate(reqs):
            if traced:
                # span + phase BEFORE _pop — _pop drops the binding
                t_id = _tracing.rid_trace(rid)
                if t_id is not None:
                    _tracing.record_span(t_id, "server.device",
                                         dur_ms=dev_ms, rid=rid, rows=n)
                    obs.REQUEST_PHASE_MS.observe(dev_ms, phase="device")
            fut = self._pop(rid)
            if fut is not None:  # None: abandoned via cancel/timeout
                fut.set_result([o[i] for o in outs])
                obs.PREDICT_LATENCY_MS.observe(
                    (now - fut._t0) * 1e3, path="server")
                obs.PREDICT_REQUESTS.inc(path="server")

    def _fail(self, reqs, e):
        """Error path: every request still gets its latency sample and a
        failure count, so error rates are visible at /metrics (the old
        loop fanned the exception out silently)."""
        now = time.perf_counter()
        for rid, _ in reqs:
            obs.PREDICT_FAILURES.inc(path="server")
            fut = self._pop(rid)
            if fut is not None:
                fut.set_exception(e)
                obs.PREDICT_LATENCY_MS.observe(
                    (now - fut._t0) * 1e3, path="server")

    def _pop(self, rid):
        # every future exit path (fan-out, failure, cancel, malformed-
        # frame reject) funnels here: the trace binding can never leak
        _tracing.pop_rid(rid)
        with self._lock:
            return self._results.pop(rid, None)

    # -- observability endpoint ------------------------------------------
    def start_http(self, port: int = 0, host: str = "127.0.0.1") -> int:
        """Expose the process metrics over HTTP for a Prometheus scrape:
        ``GET /metrics`` serves the text exposition of the global
        registry, ``GET /metrics.json`` the JSON snapshot including the
        step timeline. port=0 picks a free port; returns the bound port.
        """
        if self._http is not None:
            return self._http.server_address[1]
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        from .observability import export

        class _Handler(BaseHTTPRequestHandler):
            def do_GET(h):  # noqa: N805 — BaseHTTPRequestHandler idiom
                path = h.path.split("?", 1)[0]
                if path == "/metrics":
                    body = export.to_prometheus().encode("utf-8")
                    ctype = "text/plain; version=0.0.4; charset=utf-8"
                elif path == "/metrics.json":
                    body = export.dumps_json(indent=2).encode("utf-8")
                    ctype = "application/json"
                else:
                    h.send_response(404)
                    h.end_headers()
                    return
                h.send_response(200)
                h.send_header("Content-Type", ctype)
                h.send_header("Content-Length", str(len(body)))
                h.end_headers()
                h.wfile.write(body)

            def log_message(self, *args):  # scrape spam stays off stderr
                pass

        self._http = ThreadingHTTPServer((host, port), _Handler)
        self._http_thread = threading.Thread(
            target=self._http.serve_forever, daemon=True)
        self._http_thread.start()
        return self._http.server_address[1]

    def stop_http(self):
        if self._http is None:
            return
        self._http.shutdown()
        self._http.server_close()
        if self._http_thread is not None:
            self._http_thread.join(timeout=5)
            self._http_thread = None
        self._http = None

    def stop(self):
        self.stop_http()
        self._chan.close()
        # the stacking stage drains the channel, forwards the last
        # batches, then sends the device stage its None sentinel
        if self._stack_thread is not None:
            self._stack_thread.join(timeout=5)
            self._stack_thread = None
        if self._dev_thread is not None:
            self._dev_thread.join(timeout=5)
            self._dev_thread = None


class _Future:
    """Completion handle for one submitted sample.

    A ``result(timeout)`` that raises TimeoutError ABANDONS the request:
    its entry in the server's result table is released immediately (the
    pre-pipeline server leaked it until process exit) and the row's
    result or error is silently dropped when its batch completes.
    ``cancel()`` does the same without waiting first.
    """

    def __init__(self):
        self._ev = threading.Event()
        self._val = None
        self._exc = None
        self._t0 = 0.0
        self._server = None
        self._rid = None
        self._cb_lock = threading.Lock()
        self._callbacks: list = []

    def _bind(self, server, rid):
        self._server = server
        self._rid = rid

    def add_done_callback(self, fn):
        """Call ``fn(self)`` when the result or error lands (immediately
        if it already has). Runs on the completing thread (the server's
        device/stacking stage) — keep it short; exceptions are swallowed
        so a broken callback cannot kill the serving loop. The fleet
        worker streams responses back to the router this way instead of
        parking one thread per in-flight request."""
        run_now = False
        with self._cb_lock:
            if self._ev.is_set():
                run_now = True
            else:
                self._callbacks.append(fn)
        if run_now:
            self._run_callback(fn)

    def _run_callback(self, fn):
        try:
            fn(self)
        except Exception:
            pass

    def _fire_callbacks(self):
        with self._cb_lock:
            cbs, self._callbacks = self._callbacks, []
        for fn in cbs:
            self._run_callback(fn)

    def cancel(self):
        """Drop this request: the server forgets it now and discards its
        result when the batch completes. A result that already arrived
        stays readable."""
        srv, self._server = self._server, None
        if srv is not None and not self._ev.is_set():
            srv._pop(self._rid)

    def set_result(self, v):
        self._val = v
        with self._cb_lock:
            self._ev.set()
        self._fire_callbacks()

    def set_exception(self, e):
        self._exc = e
        with self._cb_lock:
            self._ev.set()
        self._fire_callbacks()

    def result(self, timeout: Optional[float] = None,
               cancel_on_timeout: bool = True):
        """Wait for the row. On timeout the request is ABANDONED (see
        class docstring) unless ``cancel_on_timeout=False``, which keeps
        the entry alive for poll-style callers that intend to re-wait."""
        if not self._ev.wait(timeout):
            if cancel_on_timeout:
                self.cancel()
                raise TimeoutError(
                    "predict result not ready (request abandoned; "
                    "resubmit to retry, or poll with "
                    "cancel_on_timeout=False)")
            raise TimeoutError("predict result not ready")
        if self._exc is not None:
            raise self._exc
        return self._val
