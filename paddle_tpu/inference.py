"""Inference serving: AOT-compiled Predictor + C++-batched serving loop.

Reference: paddle/fluid/inference/api/api_impl.cc — NativePredictor loads a
saved inference model and runs batches from C++ with no graph rebuild.
TPU-native equivalents:

- `Predictor` loads a save_inference_model directory, traces the program
  ONCE per feed signature, AOT-compiles it (jit → lower → compile) and
  serializes the XLA executable to `<model_dir>/__aot_cache__/` keyed on
  (program fingerprint, feed signature, backend, jax version). A fresh
  process deserializes the executable and predicts with NO re-trace and NO
  re-compile — the reference's "load once, serve forever" cold-start story.
- `PredictorServer` is the serving loop: requests enter a C++ bounded
  channel (runtime.cc), `ptrt_chan_recv_batch` drains them with dynamic
  batching (block for the first, take whatever else is queued), the worker
  stacks rows and runs the Predictor, responses fan back out by request id.
"""
from __future__ import annotations

import hashlib
import os
import pickle
import threading
import time
import warnings
from typing import Dict, List, Optional, Sequence

import numpy as np

import jax

from . import observability as obs
from .framework.core import Program
from .framework.scope import Scope
from .framework.trace import RngStream, trace_block

__all__ = ["Predictor", "PredictorServer", "create_paddle_predictor"]

_AOT_DIR = "__aot_cache__"


class Predictor:
    """NativePredictor analog (reference api_impl.cc:NativePaddlePredictor).

    predictor = Predictor(model_dir)
    outs = predictor.run({"img": batch})          # dict feed
    outs = predictor.run([batch])                 # positional feed
    """

    def __init__(self, model_dir: str, place=None, aot_cache: bool = True,
                 cache_dir: Optional[str] = None, preload: bool = True):
        from . import io as fluid_io
        from .executor import Executor

        self.model_dir = model_dir
        self._scope = Scope()
        exe = Executor(place)
        self._program, self._feed_names, self._fetch_targets = (
            fluid_io.load_inference_model(model_dir, exe, scope=self._scope))
        self._fetch_names = [t.name for t in self._fetch_targets]
        self._aot_cache = aot_cache
        self._cache_dir = cache_dir or os.path.join(model_dir, _AOT_DIR)
        self._compiled: Dict = {}
        self._touched: set = set()  # sigs whose USE this process recorded
        # params are resident device state, uploaded once at load
        self._state_names, self._state = self._load_state()
        self.traces = 0  # diagnostic: number of program traces performed
        if aot_cache and preload:
            # deserialize every cached executable NOW: the first predict
            # call pays pure execution, not AOT deserialization (measured
            # at ~200 ms for the MLP predictor — dominating a <1 ms run)
            self._preload_executables()

    # -- state -----------------------------------------------------------
    def _load_state(self):
        from .executor import analyze_state

        state_in, _ = analyze_state(self._program, set(self._feed_names))
        dev = jax.devices()[0]
        state = {}
        for n in state_in:
            val = self._scope.find_var(n)
            if val is None:
                raise RuntimeError(
                    "inference model is missing persistable %r" % n)
            # params live on device from load time: only feeds transfer
            # per predict call
            state[n] = jax.device_put(np.asarray(val), dev)
        return state_in, state

    # -- compilation cache -------------------------------------------------
    def _key(self, feed_sig) -> str:
        h = hashlib.sha1()
        h.update(repr((self._program.fingerprint(), feed_sig,
                       tuple(self._fetch_names),  # ORDER matters: the
                       # executable returns outputs in this order
                       jax.default_backend(), jax.__version__,
                       )).encode())
        return h.hexdigest()[:24]

    def _step_fn(self):
        program = self._program
        fetch_names = self._fetch_names

        def fn(feeds, state):
            self.traces += 1
            env = dict(state)
            env.update(feeds)
            rng = RngStream(jax.random.PRNGKey(0))
            trace_block(program.global_block(), env, rng)
            return tuple(env[n] for n in fetch_names)

        return fn

    def _get_executable(self, feed_arrays):
        feed_sig = tuple((n, tuple(a.shape), str(a.dtype))
                         for n, a in sorted(feed_arrays.items()))
        if feed_sig in self._compiled:
            # per-dispatch hit accounting, same contract as kind=run/loop
            # (the resident-executable path dominates a steady server)
            obs.CACHE_HITS.inc(kind="predict",
                               program=obs.program_fp(self._program))
            if feed_sig not in self._touched:
                # record USE (once per process per signature) so the
                # preload cap's recency ordering tracks traffic, not
                # write time
                self._touched.add(feed_sig)
                self._touch_sig(os.path.join(
                    self._cache_dir, self._key(feed_sig) + ".sig"))
            return self._compiled[feed_sig]
        from .executor import Executor

        # fail fast with the variable name on an impossible feed shape
        Executor._check_feed_shapes(self._program, feed_sig)

        key = self._key(feed_sig)
        path = os.path.join(self._cache_dir, key + ".xla")
        loaded = (self._deserialize_executable(path)
                  if self._aot_cache and os.path.exists(path) else None)
        if loaded is not None:
            obs.CACHE_HITS.inc(kind="predict",
                               program=obs.program_fp(self._program))
            obs.TIMELINE.record_compile(
                "predict", obs.program_fp(self._program), cache="aot-load")
            # a cache written before sidecars existed: create the .sig now
            # so the NEXT process's preload finds this executable (without
            # this, pre-sidecar caches would pay the lazy-deserialization
            # first call forever)
            sig_path = os.path.join(self._cache_dir, key + ".sig")
            if not os.path.exists(sig_path):
                self._write_sig(feed_sig, key)
            else:
                self._touch_sig(sig_path)
        if loaded is None:
            fp = obs.program_fp(self._program)
            obs.CACHE_MISSES.inc(kind="predict", program=fp)
            fn = jax.jit(self._step_fn())
            t0 = time.perf_counter()
            lowered = fn.lower(
                {n: jax.ShapeDtypeStruct(s, np.dtype(d))
                 for n, s, d in feed_sig},
                {n: jax.ShapeDtypeStruct(a.shape, a.dtype)
                 for n, a in self._state.items()})
            t1 = time.perf_counter()
            loaded = lowered.compile()
            t2 = time.perf_counter()
            # the predictor compiles AOT anyway, so the trace/XLA split
            # and cost-analysis estimates come for free here
            cost = obs.hlo_cost_stats(loaded) or {}
            obs.COMPILE_TOTAL.inc(kind="predict")
            obs.COMPILE_LATENCY_MS.observe((t2 - t0) * 1e3, kind="predict")
            obs.TIMELINE.record_compile(
                "predict", fp, wall_ms=(t2 - t0) * 1e3,
                trace_ms=(t1 - t0) * 1e3, xla_ms=(t2 - t1) * 1e3, **cost)
            if self._aot_cache:
                from jax.experimental import serialize_executable as se

                os.makedirs(self._cache_dir, exist_ok=True)
                blob, in_tree, out_tree = se.serialize(loaded)
                tmp = path + ".tmp.%d" % os.getpid()
                with open(tmp, "wb") as f:
                    pickle.dump((blob, in_tree, out_tree), f)
                os.replace(tmp, path)
                # sidecar records the feed signature so a later load can
                # preload this executable without knowing the signature
                self._write_sig(feed_sig, key)
        self._compiled[feed_sig] = loaded
        return loaded

    @staticmethod
    def _touch_sig(sig_path):
        try:
            os.utime(sig_path, None)
        except OSError:
            pass  # shared/read-only cache: recency just doesn't update

    def _write_sig(self, feed_sig, key: str):
        try:
            os.makedirs(self._cache_dir, exist_ok=True)
            tmp = os.path.join(self._cache_dir,
                               key + ".sigtmp.%d" % os.getpid())
            with open(tmp, "wb") as f:
                pickle.dump(feed_sig, f)
            os.replace(tmp, os.path.join(self._cache_dir, key + ".sig"))
        except OSError:
            pass  # a read-only cache dir only loses preload, not serving

    def _deserialize_executable(self, path):
        from jax.experimental import serialize_executable as se

        try:
            with open(path, "rb") as f:
                blob, in_tree, out_tree = pickle.load(f)
            # pin execution to one device: the executable was compiled
            # single-device, and the default (all local devices) breaks
            # under a multi-device runtime (e.g. the 8-virtual-CPU
            # test mesh)
            return se.deserialize_and_load(
                blob, in_tree, out_tree,
                execution_devices=jax.devices()[:1])
        except Exception:
            return None  # cache from another machine/version: rebuild

    def _preload_executables(self):
        """Load cached executables for this (program, backend, jax) at
        construction (VERDICT r3 weak #4: first-call latency was
        dominated by lazy AOT deserialization). Signatures come from the
        .sig sidecars; keys that don't re-hash to their filename belong
        to another program/backend/jax version and are skipped.
        Construction cost is bounded: only the PADDLE_TPU_PRELOAD_MAX
        (default 8) most-recently-used signatures preload — a deployment
        whose traffic produced many batch shapes pays lazily for the
        cold tail instead of deserializing everything up front."""
        import glob

        def mtime_or_zero(p):
            # another process may clean/rewrite the shared cache between
            # glob and stat; preload is best-effort, never a crash
            try:
                return os.path.getmtime(p)
            except OSError:
                return 0.0

        try:
            cap = int(os.environ.get("PADDLE_TPU_PRELOAD_MAX", 8))
        except ValueError:
            # preload is best-effort, never a crash: a malformed value
            # falls back to the default (PADDLE_TPU_RING_CHUNK precedent)
            warnings.warn(
                "PADDLE_TPU_PRELOAD_MAX=%r is not an integer; using 8"
                % os.environ.get("PADDLE_TPU_PRELOAD_MAX"))
            cap = 8
        sig_paths = sorted(
            glob.glob(os.path.join(self._cache_dir, "*.sig")),
            key=mtime_or_zero, reverse=True)
        for sig_path in sig_paths:
            if cap <= 0:
                break
            try:
                with open(sig_path, "rb") as f:
                    feed_sig = pickle.load(f)
            except Exception:
                continue
            key = self._key(feed_sig)
            if os.path.basename(sig_path) != key + ".sig":
                continue
            if feed_sig in self._compiled:
                continue
            loaded = self._deserialize_executable(
                os.path.join(self._cache_dir, key + ".xla"))
            if loaded is not None:
                self._compiled[feed_sig] = loaded
                cap -= 1

    # -- prediction --------------------------------------------------------
    def run(self, feed, return_numpy: bool = True,
            _obs_path: str = "direct") -> List[np.ndarray]:
        from .framework.dtypes import as_numpy_dtype

        t0 = time.perf_counter()
        if isinstance(feed, (list, tuple)):
            feed = dict(zip(self._feed_names, feed))
        gb = self._program.global_block()
        feed_arrays = {}
        for name in self._feed_names:
            if name not in feed:
                raise KeyError("missing feed %r (model expects %s)"
                               % (name, self._feed_names))
            var = gb._find_var_recursive(name)
            arr = np.asarray(feed[name])
            if var is not None:
                want = as_numpy_dtype(var.dtype)
                if arr.dtype != want:
                    arr = arr.astype(want)
            feed_arrays[name] = arr
        exe = self._get_executable(feed_arrays)
        outs = exe(feed_arrays, self._state)
        outs = ([np.asarray(o) for o in outs] if return_numpy
                else list(outs))
        # batch latency + fill distribution (per-request latency for the
        # server path is recorded by PredictorServer, queue wait included)
        first = next(iter(feed_arrays.values())) if feed_arrays else None
        rows = (first.shape[0] if first is not None and first.ndim else 1)
        obs.PREDICT_LATENCY_MS.observe((time.perf_counter() - t0) * 1e3,
                                       path=_obs_path)
        obs.PREDICT_REQUESTS.inc(path=_obs_path)
        obs.PREDICT_BATCH_ROWS.observe(rows, path=_obs_path)
        return outs

    predict = run  # api parity sugar

    @property
    def feed_names(self) -> List[str]:
        return list(self._feed_names)

    @property
    def fetch_names(self) -> List[str]:
        return list(self._fetch_names)


def create_paddle_predictor(config_or_dir, **kwargs) -> Predictor:
    """reference api.cc:CreatePaddlePredictor parity shim."""
    if isinstance(config_or_dir, str):
        return Predictor(config_or_dir, **kwargs)
    return Predictor(getattr(config_or_dir, "model_dir"), **kwargs)


class PredictorServer:
    """C++-batched serving loop (reference: the NativePredictor run loop).

    server = PredictorServer(predictor, max_batch=8)
    server.start()
    fut = server.submit((row0,))          # per-slot sample arrays
    outs = fut.result()                   # list of per-fetch rows
    server.stop()

    Requests are pickled into a C++ bounded channel; the worker thread
    drains up to max_batch per iteration with ptrt_chan_recv_batch (block
    for the first, no wait for the rest), stacks rows into one batch, runs
    the AOT predictor, and slices responses back per request.

    ``server.start_http(port)`` additionally serves the process metrics
    (request latency histograms, dynamic-batch fill, compile-cache
    counters — see paddle_tpu.observability) at ``GET /metrics`` in
    Prometheus text format and ``GET /metrics.json`` as a JSON snapshot.
    """

    def __init__(self, predictor: Predictor, max_batch: int = 8,
                 capacity: int = 256, pad_batches: bool = True):
        from .runtime.recordio import Channel

        self.predictor = predictor
        self.max_batch = max_batch
        # pad every dynamic batch up to max_batch (zero rows, sliced off
        # after predict): ONE compiled signature instead of one XLA
        # compile per distinct batch size the traffic happens to produce
        self.pad_batches = pad_batches
        self._chan = Channel(capacity)
        self._thread: Optional[threading.Thread] = None
        self._results: Dict[int, "_Future"] = {}
        self._next_id = 0
        self._lock = threading.Lock()
        self._http = None
        self._http_thread: Optional[threading.Thread] = None

    def start(self):
        if self._thread is not None and self._thread.is_alive():
            return
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def submit(self, sample: Sequence[np.ndarray]) -> "_Future":
        """sample: one array per feed slot (a single row, no batch dim)."""
        fut = _Future()
        fut._t0 = time.perf_counter()  # request latency incl. queue wait
        with self._lock:
            rid = self._next_id
            self._next_id += 1
            self._results[rid] = fut
        ok = self._chan.send(pickle.dumps(
            (rid, [np.asarray(a) for a in sample]), protocol=4))
        if not ok:
            with self._lock:
                self._results.pop(rid, None)
            raise RuntimeError("predictor server is stopped")
        return fut

    def _loop(self):
        while True:
            batch = self._chan.recv_batch(self.max_batch)
            if batch is None:
                return  # closed and drained
            reqs = []
            try:
                reqs = [pickle.loads(b) for b in batch]
                rows = [r[1] for r in reqs]
                feed = [np.stack([row[j] for row in rows])
                        for j in range(len(rows[0]))]
                if self.pad_batches and len(rows) < self.max_batch:
                    pad = self.max_batch - len(rows)
                    feed = [np.concatenate(
                        [f, np.zeros((pad,) + f.shape[1:], f.dtype)])
                        for f in feed]
                obs.PREDICT_BATCH_ROWS.observe(len(rows), path="server")
                outs = self.predictor.run(feed, _obs_path="server_batch")
                now = time.perf_counter()
                for i, (rid, _) in enumerate(reqs):
                    fut = self._pop(rid)
                    if fut is not None:
                        fut.set_result([o[i] for o in outs])
                        obs.PREDICT_LATENCY_MS.observe(
                            (now - fut._t0) * 1e3, path="server")
                        obs.PREDICT_REQUESTS.inc(path="server")
            except Exception as e:  # fan the error out; keep serving
                for rid, _ in reqs:
                    fut = self._pop(rid)
                    if fut is not None:
                        fut.set_exception(e)

    def _pop(self, rid):
        with self._lock:
            return self._results.pop(rid, None)

    # -- observability endpoint ------------------------------------------
    def start_http(self, port: int = 0, host: str = "127.0.0.1") -> int:
        """Expose the process metrics over HTTP for a Prometheus scrape:
        ``GET /metrics`` serves the text exposition of the global
        registry, ``GET /metrics.json`` the JSON snapshot including the
        step timeline. port=0 picks a free port; returns the bound port.
        """
        if self._http is not None:
            return self._http.server_address[1]
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        from .observability import export

        class _Handler(BaseHTTPRequestHandler):
            def do_GET(h):  # noqa: N805 — BaseHTTPRequestHandler idiom
                path = h.path.split("?", 1)[0]
                if path == "/metrics":
                    body = export.to_prometheus().encode("utf-8")
                    ctype = "text/plain; version=0.0.4; charset=utf-8"
                elif path == "/metrics.json":
                    body = export.dumps_json(indent=2).encode("utf-8")
                    ctype = "application/json"
                else:
                    h.send_response(404)
                    h.end_headers()
                    return
                h.send_response(200)
                h.send_header("Content-Type", ctype)
                h.send_header("Content-Length", str(len(body)))
                h.end_headers()
                h.wfile.write(body)

            def log_message(self, *args):  # scrape spam stays off stderr
                pass

        self._http = ThreadingHTTPServer((host, port), _Handler)
        self._http_thread = threading.Thread(
            target=self._http.serve_forever, daemon=True)
        self._http_thread.start()
        return self._http.server_address[1]

    def stop_http(self):
        if self._http is None:
            return
        self._http.shutdown()
        self._http.server_close()
        if self._http_thread is not None:
            self._http_thread.join(timeout=5)
            self._http_thread = None
        self._http = None

    def stop(self):
        self.stop_http()
        self._chan.close()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None


class _Future:
    def __init__(self):
        self._ev = threading.Event()
        self._val = None
        self._exc = None

    def set_result(self, v):
        self._val = v
        self._ev.set()

    def set_exception(self, e):
        self._exc = e
        self._ev.set()

    def result(self, timeout: Optional[float] = None):
        if not self._ev.wait(timeout):
            raise TimeoutError("predict result not ready")
        if self._exc is not None:
            raise self._exc
        return self._val
