"""paddle_tpu.checkpoint — elastic, preemption-proof training state.

The subsystem behind ``Trainer.fit(resumable=True)`` and the raw-loop
``ResumableLoop``:

- ``layout``: crash-safe on-disk checkpoint format — tmp-dir + fsync +
  atomic rename + ``_COMPLETE`` sentinel; readers can never observe a
  half-written checkpoint (a mid-write SIGKILL leaves an invisible
  ``tmp-`` partial, swept once its writer pid is dead).
- ``CheckpointManager`` (manager.py): async background writer off the
  step path with bounded staleness (``max_pending`` queued snapshots,
  block-don't-drop), retry-with-backoff on transient IO errors
  degrading to loud synchronous saves, retention GC, and the
  ``paddle_tpu_ckpt_*`` metric series.
- ``ResumableLoop`` (resume.py): restore-newest-complete + sample-exact
  data state (DataLoader epoch/offset) + RNG-stream restore, for
  loops driving the Executor directly.
- ``faults``: ``PADDLE_TPU_FAULT_*`` chaos hooks (kill/delay/IO-fail at
  named barriers) that tools/chaos_train.py arms.

Multi-host sharded state keeps its own orbax path
(``io.save_sharded_checkpoint``); this package is the single-host
(or per-host-replicated) dense story.
"""
from __future__ import annotations

from . import faults, layout  # noqa: F401
from .manager import CheckpointManager, CheckpointWriteError  # noqa: F401
from .resume import (  # noqa: F401
    CheckpointFingerprintWarning,
    CheckpointMismatchError,
    ResumableLoop,
    check_fingerprint,
)

__all__ = [
    "CheckpointManager", "CheckpointWriteError", "ResumableLoop",
    "CheckpointFingerprintWarning", "CheckpointMismatchError",
    "check_fingerprint", "layout", "faults",
]
