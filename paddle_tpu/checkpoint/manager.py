"""Async CheckpointManager: snapshots off the step path, crash-safe.

The training step's only cost is the state SNAPSHOT (a host copy of
every persistable, ``paddle_tpu_ckpt_save_ms{mode="snapshot"}``); the
npz encode, fsyncs, and atomic rename happen in a background writer
thread. Staleness is bounded, not unbounded: at most ``max_pending``
snapshots may be queued, and a ``save()`` beyond that BLOCKS the
trainer until the writer drains — a slow disk slows training, it never
silently drops checkpoints.

Failure ladder (never silent):
1. each write attempt that raises a transient error is retried up to
   ``retries`` times with exponential backoff
   (``paddle_tpu_ckpt_retries_total``);
2. a snapshot that exhausts its retries is counted
   (``paddle_tpu_ckpt_failures_total``), warned about, remembered in
   ``last_error``, and flips the manager into DEGRADED mode;
3. degraded mode writes synchronously in the caller's thread (the
   step path pays the IO, so pressure is visible) and RAISES on
   failure; a success heals back to async.

A save that was queued behind a failed one still writes — each queue
entry is independent; losing checkpoint N while N+1 lands costs
nothing (N+1 strictly supersedes it).

Restore (``restore()`` / ``restore_into()``) loads the NEWEST COMPLETE
serial: partials from a mid-write SIGKILL are invisible by
construction (layout.py), crashed tmp dirs are swept.
"""
from __future__ import annotations

import io as _io
import os
import queue
import threading
import time
import warnings
from typing import Dict, Optional, Tuple

import numpy as np

from .. import observability as obs
from . import faults, layout

__all__ = ["CheckpointManager", "CheckpointWriteError", "device_owned",
           "device_owned_tree"]


def device_owned_tree(arrays: Dict[str, "np.ndarray"]) -> Dict[str, object]:
    """XLA-owned device copies of every array in ``arrays``. Restored
    state must enter the scope as buffers XLA allocated itself: the
    executor's compiled steps DONATE state buffers, and donating a
    zero-copy view of numpy-owned memory lets XLA free/reuse memory it
    never allocated — observed as heap corruption or silently garbage
    parameters on the warm-AOT resume path.

    ``device_put`` usually copies (cheap, no compile); arrays it
    provably ALIASED instead (alignment-dependent on CPU: 16-byte-
    aligned host buffers are shared, not copied) are retried from a
    deliberately MISALIGNED host copy, which device_put must copy — a
    memcpy instead of a per-shape XLA compile. Anything still aliased
    after that (or whose ownership can't be verified) goes through one
    jitted tree-copy, whose outputs XLA allocates by construction."""
    import jax
    import jax.numpy as jnp

    def put_checked(host):
        put = jax.device_put(host)
        try:
            return put, put.unsafe_buffer_pointer() == host.ctypes.data
        except Exception:
            return put, True  # can't prove ownership: assume the worst

    def misaligned(a):
        # same bytes at an address that is NOT 16-aligned (but still
        # itemsize-aligned, as numpy requires). Impossible when the
        # itemsize is itself a multiple of 16 (complex128: every
        # itemsize-aligned offset is 16-aligned too) — those fall back
        # to the jitted copy below.
        step = max(a.itemsize, 1)
        if a.nbytes == 0 or step >= 16 or 16 % step != 0:
            return None
        buf = np.empty(a.nbytes + 16 + step, np.uint8)
        off = step
        while (buf.ctypes.data + off) % 16 == 0:
            off += step
        view = buf[off:off + a.nbytes].view(a.dtype).reshape(a.shape)
        view[...] = a
        return view

    out = {}
    still_aliased = {}
    for name, val in arrays.items():
        host = np.asarray(val)
        put, is_aliased = put_checked(host)
        if is_aliased:
            retry = misaligned(host)
            if retry is not None:
                put, is_aliased = put_checked(retry)
        if is_aliased:
            still_aliased[name] = put
        else:
            out[name] = put
    if still_aliased:
        copied = jax.jit(
            lambda tree: {k: jnp.copy(v) for k, v in tree.items()}
        )(still_aliased)
        out.update(copied)
    return out


def device_owned(val):
    """Single-array ``device_owned_tree`` (see its docstring)."""
    return device_owned_tree({"v": val})["v"]


class CheckpointWriteError(RuntimeError):
    """A checkpoint could not be written even after retries."""


def _np_name(name: str) -> str:
    # io/__init__.py convention: var names are filesystem-safe except "/"
    return name.replace("/", "%2F")


def _encode_npz(arrays: Dict[str, np.ndarray]) -> bytes:
    buf = _io.BytesIO()
    np.savez(buf, **{_np_name(k): v for k, v in arrays.items()})
    return buf.getvalue()


def _decode_npz(path: str) -> Dict[str, np.ndarray]:
    with np.load(path) as npz:
        return {k.replace("%2F", "/"): npz[k] for k in npz.files}


class CheckpointManager:
    """See the module docstring. Constructor arguments:

    directory — the checkpoint root (serial dirs live inside).
    max_num_checkpoints — retention: complete serials kept on disk.
    max_pending — queued async snapshots before save() blocks (bounded
        staleness; 0 = fully synchronous manager).
    retries / backoff_s — transient-IO retry ladder per write
        (backoff doubles per attempt).
    """

    def __init__(self, directory: str, *, max_num_checkpoints: int = 3,
                 max_pending: int = 2, retries: int = 3,
                 backoff_s: float = 0.05):
        self.directory = str(directory)
        self.max_num_checkpoints = max(int(max_num_checkpoints), 1)
        self.max_pending = max(int(max_pending), 0)
        self.retries = max(int(retries), 0)
        self.backoff_s = float(backoff_s)
        self._lock = threading.Lock()
        self._queue: "queue.Queue" = queue.Queue(maxsize=max(self.max_pending, 1))
        self._writer: Optional[threading.Thread] = None
        self._degraded = False
        self._closed = False
        self.last_error: Optional[BaseException] = None
        # snapshots accepted but not yet durably on disk: incremented
        # BEFORE a save enqueues, decremented AFTER its write finishes
        # — wait() polls this, so it can never return mid-write (an
        # idle-event design raced: a stale set() landing after a new
        # save's clear() made wait() return while the writer was still
        # encoding)
        self._inflight = 0
        # serials: never reuse a number any dir (even a partial) holds
        self._next_serial = layout.next_serial(self.directory)
        layout.sweep_stale_partials(self.directory)

    # -- snapshot ---------------------------------------------------------
    @staticmethod
    def snapshot(program, scope) -> Dict[str, np.ndarray]:
        """Copy every persistable with a value out of the scope as host
        numpy arrays — the only work the step path pays for an async
        save. Explicit copies: the executor donates state buffers back
        into the scope each step, so the writer thread must never hold
        views into live training state."""
        t0 = time.perf_counter()
        arrays = {}
        for v in program.list_vars():
            if not getattr(v, "persistable", False):
                continue
            val = scope.find_var(v.name)
            if val is not None:
                arrays[v.name] = np.array(val, copy=True)
        obs.CKPT_SAVE_MS.observe((time.perf_counter() - t0) * 1e3,
                                 mode="snapshot")
        return arrays

    # -- save -------------------------------------------------------------
    def save(self, arrays: Dict[str, np.ndarray], meta: Optional[dict] = None,
             *, block: bool = False) -> int:
        """Queue one snapshot for the background writer; returns the
        serial it will land at. Blocks when ``max_pending`` snapshots
        are already queued (or always, with ``block=True`` /
        ``max_pending=0``), and raises ``CheckpointWriteError`` when the
        manager is degraded and the synchronous write fails too."""
        if self._closed:
            raise RuntimeError("CheckpointManager is closed")
        with self._lock:
            serial = self._next_serial
            self._next_serial += 1
        if block or self.max_pending == 0 or self._degraded:
            self._write(serial, arrays, meta, mode="sync")
            return serial
        self._ensure_writer()
        with self._lock:
            self._inflight += 1
        self._queue.put((serial, arrays, meta))  # blocks at max_pending
        obs.CKPT_PENDING.set(self._queue.qsize())
        return serial

    def _ensure_writer(self):
        with self._lock:
            if self._writer is None or not self._writer.is_alive():
                self._writer = threading.Thread(
                    target=self._writer_main, name="ptpu-ckpt-writer",
                    daemon=True)
                self._writer.start()

    def _writer_main(self):
        while True:
            try:
                item = self._queue.get(timeout=0.2)
            except queue.Empty:
                if self._closed:
                    return
                continue
            if item is None:  # close() sentinel
                return
            serial, arrays, meta = item
            try:
                self._write(serial, arrays, meta, mode="async")
            except BaseException as e:  # noqa: BLE001 — ladder step 2
                self.last_error = e
                self._degraded = True
                obs.CKPT_FAILURES.inc()
                warnings.warn(
                    "async checkpoint %d failed after %d retries (%s); "
                    "degrading to synchronous saves" % (
                        serial, self.retries, e))
            finally:
                with self._lock:
                    self._inflight -= 1
                obs.CKPT_PENDING.set(self._queue.qsize())

    def _encode_files(self, arrays) -> Dict[str, bytes]:
        """Snapshot -> on-disk file set. The default is the checkpoint
        layout (one persistables npz); subclasses reuse this manager's
        whole async/retry/degrade/atomic-write machinery for other
        artifact layouts (training.stream's versioned inference-model
        exports override exactly this hook)."""
        return {layout.PERSISTABLES_FILE: _encode_npz(arrays)}

    def _write(self, serial: int, arrays, meta, *, mode: str):
        t0 = time.perf_counter()
        delay = self.backoff_s
        attempt = 0
        files = self._encode_files(arrays)  # attempt-invariant: ONCE
        while True:
            try:
                layout.write_checkpoint(
                    self.directory, serial, files, meta=meta or {})
                break
            except Exception as e:
                attempt += 1
                if attempt > self.retries:
                    obs.CKPT_SAVES.inc(mode=mode, result="error")
                    self.last_error = e
                    if mode == "sync":
                        obs.CKPT_FAILURES.inc()
                        raise CheckpointWriteError(
                            "checkpoint %d could not be written under %s "
                            "after %d attempts (%s: %s)" % (
                                serial, self.directory, attempt,
                                type(e).__name__, e)) from e
                    raise
                obs.CKPT_RETRIES.inc()
                time.sleep(delay)
                delay *= 2
        wall_ms = (time.perf_counter() - t0) * 1e3
        obs.CKPT_SAVE_MS.observe(wall_ms, mode=mode)
        obs.CKPT_SAVES.inc(mode=mode, result="ok")
        obs.CKPT_BYTES.inc(
            layout.dir_nbytes(layout.serial_dir(self.directory, serial)))
        if mode == "sync" and self._degraded:
            self._degraded = False  # healed: async resumes next save
        layout.retention_gc(self.directory, self.max_num_checkpoints)

    # -- drain / lifecycle -----------------------------------------------
    @property
    def pending(self) -> int:
        return self._queue.qsize()

    @property
    def degraded(self) -> bool:
        return self._degraded

    def wait(self, timeout: Optional[float] = None) -> bool:
        """Block until every accepted snapshot is durably on disk (or
        loudly failed) — True; or the timeout expires — False."""
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            with self._lock:
                done = self._inflight == 0
            if done:
                return True
            if deadline is not None and time.monotonic() >= deadline:
                return False
            time.sleep(0.01)

    def close(self, *, wait: bool = True):
        """Drain (by default) and stop the writer. Idempotent."""
        if self._closed:
            return
        if wait:
            self.wait()
        self._closed = True
        w = self._writer
        if w is not None and w.is_alive():
            try:
                self._queue.put_nowait(None)
            except queue.Full:
                pass
            w.join(timeout=10.0)
        self._writer = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # -- restore ----------------------------------------------------------
    def latest(self) -> int:
        """Newest complete serial on disk (-1 = none)."""
        return layout.latest_serial(self.directory)

    def restore(self, serial: Optional[int] = None
                ) -> Tuple[Dict[str, np.ndarray], dict]:
        """(arrays, meta) of the given (default: newest complete)
        serial; raises FileNotFoundError when none exists."""
        t0 = time.perf_counter()
        if serial is None:
            serial = self.latest()
        if serial < 0:
            raise FileNotFoundError(
                "no complete checkpoint under %s" % self.directory)
        path = layout.serial_dir(self.directory, serial)
        if not layout.is_complete(path):
            raise FileNotFoundError(
                "checkpoint %d under %s is incomplete (no %s sentinel)"
                % (serial, self.directory, layout.SENTINEL))
        faults.fault_point("ckpt.before_restore")
        arrays = _decode_npz(os.path.join(path, layout.PERSISTABLES_FILE))
        meta = layout.read_meta(path)
        # the serial the arrays ACTUALLY came from (re-scanning latest()
        # later could race a concurrent writer publishing a newer one)
        meta["_serial"] = serial
        obs.CKPT_RESTORE_MS.observe((time.perf_counter() - t0) * 1e3)
        return arrays, meta

    def restore_into(self, scope, *, serial: Optional[int] = None
                     ) -> Optional[dict]:
        """Load the newest complete checkpoint's arrays into ``scope``
        and return its meta; None when no checkpoint exists (a fresh
        run)."""
        try:
            arrays, meta = self.restore(serial=serial)
        except FileNotFoundError:
            return None
        for name, val in device_owned_tree(arrays).items():
            scope.set_var(name, val)
        meta = dict(meta)  # "_serial" already set by restore()
        meta["_restored_names"] = sorted(arrays)
        return meta
