"""Fault injection for chaos testing (PADDLE_TPU_FAULT_*).

Named barriers (``fault_point("ckpt.before_rename")``) are sprinkled
through the checkpoint writer; environment variables arm them so a test
can kill, stall, or fail the process at an EXACT instant instead of
racing a timer against the scheduler:

- ``PADDLE_TPU_FAULT_KILL=point[:nth]`` — SIGKILL this process the
  nth time (default first) the point is hit. A real SIGKILL: no atexit,
  no finally blocks, exactly what a preempted TPU VM sees.
- ``PADDLE_TPU_FAULT_DELAY=point:seconds`` — sleep at the point
  (widens race windows for kill-from-outside tests).
- ``PADDLE_TPU_FAULT_IO=point[:count]`` — raise ``InjectedIOError``
  (an OSError) at the point for its first ``count`` hits (default 1),
  then behave normally — the transient-IO-failure retry path.

Several specs are comma-separated within each variable. Hit counters
are per-process, keyed by point name. The env is re-read on every hit
so a parent can arm a child through ``subprocess`` env alone; the parse
is a few string ops — noise next to the IO these barriers decorate.
"""
from __future__ import annotations

import os
import signal
import time
from typing import Dict

__all__ = ["fault_point", "InjectedIOError", "hits", "reset"]


class InjectedIOError(OSError):
    """The injected transient IO failure (an OSError so real retry
    paths treat it exactly like disk trouble)."""


_HITS: Dict[str, int] = {}


def hits(point: str) -> int:
    """How many times this process has crossed ``point``."""
    return _HITS.get(point, 0)


def reset():
    """Zero every hit counter — for in-process tests that arm a fault
    AFTER the point has already been crossed (``nth``/``count`` specs
    count from process start otherwise). Subprocess chaos runs arm the
    env before exec and never need this."""
    _HITS.clear()


def _specs(var: str):
    raw = os.environ.get(var, "")
    for part in raw.split(","):
        part = part.strip()
        if not part:
            continue
        name, _, arg = part.partition(":")
        yield name, arg


def fault_point(point: str):
    """Cross a named barrier: apply any armed delay/IO-failure/kill."""
    _HITS[point] = n = _HITS.get(point, 0) + 1
    for name, arg in _specs("PADDLE_TPU_FAULT_DELAY"):
        if name == point:
            try:
                time.sleep(float(arg or 0.1))
            except ValueError:
                time.sleep(0.1)
    for name, arg in _specs("PADDLE_TPU_FAULT_IO"):
        if name == point:
            try:
                count = int(arg) if arg else 1
            except ValueError:
                count = 1
            if n <= count:
                raise InjectedIOError(
                    "injected IO failure at %s (hit %d/%d)"
                    % (point, n, count))
    for name, arg in _specs("PADDLE_TPU_FAULT_KILL"):
        if name == point:
            try:
                nth = int(arg) if arg else 1
            except ValueError:
                nth = 1
            if n >= nth:
                os.kill(os.getpid(), signal.SIGKILL)
