"""ResumableLoop: elastic training for raw ``Executor`` loops.

``Trainer.fit(resumable=True)`` packages the same contract for the
high-level API; this helper is for code that drives ``Executor.run`` /
``run_loop`` directly (benches, custom loops, the chaos harness):

    loop = ResumableLoop(exe, program, ckpt_dir, loader=loader,
                         step_interval=10)
    for epoch in loop.epochs(num_epochs):
        for feed in loop.skip(batches_for(epoch)):
            exe.run(program, feed=feed, fetch_list=[loss])
            loop.step_done()
        loop.end_epoch()
    loop.close()

Construction restores the newest COMPLETE checkpoint when one exists:
persistables back into the scope, the per-program RNG step fold back
into the executor (stochastic ops replay the exact stream), the
DataLoader's epoch/offset state (sample-exact: the resumed epoch
continues at the next untrained batch), and the epoch/step counters.
``step_done()`` then async-checkpoints every ``step_interval`` batches
through the CheckpointManager; a SIGKILL at any instant costs at most
``step_interval`` batches of recompute and can never corrupt the
newest checkpoint or duplicate/drop a sample.
"""
from __future__ import annotations

import itertools
import os
import warnings
from typing import Iterable, Optional

from ..framework.scope import global_scope
from .manager import CheckpointManager

__all__ = ["ResumableLoop", "CheckpointFingerprintWarning",
           "CheckpointMismatchError", "check_fingerprint", "build_meta"]


def build_meta(program, executor, *, epoch: int, offset: int,
               global_step: int, loader=None,
               extra: Optional[dict] = None) -> dict:
    """The ONE checkpoint-meta schema every resume consumer reads —
    ResumableLoop and Trainer.fit both write through here, so the
    fields (epoch / offset / global_step / rng_step / fingerprint /
    persistable_names / data_state) cannot diverge between writers."""
    meta = {
        "epoch": int(epoch),
        "offset": int(offset),
        "global_step": int(global_step),
        "fingerprint": program.fingerprint(),
        "persistable_names": sorted(
            v.name for v in program.list_vars()
            if getattr(v, "persistable", False)),
    }
    if hasattr(executor, "program_steps"):
        meta["rng_step"] = executor.program_steps(program)
    if loader is not None and hasattr(loader, "state_dict"):
        meta["data_state"] = loader.state_dict()
    if extra:
        meta.update(extra)
    return meta


class CheckpointFingerprintWarning(UserWarning):
    """Stable category for program-fingerprint mismatches on restore
    (pin it with ``pytest.warns`` / ``filterwarnings``)."""


class CheckpointMismatchError(RuntimeError):
    """Strict-mode restore refused a checkpoint written by a different
    program version."""


def _strict_env() -> bool:
    return os.environ.get("PADDLE_TPU_CKPT_STRICT", "0") == "1"


def check_fingerprint(meta: dict, program, *, strict: Optional[bool] = None,
                      saved_names: Optional[Iterable[str]] = None,
                      current_names: Optional[Iterable[str]] = None):
    """Compare a checkpoint meta's program fingerprint against the
    program about to consume it. ``strict=None`` defers to
    ``PADDLE_TPU_CKPT_STRICT=1``; strict raises CheckpointMismatchError
    with both fingerprints and the differing persistable names,
    non-strict warns (CheckpointFingerprintWarning) and loads anyway
    (var-name matched)."""
    saved_fp = meta.get("fingerprint")
    if saved_fp is None:
        return
    cur_fp = program.fingerprint()
    if saved_fp == cur_fp:
        return
    if strict is None:
        strict = _strict_env()
    saved = set(saved_names or meta.get("persistable_names") or ())
    cur = set(current_names or
              (v.name for v in program.list_vars()
               if getattr(v, "persistable", False)))
    only_ckpt = sorted(saved - cur)
    only_prog = sorted(cur - saved)
    detail = ""
    if saved:
        detail = ("; vars only in checkpoint: %s; vars only in program: %s"
                  % (only_ckpt or "none", only_prog or "none"))
    msg = ("checkpoint was written by a different program version "
           "(checkpoint fingerprint %s, current %s)%s" % (
               saved_fp, cur_fp, detail))
    if strict:
        raise CheckpointMismatchError(msg)
    warnings.warn(msg + "; loading anyway (var-name matched)",
                  CheckpointFingerprintWarning, stacklevel=3)


class ResumableLoop:
    """See the module docstring."""

    def __init__(self, executor, program, checkpoint_dir: str, *,
                 scope=None, manager: Optional[CheckpointManager] = None,
                 loader=None, step_interval: int = 10,
                 max_num_checkpoints: int = 3, max_pending: int = 2,
                 strict: Optional[bool] = None):
        self.exe = executor
        self.program = program
        self.scope = scope if scope is not None else global_scope()
        self.loader = loader
        self.step_interval = max(int(step_interval), 1)
        self.manager = manager or CheckpointManager(
            checkpoint_dir, max_num_checkpoints=max_num_checkpoints,
            max_pending=max_pending)
        self.epoch = 0
        self.offset = 0  # batches completed in the current epoch
        self.global_step = 0
        self.resumed_meta = None

        meta = self.manager.restore_into(self.scope)
        if meta is not None:
            check_fingerprint(meta, program, strict=strict)
            self.epoch = int(meta.get("epoch", 0))
            self.offset = int(meta.get("offset", 0))
            self.global_step = int(meta.get("global_step", 0))
            rng_step = meta.get("rng_step")
            if rng_step is not None and hasattr(executor,
                                                "set_program_steps"):
                executor.set_program_steps(program, int(rng_step))
            data_state = meta.get("data_state")
            if loader is not None and data_state:
                loader.load_state_dict(data_state)
            self.resumed_meta = meta

    # -- iteration --------------------------------------------------------
    def epochs(self, num_epochs: int):
        """Epoch ids still to train (resume-aware)."""
        return range(self.epoch, int(num_epochs))

    def skip(self, batches: Iterable):
        """Apply the resumed batch offset to a plain per-epoch batch
        iterable. A DataLoader given at construction already skips
        inside its workers (load_state_dict), so this is a no-op then —
        iterate the loader directly."""
        it = iter(batches)
        if self.offset and self.loader is None and self.epoch == (
                self.resumed_meta or {}).get("epoch", -1):
            it = itertools.islice(it, self.offset, None)
        return it

    # -- progress ---------------------------------------------------------
    def _meta(self, extra: Optional[dict] = None) -> dict:
        return build_meta(self.program, self.exe, epoch=self.epoch,
                          offset=self.offset,
                          global_step=self.global_step,
                          loader=self.loader, extra=extra)

    def step_done(self, batches: int = 1, extra_meta: Optional[dict] = None):
        """Record ``batches`` trained batches; checkpoints (async) when
        the global step crosses the step_interval cadence."""
        before = self.global_step // self.step_interval
        self.offset += int(batches)
        self.global_step += int(batches)
        if self.global_step // self.step_interval != before:
            self.save_now(extra_meta=extra_meta)

    def end_epoch(self, extra_meta: Optional[dict] = None):
        """Close the epoch: bump the counter, reset the offset, and
        checkpoint the boundary (so a restart never replays a finished
        epoch)."""
        self.epoch += 1
        self.offset = 0
        self.save_now(extra_meta=extra_meta)

    def save_now(self, *, block: bool = False,
                 extra_meta: Optional[dict] = None) -> int:
        """Snapshot + queue a checkpoint right now (the cadence-driven
        path calls this; explicit calls are fine too)."""
        arrays = self.manager.snapshot(self.program, self.scope)
        return self.manager.save(arrays, self._meta(extra_meta),
                                 block=block)

    def close(self, *, wait: bool = True):
        self.manager.close(wait=wait)
