"""Crash-safe checkpoint directory layout.

One serial-numbered directory per checkpoint, written so that a reader
can NEVER observe a half-written checkpoint as valid:

    <checkpoint_dir>/
      checkpoint_12/                 # complete (has the sentinel)
        __persistables__.npz         # every persistable, one npz
        meta.json                    # step/epoch/data state/fingerprint
        _COMPLETE                    # sentinel: written LAST, pre-rename
      tmp-checkpoint_13.8741.x3f2/   # in-progress or crashed partial

Write protocol (``write_checkpoint``): create a ``tmp-`` sibling, write
every file into it, fsync each file AND the tmp directory, write the
``_COMPLETE`` sentinel, then atomically ``os.rename`` the tmp dir onto
its final serial name and fsync the parent. A crash (SIGKILL included)
at ANY barrier leaves either a previous complete checkpoint untouched
plus a ``tmp-`` partial (ignored by every reader, swept once its writer
pid is dead), or the new complete checkpoint. The sentinel is belt and
braces on top of the rename: a directory that was *copied* into place
(rsync without the sentinel file yet, a restored backup cut short)
is still rejected.

Readers (``complete_serials`` / ``latest_serial``) only ever see
directories that match the serial pattern AND contain the sentinel —
legacy sentinel-less partials from the old in-place writer are skipped,
never loaded, never raised on.
"""
from __future__ import annotations

import errno
import json
import os
import re
import shutil
import time
import uuid
from typing import Callable, Dict, List, Optional, Tuple

from . import faults

CKPT_PREFIX = "checkpoint_"
TMP_PREFIX = "tmp-"
SENTINEL = "_COMPLETE"
PERSISTABLES_FILE = "__persistables__.npz"
META_FILE = "meta.json"


def serial_dir(checkpoint_dir: str, serial: int) -> str:
    return os.path.join(checkpoint_dir, "%s%d" % (CKPT_PREFIX, serial))


def is_complete(path: str) -> bool:
    """A checkpoint directory counts only once its sentinel exists."""
    return os.path.isfile(os.path.join(path, SENTINEL))


def _fsync_path(path: str):
    """fsync a file or directory; best-effort on filesystems that refuse
    directory fds (the rename itself is still atomic there)."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def write_file_durable(path: str, data: bytes):
    """Write + flush + fsync one file (contents durable before any
    rename publishes the directory)."""
    with open(path, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())


def all_serials(checkpoint_dir: str) -> List[int]:
    """Every numbered directory, complete or not (serial allocation must
    never reuse a partial's number)."""
    if not os.path.isdir(checkpoint_dir):
        return []
    out = []
    for entry in os.listdir(checkpoint_dir):
        m = re.fullmatch(CKPT_PREFIX + r"(\d+)", entry)
        if m:
            out.append(int(m.group(1)))
    return sorted(out)


def complete_serials(checkpoint_dir: str) -> List[int]:
    """Serials safe to load: numbered AND sentinel-complete."""
    return [s for s in all_serials(checkpoint_dir)
            if is_complete(serial_dir(checkpoint_dir, s))]


def latest_serial(checkpoint_dir: str) -> int:
    """Newest COMPLETE serial, -1 when none exist. Sentinel-less dirs
    (legacy in-place partial writes) and tmp- dirs are invisible here —
    but a directory holding ONLY sentinel-less serials warns loudly:
    that is either a pre-atomic-writer checkpoint set (inspect/migrate
    with tools/ckpt_ls.py, never silently restart from scratch) or
    every save so far has crashed mid-write."""
    serials = complete_serials(checkpoint_dir)
    if not serials:
        legacy = all_serials(checkpoint_dir)
        if legacy:
            import warnings

            warnings.warn(
                "checkpoint dir %s holds %d serial dir(s) but none has "
                "a %s sentinel — pre-atomic-writer checkpoints or "
                "crashed saves; they will NOT be loaded (inspect with "
                "tools/ckpt_ls.py)" % (
                    checkpoint_dir, len(legacy), SENTINEL))
        return -1
    return serials[-1]


def next_serial(checkpoint_dir: str) -> int:
    """Next unused serial (counts partials too, so a crashed slot is
    never renamed onto)."""
    serials = all_serials(checkpoint_dir)
    return (serials[-1] + 1) if serials else 0


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
    except OSError as e:
        return e.errno == errno.EPERM  # exists, owned by someone else
    return True


def stale_partials(checkpoint_dir: str) -> List[str]:
    """tmp- partials whose writer process is gone: crashed mid-write,
    safe to sweep. A live writer's tmp dir (its pid answers signal 0) is
    left alone."""
    if not os.path.isdir(checkpoint_dir):
        return []
    out = []
    for entry in os.listdir(checkpoint_dir):
        if not entry.startswith(TMP_PREFIX):
            continue
        m = re.search(r"\.(\d+)\.[0-9a-f]+$", entry)
        if m and _pid_alive(int(m.group(1))):
            continue
        out.append(os.path.join(checkpoint_dir, entry))
    return out


def sweep_stale_partials(checkpoint_dir: str) -> List[str]:
    """Remove crashed partials; returns what was removed."""
    removed = []
    for path in stale_partials(checkpoint_dir):
        shutil.rmtree(path, ignore_errors=True)
        removed.append(path)
    return removed


def write_checkpoint(
    checkpoint_dir: str,
    serial: int,
    files: Dict[str, bytes],
    *,
    meta: Optional[dict] = None,
    fault: Callable[[str], None] = faults.fault_point,
) -> str:
    """Write one checkpoint atomically; returns the final directory.

    ``files`` maps file name -> bytes (e.g. the persistables npz).
    ``meta`` (json-serialized to meta.json) rides along when given.
    Named fault barriers (``ckpt.before_files`` / ``ckpt.after_files`` /
    ``ckpt.before_sentinel`` / ``ckpt.before_rename`` /
    ``ckpt.after_rename``) let the chaos harness kill or delay the
    writer at every interesting instant.
    """
    os.makedirs(checkpoint_dir, exist_ok=True)
    final = serial_dir(checkpoint_dir, serial)
    tmp = os.path.join(
        checkpoint_dir, "%s%s%d.%d.%s" % (
            TMP_PREFIX, CKPT_PREFIX, serial, os.getpid(),
            uuid.uuid4().hex[:8]))
    os.makedirs(tmp)
    # a CRASH (SIGKILL) anywhere below leaves the tmp partial for
    # post-mortem (ckpt_ls lists it; sweep_stale_partials retires it
    # once this pid is gone) — nothing CAN clean up then. A python
    # EXCEPTION, by contrast, cleans its own tmp dir: a retrying writer
    # would otherwise strand one full-size partial per failed attempt
    # for the process lifetime (live-pid partials are never swept).
    # `final` only ever appears via the rename — the single
    # publication point.
    try:
        fault("ckpt.before_files")
        nbytes = 0
        for name, data in files.items():
            write_file_durable(os.path.join(tmp, name), data)
            nbytes += len(data)
        if meta is not None:
            blob = json.dumps(meta, sort_keys=True).encode()
            write_file_durable(os.path.join(tmp, META_FILE), blob)
            nbytes += len(blob)
        fault("ckpt.after_files")
        fault("ckpt.before_sentinel")
        write_file_durable(
            os.path.join(tmp, SENTINEL),
            json.dumps({"v": 1, "nbytes": nbytes,
                        "completed_at": time.time()}).encode())
        _fsync_path(tmp)
        fault("ckpt.before_rename")
        os.rename(tmp, final)
    except BaseException:
        shutil.rmtree(tmp, ignore_errors=True)
        raise
    _fsync_path(checkpoint_dir)
    fault("ckpt.after_rename")
    return final


def read_meta(path: str) -> dict:
    with open(os.path.join(path, META_FILE)) as f:
        return json.load(f)


def retention_gc(checkpoint_dir: str, keep: int) -> List[int]:
    """Delete all but the newest ``keep`` COMPLETE checkpoints, plus
    crashed ``tmp-`` partials of dead writers; returns the serials
    removed. Sentinel-less NUMBERED dirs are deliberately left alone:
    this writer never creates them, so they are either pre-atomic-
    writer checkpoints (an operator may still want to migrate their
    contents) or evidence of a crash worth inspecting — ``ckpt_ls``
    lists them as PARTIAL, readers skip them, and their serial numbers
    are never reused. Destroying data the new writer did not create is
    not GC's call."""
    removed = []
    complete = complete_serials(checkpoint_dir)
    for s in complete[:-keep] if keep > 0 else []:
        shutil.rmtree(serial_dir(checkpoint_dir, s), ignore_errors=True)
        removed.append(s)
    sweep_stale_partials(checkpoint_dir)
    return removed


def dir_nbytes(path: str) -> int:
    total = 0
    for root, _dirs, names in os.walk(path):
        for n in names:
            try:
                total += os.path.getsize(os.path.join(root, n))
            except OSError:
                pass
    return total


def list_entries(checkpoint_dir: str) -> List[Tuple[str, Optional[int], bool]]:
    """(path, serial_or_None_for_partials, complete) for every numbered
    dir and tmp- partial — the ckpt_ls enumeration."""
    if not os.path.isdir(checkpoint_dir):
        return []
    out = []
    for entry in sorted(os.listdir(checkpoint_dir)):
        path = os.path.join(checkpoint_dir, entry)
        m = re.fullmatch(CKPT_PREFIX + r"(\d+)", entry)
        if m:
            out.append((path, int(m.group(1)), is_complete(path)))
        elif entry.startswith(TMP_PREFIX):
            out.append((path, None, False))
    return out
