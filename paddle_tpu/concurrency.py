"""CSP-style concurrency primitives (reference:
python/paddle/fluid/concurrency.py — Go / Channel / Select ops).

TPU-native divergence: the reference compiles Go blocks and channel ops
INTO the Program (C++ threads run sub-blocks against the scope). Under
XLA the device computation is one compiled function, so CSP belongs on
the HOST side of the pipeline: ``Go`` runs a Python callable on a daemon
thread, channels are the C++ runtime's bounded blocking channel
(runtime/runtime.cc Channel — the same one behind the reader pipeline)
carrying pickled Python values, and ``Select`` polls cases like the
reference's fluid.Select. Typical use is producer/consumer structure
around ``Executor.run`` (e.g. feeding a py_reader from several workers).
"""
from __future__ import annotations

import pickle
import threading
import time
from typing import Any, Callable, Optional, Tuple

from .runtime.recordio import Channel as _ByteChannel

__all__ = ["Go", "make_channel", "channel_send", "channel_recv",
           "channel_close", "Select"]


class _Channel:
    """Typed channel over the runtime byte channel. ``capacity=0``
    (unbuffered in the reference/Go sense) is approximated with a
    1-slot buffer."""

    def __init__(self, dtype=None, capacity: int = 0):
        self.dtype = dtype
        self.capacity = max(1, int(capacity))
        self.closed = False
        self._ch = _ByteChannel(self.capacity)

    def send(self, value) -> bool:
        return self._ch.send(pickle.dumps(value, protocol=4))

    def recv(self) -> Tuple[Any, bool]:
        data = self._ch.recv()
        if data is None:
            return None, False
        return pickle.loads(data), True

    def qsize(self) -> int:
        return self._ch.qsize()

    def close(self):
        self.closed = True
        self._ch.close()


def make_channel(dtype=None, capacity: int = 0) -> _Channel:
    """reference concurrency.py:make_channel."""
    return _Channel(dtype, capacity)


def channel_send(channel: _Channel, value, is_copy: bool = False) -> bool:
    """Blocking send; returns False once the channel is closed. `is_copy`
    is accepted for parity (values are serialized, always a copy)."""
    return channel.send(value)


def channel_recv(channel: _Channel, return_value=None) -> Tuple[Any, bool]:
    """Blocking receive -> (value, ok); ok=False once closed and drained
    (then `return_value` is returned as the value)."""
    val, ok = channel.recv()
    return (val if ok else return_value), ok


def channel_close(channel: _Channel):
    channel.close()


class Go:
    """Run work concurrently (reference concurrency.py:Go). Two forms:

    - ``Go(fn, *args)`` — start `fn` immediately on a daemon thread.
    - ``with Go() as g: g.run(fn, *args)`` — the reference's block-guard
      shape; every `run` inside the block is launched on exit.

    ``join()`` waits; the callable's return value is at ``.result`` (or
    its exception re-raised)."""

    def __init__(self, fn: Optional[Callable] = None, *args, **kwargs):
        self._pending = []
        self._threads = []
        self._results = []
        self._errors = []
        self._in_block = False
        if fn is not None:
            self._spawn(fn, args, kwargs)

    def _spawn(self, fn, args, kwargs):
        idx = len(self._results)
        self._results.append(None)

        def body():
            try:
                self._results[idx] = fn(*args, **kwargs)
            except BaseException as e:  # surfaced on join()
                self._results[idx] = e  # .result shows which task died
                self._errors.append((idx, e))

        t = threading.Thread(target=body, daemon=True)
        t.start()
        self._threads.append(t)

    def run(self, fn: Callable, *args, **kwargs):
        """Inside the with-block: queue `fn`, launched together on block
        exit (the reference's Go-block shape). Outside any block: launch
        immediately (a bare go statement) — nothing is ever silently
        queued without a block exit to drain it."""
        if self._in_block:
            self._pending.append((fn, args, kwargs))
        else:
            self._spawn(fn, args, kwargs)

    def __enter__(self):
        self._in_block = True
        return self

    def __exit__(self, exc_type, exc, tb):
        self._in_block = False
        if exc_type is None:
            for fn, args, kwargs in self._pending:
                self._spawn(fn, args, kwargs)
            self._pending = []
        return False

    def join(self, timeout: Optional[float] = None):
        deadline = None if timeout is None else time.monotonic() + timeout
        for t in self._threads:
            t.join(timeout if deadline is None
                   else max(0.0, deadline - time.monotonic()))
            if t.is_alive():
                raise TimeoutError(
                    "Go.join timed out after %.3fs with work still running"
                    % timeout)
        if len(self._errors) == 1:
            raise self._errors[0][1]
        if self._errors:
            raise RuntimeError(
                "%d Go tasks failed: %s" % (
                    len(self._errors),
                    "; ".join("task %d: %r" % (i, e)
                              for i, e in self._errors))
            ) from self._errors[0][1]
        return self.result

    @property
    def result(self):
        return self._results[0] if len(self._results) == 1 else list(self._results)


class Select:
    """Wait on multiple channel operations; runs the callback of the first
    ready case (reference concurrency.py:Select)::

        sel = Select()
        sel.case_recv(ch_a, lambda v: ...)
        sel.case_send(ch_b, value, lambda: ...)
        sel.default(lambda: ...)   # optional: makes run() non-blocking
        sel.run()
    """

    def __init__(self):
        self._cases = []
        self._default = None

    def case_recv(self, channel: _Channel, callback: Callable[[Any], Any]):
        self._cases.append(("recv", channel, None, callback))
        return self

    def case_send(self, channel: _Channel, value, callback: Callable[[], Any]):
        self._cases.append(("send", channel, value, callback))
        return self

    def default(self, callback: Callable[[], Any]):
        self._default = callback
        return self

    def run(self, poll_interval: float = 0.001, timeout: Optional[float] = None):
        """Poll cases until one fires; returns its callback's result.
        recv fires when a value (or close) is available; send fires when
        buffer space is free.

        Single-selector assumption (both directions): this Select must be
        the only consumer (for recv cases) / producer (for send cases) of
        its channels. A competitor draining or filling a channel between
        the readiness check and the blocking call makes that call block
        past `timeout` (the underlying channel has no timed recv/send)."""
        if not self._cases and self._default is None:
            raise ValueError("Select has no cases")
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            for kind, ch, value, cb in self._cases:
                if kind == "recv":
                    # ready when a value is queued, or the channel was
                    # closed (then recv returns (None, ok=False) at once)
                    if ch.qsize() > 0 or ch.closed:
                        val, ok = ch.recv()
                        return cb(val if ok else None)
                else:
                    # ready when buffer space is free (single-selector
                    # assumption: nobody else fills the gap between the
                    # check and the send); a closed channel rejects the
                    # send — fire the callback only on actual delivery
                    if ch.closed or ch.qsize() < ch.capacity:
                        if ch.send(value):
                            return cb()
                        raise RuntimeError(
                            "Select: send on closed channel")
            if self._default is not None:
                return self._default()
            if deadline is not None and time.monotonic() >= deadline:
                raise TimeoutError("Select timed out")
            time.sleep(poll_interval)
