"""Python-side metric accumulators.

Reference capability: python/paddle/fluid/metrics.py — numpy state folded
in from fetched step outputs; nothing here touches the device (fetches
are already host arrays), so the API carries over while the accumulator
internals are vectorized numpy.
"""
from __future__ import annotations

import numpy as np

__all__ = [
    "MetricBase", "CompositeMetric", "Precision", "Recall", "Accuracy",
    "ChunkEvaluator", "EditDistance", "Auc", "DetectionMAP",
]


def _scalar(v, kind=float):
    """First element of a fetch as a python scalar (fetches arrive as
    0-d/1-element arrays or plain numbers)."""
    return kind(np.asarray(v).reshape(-1)[0])


def _require_numeric(name, v):
    """Accept numbers and ndarrays; reject anything a fetch can't be."""
    if isinstance(v, (int, float, np.generic, np.ndarray)):
        return
    raise ValueError(
        "%s expects a python number or numpy array, got %s"
        % (name, type(v).__name__))


def _require_weight(name, w):
    if isinstance(w, (int, float, np.generic)) or (
            isinstance(w, np.ndarray) and w.size == 1):
        return
    raise ValueError(
        "%s expects a scalar weight, got %s" % (name, type(w).__name__))


class MetricBase(object):
    """Base: reset() zeroes the numpy state, update() folds in a step's
    outputs, eval() returns the aggregate (capability of
    metrics.py:MetricBase). Public (non-underscore) attributes are the
    accumulator state."""

    def __init__(self, name):
        self._name = str(name) if name is not None else self.__class__.__name__

    def __str__(self):
        return self._name

    def reset(self):
        for attr, value in list(self.__dict__.items()):
            if attr.startswith("_"):
                continue
            if isinstance(value, (np.ndarray, np.generic)):
                zero = np.zeros_like(value)
            elif isinstance(value, (int, float)):
                zero = type(value)(0)
            else:
                zero = None
            setattr(self, attr, zero)

    def get_config(self):
        states = {a: v for a, v in self.__dict__.items()
                  if not a.startswith("_")}
        return {"name": self._name, "states": states}

    def update(self, preds, labels):
        raise NotImplementedError(
            "%s must implement update()" % self.__class__.__name__)

    def eval(self):
        raise NotImplementedError(
            "%s must implement eval()" % self.__class__.__name__)


class CompositeMetric(MetricBase):
    """Hold several metrics updated with the same (preds, labels)."""

    def __init__(self, name=None):
        super().__init__(name)
        self._metrics = []

    def add_metric(self, metric):
        if isinstance(metric, MetricBase):
            self._metrics.append(metric)
            return
        raise ValueError(
            "CompositeMetric.add_metric wants a MetricBase instance, "
            "got %s" % type(metric).__name__)

    def update(self, preds, labels):
        for m in self._metrics:
            m.update(preds, labels)

    def eval(self):
        return [m.eval() for m in self._metrics]


class Precision(MetricBase):
    """Binary precision over 0/1 preds vs labels."""

    def __init__(self, name=None):
        super().__init__(name)
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        preds = np.rint(np.asarray(preds)).astype(np.int64).reshape(-1)
        labels = np.asarray(labels).astype(np.int64).reshape(-1)
        self.tp += int(np.sum((preds == 1) & (labels == 1)))
        self.fp += int(np.sum((preds == 1) & (labels == 0)))

    def eval(self):
        predicted_pos = self.tp + self.fp
        return float(self.tp) / predicted_pos if predicted_pos else 0.0


class Recall(MetricBase):
    """Binary recall over 0/1 preds vs labels."""

    def __init__(self, name=None):
        super().__init__(name)
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        preds = np.rint(np.asarray(preds)).astype(np.int64).reshape(-1)
        labels = np.asarray(labels).astype(np.int64).reshape(-1)
        self.tp += int(np.sum((preds == 1) & (labels == 1)))
        self.fn += int(np.sum((preds == 0) & (labels == 1)))

    def eval(self):
        actual_pos = self.tp + self.fn
        return float(self.tp) / actual_pos if actual_pos else 0.0


class Accuracy(MetricBase):
    """Weighted running mean of per-batch accuracy values (pairs with
    layers.accuracy fetches)."""

    def __init__(self, name=None):
        super().__init__(name)
        self.value = 0.0
        self.weight = 0.0

    def update(self, value, weight):
        _require_numeric("Accuracy.update(value)", value)
        _require_weight("Accuracy.update(weight)", weight)
        self.value += _scalar(value) * weight
        self.weight += weight

    def eval(self):
        if self.weight == 0:
            raise ValueError(
                "Accuracy has accumulated nothing — feed it the fetched "
                "layers.accuracy output via update() before eval()")
        return self.value / self.weight


class ChunkEvaluator(MetricBase):
    """Accumulate (num_infer, num_label, num_correct) chunk counts from the
    layers.chunk_eval fetches; eval() -> (precision, recall, f1)."""

    def __init__(self, name=None):
        super().__init__(name)
        self.num_infer_chunks = 0
        self.num_label_chunks = 0
        self.num_correct_chunks = 0

    def update(self, num_infer_chunks, num_label_chunks, num_correct_chunks):
        for tag, v in (("num_infer_chunks", num_infer_chunks),
                       ("num_label_chunks", num_label_chunks),
                       ("num_correct_chunks", num_correct_chunks)):
            _require_numeric("ChunkEvaluator.update(%s)" % tag, v)
        self.num_infer_chunks += _scalar(num_infer_chunks, int)
        self.num_label_chunks += _scalar(num_label_chunks, int)
        self.num_correct_chunks += _scalar(num_correct_chunks, int)

    def eval(self):
        correct = float(self.num_correct_chunks)
        precision = correct / self.num_infer_chunks \
            if self.num_infer_chunks else 0.0
        recall = correct / self.num_label_chunks \
            if self.num_label_chunks else 0.0
        f1_score = 2 * precision * recall / (precision + recall) \
            if self.num_correct_chunks else 0.0
        return precision, recall, f1_score


class EditDistance(MetricBase):
    """Accumulate layers.edit_distance fetches; eval() -> (avg distance,
    instance error rate)."""

    def __init__(self, name=None):
        super().__init__(name)
        self.total_distance = 0.0
        self.seq_num = 0
        self.instance_error = 0

    def update(self, distances, seq_num):
        distances = np.asarray(distances, np.float64)
        n = _scalar(seq_num, int)
        self.seq_num += n
        self.instance_error += n - int(np.sum(distances == 0))
        self.total_distance += float(np.sum(distances))

    def eval(self):
        if self.seq_num == 0:
            raise ValueError(
                "EditDistance has accumulated nothing — feed it the fetched "
                "layers.edit_distance outputs via update() before eval()")
        return (self.total_distance / self.seq_num,
                self.instance_error / float(self.seq_num))


class Auc(MetricBase):
    """Threshold-bucketed ROC AUC over (N, C) probabilities (the last
    column is the positive-class probability). Buckets accumulate
    vectorized: one (T, N) comparison per update instead of a python
    loop over thresholds."""

    def __init__(self, name=None, curve="ROC", num_thresholds=200):
        super().__init__(name)
        if curve != "ROC":
            raise ValueError("only curve='ROC' is implemented")
        self._curve = curve
        self._num_thresholds = num_thresholds
        self._epsilon = 1e-6
        # threshold grid: interior points i/(T-1), endpoints nudged past
        # [0, 1] so every probability lands strictly inside the sweep
        eps = 1e-7
        self._thresholds = np.concatenate([
            [-eps],
            np.arange(1, num_thresholds - 1) / float(num_thresholds - 1),
            [1.0 + eps]])
        self.tp_list = np.zeros((num_thresholds,))
        self.fn_list = np.zeros((num_thresholds,))
        self.tn_list = np.zeros((num_thresholds,))
        self.fp_list = np.zeros((num_thresholds,))

    def update(self, preds, labels):
        preds = np.asarray(preds)
        labels = np.asarray(labels).reshape(-1)
        pos_prob = preds.reshape(preds.shape[0], -1)[:, -1]
        pred_pos = pos_prob[None, :] >= self._thresholds[:, None]  # (T, N)
        # only 0/1 labels count — sentinel labels (e.g. -1 padding rows)
        # contribute to no bucket
        is_pos = (labels == 1)[None, :]
        is_neg = (labels == 0)[None, :]
        self.tp_list += (pred_pos & is_pos).sum(axis=1)
        self.fp_list += (pred_pos & is_neg).sum(axis=1)
        self.fn_list += (~pred_pos & is_pos).sum(axis=1)
        self.tn_list += (~pred_pos & is_neg).sum(axis=1)

    def eval(self):
        eps = self._epsilon
        tpr = (self.tp_list + eps) / (self.tp_list + self.fn_list + eps)
        fpr = self.fp_list / (self.fp_list + self.tn_list + eps)
        # trapezoid over the descending-fpr sweep
        return float(np.sum((fpr[:-1] - fpr[1:]) * (tpr[:-1] + tpr[1:]) / 2.0))


class DetectionMAP(MetricBase):
    """Running mean of per-batch mAP values from layers.detection_map."""

    def __init__(self, name=None):
        super().__init__(name)
        self.value = 0.0
        self.weight = 0.0

    def update(self, value, weight=1):
        _require_numeric("DetectionMAP.update(value)", value)
        _require_weight("DetectionMAP.update(weight)", weight)
        self.value += _scalar(value) * weight
        self.weight += weight

    def eval(self):
        if self.weight == 0:
            raise ValueError(
                "DetectionMAP has accumulated nothing — feed it the fetched "
                "layers.detection_map output via update() before eval()")
        return self.value / self.weight
