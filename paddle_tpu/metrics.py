"""Python-side metric accumulators.

Reference: python/paddle/fluid/metrics.py — numpy state updated from fetched
step outputs; nothing here touches the device (fetches are already host
arrays), so the API carries over unchanged.
"""
from __future__ import annotations

import numpy as np

__all__ = [
    "MetricBase", "CompositeMetric", "Precision", "Recall", "Accuracy",
    "ChunkEvaluator", "EditDistance", "Auc", "DetectionMAP",
]


def _is_numpy_(var):
    return isinstance(var, (np.ndarray, np.generic))


def _is_number_(var):
    return isinstance(var, (int, float, np.float32, np.float64)) or (
        _is_numpy_(var) and var.size == 1)


def _is_number_or_matrix_(var):
    return _is_number_(var) or _is_numpy_(var)


class MetricBase(object):
    """Base: reset() zeroes the numpy state, update() folds in a step's
    outputs, eval() returns the aggregate (metrics.py:MetricBase)."""

    def __init__(self, name):
        self._name = str(name) if name is not None else self.__class__.__name__

    def __str__(self):
        return self._name

    def reset(self):
        states = {
            attr: value
            for attr, value in self.__dict__.items()
            if not attr.startswith("_")
        }
        for attr, value in states.items():
            if isinstance(value, int):
                setattr(self, attr, 0)
            elif isinstance(value, float):
                setattr(self, attr, 0.0)
            elif isinstance(value, (np.ndarray, np.generic)):
                setattr(self, attr, np.zeros_like(value))
            else:
                setattr(self, attr, None)

    def get_config(self):
        states = {
            attr: value
            for attr, value in self.__dict__.items()
            if not attr.startswith("_")
        }
        config = {}
        config.update({"name": self._name, "states": states})
        return config

    def update(self, preds, labels):
        raise NotImplementedError()

    def eval(self):
        raise NotImplementedError()


class CompositeMetric(MetricBase):
    """Hold several metrics updated with the same (preds, labels)."""

    def __init__(self, name=None):
        super().__init__(name)
        self._metrics = []

    def add_metric(self, metric):
        if not isinstance(metric, MetricBase):
            raise ValueError("SubMetric should be inherit from MetricBase.")
        self._metrics.append(metric)

    def update(self, preds, labels):
        for m in self._metrics:
            m.update(preds, labels)

    def eval(self):
        return [m.eval() for m in self._metrics]


class Precision(MetricBase):
    """Binary precision over 0/1 preds vs labels (metrics.py:Precision)."""

    def __init__(self, name=None):
        super().__init__(name)
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        preds = np.asarray(preds)
        labels = np.asarray(labels)
        preds = np.rint(preds).astype(np.int64).reshape(-1)
        labels = np.asarray(labels).astype(np.int64).reshape(-1)
        self.tp += int(np.sum((preds == 1) & (labels == 1)))
        self.fp += int(np.sum((preds == 1) & (labels == 0)))

    def eval(self):
        ap = self.tp + self.fp
        return float(self.tp) / ap if ap != 0 else 0.0


class Recall(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        preds = np.rint(np.asarray(preds)).astype(np.int64).reshape(-1)
        labels = np.asarray(labels).astype(np.int64).reshape(-1)
        self.tp += int(np.sum((preds == 1) & (labels == 1)))
        self.fn += int(np.sum((preds == 0) & (labels == 1)))

    def eval(self):
        recall = self.tp + self.fn
        return float(self.tp) / recall if recall != 0 else 0.0


class Accuracy(MetricBase):
    """Weighted running mean of per-batch accuracy values
    (metrics.py:Accuracy — pairs with layers.accuracy fetches)."""

    def __init__(self, name=None):
        super().__init__(name)
        self.value = 0.0
        self.weight = 0.0

    def update(self, value, weight):
        if not _is_number_or_matrix_(value):
            raise ValueError("The 'value' must be a number(int, float) or a numpy ndarray.")
        if not _is_number_(weight):
            raise ValueError("The 'weight' must be a number(int, float).")
        self.value += float(np.asarray(value).reshape(-1)[0]) * weight
        self.weight += weight

    def eval(self):
        if self.weight == 0:
            raise ValueError("There is no data in Accuracy Metrics. Please check layers.accuracy output has added to Accuracy.")
        return self.value / self.weight


class ChunkEvaluator(MetricBase):
    """Accumulate (num_infer, num_label, num_correct) chunk counts from the
    layers.chunk_eval fetches; eval() -> (precision, recall, f1)."""

    def __init__(self, name=None):
        super().__init__(name)
        self.num_infer_chunks = 0
        self.num_label_chunks = 0
        self.num_correct_chunks = 0

    def update(self, num_infer_chunks, num_label_chunks, num_correct_chunks):
        for v in (num_infer_chunks, num_label_chunks, num_correct_chunks):
            if not _is_number_or_matrix_(v):
                raise ValueError("The 'chunk counts' must be a number(int, float) or a numpy ndarray.")
        self.num_infer_chunks += int(np.asarray(num_infer_chunks).reshape(-1)[0])
        self.num_label_chunks += int(np.asarray(num_label_chunks).reshape(-1)[0])
        self.num_correct_chunks += int(np.asarray(num_correct_chunks).reshape(-1)[0])

    def eval(self):
        precision = (
            float(self.num_correct_chunks) / self.num_infer_chunks
            if self.num_infer_chunks else 0.0)
        recall = (
            float(self.num_correct_chunks) / self.num_label_chunks
            if self.num_label_chunks else 0.0)
        f1_score = (
            2 * precision * recall / (precision + recall)
            if self.num_correct_chunks else 0.0)
        return precision, recall, f1_score


class EditDistance(MetricBase):
    """Accumulate layers.edit_distance fetches; eval() -> (avg distance,
    instance error rate)."""

    def __init__(self, name=None):
        super().__init__(name)
        self.total_distance = 0.0
        self.seq_num = 0
        self.instance_error = 0

    def update(self, distances, seq_num):
        if not _is_numpy_(distances):
            distances = np.asarray(distances, np.float64)
        seq_right_count = int(np.sum(distances == 0))
        total_distance = float(np.sum(distances))
        seq_num = int(np.asarray(seq_num).reshape(-1)[0])
        self.seq_num += seq_num
        self.instance_error += seq_num - seq_right_count
        self.total_distance += total_distance

    def eval(self):
        if self.seq_num == 0:
            raise ValueError("There is no data in EditDistance Metric. Please check layers.edit_distance output has been added to EditDistance.")
        avg_distance = self.total_distance / self.seq_num
        avg_instance_error = self.instance_error / float(self.seq_num)
        return avg_distance, avg_instance_error


class Auc(MetricBase):
    """Threshold-bucketed ROC AUC over (N, 2) probabilities
    (metrics.py:Auc; the reference's python fallback path)."""

    def __init__(self, name=None, curve="ROC", num_thresholds=200):
        super().__init__(name)
        if curve != "ROC":
            raise ValueError("only curve='ROC' is implemented")
        self._curve = curve
        self._num_thresholds = num_thresholds
        self._epsilon = 1e-6
        self.tp_list = np.zeros((num_thresholds,))
        self.fn_list = np.zeros((num_thresholds,))
        self.tn_list = np.zeros((num_thresholds,))
        self.fp_list = np.zeros((num_thresholds,))

    def update(self, preds, labels):
        if not _is_numpy_(labels):
            labels = np.asarray(labels)
        if not _is_numpy_(preds):
            preds = np.asarray(preds)
        kepsilon = 1e-7
        thresholds = [
            (i + 1) * 1.0 / (self._num_thresholds - 1)
            for i in range(self._num_thresholds - 2)
        ]
        thresholds = [0.0 - kepsilon] + thresholds + [1.0 + kepsilon]
        labels = labels.reshape(-1)
        pos_prob = preds.reshape(preds.shape[0], -1)[:, -1]
        for idx_thresh, thresh in enumerate(thresholds):
            pred_pos = pos_prob >= thresh
            self.tp_list[idx_thresh] += int(np.sum(pred_pos & (labels == 1)))
            self.fp_list[idx_thresh] += int(np.sum(pred_pos & (labels == 0)))
            self.fn_list[idx_thresh] += int(np.sum(~pred_pos & (labels == 1)))
            self.tn_list[idx_thresh] += int(np.sum(~pred_pos & (labels == 0)))

    def eval(self):
        epsilon = self._epsilon
        num_thresholds = self._num_thresholds
        tpr = (self.tp_list.astype("float32") +
               epsilon) / (self.tp_list + self.fn_list + epsilon)
        fpr = self.fp_list.astype("float32") / (
            self.fp_list + self.tn_list + epsilon)

        x = fpr[:num_thresholds - 1] - fpr[1:]
        y = (tpr[:num_thresholds - 1] + tpr[1:]) / 2.0
        auc_value = float(np.sum(x * y))
        return auc_value


class DetectionMAP(MetricBase):
    """Running mean of per-batch mAP values from layers.detection_map
    (metrics.py:DetectionMAP)."""

    def __init__(self, name=None):
        super().__init__(name)
        self.value = 0.0
        self.weight = 0.0

    def update(self, value, weight=1):
        if not _is_number_or_matrix_(value):
            raise ValueError("The 'value' must be a number(int, float) or a numpy ndarray.")
        if not _is_number_(weight):
            raise ValueError("The 'weight' must be a number(int, float).")
        self.value += float(np.asarray(value).reshape(-1)[0]) * weight
        self.weight += weight

    def eval(self):
        if self.weight == 0:
            raise ValueError("There is no data in DetectionMAP Metrics.")
        return self.value / self.weight
