"""Parameter initializers (reference: python/paddle/fluid/initializer.py).

Each initializer appends an init op (fill_constant / uniform_random /
gaussian_random / assign_value) to the startup program; the executor traces
that program into one XLA computation, so all parameter init happens in a
single device program — there is no per-op init dispatch.
"""
from __future__ import annotations

import math

import numpy as np

__all__ = [
    "Initializer",
    "ConstantInitializer",
    "UniformInitializer",
    "NormalInitializer",
    "TruncatedNormalInitializer",
    "XavierInitializer",
    "MSRAInitializer",
    "BilinearInitializer",
    "Constant",
    "Uniform",
    "Normal",
    "TruncatedNormal",
    "Xavier",
    "MSRA",
    "Bilinear",
    "force_init_on_cpu",
    "init_on_cpu",
]


def force_init_on_cpu():
    # Initialization always runs through XLA; kept for API parity.
    return False


import contextlib


@contextlib.contextmanager
def init_on_cpu():
    yield


class Initializer:
    def __call__(self, var, block):
        raise NotImplementedError

    @staticmethod
    def _fan_in_out(var):
        shape = var.shape
        if len(shape) < 2:
            return (shape[0] if shape else 1,) * 2
        fan_in = shape[1] * int(np.prod(shape[2:])) if len(shape) > 2 else shape[1]
        fan_out = shape[0] * int(np.prod(shape[2:])) if len(shape) > 2 else shape[0]
        # note: for fc weights (in, out) paddle uses shape[0]=in as fan_in
        if len(shape) == 2:
            fan_in, fan_out = shape[0], shape[1]
        return fan_in, fan_out


class ConstantInitializer(Initializer):
    def __init__(self, value=0.0, force_cpu=False):
        self.value = value

    def __call__(self, var, block):
        return block.append_op(
            "fill_constant",
            outputs={"Out": [var.name]},
            attrs={"shape": list(var.shape), "dtype": var.dtype, "value": float(self.value)},
        )


class UniformInitializer(Initializer):
    def __init__(self, low=-1.0, high=1.0, seed=0):
        self.low, self.high, self.seed = low, high, seed

    def __call__(self, var, block):
        return block.append_op(
            "uniform_random",
            outputs={"Out": [var.name]},
            attrs={
                "shape": list(var.shape),
                "dtype": var.dtype,
                "min": float(self.low),
                "max": float(self.high),
                "seed": self.seed,
            },
        )


class NormalInitializer(Initializer):
    def __init__(self, loc=0.0, scale=1.0, seed=0):
        self.loc, self.scale, self.seed = loc, scale, seed

    def __call__(self, var, block):
        return block.append_op(
            "gaussian_random",
            outputs={"Out": [var.name]},
            attrs={
                "shape": list(var.shape),
                "dtype": var.dtype,
                "mean": float(self.loc),
                "std": float(self.scale),
                "seed": self.seed,
            },
        )


class TruncatedNormalInitializer(Initializer):
    def __init__(self, loc=0.0, scale=1.0, seed=0):
        self.loc, self.scale, self.seed = loc, scale, seed

    def __call__(self, var, block):
        return block.append_op(
            "truncated_gaussian_random",
            outputs={"Out": [var.name]},
            attrs={
                "shape": list(var.shape),
                "dtype": var.dtype,
                "mean": float(self.loc),
                "std": float(self.scale),
                "seed": self.seed,
            },
        )


class XavierInitializer(Initializer):
    """Glorot init (reference: initializer.py:XavierInitializer)."""

    def __init__(self, uniform=True, fan_in=None, fan_out=None, seed=0):
        self.uniform, self.fan_in, self.fan_out, self.seed = uniform, fan_in, fan_out, seed

    def __call__(self, var, block):
        fi, fo = self._fan_in_out(var)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        if self.uniform:
            limit = math.sqrt(6.0 / (fi + fo))
            return UniformInitializer(-limit, limit, self.seed)(var, block)
        std = math.sqrt(2.0 / (fi + fo))
        return NormalInitializer(0.0, std, self.seed)(var, block)


class MSRAInitializer(Initializer):
    """He/Kaiming init (reference: initializer.py:MSRAInitializer)."""

    def __init__(self, uniform=True, fan_in=None, seed=0):
        self.uniform, self.fan_in, self.seed = uniform, fan_in, seed

    def __call__(self, var, block):
        fi, _ = self._fan_in_out(var)
        fi = self.fan_in if self.fan_in is not None else fi
        if self.uniform:
            limit = math.sqrt(6.0 / fi)
            return UniformInitializer(-limit, limit, self.seed)(var, block)
        std = math.sqrt(2.0 / fi)
        return NormalInitializer(0.0, std, self.seed)(var, block)


class BilinearInitializer(Initializer):
    """Bilinear upsampling kernel init for conv_transpose (reference:
    initializer.py:BilinearInitializer)."""

    def __call__(self, var, block):
        shape = var.shape
        if len(shape) != 4:
            raise ValueError("BilinearInitializer needs a 4-D weight")
        f = math.ceil(shape[3] / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        weight = np.zeros(shape, dtype=np.float32)
        size = int(np.prod(shape))
        flat = np.arange(size)
        x = flat % shape[3]
        y = (flat // shape[3]) % shape[2]
        vals = (1 - np.abs(x / f - c)) * (1 - np.abs(y / f - c))
        weight.flat[:] = vals
        return block.append_op(
            "assign_value",
            outputs={"Out": [var.name]},
            attrs={"shape": list(shape), "dtype": var.dtype, "values": weight},
        )


# aliases matching the reference's public names
Constant = ConstantInitializer
Uniform = UniformInitializer
Normal = NormalInitializer
TruncatedNormal = TruncatedNormalInitializer
Xavier = XavierInitializer
MSRA = MSRAInitializer
Bilinear = BilinearInitializer
