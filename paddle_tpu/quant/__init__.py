"""paddle_tpu.quant — post-training int8 quantization tier.

The calibrate -> transpile -> serve flow (ROADMAP item 2; reference
lineage: the InferenceTranspiler's deploy-time rewrites, extended with
integer-arithmetic-only inference in the Jacob et al. CVPR'18 mold):

1. **Calibrate** (``calibrate.py``): stream a recordio/DataLoader
   sample through the inference program, collecting per-tensor
   activation amax for every quantizable op input and per-channel
   weight amax from the scope, into a serializable
   :class:`CalibrationTable`.
2. **Transpile** (``transpiler/passes/quantize.py``): a level-3 pass on
   the PR-11 manager rewrites ``mul``/``matmul``/``fused_fc``/
   ``conv2d`` into ``quantized_matmul``/``quantized_conv2d`` (int8
   weights materialized as persistable params, scales riding as attrs,
   int32 accumulation, fused dequant/bias/act epilogue).
3. **Serve**: ``save_inference_model(..., quantize=table)`` exports the
   quantized program; it serves through the same Predictor / AOT cache
   (distinct content fingerprint = distinct executable keys, so bf16
   and int8 coexist) — and ``DecodeServer(kv_dtype="int8")`` opts the
   KV slabs into int8 with per-(slot, position) scales (2x sequences
   per slab budget).
4. **Verify** (``parity.py``): quantized-vs-float logits tolerance and
   task-metric delta, the same A/B discipline as bench.py's O1-vs-O2
   checks; ``tools/bench_quant.py`` is the measurement instrument.
"""
from .calibrate import (  # noqa: F401
    CalibrationTable, activation_targets, calibrate, quantizable_targets,
)
from .parity import parity_report  # noqa: F401

__all__ = [
    "CalibrationTable", "activation_targets", "calibrate",
    "quantizable_targets", "parity_report",
]
