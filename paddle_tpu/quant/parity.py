"""Quantized-vs-float parity: the gate every int8 deployment runs.

Modeled on bench.py's O1-vs-O2 loss sanity checks: same feeds through
both serving paths, compared at two levels —

- **logits tolerance**: max/mean abs difference across every fetch (the
  raw numeric drift the int8 rounding introduced);
- **task-metric delta**: a scalar metric (top-1 agreement by default,
  or any caller-supplied ``metric_fn(outputs, feeds) -> float``)
  evaluated on both arms, so "is the model still the same model" is
  answered in task units, not ulps.

``parity_report`` drives two Predictors (or model dirs) and returns one
JSON-able dict; the observed ``max_abs_diff`` also lands on the
``paddle_tpu_quant_parity_max_abs_diff`` gauge so a serving fleet can
alert on quantization drift. ``tools/bench_quant.py`` embeds the same
report in every bench line — a measurement that breaks parity reports
it instead of banking a bogus speedup.
"""
from __future__ import annotations

from typing import Callable, Dict, Iterable, Optional

import numpy as np

from .. import observability as obs

__all__ = ["parity_report", "top1_agreement"]

SCHEMA = "quant_parity/1"


def _as_predictor(p):
    if isinstance(p, str):
        from ..inference import Predictor

        return Predictor(p, aot_cache=False)
    return p


def top1_agreement(base_outs, quant_outs) -> float:
    """Fraction of rows whose argmax over the FIRST fetch agrees —
    the default task metric for classifier-shaped outputs."""
    a = np.asarray(base_outs[0])
    b = np.asarray(quant_outs[0])
    if a.ndim < 2 or a.shape != b.shape:
        return float(np.array_equal(a, b))
    return float(np.mean(np.argmax(a, -1) == np.argmax(b, -1)))


def parity_report(base, quant, feeds: Iterable[Dict],
                  metric_fn: Optional[Callable] = None,
                  logits_tol: Optional[float] = None,
                  metric_tol: Optional[float] = None) -> Dict:
    """Run every feed dict through both arms and report the drift.

    ``base`` / ``quant``: Predictors or model directories. ``feeds``:
    feed dicts (each arm sees identical inputs). ``metric_fn(base_outs,
    quant_outs) -> float in [0, 1]`` scores per-batch agreement
    (default: top-1 agreement); ``metric_delta`` is ``1 - mean
    agreement``. With tolerances given, ``ok`` reflects both gates;
    without, ``ok`` is True (report-only mode)."""
    base = _as_predictor(base)
    quant = _as_predictor(quant)
    metric_fn = metric_fn or top1_agreement
    max_abs = 0.0
    abs_sum, abs_n = 0.0, 0
    agreements = []
    batches = 0
    for feed in feeds:
        b_outs = base.run(feed)
        q_outs = quant.run(feed)
        for a, b in zip(b_outs, q_outs):
            a64 = np.asarray(a, np.float64)
            b64 = np.asarray(b, np.float64)
            if a64.shape != b64.shape:
                raise ValueError(
                    "parity fetch shapes diverge: %s vs %s"
                    % (a64.shape, b64.shape))
            if a64.size:
                d = np.abs(a64 - b64)
                max_abs = max(max_abs, float(d.max()))
                abs_sum += float(d.sum())
                abs_n += d.size
        agreements.append(float(metric_fn(b_outs, q_outs)))
        batches += 1
    if batches == 0:
        raise ValueError("parity_report needs at least one feed batch")
    metric = float(np.mean(agreements))
    metric_delta = 1.0 - metric
    ok = True
    if logits_tol is not None:
        ok = ok and max_abs <= logits_tol
    if metric_tol is not None:
        ok = ok and metric_delta <= metric_tol
    obs.QUANT_PARITY.set(max_abs)
    return {
        "schema": SCHEMA,
        "batches": batches,
        "max_abs_diff": max_abs,
        "mean_abs_diff": (abs_sum / abs_n) if abs_n else 0.0,
        "metric_agreement": metric,
        "metric_delta": metric_delta,
        "logits_tol": logits_tol,
        "metric_tol": metric_tol,
        "ok": bool(ok),
    }
