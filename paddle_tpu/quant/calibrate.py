"""Calibration: collect the ranges int8 quantization scales come from.

Post-training quantization needs two kinds of ranges: per-channel
weight amax (static — read straight from the Scope) and per-tensor
activation amax (dynamic — observed by streaming a representative
sample through the program). ``calibrate`` runs the inference program
batch by batch over any feed source (a DataLoader, a reader, a list of
feed dicts), fetching exactly the activation tensors the quantize pass
will need and folding their amax into a running table; each batch also
ticks ``paddle_tpu_quant_calib_batches_total`` so a calibration job is
observable like any other run.

The product is a :class:`CalibrationTable` — a small, JSON-serializable
artifact that can be saved next to the model and replayed into
``save_inference_model(quantize=table)`` or
``optimize_program(level=3, calib=table)`` later, on another host.
"""
from __future__ import annotations

import itertools
import json
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

from .. import observability as obs
from ..ops.quant import Q_MAX, scale_for_amax

__all__ = ["CalibrationTable", "activation_targets", "calibrate",
           "quantizable_targets"]

# op types the quantize pass rewrites, and where their activation /
# weight live (input slot names)
_QUANT_OPS: Dict[str, Tuple[str, str]] = {
    "mul": ("X", "Y"),
    "matmul": ("X", "Y"),
    "fused_fc": ("X", "Y"),
    "conv2d": ("Input", "Filter"),
}


class CalibrationTable:
    """Serializable amax ranges: ``activations`` maps var name ->
    per-tensor amax; ``weights`` maps param name -> per-output-channel
    amax list (flattened output span for fc weights, O for conv
    filters). ``batches`` records how many sample batches produced the
    activation ranges."""

    VERSION = 1

    def __init__(self, activations: Optional[Dict[str, float]] = None,
                 weights: Optional[Dict[str, List[float]]] = None,
                 batches: int = 0):
        self.activations = dict(activations or {})
        self.weights = {k: list(map(float, v))
                        for k, v in (weights or {}).items()}
        self.batches = int(batches)

    # -- range folding ----------------------------------------------------
    def observe_activation(self, name: str, value) -> None:
        amax = float(np.max(np.abs(np.asarray(value, np.float64))) or 0.0)
        self.activations[name] = max(self.activations.get(name, 0.0), amax)

    def scale_for(self, name: str) -> Optional[float]:
        """Per-tensor symmetric scale for an activation, or None when
        the name was never observed (the pass then skips that op).
        Shares ops.quant.scale_for_amax so table-side and kernel-side
        scale conventions can never diverge."""
        amax = self.activations.get(name)
        if amax is None:
            return None
        return float(scale_for_amax(amax))

    # -- serialization ----------------------------------------------------
    def to_dict(self) -> Dict:
        return {"version": self.VERSION, "batches": self.batches,
                "activations": {k: float(v)
                                for k, v in sorted(self.activations.items())},
                "weights": {k: list(map(float, v))
                            for k, v in sorted(self.weights.items())}}

    @classmethod
    def from_dict(cls, d: Dict) -> "CalibrationTable":
        return cls(activations=d.get("activations"),
                   weights=d.get("weights"),
                   batches=d.get("batches", 0))

    def save(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump(self.to_dict(), f, indent=2, sort_keys=True)

    @classmethod
    def load(cls, path: str) -> "CalibrationTable":
        with open(path) as f:
            return cls.from_dict(json.load(f))

    def __repr__(self):
        return ("CalibrationTable(activations=%d, weights=%d, batches=%d)"
                % (len(self.activations), len(self.weights), self.batches))


def quantizable_targets(program):
    """Walk the global block for quantizable ops: returns
    ``[(op, activation_name, weight_name)]`` for every
    mul/matmul/fused_fc/conv2d whose weight input names a persistable
    var (training-graph ops whose "weight" is itself an activation are
    skipped here and by the pass alike)."""
    gb = program.global_block()
    out = []
    for op in gb.ops:
        slots = _QUANT_OPS.get(op.type)
        if slots is None:
            continue
        a_slot, w_slot = slots
        a_in, w_in = op.input(a_slot), op.input(w_slot)
        if not a_in or not w_in:
            continue
        wvar = gb._find_var_recursive(w_in[0])
        if wvar is None or not wvar.persistable:
            continue
        out.append((op, a_in[0], w_in[0]))
    return out


def activation_targets(program) -> List[str]:
    """The activation var names ``calibrate`` observes for ``program``
    (deduped, first-seen order) — what a synthetic table must cover."""
    seen, names = set(), []
    for _op, a_name, _w in quantizable_targets(program):
        if a_name not in seen:
            seen.add(a_name)
            names.append(a_name)
    return names


def _as_feed_dict(batch, feed_names: Sequence[str]) -> Dict:
    if isinstance(batch, dict):
        return batch
    if isinstance(batch, (list, tuple)):
        if len(batch) != len(feed_names):
            raise ValueError(
                "calibration batch has %d slots; program expects %d "
                "feeds %s" % (len(batch), len(feed_names),
                              list(feed_names)))
        return dict(zip(feed_names, batch))
    raise TypeError(
        "calibration batches must be dicts or per-feed tuples, got %s"
        % type(batch).__name__)


def calibrate(program, scope, feed_names: Sequence[str],
              sample_source: Iterable, max_batches: int = 8,
              place=None) -> CalibrationTable:
    """Stream ``max_batches`` batches from ``sample_source`` (a
    DataLoader, reader, or any iterable of feed dicts / per-feed
    tuples) through ``program`` and collect the quantization ranges.

    The program should be the INFERENCE form that will be quantized
    (``clone(for_test=True)`` / the ``save_inference_model`` pruned
    graph) so activation names line up with what the quantize pass
    sees. Only the slice of the program feeding the quantizable
    activations actually runs (a loss cone still hanging off a
    ``clone(for_test=True)`` is pruned away, so label-style feeds its
    ops would need are not required — extra keys in the batches are
    ignored). Weight amax is read from ``scope`` per output channel;
    activation amax is per tensor, folded max-wise across batches."""
    from .. import scope_guard
    from ..executor import Executor
    from ..io import _prune_for_targets
    from ..ops.quant import quantize_conv_filter, weight_scales_2d

    targets = quantizable_targets(program)
    table = CalibrationTable()
    if not targets:
        return table
    act_names = activation_targets(program)
    # activations that ARE feeds range directly off the sample batches;
    # the rest come from running ONLY the backward slice that produces
    # them — the quantizable cone never needs the label-style feeds a
    # training clone's loss ops would demand
    feed_set = set(feed_names)
    feed_acts = [n for n in act_names if n in feed_set]
    computed_acts = [n for n in act_names if n not in feed_set]
    sliced = (_prune_for_targets(program, computed_acts)
              if computed_acts else None)
    used_feeds = set(feed_acts)
    if sliced is not None:
        for op in sliced.global_block().ops:
            used_feeds.update(n for n in op.input_arg_names
                              if n in feed_set)

    exe = Executor(place, opt_level=0)
    exe._disk.enabled = False  # calibration never pollutes the AOT cache
    with scope_guard(scope):
        for batch in itertools.islice(iter(sample_source), max_batches):
            feed = _as_feed_dict(batch, feed_names)
            feed = {k: v for k, v in feed.items() if k in used_feeds}
            for name in feed_acts:
                table.observe_activation(name, feed[name])
            if sliced is not None:
                outs = exe.run(sliced, feed=feed,
                               fetch_list=list(computed_acts))
                for name, val in zip(computed_acts, outs):
                    table.observe_activation(name, val)
            table.batches += 1
            obs.QUANT_CALIB_BATCHES.inc()
    if table.batches == 0:
        raise ValueError("calibration source yielded no batches")

    # weight ranges: static, per output channel, straight from the scope
    import math as _math

    for op, _a, w_name in targets:
        if w_name in table.weights:
            continue
        val = scope.find_var(w_name)
        if val is None:
            continue  # uninitialized param: the pass will skip this op
        w = np.asarray(val)
        if op.type == "conv2d":
            _q, s = quantize_conv_filter(w)
            amax = s * Q_MAX
        else:
            ync = int(op.attr("y_num_col_dims", 1))
            w2 = w.reshape((_math.prod(w.shape[:ync]), -1))
            amax = weight_scales_2d(w2) * Q_MAX
        table.weights[w_name] = [float(v) for v in np.asarray(amax)]
    return table
