"""Weight-decay regularizers (reference: python/paddle/fluid/regularizer.py).

append_regularization_ops rewrites each (param, grad) pair to
grad = grad + penalty_grad, exactly like the reference — the extra ops fuse
into the single traced training step.
"""
from __future__ import annotations

__all__ = ["L1Decay", "L2Decay", "L1DecayRegularizer", "L2DecayRegularizer", "append_regularization_ops"]


class WeightDecayRegularizer:
    def append_ops(self, param, grad, block):
        raise NotImplementedError


class L2DecayRegularizer(WeightDecayRegularizer):
    def __init__(self, regularization_coeff=0.0):
        self._coeff = regularization_coeff

    def append_ops(self, param, grad, block):
        decay = block.create_var(
            name=grad.name + ".l2decay", dtype=param.dtype, shape=param.shape
        )
        block.append_op(
            type="scale",
            inputs={"X": [param]},
            outputs={"Out": [decay]},
            attrs={"scale": self._coeff},
        )
        new_grad = block.create_var(
            name=grad.name + ".reg", dtype=param.dtype, shape=param.shape
        )
        block.append_op(
            type="elementwise_add",
            inputs={"X": [grad], "Y": [decay]},
            outputs={"Out": [new_grad]},
            attrs={"axis": -1},
        )
        return new_grad


class L1DecayRegularizer(WeightDecayRegularizer):
    def __init__(self, regularization_coeff=0.0):
        self._coeff = regularization_coeff

    def append_ops(self, param, grad, block):
        sign = block.create_var(name=grad.name + ".sign", dtype=param.dtype, shape=param.shape)
        block.append_op(type="sign", inputs={"X": [param]}, outputs={"Out": [sign]})
        decay = block.create_var(name=grad.name + ".l1decay", dtype=param.dtype, shape=param.shape)
        block.append_op(
            type="scale",
            inputs={"X": [sign]},
            outputs={"Out": [decay]},
            attrs={"scale": self._coeff},
        )
        new_grad = block.create_var(name=grad.name + ".reg", dtype=param.dtype, shape=param.shape)
        block.append_op(
            type="elementwise_add",
            inputs={"X": [grad], "Y": [decay]},
            outputs={"Out": [new_grad]},
            attrs={"axis": -1},
        )
        return new_grad


L1Decay = L1DecayRegularizer
L2Decay = L2DecayRegularizer


def append_regularization_ops(parameters_and_grads, regularization=None):
    params_and_grads = []
    for param, grad in parameters_and_grads:
        regularization_term = None
        if getattr(param, "regularizer", None) is not None:
            regularization_term = param.regularizer
        elif regularization is not None:
            regularization_term = regularization
        if grad is None or regularization_term is None:
            params_and_grads.append((param, grad))
            continue
        new_grad = regularization_term.append_ops(param, grad, grad.block.program.global_block())
        params_and_grads.append((param, new_grad))
    return params_and_grads
