"""General RNN decoder API: training + beam-search inference (reference:
python/paddle/fluid/contrib/decoder/beam_search_decoder.py).

``StateCell`` names the hidden states / step inputs of a custom RNN cell
and holds the user's update function; ``TrainingDecoder`` runs the cell
over a target sequence (teacher forcing); ``BeamSearchDecoder`` runs it
step-by-step with a beam.

TPU-native divergences from the reference:

- The reference's beam loop is a ``While`` over LoD TensorArrays whose
  batch shrinks as hypotheses finish and whose states reorder through LoD
  lineage (``sequence_expand`` on prev scores). Here the loop is a
  fixed-trip ``StaticRNN`` (one ``lax.scan``) over dense (B, K) beams:
  finished beams keep proposing only ``end_id`` at frozen score (the
  ``beam_search`` op's contract), and state rows reorder with the
  ``beam_gather`` op driven by the step's parent pointers — same results,
  static shapes.
- ``InitState(need_reorder=...)`` is accepted but has nothing to do:
  dense batches have no LoD rank order.
"""
from __future__ import annotations

import contextlib

from ... import layers
from ...framework.core import Variable
from ...layer_helper import LayerHelper

__all__ = ["InitState", "StateCell", "TrainingDecoder", "BeamSearchDecoder"]


class _DecoderType:
    TRAINING = 1
    BEAM_SEARCH = 2


class InitState:
    """Initial hidden state: wraps `init`, or builds a constant tensor
    batch-shaped like `init_boot` (reference beam_search_decoder.py:43)."""

    def __init__(self, init=None, shape=None, value=0.0, init_boot=None,
                 need_reorder=False, dtype="float32"):
        if init is not None:
            self._init = init
        elif init_boot is None:
            raise ValueError(
                "init_boot must be provided to infer the shape of InitState.")
        else:
            self._init = layers.fill_constant_batch_size_like(
                input=init_boot, value=value, shape=shape, dtype=dtype)
        self._shape = shape
        self._value = value
        self._need_reorder = need_reorder  # no-op on dense batches
        self._dtype = dtype

    @property
    def value(self):
        return self._init

    @property
    def need_reorder(self):
        return self._need_reorder


class _MemoryState:
    """A state bound to a decoder loop memory (reference _MemoryState /
    _ArrayState collapse into one here: both decoders are scan loops)."""

    def __init__(self, rnn, init_value):
        self._rnn = rnn
        self._mem = rnn.memory(init=init_value)
        self.pending = None

    def get_state(self):
        return self._mem

    def update_state(self, state):
        self.pending = state


class StateCell:
    """Named states + step inputs + a user update function (reference
    beam_search_decoder.py:159). The updater reads inputs with
    ``get_input``, reads/writes states with ``get_state``/``set_state``;
    ``out_state`` names the state the decoder scores."""

    def __init__(self, inputs, states, out_state, name=None):
        self._cur_states = {}
        self._state_names = []
        for state_name, state in states.items():
            if not isinstance(state, InitState):
                raise ValueError("state must be an InitState object.")
            self._cur_states[state_name] = state
            self._state_names.append(state_name)
        self._inputs = dict(inputs)
        self._cur_decoder_obj = None
        self._in_decoder = False
        self._states_holder = {}
        self._switched_decoder = False
        self._state_updater = None
        self._out_state = out_state
        if self._out_state not in self._cur_states:
            raise ValueError("out_state must be one state in states")

    def _enter_decoder(self, decoder_obj):
        if self._in_decoder or self._cur_decoder_obj is not None:
            raise ValueError("StateCell has already entered a decoder.")
        self._in_decoder = True
        self._cur_decoder_obj = decoder_obj
        self._switched_decoder = False

    def _leave_decoder(self, decoder_obj):
        if not self._in_decoder:
            raise ValueError("StateCell not in decoder, invalid leave.")
        if self._cur_decoder_obj is not decoder_obj:
            raise ValueError("Inconsistent decoder object in StateCell.")
        self._in_decoder = False
        self._cur_decoder_obj = None
        self._switched_decoder = False

    def _switch_decoder(self):
        """Bind each InitState to a loop memory of the current decoder
        (lazily, on first state access inside the decoder block)."""
        if not self._in_decoder:
            raise ValueError("StateCell must enter a decoder first.")
        if self._switched_decoder:
            raise ValueError("StateCell already done switching.")
        holder = self._states_holder.setdefault(id(self._cur_decoder_obj), {})
        for state_name in self._state_names:
            state = self._cur_states[state_name]
            if not isinstance(state, InitState):
                raise ValueError(
                    "state %r was already consumed by another decoder; "
                    "build a fresh StateCell per decoder pair" % state_name)
            init_value = self._cur_decoder_obj._prepare_init(state)
            holder[state_name] = _MemoryState(
                self._cur_decoder_obj._loop, init_value)
            self._cur_states[state_name] = holder[state_name].get_state()
        self._switched_decoder = True

    def _holders(self):
        return self._states_holder[id(self._cur_decoder_obj)]

    def get_state(self, state_name):
        if self._in_decoder and not self._switched_decoder:
            self._switch_decoder()
        if state_name not in self._cur_states:
            raise ValueError("Unknown state %s." % state_name)
        return self._cur_states[state_name]

    def get_input(self, input_name):
        if input_name not in self._inputs or self._inputs[input_name] is None:
            raise ValueError("Invalid input %s." % input_name)
        return self._inputs[input_name]

    def set_state(self, state_name, state_value):
        self._cur_states[state_name] = state_value

    def state_updater(self, updater):
        """Decorator registering the per-step update function (takes this
        StateCell, reads inputs, set_state's the new states)."""
        self._state_updater = updater
        return updater

    def compute_state(self, inputs):
        """Feed this step's inputs and run the updater."""
        if self._in_decoder and not self._switched_decoder:
            self._switch_decoder()
        for input_name, input_value in inputs.items():
            if input_name not in self._inputs:
                raise ValueError(
                    "Unknown input %s: not an input placeholder" % input_name)
            self._inputs[input_name] = input_value
        if self._state_updater is None:
            raise ValueError("state_updater not set on StateCell")
        self._state_updater(self)

    def update_states(self):
        """Record this step's new state values into the loop memories."""
        if self._in_decoder and not self._switched_decoder:
            self._switch_decoder()
        for state_name, holder in self._holders().items():
            holder.update_state(self._cur_states[state_name])
        self._cur_decoder_obj._commit_states(self._holders())

    def out_state(self):
        return self._cur_states[self._out_state]


class TrainingDecoder:
    """Teacher-forced decoder over a target sequence (reference
    beam_search_decoder.py:384)::

        decoder = TrainingDecoder(state_cell)
        with decoder.block():
            current_word = decoder.step_input(trg_embedding)
            decoder.state_cell.compute_state(inputs={'x': current_word})
            out = layers.fc(decoder.state_cell.get_state('h'), size=V,
                            act='softmax')
            decoder.state_cell.update_states()
            decoder.output(out)
        rnn_out = decoder()
    """

    BEFORE_DECODER = 0
    IN_DECODER = 1
    AFTER_DECODER = 2

    def __init__(self, state_cell, name=None):
        self._helper = LayerHelper("training_decoder", name=name)
        self._status = TrainingDecoder.BEFORE_DECODER
        self._loop = layers.DynamicRNN()
        self._type = _DecoderType.TRAINING
        self._state_cell = state_cell
        self._state_cell._enter_decoder(self)

    @contextlib.contextmanager
    def block(self):
        if self._status != TrainingDecoder.BEFORE_DECODER:
            raise ValueError("decoder.block() can only be invoked once")
        self._status = TrainingDecoder.IN_DECODER
        with self._loop.block():
            yield
        self._status = TrainingDecoder.AFTER_DECODER
        self._state_cell._leave_decoder(self)

    @property
    def state_cell(self):
        self._assert_in_decoder_block("state_cell")
        return self._state_cell

    @property
    def dynamic_rnn(self):
        return self._loop

    @property
    def type(self):
        return self._type

    def _prepare_init(self, init_state):
        return init_state.value

    def _commit_states(self, holders):
        for holder in holders.values():
            if holder.pending is not None:
                self._loop.update_memory(holder.get_state(), holder.pending)
                holder.pending = None

    def step_input(self, x, lengths=None):
        self._assert_in_decoder_block("step_input")
        return self._loop.step_input(x, lengths=lengths)

    def static_input(self, x):
        """A variable used whole in every step (not sliced over time)."""
        self._assert_in_decoder_block("static_input")
        return x  # dense scan bodies close over outer vars directly

    def __call__(self):
        if self._status != TrainingDecoder.AFTER_DECODER:
            raise ValueError(
                "Training decoder outputs are only visible after its block.")
        return self._loop()

    def output(self, *outputs):
        self._assert_in_decoder_block("output")
        self._loop.output(*outputs)

    def _assert_in_decoder_block(self, method):
        if self._status != TrainingDecoder.IN_DECODER:
            raise ValueError(
                "%s must be invoked inside the TrainingDecoder block" % method)


def _beam_gather(x, parent, name=None):
    """Layer over the beam_gather op: reorder (B*K, ...) state rows by
    (B, K) parent pointers."""
    helper = LayerHelper("beam_gather", name=name)
    out = helper.create_variable_for_type_inference(x.dtype, shape=x.shape)
    helper.append_op(
        type="beam_gather",
        inputs={"X": [x.name], "Parent": [parent.name]},
        outputs={"Out": [out.name]},
    )
    return out


def _tile_rows(x, k):
    """(B, D) -> (B*K, D): each row repeated K times (beam expansion)."""
    if k == 1:
        return x
    d = x.shape[-1]
    un = layers.reshape(x, shape=[-1, 1, d])
    rep = layers.concat([un] * k, axis=1)
    return layers.reshape(rep, shape=[-1, d])


class BeamSearchDecoder:
    """Beam-search inference decoder (reference
    beam_search_decoder.py:523)::

        decoder = BeamSearchDecoder(state_cell, init_ids, init_scores,
                                    target_dict_dim, word_dim,
                                    beam_size=4, end_id=1, max_len=32)
        decoder.decode()
        translation_ids, translation_scores = decoder()

    ``init_ids``/``init_scores`` are (B, 1); beams 1..K-1 start at score
    -1e9 so the search leaves beam 0 (the reference achieves the same by
    starting with a single-hypothesis LoD level).
    """

    BEFORE_BEAM_SEARCH_DECODER = 0
    IN_BEAM_SEARCH_DECODER = 1
    AFTER_BEAM_SEARCH_DECODER = 2

    def __init__(self, state_cell, init_ids, init_scores, target_dict_dim,
                 word_dim, input_var_dict=None, topk_size=50, sparse_emb=True,
                 max_len=100, beam_size=1, end_id=1, name=None,
                 emb_param_attr=None):
        self._helper = LayerHelper("beam_search_decoder", name=name)
        self._type = _DecoderType.BEAM_SEARCH
        self._status = BeamSearchDecoder.BEFORE_BEAM_SEARCH_DECODER
        self._loop = layers.StaticRNN()
        self._state_cell = state_cell
        self._state_cell._enter_decoder(self)
        self._max_len = int(max_len)
        self._beam_size = int(beam_size)
        self._end_id = int(end_id)
        self._init_ids = init_ids
        self._init_scores = init_scores
        self._target_dict_dim = int(target_dict_dim)
        self._topk_size = min(int(topk_size), int(target_dict_dim))
        self._sparse_emb = sparse_emb
        self._word_dim = int(word_dim)
        self._input_var_dict = dict(input_var_dict or {})
        # name the prev-token embedding (e.g. ParamAttr("vemb")) to share
        # it with the training decoder's table across separate programs
        self._emb_param_attr = emb_param_attr
        self._outputs = None

    @property
    def state_cell(self):
        return self._state_cell

    @property
    def type(self):
        return self._type

    def _prepare_init(self, init_state):
        """Beam states live as (B*K, D): repeat each batch row K times.
        The tiling ops must sit in the parent block (loop boot values),
        so decode() pre-tiles before entering the scan and this just
        looks the result up."""
        pre = getattr(self, "_pretiled", {})
        if id(init_state) in pre:
            return pre[id(init_state)]
        return _tile_rows(init_state.value, self._beam_size)

    def _commit_states(self, holders):
        # actual reorder-by-parent + memory update happens in decode()
        # once the step's parent pointers exist
        pass

    @contextlib.contextmanager
    def block(self):
        """The per-step block. decode() drives it; override decode() for a
        custom cell wiring (reference contract)."""
        if self._status != BeamSearchDecoder.BEFORE_BEAM_SEARCH_DECODER:
            raise ValueError("block() can only be invoked once.")
        self._status = BeamSearchDecoder.IN_BEAM_SEARCH_DECODER
        with self._loop.step():
            yield
        self._status = BeamSearchDecoder.AFTER_BEAM_SEARCH_DECODER
        self._state_cell._leave_decoder(self)

    def early_stop(self):
        """No-op on the fixed-trip dense loop: finished beams freeze via
        the beam_search op, extra steps are pure end_id padding (masked
        out by beam_search_decode's lengths)."""

    def decode(self):
        k = self._beam_size
        # beam-expanded initial ids/scores in the parent block
        ids0 = layers.concat([self._init_ids] * k, axis=1) if k > 1 \
            else self._init_ids
        if k > 1:
            dead = layers.fill_constant_batch_size_like(
                input=self._init_scores, shape=[-1, k - 1], value=-1e9,
                dtype="float32")
            scores0 = layers.concat([self._init_scores, dead], axis=1)
        else:
            scores0 = self._init_scores
        # beam-expand any static feed variables once, outside the loop
        expanded_feeds = {}
        for name, var in self._input_var_dict.items():
            if name not in self._state_cell._inputs:
                raise ValueError("Variable %s not found in StateCell" % name)
            expanded_feeds[name] = _tile_rows(var, k)
        # beam-expand the initial states in the parent block too: they
        # become the scan's boot values (see _prepare_init)
        self._pretiled = {
            id(state): _tile_rows(state.value, k)
            for state in self._state_cell._cur_states.values()
            if isinstance(state, InitState)}

        # fixed trip count: a (max_len, 1) dummy sequence drives the scan
        ticks = layers.fill_constant(
            shape=[self._max_len, 1], dtype="float32", value=0.0)

        with self.block():
            self._loop.step_input(ticks)
            prev_ids = self._loop.memory(init=ids0)        # (B, K) int
            prev_scores = self._loop.memory(init=scores0)  # (B, K) f32

            flat_ids = layers.reshape(prev_ids, shape=[-1, 1])
            prev_emb = layers.embedding(
                flat_ids, size=[self._target_dict_dim, self._word_dim],
                dtype="float32", is_sparse=self._sparse_emb,
                param_attr=self._emb_param_attr)

            feed_dict = dict(expanded_feeds)
            for input_name in self._state_cell._inputs:
                if input_name not in feed_dict:
                    feed_dict[input_name] = prev_emb
            self._state_cell.compute_state(inputs=feed_dict)

            current_state = self._state_cell.out_state()  # (B*K, D)
            scores = layers.fc(input=current_state,
                               size=self._target_dict_dim, act="softmax")
            topk_scores, topk_indices = layers.topk(scores, k=self._topk_size)
            accu_scores = layers.elementwise_add(
                x=layers.log(topk_scores),
                y=layers.reshape(prev_scores, shape=[-1, 1]))
            sel_ids, sel_scores, parent = layers.beam_search(
                prev_ids, prev_scores,
                layers.reshape(topk_indices, shape=[-1, k, self._topk_size]),
                layers.reshape(accu_scores, shape=[-1, k, self._topk_size]),
                self._beam_size, end_id=self._end_id)

            # reorder every state by this step's winning parents, then
            # store for the next step
            self._state_cell.update_states()
            for holder in self._state_cell._holders().values():
                new = holder.pending if holder.pending is not None \
                    else holder.get_state()
                holder.pending = None
                self._loop.update_memory(holder.get_state(),
                                         _beam_gather(new, parent))
            self._loop.update_memory(
                prev_ids, layers.cast(sel_ids, self._init_ids.dtype))
            self._loop.update_memory(prev_scores, sel_scores)
            self._loop.output(sel_ids, sel_scores, parent)

    def read_array(self, init, is_ids=False, is_scores=False):
        raise NotImplementedError(
            "read_array/update_array are LoD TensorArray plumbing of the "
            "reference While loop; the dense decoder manages beam state "
            "through StaticRNN memories — override decode() instead.")

    update_array = read_array

    def __call__(self):
        if self._status != BeamSearchDecoder.AFTER_BEAM_SEARCH_DECODER:
            raise ValueError("decode() must run before reading outputs.")
        ids_stack, scores_stack, parent_stack = self._loop()
        return layers.beam_search_decode(
            ids_stack, scores_stack, beam_size=self._beam_size,
            end_id=self._end_id, parent_idx=parent_stack)
