"""General RNN decoder API: training + beam-search inference (reference
capability: python/paddle/fluid/contrib/decoder/beam_search_decoder.py —
public classes InitState/StateCell/TrainingDecoder/BeamSearchDecoder).

``StateCell`` names the hidden states / step inputs of a custom RNN cell
and holds the user's update function; ``TrainingDecoder`` runs the cell
over a target sequence (teacher forcing); ``BeamSearchDecoder`` runs it
step-by-step with a beam.

TPU-native design (a redesign, not a port of the reference's internals):

- The reference's beam loop is a ``While`` over LoD TensorArrays whose
  batch shrinks as hypotheses finish and whose states reorder through LoD
  lineage (``sequence_expand`` on prev scores). Here the loop is a
  fixed-trip ``StaticRNN`` (one ``lax.scan``) over dense (B, K) beams:
  finished beams keep proposing only ``end_id`` at frozen score (the
  ``beam_search`` op's contract), and state rows reorder with the
  ``beam_gather`` op driven by the step's parent pointers — same results,
  static shapes.
- The cell↔decoder wiring is a ``_LoopBinding`` created when the decoder
  block is entered: boot values (beam-tiled for beam search) are emitted
  into the PARENT block right before the loop opens, then each state gets
  a loop memory. There is no deferred/lazy state migration — custom
  ``decode()`` overrides get correct boot placement for free because
  ``block()`` itself does it.
- ``InitState(need_reorder=...)`` is accepted but has nothing to do:
  dense batches have no LoD rank order.
"""
from __future__ import annotations

import contextlib

from ... import layers
from ...layer_helper import LayerHelper

__all__ = ["InitState", "StateCell", "TrainingDecoder", "BeamSearchDecoder"]


class _DecoderType:
    TRAINING = 1
    BEAM_SEARCH = 2


class InitState:
    """Initial hidden state: wraps `init`, or builds a constant tensor
    batch-shaped like `init_boot` (reference capability:
    beam_search_decoder.py:43)."""

    def __init__(self, init=None, shape=None, value=0.0, init_boot=None,
                 need_reorder=False, dtype="float32"):
        if init is not None:
            self._init = init
        elif init_boot is None:
            raise ValueError(
                "InitState needs either `init` (a Variable) or `init_boot` "
                "(a batch-shaped Variable to size a constant state from)")
        else:
            self._init = layers.fill_constant_batch_size_like(
                shape=shape, dtype=dtype, input=init_boot, value=value)
        self._shape = shape
        self._value = value
        self._need_reorder = need_reorder  # no-op on dense batches
        self._dtype = dtype

    @property
    def value(self):
        return self._init

    @property
    def need_reorder(self):
        return self._need_reorder


class _LoopBinding:
    """Live connection between a StateCell and one decoder's scan loop.

    Built at decoder-block entry: every named state gets a loop memory
    booted from the (possibly beam-tiled) InitState value. During a step,
    ``current`` tracks the in-flight value the updater produces;
    ``update_states`` stamps those as the step's pending results for the
    decoder to commit (directly, or reordered by beam parents)."""

    def __init__(self, loop, boot_values):
        self.loop = loop
        self.memories = {n: loop.memory(init=v)
                         for n, v in boot_values.items()}
        self.current = dict(self.memories)
        self.pending = {}

    def stage_updates(self, values):
        self.pending = {n: values[n] for n in self.memories}

    def take_pending(self, name):
        return self.pending.pop(name, self.memories[name])


class StateCell:
    """Named states + step inputs + a user update function (reference
    capability: beam_search_decoder.py:159). The updater reads inputs
    with ``get_input``, reads/writes states with ``get_state``/
    ``set_state``; ``out_state`` names the state the decoder scores.

    One cell drives one decoder: the decoder claims the cell when
    constructed, and all state access happens inside its block."""

    def __init__(self, inputs, states, out_state, name=None):
        self._init_states = {}
        for state_name, state in states.items():
            if not isinstance(state, InitState):
                raise ValueError(
                    "states[%r] must be an InitState" % state_name)
            self._init_states[state_name] = state
        self._inputs = dict(inputs)
        self._out_state = out_state
        self._state_updater = None
        self._owner = None      # the decoder this cell drives
        self._binding = None    # _LoopBinding while its block is open
        if self._out_state not in self._init_states:
            raise ValueError("out_state %r is not one of the states %s"
                             % (out_state, sorted(self._init_states)))

    # -- decoder-side wiring --------------------------------------------
    def _claim(self, decoder):
        if self._owner is not None:
            raise ValueError(
                "this StateCell already drives a %s; build one StateCell "
                "per decoder" % type(self._owner).__name__)
        self._owner = decoder

    def _bind(self, binding):
        self._binding = binding

    def _unbind(self):
        self._binding = None

    def _require_binding(self, what):
        if self._binding is None:
            raise ValueError(
                "%s is only valid inside the decoder block (the states "
                "live as loop memories there)" % what)
        return self._binding

    # -- user API --------------------------------------------------------
    def get_state(self, state_name):
        binding = self._require_binding("get_state")
        if state_name not in binding.current:
            raise ValueError("unknown state %r; cell has %s"
                             % (state_name, sorted(binding.current)))
        return binding.current[state_name]

    def get_input(self, input_name):
        if input_name not in self._inputs or self._inputs[input_name] is None:
            raise ValueError(
                "input %r has no value this step; feed it through "
                "compute_state(inputs=...)" % input_name)
        return self._inputs[input_name]

    def set_state(self, state_name, state_value):
        binding = self._require_binding("set_state")
        binding.current[state_name] = state_value

    def state_updater(self, updater):
        """Decorator registering the per-step update function (takes this
        StateCell, reads inputs, set_state's the new states)."""
        self._state_updater = updater
        return updater

    def compute_state(self, inputs):
        """Feed this step's inputs and run the updater."""
        self._require_binding("compute_state")
        for input_name, input_value in inputs.items():
            if input_name not in self._inputs:
                raise ValueError(
                    "unknown input %r: not declared in StateCell(inputs=...)"
                    % input_name)
            self._inputs[input_name] = input_value
        if self._state_updater is None:
            raise ValueError(
                "no state updater registered; decorate one with "
                "@cell.state_updater")
        self._state_updater(self)

    def update_states(self):
        """Stamp this step's state values as the step result and hand
        them to the decoder (committed directly in training; reordered by
        beam parents in beam search)."""
        binding = self._require_binding("update_states")
        binding.stage_updates(binding.current)
        self._owner._commit_states(binding)

    def out_state(self):
        binding = self._require_binding("out_state")
        return binding.current[self._out_state]


class TrainingDecoder:
    """Teacher-forced decoder over a target sequence (reference
    capability: beam_search_decoder.py:384)::

        td = TrainingDecoder(cell)
        with td.block():
            word = td.step_input(trg_embedding)
            td.state_cell.compute_state(inputs={'x': word})
            out = layers.fc(td.state_cell.get_state('h'), size=V,
                            act='softmax')
            td.state_cell.update_states()
            td.output(out)
        rnn_out = td()
    """

    # phase constants kept for API parity; internally _phase is a string
    BEFORE_DECODER = 0
    IN_DECODER = 1
    AFTER_DECODER = 2

    def __init__(self, state_cell, name=None):
        self._helper = LayerHelper("training_decoder", name=name)
        self._phase = "building"
        self._loop = layers.DynamicRNN()
        self._type = _DecoderType.TRAINING
        self._cell = state_cell
        state_cell._claim(self)

    @contextlib.contextmanager
    def block(self):
        if self._phase != "building":
            raise ValueError("decoder.block() can only be entered once")
        self._phase = "in_block"
        cell = self._cell
        with self._loop.block():
            cell._bind(_LoopBinding(
                self._loop,
                {n: st.value for n, st in cell._init_states.items()}))
            yield
        cell._unbind()
        self._phase = "done"

    @property
    def state_cell(self):
        self._require_block("state_cell")
        return self._cell

    @property
    def dynamic_rnn(self):
        return self._loop

    @property
    def type(self):
        return self._type

    def _commit_states(self, binding):
        for name, mem in binding.memories.items():
            self._loop.update_memory(mem, binding.take_pending(name))

    def step_input(self, x, lengths=None):
        self._require_block("step_input")
        return self._loop.step_input(x, lengths=lengths)

    def static_input(self, x):
        """A variable used whole in every step (not sliced over time)."""
        self._require_block("static_input")
        return x  # dense scan bodies close over outer vars directly

    def __call__(self):
        if self._phase != "done":
            raise ValueError(
                "training decoder outputs exist only after its block closes")
        return self._loop()

    def output(self, *outputs):
        self._require_block("output")
        self._loop.output(*outputs)

    def _require_block(self, method):
        if self._phase != "in_block":
            raise ValueError(
                "%s is only valid inside decoder.block()" % method)


def _beam_gather(x, parent, name=None):
    """Layer over the beam_gather op: reorder (B*K, ...) state rows by
    (B, K) parent pointers."""
    helper = LayerHelper("beam_gather", name=name)
    out = helper.create_variable_for_type_inference(x.dtype, shape=x.shape)
    helper.append_op(
        type="beam_gather",
        inputs={"X": [x.name], "Parent": [parent.name]},
        outputs={"Out": [out.name]},
    )
    return out


def _tile_rows(x, k):
    """(B, D) -> (B*K, D): each row repeated K times (beam expansion)."""
    if k == 1:
        return x
    d = x.shape[-1]
    un = layers.reshape(x, shape=[-1, 1, d])
    rep = layers.concat([un] * k, axis=1)
    return layers.reshape(rep, shape=[-1, d])


class BeamSearchDecoder:
    """Beam-search inference decoder (reference capability:
    beam_search_decoder.py:523)::

        decoder = BeamSearchDecoder(state_cell, init_ids, init_scores,
                                    target_dict_dim, word_dim,
                                    beam_size=4, end_id=1, max_len=32)
        decoder.decode()
        out_ids, out_scores = decoder()

    ``init_ids``/``init_scores`` are (B, 1); beams 1..K-1 start at score
    -1e9 so the search leaves beam 0 (the reference achieves the same by
    starting with a single-hypothesis LoD level).
    """

    # phase constants kept for API parity; internally _phase is a string
    BEFORE_BEAM_SEARCH_DECODER = 0
    IN_BEAM_SEARCH_DECODER = 1
    AFTER_BEAM_SEARCH_DECODER = 2

    def __init__(self, state_cell, init_ids, init_scores, target_dict_dim,
                 word_dim, input_var_dict=None, topk_size=50, sparse_emb=True,
                 max_len=100, beam_size=1, end_id=1, name=None,
                 emb_param_attr=None):
        self._helper = LayerHelper("beam_search_decoder", name=name)
        self._type = _DecoderType.BEAM_SEARCH
        self._phase = "building"
        self._loop = layers.StaticRNN()
        self._cell = state_cell
        state_cell._claim(self)
        self._max_len, self._beam_size = int(max_len), int(beam_size)
        self._end_id = int(end_id)
        self._init_ids, self._init_scores = init_ids, init_scores
        self._target_dict_dim = int(target_dict_dim)
        self._topk_size = min(int(topk_size), int(target_dict_dim))
        self._sparse_emb, self._word_dim = sparse_emb, int(word_dim)
        self._input_var_dict = dict(input_var_dict or {})
        # name the prev-token embedding (e.g. ParamAttr("vemb")) to share
        # it with the training decoder's table across separate programs
        self._emb_param_attr = emb_param_attr
        self._outputs = None

    @property
    def state_cell(self):
        return self._cell

    @property
    def type(self):
        return self._type

    def _commit_states(self, binding):
        # the reorder-by-parent + memory update happens in decode() once
        # the step's parent pointers exist; staged values wait in pending
        pass

    @contextlib.contextmanager
    def block(self):
        """The per-step block. decode() drives it; override decode() for
        a custom cell wiring. Beam-tiled state boot values are emitted
        into the parent block HERE, right before the loop opens — custom
        decode() implementations get correct placement automatically."""
        if self._phase != "building":
            raise ValueError("block() can only be entered once")
        cell = self._cell
        # parent-block scope: beam-expand every initial state
        boots = {n: _tile_rows(st.value, self._beam_size)
                 for n, st in cell._init_states.items()}
        self._phase = "in_block"
        with self._loop.step():
            cell._bind(_LoopBinding(self._loop, boots))
            yield
        cell._unbind()
        self._phase = "done"

    def early_stop(self):
        """No-op on the fixed-trip dense loop: finished beams freeze via
        the beam_search op, extra steps are pure end_id padding (masked
        out by beam_search_decode's lengths)."""

    def decode(self):
        k = self._beam_size
        # beam-expanded initial ids/scores in the parent block
        ids0 = layers.concat([self._init_ids] * k, axis=1) if k > 1 \
            else self._init_ids
        if k > 1:
            dead = layers.fill_constant_batch_size_like(
                input=self._init_scores, shape=[-1, k - 1], value=-1e9,
                dtype="float32")
            scores0 = layers.concat([self._init_scores, dead], axis=1)
        else:
            scores0 = self._init_scores
        # beam-expand any static feed variables once, outside the loop
        expanded_feeds = {}
        for name, var in self._input_var_dict.items():
            if name not in self._cell._inputs:
                raise ValueError(
                    "input_var_dict[%r] is not a StateCell input" % name)
            expanded_feeds[name] = _tile_rows(var, k)

        # fixed trip count: a (max_len, 1) dummy sequence drives the scan
        ticks = layers.fill_constant(
            shape=[self._max_len, 1], dtype="float32", value=0.0)

        with self.block():
            self._loop.step_input(ticks)
            prev_ids = self._loop.memory(init=ids0)        # (B, K) int
            prev_scores = self._loop.memory(init=scores0)  # (B, K) f32

            flat_ids = layers.reshape(prev_ids, shape=[-1, 1])
            prev_emb = layers.embedding(
                flat_ids, size=[self._target_dict_dim, self._word_dim],
                dtype="float32", is_sparse=self._sparse_emb,
                param_attr=self._emb_param_attr)

            feed_dict = dict(expanded_feeds)
            for input_name in self._cell._inputs:
                feed_dict.setdefault(input_name, prev_emb)
            self._cell.compute_state(inputs=feed_dict)

            word_probs = layers.fc(input=self._cell.out_state(),
                                   size=self._target_dict_dim,
                                   act="softmax")
            cand_probs, cand_ids = layers.topk(word_probs,
                                               k=self._topk_size)
            cum = layers.elementwise_add(
                x=layers.log(cand_probs),
                y=layers.reshape(prev_scores, shape=[-1, 1]))
            sel_ids, sel_scores, parent = layers.beam_search(
                prev_ids, prev_scores,
                layers.reshape(cand_ids, shape=[-1, k, self._topk_size]),
                layers.reshape(cum, shape=[-1, k, self._topk_size]),
                self._beam_size, end_id=self._end_id)

            # reorder every state by this step's winning parents, then
            # store for the next step
            self._cell.update_states()
            binding = self._cell._binding
            for name, mem in binding.memories.items():
                self._loop.update_memory(
                    mem, _beam_gather(binding.take_pending(name), parent))
            self._loop.update_memory(
                prev_ids, layers.cast(sel_ids, self._init_ids.dtype))
            self._loop.update_memory(prev_scores, sel_scores)
            self._loop.output(sel_ids, sel_scores, parent)

    def read_array(self, init, is_ids=False, is_scores=False):
        raise NotImplementedError(
            "read_array/update_array are LoD TensorArray plumbing of the "
            "reference While loop; the dense decoder manages beam state "
            "through StaticRNN memories — override decode() instead.")

    update_array = read_array

    def __call__(self):
        if self._phase != "done":
            raise ValueError("decode() must run before reading outputs")
        ids_stack, scores_stack, parent_stack = self._loop()
        return layers.beam_search_decode(ids_stack, scores_stack,
                                         beam_size=self._beam_size,
                                         end_id=self._end_id,
                                         parent_idx=parent_stack)
