"""Contrib namespace (reference: python/paddle/fluid/contrib)."""
from . import decoder  # noqa: F401
from .decoder import BeamSearchDecoder, InitState, StateCell, TrainingDecoder  # noqa: F401
from .memory_usage_calc import memory_usage  # noqa: F401

__all__ = ["decoder", "memory_usage", "InitState", "StateCell",
           "TrainingDecoder", "BeamSearchDecoder"]
