"""Estimate a Program's variable memory at a batch size (reference:
python/paddle/fluid/contrib/memory_usage_calc.py).

The estimate sums the global block's variable sizes with -1 dims bound to
``batch_size``. On TPU the true footprint is decided by XLA (fusion keeps
most intermediates out of HBM; donation reuses parameter buffers), so this
is an upper-bound-style planning number, same spirit as the reference.
"""
from __future__ import annotations

import numpy as np

from ..framework.core import Program
from ..framework.dtypes import as_numpy_dtype

__all__ = ["memory_usage"]


def memory_usage(program, batch_size):
    """Returns (min_estimate, max_estimate, unit_str) like the reference:
    the raw sum plus the reference's 5%..10% slack band, scaled to
    B/KB/MB."""
    if not isinstance(program, Program):
        raise TypeError(
            "Calculating memory usage requires a Program, got %s"
            % type(program))
    if batch_size <= 0:
        raise ValueError("The batch size must be positive.")

    total = 0.0
    for var in program.global_block().vars.values():
        shape = getattr(var, "shape", None)
        if shape is None:
            continue
        count = 1
        for x in shape:
            count *= batch_size if x in (-1, None) else int(x)
        total += count * np.dtype(as_numpy_dtype(var.dtype)).itemsize

    unit = "B"
    if total > 1024:
        total /= 1024.0
        unit = "KB"
        if total > 1024:
            total /= 1024.0
            unit = "MB"
    return total * 1.05, total * 1.1, unit
