"""Parallel / distributed execution over TPU meshes.

Reference counterparts: python/paddle/fluid/parallel_executor.py (multi-GPU
SSA graphs + NCCL), paddle/fluid/framework/details/* (all-reduce/broadcast
op handles), transpiler/distribute_transpiler.py (pserver graphs). Here the
whole area collapses onto jax.sharding: a Mesh names the device topology, a
ShardingPlan assigns PartitionSpecs, pjit/GSPMD inserts the collectives.
"""
from .mesh import (  # noqa: F401
    default_mesh,
    device_count,
    get_places,
    init_distributed,
    make_hybrid_mesh,
    make_mesh,
)
from .collective import (  # noqa: F401
    all_gather,
    all_reduce,
    all_to_all,
    axis_index,
    axis_size,
    broadcast,
    ppermute,
    reduce_scatter,
)
from .sharding import (  # noqa: F401
    PartitionSpec,
    ShardingPlan,
    megatron_transformer_plan,
    seq_parallel_plan,
    zero_plan,
)
from .parallel_executor import (  # noqa: F401
    BuildStrategy,
    ExecutionStrategy,
    ParallelExecutor,
)
from .ring_attention import (  # noqa: F401
    full_attention,
    ring_attention,
    ring_self_attention,
)
from .pipeline import (  # noqa: F401
    num_pipeline_ticks,
    pipeline_apply,
    stack_stage_params,
)
from .pipeline_program import (  # noqa: F401
    PipelineError,
    PipelinePlan,
    build_pipeline_step_fn,
    plan_pipeline,
)
from .moe import (  # noqa: F401
    MoEParams,
    expert_parallel_ffn,
    init_moe_params,
    moe_ffn_local,
)
