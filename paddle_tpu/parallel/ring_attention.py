"""Ring attention: exact attention over sequence shards with O(T/N) memory
per chip and compute/communication overlap on the ICI ring.

No reference twin — codeWorm2015/Paddle (2018) predates long-context
attention; this is the TPU-native capability the survey lists as
first-class (SURVEY.md §2 parallel). The design follows the blockwise
online-softmax formulation: K/V blocks rotate around the mesh axis with
``lax.ppermute`` while each device keeps its Q shard resident and folds
each visiting block into (m, num, den) running statistics, so the full
(T, T) score matrix never materializes.

Used three ways:
- `ring_attention(...)` — inside an existing shard_map body (axis in scope)
- `ring_self_attention(...)` — standalone: shard_maps itself over a mesh
- the `ring_attention` IR op (ops/nn.py) — inside a Program; falls back to
  exact full attention when the step is not compiled over a sequence axis.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ._compat import shard_map

__all__ = ["ring_attention", "ring_self_attention", "full_attention"]

_NEG = -1e30


def full_attention(q, k, v, causal: bool = False, scale: Optional[float] = None):
    """Exact single-device attention, the numeric reference for the ring.
    q,k,v: (B, H, T, Dh)."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if causal:
        Tq, Tk = logits.shape[-2], logits.shape[-1]
        mask = jnp.tril(jnp.ones((Tq, Tk), bool), k=Tk - Tq)
        logits = jnp.where(mask, logits, _NEG)
    weights = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", weights.astype(v.dtype), v)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def ring_attention(q, k, v, axis_name: str, causal: bool = False,
                   scale: Optional[float] = None):
    """Blockwise-exact attention inside a shard_map body.

    q, k, v: (B, H, T_local, Dh) — the local sequence shard; the global
    sequence is the concatenation over `axis_name` in axis-index order.
    Accumulates in fp32 regardless of input dtype (bf16-safe).

    Differentiable with O(T_local) residuals: the custom backward saves
    only (q, k, v, out, lse) and RE-ROTATES K/V around the ring,
    recomputing each block's probabilities from the logsumexp — dK/dV
    accumulators travel with their blocks and arrive home after the
    full cycle. Plain autodiff would instead save every rotation's
    (T_local, T_local) probability tensor (O(size * T_local^2), i.e.
    the full (T, T) ring attention exists to avoid).
    """
    out, _ = _ring_fwd_impl(q, k, v, axis_name, causal, scale)
    return out


def _ring_steps(axis_name):
    size = lax.psum(1, axis_name)
    my_blk = lax.axis_index(axis_name)
    fwd = [(i, (i + 1) % size) for i in range(size)]
    return int(size), my_blk, fwd


def _block_scores(qs, kc, kv_blk, q_pos, T, causal):
    """(B, H, T, T) f32 scores of the local q shard against a visiting
    K block, causal-masked by GLOBAL positions; bf16 inputs run on the
    MXU at full rate (f32 accumulation)."""
    scores = jnp.einsum("bhqd,bhkd->bhqk", qs, kc,
                        preferred_element_type=jnp.float32)
    if causal:
        k_pos = kv_blk * T + jnp.arange(T)
        keep = q_pos[:, None] >= k_pos[None, :]  # (T, T)
        scores = jnp.where(keep[None, None], scores, _NEG)
    return scores


def _ring_fwd_impl(q, k, v, axis_name, causal, scale):
    size, my_blk, fwd = _ring_steps(axis_name)
    B, H, T, Dh = q.shape
    if scale is None:
        scale = Dh ** -0.5
    # fold the scale into q and KEEP the input dtype: under bf16 AMP the
    # score einsum then runs bf16 x bf16 -> f32 on the MXU (full rate,
    # f32 accumulation via preferred_element_type) — same recipe as the
    # flash kernels; with f32 inputs this is numerically unchanged.
    qs = (q * jnp.asarray(scale, q.dtype)).astype(q.dtype)
    q_pos = my_blk * T + jnp.arange(T)  # global query positions

    # kv rotates "forward" (device i -> i+1), so at step s device i holds
    # the block originally resident on (i - s) mod size.
    def body(s, carry):
        kc, vc, m, num, den = carry
        kv_blk = (my_blk - s) % size
        scores = _block_scores(qs, kc, kv_blk, q_pos, T, causal)
        m_new = jnp.maximum(m, scores.max(axis=-1))
        # rows where everything so far is masked keep m=_NEG; exp(score-m)
        # would be exp(0)=1 there, so zero masked terms explicitly.
        p = jnp.exp(scores - m_new[..., None])
        if causal:
            p = jnp.where(scores <= _NEG / 2, 0.0, p)
        corr = jnp.exp(m - m_new)
        num = num * corr[..., None] + jnp.einsum(
            "bhqk,bhkd->bhqd", p.astype(vc.dtype), vc,
            preferred_element_type=jnp.float32)
        den = den * corr + p.sum(axis=-1)
        kc = lax.ppermute(kc, axis_name, perm=fwd)
        vc = lax.ppermute(vc, axis_name, perm=fwd)
        return kc, vc, m_new, num, den

    init = (
        k, v,
        jnp.full((B, H, T), _NEG, jnp.float32),
        jnp.zeros((B, H, T, Dh), jnp.float32),
        jnp.zeros((B, H, T), jnp.float32),
    )
    # unrolled python loop (size is static): lets XLA overlap each step's
    # einsums with the next ppermute's ICI transfer.
    kc, vc, m, num, den = init
    for s in range(size):
        kc, vc, m, num, den = body(s, (kc, vc, m, num, den))
    den = jnp.maximum(den, 1e-30)
    out = (num / den[..., None]).astype(q.dtype)
    lse = m + jnp.log(den)  # (B, H, T) f32; fully-masked rows: ~_NEG
    return out, lse


def _ring_fwd(q, k, v, axis_name, causal, scale):
    out, lse = _ring_fwd_impl(q, k, v, axis_name, causal, scale)
    return out, (q, k, v, out, lse)


def _ring_bwd(axis_name, causal, scale, res, dout):
    q, k, v, out, lse = res
    size, my_blk, fwd = _ring_steps(axis_name)
    B, H, T, Dh = q.shape
    if scale is None:
        scale = Dh ** -0.5
    qs = (q * jnp.asarray(scale, q.dtype)).astype(q.dtype)
    q_pos = my_blk * T + jnp.arange(T)
    do = dout
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1)  # (B, H, T)

    def body(s, carry):
        kc, vc, dkc, dvc, dq = carry
        kv_blk = (my_blk - s) % size
        scores = _block_scores(qs, kc, kv_blk, q_pos, T, causal)
        # p = softmax weights reconstructed from the saved logsumexp;
        # masked entries give exp(_NEG - lse) == 0 exactly
        p = jnp.exp(scores - lse[..., None])
        dv_step = jnp.einsum("bhqk,bhqd->bhkd", p.astype(do.dtype), do,
                             preferred_element_type=jnp.float32)
        dp = jnp.einsum("bhqd,bhkd->bhqk", do, vc,
                        preferred_element_type=jnp.float32)
        ds = p * (dp - delta[..., None])
        dq = dq + jnp.einsum("bhqk,bhkd->bhqd", ds.astype(kc.dtype), kc,
                             preferred_element_type=jnp.float32)
        dk_step = jnp.einsum("bhqk,bhqd->bhkd", ds.astype(qs.dtype), qs,
                             preferred_element_type=jnp.float32)
        # the dK/dV accumulators TRAVEL WITH their blocks: after the full
        # cycle each block is home again carrying every device's
        # contribution
        dkc = lax.ppermute(dkc + dk_step, axis_name, perm=fwd)
        dvc = lax.ppermute(dvc + dv_step, axis_name, perm=fwd)
        kc = lax.ppermute(kc, axis_name, perm=fwd)
        vc = lax.ppermute(vc, axis_name, perm=fwd)
        return kc, vc, dkc, dvc, dq

    zero_kv = jnp.zeros((B, H, T, Dh), jnp.float32)
    carry = (k, v, zero_kv, zero_kv,
             jnp.zeros((B, H, T, Dh), jnp.float32))
    for s in range(size):
        carry = body(s, carry)
    _, _, dkc, dvc, dq = carry
    # d(qs)/dq = scale (the fold at the top)
    dq = dq * jnp.asarray(scale, jnp.float32)
    return (dq.astype(q.dtype), dkc.astype(k.dtype), dvc.astype(v.dtype))


ring_attention.defvjp(_ring_fwd, _ring_bwd)


def ring_self_attention(q, k, v, mesh: Mesh, sp_axis: str = "sp",
                        causal: bool = False, scale: Optional[float] = None):
    """Standalone entry: q,k,v are global (B, H, T, Dh) arrays; the sequence
    dim is sharded over mesh axis `sp_axis` and attention is exact."""
    spec = P(None, None, sp_axis, None)
    fn = shard_map(
        functools.partial(ring_attention, axis_name=sp_axis, causal=causal,
                          scale=scale),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
    )
    return fn(q, k, v)
