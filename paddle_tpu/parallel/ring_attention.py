"""Ring attention: exact attention over sequence shards with O(T/N) memory
per chip and compute/communication overlap on the ICI ring.

No reference twin — codeWorm2015/Paddle (2018) predates long-context
attention; this is the TPU-native capability the survey lists as
first-class (SURVEY.md §2 parallel). The design follows the blockwise
online-softmax formulation: K/V blocks rotate around the mesh axis with
``lax.ppermute`` while each device keeps its Q shard resident and folds
each visiting block into (m, num, den) running statistics, so the full
(T, T) score matrix never materializes.

Used three ways:
- `ring_attention(...)` — inside an existing shard_map body (axis in scope)
- `ring_self_attention(...)` — standalone: shard_maps itself over a mesh
- the `ring_attention` IR op (ops/nn.py) — inside a Program; falls back to
  exact full attention when the step is not compiled over a sequence axis.
"""
from __future__ import annotations

import functools
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ._compat import pvary as _compat_pvary, shard_map

__all__ = ["ring_attention", "ring_self_attention", "full_attention"]

_NEG = -1e30
_U = np.uint32


def _mix32(x):
    """lowbias32 avalanche finalizer on uint32 lattices (public-domain
    integer-hash constants); statistically fine for dropout bits."""
    x = x ^ (x >> _U(16))
    x = x * _U(0x7FEB352D)
    x = x ^ (x >> _U(15))
    x = x * _U(0x846CA68B)
    x = x ^ (x >> _U(16))
    return x


def _dropout_keep_scale(seed, B, H, q_pos, k_pos, rate):
    """(B, H, len(q_pos), len(k_pos)) f32 multiplicative dropout factor
    keep/(1-rate), where `keep` is a pure function of (seed, batch, head,
    GLOBAL query position, GLOBAL key position).

    Position-stable by construction: the mask for any (q, k) score element
    is independent of how the sequence is blocked or sharded, so the ring
    path (any number of sp shards) and the single-device full-attention
    fallback draw bit-identical masks — that is what makes ring-vs-full
    parity hold WITH dropout. `seed` is a uint32 (2,) array
    (jax.random.key_data of a PRNG key)."""
    seed = jnp.asarray(seed, jnp.uint32).reshape(-1)
    b = jnp.arange(B, dtype=jnp.uint32).reshape(B, 1, 1, 1)
    h = jnp.arange(H, dtype=jnp.uint32).reshape(1, H, 1, 1)
    qp = q_pos.astype(jnp.uint32).reshape(1, 1, -1, 1)
    kp = k_pos.astype(jnp.uint32).reshape(1, 1, 1, -1)
    x = _mix32(seed[0] ^ _mix32(seed[1]))
    x = _mix32(x ^ (b * _U(0x9E3779B1)))
    x = _mix32(x ^ (h * _U(0x85EBCA77)))
    x = _mix32(x ^ (qp * _U(0xC2B2AE3D)))
    x = _mix32(x ^ (kp * _U(0x27D4EB2F)))
    # top 24 bits -> uniform [0, 1)
    u = (x >> _U(8)).astype(jnp.float32) * (1.0 / (1 << 24))
    return (u >= rate).astype(jnp.float32) / (1.0 - rate)


def full_attention(q, k, v, causal: bool = False, scale: Optional[float] = None,
                   lengths=None, dropout_rate: float = 0.0, dropout_seed=None):
    """Exact single-device attention, the numeric reference for the ring.
    q,k,v: (B, H, T, Dh). `lengths` (B,) masks padded KV positions;
    `dropout_rate`/`dropout_seed` apply the same position-stable dropout
    as the ring path (see _dropout_keep_scale), so this stays its numeric
    twin under both features."""
    if dropout_rate and dropout_seed is None:
        raise ValueError("dropout_rate > 0 requires dropout_seed "
                         "(uint32 (2,) array, e.g. jax.random.key_data)")
    if scale is None:
        scale = q.shape[-1] ** -0.5
    B, H, Tq, _ = q.shape
    Tk = k.shape[2]
    # match the ring path's score numerics exactly (ADVICE r4): fold the
    # scale into q in the INPUT dtype (as _ring_fwd_impl does) and
    # accumulate the einsum in f32 — both halves matter for bf16 parity
    qs = (q * jnp.asarray(scale, q.dtype)).astype(q.dtype)
    logits = jnp.einsum("bhqd,bhkd->bhqk", qs, k,
                        preferred_element_type=jnp.float32)
    masked = causal or lengths is not None
    if causal:
        mask = jnp.tril(jnp.ones((Tq, Tk), bool), k=Tk - Tq)
        logits = jnp.where(mask, logits, _NEG)
    if lengths is not None:
        valid = jnp.arange(Tk)[None, :] < lengths.reshape(-1)[:, None]  # (B, Tk)
        logits = jnp.where(valid[:, None, None, :], logits, _NEG)
    weights = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    if masked:
        # a fully-masked row (e.g. lengths[b] == 0) must produce 0, not
        # the softmax of a constant row (the mean of V) — mirrors the
        # ring path's zeroed accumulators
        weights = jnp.where(logits <= _NEG / 2, 0.0, weights)
    if dropout_rate:
        weights = weights * _dropout_keep_scale(
            dropout_seed, B, H, jnp.arange(Tq), jnp.arange(Tk), dropout_rate)
    return jnp.einsum("bhqk,bhkd->bhqd", weights.astype(v.dtype), v)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 9))
def ring_attention(q, k, v, axis_name: str, causal: bool = False,
                   scale: Optional[float] = None, dropout_rate: float = 0.0,
                   lengths=None, dropout_seed=None,
                   chunk: Optional[int] = None):
    """Blockwise-exact attention inside a shard_map body.

    q, k, v: (B, H, T_local, Dh) — the local sequence shard; the global
    sequence is the concatenation over `axis_name` in axis-index order.
    Accumulates in fp32 regardless of input dtype (bf16-safe).

    `lengths` (B,) are GLOBAL KV lengths: keys at global position >=
    lengths[b] are masked out of batch row b (the reference's sequence
    padding semantics — /root/reference/python/paddle/fluid/nets.py:332's
    attention over padded batches). `dropout_rate`/`dropout_seed` apply
    attention-probability dropout with a position-stable mask
    (_dropout_keep_scale), matching full_attention bit-for-bit. Both are
    replicated inputs — every device sees the full (B,) lengths and the
    same seed.

    `chunk` bounds per-rotation-step TRANSIENT memory: each visiting KV
    block is consumed in sub-blocks of `chunk` keys (a lax.scan with an
    online-softmax carry), so the largest live score tensor is
    (B, H, T_local, chunk) instead of (B, H, T_local, T_local) — the
    difference between seq ~64k and seq 1M+ fitting a chip. None picks
    automatically: whole-block below _CHUNK_AUTO keys (best XLA fusion
    at bench sizes), the largest lane-aligned divisor above it. The
    position-stable masks/dropout make chunking invisible numerically.

    Differentiable with O(T_local) residuals: the custom backward saves
    only (q, k, v, out, lse) and RE-ROTATES K/V around the ring,
    recomputing each block's probabilities from the logsumexp — dK/dV
    accumulators travel with their blocks and arrive home after the
    full cycle. Plain autodiff would instead save every rotation's
    (T_local, T_local) probability tensor (O(size * T_local^2), i.e.
    the full (T, T) ring attention exists to avoid).
    """
    out, _ = _ring_fwd_impl(q, k, v, lengths, dropout_seed, axis_name,
                            causal, scale, dropout_rate, chunk)
    return out


_CHUNK_AUTO = 2048  # auto-chunk threshold AND the auto chunk size


def _pick_chunk(T: int, chunk: Optional[int]):
    """(n_chunks, chunk_size) for a T-key block. Explicit chunk must
    divide T; auto keeps small blocks whole and splits big ones at the
    largest power-of-two divisor <= _CHUNK_AUTO."""
    if chunk is not None:
        chunk = int(chunk)
        if chunk <= 0 or T % chunk:
            raise ValueError(
                "ring attention chunk=%d must positively divide the "
                "local block length %d" % (chunk, T))
        return T // chunk, chunk
    if T <= _CHUNK_AUTO:
        return 1, T
    c = _CHUNK_AUTO
    while c > 128 and T % c:
        c //= 2
    if T % c:
        return 1, T  # odd length: stay whole rather than mis-split
    return T // c, c


def _ring_steps(axis_name):
    size = lax.psum(1, axis_name)
    my_blk = lax.axis_index(axis_name)
    fwd = [(i, (i + 1) % size) for i in range(size)]
    return int(size), my_blk, fwd


def _vary_like(x, axis_name):
    """Mark x varying over the manual mesh axis (shard_map vma typing):
    the chunk scans' initial carries are device-invariant zeros while
    the body outputs mix in the varying q/kv shards."""
    return _compat_pvary(x, axis_name)


def _chunk_scores(qs, kcc, k_pos, q_pos, causal, lengths=None):
    """(B, H, Tq, C) f32 scores of the local q shard against a visiting
    KV sub-chunk at GLOBAL key positions k_pos, causal- and padding-
    masked; bf16 inputs run on the MXU at full rate (f32 accumulation)."""
    scores = jnp.einsum("bhqd,bhkd->bhqk", qs, kcc,
                        preferred_element_type=jnp.float32)
    if causal:
        keep = q_pos[:, None] >= k_pos[None, :]  # (Tq, C)
        scores = jnp.where(keep[None, None], scores, _NEG)
    if lengths is not None:
        valid = k_pos[None, :] < lengths.reshape(-1)[:, None]  # (B, C)
        scores = jnp.where(valid[:, None, None, :], scores, _NEG)
    return scores


def _kv_chunk_axes(x, nc, C):
    """(B, H, T, Dh) -> (nc, B, H, C, Dh) scan-ready sub-chunks."""
    B, H, T, Dh = x.shape
    return x.reshape(B, H, nc, C, Dh).transpose(2, 0, 1, 3, 4)


def _ring_fwd_impl(q, k, v, lengths, dropout_seed, axis_name, causal, scale,
                   dropout_rate, chunk):
    size, my_blk, fwd = _ring_steps(axis_name)
    B, H, T, Dh = q.shape
    if scale is None:
        scale = Dh ** -0.5
    nc, C = _pick_chunk(T, chunk)
    # fold the scale into q and KEEP the input dtype: under bf16 AMP the
    # score einsum then runs bf16 x bf16 -> f32 on the MXU (full rate,
    # f32 accumulation via preferred_element_type) — same recipe as the
    # flash kernels; with f32 inputs this is numerically unchanged.
    qs = (q * jnp.asarray(scale, q.dtype)).astype(q.dtype)
    q_pos = my_blk * T + jnp.arange(T)  # global query positions
    masked = causal or lengths is not None

    def fwd_chunk(carry, kcc, vcc, k_pos):
        """Fold one visiting KV sub-chunk into the (m, num, den) online-
        softmax carry."""
        m, num, den = carry
        scores = _chunk_scores(qs, kcc, k_pos, q_pos, causal, lengths)
        m_new = jnp.maximum(m, scores.max(axis=-1))
        # rows where everything so far is masked keep m=_NEG; exp(score-m)
        # would be exp(0)=1 there, so zero masked terms explicitly.
        p = jnp.exp(scores - m_new[..., None])
        if masked:
            p = jnp.where(scores <= _NEG / 2, 0.0, p)
        if dropout_rate:
            # dropout applies to the normalized softmax weights, which
            # factor as p / den: scale the numerator's p, keep den on the
            # un-dropped p (normalization is over pre-dropout weights)
            p_num = p * _dropout_keep_scale(dropout_seed, B, H, q_pos,
                                            k_pos, dropout_rate)
        else:
            p_num = p
        corr = jnp.exp(m - m_new)
        num = num * corr[..., None] + jnp.einsum(
            "bhqk,bhkd->bhqd", p_num.astype(vcc.dtype), vcc,
            preferred_element_type=jnp.float32)
        den = den * corr + p.sum(axis=-1)
        return m_new, num, den

    # kv rotates "forward" (device i -> i+1), so at step s device i holds
    # the block originally resident on (i - s) mod size.
    def body(s, carry):
        kc, vc, m, num, den = carry
        base = ((my_blk - s) % size) * T
        if nc == 1:
            m, num, den = fwd_chunk((m, num, den), kc, vc,
                                    base + jnp.arange(T))
        else:
            def sub(c2, args):
                kcc, vcc, j = args
                return fwd_chunk(c2, kcc, vcc,
                                 base + j * C + jnp.arange(C)), None

            # the scan body's outputs vary over the manual sp axis (they
            # mix in the varying q/kv shards), so the initial carry must
            # be marked varying too (shard_map scan-vma typing)
            init_c = tuple(_vary_like(x, axis_name) for x in (m, num, den))
            (m, num, den), _ = lax.scan(
                sub, init_c,
                (_kv_chunk_axes(kc, nc, C), _kv_chunk_axes(vc, nc, C),
                 jnp.arange(nc)))
        kc = lax.ppermute(kc, axis_name, perm=fwd)
        vc = lax.ppermute(vc, axis_name, perm=fwd)
        return kc, vc, m, num, den

    init = (
        k, v,
        jnp.full((B, H, T), _NEG, jnp.float32),
        jnp.zeros((B, H, T, Dh), jnp.float32),
        jnp.zeros((B, H, T), jnp.float32),
    )
    # unrolled python loop (size is static): lets XLA overlap each step's
    # einsums with the next ppermute's ICI transfer.
    kc, vc, m, num, den = init
    for s in range(size):
        kc, vc, m, num, den = body(s, (kc, vc, m, num, den))
    den = jnp.maximum(den, 1e-30)
    out = (num / den[..., None]).astype(q.dtype)
    lse = m + jnp.log(den)  # (B, H, T) f32; fully-masked rows: ~_NEG
    return out, lse


def _ring_fwd(q, k, v, axis_name, causal, scale, dropout_rate, lengths,
              dropout_seed, chunk):
    out, lse = _ring_fwd_impl(q, k, v, lengths, dropout_seed, axis_name,
                              causal, scale, dropout_rate, chunk)
    return out, (q, k, v, out, lse, lengths, dropout_seed)


def _ring_bwd(axis_name, causal, scale, dropout_rate, chunk, res, dout):
    q, k, v, out, lse, lengths, dropout_seed = res
    size, my_blk, fwd = _ring_steps(axis_name)
    B, H, T, Dh = q.shape
    if scale is None:
        scale = Dh ** -0.5
    nc, C = _pick_chunk(T, chunk)
    qs = (q * jnp.asarray(scale, q.dtype)).astype(q.dtype)
    q_pos = my_blk * T + jnp.arange(T)
    do = dout
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1)  # (B, H, T)

    def bwd_chunk(dq, kcc, vcc, k_pos):
        """One visiting KV sub-chunk's gradient contributions:
        accumulates into dq, returns this chunk's (dk, dv)."""
        scores = _chunk_scores(qs, kcc, k_pos, q_pos, causal, lengths)
        # p = softmax weights reconstructed from the saved logsumexp;
        # masked entries give exp(_NEG - lse) == 0 exactly — EXCEPT on a
        # fully-masked row, where lse itself is ~_NEG and the subtraction
        # would overflow toward +inf: zero those explicitly (the forward
        # already outputs 0 there, so 0 gradient is exact)
        p = jnp.exp(scores - lse[..., None])
        p = jnp.where(scores <= _NEG / 2, 0.0, p)
        if dropout_rate:
            # out = sum_k p_k * ks_k * v_k / den with den over un-dropped
            # p (see forward): d s_i = p_i * (ks_i * (do . v_i) - delta)
            pd = p * _dropout_keep_scale(dropout_seed, B, H, q_pos, k_pos,
                                         dropout_rate)
        else:
            pd = p
        dv_c = jnp.einsum("bhqk,bhqd->bhkd", pd.astype(do.dtype), do,
                          preferred_element_type=jnp.float32)
        dp = jnp.einsum("bhqd,bhkd->bhqk", do, vcc,
                        preferred_element_type=jnp.float32)
        ds = pd * dp - p * delta[..., None]
        dq = dq + jnp.einsum("bhqk,bhkd->bhqd", ds.astype(kcc.dtype), kcc,
                             preferred_element_type=jnp.float32)
        dk_c = jnp.einsum("bhqk,bhqd->bhkd", ds.astype(qs.dtype), qs,
                          preferred_element_type=jnp.float32)
        return dq, dk_c, dv_c

    def body(s, carry):
        kc, vc, dkc, dvc, dq = carry
        base = ((my_blk - s) % size) * T
        if nc == 1:
            dq, dk_step, dv_step = bwd_chunk(dq, kc, vc,
                                             base + jnp.arange(T))
        else:
            def sub(dq2, args):
                kcc, vcc, j = args
                dq2, dk_c, dv_c = bwd_chunk(dq2, kcc, vcc,
                                            base + j * C + jnp.arange(C))
                return dq2, (dk_c, dv_c)

            dq, (dks, dvs) = lax.scan(
                sub, _vary_like(dq, axis_name),
                (_kv_chunk_axes(kc, nc, C), _kv_chunk_axes(vc, nc, C),
                 jnp.arange(nc)))
            # (nc, B, H, C, Dh) stacked chunk grads -> (B, H, T, Dh)
            dk_step = dks.transpose(1, 2, 0, 3, 4).reshape(B, H, T, Dh)
            dv_step = dvs.transpose(1, 2, 0, 3, 4).reshape(B, H, T, Dh)
        # the dK/dV accumulators TRAVEL WITH their blocks: after the full
        # cycle each block is home again carrying every device's
        # contribution
        dkc = lax.ppermute(dkc + dk_step, axis_name, perm=fwd)
        dvc = lax.ppermute(dvc + dv_step, axis_name, perm=fwd)
        kc = lax.ppermute(kc, axis_name, perm=fwd)
        vc = lax.ppermute(vc, axis_name, perm=fwd)
        return kc, vc, dkc, dvc, dq

    zero_kv = jnp.zeros((B, H, T, Dh), jnp.float32)
    carry = (k, v, zero_kv, zero_kv,
             jnp.zeros((B, H, T, Dh), jnp.float32))
    for s in range(size):
        carry = body(s, carry)
    _, _, dkc, dvc, dq = carry
    # d(qs)/dq = scale (the fold at the top)
    dq = dq * jnp.asarray(scale, jnp.float32)
    return (dq.astype(q.dtype), dkc.astype(k.dtype), dvc.astype(v.dtype),
            None, None)


ring_attention.defvjp(_ring_fwd, _ring_bwd)


def ring_self_attention(q, k, v, mesh: Mesh, sp_axis: str = "sp",
                        causal: bool = False, scale: Optional[float] = None,
                        lengths=None, dropout_rate: float = 0.0,
                        dropout_seed=None, chunk: Optional[int] = None):
    """Standalone entry: q,k,v are global (B, H, T, Dh) arrays; the sequence
    dim is sharded over mesh axis `sp_axis` and attention is exact.
    `lengths` (global KV lengths) and the dropout seed are replicated."""
    if dropout_rate and dropout_seed is None:
        raise ValueError("dropout_rate > 0 requires dropout_seed "
                         "(uint32 (2,) array, e.g. jax.random.key_data)")
    spec = P(None, None, sp_axis, None)

    def body(q, k, v, lengths, seed):
        return ring_attention(q, k, v, sp_axis, causal, scale,
                              dropout_rate, lengths, seed, chunk)

    fn = shard_map(
        body, mesh=mesh,
        in_specs=(spec, spec, spec, P(), P()), out_specs=spec,
    )
    return fn(q, k, v,
              None if lengths is None else jnp.asarray(lengths),
              None if dropout_seed is None
              else jnp.asarray(dropout_seed, jnp.uint32))
