"""Device mesh management.

The reference enumerates CUDA devices and builds one SSA sub-graph per GPU
(reference: python/paddle/fluid/parallel_executor.py:__init__ collects
CUDAPlace list; paddle/fluid/framework/details/*). TPU-native, a
``jax.sharding.Mesh`` is the device topology: named axes (dp/mp/pp/sp/ep)
over which shardings are declared; XLA's SPMD partitioner inserts the
collectives (over ICI within a slice, DCN across hosts).
"""
from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np

import jax
from jax.sharding import Mesh

__all__ = [
    "make_mesh",
    "default_mesh",
    "device_count",
    "get_places",
    "init_distributed",
]


def device_count() -> int:
    return jax.device_count()


def make_mesh(
    shape: Optional[Sequence[int]] = None,
    axis_names: Sequence[str] = ("dp",),
    devices=None,
) -> Mesh:
    """Build a Mesh over (a prefix of) the available devices.

    ``shape=None`` puts every device on the first axis. Multi-host meshes
    should lay the DCN-crossing axis outermost (JAX enumerates devices
    host-major, so axis 0 naturally maps across hosts).
    """
    devices = list(jax.devices()) if devices is None else list(devices)
    if shape is None:
        shape = [len(devices)] + [1] * (len(axis_names) - 1)
    shape = tuple(int(s) for s in shape)
    n = int(np.prod(shape))
    if n > len(devices):
        raise ValueError(
            "mesh shape %s needs %d devices, only %d available"
            % (shape, n, len(devices))
        )
    arr = np.array(devices[:n]).reshape(shape)
    return Mesh(arr, tuple(axis_names))


def default_mesh(axis_name: str = "dp") -> Mesh:
    """1-D mesh over all devices (the ParallelExecutor default)."""
    return make_mesh(axis_names=(axis_name,))


def get_places(device_count_: Optional[int] = None):
    """Parity with fluid.layers.device.get_places (reference:
    python/paddle/fluid/layers/device.py): enumerate execution places.
    Returns TPUPlace list on accelerator backends, CPUPlace otherwise."""
    from ..framework.scope import CPUPlace, TPUPlace

    devs = jax.devices()
    n = len(devs) if device_count_ is None else min(device_count_, len(devs))
    cls = CPUPlace if devs[0].platform == "cpu" else TPUPlace
    return [cls(i) for i in range(n)]


def init_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
):
    """Multi-host runtime initialization.

    Plays the role of the reference's NCCL bootstrap (ParallelExecutor's
    num_trainers/trainer_id → ncclCommInitRank). On TPU pods the arguments
    are auto-detected from the environment; on CPU/GPU clusters pass them
    explicitly. After this, ``jax.devices()`` spans the whole job and
    meshes built from it are global.
    """
    kwargs = {}
    if coordinator_address is not None:
        kwargs["coordinator_address"] = coordinator_address
    if num_processes is not None:
        kwargs["num_processes"] = num_processes
    if process_id is not None:
        kwargs["process_id"] = process_id
    jax.distributed.initialize(**kwargs)
