"""Device mesh management.

The reference enumerates CUDA devices and builds one SSA sub-graph per GPU
(reference: python/paddle/fluid/parallel_executor.py:__init__ collects
CUDAPlace list; paddle/fluid/framework/details/*). TPU-native, a
``jax.sharding.Mesh`` is the device topology: named axes (dp/mp/pp/sp/ep)
over which shardings are declared; XLA's SPMD partitioner inserts the
collectives (over ICI within a slice, DCN across hosts).
"""
from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np

import jax
from jax.sharding import Mesh

__all__ = [
    "make_mesh",
    "make_hybrid_mesh",
    "default_mesh",
    "device_count",
    "get_places",
    "init_distributed",
]


def device_count() -> int:
    return jax.device_count()


def make_mesh(
    shape: Optional[Sequence[int]] = None,
    axis_names: Sequence[str] = ("dp",),
    devices=None,
) -> Mesh:
    """Build a Mesh over (a prefix of) the available devices.

    ``shape=None`` puts every device on the first axis. Multi-host meshes
    should lay the DCN-crossing axis outermost (JAX enumerates devices
    host-major, so axis 0 naturally maps across hosts).
    """
    devices = list(jax.devices()) if devices is None else list(devices)
    if shape is None:
        shape = [len(devices)] + [1] * (len(axis_names) - 1)
    shape = tuple(int(s) for s in shape)
    n = int(np.prod(shape))
    if n > len(devices):
        raise ValueError(
            "mesh shape %s needs %d devices, only %d available"
            % (shape, n, len(devices))
        )
    arr = np.array(devices[:n]).reshape(shape)
    return Mesh(arr, tuple(axis_names))


def make_hybrid_mesh(
    axis_names: Sequence[str],
    ici_shape: Sequence[int],
    dcn_shape: Sequence[int],
    devices=None,
) -> Mesh:
    """Hybrid ICI×DCN mesh: axis ``i`` has size ``dcn[i] * ici[i]`` with
    the DCN (cross-host) factor slowest-varying, so collectives along an
    axis whose dcn factor is 1 stay entirely on ICI and only the axes
    that genuinely span hosts ride DCN.

    The reference's multi-trainer layout splits work host-major the same
    way (reference: transpiler/distribute_transpiler.py trainer split +
    ParallelExecutor num_trainers/trainer_id NCCL bootstrap); here the
    layout is a device permutation and XLA routes each collective over
    the fastest fabric it spans.

    Typical pod use: ``make_hybrid_mesh(("dp", "mp"), ici_shape=(1, 8),
    dcn_shape=(n_hosts, 1))`` — data parallel across hosts over DCN,
    tensor parallel inside each host over ICI.

    Under ``jax.distributed`` this delegates to
    ``mesh_utils.create_hybrid_device_mesh`` (groups by process). Single-
    process (virtual-device tests), devices are arranged host-major with
    ``prod(ici_shape)`` consecutive devices per emulated host — the same
    ordering a real multi-process enumeration produces, which is what the
    ordering tests pin down.
    """
    axis_names = tuple(axis_names)
    ici_shape = tuple(int(s) for s in ici_shape)
    dcn_shape = tuple(int(s) for s in dcn_shape)
    if not (len(axis_names) == len(ici_shape) == len(dcn_shape)):
        raise ValueError(
            "axis_names %s, ici_shape %s and dcn_shape %s must align"
            % (axis_names, ici_shape, dcn_shape))
    devices = list(jax.devices()) if devices is None else list(devices)
    n = int(np.prod(ici_shape)) * int(np.prod(dcn_shape))
    if n > len(devices):
        raise ValueError(
            "hybrid mesh ici %s x dcn %s needs %d devices, only %d "
            "available" % (ici_shape, dcn_shape, n, len(devices)))
    devices = devices[:n]

    if jax.process_count() > 1:
        # TPU pods: prefer jax's topology-aware construction (it groups
        # by pod slice); CPU/GPU jobs have one degenerate slice — group
        # by process there instead
        slices = {getattr(d, "slice_index", None) for d in devices}
        if None not in slices and len(slices) == int(np.prod(dcn_shape)):
            from jax.experimental import mesh_utils

            arr = mesh_utils.create_hybrid_device_mesh(
                ici_shape, dcn_shape, devices=devices)
            return Mesh(arr, axis_names)
        devices = sorted(devices, key=lambda d: (d.process_index, d.id))
        # the host-major reshape below puts prod(ici) CONSECUTIVE devices
        # on one emulated host; that only matches reality when each
        # process contributes exactly prod(ici) devices — otherwise an
        # "ICI" group would silently span processes (i.e. ride DCN)
        per_proc: dict = {}
        for d in devices:
            per_proc[d.process_index] = per_proc.get(d.process_index, 0) + 1
        ici_n = int(np.prod(ici_shape))
        if set(per_proc.values()) != {ici_n}:
            raise ValueError(
                "hybrid mesh needs prod(ici_shape)=%d devices per "
                "process, but processes contribute %s; pick an ici_shape "
                "matching the per-host device count"
                % (ici_n, sorted(per_proc.values())))
        if len(per_proc) != int(np.prod(dcn_shape)):
            raise ValueError(
                "hybrid mesh dcn_shape %s implies %d hosts but the "
                "devices span %d processes"
                % (dcn_shape, int(np.prod(dcn_shape)), len(per_proc)))

    # host-major enumeration: prod(ici) consecutive devices per host
    k = len(axis_names)
    arr = np.array(devices).reshape(dcn_shape + ici_shape)
    # interleave (dcn_0, ici_0, dcn_1, ici_1, ...) then merge per axis
    arr = arr.transpose([ax for i in range(k) for ax in (i, k + i)])
    arr = arr.reshape([d * i for d, i in zip(dcn_shape, ici_shape)])
    return Mesh(arr, axis_names)


def default_mesh(axis_name: str = "dp") -> Mesh:
    """1-D mesh over all devices (the ParallelExecutor default)."""
    return make_mesh(axis_names=(axis_name,))


def get_places(device_count_: Optional[int] = None):
    """Parity with fluid.layers.device.get_places (reference:
    python/paddle/fluid/layers/device.py): enumerate execution places.
    Returns TPUPlace list on accelerator backends, CPUPlace otherwise."""
    from ..framework.scope import CPUPlace, TPUPlace

    devs = jax.devices()
    n = len(devs) if device_count_ is None else min(device_count_, len(devs))
    cls = CPUPlace if devs[0].platform == "cpu" else TPUPlace
    return [cls(i) for i in range(n)]


def init_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
):
    """Multi-host runtime initialization.

    Plays the role of the reference's NCCL bootstrap (ParallelExecutor's
    num_trainers/trainer_id → ncclCommInitRank). On TPU pods the arguments
    are auto-detected from the environment; on CPU/GPU clusters pass them
    explicitly. After this, ``jax.devices()`` spans the whole job and
    meshes built from it are global.
    """
    kwargs = {}
    if coordinator_address is not None:
        kwargs["coordinator_address"] = coordinator_address
    if num_processes is not None:
        kwargs["num_processes"] = num_processes
    if process_id is not None:
        kwargs["process_id"] = process_id
    jax.distributed.initialize(**kwargs)
