"""Pipeline parallelism: GPipe-style fill-drain over a ``pp`` mesh axis.

The reference's pipeline story is graph partitioning over trainers
(transpiler/distribute_transpiler.py splits the ProgramDesc and wires
send/recv ops). TPU-native redesign: all pipeline stages share one traced
stage function; per-stage parameters are STACKED with a leading stage axis
and sharded over the ``pp`` mesh axis, activations hop stage→stage with
``lax.ppermute`` on the ICI ring, and a ``lax.scan`` over
(microbatches + stages - 1) ticks implements the fill/drain schedule inside
``shard_map``. The whole schedule is one differentiable XLA computation —
``jax.grad`` through it yields the reverse pipeline automatically, so a
training step is just grad(loss ∘ pipeline).

Garbage circulates through bubble slots (every device computes every tick —
that is the SPMD way; masking, not control flow) but is zeroed before
collection and never reaches a valid microbatch's data path.
"""
from __future__ import annotations

from typing import Callable, Sequence

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ._compat import shard_map


from ._compat import pvary as _pvary  # shared vma-typing shim

__all__ = ["pipeline_apply", "stack_stage_params", "num_pipeline_ticks"]


def stack_stage_params(stage_params: Sequence):
    """Stack a list of per-stage parameter pytrees along a new leading
    stage axis (shard that axis over ``pp``)."""
    return jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs, axis=0), *stage_params)


def num_pipeline_ticks(n_microbatches: int, n_stages: int) -> int:
    return n_microbatches + n_stages - 1


def pipeline_apply(stage_fn: Callable, stacked_params, x, mesh: Mesh,
                   axis: str = "pp", batch_axis: str = None):
    """Run ``x`` through all pipeline stages.

    stage_fn: ``(params_of_one_stage, act) -> act`` with act shapes equal
        in and out (the stage-homogeneous condition pipelining needs).
    stacked_params: pytree whose leaves have a leading stage axis of size
        S == mesh.shape[axis] (see stack_stage_params).
    x: (M, mb, ...) microbatched input (M = number of microbatches).
    batch_axis: optional mesh axis name to shard the microbatch (second)
        dim over — combines dp×pp on one mesh.

    Returns (M, mb, ...) outputs, replicated over ``axis`` (sharded over
    ``batch_axis`` if given). Differentiable.
    """
    n_stages = mesh.shape[axis]
    n_micro = x.shape[0]
    ticks = num_pipeline_ticks(n_micro, n_stages)
    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    bspec = P(None, batch_axis) if batch_axis else P(None)
    param_spec = jax.tree_util.tree_map(lambda _: P(axis), stacked_params)

    def device_fn(params_stacked, x_local):
        # params_stacked leaf: (1, ...) — this device's stage slice
        params = jax.tree_util.tree_map(
            lambda p: jnp.squeeze(p, axis=0), params_stacked)
        stage = lax.axis_index(axis)
        mb_shape = x_local.shape[1:]

        def tick(carry, t):
            state, outs = carry
            # stage 0 injects microbatch t while filling; everyone else
            # consumes what the previous stage sent last tick
            inj = lax.dynamic_index_in_dim(
                x_local, jnp.minimum(t, n_micro - 1), axis=0,
                keepdims=False)
            inj = jnp.where(t < n_micro, inj, jnp.zeros_like(inj))
            inp = jnp.where(stage == 0, inj, state)
            y = stage_fn(params, inp)
            # last stage emits microbatch m = t - (S-1)
            m = t - (n_stages - 1)
            emit = jnp.where(
                (stage == n_stages - 1) & (m >= 0), y, jnp.zeros_like(y))
            # fill ticks (m<0) clip to slot 0 and write zeros there; the
            # real m=0 write happens later, so the final slot is correct
            outs = lax.dynamic_update_index_in_dim(
                outs, emit, jnp.clip(m, 0, n_micro - 1), axis=0)
            state = lax.ppermute(y, axis, perm)
            return (state, outs), None

        # the carry is device-varying (it depends on axis_index/ppermute);
        # mark the zero initializers varying too or the scan carry types
        # disagree under the VMA type system
        vary = (axis,) + ((batch_axis,) if batch_axis else ())
        outs0 = _pvary(jnp.zeros((n_micro,) + mb_shape, x_local.dtype),
                       vary)
        state0 = _pvary(jnp.zeros(mb_shape, x_local.dtype), vary)
        (state, outs), _ = lax.scan(tick, (state0, outs0),
                                    jnp.arange(ticks))
        # outputs live on the last stage; replicate over the pp axis
        outs = lax.psum(jnp.where(stage == n_stages - 1, outs,
                                  jnp.zeros_like(outs)), axis)
        return outs

    return shard_map(
        device_fn, mesh=mesh,
        in_specs=(param_spec, bspec),
        out_specs=bspec,
    )(stacked_params, x)
