"""Version-compat shims shared by the parallel modules."""
from __future__ import annotations

import jax

try:  # jax>=0.6 top level; older: experimental
    shard_map = jax.shard_map
except AttributeError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map  # noqa: F401

__all__ = ["shard_map"]
