"""Version-compat shims shared by the parallel modules."""
from __future__ import annotations

import jax

try:  # jax>=0.6 top level; older: experimental
    shard_map = jax.shard_map
except AttributeError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map  # noqa: F401

__all__ = ["shard_map", "shard_map_partial", "pvary"]


def pvary(x, axes):
    """Mark x varying over manual mesh axes (shard_map vma typing);
    lax.pvary is deprecated in favor of lax.pcast(..., to='varying') on
    newer jax. `axes`: one axis name or a tuple. IDEMPOTENT: axes x
    already varies over are skipped (pcast rejects varying->varying,
    and callers often promote loop carries that are invariant only on
    the first ring/pipeline step)."""
    from jax import lax

    if not isinstance(axes, tuple):
        axes = (axes,)
    typeof = getattr(jax, "typeof", None)
    if typeof is not None:
        try:
            have = set(getattr(typeof(x), "vma", ()) or ())
        except Exception:
            have = set()
        axes = tuple(a for a in axes if a not in have)
    if not axes:
        return x
    pcast = getattr(lax, "pcast", None)
    if pcast is not None:
        return pcast(x, axes, to="varying")
    return lax.pvary(x, axes)


def shard_map_partial(f, mesh, in_specs, out_specs, manual_axes):
    """shard_map manual over `manual_axes` only; any other mesh axes stay
    automatic (GSPMD partitions over them inside the manual region —
    e.g. the pipeline tick loop is manual over (dp, pp) while tensor
    parallelism rides an auto mp axis). Newer jax spells this
    ``axis_names=...``; older jax ``auto=<complement>``."""
    manual = frozenset(manual_axes)
    try:
        return shard_map(f, mesh=mesh, in_specs=in_specs,
                         out_specs=out_specs, axis_names=set(manual))
    except TypeError:  # pragma: no cover — older jax
        auto = frozenset(mesh.axis_names) - manual
        if not auto:
            return shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs)
        return shard_map(f, mesh=mesh, in_specs=in_specs,
                        out_specs=out_specs, auto=auto)
