"""Version-compat shims shared by the parallel modules."""
from __future__ import annotations

import jax

try:  # jax>=0.6 top level; older: experimental
    shard_map = jax.shard_map
except AttributeError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map  # noqa: F401

__all__ = ["shard_map", "shard_map_partial"]


def shard_map_partial(f, mesh, in_specs, out_specs, manual_axes):
    """shard_map manual over `manual_axes` only; any other mesh axes stay
    automatic (GSPMD partitions over them inside the manual region —
    e.g. the pipeline tick loop is manual over (dp, pp) while tensor
    parallelism rides an auto mp axis). Newer jax spells this
    ``axis_names=...``; older jax ``auto=<complement>``."""
    manual = frozenset(manual_axes)
    try:
        return shard_map(f, mesh=mesh, in_specs=in_specs,
                         out_specs=out_specs, axis_names=set(manual))
    except TypeError:  # pragma: no cover — older jax
        auto = frozenset(mesh.axis_names) - manual
        if not auto:
            return shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs)
        return shard_map(f, mesh=mesh, in_specs=in_specs,
                        out_specs=out_specs, auto=auto)
